"""Span tracer: nested, thread-local timing regions.

``with telemetry.span("fwdbwd", step=n):`` stamps one region. Spans

- nest per thread (a thread-local stack tracks the enclosing span, so
  every record knows its parent and depth),
- aggregate into the ``mxtpu.span_seconds`` histogram (labelled by span
  name) — the per-phase totals ``tools/trace_summary.py`` and the
  Prometheus dump report,
- emit a complete chrome-trace ``"X"`` event into the profiler's event
  buffer when the profiler is running, so one ``profile.json`` shows
  framework spans alongside jax.profiler device traces,
- append a JSONL record when ``MXTPU_TELEMETRY_FILE`` export is active.

When telemetry is disabled ``span()`` returns a shared no-op context
manager — no allocation, no clock read.
"""
from __future__ import annotations

import threading
import time

from . import registry as _reg

_tls = threading.local()

SPAN_SECONDS = _reg.histogram(
    "mxtpu.span_seconds", "time spent inside telemetry spans, by name")


class _NullSpan:
    """Shared disabled-path span: every method is a no-op."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_attrs(self, **attrs):
        pass


_NULL = _NullSpan()


def _stack():
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


class Span:
    __slots__ = ("name", "attrs", "parent", "depth", "_t0", "_ts_us",
                 "duration")

    def __init__(self, name, attrs):
        self.name = name
        self.attrs = attrs
        self.parent = None
        self.depth = 0
        self.duration = None

    def set_attrs(self, **attrs):
        self.attrs.update(attrs)

    def __enter__(self):
        st = _stack()
        if st:
            self.parent = st[-1]
            self.depth = self.parent.depth + 1
        st.append(self)
        # wall clock for the trace timeline, monotonic for the duration
        self._ts_us = time.time() * 1e6
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        dur = time.perf_counter() - self._t0
        self.duration = dur
        st = _stack()
        if st and st[-1] is self:
            st.pop()
        SPAN_SECONDS.observe(dur, span=self.name)
        args = dict(self.attrs)
        if self.parent is not None:
            args["parent"] = self.parent.name
        if exc_type is not None:
            args["error"] = exc_type.__name__
        # profiler buffer (no-op unless profiler_set_state("run"));
        # deferred import: profiler pulls in jax at call sites and must
        # never become a hard dependency of the metrics layer
        from .. import profiler as _profiler

        _profiler.record_event_complete(
            self.name, self._ts_us, dur * 1e6, category="framework",
            args=args or None)
        from . import export as _export

        _export.emit_span({
            "type": "span", "name": self.name, "ts": self._ts_us / 1e6,
            "dur": dur, "depth": self.depth,
            "thread": threading.get_ident() % 10000, "attrs": args,
        })
        return False


def span(name, **attrs):
    """Open a timing region. Usage::

        with telemetry.span("fwdbwd", step=n):
            ...
    """
    if not _reg._enabled:
        return _NULL
    return Span(name, attrs)


def current_span():
    """The innermost active span on this thread, or None."""
    st = getattr(_tls, "stack", None)
    return st[-1] if st else None
