"""Telemetry exporters: structured JSONL + Prometheus text dump.

Two sinks, both optional and env-driven so production jobs opt in
without code changes:

- ``MXTPU_TELEMETRY_FILE`` — every span appends one JSON line as it
  closes; ``flush_metrics()`` (called per fit epoch, on ``flush()``, and
  at interpreter exit) appends a full ``{"type": "metrics"}`` registry
  snapshot. ``tools/trace_summary.py`` reads this format.
- ``MXTPU_TELEMETRY_PROM_FILE`` — ``render_prometheus()`` text written
  on every flush, and periodically (every
  ``MXTPU_TELEMETRY_PROM_INTERVAL`` seconds, default 30) by a daemon
  thread, for a node-exporter-style textfile collector to scrape.

Also home of the per-step device gauges: ``sample_device_memory()``
reads ``jax.local_devices()[...].memory_stats()`` into
``device.memory.*`` gauges (a no-op on backends without memory stats,
e.g. CPU).
"""
from __future__ import annotations

import atexit
import json
import os
import threading
import time

from . import registry as _reg

_lock = threading.Lock()
_jsonl_path = os.environ.get("MXTPU_TELEMETRY_FILE") or None
_jsonl_fh = None
_prom_path = os.environ.get("MXTPU_TELEMETRY_PROM_FILE") or None
_prom_interval = float(os.environ.get("MXTPU_TELEMETRY_PROM_INTERVAL", "30"))
_prom_thread = None
_prom_stop = threading.Event()


def jsonl_path():
    return _jsonl_path


def set_jsonl_path(path):
    """Point (or stop, with None) the JSONL sink at ``path``."""
    global _jsonl_path, _jsonl_fh
    with _lock:
        if _jsonl_fh is not None:
            try:
                _jsonl_fh.close()
            except OSError:
                pass
            _jsonl_fh = None
        _jsonl_path = path or None


def _fh():
    """Open the JSONL sink lazily (caller holds _lock)."""
    global _jsonl_fh
    if _jsonl_fh is None and _jsonl_path is not None:
        _jsonl_fh = open(_jsonl_path, "a")
    return _jsonl_fh


def emit_span(record):
    if _jsonl_path is None:
        return
    line = json.dumps(record)
    with _lock:
        fh = _fh()
        if fh is not None:
            fh.write(line + "\n")
            fh.flush()


# any structured record ({"type": "anatomy"|"recompile"|...}) goes down
# the same sink; the span name is historical
emit_record = emit_span


def flush_metrics():
    """Append a registry snapshot to the JSONL sink and rewrite the
    Prometheus file, whichever are configured."""
    if _jsonl_path is not None:
        line = json.dumps({
            "type": "metrics", "ts": time.time(),
            "metrics": _reg.snapshot(),
        })
        with _lock:
            fh = _fh()
            if fh is not None:
                fh.write(line + "\n")
                fh.flush()
    write_prometheus_file()


def set_prometheus_file(path, interval=None):
    """Configure the Prometheus text sink; interval > 0 starts the
    periodic writer thread."""
    global _prom_path, _prom_interval
    _prom_path = path or None
    if interval is not None:
        _prom_interval = float(interval)
    if _prom_path is not None and _prom_interval > 0:
        _start_prom_thread()


def write_prometheus_file():
    if _prom_path is None:
        return
    text = _reg.render_prometheus()
    tmp = _prom_path + ".tmp"
    try:
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, _prom_path)  # atomic vs a concurrent scraper
    except OSError:
        pass  # export is advisory; never take training down


def _start_prom_thread():
    global _prom_thread
    if _prom_thread is not None and _prom_thread.is_alive():
        return
    _prom_stop.clear()

    def _loop():
        while not _prom_stop.wait(_prom_interval):
            if _reg._enabled:
                write_prometheus_file()

    _prom_thread = threading.Thread(
        target=_loop, name="mxtpu-telemetry-prom", daemon=True)
    _prom_thread.start()


def stop_prom_thread():
    _prom_stop.set()


# -- device memory gauges ---------------------------------------------
def sample_device_memory():
    """Read each local device's memory_stats() into gauges. Safe to call
    per step: backends without stats (CPU) return None and are skipped."""
    if not _reg._enabled:
        return
    import jax

    for dev in jax.local_devices():
        try:
            stats = dev.memory_stats()
        except Exception:  # noqa: BLE001 — backend-dependent surface
            stats = None
        if not stats:
            continue
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if key in stats:
                _reg.gauge("device.memory." + key).set(
                    stats[key], device=str(dev.id))


def _at_exit():
    """Interpreter-exit flush: a crashed-after-N-epochs run still leaves
    its last metrics snapshot on disk."""
    if _reg._enabled and (_jsonl_path is not None or _prom_path is not None):
        try:
            flush_metrics()
        except Exception:  # noqa: BLE001 — exit path must not raise
            pass


atexit.register(_at_exit)

if (_prom_path is not None and _prom_interval > 0
        and os.environ.get("MXTPU_TELEMETRY_PROM_FILE")):
    _start_prom_thread()
