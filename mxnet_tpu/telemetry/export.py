"""Telemetry exporters: structured JSONL + Prometheus text dump.

Two sinks, both optional and env-driven so production jobs opt in
without code changes:

- ``MXTPU_TELEMETRY_FILE`` — every span appends one JSON line as it
  closes; ``flush_metrics()`` (called per fit epoch, on ``flush()``, and
  at interpreter exit) appends a full ``{"type": "metrics"}`` registry
  snapshot. ``tools/trace_summary.py`` reads this format.
- ``MXTPU_TELEMETRY_PROM_FILE`` — ``render_prometheus()`` text written
  on every flush, and periodically (every
  ``MXTPU_TELEMETRY_PROM_INTERVAL`` seconds, default 30) by a daemon
  thread, for a node-exporter-style textfile collector to scrape.

Also home of the per-step device gauges: ``sample_device_memory()``
reads ``jax.local_devices()[...].memory_stats()`` into
``device.memory.*`` gauges (a no-op on backends without memory stats,
e.g. CPU).
"""
from __future__ import annotations

import atexit
import json
import os
import socket
import threading
import time

from . import registry as _reg

RUN_DIR_ENV = "MXTPU_RUN_DIR"

_lock = threading.Lock()
_host = socket.gethostname()
_seq = 0  # per-process metrics-snapshot sequence (fleet merge idempotence)
_handshake_done = False


def fleet_rank():
    """This process's rank in the run: DMLC_RANK (launcher), else
    JAX_PROCESS_ID (multi-host jax), else 0. Read per call — launchers
    set it after import."""
    for var in ("DMLC_RANK", "JAX_PROCESS_ID"):
        val = os.environ.get(var)
        if val:
            try:
                return int(val)
            except ValueError:
                pass
    return 0


def _tags_enabled():
    # default ON: fleet aggregation needs every record to say who wrote it
    return os.environ.get("MXTPU_RANK_TAGS", "1") not in ("", "0")


def tag_record(record):
    """Stamp rank/pid/host identity onto a JSONL record (copy, don't
    mutate the caller's dict). MXTPU_RANK_TAGS=0 opts out."""
    if not _tags_enabled():
        return record
    record = dict(record)
    record.setdefault("rank", fleet_rank())
    record.setdefault("pid", os.getpid())
    record.setdefault("host", _host)
    return record


def _default_rank_sink():
    """``<MXTPU_RUN_DIR>/telemetry_r<rank>.jsonl`` when a run dir is
    configured (the fleet aggregator's discovery convention), else None."""
    run_dir = os.environ.get(RUN_DIR_ENV)
    if not run_dir:
        return None
    return os.path.join(run_dir, "telemetry_r%d.jsonl" % fleet_rank())


def write_clock_handshake(run_dir=None, rank=None):
    """Write ``clock_<rank>.json`` into the run dir: a paired
    (wall-clock, monotonic) reading taken at write time. The aggregator
    compares the file's mtime (stamped by the shared filesystem's
    clock) against the recorded wall reading to place every rank's
    timestamps on one timeline even when local clocks drift."""
    run_dir = run_dir or os.environ.get(RUN_DIR_ENV)
    if not run_dir:
        return None
    rank = fleet_rank() if rank is None else rank
    path = os.path.join(run_dir, "clock_%d.json" % rank)
    rec = {"rank": rank, "pid": os.getpid(), "host": _host,
           "wall": time.time(), "mono": time.monotonic()}
    try:
        os.makedirs(run_dir, exist_ok=True)
        tmp = path + ".tmp.%d" % os.getpid()
        with open(tmp, "w") as f:
            json.dump(rec, f)
        os.replace(tmp, path)
    except OSError:
        return None
    return path


def _maybe_handshake():
    """Write the clock handshake once per process, the first time the
    JSONL sink is actually used with a run dir configured."""
    global _handshake_done
    if _handshake_done or not os.environ.get(RUN_DIR_ENV):
        return
    _handshake_done = True
    write_clock_handshake()


def ensure_fleet_sink():
    """Adopt the per-rank run-dir sink if telemetry is enabled, a run
    dir is set, and no explicit MXTPU_TELEMETRY_FILE overrode it; write
    the clock handshake either way. Called from ``telemetry.enable()``."""
    if not _reg._enabled:
        return
    if _jsonl_path is None and not os.environ.get("MXTPU_TELEMETRY_FILE"):
        default = _default_rank_sink()
        if default is not None:
            set_jsonl_path(default)
    _maybe_handshake()


_jsonl_path = os.environ.get("MXTPU_TELEMETRY_FILE") or None
if _jsonl_path is None and _reg._enabled:
    # MXTPU_TELEMETRY=1 + MXTPU_RUN_DIR: land per-rank streams where the
    # fleet aggregator looks, with no further configuration
    _jsonl_path = _default_rank_sink()
_jsonl_fh = None
_prom_path = os.environ.get("MXTPU_TELEMETRY_PROM_FILE") or None
_prom_interval = float(os.environ.get("MXTPU_TELEMETRY_PROM_INTERVAL", "30"))
_prom_thread = None
_prom_stop = threading.Event()


def jsonl_path():
    return _jsonl_path


def set_jsonl_path(path):
    """Point (or stop, with None) the JSONL sink at ``path``."""
    global _jsonl_path, _jsonl_fh
    with _lock:
        if _jsonl_fh is not None:
            try:
                _jsonl_fh.close()
            except OSError:
                pass
            _jsonl_fh = None
        _jsonl_path = path or None


def _fh():
    """Open the JSONL sink lazily (caller holds _lock)."""
    global _jsonl_fh
    if _jsonl_fh is None and _jsonl_path is not None:
        _jsonl_fh = open(_jsonl_path, "a")
    return _jsonl_fh


def emit_span(record):
    if _jsonl_path is None:
        return
    _maybe_handshake()
    line = json.dumps(tag_record(record))
    with _lock:
        fh = _fh()
        if fh is not None:
            fh.write(line + "\n")
            fh.flush()


# any structured record ({"type": "anatomy"|"recompile"|...}) goes down
# the same sink; the span name is historical
emit_record = emit_span


def flush_metrics():
    """Append a registry snapshot to the JSONL sink and rewrite the
    Prometheus file, whichever are configured."""
    global _seq
    if _jsonl_path is not None:
        _maybe_handshake()
        with _lock:
            _seq += 1
            seq = _seq
        line = json.dumps(tag_record({
            "type": "metrics", "ts": time.time(), "seq": seq,
            "metrics": _reg.snapshot(),
        }))
        with _lock:
            fh = _fh()
            if fh is not None:
                fh.write(line + "\n")
                fh.flush()
    write_prometheus_file()


def set_prometheus_file(path, interval=None):
    """Configure the Prometheus text sink; interval > 0 starts the
    periodic writer thread."""
    global _prom_path, _prom_interval
    _prom_path = path or None
    if interval is not None:
        _prom_interval = float(interval)
    if _prom_path is not None and _prom_interval > 0:
        _start_prom_thread()


def write_prometheus_file():
    if _prom_path is None:
        return
    text = _reg.render_prometheus()
    tmp = _prom_path + ".tmp"
    try:
        with open(tmp, "w") as f:
            f.write(text)
        os.replace(tmp, _prom_path)  # atomic vs a concurrent scraper
    except OSError:
        pass  # export is advisory; never take training down


def _start_prom_thread():
    global _prom_thread
    if _prom_thread is not None and _prom_thread.is_alive():
        return
    _prom_stop.clear()

    def _loop():
        while not _prom_stop.wait(_prom_interval):
            if _reg._enabled:
                write_prometheus_file()

    _prom_thread = threading.Thread(
        target=_loop, name="mxtpu-telemetry-prom", daemon=True)
    _prom_thread.start()


def stop_prom_thread():
    _prom_stop.set()


# -- device memory gauges ---------------------------------------------
def sample_device_memory():
    """Read each local device's memory_stats() into gauges. Safe to call
    per step: backends without stats (CPU) return None and are skipped."""
    if not _reg._enabled:
        return
    import jax

    for dev in jax.local_devices():
        try:
            stats = dev.memory_stats()
        except Exception:  # noqa: BLE001 — backend-dependent surface
            stats = None
        if not stats:
            continue
        for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if key in stats:
                _reg.gauge("device.memory." + key).set(
                    stats[key], device=str(dev.id))


def _at_exit():
    """Interpreter-exit flush: a crashed-after-N-epochs run still leaves
    its last metrics snapshot on disk."""
    if _reg._enabled and (_jsonl_path is not None or _prom_path is not None):
        try:
            flush_metrics()
        except Exception:  # noqa: BLE001 — exit path must not raise
            pass


atexit.register(_at_exit)

if (_prom_path is not None and _prom_interval > 0
        and os.environ.get("MXTPU_TELEMETRY_PROM_FILE")):
    _start_prom_thread()
