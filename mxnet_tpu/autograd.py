"""Imperative autograd.

Parity: reference ``src/ndarray/autograd.{h,cc}`` + python
``contrib/autograd.py`` (mark_variables, backward, set_is_training,
grad_and_loss/grad decorators). The reference records an AGNode tape and
replays it through a GraphExecutor; here the tape replays as a pure JAX
function of the marked variables and ``jax.vjp`` produces the gradients —
the NNVM Gradient pass is jax's AD.
"""
from __future__ import annotations

import functools
import threading

from .base import MXNetError

_state = threading.local()


def _st():
    if not hasattr(_state, "recording"):
        _state.recording = False
        _state.training = False
        _state.tape = []  # list of (opdef, attrs, input NDArrays, output NDArrays)
        _state.marked = {}  # id(NDArray) -> grad NDArray
        _state.grad_reqs = {}
    return _state


def is_recording():
    return _st().recording


def is_training():
    return _st().training


def set_is_training(train_mode):
    """Parity: MXAutogradSetIsTraining. Returns previous state."""
    st = _st()
    prev = st.training
    st.training = bool(train_mode)
    st.recording = bool(train_mode)
    return prev


class train_section:
    """``with autograd.train_section():`` — reference contrib/autograd.py."""

    def __enter__(self):
        self._prev = set_is_training(True)
        return self

    def __exit__(self, *args):
        set_is_training(self._prev)


class test_section:
    def __enter__(self):
        self._prev = set_is_training(False)

    def __exit__(self, *args):
        set_is_training(self._prev)


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers to variables (parity: MXAutogradMarkVariables)."""
    st = _st()
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for var, grad, req in zip(variables, gradients, grad_reqs):
        st.marked[id(var)] = (var, grad)
        st.grad_reqs[id(var)] = req


def record_op(opdef, attrs, inputs, outputs):
    """Called by the imperative invoke path while recording."""
    st = _st()
    st.tape.append((opdef, dict(attrs), list(inputs), list(outputs)))


def backward(outputs, out_grads=None, retain_graph=False):
    """Replay the tape as a jax function of the marked variables and write
    gradients into their attached buffers."""
    import jax
    import jax.numpy as jnp

    from .ndarray import NDArray

    st = _st()
    if not st.marked:
        raise MXNetError("autograd.backward: no variables marked")
    tape = list(st.tape)
    var_ids = list(st.marked.keys())
    var_arrays = [st.marked[i][0] for i in var_ids]

    # map from NDArray identity to its position in the replay environment
    def replay(var_values):
        env = {i: v for i, v in zip(var_ids, var_values)}

        def lookup(x):
            from .ndarray import NDArray as _ND

            if not isinstance(x, _ND):
                return x  # constant input recorded as a raw array
            if id(x) in env:
                return env[id(x)]
            return x._data

        for opdef, attrs, ins, outs in tape:
            in_vals = [lookup(x) for x in ins]
            result = opdef.fcompute(attrs, in_vals, True)
            for o, v in zip(outs, result):
                env[id(o)] = v
        return [env.get(id(o), o._data) for o in outputs]

    primals = [v._data for v in var_arrays]
    outs, vjp_fn = jax.vjp(lambda *vs: tuple(replay(list(vs))), *primals)
    if out_grads is None:
        cts = tuple(jnp.ones_like(o) for o in outs)
    else:
        cts = tuple(
            g._data if isinstance(g, NDArray) else jnp.asarray(g) for g in out_grads
        )
    grads = vjp_fn(cts)
    for i, g in zip(var_ids, grads):
        var, gbuf = st.marked[i]
        req = st.grad_reqs.get(i, "write")
        if req == "null":
            continue
        if req == "add":
            gbuf._data = gbuf._data + g
        else:
            gbuf._data = g
    if not retain_graph:
        st.tape = []


def compute_gradient(outputs):
    """Deprecated reference API alias."""
    backward(outputs)


def grad_and_loss(func, argnum=None):
    """Decorator returning (gradients, loss) (parity contrib/autograd.py)."""

    @functools.wraps(func)
    def wrapped(*args):
        from . import ndarray as nd
        from .ndarray import NDArray

        variables = list(args)
        if argnum is not None:
            argnums = [argnum] if isinstance(argnum, int) else list(argnum)
            variables = [args[i] for i in argnums]
        for x in variables:
            if not isinstance(x, NDArray):
                raise MXNetError("variables must be NDArrays")
        grads = [nd.zeros_like(x) for x in variables]
        mark_variables(variables, grads)
        prev = set_is_training(True)
        try:
            outputs = func(*args)
        finally:
            set_is_training(prev)
        backward([outputs] if isinstance(outputs, NDArray) else outputs)
        return grads, outputs

    return wrapped


def grad(func, argnum=None):
    grad_with_loss_func = grad_and_loss(func, argnum)

    @functools.wraps(grad_with_loss_func)
    def wrapped(*args):
        return grad_with_loss_func(*args)[0]

    return wrapped
