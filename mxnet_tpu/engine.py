"""Host-side dependency engine.

Parity: reference ``src/engine/`` (SURVEY.md §2 N1). On TPU the device-side
scheduling role of the reference's ThreadedEnginePerDevice is played by
XLA's async dispatch: jax ops return futures immediately and data
dependencies serialize execution per device — exactly the WAR/WAW/RAW
discipline ThreadedVar implements, but tracked by value instead of by
handle. What remains host-side (file IO, KVStore host reductions, decode
workers) still benefits from an explicit dependency scheduler, so this
module provides one with the reference's interface:

- ``push(fn, const_vars, mutable_vars)`` — async execute once deps drain
  (Engine::PushAsync, engine.h:147)
- ``Var`` read/write queues (ThreadedVar, threaded_engine.h:93)
- ``wait_for_var`` / ``wait_for_all`` (WaitForVar/WaitForAll)
- ``NaiveEngine`` (synchronous) selected via MXNET_ENGINE_TYPE — the same
  debug escape hatch the reference documents (threaded_engine.h:329).
"""
from __future__ import annotations

import heapq
import os
import sys
import threading
import time
import traceback
from collections import deque

from .base import MXNetError, get_env
from . import telemetry as _tm

# module-level handles: .inc()/.set()/.observe() are guarded no-ops
# while telemetry is disabled, so the hot path pays one flag check
_M_OPS_PUSHED = _tm.counter(
    "engine.ops_pushed", "ops pushed to the host dependency engine")
_M_OPS_EXECUTED = _tm.counter(
    "engine.ops_executed", "ops executed by engine workers")
_M_OP_ERRORS = _tm.counter(
    "engine.op_errors", "async ops that raised (surfaced via raise_pending)")
_M_WORKER_WAIT = _tm.counter(
    "engine.worker_wait_seconds",
    "cumulative time workers spent waiting for runnable ops")
_G_QUEUE_DEPTH = _tm.gauge(
    "engine.queue_depth", "ready-queue depth at last dispatch/pop")
_H_OP_SECONDS = _tm.histogram(
    "engine.op_seconds", "execution time of engine-scheduled ops")


class Var:
    """A dependency variable with read/write queues (ThreadedVar)."""

    __slots__ = ("_lock", "_queue", "_pending_write", "_num_pending_reads",
                 "_last_opr")

    def __init__(self):
        self._lock = threading.Lock()
        self._queue = deque()  # of _OprBlock waiting on this var
        self._pending_write = False
        self._num_pending_reads = 0
        self._last_opr = None  # most recently PUSHED op touching this var


class _OprBlock:
    __slots__ = ("fn", "const_vars", "mutable_vars", "wait", "done", "lock",
                 "priority", "name")

    def __init__(self, fn, const_vars, mutable_vars, priority=0, name=None):
        self.fn = fn
        self.const_vars = const_vars
        self.mutable_vars = mutable_vars
        self.wait = 0
        self.done = threading.Event()
        self.lock = threading.Lock()
        self.priority = priority
        self.name = name


class ThreadedEngine:
    """Asynchronous host-side dependency engine (ThreadedEnginePooled).

    Ready-to-run ops dispatch through a PRIORITY heap (higher ``priority``
    runs first when workers are contended), the discipline the reference
    uses to overlap gradient communication with backward: push(key,
    priority=-param_index) makes the front layers' reduces jump the queue
    so the next forward can start sooner (reference
    src/kvstore/comm.h kCPUPrioritized reduce + engine PushAsync
    priority)."""

    def __init__(self, num_workers=None):
        if num_workers is None:
            num_workers = get_env("MXNET_CPU_WORKER_NTHREADS", 4)
        self._lock = threading.Lock()
        self._inflight = 0
        self._all_done = threading.Condition(self._lock)
        self._ready = []  # heap of (-priority, seq, opr)
        self._ready_cv = threading.Condition()
        self._seq = 0
        self._trace = None  # list when tracing, else None
        # op exceptions: recorded here (workers never die from an op
        # failure) and re-raised on the CALLER's thread by
        # raise_pending() — kvstore calls it at every API entry, so a
        # failed async push/pull stops training deterministically
        # instead of silently dropping updates
        self._errors = []
        self._workers = []
        for i in range(num_workers):
            t = threading.Thread(
                target=self._worker, daemon=True,
                name="mxtpu-engine-%d" % i)
            t.start()
            self._workers.append(t)

    def new_variable(self):
        return Var()

    # -- tracing (test/diagnostic hook: records execution order) --------
    def start_trace(self):
        """Begin recording executed ops as dicts (name, priority, start,
        end, thread). Returns the live list; stop_trace() detaches it."""
        self._trace = []
        return self._trace

    def stop_trace(self):
        t, self._trace = self._trace, None
        return t

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0,
             name=None):
        """Schedule fn once all vars' prior conflicting ops complete."""
        const_vars = list(const_vars)
        mutable_vars = list(mutable_vars)
        self._check_duplicate(const_vars, mutable_vars)
        _M_OPS_PUSHED.inc()
        opr = _OprBlock(fn, const_vars, mutable_vars, priority, name)
        with self._lock:
            self._inflight += 1
        # Self-hold refcount: opr.wait starts at 1 so a producer that
        # completes DURING this enqueue loop can decrement freely without
        # racing a later bulk assignment (the increment happens-before
        # the queue append, both under the var lock, so _on_complete can
        # only ever see an already-counted entry).
        opr.wait = 1
        for var in const_vars:
            with var._lock:
                var._last_opr = opr
                if var._pending_write or var._queue:
                    with opr.lock:
                        opr.wait += 1
                    var._queue.append(("r", opr))
                else:
                    var._num_pending_reads += 1
        for var in mutable_vars:
            with var._lock:
                var._last_opr = opr
                if var._pending_write or var._num_pending_reads or var._queue:
                    with opr.lock:
                        opr.wait += 1
                    var._queue.append(("w", opr))
                else:
                    var._pending_write = True
        with opr.lock:
            opr.wait -= 1  # release the self-hold
            ready = opr.wait == 0
        if ready:
            self._dispatch(opr)
        return opr

    def _check_duplicate(self, const_vars, mutable_vars):
        mset = set(id(v) for v in mutable_vars)
        if len(mset) != len(mutable_vars):
            raise MXNetError("duplicate mutable vars")
        for v in const_vars:
            if id(v) in mset:
                raise MXNetError(
                    "var appears in both const_vars and mutable_vars"
                )

    def _dispatch(self, opr):
        with self._ready_cv:
            heapq.heappush(self._ready, (-opr.priority, self._seq, opr))
            self._seq += 1
            if _tm.enabled():
                _G_QUEUE_DEPTH.set(len(self._ready))
            self._ready_cv.notify()

    def _worker(self):
        while True:
            with self._ready_cv:
                if not self._ready:
                    t0 = time.monotonic()
                    while not self._ready:
                        self._ready_cv.wait()
                    _M_WORKER_WAIT.inc(time.monotonic() - t0)
                _, _, opr = heapq.heappop(self._ready)
                if _tm.enabled():
                    _G_QUEUE_DEPTH.set(len(self._ready))
            self._execute(opr)

    def _execute(self, opr):
        t0 = time.monotonic()
        try:
            opr.fn()
        except BaseException as e:  # noqa: BLE001 — worker must survive
            # A raising op must NOT kill the worker (a dead worker
            # eventually deadlocks every dependent op); record for
            # raise_pending() and keep going.
            self._errors.append(e)
            _M_OP_ERRORS.inc()
            traceback.print_exc(file=sys.stderr)
        finally:
            _M_OPS_EXECUTED.inc()
            if _tm.enabled():
                _H_OP_SECONDS.observe(time.monotonic() - t0)
            trace = self._trace
            if trace is not None:
                trace.append({
                    "name": opr.name, "priority": opr.priority,
                    "start": t0, "end": time.monotonic(),
                    "thread": threading.current_thread().name,
                })
            self._on_complete(opr)

    def raise_pending(self):
        """Re-raise the first recorded async-op exception on the
        caller's thread (clearing the queue). No-op if none."""
        if self._errors:
            errs, self._errors = self._errors, []
            raise errs[0]

    def _on_complete(self, opr):
        """CompleteReadDependency/CompleteWriteDependency + trigger
        successors (ThreadedEngine::OnComplete, threaded_engine.cc:351)."""
        to_dispatch = []
        for var in opr.const_vars:
            with var._lock:
                var._num_pending_reads -= 1
                if var._num_pending_reads == 0:
                    to_dispatch.extend(self._drain(var))
        for var in opr.mutable_vars:
            with var._lock:
                var._pending_write = False
                to_dispatch.extend(self._drain(var))
        for nxt in to_dispatch:
            with nxt.lock:
                nxt.wait -= 1
                ready = nxt.wait == 0
            if ready:
                self._dispatch(nxt)
        opr.done.set()
        with self._lock:
            self._inflight -= 1
            if self._inflight == 0:
                self._all_done.notify_all()

    def _drain(self, var):
        """Pop newly-runnable ops off a var's queue (caller holds var lock)."""
        out = []
        while var._queue:
            mode, opr = var._queue[0]
            if mode == "r":
                if var._pending_write:
                    break
                var._queue.popleft()
                var._num_pending_reads += 1
                out.append(opr)
            else:
                if var._pending_write or var._num_pending_reads:
                    break
                var._queue.popleft()
                var._pending_write = True
                out.append(opr)
                break
        return out

    def wait_for_var(self, var):
        done = threading.Event()
        self.push(done.set, const_vars=[var])
        done.wait()

    def wait_last(self, var):
        """Cheaper read-barrier: wait for the most recently PUSHED op on
        var (whose completion implies every earlier WRITE on var is
        done — var grants are FIFO). Used by NDArray._drain_engine on
        the per-batch hot path, where pushing a sentinel op per array
        per step (wait_for_var) measurably costs throughput."""
        opr = var._last_opr
        if opr is not None:
            opr.done.wait()

    def wait_for_all(self):
        with self._lock:
            while self._inflight:
                self._all_done.wait()


class NaiveEngine:
    """Synchronous engine for debugging (naive_engine.cc:16)."""

    def new_variable(self):
        return Var()

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0,
             name=None):
        _M_OPS_PUSHED.inc()
        fn()
        _M_OPS_EXECUTED.inc()

    def raise_pending(self):
        pass

    def wait_for_var(self, var):
        pass

    def wait_last(self, var):
        pass

    def wait_for_all(self):
        pass

    def start_trace(self):
        return []

    def stop_trace(self):
        return []


_ENGINE = None


def get():
    """Engine singleton, type from MXNET_ENGINE_TYPE (engine.cc:13).
    Default prefers the native C++ engine (mxnet_tpu/src/engine.cc) when
    the toolchain built it; NaiveEngine remains the synchronous debug
    fallback exactly as in the reference."""
    global _ENGINE
    if _ENGINE is None:
        etype = os.environ.get("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")
        if etype == "NaiveEngine":
            _ENGINE = NaiveEngine()
        elif etype == "ThreadedEngine":  # explicit python engine
            _ENGINE = ThreadedEngine()
        else:
            try:
                from .native import NativeEngine

                _ENGINE = NativeEngine(
                    get_env("MXNET_CPU_WORKER_NTHREADS", 4)
                )
            except Exception:
                _ENGINE = ThreadedEngine()
    return _ENGINE


_COMM_ENGINE = None


def comm():
    """The COMMUNICATION engine: schedules KVStore push/pull host work
    (reduce, cross-process allreduce, optimizer update, broadcast-copy)
    so gradient sync overlaps the python train loop the way the
    reference's engine-scheduled kvstore ops overlap backward
    (src/kvstore/comm.h kCPUPrioritized; SURVEY §5.8 "the key scheduling
    idea to preserve").

    Always the python ThreadedEngine (or NaiveEngine under
    MXNET_ENGINE_TYPE=NaiveEngine — the same synchronous debug toggle
    governs both engines): comm ops are chunky host-side reductions
    where dispatch overhead is irrelevant, and the python engine carries
    the priority heap + execution trace the kvstore tests assert on.
    Separate from get() so IO prefetch load can never starve gradient
    sync (the reference likewise splits IO and comm thread pools)."""
    global _COMM_ENGINE
    if _COMM_ENGINE is None:
        if os.environ.get("MXNET_ENGINE_TYPE") == "NaiveEngine":
            _COMM_ENGINE = NaiveEngine()
        else:
            _COMM_ENGINE = ThreadedEngine(
                get_env("MXNET_KVSTORE_NTHREADS", 4))
    return _COMM_ENGINE
