"""Host-side dependency engine.

Parity: reference ``src/engine/`` (SURVEY.md §2 N1). On TPU the device-side
scheduling role of the reference's ThreadedEnginePerDevice is played by
XLA's async dispatch: jax ops return futures immediately and data
dependencies serialize execution per device — exactly the WAR/WAW/RAW
discipline ThreadedVar implements, but tracked by value instead of by
handle. What remains host-side (file IO, KVStore host reductions, decode
workers) still benefits from an explicit dependency scheduler, so this
module provides one with the reference's interface:

- ``push(fn, const_vars, mutable_vars)`` — async execute once deps drain
  (Engine::PushAsync, engine.h:147)
- ``Var`` read/write queues (ThreadedVar, threaded_engine.h:93)
- ``wait_for_var`` / ``wait_for_all`` (WaitForVar/WaitForAll)
- ``NaiveEngine`` (synchronous) selected via MXNET_ENGINE_TYPE — the same
  debug escape hatch the reference documents (threaded_engine.h:329).
"""
from __future__ import annotations

import os
import threading
from collections import deque
from concurrent.futures import ThreadPoolExecutor

from .base import MXNetError, get_env


class Var:
    """A dependency variable with read/write queues (ThreadedVar)."""

    __slots__ = ("_lock", "_queue", "_pending_write", "_num_pending_reads")

    def __init__(self):
        self._lock = threading.Lock()
        self._queue = deque()  # of _OprBlock waiting on this var
        self._pending_write = False
        self._num_pending_reads = 0


class _OprBlock:
    __slots__ = ("fn", "const_vars", "mutable_vars", "wait", "done", "lock")

    def __init__(self, fn, const_vars, mutable_vars):
        self.fn = fn
        self.const_vars = const_vars
        self.mutable_vars = mutable_vars
        self.wait = 0
        self.done = threading.Event()
        self.lock = threading.Lock()


class ThreadedEngine:
    """Asynchronous host-side dependency engine (ThreadedEnginePooled)."""

    def __init__(self, num_workers=None):
        if num_workers is None:
            num_workers = get_env("MXNET_CPU_WORKER_NTHREADS", 4)
        self._pool = ThreadPoolExecutor(max_workers=num_workers)
        self._lock = threading.Lock()
        self._inflight = 0
        self._all_done = threading.Condition(self._lock)

    def new_variable(self):
        return Var()

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0):
        """Schedule fn once all vars' prior conflicting ops complete."""
        const_vars = list(const_vars)
        mutable_vars = list(mutable_vars)
        self._check_duplicate(const_vars, mutable_vars)
        opr = _OprBlock(fn, const_vars, mutable_vars)
        with self._lock:
            self._inflight += 1
        # Self-hold refcount: opr.wait starts at 1 so a producer that
        # completes DURING this enqueue loop can decrement freely without
        # racing a later bulk assignment (the increment happens-before
        # the queue append, both under the var lock, so _on_complete can
        # only ever see an already-counted entry).
        opr.wait = 1
        for var in const_vars:
            with var._lock:
                if var._pending_write or var._queue:
                    with opr.lock:
                        opr.wait += 1
                    var._queue.append(("r", opr))
                else:
                    var._num_pending_reads += 1
        for var in mutable_vars:
            with var._lock:
                if var._pending_write or var._num_pending_reads or var._queue:
                    with opr.lock:
                        opr.wait += 1
                    var._queue.append(("w", opr))
                else:
                    var._pending_write = True
        with opr.lock:
            opr.wait -= 1  # release the self-hold
            ready = opr.wait == 0
        if ready:
            self._dispatch(opr)
        return opr

    def _check_duplicate(self, const_vars, mutable_vars):
        mset = set(id(v) for v in mutable_vars)
        if len(mset) != len(mutable_vars):
            raise MXNetError("duplicate mutable vars")
        for v in const_vars:
            if id(v) in mset:
                raise MXNetError(
                    "var appears in both const_vars and mutable_vars"
                )

    def _dispatch(self, opr):
        self._pool.submit(self._execute, opr)

    def _execute(self, opr):
        try:
            opr.fn()
        finally:
            self._on_complete(opr)

    def _on_complete(self, opr):
        """CompleteReadDependency/CompleteWriteDependency + trigger
        successors (ThreadedEngine::OnComplete, threaded_engine.cc:351)."""
        to_dispatch = []
        for var in opr.const_vars:
            with var._lock:
                var._num_pending_reads -= 1
                if var._num_pending_reads == 0:
                    to_dispatch.extend(self._drain(var))
        for var in opr.mutable_vars:
            with var._lock:
                var._pending_write = False
                to_dispatch.extend(self._drain(var))
        for nxt in to_dispatch:
            with nxt.lock:
                nxt.wait -= 1
                ready = nxt.wait == 0
            if ready:
                self._dispatch(nxt)
        opr.done.set()
        with self._lock:
            self._inflight -= 1
            if self._inflight == 0:
                self._all_done.notify_all()

    def _drain(self, var):
        """Pop newly-runnable ops off a var's queue (caller holds var lock)."""
        out = []
        while var._queue:
            mode, opr = var._queue[0]
            if mode == "r":
                if var._pending_write:
                    break
                var._queue.popleft()
                var._num_pending_reads += 1
                out.append(opr)
            else:
                if var._pending_write or var._num_pending_reads:
                    break
                var._queue.popleft()
                var._pending_write = True
                out.append(opr)
                break
        return out

    def wait_for_var(self, var):
        done = threading.Event()
        self.push(done.set, const_vars=[var])
        done.wait()

    def wait_for_all(self):
        with self._lock:
            while self._inflight:
                self._all_done.wait()


class NaiveEngine:
    """Synchronous engine for debugging (naive_engine.cc:16)."""

    def new_variable(self):
        return Var()

    def push(self, fn, const_vars=(), mutable_vars=(), priority=0):
        fn()

    def wait_for_var(self, var):
        pass

    def wait_for_all(self):
        pass


_ENGINE = None


def get():
    """Engine singleton, type from MXNET_ENGINE_TYPE (engine.cc:13).
    Default prefers the native C++ engine (mxnet_tpu/src/engine.cc) when
    the toolchain built it; NaiveEngine remains the synchronous debug
    fallback exactly as in the reference."""
    global _ENGINE
    if _ENGINE is None:
        etype = os.environ.get("MXNET_ENGINE_TYPE", "ThreadedEnginePerDevice")
        if etype == "NaiveEngine":
            _ENGINE = NaiveEngine()
        elif etype == "ThreadedEngine":  # explicit python engine
            _ENGINE = ThreadedEngine()
        else:
            try:
                from .native import NativeEngine

                _ENGINE = NativeEngine(
                    get_env("MXNET_CPU_WORKER_NTHREADS", 4)
                )
            except Exception:
                _ENGINE = ThreadedEngine()
    return _ENGINE
