"""KVStore: key-value parameter synchronization.

Parity: reference ``python/mxnet/kvstore.py`` + ``src/kvstore/``
(KVStoreLocal, CommCPU/CommDevice, KVStoreDist over ps-lite). TPU-native
redesign per SURVEY.md §5.8: the parameter-server tier is deleted —

- ``local``/``device``: single-process multi-device reduce. The reference
  reduces on pinned CPU (CommCPU) or on one GPU with P2P (CommDevice);
  here values on accelerator devices are summed where they live and XLA
  inserts the transfers (ICI on a multi-chip host).
- ``dist_sync``/``dist_device_sync``/``dist_async``: multi-process modes.
  In a multi-host JAX setup gradients sync via psum over ICI/DCN inside
  the compiled step (see mxnet_tpu.parallel); this class keeps the
  reference's worker-facing API (rank/num_workers/barrier/set_optimizer)
  so training scripts run unmodified.

The key scheduling idea the reference encodes — push/pull are async engine
ops with priority = -param_index so backward-order layers sync first
(SURVEY.md §5.8) — is preserved by XLA latency-hiding scheduling when sync
happens inside the step; the explicit `priority` argument is accepted for
API parity.
"""
from __future__ import annotations

import os
import pickle

from . import ndarray as nd
from . import optimizer as opt
from .base import MXNetError
from .ndarray import NDArray


def _ctype_key_value(keys, vals):
    if isinstance(keys, (int, str)):
        keys = [keys]
        vals = [vals]
    out = []
    for k, v in zip(keys, vals):
        if isinstance(v, NDArray):
            v = [v]
        out.append((k, list(v)))
    return out


class KVStore(object):
    def __init__(self, kv_type="local"):
        self.type = kv_type
        self._store = {}
        self._updater = None
        self._barrier_count = 0
        # Multi-process distributed rank/size come from the JAX bootstrap
        # (jax.distributed) or the reference's DMLC_* env names.
        self._rank = int(os.environ.get("DMLC_RANK", os.environ.get("JAX_PROCESS_ID", 0)))
        self._size = int(
            os.environ.get("DMLC_NUM_WORKER", os.environ.get("JAX_NUM_PROCESSES", 1))
        )

    # ------------------------------------------------------------------
    def init(self, key, value):
        for k, vals in _ctype_key_value(key, value):
            if k in self._store:
                raise MXNetError("key %s already initialized" % str(k))
            self._store[k] = vals[0].copy()

    def push(self, key, value, priority=0):
        """Reduce value(s) into the store; updater applies if set.
        Parity: KVStoreLocal::Push (kvstore_local.h) — merged = sum over
        the per-device list (Comm::Reduce), then updater(key, merged,
        stored) or plain store write."""
        for k, vals in _ctype_key_value(key, value):
            if k not in self._store:
                raise MXNetError("key %s not initialized" % str(k))
            merged = self._reduce(vals)
            if self._updater is not None:
                self._updater(
                    k if isinstance(k, int) else self._str_key(k), merged,
                    self._store[k]
                )
            else:
                merged.copyto(self._store[k])

    def pull(self, key, out=None, priority=0):
        """Broadcast stored value to out array(s) (Comm::Broadcast)."""
        assert out is not None
        for k, outs in _ctype_key_value(key, out):
            if k not in self._store:
                raise MXNetError("key %s not initialized" % str(k))
            stored = self._store[k]
            for o in outs:
                stored.copyto(o)

    def _str_key(self, k):
        """Stable string-key → updater-index mapping (insertion order;
        NOT hash(): that's randomized per process and would break
        optimizer-state save/restore)."""
        if not hasattr(self, "_str_key_map"):
            self._str_key_map = {}
        if k not in self._str_key_map:
            self._str_key_map[k] = len(self._str_key_map)
        return self._str_key_map[k]

    def _reduce(self, vals):
        if len(vals) == 1:
            return vals[0]
        # sum where the first value lives; jax moves the shards over
        # ICI/PCIe as needed (reference: CommCPU pinned-host tree /
        # CommDevice GPU gather)
        merged = vals[0].copy()
        for v in vals[1:]:
            merged += v.as_in_context(merged.context)
        return merged

    # ------------------------------------------------------------------
    def set_updater(self, updater):
        self._updater = updater

    _set_updater = set_updater

    def set_optimizer(self, optimizer):
        """Parity kvstore.py:226: on dist stores the reference pickles the
        optimizer to the servers; with the PS tier deleted the optimizer
        always runs in-process."""
        if "dist" in self.type and self._size > 1:
            # serialize/deserialize to mirror the reference's server-side
            # transport (and catch unpicklable optimizers early)
            optimizer = pickle.loads(pickle.dumps(optimizer))
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    # ------------------------------------------------------------------
    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._size

    def _barrier(self):
        """Global barrier (reference: ps::Postoffice::Barrier). Multi-host
        jax programs synchronize implicitly at collective boundaries; an
        explicit barrier only matters cross-process."""
        if self._size > 1:
            import jax

            # a tiny psum across processes acts as the barrier
            try:
                from .parallel import barrier as _mesh_barrier

                _mesh_barrier()
            except Exception:
                pass

    def save_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("Cannot save states for distributed training")
        with open(fname, "wb") as fout:
            fout.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("Cannot load states for distributed training")
        with open(fname, "rb") as fin:
            self._updater.set_states(fin.read())

    def get_num_dead_node(self, node_id, timeout=60):
        """Parity kvstore.h:235 — PS heartbeats; with no PS tier, failed
        hosts surface as jax.distributed errors, so this reports 0."""
        return 0

    @property
    def barrier_before_exit(self):
        return True


def create(name="local"):
    """Create a KVStore (parity kvstore.py create). Accepted types mirror
    the reference: local / local_allreduce_cpu / local_allreduce_device /
    device / dist_sync / dist_device_sync / dist_async."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    valid = (
        "local", "local_allreduce_cpu", "local_allreduce_device", "device",
        "dist_sync", "dist_device_sync", "dist_async", "dist",
    )
    if name not in valid:
        raise MXNetError("unknown kvstore type %s" % name)
    return KVStore(name)
