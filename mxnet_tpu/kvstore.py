"""KVStore: key-value parameter synchronization.

Parity: reference ``python/mxnet/kvstore.py`` + ``src/kvstore/``
(KVStoreLocal, CommCPU/CommDevice, KVStoreDist over ps-lite). TPU-native
redesign per SURVEY.md §5.8: the parameter-server tier is deleted —

- ``local``/``device``: single-process multi-device reduce. The reference
  reduces on pinned CPU (CommCPU) or on one GPU with P2P (CommDevice);
  here values on accelerator devices are summed where they live and XLA
  inserts the transfers (ICI on a multi-chip host).
- ``dist_sync``/``dist_device_sync``/``dist_async``: multi-process modes.
  In a multi-host JAX setup gradients sync via psum over ICI/DCN inside
  the compiled step (see mxnet_tpu.parallel); this class keeps the
  reference's worker-facing API (rank/num_workers/barrier/set_optimizer)
  so training scripts run unmodified.

The key scheduling idea the reference encodes — push/pull are async engine
ops with priority = -param_index so front layers' syncs jump the queue and
overlap the rest of the train loop (SURVEY.md §5.8 "the key scheduling
idea to preserve"; reference src/kvstore/comm.h kCPUPrioritized +
python/mxnet/kvstore.py push(priority)) — is preserved two ways:

- fused path (ShardedTrainStep): sync happens inside the compiled step;
  XLA's latency-hiding scheduler owns the overlap.
- executor path (THIS class): push/pull are scheduled on the
  communication engine (engine.comm()) with the caller's priority and a
  per-key dependency Var, so the python thread returns immediately, the
  host reduce / cross-process allreduce / optimizer update runs on comm
  workers, and the next forward only waits for the specific weights it
  reads (NDArray engine-var discipline). Cross-process ops additionally
  chain on one Var so every rank issues collectives in the same order —
  a hard correctness requirement for collective-based allreduce that the
  reference's server tier never had to face (priority therefore cannot
  reorder DIST ops, only local ones).

MXNET_KVSTORE_ASYNC=0 restores the fully synchronous path (and
MXNET_ENGINE_TYPE=NaiveEngine makes every engine synchronous, same as
the reference's debug toggle).
"""
from __future__ import annotations

import os
import pickle
import threading
import time

import numpy as _np

from . import engine as _engine
from . import ndarray as nd
from . import optimizer as opt
from . import telemetry as _tm
from .base import MXNetError, bucket_bytes_env as _env_bucket_bytes
from .ndarray import NDArray
from .resilience import fault as _fault
from .resilience import retry as _retry

_M_PUSH_BYTES = _tm.counter(
    "kvstore.push_bytes", "Bytes pushed into the kvstore")
_M_PULL_BYTES = _tm.counter(
    "kvstore.pull_bytes", "Bytes pulled out of the kvstore")
_H_PUSH_SECONDS = _tm.histogram(
    "kvstore.push_seconds", "Latency of the engine-side push body "
    "(reduce + updater), per key")
_H_PULL_SECONDS = _tm.histogram(
    "kvstore.pull_seconds", "Latency of the engine-side pull body, per key")
_H_ALLREDUCE_SECONDS = _tm.histogram(
    "kvstore.allreduce_seconds", "Cross-process allreduce+update stage "
    "latency (dist stores)")
_H_BUCKET_BYTES = _tm.histogram(
    "kvstore.bucket_bytes", "Payload bytes per coalesced gradient bucket "
    "(kvstore GradBucketer flushes and fused flat-update plan buckets)")
_M_BUCKET_FLUSHES = _tm.counter(
    "kvstore.bucket_flushes", "GradBucketer flushes (one count per "
    "collective issued on the dist deferred-reduce queue)")
# same name mesh.py uses for cross-process collectives — the registry
# dedupes by name, so local reduces and gloo/jax collectives land in one
# anatomy 'collective' phase
_H_COLLECTIVE_SECONDS = _tm.histogram(
    "parallel.collective_seconds",
    "Wall time inside collective operations, by op")


def _nbytes(vals):
    return sum(int(v.size) * _np.dtype(v.dtype).itemsize for v in vals)


class _PendingPush(object):
    """One deferred dist stage-2 entry: the cross-process reduce+apply
    for a key whose local reduce (stage 1) is already in flight."""

    __slots__ = ("priority", "seq", "key", "upd_key", "box", "shape",
                 "dtype", "nbytes", "apply_fn")

    def __init__(self, priority, seq, key, upd_key, box, snap0, apply_fn):
        self.priority = priority
        self.seq = seq
        self.key = key
        self.upd_key = upd_key
        self.box = box  # filled by stage 1 on a comm worker
        self.shape = tuple(snap0.shape)
        self.dtype = _np.dtype(snap0.dtype)
        self.nbytes = int(snap0.size) * self.dtype.itemsize
        self.apply_fn = apply_fn


class GradBucketer(object):
    """Deferred-reduce queue for dist stores (tentpole part 2: bucketed,
    overlapped gradient collectives).

    The reference overlaps communication by making each key's push an
    engine op with priority=-index; our dist stage 2 additionally rides
    ONE chain var so every rank issues collectives in identical order —
    which used to mean strict CALL order, priority ignored. This class
    restores the priority discipline AND amortizes collective fixed
    cost: stage-2 entries accumulate here (caller thread, deterministic),
    and a flush (a) sorts them higher-priority-first, (b) packs them
    into size-capped same-dtype flat buckets (``MXTPU_BUCKET_BYTES``,
    default 4 MiB; 0 = one collective per key, the legacy shape), and
    (c) issues ONE collective per bucket, carving per-key views back out
    for the updater. Composition happens on the caller's thread from
    (priority, push order, shapes) alone — all ranks run the same
    script, so all ranks build identical buckets, preserving the
    lockstep collective order the chain var enforces.

    Flush triggers: accumulated bytes reach the cap; any pull (the pull
    must order after its key's deferred update); barrier / updater
    change / optimizer-state IO (quiescence points).

    Dtype-aware: buckets group by the pushed grad dtype and the byte cap
    counts ACTUAL itemsize (a bf16 model packs 2x the keys per bucket an
    fp32 model does). ``MXTPU_BUCKET_REDUCE_DTYPE=float32`` upcasts
    low-precision buckets for the cross-worker sum only — see
    _bucket_allreduce_apply."""

    def __init__(self, bucket_bytes):
        self.bucket_bytes = bucket_bytes
        self.pending = []
        self.pending_bytes = 0
        self._seq = 0

    def add(self, priority, key, upd_key, box, snap0, apply_fn):
        self.pending.append(_PendingPush(
            priority, self._seq, key, upd_key, box, snap0, apply_fn))
        self._seq += 1
        self.pending_bytes += self.pending[-1].nbytes
        return self.pending_bytes >= max(self.bucket_bytes, 1)

    def drain(self):
        """Priority-ordered (then FIFO) bucket composition; returns a
        list of same-dtype entry lists, each capped at bucket_bytes."""
        entries = self.pending
        self.pending = []
        self.pending_bytes = 0
        entries.sort(key=lambda e: (-e.priority, e.seq))
        buckets = []
        cur, cur_bytes = [], 0
        for e in entries:
            if cur and (cur[0].dtype != e.dtype
                        or cur_bytes + e.nbytes > self.bucket_bytes):
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(e)
            cur_bytes += e.nbytes
        if cur:
            buckets.append(cur)
        return buckets


def _ctype_key_value(keys, vals):
    if isinstance(keys, (int, str)):
        keys = [keys]
        vals = [vals]
    out = []
    for k, v in zip(keys, vals):
        if isinstance(v, NDArray):
            v = [v]
        out.append((k, list(v)))
    return out


class KVStore(object):
    def __init__(self, kv_type="local"):
        self.type = kv_type
        self._store = {}
        self._updater = None
        self._barrier_count = 0
        self._heartbeat = None
        self._key_vars = {}  # key -> engine Var (per-key push/pull order)
        self._update_lock = threading.Lock()  # updater/store mutation
        self._dist_chain = None  # lazily: serializes cross-process ops
        self._bucketer = GradBucketer(_env_bucket_bytes())
        if os.environ.get("MXNET_KVSTORE_ASYNC", "1") == "0":
            self._comm = _engine.NaiveEngine()
        else:
            self._comm = _engine.comm()
        # Multi-process distributed rank/size come from the JAX runtime
        # itself once a dist store is requested (the env names are only
        # the pre-init fallback): trusting env alone let round-2 report
        # a size the runtime never actually had.
        self._rank = int(os.environ.get(
            "DMLC_RANK", os.environ.get("JAX_PROCESS_ID", 0)))
        self._size = int(os.environ.get(
            "DMLC_NUM_WORKER", os.environ.get("JAX_NUM_PROCESSES", 1)))
        if "dist" in kv_type:
            import jax

            from .parallel import init_distributed

            # The reference joins the PS cluster at kvstore creation
            # (KVStore::InitPSEnv); the analog is joining the JAX
            # distributed runtime here, so scripts that only ever call
            # mx.kv.create('dist_sync') work unmodified under launch.py.
            init_distributed()
            env_size = self._size
            self._rank = jax.process_index()
            self._size = jax.process_count()
            if env_size > 1 and self._size == 1:
                raise MXNetError(
                    "kvstore %s: launcher env promises %d workers but this "
                    "process never joined a distributed JAX runtime "
                    "(missing/unreachable coordinator?) — refusing to "
                    "silently train un-synchronized" % (kv_type, env_size))
            # Liveness (SURVEY §5.3): under a launcher-provided run dir,
            # heartbeat so peers/watchdogs can see this worker is alive
            # (reference: Van heartbeats to the scheduler).
            from .parallel import heartbeat as _hb

            if _hb.run_dir() is not None:
                self._heartbeat = _hb.HeartbeatWriter(
                    _hb.run_dir(), self._rank).start()

    # ------------------------------------------------------------------
    def init(self, key, value):
        for k, vals in _ctype_key_value(key, value):
            if k in self._store:
                raise MXNetError("key %s already initialized" % str(k))
            v = vals[0]
            if self._is_dist:
                # Reference dist init: rank 0's value lands on the
                # servers and every worker pulls it — all workers start
                # identical whatever their local seeding did.
                from .parallel import mesh as _mesh

                v = nd.array(_mesh.broadcast_from_root(v.asnumpy()),
                             ctx=v.context, dtype=v.dtype)
            self._store[k] = v.copy()

    def _key_var(self, k):
        var = self._key_vars.get(k)
        if var is None:
            var = self._comm.new_variable()
            self._key_vars[k] = var
        return var

    def push(self, key, value, priority=0):
        """Reduce value(s) into the store; updater applies if set.

        Parity: KVStoreLocal::Push (kvstore_local.h) — merged = sum over
        the per-device list (Comm::Reduce), then updater(key, merged,
        stored) or plain store write. The whole body is an ASYNC comm-
        engine op (write on the key's Var, priority honored for local
        stores), so the caller's thread keeps dispatching — the overlap
        the reference gets from engine-scheduled kvstore ops."""
        self._comm.raise_pending()  # surface earlier async-op failures
        if self._heartbeat is not None:
            # progress beat from the hot path: a rank wedged in a
            # collective stops marking progress even though its liveness
            # daemon keeps beating (parallel/heartbeat.py)
            self._heartbeat.progress()
        for k, vals in _ctype_key_value(key, value):
            if k not in self._store:
                raise MXNetError("key %s not initialized" % str(k))
            # Resolved on the CALLER's thread: _str_key assigns updater
            # indices in first-seen order, which must be the script's
            # deterministic push order, not the workers' race order.
            upd_key = k if isinstance(k, int) else self._str_key(k)
            # Snapshot the jax arrays now — they are immutable values, so
            # the body is immune to the trainer overwriting the grad
            # NDArrays (next backward) before the op runs.
            snap = [NDArray(v._data) for v in vals]
            if _tm.enabled():
                _M_PUSH_BYTES.inc(_nbytes(snap))

            def _apply(merged, k, upd_key):
                with self._update_lock:
                    if self._updater is not None:
                        self._updater(upd_key, merged, self._store[k])
                    else:
                        merged.copyto(self._store[k])

            if not self._is_dist:
                # Single-process data-parallel: the cross-device reduce
                # below IS this run's collective, so it must be visible
                # to the anatomy 'collective' phase (the fleet view
                # attributes skew through it). The fault point fires on
                # the CALLER's thread and is timed into the same metric
                # — an injected delay_collective_ms therefore lands in
                # the collective phase, not smeared into dispatch.
                tc = time.perf_counter()
                _fault.fire("collective", key=k, local=True)
                _H_COLLECTIVE_SECONDS.observe(
                    time.perf_counter() - tc, op="local_reduce")

                def _do_push(snap=snap, k=k, upd_key=upd_key):
                    t0 = time.perf_counter()

                    def _reduce_body():
                        _fault.fire("kv_push", key=k)
                        return self._reduce(snap)

                    # Retry covers the reduce only — it reads immutable
                    # snapshots, so a re-run is exact. The updater is
                    # applied once, after a successful reduce (retrying
                    # through a half-applied update would double-step
                    # momentum).
                    merged = _retry.call(_reduce_body, name="kv.push")
                    _H_COLLECTIVE_SECONDS.observe(
                        time.perf_counter() - t0, op="local_reduce")
                    _apply(merged, k, upd_key)
                    _H_PUSH_SECONDS.observe(time.perf_counter() - t0)

                self._comm.push(_do_push, mutable_vars=[self._key_var(k)],
                                priority=priority, name="push:%s" % k)
                continue
            # DIST: two pipelined stages, the reference's Reduce -> server
            # push structure (kvstore_local.h Comm::Reduce, then the
            # merge_buf_ sum of kvstore_dist_server.h:163-200 minus the
            # server tier). Stage 1 (per-key var): local multi-device
            # reduce + host fetch — runs CONCURRENTLY across keys.
            # Stage 2 (key var + ONE chain var): gloo allreduce + update.
            # The chain makes every rank issue collectives in schedule
            # order — a hard correctness requirement for collective
            # allreduce (no server to absorb reordering), so priority
            # cannot reorder dist collectives; it still orders stage 1.
            # The pipeline win: key k+1's local reduce/fetch overlaps
            # key k's cross-process allreduce.
            box = {}

            def _local_reduce(snap=snap, box=box, k=k):
                try:
                    t0 = time.perf_counter()

                    def _reduce_body():
                        _fault.fire("kv_push", key=k)
                        return self._reduce(snap)

                    # Retryable: purely local, reads immutable snapshots.
                    # Stage 2's collective is NOT retried — see below.
                    merged = _retry.call(_reduce_body, name="kv.push")
                    _H_PUSH_SECONDS.observe(time.perf_counter() - t0)
                    box["host"] = merged.asnumpy()
                    box["ctx"] = merged.context
                    box["dtype"] = merged.dtype
                except BaseException as e:  # noqa: BLE001
                    # stage 2 must still ENTER the collective (peers are
                    # already committed to it — bailing here would wedge
                    # every other rank in gloo); it contributes zeros
                    # and the error surfaces on the caller's thread via
                    # raise_pending at the next kvstore call.
                    box["error"] = e
                    raise

            self._comm.push(_local_reduce,
                            mutable_vars=[self._key_var(k)],
                            priority=priority, name="reduce:%s" % k)
            # Stage 2 is DEFERRED into the bucketer (not enqueued yet):
            # later pushes can coalesce into the same collective, and the
            # drain order is priority-sorted rather than call-ordered.
            if self._bucketer.add(priority, k, upd_key, box, snap[0],
                                  _apply):
                self._flush_buckets()

    def _flush_buckets(self):
        """Drain the deferred-reduce queue: enqueue one engine op per
        coalesced bucket (priority-ordered composition — see
        GradBucketer). Runs on the caller's thread, so bucket contents
        and collective order are identical on every rank."""
        if not self._bucketer.pending:
            return
        if self._dist_chain is None:
            self._dist_chain = self._comm.new_variable()
        two_phase = os.environ.get("MXTPU_BUCKET_TWO_PHASE", "0") != "0"
        for entries in self._bucketer.drain():

            def _bucket_allreduce_apply(entries=entries,
                                        two_phase=two_phase):
                # Deliberately NO retry around this op: every rank
                # issues collectives in lockstep on the chain var, and a
                # rank re-entering an allreduce its peers already left
                # deadlocks the mesh. Collective failure is process-
                # fatal by design — recovery is watchdog restart +
                # checkpoint resume (resilience/checkpoint.py).
                import jax

                from .parallel import mesh as _mesh

                t0 = time.perf_counter()
                dtype = entries[0].dtype
                sizes = [int(_np.prod(e.shape)) if e.shape else 1
                         for e in entries]
                offsets = _np.cumsum([0] + sizes[:-1])
                flat = _np.zeros(int(sum(sizes)), dtype=dtype)
                for e, off, n in zip(entries, offsets, sizes):
                    # a failed stage 1 still contributes (zeros) to the
                    # collective — peers are already committed to it;
                    # its error surfaces via raise_pending
                    if "error" not in e.box:
                        flat[off:off + n] = e.box.pop("host").ravel()
                # MXTPU_BUCKET_REDUCE_DTYPE upcasts a low-precision
                # bucket for the SUM only (e.g. float32 accumulation of
                # bf16 grads: a W-worker sum in bf16 loses ~log2(W) of
                # bf16's 8 mantissa bits). Wire bytes go back up to the
                # accumulation width; the carve-back below re-casts each
                # key to its own dtype, so the updater sees the same
                # dtypes either way.
                rdt = os.environ.get("MXTPU_BUCKET_REDUCE_DTYPE")
                if rdt:
                    rdt = _np.dtype(rdt)
                    if rdt != dtype:
                        flat = flat.astype(rdt)
                _H_BUCKET_BYTES.observe(flat.nbytes, path="dist")
                _M_BUCKET_FLUSHES.inc()
                if two_phase:
                    # explicit reduce-scatter + all-gather round trip
                    # (the sharded-update decomposition) instead of one
                    # allreduce; same bytes on a ring, but keeps the
                    # whole bucket path on the primitives the fused
                    # sharded update uses
                    nproc = jax.process_count()
                    padded = -(-flat.size // nproc) * nproc
                    buf = _np.zeros(padded, dtype=flat.dtype)
                    buf[:flat.size] = flat
                    shard = _mesh.reduce_scatter_sum(buf)
                    summed = _mesh.all_gather(shard)[:flat.size]
                else:
                    summed = _mesh.allreduce_sum(flat)
                for e, off, n in zip(entries, offsets, sizes):
                    if "error" in e.box:
                        continue
                    merged = nd.array(
                        summed[off:off + n].reshape(e.shape),
                        ctx=e.box.pop("ctx"), dtype=e.box.pop("dtype"))
                    e.apply_fn(merged, e.key, e.upd_key)
                _H_ALLREDUCE_SECONDS.observe(time.perf_counter() - t0)

            mutable = [self._dist_chain]
            seen = set()
            for e in entries:
                var = self._key_var(e.key)
                if id(var) not in seen:  # same key pushed twice
                    seen.add(id(var))
                    mutable.append(var)
            name = ("push:%s" % entries[0].key if len(entries) == 1
                    else "push_bucket:%s" % "+".join(
                        str(e.key) for e in entries))
            self._comm.push(_bucket_allreduce_apply,
                            mutable_vars=mutable,
                            priority=max(e.priority for e in entries),
                            name=name)

    def pull(self, key, out=None, priority=0):
        """Broadcast stored value to out array(s) (Comm::Broadcast).
        Async like push: reads the key's Var (so it orders after the
        in-flight push of the same key), writes the out arrays' Vars;
        any reader of those NDArrays (executor forward, asnumpy) drains
        automatically."""
        assert out is not None
        self._comm.raise_pending()
        if self._heartbeat is not None:
            self._heartbeat.progress()
        # a pull must order after its key's deferred update: drain the
        # bucketer BEFORE enqueueing (buckets mix keys, so drain all)
        self._flush_buckets()
        for k, outs in _ctype_key_value(key, out):
            if k not in self._store:
                raise MXNetError("key %s not initialized" % str(k))
            if _tm.enabled():
                _M_PULL_BYTES.inc(_nbytes(outs))

            def _do_pull(k=k, outs=outs):
                import jax

                def _body():
                    t0 = time.perf_counter()
                    _fault.fire("kv_pull", key=k)
                    stored = self._store[k]
                    for o in outs:
                        # direct _data write, NOT copyto: copyto drains
                        # the target's engine var, which is held by THIS
                        # op — calling it here would self-deadlock
                        o._data = jax.device_put(stored._data,
                                                 o._data.device)
                    _H_PULL_SECONDS.observe(time.perf_counter() - t0)

                # device_put is idempotent (pure read of the stored
                # value, rebind of the out handle), so pulls retry whole.
                _retry.call(_body, name="kv.pull")

            out_vars = []
            seen = set()
            for o in outs:
                var = o._engine_var(self._comm)
                if id(var) not in seen:
                    seen.add(id(var))
                    out_vars.append(var)
            self._comm.push(_do_pull, const_vars=[self._key_var(k)],
                            mutable_vars=out_vars, priority=priority,
                            name="pull:%s" % k)

    def _str_key(self, k):
        """Stable string-key → updater-index mapping (insertion order;
        NOT hash(): that's randomized per process and would break
        optimizer-state save/restore)."""
        if not hasattr(self, "_str_key_map"):
            self._str_key_map = {}
        if k not in self._str_key_map:
            self._str_key_map[k] = len(self._str_key_map)
        return self._str_key_map[k]

    def _reduce(self, vals):
        if len(vals) == 1:
            return vals[0]
        # sum where the first value lives; jax moves the shards over
        # ICI/PCIe as needed (reference: CommCPU pinned-host tree /
        # CommDevice GPU gather)
        merged = vals[0].copy()
        for v in vals[1:]:
            merged += v.as_in_context(merged.context)
        return merged

    # ------------------------------------------------------------------
    def set_updater(self, updater):
        self._flush_buckets()  # deferred pushes use the old updater
        self._comm.wait_for_all()  # in-flight pushes use the old updater
        self._updater = updater

    _set_updater = set_updater

    def set_optimizer(self, optimizer):
        """Parity kvstore.py:226: on dist stores the reference pickles the
        optimizer to the servers; with the PS tier deleted the optimizer
        always runs in-process."""
        if "dist" in self.type and self._size > 1:
            # serialize/deserialize to mirror the reference's server-side
            # transport (and catch unpicklable optimizers early). The
            # bound symbol is transport-hostile (op defs hold lambdas)
            # and already spent: set_lr_mult/set_wd_mult read it at
            # construction, so the wire copy travels without it.
            import copy

            clone = copy.copy(optimizer)  # caller's object untouched
            clone.sym = None
            optimizer = pickle.loads(pickle.dumps(clone))
        self._flush_buckets()
        self._comm.wait_for_all()
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    # ------------------------------------------------------------------
    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._size

    @property
    def _is_dist(self):
        return "dist" in self.type and self._size > 1

    def _barrier(self):
        """Global barrier (reference: ps::Postoffice::Barrier).

        Must hard-fail if a peer is unreachable — a barrier that
        swallows errors silently un-synchronizes exactly the path that
        exists to synchronize (round-1/2 finding, fixed)."""
        if self._heartbeat is not None:
            self._heartbeat.progress()
        self._flush_buckets()  # a barrier implies the queue is drained
        self._comm.wait_for_all()  # a barrier implies local quiescence
        if self._size > 1:
            from .parallel import barrier as _mesh_barrier

            self._barrier_count += 1
            _mesh_barrier("kvstore-barrier-%d" % self._barrier_count)

    def save_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("Cannot save states for distributed training")
        from .resilience.checkpoint import atomic_file

        self._flush_buckets()
        self._comm.wait_for_all()  # states must include in-flight updates
        with atomic_file(fname) as fout:
            fout.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("Cannot load states for distributed training")
        self._flush_buckets()
        self._comm.wait_for_all()
        with open(fname, "rb") as fin:
            self._updater.set_states(fin.read())

    def get_num_dead_node(self, node_id, timeout=60):
        """Parity kvstore.h:235-244: number of peers whose heartbeat went
        stale. Heartbeats ride the launcher's run dir (parallel/
        heartbeat.py) rather than a scheduler process; outside a
        launched job there is nothing to be dead, so 0."""
        from .parallel import heartbeat as _hb

        directory = _hb.run_dir()
        if directory is None or self._size <= 1:
            return 0
        return len(_hb.dead_nodes(directory, self._size, timeout))

    @property
    def barrier_before_exit(self):
        return True


def create(name="local"):
    """Create a KVStore (parity kvstore.py create). Accepted types mirror
    the reference: local / local_allreduce_cpu / local_allreduce_device /
    device / dist_sync / dist_device_sync / dist_async."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    valid = (
        "local", "local_allreduce_cpu", "local_allreduce_device", "device",
        "dist_sync", "dist_device_sync", "dist_async", "dist",
    )
    if name not in valid:
        raise MXNetError("unknown kvstore type %s" % name)
    return KVStore(name)
