"""KVStore: key-value parameter synchronization.

Parity: reference ``python/mxnet/kvstore.py`` + ``src/kvstore/``
(KVStoreLocal, CommCPU/CommDevice, KVStoreDist over ps-lite). TPU-native
redesign per SURVEY.md §5.8: the parameter-server tier is deleted —

- ``local``/``device``: single-process multi-device reduce. The reference
  reduces on pinned CPU (CommCPU) or on one GPU with P2P (CommDevice);
  here values on accelerator devices are summed where they live and XLA
  inserts the transfers (ICI on a multi-chip host).
- ``dist_sync``/``dist_device_sync``/``dist_async``: multi-process modes.
  In a multi-host JAX setup gradients sync via psum over ICI/DCN inside
  the compiled step (see mxnet_tpu.parallel); this class keeps the
  reference's worker-facing API (rank/num_workers/barrier/set_optimizer)
  so training scripts run unmodified.

The key scheduling idea the reference encodes — push/pull are async engine
ops with priority = -param_index so backward-order layers sync first
(SURVEY.md §5.8) — is preserved by XLA latency-hiding scheduling when sync
happens inside the step; the explicit `priority` argument is accepted for
API parity.
"""
from __future__ import annotations

import os
import pickle

from . import ndarray as nd
from . import optimizer as opt
from .base import MXNetError
from .ndarray import NDArray


def _ctype_key_value(keys, vals):
    if isinstance(keys, (int, str)):
        keys = [keys]
        vals = [vals]
    out = []
    for k, v in zip(keys, vals):
        if isinstance(v, NDArray):
            v = [v]
        out.append((k, list(v)))
    return out


class KVStore(object):
    def __init__(self, kv_type="local"):
        self.type = kv_type
        self._store = {}
        self._updater = None
        self._barrier_count = 0
        self._heartbeat = None
        # Multi-process distributed rank/size come from the JAX runtime
        # itself once a dist store is requested (the env names are only
        # the pre-init fallback): trusting env alone let round-2 report
        # a size the runtime never actually had.
        self._rank = int(os.environ.get(
            "DMLC_RANK", os.environ.get("JAX_PROCESS_ID", 0)))
        self._size = int(os.environ.get(
            "DMLC_NUM_WORKER", os.environ.get("JAX_NUM_PROCESSES", 1)))
        if "dist" in kv_type:
            import jax

            from .parallel import init_distributed

            # The reference joins the PS cluster at kvstore creation
            # (KVStore::InitPSEnv); the analog is joining the JAX
            # distributed runtime here, so scripts that only ever call
            # mx.kv.create('dist_sync') work unmodified under launch.py.
            init_distributed()
            env_size = self._size
            self._rank = jax.process_index()
            self._size = jax.process_count()
            if env_size > 1 and self._size == 1:
                raise MXNetError(
                    "kvstore %s: launcher env promises %d workers but this "
                    "process never joined a distributed JAX runtime "
                    "(missing/unreachable coordinator?) — refusing to "
                    "silently train un-synchronized" % (kv_type, env_size))
            # Liveness (SURVEY §5.3): under a launcher-provided run dir,
            # heartbeat so peers/watchdogs can see this worker is alive
            # (reference: Van heartbeats to the scheduler).
            from .parallel import heartbeat as _hb

            if _hb.run_dir() is not None:
                self._heartbeat = _hb.HeartbeatWriter(
                    _hb.run_dir(), self._rank).start()

    # ------------------------------------------------------------------
    def init(self, key, value):
        for k, vals in _ctype_key_value(key, value):
            if k in self._store:
                raise MXNetError("key %s already initialized" % str(k))
            v = vals[0]
            if self._is_dist:
                # Reference dist init: rank 0's value lands on the
                # servers and every worker pulls it — all workers start
                # identical whatever their local seeding did.
                from .parallel import mesh as _mesh

                v = nd.array(_mesh.broadcast_from_root(v.asnumpy()),
                             ctx=v.context, dtype=v.dtype)
            self._store[k] = v.copy()

    def push(self, key, value, priority=0):
        """Reduce value(s) into the store; updater applies if set.
        Parity: KVStoreLocal::Push (kvstore_local.h) — merged = sum over
        the per-device list (Comm::Reduce), then updater(key, merged,
        stored) or plain store write."""
        if self._heartbeat is not None:
            # progress beat from the hot path: a rank wedged in a
            # collective stops marking progress even though its liveness
            # daemon keeps beating (parallel/heartbeat.py)
            self._heartbeat.progress()
        for k, vals in _ctype_key_value(key, value):
            if k not in self._store:
                raise MXNetError("key %s not initialized" % str(k))
            merged = self._reduce(vals)
            if self._is_dist:
                # Cross-worker merge (the server-side merge_buf_ sum in
                # kvstore_dist_server.h:163-200, minus the server): every
                # worker contributes, every worker sees the global sum.
                # dist_async gets the same synchronous reduction — with
                # no PS tier there is no one-sided push target, and sync
                # semantics are strictly stronger.
                from .parallel import mesh as _mesh

                merged = nd.array(_mesh.allreduce_sum(merged.asnumpy()),
                                  ctx=merged.context, dtype=merged.dtype)
            if self._updater is not None:
                self._updater(
                    k if isinstance(k, int) else self._str_key(k), merged,
                    self._store[k]
                )
            else:
                merged.copyto(self._store[k])

    def pull(self, key, out=None, priority=0):
        """Broadcast stored value to out array(s) (Comm::Broadcast)."""
        assert out is not None
        if self._heartbeat is not None:
            self._heartbeat.progress()
        for k, outs in _ctype_key_value(key, out):
            if k not in self._store:
                raise MXNetError("key %s not initialized" % str(k))
            stored = self._store[k]
            for o in outs:
                stored.copyto(o)

    def _str_key(self, k):
        """Stable string-key → updater-index mapping (insertion order;
        NOT hash(): that's randomized per process and would break
        optimizer-state save/restore)."""
        if not hasattr(self, "_str_key_map"):
            self._str_key_map = {}
        if k not in self._str_key_map:
            self._str_key_map[k] = len(self._str_key_map)
        return self._str_key_map[k]

    def _reduce(self, vals):
        if len(vals) == 1:
            return vals[0]
        # sum where the first value lives; jax moves the shards over
        # ICI/PCIe as needed (reference: CommCPU pinned-host tree /
        # CommDevice GPU gather)
        merged = vals[0].copy()
        for v in vals[1:]:
            merged += v.as_in_context(merged.context)
        return merged

    # ------------------------------------------------------------------
    def set_updater(self, updater):
        self._updater = updater

    _set_updater = set_updater

    def set_optimizer(self, optimizer):
        """Parity kvstore.py:226: on dist stores the reference pickles the
        optimizer to the servers; with the PS tier deleted the optimizer
        always runs in-process."""
        if "dist" in self.type and self._size > 1:
            # serialize/deserialize to mirror the reference's server-side
            # transport (and catch unpicklable optimizers early). The
            # bound symbol is transport-hostile (op defs hold lambdas)
            # and already spent: set_lr_mult/set_wd_mult read it at
            # construction, so the wire copy travels without it.
            import copy

            clone = copy.copy(optimizer)  # caller's object untouched
            clone.sym = None
            optimizer = pickle.loads(pickle.dumps(clone))
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    # ------------------------------------------------------------------
    @property
    def rank(self):
        return self._rank

    @property
    def num_workers(self):
        return self._size

    @property
    def _is_dist(self):
        return "dist" in self.type and self._size > 1

    def _barrier(self):
        """Global barrier (reference: ps::Postoffice::Barrier).

        Must hard-fail if a peer is unreachable — a barrier that
        swallows errors silently un-synchronizes exactly the path that
        exists to synchronize (round-1/2 finding, fixed)."""
        if self._heartbeat is not None:
            self._heartbeat.progress()
        if self._size > 1:
            from .parallel import barrier as _mesh_barrier

            self._barrier_count += 1
            _mesh_barrier("kvstore-barrier-%d" % self._barrier_count)

    def save_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("Cannot save states for distributed training")
        with open(fname, "wb") as fout:
            fout.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("Cannot load states for distributed training")
        with open(fname, "rb") as fin:
            self._updater.set_states(fin.read())

    def get_num_dead_node(self, node_id, timeout=60):
        """Parity kvstore.h:235-244: number of peers whose heartbeat went
        stale. Heartbeats ride the launcher's run dir (parallel/
        heartbeat.py) rather than a scheduler process; outside a
        launched job there is nothing to be dead, so 0."""
        from .parallel import heartbeat as _hb

        directory = _hb.run_dir()
        if directory is None or self._size <= 1:
            return 0
        return len(_hb.dead_nodes(directory, self._size, timeout))

    @property
    def barrier_before_exit(self):
        return True


def create(name="local"):
    """Create a KVStore (parity kvstore.py create). Accepted types mirror
    the reference: local / local_allreduce_cpu / local_allreduce_device /
    device / dist_sync / dist_device_sync / dist_async."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    valid = (
        "local", "local_allreduce_cpu", "local_allreduce_device", "device",
        "dist_sync", "dist_device_sync", "dist_async", "dist",
    )
    if name not in valid:
        raise MXNetError("unknown kvstore type %s" % name)
    return KVStore(name)
