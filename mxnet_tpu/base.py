"""Core shared definitions for the TPU-native framework.

Capability parity target: pre-Gluon MXNet 0.9.5 (`/root/reference`). The
reference routes every frontend through a C ABI (`include/mxnet/c_api.h`);
here the "ABI" is this Python package itself — JAX is the device runtime, so
the ctypes/handle layer of the reference (`python/mxnet/base.py`) collapses
into plain Python objects.
"""
from __future__ import annotations

import ast
import os
import numpy as np

__version__ = "0.9.5"


class MXNetError(RuntimeError):
    """Error raised by the framework (parity: reference ``base.py:MXNetError``)."""


# ---------------------------------------------------------------------------
# dtype registry
#
# Parity with mshadow's TypeFlag enum (reference include/mxnet/base.h +
# mshadow dtype switch macros); the integer codes match the reference so
# serialized params / graph JSON agree.
# ---------------------------------------------------------------------------
_DTYPE_NP_TO_MX = {
    np.float32: 0,
    np.float64: 1,
    np.float16: 2,
    np.uint8: 3,
    np.int32: 4,
    np.int8: 5,
    np.int64: 6,
}
# TPU-native extension: bfloat16 is the MXU's preferred dtype.
try:  # ml_dtypes ships with jax
    import ml_dtypes

    _DTYPE_NP_TO_MX[ml_dtypes.bfloat16] = 12
    bfloat16 = ml_dtypes.bfloat16
except ImportError:  # pragma: no cover
    bfloat16 = None

_DTYPE_MX_TO_NP = {v: k for k, v in _DTYPE_NP_TO_MX.items()}

_DTYPE_NAMES = {
    "float32": np.float32,
    "float64": np.float64,
    "float16": np.float16,
    "uint8": np.uint8,
    "int32": np.int32,
    "int8": np.int8,
    "int64": np.int64,
}
if bfloat16 is not None:
    _DTYPE_NAMES["bfloat16"] = bfloat16


def np_dtype(dtype):
    """Normalize any dtype spec (np dtype, type, string, mx code) to a numpy type."""
    if dtype is None:
        return np.float32
    if isinstance(dtype, (int, np.integer)) and not isinstance(dtype, bool):
        return _DTYPE_MX_TO_NP[int(dtype)]
    if isinstance(dtype, str):
        if dtype not in _DTYPE_NAMES:
            raise MXNetError("unknown dtype name %s" % dtype)
        return _DTYPE_NAMES[dtype]
    d = np.dtype(dtype)
    for k in _DTYPE_NP_TO_MX:
        if np.dtype(k) == d:
            return k
    raise MXNetError("unsupported dtype %s" % dtype)


def dtype_name(dtype) -> str:
    return np.dtype(np_dtype(dtype)).name


def mx_dtype_code(dtype) -> int:
    return _DTYPE_NP_TO_MX[np_dtype(dtype)]


# ---------------------------------------------------------------------------
# attribute-string parsing
#
# The reference parses operator params from strings via dmlc::Parameter
# (every ``*-inl.h`` has DMLC_DECLARE_PARAMETER). We keep the
# everything-is-a-string wire format for Symbol attrs / graph JSON parity and
# normalize here.
# ---------------------------------------------------------------------------
def parse_attr_value(value):
    """Parse a string attr ('(2,2)', 'True', '0.9', 'relu') into a Python value."""
    if not isinstance(value, str):
        return value
    s = value.strip()
    if s in ("True", "true"):
        return True
    if s in ("False", "false"):
        return False
    if s in ("None", "null"):
        return None
    try:
        return ast.literal_eval(s)
    except (ValueError, SyntaxError):
        return s


def attr_repr(value) -> str:
    """Inverse of :func:`parse_attr_value` — stringify for graph JSON."""
    if isinstance(value, bool):
        return "True" if value else "False"
    if value is None:
        return "None"
    if isinstance(value, (list, tuple)):
        if len(value) == 1:  # "(100,)" — "(100)" would parse back as int
            return "(" + attr_repr(value[0]) + ",)"
        return "(" + ", ".join(attr_repr(v) for v in value) + ")"
    return str(value)


def get_env(name, default, typ=None):
    """Runtime knob lookup (parity: dmlc::GetEnv; knobs documented in
    reference docs/how_to/env_var.md). Same env-var names are honored where
    the knob still makes sense on TPU."""
    v = os.environ.get(name)
    if v is None:
        return default
    if typ is bool or isinstance(default, bool):
        return v not in ("0", "false", "False", "")
    if typ is int or isinstance(default, int):
        return int(v)
    if typ is float or isinstance(default, float):
        return float(v)
    return v


_DEFAULT_BUCKET_BYTES = 4 * 1024 * 1024


def bucket_bytes_env():
    """MXTPU_BUCKET_BYTES: size cap for coalesced gradient buckets,
    shared by the kvstore GradBucketer and the fused flat-update plan
    (docs/env_vars.md). Missing/empty/garbage → 4 MiB default; negative
    clamps to 0 (0 disables coalescing: one collective per key and the
    legacy per-param fused update)."""
    raw = os.environ.get("MXTPU_BUCKET_BYTES")
    if raw is None or raw == "":
        return _DEFAULT_BUCKET_BYTES
    try:
        return max(0, int(raw))
    except ValueError:
        return _DEFAULT_BUCKET_BYTES


def _init_compile_cache():
    """MXTPU_COMPILE_CACHE=<dir>: turn on JAX's persistent compilation
    cache at import, so benchmark re-runs and preemption-resumed jobs
    (resilience/checkpoint.py auto-resume) skip XLA recompiles. The
    thresholds drop to 0 because our programs are many small jit bodies
    (per-key ops, fused steps) that the default 1s/too-small gates would
    mostly skip."""
    cache_dir = os.environ.get("MXTPU_COMPILE_CACHE")
    if not cache_dir:
        return
    import jax

    for knob, value in (
        ("jax_compilation_cache_dir", cache_dir),
        ("jax_persistent_cache_min_compile_time_secs", 0),
        ("jax_persistent_cache_min_entry_size_bytes", 0),
    ):
        try:
            jax.config.update(knob, value)
        except (AttributeError, ValueError):  # knob absent in this jax
            pass


_init_compile_cache()
