"""Executor: symbol → compiled XLA forward/backward.

Parity: reference ``python/mxnet/executor.py`` + ``src/executor/``
(GraphExecutor). This is THE seam SURVEY.md §3.2 identifies: everything the
reference does in GraphExecutor::Init — gradient pass, placement,
shape/type inference, memory planning, cached engine ops, bulk segments —
is replaced by tracing the whole symbol into one JAX function and
jit-compiling it:

- InitFullGraph + nnvm Gradient pass  → jax.vjp over the traced forward
- PlanMemory / InplaceAddTo           → XLA buffer assignment (+ donation)
- InitCachedOps / bulk-exec segments  → a single fused XLA module per
  (forward, forward+backward) — strictly stronger than the reference's
  15-node bulk segments
- AttachOpResources (temp space/rng)  → functional PRNG keys folded per-node

The training step (forward+backward) compiles to ONE XLA executable, so
per-op dispatch overhead — the reason the reference needs its threaded
engine — is zero on the hot path.
"""
from __future__ import annotations

import functools
import itertools

import jax
import jax.numpy as jnp
import numpy as np

from . import ndarray as nd
from . import random as _random
from .base import MXNetError, np_dtype
from .context import Context
from .ndarray import NDArray
from .symbol import Symbol, _topo_order

__all__ = ["Executor"]


def _as_jax(x):
    return x._data if isinstance(x, NDArray) else x


class _GraphProgram:
    """A symbol lowered to a pure function of (args, aux, rng) — the unit
    that gets jitted. Built once per bind; shared by fwd and fwd+bwd."""

    _uid_counter = itertools.count()

    def __init__(self, symbol: Symbol, shape_overrides=None):
        # monotonic uid (not id(self): CPython recycles ids, which would let
        # a new program inherit a dead bind's stateful CustomOp instances)
        self._program_uid = next(_GraphProgram._uid_counter)
        self.symbol = symbol
        # id(node) -> resolved out shape, for creation ops whose attr shape
        # has unknown (0) dims (RNN begin_state zeros)
        self.shape_overrides = shape_overrides or {}
        self.nodes = _topo_order([n for n, _ in symbol._outputs])
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.output_entries = list(symbol._outputs)
        self._var_nodes = {
            n.name: n for n in self.nodes if n.is_variable
        }
        # stable per-node ids for rng folding
        self._node_ids = {id(n): i for i, n in enumerate(self.nodes)}

    def __call__(self, arg_values, aux_values, rng, is_train):
        """arg_values: dict name→jax array; aux_values: dict name→jax array.
        Returns (outputs list, new_aux dict)."""
        import jax

        env = {}
        for name, v in arg_values.items():
            node = self._var_nodes.get(name)
            if node is not None:
                env[(id(node), 0)] = v
        for name, v in aux_values.items():
            node = self._var_nodes.get(name)
            if node is not None:
                env[(id(node), 0)] = v
        new_aux = {}
        for node in self.nodes:
            if node.is_variable:
                if (id(node), 0) not in env:
                    raise MXNetError("executor: missing input %s" % node.name)
                continue
            attrs = node.canon_attrs()
            if id(node) in self.shape_overrides:
                attrs["shape"] = self.shape_overrides[id(node)]
            if node.op.name == "Custom":
                # stateful CustomOp instances live per (bind, node) like the
                # reference's one-CustomOp-per-bind (custom-inl.h); the host
                # uses these keys to scope instance caching
                attrs["__program_id__"] = self._program_uid
                attrs["__node_name__"] = node.name
            if node.op.needs_rng:
                if rng is None:
                    raise MXNetError("executor: rng required for %s" % node.name)
                attrs["__rng__"] = jax.random.fold_in(rng, self._node_ids[id(node)])
            in_vals = [env[(id(c), i)] for (c, i) in node.inputs]
            results = node.op.fcompute(attrs, in_vals, is_train)
            n_outs = node.num_outputs()
            for i, v in enumerate(results[:n_outs]):
                env[(id(node), i)] = v
            # trailing results update this node's aux-state variables
            n_args = node._extra.get("n_args", len(node.inputs))
            aux_inputs = node.inputs[n_args:]
            for (c, _), v in zip(aux_inputs, results[n_outs:]):
                new_aux[c.name] = v
        outputs = [env[(id(n), i)] for (n, i) in self.output_entries]
        for name in self.aux_names:
            if name not in new_aux:
                new_aux[name] = aux_values[name]
        return outputs, new_aux


class _LazyOutputs:
    """Sequence view over an executor's outputs that materializes the
    deferred train-step forward on first access."""

    def __init__(self, exe):
        self._exe = exe

    def __len__(self):
        return len(self._exe.outputs)

    def __getitem__(self, i):
        return self._exe.outputs[i]

    def __iter__(self):
        return iter(self._exe.outputs)

    def __repr__(self):
        return repr(self._exe.outputs)


def resolve_creation_shapes(symbol, shapes_by_name):
    """For creation ops (_zeros/_ones) whose shape attr has unknown (0)
    dims — MXNet's bind-time-inferred convention, e.g. rnn_cell
    begin_state batch dims — resolve concrete shapes via graph-wide
    inference given the input shapes. Returns a _GraphProgram
    shape_overrides dict. Used by Executor at bind and ShardedTrainStep
    at first call (same program layer, two front doors)."""
    nodes = _topo_order([n for n, _ in symbol._outputs])
    from .ops.utils import as_tuple

    def _shape_attr(n):
        return as_tuple(n.canon_attrs().get("shape")) or ()

    pending = [
        n for n in nodes
        if (not n.is_variable) and not n.inputs and 0 in _shape_attr(n)
    ]
    if not pending:
        return {}
    env = symbol._infer_shape_env(**shapes_by_name)
    return {id(n): env[(id(n), 0)] for n in pending if (id(n), 0) in env}


class Executor:
    """Bound computation: holds arg/grad/aux NDArrays + compiled step fns.

    Parity: reference ``include/mxnet/executor.h`` —
    Forward/Backward/outputs/arg_dict/grad_dict/aux_dict/reshape/
    copy_params_from/set_monitor_callback.
    """

    def __init__(self, symbol, ctx, arg_arrays, grad_arrays, grad_req,
                 aux_arrays, group2ctx=None):
        self._symbol = symbol
        self._ctx = ctx
        overrides = self._resolve_creation_shapes(symbol, arg_arrays)
        self._program = _GraphProgram(symbol, overrides)
        self.arg_arrays = list(arg_arrays)
        self.grad_arrays = list(grad_arrays)
        self.aux_arrays = list(aux_arrays)
        self._arg_names = self._program.arg_names
        self._aux_names = self._program.aux_names
        self._output_names = symbol.list_outputs()
        self._group2ctx = group2ctx or {}
        self._monitor_callback = None
        if isinstance(grad_req, str):
            grad_req = {n: grad_req for n in self._arg_names}
        elif isinstance(grad_req, (list, tuple)):
            grad_req = dict(zip(self._arg_names, grad_req))
        self._grad_req = grad_req
        # names we differentiate wrt (grad buffer attached + req != null)
        self._grad_names = [
            n
            for n, g in zip(self._arg_names, self.grad_arrays)
            if g is not None and self._grad_req.get(n, "null") != "null"
        ]
        self._outputs_list = [None] * len(self._output_names)
        self._stash = None  # (arg_vals, aux_vals, rng) captured at forward()
        self._needs_rng = any(
            (not n.is_variable) and n.op.needs_rng for n in self._program.nodes
        )
        self._fwd_jit = self._make_fwd()
        self._fwdbwd_jit = self._make_fwdbwd()
        self._pending_train_step = False

    @staticmethod
    def _resolve_creation_shapes(symbol, arg_arrays):
        arg_names = symbol.list_arguments()
        shapes = {
            n: a.shape for n, a in zip(arg_names, arg_arrays) if a is not None
        }
        return resolve_creation_shapes(symbol, shapes)

    # ------------------------------------------------------------------
    # compiled callables
    # ------------------------------------------------------------------
    def _make_fwd(self):
        program = self._program
        arg_names = tuple(self._arg_names)
        aux_names = tuple(self._aux_names)

        @functools.partial(jax.jit, static_argnums=(3,))
        def fwd(arg_vals, aux_vals, rng, is_train):
            args = dict(zip(arg_names, arg_vals))
            aux = dict(zip(aux_names, aux_vals))
            outs, new_aux = program(args, aux, rng, is_train)
            return tuple(outs), tuple(new_aux[n] for n in aux_names)

        return fwd

    def _make_fwdbwd(self):
        program = self._program
        arg_names = tuple(self._arg_names)
        aux_names = tuple(self._aux_names)
        grad_names = tuple(self._grad_names)

        @jax.jit
        def fwdbwd(arg_vals, aux_vals, rng, out_grads):
            args = dict(zip(arg_names, arg_vals))
            aux = dict(zip(aux_names, aux_vals))
            fixed = {k: v for k, v in args.items() if k not in grad_names}

            def f(diff_vals):
                a = dict(fixed)
                a.update(dict(zip(grad_names, diff_vals)))
                outs, new_aux = program(a, aux, rng, True)
                return tuple(outs), tuple(new_aux[n] for n in aux_names)

            diff_vals = tuple(args[n] for n in grad_names)
            (outs, new_aux), vjp_fn = jax.vjp(f, diff_vals)
            if out_grads is None:
                cts = tuple(jnp.ones_like(o) for o in outs)
            else:
                cts = tuple(out_grads)
            zero_aux_ct = tuple(jnp.zeros_like(a) for a in new_aux)
            (grads,) = vjp_fn((cts, zero_aux_ct))
            return outs, new_aux, grads

        return fwdbwd

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        """Parity: Executor::Forward. For a training step the launch is
        deferred so backward() can run forward+backward as ONE fused XLA
        executable (the whole-graph analog of the reference's bulk-exec
        segments); reading .outputs before backward() materializes a
        forward-only run from the same stashed inputs + rng, so results are
        bit-identical either way."""
        if kwargs:
            arg_dict = self.arg_dict
            for k, v in kwargs.items():
                if k not in arg_dict:
                    raise MXNetError("unknown input %s" % k)
                if isinstance(v, NDArray):
                    arg_dict[k]._data = v._data
                else:
                    arg_dict[k]._data = nd.array(v)._data
        rng = _random.next_key() if self._needs_rng else None
        arg_vals = tuple(a._data for a in self.arg_arrays)
        aux_vals = tuple(a._data for a in self.aux_arrays)
        self._stash = (arg_vals, aux_vals, rng, bool(is_train))
        if is_train and self._grad_names:
            self._pending_train_step = True
            # lazy view: materializes via the outputs property on first
            # element access, so callers using forward()'s return value get
            # fresh data while the fit loop (which ignores it) keeps the
            # single fused fwd+bwd launch.
            return _LazyOutputs(self)
        self._pending_train_step = False
        outs, new_aux = self._fwd_jit(arg_vals, aux_vals, rng, bool(is_train))
        self._set_outputs(outs)
        if is_train:
            for a, v in zip(self.aux_arrays, new_aux):
                a._data = v
        self._run_monitor()
        return self.outputs

    @property
    def outputs(self):
        if self._pending_train_step:
            arg_vals, aux_vals, rng, _ = self._stash
            outs, new_aux = self._fwd_jit(arg_vals, aux_vals, rng, True)
            self._set_outputs(outs)
            # moving-stat aux updates happen on forward in the reference
            # (FMutateInputs); backward recomputes the same values from the
            # stashed aux so there is no double-apply.
            for a, v in zip(self.aux_arrays, new_aux):
                a._data = v
            self._pending_train_step = False
        return self._outputs_list

    def _set_outputs(self, outs):
        for i, v in enumerate(outs):
            if self._outputs_list[i] is None:
                self._outputs_list[i] = NDArray(v)
            else:
                self._outputs_list[i]._data = v
        return self._outputs_list

    def backward(self, out_grads=None):
        """Run the fused forward+backward XLA step and write gradients into
        grad_arrays honoring grad_req (write/add/null). Parity:
        Executor::Backward; grad_req semantics = kWriteTo/kAddTo/kNullOp."""
        if out_grads is not None:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            out_grads = tuple(_as_jax(g) for g in out_grads)
        if not self._grad_names:
            return
        if self._stash is not None:
            arg_vals, aux_vals, rng, _ = self._stash
        else:
            arg_vals = tuple(a._data for a in self.arg_arrays)
            aux_vals = tuple(a._data for a in self.aux_arrays)
            rng = _random.next_key() if self._needs_rng else None
        outs, new_aux, grads = self._fwdbwd_jit(arg_vals, aux_vals, rng, out_grads)
        self._pending_train_step = False
        self._set_outputs(outs)
        for a, v in zip(self.aux_arrays, new_aux):
            a._data = v
        gmap = dict(zip(self._grad_names, grads))
        for name, garr in zip(self._arg_names, self.grad_arrays):
            if garr is None or name not in gmap:
                continue
            req = self._grad_req.get(name, "write")
            if req == "add":
                garr._data = garr._data + gmap[name]
            elif req == "write":
                garr._data = gmap[name]
        self._run_monitor()

    # ------------------------------------------------------------------
    # dict views (parity executor.py:248-298)
    # ------------------------------------------------------------------
    @property
    def arg_dict(self):
        return dict(zip(self._arg_names, self.arg_arrays))

    @property
    def grad_dict(self):
        return dict(zip(self._arg_names, self.grad_arrays))

    @property
    def aux_dict(self):
        return dict(zip(self._aux_names, self.aux_arrays))

    @property
    def output_dict(self):
        return dict(zip(self._output_names, self.outputs))

    def copy_params_from(self, arg_params, aux_params=None, allow_extra_params=False):
        arg_dict = self.arg_dict
        for name, array in arg_params.items():
            if name in arg_dict:
                array.copyto(arg_dict[name])
            elif not allow_extra_params:
                raise MXNetError("Found name \"%s\" not in executor arguments" % name)
        if aux_params is not None:
            aux_dict = self.aux_dict
            for name, array in aux_params.items():
                if name in aux_dict:
                    array.copyto(aux_dict[name])
                elif not allow_extra_params:
                    raise MXNetError("Found name \"%s\" not in executor aux states" % name)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Return a new executor with new input shapes, sharing parameter
        arrays (parity executor.py:360; the reference shares memory — XLA
        owns buffers here so we share the NDArray handles)."""
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        new_args = []
        new_grads = []
        for name, arr, garr, shp in zip(
            self._arg_names, self.arg_arrays, self.grad_arrays, arg_shapes
        ):
            if name in kwargs or tuple(arr.shape) != tuple(shp):
                new_args.append(nd.zeros(shp, ctx=self._ctx, dtype=arr.dtype))
                new_grads.append(
                    None if garr is None else nd.zeros(shp, ctx=self._ctx, dtype=arr.dtype)
                )
            else:
                new_args.append(arr)
                new_grads.append(garr)
        new_aux = []
        for arr, shp in zip(self.aux_arrays, aux_shapes):
            if tuple(arr.shape) != tuple(shp):
                new_aux.append(nd.zeros(shp, ctx=self._ctx, dtype=arr.dtype))
            else:
                new_aux.append(arr)
        return Executor(
            self._symbol, self._ctx, new_args, new_grads, self._grad_req,
            new_aux, self._group2ctx
        )

    def set_monitor_callback(self, callback):
        self._monitor_callback = callback

    def _run_monitor(self):
        if self._monitor_callback is None:
            return
        for name, out in zip(self._output_names, self.outputs):
            if out is not None:
                self._monitor_callback(name, out)

    def debug_str(self):
        return self._symbol.debug_str()

    # ------------------------------------------------------------------
    # binding entry points
    # ------------------------------------------------------------------
    @staticmethod
    def bind(symbol, ctx, args, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0]
        if not isinstance(ctx, Context):
            ctx = Context(ctx)
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        arg_arrays = _check_arguments(args, arg_names, "args")
        if args_grad is None:
            grad_arrays = [None] * len(arg_names)
        elif isinstance(args_grad, dict):
            grad_arrays = [args_grad.get(n) for n in arg_names]
        else:
            grad_arrays = list(args_grad)
            grad_arrays += [None] * (len(arg_names) - len(grad_arrays))
        if aux_states is None:
            aux_arrays = []
            if aux_names:
                _, _, aux_shapes = symbol.infer_shape(
                    **{n: a.shape for n, a in zip(arg_names, arg_arrays)}
                )
                aux_arrays = [nd.zeros(s, ctx=ctx) for s in aux_shapes]
        elif isinstance(aux_states, dict):
            aux_arrays = [aux_states[n] for n in aux_names]
        else:
            aux_arrays = list(aux_states)
        return Executor(
            symbol, ctx, arg_arrays, grad_arrays, grad_req, aux_arrays, group2ctx
        )

    @staticmethod
    def simple_bind(symbol, ctx, grad_req="write", type_dict=None,
                    group2ctx=None, shared_exec=None, **kwargs):
        """Infer shapes/types, allocate arg/grad/aux arrays, bind.
        Parity: symbol.py:1114."""
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0]
        if not isinstance(ctx, Context):
            ctx = Context(ctx)
        arg_shapes, _, aux_shapes = symbol.infer_shape(**kwargs)
        arg_types, _, aux_types = symbol.infer_type(**(type_dict or {}))
        arg_names = symbol.list_arguments()
        # share param arrays with shared_exec when shapes match (bucketing)
        shared = shared_exec.arg_dict if shared_exec is not None else {}
        arg_arrays = []
        for name, shape, dtype in zip(arg_names, arg_shapes, arg_types):
            if name in shared and tuple(shared[name].shape) == tuple(shape):
                arg_arrays.append(shared[name])
            else:
                arg_arrays.append(nd.zeros(shape, ctx=ctx, dtype=dtype))
        req_of = (
            (lambda n: grad_req)
            if isinstance(grad_req, str)
            else (lambda n: grad_req.get(n, "null"))
            if isinstance(grad_req, dict)
            else (lambda n: dict(zip(arg_names, grad_req)).get(n, "null"))
        )
        grad_arrays = [
            nd.zeros(shape, ctx=ctx, dtype=dtype) if req_of(name) != "null" else None
            for name, shape, dtype in zip(arg_names, arg_shapes, arg_types)
        ]
        shared_aux = shared_exec.aux_dict if shared_exec is not None else {}
        aux_names = symbol.list_auxiliary_states()
        aux_arrays = []
        for name, shape, dtype in zip(aux_names, aux_shapes, aux_types):
            if name in shared_aux and tuple(shared_aux[name].shape) == tuple(shape):
                aux_arrays.append(shared_aux[name])
            else:
                aux_arrays.append(nd.zeros(shape, ctx=ctx, dtype=dtype))
        return Executor(
            symbol, ctx, arg_arrays, grad_arrays, grad_req, aux_arrays, group2ctx
        )


def _check_arguments(args, names, kind):
    if isinstance(args, dict):
        out = []
        for n in names:
            if n not in args:
                raise MXNetError("missing %s: %s" % (kind, n))
            out.append(args[n])
        return out
    args = list(args)
    if len(args) != len(names):
        raise MXNetError(
            "%s length %d != expected %d (%s)" % (kind, len(args), len(names), names)
        )
    return args
