"""Executor: symbol → compiled XLA forward/backward.

Parity: reference ``python/mxnet/executor.py`` + ``src/executor/``
(GraphExecutor). This is THE seam SURVEY.md §3.2 identifies: everything the
reference does in GraphExecutor::Init — gradient pass, placement,
shape/type inference, memory planning, cached engine ops, bulk segments —
is replaced by tracing the whole symbol into one JAX function and
jit-compiling it:

- InitFullGraph + nnvm Gradient pass  → jax.vjp over the traced forward
- PlanMemory / InplaceAddTo           → XLA buffer assignment (+ donation)
- InitCachedOps / bulk-exec segments  → a single fused XLA module per
  (forward, forward+backward) — strictly stronger than the reference's
  15-node bulk segments
- AttachOpResources (temp space/rng)  → functional PRNG keys folded per-node

The training step (forward+backward) compiles to ONE XLA executable, so
per-op dispatch overhead — the reason the reference needs its threaded
engine — is zero on the hot path.
"""
from __future__ import annotations

import functools
import itertools
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from . import ndarray as nd
from . import random as _random
from . import telemetry as _tm
from .base import MXNetError, np_dtype
from .context import Context
from .ndarray import NDArray
from .symbol import Symbol, _topo_order

__all__ = ["Executor"]

_M_COMPILE_COUNT = _tm.counter(
    "executor.jit_compile_count", "XLA trace+compile events, by segment key")
_M_COMPILE_SECONDS = _tm.counter(
    "executor.jit_compile_seconds",
    "wall seconds spent in first-call trace+compile, by segment key")
_M_CACHE_HITS = _tm.counter(
    "executor.fn_cache_hits", "compiled-callable cache hits, by segment key")
_M_CACHE_MISSES = _tm.counter(
    "executor.fn_cache_misses",
    "compiled-callable cache misses (compiles), by segment key")
_H_STEP_SECONDS = _tm.histogram(
    "executor.step_seconds", "executor forward / fused fwd+bwd dispatch time")
_M_PLAN_HITS = _tm.counter(
    "executor.dispatch_plan_hits",
    "Steady-state dispatches served from the cached canonicalization "
    "plan (per-step graph-wide shape resolution and arg-dict churn "
    "skipped)")
_M_PLAN_MISSES = _tm.counter(
    "executor.dispatch_plan_misses",
    "Dispatch-plan cache misses: a new (shape, dtype, sharding) input "
    "signature was canonicalized and cached")


def _instrument_jit(fn, key):
    """Wrap a jitted callable with compile/cache accounting: the first
    call is where jax traces + XLA compiles (recorded as a cache miss
    plus compile count/seconds under ``segment=key``); every later call
    counts as a cache hit. Zero-overhead passthrough while telemetry is
    disabled."""
    state = {"compiled": False}

    def wrapper(*args, **kwargs):
        if not _tm.enabled():
            state["compiled"] = True
            return fn(*args, **kwargs)
        if state["compiled"]:
            _M_CACHE_HITS.inc(segment=key)
            return fn(*args, **kwargs)
        state["compiled"] = True
        _M_CACHE_MISSES.inc(segment=key)
        with _tm.span("jit_compile", segment=key):
            t0 = time.perf_counter()
            out = fn(*args, **kwargs)
            dt = time.perf_counter() - t0
        _M_COMPILE_COUNT.inc(segment=key)
        _M_COMPILE_SECONDS.inc(dt, segment=key)
        return out

    return wrapper


def _as_jax(x):
    return x._data if isinstance(x, NDArray) else x


def _ctx_group(node):
    """A node's placement group: accepts both the in-memory attr name and
    the reference's serialized __ctx_group__ spelling (symbol.py:1183)."""
    return node.attrs.get("ctx_group") or node.attrs.get("__ctx_group__")


def _mirror_enabled():
    """Whole-graph gradient-checkpoint switch: the env flag only
    (reference MXNET_BACKWARD_DO_MIRROR, graph_executor.cc:213-226) —
    process-wide, not per-graph. Per-node __force_mirroring__ attrs remat
    just their own node — see _compute_node — so one flagged activation
    doesn't silently escalate to whole-model recompute."""
    from .base import get_env

    return bool(get_env("MXNET_BACKWARD_DO_MIRROR", 0))


def _force_mirrored(node):
    return node.attrs.get("__force_mirroring__") in ("True", "true", "1")


def _compute_node(node, attrs, in_vals, is_train):
    """Run one node's fcompute; a node carrying __force_mirroring__
    recomputes (only) itself in backward via jax.checkpoint — the
    per-node escape hatch the reference's need_mirror honors first."""
    if is_train and _force_mirrored(node):
        fn = jax.checkpoint(
            lambda *iv: node.op.fcompute(attrs, list(iv), is_train))
        return fn(*in_vals)
    return node.op.fcompute(attrs, in_vals, is_train)


_MIRROR_SAVE_DEFAULT = "dot_general,conv_general_dilated"


def _mirror_policy(prim, *_args, **_params):
    """Which residuals to SAVE under memory mirroring. The reference
    recomputes every op in backward except Convolution / FullyConnected /
    Concat / SoftmaxOutput (graph_executor.cc need_mirror) — i.e. keep
    the MXU-expensive results, rematerialize the bandwidth-cheap ones
    (activations, BN, pooling). The XLA translation: save dot/conv
    primitive outputs, recompute everything else. (Dropout recompute is
    safe here: masks come from deterministic per-node fold_in keys.)

    MXNET_MIRROR_SAVE tunes the saved set (comma-separated primitive
    names) — the knob benchmarks/mirror_inception.py sweeps to trade
    recompute time against activation memory, e.g. adding
    reduce_window_max,reduce_window_sum (pooling) or concatenate
    (the reference's Concat) cuts the recompute chains at extra pins.
    Read per call (trace-time only) so a sweep can change it between
    compiles without cache invalidation."""
    names = os.environ.get("MXNET_MIRROR_SAVE", _MIRROR_SAVE_DEFAULT)
    return prim.name in _mirror_save_set(names)


@functools.lru_cache(maxsize=8)
def _mirror_save_set(names):
    return frozenset(n.strip() for n in names.split(",") if n.strip())


def _node_attrs(program, node, rng):
    """Execution-time attrs for one node — the ONE place where per-node
    execution semantics (shape overrides, CustomOp scoping keys, rng
    folding) live; _GraphProgram.__call__ and _PlacedProgram segments
    both call it so the two paths cannot silently diverge."""
    attrs = node.canon_attrs()
    if id(node) in program.shape_overrides:
        attrs["shape"] = program.shape_overrides[id(node)]
    if node.op.name == "Custom":
        # stateful CustomOp instances live per (bind, node) like the
        # reference's one-CustomOp-per-bind (custom-inl.h); the host
        # uses these keys to scope instance caching
        attrs["__program_id__"] = program._program_uid
        attrs["__node_name__"] = node.name
    if node.op.needs_rng:
        if rng is None:
            raise MXNetError("executor: rng required for %s" % node.name)
        attrs["__rng__"] = jax.random.fold_in(
            rng, program._node_ids[id(node)])
    return attrs


class _GraphProgram:
    """A symbol lowered to a pure function of (args, aux, rng) — the unit
    that gets jitted. Built once per bind; shared by fwd and fwd+bwd."""

    _uid_counter = itertools.count()

    def __init__(self, symbol: Symbol, shape_overrides=None):
        # monotonic uid (not id(self): CPython recycles ids, which would let
        # a new program inherit a dead bind's stateful CustomOp instances)
        self._program_uid = next(_GraphProgram._uid_counter)
        self.symbol = symbol
        # id(node) -> resolved out shape, for creation ops whose attr shape
        # has unknown (0) dims (RNN begin_state zeros)
        self.shape_overrides = shape_overrides or {}
        self.nodes = _topo_order([n for n, _ in symbol._outputs])
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.output_entries = list(symbol._outputs)
        self._var_nodes = {
            n.name: n for n in self.nodes if n.is_variable
        }
        # stable per-node ids for rng folding
        self._node_ids = {id(n): i for i, n in enumerate(self.nodes)}
        # (shape, dtype, sharding) input signature -> canonicalized
        # per-signature dispatch state (see dispatch_plan)
        self._dispatch_plans = {}

    def dispatch_plan(self, sig, build):
        """Steady-state dispatch fast path. ``sig`` is the caller's
        (shape, dtype, sharding) input signature; ``build()`` produces
        the canonicalized per-signature state — today the resolved
        creation-op shape overrides (the arg-ordering/donation plan
        proper lives inside jax.jit, keyed by the same signature).
        Repeat signatures skip the graph-wide shape re-resolution and
        the full params+batch dict build/sort that used to run before
        EVERY dispatch; a shape, dtype, or sharding change (partial
        final batch, Module.reshape, re-placed inputs) re-canonicalizes
        exactly once."""
        plan = self._dispatch_plans.get(sig)
        if plan is None:
            _M_PLAN_MISSES.inc()
            # a miss past warmup is a fresh trace/compile on the hot
            # path — the anatomy layer fingerprints it and diffs against
            # the previous signature (no-op unless telemetry is on)
            _tm.anatomy.note_plan_miss(self._program_uid, sig)
            plan = build()
            self._dispatch_plans[sig] = plan
        else:
            _M_PLAN_HITS.inc()
        self.shape_overrides = plan
        return plan

    def __call__(self, arg_values, aux_values, rng, is_train):
        """arg_values: dict name→jax array; aux_values: dict name→jax array.
        Returns (outputs list, new_aux dict)."""
        import jax

        env = {}
        for name, v in arg_values.items():
            node = self._var_nodes.get(name)
            if node is not None:
                env[(id(node), 0)] = v
        for name, v in aux_values.items():
            node = self._var_nodes.get(name)
            if node is not None:
                env[(id(node), 0)] = v
        new_aux = {}
        for node in self.nodes:
            if node.is_variable:
                if (id(node), 0) not in env:
                    raise MXNetError("executor: missing input %s" % node.name)
                continue
            attrs = _node_attrs(self, node, rng)
            in_vals = [env[(id(c), i)] for (c, i) in node.inputs]
            results = _compute_node(node, attrs, in_vals, is_train)
            n_outs = node.num_outputs()
            for i, v in enumerate(results[:n_outs]):
                env[(id(node), i)] = v
            # trailing results update this node's aux-state variables
            n_args = node._extra.get("n_args", len(node.inputs))
            aux_inputs = node.inputs[n_args:]
            for (c, _), v in zip(aux_inputs, results[n_outs:]):
                new_aux[c.name] = v
        outputs = [env[(id(n), i)] for (n, i) in self.output_entries]
        for name in self.aux_names:
            if name not in new_aux:
                new_aux[name] = aux_values[name]
        return outputs, new_aux


class _LazyOutputs:
    """Sequence view over an executor's outputs that materializes the
    deferred train-step forward on first access."""

    def __init__(self, exe):
        self._exe = exe

    def __len__(self):
        return len(self._exe.outputs)

    def __getitem__(self, i):
        return self._exe.outputs[i]

    def __iter__(self):
        return iter(self._exe.outputs)

    def __repr__(self):
        return repr(self._exe.outputs)


class _PlacedProgram:
    """Model-parallel execution of a _GraphProgram across devices.

    The TPU-native redesign of the reference's placement pipeline
    (nnvm::pass::PlaceDevice + _CrossDeviceCopy insertion + engine
    overlap, src/executor/graph_executor.cc:245-334,
    src/operator/cross_device_copy.cc): the topo order is split into
    maximal contiguous same-device segments; each segment jit-compiles
    ONCE on its device (computation follows its committed inputs);
    boundary values move with an explicit eager ``jax.device_put`` — the
    _CrossDeviceCopy analog — and jax's async dispatch pipelines
    segments on different devices exactly like the reference's engine
    pipelines model-parallel LSTM stages.

    Backward runs segment-by-segment in reverse: each segment has a
    cached JITTED backward that recomputes its forward from the saved
    boundary inputs and transposes it (rematerialization — one extra
    segment-forward per step buys a fully-compiled backward with no
    per-step python AD tracing). Cotangents move back across the same
    device boundaries, and are only computed for inputs that can reach
    a gradient variable — data/label cotangents are never materialized.
    This stitched design exists because SPMD alone cannot express
    distinct per-stage computations on distinct devices in one program.
    """

    def __init__(self, program, node_dev, grad_names=()):
        self.program = program
        segs = []
        for node in program.nodes:
            if node.is_variable:
                continue
            dev = node_dev[id(node)]
            if segs and segs[-1][0] == dev:
                segs[-1][1].append(node)
            else:
                segs.append((dev, [node]))
        self.segments = segs

        # which nodes' outputs can influence a gradient variable's ct:
        # a value needs a cotangent iff a grad var is among its ancestors
        grad_names = set(grad_names)
        needs_ct = {}
        for node in program.nodes:
            if node.is_variable:
                needs_ct[id(node)] = node.name in grad_names
            else:
                needs_ct[id(node)] = any(
                    needs_ct[id(c)] for (c, _) in node.inputs)
        self._needs_ct = needs_ct

        final_keys = {(id(n), i) for n, i in program.output_entries}
        raw = []
        for dev, nodes in segs:
            in_seg = {id(n) for n in nodes}
            needs, seen = [], set()
            prods = []
            aux_names = []
            for node in nodes:
                for (c, i) in node.inputs:
                    k = (id(c), i)
                    if id(c) in in_seg or k in seen:
                        continue
                    seen.add(k)
                    needs.append(k)
                prods.extend(
                    (id(node), i) for i in range(node.num_outputs()))
                n_args = node._extra.get("n_args", len(node.inputs))
                aux_names.extend(c.name for (c, _) in node.inputs[n_args:])
            raw.append((needs, prods, aux_names))
        # keep only produced keys someone later actually reads
        consumed = set(final_keys)
        for needs, _, _ in raw:
            consumed.update(needs)
        self._seg_io = [
            (needs, [k for k in prods if k in consumed], aux_names)
            for needs, prods, aux_names in raw
        ]
        self._fn_cache = {}

    def _seg_run(self, si, is_train):
        """Pure per-segment forward body (traced under fwd and bwd jits)."""
        _, nodes = self.segments[si]
        needs, out_keys, _ = self._seg_io[si]
        program = self.program

        def run(in_vals, rng):
            env = dict(zip(needs, in_vals))
            aux_out = []
            for node in nodes:
                attrs = _node_attrs(program, node, rng)
                ins = [env[(id(c), i)] for (c, i) in node.inputs]
                results = _compute_node(node, attrs, ins, is_train)
                n_outs = node.num_outputs()
                for i, v in enumerate(results[:n_outs]):
                    env[(id(node), i)] = v
                n_args = node._extra.get("n_args", len(node.inputs))
                for _c, v in zip(node.inputs[n_args:], results[n_outs:]):
                    aux_out.append(v)
            return tuple(env[k] for k in out_keys), tuple(aux_out)

        return run

    def _seg_fn(self, si, is_train):
        key = ("fwd", si, is_train)
        if key not in self._fn_cache:
            _M_CACHE_MISSES.inc(segment="seg%d_fwd" % si)
            self._fn_cache[key] = jax.jit(self._seg_run(si, is_train))
        else:
            _M_CACHE_HITS.inc(segment="seg%d_fwd" % si)
        return self._fn_cache[key]

    def _seg_bwd_fn(self, si):
        """Jitted backward for segment si: recompute forward from the
        saved boundary inputs, transpose, and return cotangents ONLY for
        inputs that can reach a gradient variable."""
        key = ("bwd", si)
        if key not in self._fn_cache:
            _M_CACHE_MISSES.inc(segment="seg%d_bwd" % si)
            needs, _, _ = self._seg_io[si]
            diff_idx = tuple(
                i for i, (nid, _o) in enumerate(needs)
                if self._needs_ct.get(nid, False))
            run = self._seg_run(si, True)

            def bwd(in_vals, rng, cts_out):
                diff_vals = tuple(in_vals[i] for i in diff_idx)

                # has_aux keeps aux-state updates (BN running stats)
                # outside the cotangent space, so custom_vjp symbolic-zero
                # fast paths (e.g. BN's one-pass backward) apply on the
                # placed path exactly as on the fused path.
                def f(dv):
                    iv = list(in_vals)
                    for i, v in zip(diff_idx, dv):
                        iv[i] = v
                    return run(tuple(iv), rng)

                _, vjp_fn, _aux = jax.vjp(f, diff_vals, has_aux=True)
                (cts_in,) = vjp_fn(cts_out)
                return cts_in

            self._fn_cache[key] = (jax.jit(bwd), diff_idx)
        else:
            _M_CACHE_HITS.inc(segment="seg%d_bwd" % si)
        return self._fn_cache[key]

    @staticmethod
    def _dev_of(v):
        devs = getattr(v, "devices", None)
        return next(iter(devs())) if callable(devs) else None

    def __call__(self, args_by_name, aux_by_name, rng, is_train,
                 with_vjp=False):
        env = {}
        for name, v in args_by_name.items():
            node = self.program._var_nodes.get(name)
            if node is not None:
                env[(id(node), 0)] = v
        for name, v in aux_by_name.items():
            node = self.program._var_nodes.get(name)
            if node is not None:
                env[(id(node), 0)] = v
        new_aux = {}
        saved = []
        for si, (dev, _nodes) in enumerate(self.segments):
            needs, out_keys, aux_names = self._seg_io[si]
            for k in needs:
                if k not in env:
                    raise MXNetError(
                        "executor: missing input for placed segment")
            in_vals = tuple(jax.device_put(env[k], dev) for k in needs)
            outs, aux_vals = self._seg_fn(si, is_train)(in_vals, rng)
            if with_vjp:
                saved.append((in_vals, aux_vals, rng))
            env.update(zip(out_keys, outs))
            new_aux.update(zip(aux_names, aux_vals))
        outputs = [env[(id(n), i)] for n, i in self.program.output_entries]
        for name in self.program.aux_names:
            if name not in new_aux:
                new_aux[name] = aux_by_name[name]
        return outputs, new_aux, (env, saved)

    def backward(self, env, saved, out_cts):
        """Reverse pass over the segments; returns cotangent env keyed
        like the forward env (var grads live at their var-node keys)."""
        ct_env = {}

        def _accum(k, ct):
            if k in ct_env:
                ct_env[k] = ct_env[k] + jax.device_put(
                    ct, self._dev_of(ct_env[k]))
            else:
                ct_env[k] = ct

        for (n, i), ct in zip(self.program.output_entries, out_cts):
            _accum((id(n), i), ct)
        for si in reversed(range(len(self.segments))):
            dev, _nodes = self.segments[si]
            needs, out_keys, _aux_names = self._seg_io[si]
            in_vals, _aux_vals, rng = saved[si]
            bwd, diff_idx = self._seg_bwd_fn(si)
            if not diff_idx:
                continue  # nothing upstream of this segment needs grads
            cts_out = tuple(
                jax.device_put(ct_env[k], dev) if k in ct_env
                else jnp.zeros_like(env[k])
                for k in out_keys
            )
            cts_in = bwd(in_vals, rng, cts_out)
            for i, ct in zip(diff_idx, cts_in):
                _accum(needs[i], ct)
        return ct_env


def resolve_creation_shapes(symbol, shapes_by_name):
    """For creation ops (_zeros/_ones) whose shape attr has unknown (0)
    dims — MXNet's bind-time-inferred convention, e.g. rnn_cell
    begin_state batch dims — resolve concrete shapes via graph-wide
    inference given the input shapes. Returns a _GraphProgram
    shape_overrides dict. Used by Executor at bind and ShardedTrainStep
    at first call (same program layer, two front doors)."""
    nodes = _topo_order([n for n, _ in symbol._outputs])
    from .ops.utils import as_tuple

    def _shape_attr(n):
        return as_tuple(n.canon_attrs().get("shape")) or ()

    pending = [
        n for n in nodes
        if (not n.is_variable) and not n.inputs and 0 in _shape_attr(n)
    ]
    if not pending:
        return {}
    env = symbol._infer_shape_env(**shapes_by_name)
    return {id(n): env[(id(n), 0)] for n in pending if (id(n), 0) in env}


class Executor:
    """Bound computation: holds arg/grad/aux NDArrays + compiled step fns.

    Parity: reference ``include/mxnet/executor.h`` —
    Forward/Backward/outputs/arg_dict/grad_dict/aux_dict/reshape/
    copy_params_from/set_monitor_callback.
    """

    def __init__(self, symbol, ctx, arg_arrays, grad_arrays, grad_req,
                 aux_arrays, group2ctx=None):
        self._symbol = symbol
        self._ctx = ctx
        overrides = self._resolve_creation_shapes(symbol, arg_arrays)
        self._program = _GraphProgram(symbol, overrides)
        self.arg_arrays = list(arg_arrays)
        self.grad_arrays = list(grad_arrays)
        self.aux_arrays = list(aux_arrays)
        self._arg_names = self._program.arg_names
        self._aux_names = self._program.aux_names
        self._output_names = symbol.list_outputs()
        self._group2ctx = group2ctx or {}
        self._monitor_callback = None
        if isinstance(grad_req, str):
            grad_req = {n: grad_req for n in self._arg_names}
        elif isinstance(grad_req, (list, tuple)):
            grad_req = dict(zip(self._arg_names, grad_req))
        self._grad_req = grad_req
        # names we differentiate wrt (grad buffer attached + req != null)
        self._grad_names = [
            n
            for n, g in zip(self._arg_names, self.grad_arrays)
            if g is not None and self._grad_req.get(n, "null") != "null"
        ]
        self._outputs_list = [None] * len(self._output_names)
        self._stash = None  # (arg_vals, aux_vals, rng) captured at forward()
        self._needs_rng = any(
            (not n.is_variable) and n.op.needs_rng for n in self._program.nodes
        )
        self._placed = self._build_placed()
        if self._placed is not None:
            self._fwd_jit = _instrument_jit(
                self._make_fwd_placed(), "fwd_placed")
            self._fwdbwd_jit = _instrument_jit(
                self._make_fwdbwd_placed(), "fwdbwd_placed")
        else:
            self._fwd_jit = _instrument_jit(self._make_fwd(), "fwd")
            self._fwdbwd_jit = _instrument_jit(self._make_fwdbwd(), "fwdbwd")
        self._pending_train_step = False

    def _build_placed(self):
        """ctx_group placement (reference AssignContext/PlaceDevice):
        returns a _PlacedProgram when any node's ctx_group maps through
        group2ctx to a device other than the bind ctx, else None (the
        whole-graph single-device jit stays the fast path)."""
        if not self._group2ctx:
            return None
        default_dev = self._ctx.jax_device
        node_dev = {}
        distinct = False
        for node in self._program.nodes:
            if node.is_variable:
                # variable-only groups count too: simple_bind committed
                # such params to their group's device, and the whole-
                # graph jit would crash on mixed committed inputs
                grp = _ctx_group(node)
                ctx = self._group2ctx.get(grp) if grp else None
                if ctx is not None and ctx.jax_device != default_dev:
                    distinct = True
                continue
            grp = _ctx_group(node)
            ctx = self._group2ctx.get(grp) if grp else None
            dev = ctx.jax_device if ctx is not None else default_dev
            node_dev[id(node)] = dev
            if dev != default_dev:
                distinct = True
        if not distinct:
            return None
        return _PlacedProgram(self._program, node_dev,
                              grad_names=self._grad_names)

    @staticmethod
    def _resolve_creation_shapes(symbol, arg_arrays):
        arg_names = symbol.list_arguments()
        shapes = {
            n: a.shape for n, a in zip(arg_names, arg_arrays) if a is not None
        }
        return resolve_creation_shapes(symbol, shapes)

    # ------------------------------------------------------------------
    # compiled callables
    # ------------------------------------------------------------------
    def _make_fwd(self):
        program = self._program
        arg_names = tuple(self._arg_names)
        aux_names = tuple(self._aux_names)

        @functools.partial(jax.jit, static_argnums=(3,))
        def fwd(arg_vals, aux_vals, rng, is_train):
            args = dict(zip(arg_names, arg_vals))
            aux = dict(zip(aux_names, aux_vals))
            outs, new_aux = program(args, aux, rng, is_train)
            return tuple(outs), tuple(new_aux[n] for n in aux_names)

        return fwd

    def _make_fwdbwd(self):
        program = self._program
        arg_names = tuple(self._arg_names)
        aux_names = tuple(self._aux_names)
        grad_names = tuple(self._grad_names)

        do_mirror = _mirror_enabled()

        @jax.jit
        def fwdbwd(arg_vals, aux_vals, rng, out_grads):
            args = dict(zip(arg_names, arg_vals))
            aux = dict(zip(aux_names, aux_vals))
            fixed = {k: v for k, v in args.items() if k not in grad_names}

            def f(diff_vals):
                a = dict(fixed)
                a.update(dict(zip(grad_names, diff_vals)))
                outs, new_aux = program(a, aux, rng, True)
                return tuple(outs), tuple(new_aux[n] for n in aux_names)

            if do_mirror:
                # memory mirror: trade recompute FLOPs for activation
                # memory exactly where the reference does
                f = jax.checkpoint(f, policy=_mirror_policy)

            diff_vals = tuple(args[n] for n in grad_names)
            # has_aux: aux-state updates ride OUTSIDE the cotangent space
            # (they never carry gradient), so ops whose bwd rule detects
            # symbolic-zero cotangents (BatchNorm's mean/var outputs)
            # skip those terms instead of streaming zero arrays through
            # the graph.
            outs, vjp_fn, new_aux = jax.vjp(f, diff_vals, has_aux=True)
            if out_grads is None:
                cts = tuple(jnp.ones_like(o) for o in outs)
            else:
                cts = tuple(out_grads)
            (grads,) = vjp_fn(cts)
            return outs, new_aux, grads

        return fwdbwd

    def _make_fwd_placed(self):
        placed = self._placed
        arg_names = tuple(self._arg_names)
        aux_names = tuple(self._aux_names)

        def fwd(arg_vals, aux_vals, rng, is_train):
            args = dict(zip(arg_names, arg_vals))
            aux = dict(zip(aux_names, aux_vals))
            outs, new_aux, _ = placed(args, aux, rng, is_train)
            return tuple(outs), tuple(new_aux[n] for n in aux_names)

        return fwd

    def _make_fwdbwd_placed(self):
        placed = self._placed
        arg_names = tuple(self._arg_names)
        aux_names = tuple(self._aux_names)
        grad_names = tuple(self._grad_names)
        var_nodes = self._program._var_nodes

        def fwdbwd(arg_vals, aux_vals, rng, out_grads):
            args = dict(zip(arg_names, arg_vals))
            aux = dict(zip(aux_names, aux_vals))
            outs, new_aux, (env, vjps) = placed(
                args, aux, rng, True, with_vjp=True)
            if out_grads is None:
                cts = tuple(jnp.ones_like(o) for o in outs)
            else:
                cts = tuple(out_grads)
            ct_env = placed.backward(env, vjps, cts)
            grads = []
            for name in grad_names:
                key = (id(var_nodes[name]), 0)
                ct = ct_env.get(key)
                if ct is None:
                    ct = jnp.zeros_like(args[name])
                else:
                    # grad lands where the param lives (its ctx_group
                    # device), like reference arg_grad ctx assignment
                    ct = jax.device_put(
                        ct, _PlacedProgram._dev_of(args[name]))
                grads.append(ct)
            return (tuple(outs), tuple(new_aux[n] for n in aux_names),
                    tuple(grads))

        return fwdbwd

    # ------------------------------------------------------------------
    # public API
    # ------------------------------------------------------------------
    def _drain_pending_pulls(self):
        """kvstore-managed weights may have an engine-scheduled pull
        still in flight (the executor-path overlap this framework
        preserves from the reference's prioritized comm engine); drain
        before snapshotting ._data. The inline attr check keeps the
        common no-kvstore case to one comparison per array."""
        for a in self.arg_arrays:
            if a._engine_dep is not None:
                a._drain_engine()

    def forward(self, is_train=False, **kwargs):
        """Parity: Executor::Forward. For a training step the launch is
        deferred so backward() can run forward+backward as ONE fused XLA
        executable (the whole-graph analog of the reference's bulk-exec
        segments); reading .outputs before backward() materializes a
        forward-only run from the same stashed inputs + rng, so results are
        bit-identical either way."""
        if kwargs:
            arg_dict = self.arg_dict
            for k, v in kwargs.items():
                if k not in arg_dict:
                    raise MXNetError("unknown input %s" % k)
                if arg_dict[k]._engine_dep is not None:
                    arg_dict[k]._drain_engine()  # don't race a pull
                if isinstance(v, NDArray):
                    arg_dict[k]._data = v._data
                else:
                    arg_dict[k]._data = nd.array(v)._data
        rng = _random.next_key() if self._needs_rng else None
        self._drain_pending_pulls()
        arg_vals = tuple(a._data for a in self.arg_arrays)
        aux_vals = tuple(a._data for a in self.aux_arrays)
        self._stash = (arg_vals, aux_vals, rng, bool(is_train))
        if is_train and self._grad_names:
            self._pending_train_step = True
            # lazy view: materializes via the outputs property on first
            # element access, so callers using forward()'s return value get
            # fresh data while the fit loop (which ignores it) keeps the
            # single fused fwd+bwd launch.
            return _LazyOutputs(self)
        self._pending_train_step = False
        with _tm.span("executor.forward", train=bool(is_train)):
            t0 = time.perf_counter()
            outs, new_aux = self._fwd_jit(
                arg_vals, aux_vals, rng, bool(is_train))
            _H_STEP_SECONDS.observe(time.perf_counter() - t0, phase="fwd")
        self._set_outputs(outs)
        if is_train:
            for a, v in zip(self.aux_arrays, new_aux):
                a._data = v
        self._run_monitor()
        return self.outputs

    @property
    def outputs(self):
        if self._pending_train_step:
            arg_vals, aux_vals, rng, _ = self._stash
            outs, new_aux = self._fwd_jit(arg_vals, aux_vals, rng, True)
            self._set_outputs(outs)
            # moving-stat aux updates happen on forward in the reference
            # (FMutateInputs); backward recomputes the same values from the
            # stashed aux so there is no double-apply.
            for a, v in zip(self.aux_arrays, new_aux):
                a._data = v
            self._pending_train_step = False
        return self._outputs_list

    def _set_outputs(self, outs):
        for i, v in enumerate(outs):
            if self._outputs_list[i] is None:
                self._outputs_list[i] = NDArray(v)
            else:
                self._outputs_list[i]._data = v
        return self._outputs_list

    def backward(self, out_grads=None):
        """Run the fused forward+backward XLA step and write gradients into
        grad_arrays honoring grad_req (write/add/null). Parity:
        Executor::Backward; grad_req semantics = kWriteTo/kAddTo/kNullOp."""
        if out_grads is not None:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            out_grads = tuple(_as_jax(g) for g in out_grads)
        if not self._grad_names:
            return
        if self._stash is not None:
            arg_vals, aux_vals, rng, _ = self._stash
        else:
            # backward without a prior forward must not snapshot stale
            # weights either
            self._drain_pending_pulls()
            arg_vals = tuple(a._data for a in self.arg_arrays)
            aux_vals = tuple(a._data for a in self.aux_arrays)
            rng = _random.next_key() if self._needs_rng else None
        with _tm.span("executor.fwdbwd"):
            t0 = time.perf_counter()
            outs, new_aux, grads = self._fwdbwd_jit(
                arg_vals, aux_vals, rng, out_grads)
            _H_STEP_SECONDS.observe(time.perf_counter() - t0, phase="fwdbwd")
        self._pending_train_step = False
        self._set_outputs(outs)
        for a, v in zip(self.aux_arrays, new_aux):
            a._data = v
        gmap = dict(zip(self._grad_names, grads))
        for name, garr in zip(self._arg_names, self.grad_arrays):
            if garr is None or name not in gmap:
                continue
            req = self._grad_req.get(name, "write")
            if req == "add":
                garr._data = garr._data + gmap[name]
            elif req == "write":
                garr._data = gmap[name]
        self._run_monitor()

    # ------------------------------------------------------------------
    # dict views (parity executor.py:248-298)
    # ------------------------------------------------------------------
    @property
    def arg_dict(self):
        return dict(zip(self._arg_names, self.arg_arrays))

    @property
    def grad_dict(self):
        return dict(zip(self._arg_names, self.grad_arrays))

    @property
    def aux_dict(self):
        return dict(zip(self._aux_names, self.aux_arrays))

    @property
    def output_dict(self):
        return dict(zip(self._output_names, self.outputs))

    def copy_params_from(self, arg_params, aux_params=None, allow_extra_params=False):
        arg_dict = self.arg_dict
        for name, array in arg_params.items():
            if name in arg_dict:
                array.copyto(arg_dict[name])
            elif not allow_extra_params:
                raise MXNetError("Found name \"%s\" not in executor arguments" % name)
        if aux_params is not None:
            aux_dict = self.aux_dict
            for name, array in aux_params.items():
                if name in aux_dict:
                    array.copyto(aux_dict[name])
                elif not allow_extra_params:
                    raise MXNetError("Found name \"%s\" not in executor aux states" % name)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Return a new executor with new input shapes, sharing parameter
        arrays (parity executor.py:360; the reference shares memory — XLA
        owns buffers here so we share the NDArray handles)."""
        arg_shapes, _, aux_shapes = self._symbol.infer_shape(**kwargs)
        new_args = []
        new_grads = []
        for name, arr, garr, shp in zip(
            self._arg_names, self.arg_arrays, self.grad_arrays, arg_shapes
        ):
            if name in kwargs or tuple(arr.shape) != tuple(shp):
                new_args.append(nd.zeros(shp, ctx=self._ctx, dtype=arr.dtype))
                new_grads.append(
                    None if garr is None else nd.zeros(shp, ctx=self._ctx, dtype=arr.dtype)
                )
            else:
                new_args.append(arr)
                new_grads.append(garr)
        new_aux = []
        for arr, shp in zip(self.aux_arrays, aux_shapes):
            if tuple(arr.shape) != tuple(shp):
                new_aux.append(nd.zeros(shp, ctx=self._ctx, dtype=arr.dtype))
            else:
                new_aux.append(arr)
        return Executor(
            self._symbol, self._ctx, new_args, new_grads, self._grad_req,
            new_aux, self._group2ctx
        )

    def set_monitor_callback(self, callback):
        self._monitor_callback = callback

    def _run_monitor(self):
        if self._monitor_callback is None:
            return
        for name, out in zip(self._output_names, self.outputs):
            if out is not None:
                self._monitor_callback(name, out)

    def debug_str(self):
        return self._symbol.debug_str()

    # ------------------------------------------------------------------
    # binding entry points
    # ------------------------------------------------------------------
    @staticmethod
    def bind(symbol, ctx, args, args_grad=None, grad_req="write",
             aux_states=None, group2ctx=None, shared_exec=None):
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0]
        if not isinstance(ctx, Context):
            ctx = Context(ctx)
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        arg_arrays = _check_arguments(args, arg_names, "args")
        if args_grad is None:
            grad_arrays = [None] * len(arg_names)
        elif isinstance(args_grad, dict):
            grad_arrays = [args_grad.get(n) for n in arg_names]
        else:
            grad_arrays = list(args_grad)
            grad_arrays += [None] * (len(arg_names) - len(grad_arrays))
        if aux_states is None:
            aux_arrays = []
            if aux_names:
                _, _, aux_shapes = symbol.infer_shape(
                    **{n: a.shape for n, a in zip(arg_names, arg_arrays)}
                )
                aux_arrays = [nd.zeros(s, ctx=ctx) for s in aux_shapes]
        elif isinstance(aux_states, dict):
            aux_arrays = [aux_states[n] for n in aux_names]
        else:
            aux_arrays = list(aux_states)
        return Executor(
            symbol, ctx, arg_arrays, grad_arrays, grad_req, aux_arrays, group2ctx
        )

    @staticmethod
    def _var_contexts(symbol, group2ctx):
        """name -> Context for inputs with a ctx_group placement: a
        variable's own ctx_group attr wins, else it inherits its first
        consumer's group (reference AssignContext propagation,
        graph_executor.cc:245-334)."""
        if not group2ctx:
            return {}
        out = {}
        nodes = _topo_order([n for n, _ in symbol._outputs])
        for n in nodes:
            if n.is_variable:
                grp = _ctx_group(n)
                if grp in group2ctx:
                    out[n.name] = group2ctx[grp]
        for n in nodes:
            if n.is_variable:
                continue
            grp = _ctx_group(n)
            if grp not in group2ctx:
                continue
            for (c, _i) in n.inputs:
                if c.is_variable and c.name not in out:
                    out[c.name] = group2ctx[grp]
        return out

    @staticmethod
    def simple_bind(symbol, ctx, grad_req="write", type_dict=None,
                    group2ctx=None, shared_exec=None, **kwargs):
        """Infer shapes/types, allocate arg/grad/aux arrays, bind.
        Parity: symbol.py:1114. With group2ctx, params/grads allocate on
        their group's device (reference simple_bind honors AssignContext
        when allocating, symbol.py:1114-1210)."""
        if isinstance(ctx, (list, tuple)):
            ctx = ctx[0]
        if not isinstance(ctx, Context):
            ctx = Context(ctx)
        arg_shapes, _, aux_shapes = symbol.infer_shape(**kwargs)
        arg_types, _, aux_types = symbol.infer_type(**(type_dict or {}))
        arg_names = symbol.list_arguments()
        var_ctx = Executor._var_contexts(symbol, group2ctx)
        # share param arrays with shared_exec when shapes match (bucketing)
        shared = shared_exec.arg_dict if shared_exec is not None else {}
        arg_arrays = []
        for name, shape, dtype in zip(arg_names, arg_shapes, arg_types):
            if name in shared and tuple(shared[name].shape) == tuple(shape):
                arg_arrays.append(shared[name])
            else:
                arg_arrays.append(
                    nd.zeros(shape, ctx=var_ctx.get(name, ctx), dtype=dtype))
        req_of = (
            (lambda n: grad_req)
            if isinstance(grad_req, str)
            else (lambda n: grad_req.get(n, "null"))
            if isinstance(grad_req, dict)
            else (lambda n: dict(zip(arg_names, grad_req)).get(n, "null"))
        )
        grad_arrays = [
            nd.zeros(shape, ctx=var_ctx.get(name, ctx), dtype=dtype)
            if req_of(name) != "null" else None
            for name, shape, dtype in zip(arg_names, arg_shapes, arg_types)
        ]
        shared_aux = shared_exec.aux_dict if shared_exec is not None else {}
        aux_names = symbol.list_auxiliary_states()
        aux_arrays = []
        for name, shape, dtype in zip(aux_names, aux_shapes, aux_types):
            if name in shared_aux and tuple(shared_aux[name].shape) == tuple(shape):
                aux_arrays.append(shared_aux[name])
            else:
                # aux states (BN moving stats) live with their owning
                # node's group too — _var_contexts covers them because
                # aux vars appear among consumer-node inputs
                aux_arrays.append(
                    nd.zeros(shape, ctx=var_ctx.get(name, ctx), dtype=dtype))
        return Executor(
            symbol, ctx, arg_arrays, grad_arrays, grad_req, aux_arrays, group2ctx
        )


def _check_arguments(args, names, kind):
    if isinstance(args, dict):
        out = []
        for n in names:
            if n not in args:
                raise MXNetError("missing %s: %s" % (kind, n))
            out.append(args[n])
        return out
    args = list(args)
    if len(args) != len(names):
        raise MXNetError(
            "%s length %d != expected %d (%s)" % (kind, len(args), len(names), names)
        )
    return args
