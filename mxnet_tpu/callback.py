"""Training-loop callbacks.

Capability parity with reference ``python/mxnet/callback.py``
(Speedometer — the samples/sec logger behind every reference benchmark —
do_checkpoint, module_checkpoint, log_train_metric, ProgressBar),
re-designed around two small shared pieces: a ``_every`` period gate and
a ``_Throughput`` timer, instead of open-coded state in each callback.
Log message formats match the reference (they are observable output that
downstream log scrapers parse).
"""
from __future__ import annotations

import logging
import sys
import time

from . import telemetry as _tm

_G_SAMPLES_PER_SEC = _tm.gauge(
    "fit.samples_per_sec", "Training throughput over the Speedometer's "
    "last window")


def _every(period, fn):
    """Epoch-end callback firing fn on each period-th (1-based) epoch."""
    period = max(1, int(period))

    def _callback(iter_no, *state):
        epoch = iter_no + 1
        if epoch % period == 0:
            fn(epoch, *state)

    return _callback


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Checkpoint a Module every ``period`` epochs."""
    return _every(
        period,
        lambda epoch, *_s: mod.save_checkpoint(
            prefix, epoch, save_optimizer_states),
    )


def do_checkpoint(prefix, period=1):
    """Checkpoint (symbol, args, aux) every ``period`` epochs — the
    epoch_end_callback shape fit() passes (iter_no, sym, arg, aux)."""
    from .model import save_checkpoint

    return _every(
        period,
        lambda epoch, sym, arg, aux: save_checkpoint(
            prefix, epoch, sym, arg, aux),
    )


def log_train_metric(period, auto_reset=False):
    """Log the running training metric every ``period`` batches."""

    def _callback(param):
        if param.nbatch % period == 0 and param.eval_metric is not None:
            for name, value in param.eval_metric.get_name_value():
                logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                             param.epoch, param.nbatch, name, value)
            if auto_reset:
                param.eval_metric.reset()

    return _callback


class _Throughput:
    """Samples/sec sampled every ``frequent`` batches; owns ALL window
    state, including epoch-rollover restarts."""

    def __init__(self, batch_size, frequent):
        self.batch_size = batch_size
        self.frequent = frequent
        self._since = None
        self._last_batch = 0

    def sample(self, nbatch):
        """samples/sec when a full window just closed at nbatch, else
        None (off-period batch, first window still filling, or an epoch
        rollover that restarts the window)."""
        rolled = nbatch < self._last_batch
        if not rolled and nbatch % self.frequent != 0:
            return None
        now = time.time()
        armed = self._since is not None
        elapsed = max(now - (self._since or now), 1e-12)
        n_batches = nbatch - self._last_batch
        self._since = now
        self._last_batch = nbatch
        if rolled or not armed:
            return None
        return n_batches * self.batch_size / elapsed


class Speedometer(object):
    """Log throughput (and the running metric, which it resets) every
    ``frequent`` batches — the number all BASELINE.md rows quote."""

    def __init__(self, batch_size, frequent=50):
        self.batch_size = batch_size
        self.frequent = frequent
        self._meter = _Throughput(batch_size, frequent)

    def __call__(self, param):
        nbatch = param.nbatch
        speed = self._meter.sample(nbatch)
        if speed is None:
            return
        _G_SAMPLES_PER_SEC.set(speed)
        if param.eval_metric is not None:
            name_values = param.eval_metric.get_name_value()
            param.eval_metric.reset()
            for name, value in name_values:
                logging.info(
                    "Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec\t"
                    "Train-%s=%f", param.epoch, nbatch, speed, name, value)
        else:
            logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                         param.epoch, nbatch, speed)


class ProgressBar(object):
    """Render batch progress as a fixed-width terminal bar."""

    def __init__(self, total, length=80):
        self.bar_len = length
        self.total = total

    def __call__(self, param):
        frac = param.nbatch / float(self.total)
        done = int(round(self.bar_len * frac))
        pct = -(-100 * param.nbatch // self.total)  # ceil
        sys.stdout.write("[%s] %s%%\r" % (
            "=" * done + "-" * (self.bar_len - done), pct))
