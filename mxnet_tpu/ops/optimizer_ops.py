"""Fused optimizer update ops.

Parity: reference ``src/operator/tensor/optimizer_op.cc:18-102``
(sgd_update, sgd_mom_update, adam_update, rmsprop_update,
rmspropalex_update). Each is one fused XLA computation; the reference's
in-place mutation of weight/state maps to ``mutate_inputs`` write-back.

Update math matches the reference kernels in ``optimizer_op-inl.h``:
  rescaled = clip(rescale_grad * grad, clip_gradient) + wd * weight
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import OpDef, register


def _prep_grad(weight, grad, attrs):
    g = grad * _f(attrs.get("rescale_grad", 1.0))
    clip = attrs.get("clip_gradient", -1.0)
    if clip is not None and float(clip) > 0:
        g = jnp.clip(g, -float(clip), float(clip))
    return g + _f(attrs.get("wd", 0.0)) * weight



def _f(v):
    """Attr as multiplier: host floats stay floats; traced scalars pass
    through (lr/wd enter the fused ShardedTrainStep as per-call inputs)."""
    try:
        return float(v)
    except TypeError:
        return v

_COMMON = {"lr": 0.01, "wd": 0.0, "rescale_grad": 1.0, "clip_gradient": -1.0}


def _sgd_update(attrs, ins, is_train):
    weight, grad = ins
    g = _prep_grad(weight, grad, attrs)
    return [weight - _f(attrs["lr"]) * g]


register(
    OpDef(
        "sgd_update",
        _sgd_update,
        arguments=("weight", "grad"),
        defaults=dict(_COMMON),
    )
)


def _sgd_mom_update(attrs, ins, is_train):
    weight, grad, mom = ins
    g = _prep_grad(weight, grad, attrs)
    new_mom = _f(attrs.get("momentum", 0.0)) * mom - _f(attrs["lr"]) * g
    return [weight + new_mom, new_mom]


register(
    OpDef(
        "sgd_mom_update",
        _sgd_mom_update,
        arguments=("weight", "grad", "mom"),
        defaults=dict(_COMMON, momentum=0.0),
        mutate_inputs=(2,),
    )
)


def _adam_update(attrs, ins, is_train):
    weight, grad, mean, var = ins
    beta1 = float(attrs.get("beta1", 0.9))
    beta2 = float(attrs.get("beta2", 0.999))
    eps = float(attrs.get("epsilon", 1e-8))
    g = _prep_grad(weight, grad, attrs)
    new_mean = beta1 * mean + (1.0 - beta1) * g
    new_var = beta2 * var + (1.0 - beta2) * jnp.square(g)
    new_w = weight - _f(attrs["lr"]) * new_mean / (jnp.sqrt(new_var) + eps)
    return [new_w, new_mean, new_var]


register(
    OpDef(
        "adam_update",
        _adam_update,
        arguments=("weight", "grad", "mean", "var"),
        defaults=dict(_COMMON, beta1=0.9, beta2=0.999, epsilon=1e-8),
        mutate_inputs=(2, 3),
    )
)


def _rmsprop_update(attrs, ins, is_train):
    weight, grad, n = ins
    gamma1 = float(attrs.get("gamma1", 0.95))
    eps = float(attrs.get("epsilon", 1e-8))
    g = _prep_grad(weight, grad, attrs)
    new_n = (1.0 - gamma1) * jnp.square(g) + gamma1 * n
    delta = -_f(attrs["lr"]) * g / jnp.sqrt(new_n + eps)
    cw = attrs.get("clip_weights", -1.0)
    new_w = weight + delta
    if cw is not None and float(cw) > 0:
        new_w = jnp.clip(new_w, -float(cw), float(cw))
    return [new_w, new_n]


register(
    OpDef(
        "rmsprop_update",
        _rmsprop_update,
        arguments=("weight", "grad", "n"),
        defaults=dict(_COMMON, gamma1=0.95, epsilon=1e-8, clip_weights=-1.0),
        mutate_inputs=(2,),
    )
)


def _rmspropalex_update(attrs, ins, is_train):
    weight, grad, n, g_avg, delta = ins
    gamma1 = float(attrs.get("gamma1", 0.95))
    gamma2 = float(attrs.get("gamma2", 0.9))
    eps = float(attrs.get("epsilon", 1e-8))
    g = _prep_grad(weight, grad, attrs)
    new_n = (1.0 - gamma1) * jnp.square(g) + gamma1 * n
    new_g = (1.0 - gamma1) * g + gamma1 * g_avg
    new_delta = gamma2 * delta - _f(attrs["lr"]) * g / jnp.sqrt(
        new_n - jnp.square(new_g) + eps
    )
    new_w = weight + new_delta
    cw = attrs.get("clip_weights", -1.0)
    if cw is not None and float(cw) > 0:
        new_w = jnp.clip(new_w, -float(cw), float(cw))
    return [new_w, new_n, new_g, new_delta]


register(
    OpDef(
        "rmspropalex_update",
        _rmspropalex_update,
        arguments=("weight", "grad", "n", "g", "delta"),
        defaults=dict(_COMMON, gamma1=0.95, gamma2=0.9, epsilon=1e-8, clip_weights=-1.0),
        mutate_inputs=(2, 3, 4),
    )
)
