"""Index / gather / ordering ops.

Parity: reference ``src/operator/tensor/indexing_op.cc`` (take, batch_take,
one_hot, Embedding, pick, argsort family in ``ordering_op.cc``). The
reference's GPU path uses cub/thrust device sorts (``sort_op-inl.cuh``);
XLA's variadic sort replaces that here.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .registry import OpDef, register


# --------------------------------------------------------------------------
# take / batch_take / Embedding
# --------------------------------------------------------------------------
def _take(attrs, ins, is_train):
    a, idx = ins
    axis = int(attrs.get("axis", 0))
    mode = attrs.get("mode", "clip")
    return [jnp.take(a, idx.astype(jnp.int32), axis=axis, mode=mode)]


def _take_infer(attrs, in_shapes):
    a, idx = in_shapes
    if a is None or idx is None:
        raise MXNetError("take: both shapes required")
    axis = int(attrs.get("axis", 0))
    out = tuple(a[:axis]) + tuple(idx) + tuple(a[axis + 1:])
    return [tuple(a), tuple(idx)], [out], []


register(
    OpDef(
        "take",
        _take,
        arguments=("a", "indices"),
        defaults={"axis": 0, "mode": "clip"},
        infer_shape=_take_infer,
    )
)


def _batch_take(attrs, ins, is_train):
    a, idx = ins
    return [jnp.take_along_axis(a, idx.astype(jnp.int32)[:, None], axis=1)[:, 0]]


register(
    OpDef(
        "batch_take",
        _batch_take,
        arguments=("a", "indices"),
        infer_shape=lambda attrs, in_shapes: (
            [tuple(in_shapes[0]), tuple(in_shapes[1])],
            [tuple(in_shapes[1])],
            [],
        ),
    )
)


def _embedding(attrs, ins, is_train):
    data, weight = ins
    idx = data.astype(jnp.int32)
    return [jnp.take(weight, idx, axis=0)]


def _embedding_infer(attrs, in_shapes):
    dshape, wshape = in_shapes
    if dshape is None:
        raise MXNetError("Embedding: data shape required")
    inp = int(attrs["input_dim"])
    out = int(attrs["output_dim"])
    wshape = (inp, out)
    return [tuple(dshape), wshape], [tuple(dshape) + (out,)], []


register(
    OpDef(
        "Embedding",
        _embedding,
        arguments=("data", "weight"),
        defaults={"input_dim": 0, "output_dim": 0},
        infer_shape=_embedding_infer,
    )
)


# --------------------------------------------------------------------------
# one_hot / pick
# --------------------------------------------------------------------------
def _one_hot(attrs, ins, is_train):
    depth = int(attrs["depth"])
    on = float(attrs.get("on_value", 1.0))
    off = float(attrs.get("off_value", 0.0))
    from ..base import np_dtype

    dt = np_dtype(attrs.get("dtype", "float32"))
    oh = jax.nn.one_hot(ins[0].astype(jnp.int32), depth)
    return [(oh * (on - off) + off).astype(dt)]


register(
    OpDef(
        "one_hot",
        _one_hot,
        arguments=("indices",),
        defaults={"depth": 1, "on_value": 1.0, "off_value": 0.0, "dtype": "float32"},
        infer_shape=lambda attrs, in_shapes: (
            [tuple(in_shapes[0])],
            [tuple(in_shapes[0]) + (int(attrs["depth"]),)],
            [],
        ),
    )
)


def _pick(attrs, ins, is_train):
    data, index = ins
    axis = attrs.get("axis", -1)
    axis = int(axis) if axis is not None else -1
    keepdims = bool(attrs.get("keepdims", False))
    idx = jnp.expand_dims(index.astype(jnp.int32), axis=axis)
    out = jnp.take_along_axis(data, idx, axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return [out]


def _pick_infer(attrs, in_shapes):
    dshape = list(in_shapes[0])
    axis = attrs.get("axis", -1)
    axis = int(axis) if axis is not None else -1
    axis = axis % len(dshape)
    keepdims = bool(attrs.get("keepdims", False))
    ishape = dshape[:axis] + dshape[axis + 1:]
    out = list(dshape)
    if keepdims:
        out[axis] = 1
    else:
        out = ishape
    return [tuple(in_shapes[0]), tuple(ishape)], [tuple(out)], []


register(
    OpDef(
        "pick",
        _pick,
        arguments=("data", "index"),
        defaults={"axis": -1, "keepdims": False},
        infer_shape=_pick_infer,
        aliases=("choose_element_0index",),
    )
)


# --------------------------------------------------------------------------
# sort / argsort / topk (reference ordering_op.cc)
# --------------------------------------------------------------------------
def _resolve_axis(attrs, ndim):
    axis = attrs.get("axis", -1)
    if axis is None:
        return None
    return int(axis) % ndim


def _sort(attrs, ins, is_train):
    axis = _resolve_axis(attrs, ins[0].ndim)
    x = ins[0].reshape(-1) if axis is None else ins[0]
    axis = 0 if _resolve_axis(attrs, ins[0].ndim) is None else axis
    out = jnp.sort(x, axis=axis)
    if not bool(attrs.get("is_ascend", True)):
        out = jnp.flip(out, axis=axis)
    return [out]


register(
    OpDef(
        "sort",
        _sort,
        arguments=("data",),
        defaults={"axis": -1, "is_ascend": True},
    )
)


def _argsort(attrs, ins, is_train):
    axis = _resolve_axis(attrs, ins[0].ndim)
    x = ins[0].reshape(-1) if axis is None else ins[0]
    ax = 0 if axis is None else axis
    out = jnp.argsort(x, axis=ax)
    if not bool(attrs.get("is_ascend", True)):
        out = jnp.flip(out, axis=ax)
    return [out.astype(ins[0].dtype)]


register(
    OpDef(
        "argsort",
        _argsort,
        arguments=("data",),
        defaults={"axis": -1, "is_ascend": True},
    )
)


def _topk_out_shapes(attrs, ishape):
    axis = attrs.get("axis", -1)
    axis = len(ishape) - 1 if axis is None else int(axis) % len(ishape)
    k = int(attrs.get("k", 1))
    ret_typ = attrs.get("ret_typ", "indices")
    s = list(ishape)
    if ret_typ != "mask":
        s[axis] = k
    n_out = 2 if ret_typ == "both" else 1
    return [tuple(s)] * n_out, axis, k, ret_typ


def _topk(attrs, ins, is_train):
    out_shapes, axis, k, ret_typ = _topk_out_shapes(attrs, ins[0].shape)
    x = ins[0]
    is_ascend = bool(attrs.get("is_ascend", False))
    key = -x if not is_ascend else x
    idx = jnp.argsort(key, axis=axis)
    idx = jax.lax.slice_in_dim(idx, 0, k, axis=axis)
    vals = jnp.take_along_axis(x, idx, axis=axis)
    if ret_typ == "value":
        return [vals]
    if ret_typ == "indices":
        return [idx.astype(x.dtype)]
    if ret_typ == "mask":
        m = jnp.zeros(x.shape, x.dtype)
        m = jnp.put_along_axis(m, idx, jnp.ones_like(vals), axis=axis, inplace=False)
        return [m]
    return [vals, idx.astype(x.dtype)]


def _topk_infer(attrs, in_shapes):
    out_shapes, _, _, _ = _topk_out_shapes(attrs, in_shapes[0])
    return [tuple(in_shapes[0])], out_shapes, []


_topk_def = OpDef(
    "topk",
    _topk,
    arguments=("data",),
    defaults={"axis": -1, "k": 1, "ret_typ": "indices", "is_ascend": False},
    infer_shape=_topk_infer,
)
_topk_def.list_outputs = lambda attrs=None: (
    ["value", "indices"]
    if (attrs or {}).get("ret_typ") == "both"
    else ["output"]
)
register(_topk_def)
