"""Matrix / layout / slicing ops.

Parity: reference ``src/operator/tensor/matrix_op.cc`` (dot, batch_dot,
transpose, Reshape incl. the 0/-1/-2/-3/-4 special codes, Flatten,
expand_dims, slice, slice_axis, clip, repeat, tile, reverse),
``concat.cc``/``slice_channel.cc`` (layer-op generation in the reference),
``swapaxis.cc``, ``pad.cc``, and ``control_flow_op.cc`` (where).

dot/batch_dot lower to ``jax.lax.dot_general`` → the MXU systolic array;
`preferred_element_type=float32` keeps bf16 inputs accumulating in fp32,
matching TPU best practice rather than the reference's SGEMM.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .registry import OpDef, register
from .utils import as_tuple


# --------------------------------------------------------------------------
# Reshape with MXNet special codes (reference matrix_op-inl.h ReshapeParam)
# --------------------------------------------------------------------------
def _infer_reshape_target(ishape, target):
    ishape = tuple(ishape)
    if not target:
        raise MXNetError("Reshape: shape attr required")
    out = []
    src = list(ishape)
    i = 0  # index into src
    t = 0
    target = list(target)
    while t < len(target):
        d = target[t]
        if d == 0:
            out.append(src[i])
            i += 1
        elif d == -1:
            out.append(-1)
            i += 1  # placeholder; fixed below
        elif d == -2:
            out.extend(src[i:])
            i = len(src)
        elif d == -3:
            out.append(src[i] * src[i + 1])
            i += 2
        elif d == -4:
            d1, d2 = target[t + 1], target[t + 2]
            if d1 == -1:
                d1 = src[i] // d2
            if d2 == -1:
                d2 = src[i] // d1
            out.extend([d1, d2])
            i += 1
            t += 2
        else:
            out.append(int(d))
            i += 1
        t += 1
    if out.count(-1) > 1:
        raise MXNetError("Reshape: more than one -1")
    if -1 in out:
        knownprod = int(np.prod([d for d in out if d != -1])) or 1
        total = int(np.prod(ishape)) if ishape else 1
        out[out.index(-1)] = total // knownprod
    if int(np.prod(out) if out else 1) != int(np.prod(ishape) if ishape else 1):
        raise MXNetError("Reshape: size mismatch %s -> %s" % (ishape, out))
    return tuple(out)


def _reshape_fcompute(attrs, ins, is_train):
    tgt = attrs.get("shape") or attrs.get("target_shape")
    if isinstance(tgt, (int, np.integer)):
        tgt = (int(tgt),)
    return [ins[0].reshape(_infer_reshape_target(ins[0].shape, tgt))]


def _reshape_infer(attrs, in_shapes):
    ishape = in_shapes[0]
    if ishape is None:
        raise MXNetError("Reshape: input shape required")
    tgt = attrs.get("shape") or attrs.get("target_shape")
    if isinstance(tgt, (int, np.integer)):
        tgt = (int(tgt),)
    return [tuple(ishape)], [_infer_reshape_target(ishape, tgt)], []


register(
    OpDef(
        "Reshape",
        _reshape_fcompute,
        arguments=("data",),
        defaults={"shape": None},
        infer_shape=_reshape_infer,
        aliases=("reshape",),
    )
)

register(
    OpDef(
        "Flatten",
        lambda attrs, ins, is_train: [
            ins[0].reshape(ins[0].shape[0], -1)
        ],
        arguments=("data",),
        infer_shape=lambda attrs, in_shapes: (
            [tuple(in_shapes[0])],
            [(in_shapes[0][0], int(np.prod(in_shapes[0][1:])))],
            [],
        ),
        aliases=("flatten",),
    )
)


# --------------------------------------------------------------------------
# transpose / expand_dims / SwapAxis
# --------------------------------------------------------------------------
def _transpose(attrs, ins, is_train):
    axes = attrs.get("axes") or None
    return [jnp.transpose(ins[0], axes)]


def _transpose_infer(attrs, in_shapes):
    ishape = in_shapes[0]
    axes = attrs.get("axes") or tuple(reversed(range(len(ishape))))
    return [tuple(ishape)], [tuple(ishape[a] for a in axes)], []


register(
    OpDef(
        "transpose",
        _transpose,
        arguments=("data",),
        defaults={"axes": ()},
        infer_shape=_transpose_infer,
    )
)

register(
    OpDef(
        "expand_dims",
        lambda attrs, ins, is_train: [jnp.expand_dims(ins[0], int(attrs["axis"]))],
        arguments=("data",),
        defaults={"axis": 0},
        infer_shape=lambda attrs, in_shapes: (
            [tuple(in_shapes[0])],
            [
                tuple(
                    list(in_shapes[0])[: int(attrs["axis"]) % (len(in_shapes[0]) + 1)]
                    + [1]
                    + list(in_shapes[0])[int(attrs["axis"]) % (len(in_shapes[0]) + 1):]
                )
            ],
            [],
        ),
    )
)


def _swapaxis_infer(attrs, in_shapes):
    s = list(in_shapes[0])
    a, b = int(attrs.get("dim1", 0)), int(attrs.get("dim2", 0))
    s[a], s[b] = s[b], s[a]
    return [tuple(in_shapes[0])], [tuple(s)], []


register(
    OpDef(
        "SwapAxis",
        lambda attrs, ins, is_train: [
            jnp.swapaxes(ins[0], int(attrs.get("dim1", 0)), int(attrs.get("dim2", 0)))
        ],
        arguments=("data",),
        defaults={"dim1": 0, "dim2": 0},
        infer_shape=_swapaxis_infer,
        aliases=("swapaxes",),
    )
)


# --------------------------------------------------------------------------
# dot / batch_dot — the MXU path
# --------------------------------------------------------------------------
def _dot(attrs, ins, is_train):
    a, b = ins
    if attrs.get("transpose_a"):
        a = a.T if a.ndim == 2 else jnp.transpose(a)
    if attrs.get("transpose_b"):
        b = b.T if b.ndim == 2 else jnp.transpose(b)
    if a.ndim == 1 and b.ndim == 1:
        return [jnp.dot(a, b).reshape(1)]
    out = jnp.dot(a, b, preferred_element_type=jnp.float32)
    return [out.astype(jnp.result_type(ins[0], ins[1]))]


def _dot_infer(attrs, in_shapes):
    a, b = in_shapes
    if a is None or b is None:
        raise MXNetError("dot: both input shapes required")
    a = tuple(reversed(a)) if attrs.get("transpose_a") else tuple(a)
    b = tuple(reversed(b)) if attrs.get("transpose_b") else tuple(b)
    if len(a) == 1 and len(b) == 1:
        out = (1,)
    else:
        if a[-1] != b[0]:
            raise MXNetError("dot: shape mismatch %s %s" % (in_shapes[0], in_shapes[1]))
        out = a[:-1] + b[1:]
    return [tuple(in_shapes[0]), tuple(in_shapes[1])], [out], []


register(
    OpDef(
        "dot",
        _dot,
        arguments=("lhs", "rhs"),
        defaults={"transpose_a": False, "transpose_b": False},
        infer_shape=_dot_infer,
    )
)


def _batch_dot(attrs, ins, is_train):
    a, b = ins
    if attrs.get("transpose_a"):
        a = jnp.swapaxes(a, -1, -2)
    if attrs.get("transpose_b"):
        b = jnp.swapaxes(b, -1, -2)
    out = jnp.matmul(a, b, preferred_element_type=jnp.float32)
    return [out.astype(jnp.result_type(ins[0], ins[1]))]


def _batch_dot_infer(attrs, in_shapes):
    a, b = [list(s) for s in in_shapes]
    if attrs.get("transpose_a"):
        a[-1], a[-2] = a[-2], a[-1]
    if attrs.get("transpose_b"):
        b[-1], b[-2] = b[-2], b[-1]
    if a[-1] != b[-2] or a[:-2] != b[:-2]:
        raise MXNetError("batch_dot: shape mismatch %s %s" % tuple(in_shapes))
    return (
        [tuple(in_shapes[0]), tuple(in_shapes[1])],
        [tuple(a[:-1] + [b[-1]])],
        [],
    )


register(
    OpDef(
        "batch_dot",
        _batch_dot,
        arguments=("lhs", "rhs"),
        defaults={"transpose_a": False, "transpose_b": False},
        infer_shape=_batch_dot_infer,
    )
)


# --------------------------------------------------------------------------
# slice / slice_axis / clip / repeat / tile / reverse
# --------------------------------------------------------------------------
def _norm_begin_end(shape, begin, end):
    begin = list(begin)
    end = list(end)
    out_b, out_e = [], []
    for i, dim in enumerate(shape):
        b = begin[i] if i < len(begin) and begin[i] is not None else 0
        e = end[i] if i < len(end) and end[i] is not None else dim
        if b < 0:
            b += dim
        if e < 0:
            e += dim
        out_b.append(int(b))
        out_e.append(int(min(e, dim)))
    return out_b, out_e


def _slice(attrs, ins, is_train):
    b, e = _norm_begin_end(ins[0].shape, attrs["begin"], attrs["end"])
    idx = tuple(slice(bb, ee) for bb, ee in zip(b, e))
    return [ins[0][idx]]


def _slice_infer(attrs, in_shapes):
    b, e = _norm_begin_end(in_shapes[0], attrs["begin"], attrs["end"])
    return (
        [tuple(in_shapes[0])],
        [tuple(ee - bb for bb, ee in zip(b, e))],
        [],
    )


register(
    OpDef(
        "slice",
        _slice,
        arguments=("data",),
        defaults={"begin": (), "end": ()},
        infer_shape=_slice_infer,
        aliases=("crop",),
    )
)


def _slice_axis(attrs, ins, is_train):
    ax = int(attrs["axis"])
    dim = ins[0].shape[ax]
    b = int(attrs.get("begin", 0))
    e = attrs.get("end")
    e = dim if e is None else int(e)
    if b < 0:
        b += dim
    if e < 0:
        e += dim
    idx = [slice(None)] * ins[0].ndim
    idx[ax] = slice(b, e)
    return [ins[0][tuple(idx)]]


def _slice_axis_infer(attrs, in_shapes):
    s = list(in_shapes[0])
    ax = int(attrs["axis"])
    dim = s[ax]
    b = int(attrs.get("begin", 0))
    e = attrs.get("end")
    e = dim if e is None else int(e)
    if b < 0:
        b += dim
    if e < 0:
        e += dim
    s[ax] = e - b
    return [tuple(in_shapes[0])], [tuple(s)], []


register(
    OpDef(
        "slice_axis",
        _slice_axis,
        arguments=("data",),
        defaults={"axis": 0, "begin": 0, "end": None},
        infer_shape=_slice_axis_infer,
    )
)

register(
    OpDef(
        "clip",
        lambda attrs, ins, is_train: [
            jnp.clip(ins[0], float(attrs["a_min"]), float(attrs["a_max"]))
        ],
        arguments=("data",),
        defaults={"a_min": 0.0, "a_max": 1.0},
    )
)


def _repeat(attrs, ins, is_train):
    ax = attrs.get("axis")
    reps = int(attrs["repeats"])
    if ax is None:
        return [jnp.repeat(ins[0].reshape(-1), reps)]
    return [jnp.repeat(ins[0], reps, axis=int(ax))]


def _repeat_infer(attrs, in_shapes):
    ax = attrs.get("axis")
    reps = int(attrs["repeats"])
    if ax is None:
        out = (int(np.prod(in_shapes[0])) * reps,)
    else:
        s = list(in_shapes[0])
        s[int(ax)] *= reps
        out = tuple(s)
    return [tuple(in_shapes[0])], [out], []


register(
    OpDef(
        "repeat",
        _repeat,
        arguments=("data",),
        defaults={"repeats": 1, "axis": None},
        infer_shape=_repeat_infer,
    )
)


def _tile_infer(attrs, in_shapes):
    reps = as_tuple(attrs["reps"])
    s = list(in_shapes[0])
    if len(reps) < len(s):
        reps = (1,) * (len(s) - len(reps)) + reps
    if len(s) < len(reps):
        s = [1] * (len(reps) - len(s)) + s
    return [tuple(in_shapes[0])], [tuple(a * b for a, b in zip(s, reps))], []


register(
    OpDef(
        "tile",
        lambda attrs, ins, is_train: [jnp.tile(ins[0], as_tuple(attrs["reps"]))],
        arguments=("data",),
        defaults={"reps": (1,)},
        infer_shape=_tile_infer,
    )
)

register(
    OpDef(
        "reverse",
        lambda attrs, ins, is_train: [jnp.flip(ins[0], as_tuple(attrs["axis"]))],
        arguments=("data",),
        defaults={"axis": (0,)},
        aliases=("flip",),
    )
)


# --------------------------------------------------------------------------
# Concat / SliceChannel (multi-in / multi-out layer ops)
# --------------------------------------------------------------------------
def _concat_infer(attrs, in_shapes):
    dim = int(attrs.get("dim", 1))
    known = [s for s in in_shapes if s is not None]
    if not known:
        raise MXNetError("Concat: need at least one known shape")
    base = list(known[0])
    total = 0
    completed = []
    for s in in_shapes:
        if s is None:
            raise MXNetError("Concat: all input shapes required")
        total += s[dim]
        completed.append(tuple(s))
    out = list(base)
    out[dim] = total
    return completed, [tuple(out)], []


register(
    OpDef(
        "Concat",
        lambda attrs, ins, is_train: [
            jnp.concatenate(ins, axis=int(attrs.get("dim", 1)))
        ],
        arguments=("data",),
        key_var_num_args="num_args",
        defaults={"dim": 1, "num_args": 1},
        infer_shape=_concat_infer,
        aliases=("concat",),
    )
)


def _slice_channel(attrs, ins, is_train):
    n = int(attrs["num_outputs"])
    ax = int(attrs.get("axis", 1))
    parts = jnp.split(ins[0], n, axis=ax)
    if attrs.get("squeeze_axis"):
        parts = [jnp.squeeze(p, axis=ax) for p in parts]
    return parts


def _slice_channel_infer(attrs, in_shapes):
    n = int(attrs["num_outputs"])
    ax = int(attrs.get("axis", 1))
    s = list(in_shapes[0])
    if s[ax] % n != 0:
        raise MXNetError("SliceChannel: axis %d (%d) not divisible by %d" % (ax, s[ax], n))
    s[ax] //= n
    if attrs.get("squeeze_axis"):
        if s[ax] != 1:
            raise MXNetError("SliceChannel: squeeze_axis needs size-1 result")
        s = s[:ax] + s[ax + 1:]
    return [tuple(in_shapes[0])], [tuple(s)] * n, []


register(
    OpDef(
        "SliceChannel",
        _slice_channel,
        arguments=("data",),
        outputs=("output",),  # dynamic count via list_outputs override below
        defaults={"num_outputs": 1, "axis": 1, "squeeze_axis": False},
        infer_shape=_slice_channel_infer,
        aliases=("split",),
    )
)
def _slice_channel_outputs(attrs=None):
    n = int((attrs or {}).get("num_outputs", 1))
    return ["output%d" % i for i in range(n)]


from .registry import get as _get_op

_get_op("SliceChannel").list_outputs = _slice_channel_outputs


# --------------------------------------------------------------------------
# Pad (reference pad.cc) — NCHW/NCDHW edge/constant/reflect padding
# --------------------------------------------------------------------------
def _pad(attrs, ins, is_train):
    pw = as_tuple(attrs["pad_width"])
    mode = attrs.get("mode", "constant")
    pad_pairs = [(pw[2 * i], pw[2 * i + 1]) for i in range(len(pw) // 2)]
    if mode == "constant":
        return [
            jnp.pad(
                ins[0],
                pad_pairs,
                mode="constant",
                constant_values=float(attrs.get("constant_value", 0.0)),
            )
        ]
    jmode = {"edge": "edge", "reflect": "reflect"}[mode]
    return [jnp.pad(ins[0], pad_pairs, mode=jmode)]


def _pad_infer(attrs, in_shapes):
    pw = as_tuple(attrs["pad_width"])
    s = list(in_shapes[0])
    out = [d + pw[2 * i] + pw[2 * i + 1] for i, d in enumerate(s)]
    return [tuple(in_shapes[0])], [tuple(out)], []


register(
    OpDef(
        "Pad",
        _pad,
        arguments=("data",),
        defaults={"mode": "constant", "pad_width": (), "constant_value": 0.0},
        infer_shape=_pad_infer,
        aliases=("pad",),
    )
)


# --------------------------------------------------------------------------
# where (reference control_flow_op.cc)
# --------------------------------------------------------------------------
def _where_infer(attrs, in_shapes):
    cond, x, y = in_shapes
    shp = tuple(x if x is not None else y)
    return [tuple(cond) if cond else shp, shp, shp], [shp], []


register(
    OpDef(
        "where",
        lambda attrs, ins, is_train: [
            jnp.where(
                (ins[0] != 0)
                if ins[0].ndim == ins[1].ndim
                else (ins[0] != 0).reshape(
                    ins[0].shape + (1,) * (ins[1].ndim - ins[0].ndim)
                ),
                ins[1],
                ins[2],
            )
        ],
        arguments=("condition", "x", "y"),
        infer_shape=_where_infer,
    )
)
