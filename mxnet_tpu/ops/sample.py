"""Random sampling ops.

Parity: reference ``src/operator/tensor/sample_op.cc`` (uniform, normal,
gamma, exponential, poisson, negative_binomial, generalized_nb). The
reference draws from a per-device mshadow PRNG owned by the ResourceManager
(``src/resource.cc``); here each call gets a functional threefry key
(attrs["__rng__"]) split from the global seed stream in
:mod:`mxnet_tpu.random` — parity is distributional, not stream-exact
(SURVEY.md §7 "RNG parity").
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import np_dtype
from .registry import OpDef, register
from .utils import as_tuple


def _sample_infer(attrs, in_shapes):
    return [], [as_tuple(attrs.get("shape", (1,)))], []


def _sample_type(attrs, in_types):
    return [], [np_dtype(attrs.get("dtype", "float32"))], []


def _register_sampler(name, fn, defaults, aliases=()):
    def fcompute(attrs, ins, is_train, _fn=fn):
        key = attrs["__rng__"]
        shape = as_tuple(attrs.get("shape", (1,)))
        dt = np_dtype(attrs.get("dtype", "float32"))
        return [_fn(key, shape, attrs).astype(dt)]

    d = {"shape": (1,), "dtype": "float32"}
    d.update(defaults)
    register(
        OpDef(
            name,
            fcompute,
            arguments=(),
            defaults=d,
            infer_shape=_sample_infer,
            infer_type=_sample_type,
            needs_rng=True,
            aliases=aliases,
        )
    )


_register_sampler(
    "_sample_uniform",
    lambda key, shape, a: jax.random.uniform(
        key, shape, minval=float(a.get("low", 0.0)), maxval=float(a.get("high", 1.0))
    ),
    {"low": 0.0, "high": 1.0},
    aliases=("uniform", "_random_uniform"),
)
_register_sampler(
    "_sample_normal",
    lambda key, shape, a: jax.random.normal(key, shape) * float(a.get("scale", 1.0))
    + float(a.get("loc", 0.0)),
    {"loc": 0.0, "scale": 1.0},
    aliases=("normal", "_random_normal"),
)
_register_sampler(
    "_sample_gamma",
    lambda key, shape, a: jax.random.gamma(key, float(a.get("alpha", 1.0)), shape)
    * float(a.get("beta", 1.0)),
    {"alpha": 1.0, "beta": 1.0},
    aliases=("_random_gamma",),
)
_register_sampler(
    "_sample_exponential",
    lambda key, shape, a: jax.random.exponential(key, shape) / float(a.get("lam", 1.0)),
    {"lam": 1.0},
    aliases=("_random_exponential",),
)
_register_sampler(
    "_sample_poisson",
    lambda key, shape, a: jax.random.poisson(key, float(a.get("lam", 1.0)), shape).astype(
        jnp.float32
    ),
    {"lam": 1.0},
    aliases=("_random_poisson",),
)


def _neg_binomial(key, shape, a):
    k = float(a.get("k", 1.0))
    p = float(a.get("p", 1.0))
    # NB(k, p) == Poisson(Gamma(k, (1-p)/p))
    kg, kp = jax.random.split(key)
    lam = jax.random.gamma(kg, k, shape) * ((1.0 - p) / p)
    return jax.random.poisson(kp, lam, shape).astype(jnp.float32)


_register_sampler(
    "_sample_negbinomial",
    _neg_binomial,
    {"k": 1.0, "p": 1.0},
    aliases=("_random_negative_binomial",),
)


def _gen_neg_binomial(key, shape, a):
    mu = float(a.get("mu", 1.0))
    alpha = float(a.get("alpha", 1.0))
    kg, kp = jax.random.split(key)
    lam = jax.random.gamma(kg, 1.0 / alpha, shape) * (mu * alpha)
    return jax.random.poisson(kp, lam, shape).astype(jnp.float32)


_register_sampler(
    "_sample_gennegbinomial",
    _gen_neg_binomial,
    {"mu": 1.0, "alpha": 1.0},
    aliases=("_random_generalized_negative_binomial",),
)
