"""Operator registry.

This single registry replaces three reference subsystems at once:

- the NNVM ``Op`` registry with per-op attribute maps (reference vendored
  ``nnvm/``; attrs used by MXNet listed in SURVEY.md §2 N19),
- the legacy ``OperatorProperty`` registration (`MXNET_REGISTER_OP_PROPERTY`,
  reference ``include/mxnet/operator.h:166+`` bridged by
  ``src/nnvm/legacy_op_util.cc:304``),
- mshadow/cuDNN kernels (each op's ``fcompute`` is a pure JAX function that
  XLA fuses and schedules on the MXU/VPU).

An op is stateless and pure: ``fcompute(attrs, inputs, is_train)`` maps JAX
arrays to JAX arrays. Ops with auxiliary state (BatchNorm moving stats —
reference mutates them in forward via FMutateInputs) take aux arrays as
trailing inputs and return updated aux as trailing outputs; the executor and
imperative layers thread the state functionally.
"""
from __future__ import annotations

from ..base import MXNetError, parse_attr_value

_REGISTRY: dict[str, "OpDef"] = {}


class OpDef:
    """Metadata + compute for one operator."""

    def __init__(
        self,
        name,
        fcompute,
        arguments=("data",),
        outputs=("output",),
        aux=(),
        defaults=None,
        infer_shape=None,
        infer_type=None,
        backward_infer_shape=None,
        key_var_num_args=None,
        aliases=(),
        need_top_grad=True,
        visible=True,
        needs_rng=False,
        mutate_inputs=(),
        open_attrs=False,
    ):
        self.name = name
        self.fcompute = fcompute
        self._arguments = list(arguments)
        self._outputs = list(outputs)
        self._aux = list(aux)
        self.defaults = dict(defaults or {})
        self._infer_shape = infer_shape
        self._infer_type = infer_type
        # Optional reverse inference: (attrs, in_shapes, out_shapes) ->
        # refined in_shapes. The lightweight stand-in for nnvm's
        # bidirectional InferShape pass — needed where consumers determine
        # producers (RNN begin_state zeros with unknown batch).
        self.backward_infer_shape = backward_infer_shape
        # like NNVM's key_var_num_args: attr holding the variable input count
        # (Concat's num_args, add_n's num_args)
        self.key_var_num_args = key_var_num_args
        self.aliases = list(aliases)
        # False for loss/output ops whose backward ignores the head gradient
        # (reference SoftmaxOutput/MakeLoss semantics)
        self.need_top_grad = need_top_grad
        self.visible = visible
        # Ops needing randomness (samplers, Dropout) get a fresh PRNG key in
        # attrs["__rng__"]; JAX threefry replaces mshadow's global PRNG
        # (reference src/resource.cc kRandom) — functional keys instead of a
        # mutable engine-protected generator.
        self.needs_rng = needs_rng
        # Indices of inputs the reference op mutates in place (FMutateInputs:
        # sgd_mom_update's momentum). fcompute returns the updated values as
        # extra trailing outputs; the imperative layer writes them back.
        self.mutate_inputs = tuple(mutate_inputs)
        # ops forwarding arbitrary kwargs to user code (Custom): the
        # typo net cannot know their parameter space
        self.open_attrs = open_attrs

    # -- attr handling ------------------------------------------------------
    def canon_attrs(self, raw_attrs):
        """Parse string attrs and fill defaults (dmlc::Parameter equivalent)."""
        attrs = dict(self.defaults)
        for k, v in (raw_attrs or {}).items():
            if k.startswith("__"):  # __ctx_group__ etc. — graph-level attrs
                continue
            attrs[k] = parse_attr_value(v)
        return attrs

    # graph/scope attrs every op silently carries (AttrScope, placement,
    # display); never operator parameters
    _GENERIC_ATTRS = frozenset({"ctx_group", "lr_mult", "wd_mult",
                                "force_mirroring"})

    def known_attrs(self):
        """Over-approximate set of parameter names this op accepts:
        declared defaults ∪ every attrs.get("x")/attrs["x"] key in the
        fcompute/infer sources AND the same-module helpers they call
        (Convolution reads its dims inside _conv_dims) — the
        dmlc::Parameter field-list analog, recovered rather than
        declared. Used to flag typo'd kwargs. Returns None (cached) when
        any source is uninspectable."""
        cached = getattr(self, "_known_attrs", "unset")
        if cached != "unset":
            return cached or None  # False sentinel -> None
        import inspect
        import re

        keys = set(self.defaults) | self._GENERIC_ATTRS
        if self.key_var_num_args:
            keys.add(self.key_var_num_args)
        seen = set()
        queue = [fn for fn in (self.fcompute, self._infer_shape,
                               self._infer_type, self.backward_infer_shape)
                 if fn is not None]
        depth = 0
        while queue and depth < 64:
            fn = queue.pop()
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            depth += 1
            try:
                src = inspect.getsource(fn)
            except (OSError, TypeError):
                # builtins/lambda-in-repl: cannot introspect — accept all
                self._known_attrs = False
                return None
            keys.update(re.findall(
                r"""attrs\s*(?:\.get\(\s*|\[\s*)["']([A-Za-z_][\w]*)""",
                src))
            # follow helpers that are handed the attrs dict in ANY
            # argument position ("_conv_dims(attrs)", "_prep(w, g, attrs)")
            # so delegated reads count too
            for callee in re.findall(r"(\w+)\s*\([^()]*\battrs\b", src):
                target = getattr(fn, "__globals__", {}).get(callee)
                if inspect.isfunction(target):
                    queue.append(target)
        self._known_attrs = frozenset(keys)
        return self._known_attrs

    def check_call_attrs(self, attrs):
        """Warn on kwargs the op cannot possibly read — the typo net the
        reference gets from dmlc::Parameter's strict field parsing."""
        if self.open_attrs:
            return
        known = self.known_attrs()
        if known is None:
            return
        unknown = [k for k in attrs
                   if not k.startswith("__") and k not in known]
        if unknown:
            import warnings

            suggest = sorted(k for k in known
                             if not k.startswith("__")
                             and k not in self._GENERIC_ATTRS)
            warnings.warn(
                "%s: parameter(s) %s not recognized by this operator "
                "(typo?) — accepted: %s"
                % (self.name, sorted(unknown), suggest),
                stacklevel=4)

    def docstring(self):
        """Generated operator doc (parity: MXSymbolGetAtomicSymbolInfo's
        dmlc::Parameter docgen feeding the python op factories)."""
        lines = ["%s(%s, **params)" % (
            self.name, ", ".join(self._arguments)), ""]
        if self.defaults:
            lines.append("Parameters (with defaults):")
            for k in sorted(self.defaults):
                lines.append("    %s = %r" % (k, self.defaults[k]))
        if self._aux:
            lines.append("Auxiliary states: %s" % ", ".join(self._aux))
        if self.aliases:
            lines.append("Aliases: %s" % ", ".join(self.aliases))
        lines.append("")
        lines.append("Auto-generated from the operator registry "
                     "(see mxnet_tpu/ops).")
        return "\n".join(lines)

    # -- arity --------------------------------------------------------------
    def num_inputs(self, attrs):
        if self.key_var_num_args is not None:
            n = attrs.get(self.key_var_num_args)
            if n is None:
                raise MXNetError(
                    "%s requires attr %s" % (self.name, self.key_var_num_args)
                )
            return int(n)
        return len(self._arguments)

    def list_arguments(self, attrs=None):
        if self.key_var_num_args is not None and attrs is not None:
            n = int(attrs.get(self.key_var_num_args, 1))
            return ["arg%d" % i for i in range(n)]
        return list(self._arguments)

    def list_outputs(self, attrs=None):
        return list(self._outputs)

    def num_visible_outputs(self, attrs=None):
        """Outputs visible to Symbol composition (reference
        OperatorProperty::NumVisibleOutputs — BatchNorm exposes 1 of 3)."""
        if getattr(self, "_num_visible_outputs", None) is not None:
            return self._num_visible_outputs
        return len(self.list_outputs(attrs))

    def list_auxiliary_states(self, attrs=None):
        return list(self._aux)

    # -- inference ----------------------------------------------------------
    def infer_shape(self, attrs, in_shapes):
        """(in_shapes with Nones) -> (completed in, out, aux shapes)."""
        if self._infer_shape is not None:
            return self._infer_shape(attrs, in_shapes)
        # default: all inputs/outputs share one (dim-merged) shape
        from .utils import merge_shapes

        merged = None
        for s in in_shapes:
            merged = merge_shapes(merged, s, self.name)
        if merged is None:
            raise MXNetError("%s: cannot infer shape, no known inputs" % self.name)
        return (
            [merged] * len(in_shapes),
            [merged] * len(self._outputs),
            [],
        )

    def infer_type(self, attrs, in_types):
        import numpy as np

        if self._infer_type is not None:
            return self._infer_type(attrs, in_types)
        known = [t for t in in_types if t is not None]
        if not known:
            raise MXNetError("%s: cannot infer type" % self.name)
        t = known[0]
        completed = [t if x is None else x for x in in_types]
        return completed, [t] * len(self._outputs), [np.float32] * len(self._aux)

    def __repr__(self):
        return "OpDef(%s)" % self.name


def register(opdef: OpDef):
    for name in [opdef.name] + opdef.aliases:
        if name in _REGISTRY:
            raise MXNetError("op %s already registered" % name)
        _REGISTRY[name] = opdef
    return opdef


def register_op(name, fcompute, **kwargs):
    return register(OpDef(name, fcompute, **kwargs))


def get(name) -> OpDef:
    op = _REGISTRY.get(name)
    if op is None:
        raise MXNetError("operator %s is not registered" % name)
    return op


def exists(name) -> bool:
    return name in _REGISTRY


def list_ops():
    return sorted(_REGISTRY)


def primary_ops():
    """Unique OpDefs (no alias duplicates)."""
    seen, out = set(), []
    for op in _REGISTRY.values():
        if id(op) not in seen:
            seen.add(id(op))
            out.append(op)
    return out
