"""Spatial-transform and matching operators.

Parity: the reference's spatial family (SURVEY.md §2 N6):
GridGenerator (``src/operator/grid_generator-inl.h``), BilinearSampler
(``src/operator/bilinear_sampler-inl.h``), SpatialTransformer
(``src/operator/spatial_transformer-inl.h``), Correlation
(``src/operator/correlation-inl.h``), and IdentityAttachKLSparseReg
(``src/operator/identity_attach_KL_sparse_reg-inl.h``).

TPU-native notes:
- The reference implements bilinear sampling with hand-written CUDA gather
  kernels (plus cuDNN SpatialTransformer); here the sampler is written as
  differentiable gathers + interpolation weights so jax.grad produces both
  the data and the grid gradients that the reference codes by hand
  (``bilinear_sampler-inl.h`` backward) — no custom kernels needed, XLA
  fuses the four corner gathers.
- Correlation (FlowNet) is expressed as a static loop over the (small)
  displacement neighbourhood with an XLA ``reduce_window`` box filter per
  displacement — each displacement is one fused multiply+window-sum, which
  maps to the VPU far better than the reference's per-output-pixel CUDA
  loop (``correlation-inl.h`` CorrelateData kernel).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .registry import OpDef, register
from .utils import as_tuple


# ---------------------------------------------------------------------------
# bilinear sampling core (shared by BilinearSampler / SpatialTransformer)
# ---------------------------------------------------------------------------

def _bilinear_sample(data, grid):
    """Sample ``data`` [B,C,H,W] at normalized ``grid`` [B,2,Ho,Wo].

    grid channel 0 = x in [-1,1], channel 1 = y in [-1,1] (reference
    convention, ``bilinear_sampler-inl.h``: x_real = (x+1)*(W-1)/2).
    Out-of-bounds reads contribute 0 (reference zero-padding semantics).
    """
    _, _, h, w = data.shape
    gx = (grid[:, 0] + 1.0) * (w - 1) / 2.0  # [B,Ho,Wo]
    gy = (grid[:, 1] + 1.0) * (h - 1) / 2.0
    x0 = jnp.floor(gx)
    y0 = jnp.floor(gy)
    wx1 = gx - x0
    wy1 = gy - y0
    wx0 = 1.0 - wx1
    wy0 = 1.0 - wy1

    def corner(y, x):
        yi = jnp.clip(y, 0, h - 1).astype(jnp.int32)
        xi = jnp.clip(x, 0, w - 1).astype(jnp.int32)
        valid = ((y >= 0) & (y <= h - 1) & (x >= 0) & (x <= w - 1))
        vals = jax.vmap(lambda d, yy, xx: d[:, yy, xx])(data, yi, xi)
        return vals * valid[:, None].astype(data.dtype)

    out = (
        corner(y0, x0) * (wy0 * wx0)[:, None]
        + corner(y0, x0 + 1) * (wy0 * wx1)[:, None]
        + corner(y0 + 1, x0) * (wy1 * wx0)[:, None]
        + corner(y0 + 1, x0 + 1) * (wy1 * wx1)[:, None]
    )
    return out.astype(data.dtype)


def _affine_grid(theta, target_shape):
    """theta [B,6] affine params -> normalized grid [B,2,H,W].

    Reference ``grid_generator-inl.h`` builds grid_dst rows (x, y, 1) with
    x,y in [-1,1] and computes theta([B,2,3]) @ grid_dst([3,HW]).
    """
    h, w = target_shape
    if h <= 0 or w <= 0:
        raise MXNetError(
            "target_shape is required and must be positive, got %s"
            % (target_shape,)
        )
    b = theta.shape[0]
    xs = jnp.linspace(-1.0, 1.0, w) if w > 1 else jnp.zeros((1,))
    ys = jnp.linspace(-1.0, 1.0, h) if h > 1 else jnp.zeros((1,))
    gx, gy = jnp.meshgrid(xs, ys)  # [H,W]
    ones = jnp.ones_like(gx)
    src = jnp.stack([gx, gy, ones], axis=0).reshape(3, h * w)  # [3,HW]
    mat = theta.reshape(b, 2, 3)
    grid = jnp.einsum("bij,jk->bik", mat, src.astype(theta.dtype))
    return grid.reshape(b, 2, h, w)


# ---------------------------------------------------------------------------
# GridGenerator
# ---------------------------------------------------------------------------

def _grid_generator(attrs, ins, is_train):
    ttype = attrs.get("transform_type", "affine")
    if ttype == "affine":
        target = as_tuple(attrs["target_shape"], 2, "target_shape")
        return [_affine_grid(ins[0], target).astype(ins[0].dtype)]
    if ttype == "warp":
        flow = ins[0]  # [B,2,H,W] pixel offsets
        _, _, h, w = flow.shape
        xs = jnp.arange(w, dtype=flow.dtype)
        ys = jnp.arange(h, dtype=flow.dtype)
        gx = (flow[:, 0] + xs[None, None, :]) * (2.0 / max(w - 1, 1)) - 1.0
        gy = (flow[:, 1] + ys[None, :, None]) * (2.0 / max(h - 1, 1)) - 1.0
        return [jnp.stack([gx, gy], axis=1)]
    raise MXNetError("GridGenerator: unknown transform_type %s" % ttype)


def _grid_generator_infer(attrs, in_shapes):
    ttype = attrs.get("transform_type", "affine")
    dshape = in_shapes[0]
    if dshape is None:
        raise MXNetError("GridGenerator: input shape required")
    if ttype == "affine":
        target = as_tuple(attrs["target_shape"], 2, "target_shape")
        if len(dshape) != 2 or (dshape[1] not in (0, 6)):
            raise MXNetError(
                "GridGenerator(affine): data must be [batch, 6], got %s" % (dshape,)
            )
        return [(dshape[0], 6)], [(dshape[0], 2) + target], []
    if len(dshape) != 4 or dshape[1] not in (0, 2):
        raise MXNetError(
            "GridGenerator(warp): data must be [batch,2,H,W], got %s" % (dshape,)
        )
    full = (dshape[0], 2, dshape[2], dshape[3])
    return [full], [full], []


register(
    OpDef(
        "GridGenerator",
        _grid_generator,
        arguments=("data",),
        defaults={"transform_type": "affine", "target_shape": (0, 0)},
        infer_shape=_grid_generator_infer,
    )
)


# ---------------------------------------------------------------------------
# BilinearSampler
# ---------------------------------------------------------------------------

def _bilinear_sampler_infer(attrs, in_shapes):
    dshape, gshape = in_shapes
    if dshape is None or gshape is None:
        raise MXNetError("BilinearSampler: data and grid shapes required")
    if len(dshape) != 4 or len(gshape) != 4:
        raise MXNetError("BilinearSampler: data/grid must be 4D")
    out = (dshape[0], dshape[1], gshape[2], gshape[3])
    return [tuple(dshape), (dshape[0], 2, gshape[2], gshape[3])], [out], []


register(
    OpDef(
        "BilinearSampler",
        lambda attrs, ins, is_train: [_bilinear_sample(ins[0], ins[1])],
        arguments=("data", "grid"),
        infer_shape=_bilinear_sampler_infer,
    )
)


# ---------------------------------------------------------------------------
# SpatialTransformer (= affine GridGenerator + BilinearSampler, the
# reference's cuDNN-backed fused version)
# ---------------------------------------------------------------------------

def _spatial_transformer(attrs, ins, is_train):
    if attrs.get("transform_type", "affine") != "affine":
        raise MXNetError("SpatialTransformer: only affine supported (as reference)")
    if attrs.get("sampler_type", "bilinear") != "bilinear":
        raise MXNetError("SpatialTransformer: only bilinear supported (as reference)")
    data, loc = ins
    target = as_tuple(attrs["target_shape"], 2, "target_shape")
    grid = _affine_grid(loc, target)
    return [_bilinear_sample(data, grid.astype(data.dtype))]


def _spatial_transformer_infer(attrs, in_shapes):
    dshape = in_shapes[0]
    if dshape is None:
        raise MXNetError("SpatialTransformer: data shape required")
    target = as_tuple(attrs["target_shape"], 2, "target_shape")
    out = (dshape[0], dshape[1]) + target
    return [tuple(dshape), (dshape[0], 6)], [out], []


register(
    OpDef(
        "SpatialTransformer",
        _spatial_transformer,
        arguments=("data", "loc"),
        defaults={
            "transform_type": "affine",
            "sampler_type": "bilinear",
            "target_shape": (0, 0),
        },
        infer_shape=_spatial_transformer_infer,
    )
)


# ---------------------------------------------------------------------------
# Correlation (FlowNet cost volume)
# ---------------------------------------------------------------------------

def _corr_dims(attrs, dshape):
    k = int(attrs.get("kernel_size", 1))
    md = int(attrs.get("max_displacement", 1))
    s1 = int(attrs.get("stride1", 1))
    s2 = int(attrs.get("stride2", 1))
    pad = int(attrs.get("pad_size", 0))
    kr = (k - 1) // 2
    border = md + kr
    ph, pw = dshape[2] + 2 * pad, dshape[3] + 2 * pad
    top_h = int(math.ceil((ph - 2 * border) / float(s1)))
    top_w = int(math.ceil((pw - 2 * border) / float(s1)))
    if top_h <= 0 or top_w <= 0:
        raise MXNetError("Correlation: output size would be empty")
    radius = md // s2
    ngrid = 2 * radius + 1
    return k, md, s1, s2, pad, kr, top_h, top_w, radius, ngrid


def _correlation(attrs, ins, is_train):
    d1, d2 = ins
    k, md, s1, s2, pad, kr, top_h, top_w, radius, ngrid = _corr_dims(attrs, d1.shape)
    is_multiply = bool(attrs.get("is_multiply", True))
    c = d1.shape[1]
    # pad an extra kernel length so every displacement window slice below is
    # statically in-bounds regardless of k parity
    extra = k
    cfg = [(0, 0), (0, 0), (pad, pad + extra), (pad, pad + extra)]
    acc_t = jnp.promote_types(d1.dtype, jnp.float32)
    p1 = jnp.pad(d1.astype(acc_t), cfg)
    p2 = jnp.pad(d2.astype(acc_t), cfg)
    span_h = (top_h - 1) * s1 + k
    span_w = (top_w - 1) * s1 + k
    a = p1[:, :, md : md + span_h, md : md + span_w]
    norm = float(k * k * c)
    maps = []
    for dy in range(-radius, radius + 1):
        for dx in range(-radius, radius + 1):
            sh, sw = dy * s2, dx * s2
            b = p2[:, :, md + sh : md + sh + span_h, md + sw : md + sw + span_w]
            term = a * b if is_multiply else jnp.abs(a - b)
            term = jnp.sum(term, axis=1, keepdims=True)  # over channels
            box = jax.lax.reduce_window(
                term, 0.0, jax.lax.add,
                (1, 1, k, k), (1, 1, s1, s1), "valid",
            )
            maps.append(box[:, 0] / norm)
    out = jnp.stack(maps, axis=1)  # [B, ngrid^2, top_h, top_w]
    return [out.astype(d1.dtype)]


def _correlation_infer(attrs, in_shapes):
    dshape = in_shapes[0] or in_shapes[1]
    if dshape is None:
        raise MXNetError("Correlation: input shape required")
    _, _, _, _, _, _, top_h, top_w, _, ngrid = _corr_dims(attrs, dshape)
    out = (dshape[0], ngrid * ngrid, top_h, top_w)
    return [tuple(dshape), tuple(dshape)], [out], []


register(
    OpDef(
        "Correlation",
        _correlation,
        arguments=("data1", "data2"),
        defaults={
            "kernel_size": 1,
            "max_displacement": 1,
            "stride1": 1,
            "stride2": 1,
            "pad_size": 0,
            "is_multiply": True,
        },
        infer_shape=_correlation_infer,
    )
)


# ---------------------------------------------------------------------------
# IdentityAttachKLSparseReg
# ---------------------------------------------------------------------------

def _kl_sparse_fcompute(attrs, ins, is_train):
    data, moving_avg = ins
    momentum = float(attrs.get("momentum", 0.9))
    penalty = float(attrs.get("penalty", 0.001))
    rho = float(attrs.get("sparseness_target", 0.1))

    if is_train:
        axes = tuple(i for i in range(data.ndim) if i != 1)
        rho_hat = jnp.mean(data, axis=axes)
        new_avg = momentum * moving_avg + (1.0 - momentum) * rho_hat
    else:
        new_avg = moving_avg

    @jax.custom_vjp
    def _identity_with_kl(x, avg):
        return x

    def _fwd(x, avg):
        return x, avg

    def _bwd(avg, g):
        # reference backward: grad += penalty * (-rho/rho_hat + (1-rho)/(1-rho_hat))
        eps = 1e-8
        kl_grad = penalty * (
            -rho / (avg + eps) + (1.0 - rho) / (1.0 - avg + eps)
        )
        if g.ndim > 1:
            bshape = [1] * g.ndim
            bshape[1] = g.shape[1]
            kl_grad = kl_grad.reshape(bshape)
        kl_grad = kl_grad.astype(g.dtype)
        return (g + kl_grad, jnp.zeros_like(avg))

    _identity_with_kl.defvjp(_fwd, _bwd)
    out = _identity_with_kl(data, new_avg)
    return [out, new_avg]


def _kl_sparse_infer(attrs, in_shapes):
    dshape = in_shapes[0]
    if dshape is None:
        raise MXNetError("IdentityAttachKLSparseReg: data shape required")
    c = dshape[1] if len(dshape) > 1 else dshape[0]
    return [tuple(dshape)], [tuple(dshape)], [(c,)]


register(
    OpDef(
        "IdentityAttachKLSparseReg",
        _kl_sparse_fcompute,
        arguments=("data",),
        aux=("moving_avg",),
        defaults={"momentum": 0.9, "penalty": 0.001, "sparseness_target": 0.1},
        infer_shape=_kl_sparse_infer,
    )
)
