"""Pallas TPU kernels for the hot ops.

The reference reaches for hand-written CUDA / cuDNN where the stock ops
are too slow (SURVEY.md §2 N6 cudnn_*-inl.h, N18 mshadow). The TPU-native
equivalent is Pallas: kernels that XLA cannot produce from jnp alone
because they need explicit on-chip (VMEM) accumulation patterns. The
flagship here is flash attention — blockwise online-softmax attention
whose VMEM working set is O(block²+block·D) per grid step (the K/V axis
is walked by the innermost grid dimension, not loaded whole), forward and
backward both as MXU-tiled kernels.

Everything degrades gracefully off-TPU: ``interpret=True`` runs the same
kernels through the Pallas interpreter (tests), and callers can always
use the pure-jnp reference path (``reference_attention``).

Layout convention matches ``parallel/ring_attention``: [B, T, H, D].
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _use_interpret():
    return jax.default_backend() != "tpu"


def _no_x64():
    """Context manager forcing 32-bit tracing: the framework enables
    jax_enable_x64 globally (reference float64 NDArray parity) but
    Mosaic kernels must stay 32-bit. `jax.enable_x64` was removed in
    jax 0.4.x; `jax.experimental.disable_x64` is the stable spelling."""
    try:
        return jax.experimental.disable_x64()
    except AttributeError:  # pragma: no cover — future jax renames
        import contextlib

        return contextlib.nullcontext()


def fused_update_enabled():
    """Whether the fused optimizer-slab kernel replaces the jnp update
    chain. ``MXTPU_FUSED_UPDATE_KERNEL``: "1" forces it on everywhere
    (interpret mode off-TPU — the parity tests), "0" forces the jnp
    reference, unset enables it on TPU only."""
    v = os.environ.get("MXTPU_FUSED_UPDATE_KERNEL", "")
    if v == "0":
        return False
    if v == "1":
        return True
    return jax.default_backend() == "tpu"


def _pad_to(x, axis, mult):
    size = x.shape[axis]
    rem = size % mult
    if rem == 0:
        return x, size
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, mult - rem)
    return jnp.pad(x, pad), size


# ---------------------------------------------------------------------------
# forward kernel: grid (B*H, nq, nk), k innermost. The output block index
# map ignores the k dimension, so Mosaic keeps o_ref resident in VMEM
# while the k loop accumulates into scratch; only one (block_q, block_k)
# tile of each operand is on-chip at a time.
# ---------------------------------------------------------------------------

def _causal_block_live(qi, ki, block_q, block_k):
    """Whether k block ki intersects the causal triangle of q block qi."""
    return ki * jnp.int32(block_k) <= qi * jnp.int32(block_q) + jnp.int32(
        block_q - 1
    )


def _masked_scores(q, k_blk, qi, ki, *, block_q, block_k, t_real, scale,
                   causal):
    """The shared score/mask invariant of all three kernels:
    s = scale·q@kᵀ on the MXU plus the (padding, causal) keep-mask for
    this (qi, ki) block pair. Kept in ONE place so forward and backward
    can never disagree on masking."""
    s = jnp.float32(scale) * jax.lax.dot_general(
        q, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [bq, bk]
    q_pos = qi * jnp.int32(block_q) + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = ki * jnp.int32(block_k) + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    mask = k_pos < jnp.int32(t_real)
    if causal:
        mask = mask & (q_pos >= k_pos)
    return s, mask


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, l_ref, acc, m_s, l_s,
                *, block_q, block_k, t_real, scale, causal):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, jnp.float32(_NEG_INF))
        l_s[:] = jnp.zeros_like(l_s)

    live = True
    if causal:
        live = _causal_block_live(qi, ki, block_q, block_k)

    @pl.when(live)
    def _():
        q = q_ref[0].astype(jnp.float32)  # [bq, D]
        k_blk = k_ref[0].astype(jnp.float32)  # [bk, D]
        v_blk = v_ref[0].astype(jnp.float32)
        s, mask = _masked_scores(
            q, k_blk, qi, ki, block_q=block_q, block_k=block_k,
            t_real=t_real, scale=scale, causal=causal)
        s = jnp.where(mask, s, jnp.float32(_NEG_INF))

        m_prev = m_s[:, 0]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[:, None])
        l_s[:, 0] = l_s[:, 0] * alpha + jnp.sum(p, axis=1)
        m_s[:, 0] = m_cur
        acc[:] = acc[:] * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _():
        l_fin = l_s[:, 0]
        safe_l = jnp.where(l_fin > 0, l_fin, jnp.float32(1.0))
        o_ref[0] = (acc[:] / safe_l[:, None]).astype(o_ref.dtype)
        # logsumexp residual for backward
        l_ref[0, :, 0] = (m_s[:, 0] + jnp.log(safe_l)).astype(jnp.float32)


# ---------------------------------------------------------------------------
# backward kernels. dq: grid (bh, nq, nk); dkv: grid (bh, nk, nq).
# dS = P * (dP - delta), P = exp(S - L), dP = dO V^T,
# delta_i = sum_d dO_id * O_id.
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, l_ref, d_ref, dq_ref,
                   dq_acc, *, block_q, block_k, t_real, scale, causal):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    live = True
    if causal:
        live = _causal_block_live(qi, ki, block_q, block_k)

    @pl.when(live)
    def _():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = l_ref[0, :, 0]
        delta = d_ref[0, :, 0]
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        s, mask = _masked_scores(
            q, k_blk, qi, ki, block_q=block_q, block_k=block_k,
            t_real=t_real, scale=scale, causal=causal)
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), jnp.float32(0.0))
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None])
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[0] = (jnp.float32(scale) * dq_acc[:]).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, l_ref, d_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, block_q, block_k,
                    t_real, scale, causal):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    live = True
    if causal:
        live = _causal_block_live(qi, ki, block_q, block_k)

    @pl.when(live)
    def _():
        k_blk = k_ref[0].astype(jnp.float32)  # [bk, D]
        v_blk = v_ref[0].astype(jnp.float32)
        q = q_ref[0].astype(jnp.float32)  # [bq, D]
        do = do_ref[0].astype(jnp.float32)
        lse = l_ref[0, :, 0]
        delta = d_ref[0, :, 0]
        s, mask = _masked_scores(
            q, k_blk, qi, ki, block_q=block_q, block_k=block_k,
            t_real=t_real, scale=scale, causal=causal)
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), jnp.float32(0.0))
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bk, D]
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None])
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[0] = (jnp.float32(scale) * dk_acc[:]).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# host-side wrappers
# ---------------------------------------------------------------------------

def _fwd_call(q3, k3, v3, t_real, scale, causal, block_q, block_k,
              interpret):
    bh, t_pad, d = q3.shape
    nq = t_pad // block_q
    nk = t_pad // block_k
    kern = functools.partial(
        _fwd_kernel, block_q=block_q, block_k=block_k, t_real=t_real,
        scale=scale, causal=causal,
    )
    with _no_x64():
        out, lse = pl.pallas_call(
            kern,
            grid=(bh, nq, nk),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, t_pad, d), q3.dtype),
                jax.ShapeDtypeStruct((bh, t_pad, 1), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, d), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
            ],
            interpret=interpret,
        )(q3, k3, v3)
    return out, lse


def _bwd_call(q3, k3, v3, do3, lse, delta, t_real, scale, causal,
              block_q, block_k, interpret):
    bh, t_pad, d = q3.shape
    nq = t_pad // block_q
    nk = t_pad // block_k
    with _no_x64():
        dq = pl.pallas_call(
            functools.partial(
                _bwd_dq_kernel, block_q=block_q, block_k=block_k,
                t_real=t_real, scale=scale, causal=causal,
            ),
            grid=(bh, nq, nk),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, block_q, d), lambda b, i, j: (b, i, 0)
            ),
            out_shape=jax.ShapeDtypeStruct((bh, t_pad, d), q3.dtype),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
            interpret=interpret,
        )(q3, k3, v3, do3, lse, delta)
        dk, dv = pl.pallas_call(
            functools.partial(
                _bwd_dkv_kernel, block_q=block_q, block_k=block_k,
                t_real=t_real, scale=scale, causal=causal,
            ),
            grid=(bh, nk, nq),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, t_pad, d), q3.dtype),
                jax.ShapeDtypeStruct((bh, t_pad, d), q3.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, d), jnp.float32),
                pltpu.VMEM((block_k, d), jnp.float32),
            ],
            interpret=interpret,
        )(q3, k3, v3, do3, lse, delta)
    return dq, dk, dv


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def _flash(q3, k3, v3, t_real, scale, causal, block_q, block_k):
    interp = _use_interpret()
    out, _ = _fwd_call(q3, k3, v3, t_real, scale, causal, block_q,
                       block_k, interp)
    return out


def _flash_fwd(q3, k3, v3, t_real, scale, causal, block_q, block_k):
    interp = _use_interpret()
    out, lse = _fwd_call(q3, k3, v3, t_real, scale, causal, block_q,
                         block_k, interp)
    return out, (q3, k3, v3, out, lse)


def _flash_bwd(t_real, scale, causal, block_q, block_k, res, g):
    q3, k3, v3, out, lse = res
    interp = _use_interpret()
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1,
        keepdims=True,
    )  # [BH, T, 1]
    dq, dk, dv = _bwd_call(
        q3, k3, v3, g.astype(q3.dtype), lse, delta, t_real, scale,
        causal, block_q, block_k, interp,
    )
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, scale=None, block_q=128,
                    block_k=128):
    """Blockwise (flash) attention. q/k/v: [B, T, H, D] -> [B, T, H, D].

    Pallas MXU kernels on TPU; the same kernels run under the Pallas
    interpreter elsewhere so tests don't need hardware. The TPU-native
    replacement for what the reference delegates to cuDNN fused kernels
    (cudnn_rnn-inl.h being the closest 2017 analog of a fused
    sequence kernel).

    NOTE: pallas_call has no GSPMD partitioning rules — inside pjit over a
    sharded mesh, wrap calls in shard_map (see parallel/ring_attention for
    the sp-sharded composition) or keep attention inputs replicated.
    """
    b, t, h, d = q.shape
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))
    blk = min(block_q, block_k)
    if t < blk:
        block_q = block_k = max(8, 1 << (t - 1).bit_length())
    q3 = q.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    k3 = k.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    v3 = v.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    mult = int(np.lcm(block_q, block_k))
    q3, _ = _pad_to(q3, 1, mult)
    k3, _ = _pad_to(k3, 1, mult)
    v3, _ = _pad_to(v3, 1, mult)
    out = _flash(q3, k3, v3, t, float(scale), bool(causal), int(block_q),
                 int(block_k))
    out = out[:, :t]
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def attention(q, k, v, causal=False, scale=None, mesh=None):
    """Shared attention dispatch for every model that wants fused
    attention without hand-picking a kernel: sequence-parallel ring
    attention when the mesh shards the sequence axis, the Pallas flash
    kernel when it pays (TPU and T >= 128, or forced via
    ``MXNET_TPU_FORCE_FLASH=1``), the materialized reference otherwise.
    q/k/v: [B, T, H, D] -> [B, T, H, D]."""
    t = q.shape[1]
    if mesh is not None and mesh.shape.get("sp", 1) > 1:
        from ..parallel.ring_attention import sequence_parallel_attention

        return sequence_parallel_attention(q, k, v, mesh, causal=causal)
    force = os.environ.get("MXNET_TPU_FORCE_FLASH") == "1"
    on_tpu = jax.default_backend() == "tpu"
    if mesh is None and (force or (on_tpu and t >= 128)):
        return flash_attention(q, k, v, causal=causal, scale=scale)
    return reference_attention(q, k, v, causal=causal, scale=scale)


# ---------------------------------------------------------------------------
# fused optimizer-slab kernel (AMP update path, parallel/train_step.py).
#
# The flat sharded update applies one elementwise optimizer step to a 1/N
# contiguous slab of the flattened parameter space. Under AMP that step
# is a chain of ~10 elementwise HLOs (unscale, clip, wd, state math,
# finite-select, bf16 cast-out) each of which round-trips the slab
# through HBM. The kernel below runs the whole chain in one VMEM pass:
# each grid step streams a (block_rows, 128) tile of every operand in,
# does the full update in registers, and writes new master weight, new
# state, and the bf16 weight copy out.
#
# The jnp path (`slab_update_reference`) and the kernel share
# `_slab_update_math`, so kernel-vs-reference parity reduces to the
# pallas_call plumbing (tiling, padding, SMEM scalars) — which is what
# the interpret-mode tests pin across 1/2/4/8 simulated devices.
# ---------------------------------------------------------------------------

_SLAB_LANES = 128
_SLAB_STATE_SLOTS = {"sgd": 0, "sgd_mom": 1, "adam": 2}


def _slab_update_math(kind, w, g, states, lr, inv_scale, finite, *, wd,
                      rescale_grad, clip_gradient, momentum, beta1, beta2,
                      epsilon):
    """One AMP optimizer step on a slab, mirroring optimizer_ops.py
    (`_prep_grad` + sgd/sgd_mom/adam update) with the AMP extras: grad
    unscale up front, branchless finite-select at the end, bf16 weight
    copy out. All math in f32 regardless of grad dtype."""
    w = w.astype(jnp.float32)
    g = g.astype(jnp.float32) * inv_scale
    if rescale_grad != 1.0:
        g = g * jnp.float32(rescale_grad)
    if clip_gradient is not None and clip_gradient > 0:
        g = jnp.clip(g, -jnp.float32(clip_gradient),
                     jnp.float32(clip_gradient))
    if wd != 0.0:
        g = g + jnp.float32(wd) * w
    if kind == "sgd":
        new_w = w - lr * g
        new_states = ()
    elif kind == "sgd_mom":
        mom = states[0].astype(jnp.float32)
        new_mom = jnp.float32(momentum) * mom - lr * g
        new_w = w + new_mom
        new_states = (new_mom,)
    elif kind == "adam":
        mean = states[0].astype(jnp.float32)
        var = states[1].astype(jnp.float32)
        new_mean = beta1 * mean + (1.0 - beta1) * g
        new_var = beta2 * var + (1.0 - beta2) * jnp.square(g)
        new_w = w - lr * new_mean / (jnp.sqrt(new_var) + epsilon)
        new_states = (new_mean, new_var)
    else:
        raise ValueError("unknown slab kind %r" % (kind,))
    keep = finite > jnp.float32(0.5)
    new_w = jnp.where(keep, new_w, w)
    new_states = tuple(jnp.where(keep, ns, os_.astype(jnp.float32))
                       for ns, os_ in zip(new_states, states))
    return new_w, new_states, new_w.astype(jnp.bfloat16)


def _slab_kernel(kind, n_state, scalar_ref, w_ref, g_ref, *refs, wd,
                 rescale_grad, clip_gradient, momentum, beta1, beta2,
                 epsilon):
    state_refs = refs[:n_state]
    out_w_ref = refs[n_state]
    out_state_refs = refs[n_state + 1:2 * n_state + 1]
    out_w16_ref = refs[2 * n_state + 1]
    lr = scalar_ref[0, 0]
    inv_scale = scalar_ref[0, 1]
    finite = scalar_ref[0, 2]
    new_w, new_states, w16 = _slab_update_math(
        kind, w_ref[...], g_ref[...],
        tuple(r[...] for r in state_refs), lr, inv_scale, finite,
        wd=wd, rescale_grad=rescale_grad, clip_gradient=clip_gradient,
        momentum=momentum, beta1=beta1, beta2=beta2, epsilon=epsilon)
    out_w_ref[...] = new_w
    for r, ns in zip(out_state_refs, new_states):
        r[...] = ns
    out_w16_ref[...] = w16


def _slab_pad_2d(x, rows, block_rows):
    """(S,) -> (rows_padded, 128), zero-filled."""
    x2 = jnp.pad(x, (0, rows * _SLAB_LANES - x.shape[0])).reshape(
        rows, _SLAB_LANES)
    if rows % block_rows:
        x2 = jnp.pad(x2, ((0, block_rows - rows % block_rows), (0, 0)))
    return x2


def slab_update_reference(kind, w, g, states, lr, inv_scale, finite, *,
                          wd, rescale_grad, clip_gradient, momentum=0.0,
                          beta1=0.9, beta2=0.999, epsilon=1e-8):
    """The pure-jnp slab update (the XLA path and the kernel's oracle)."""
    new_w, new_states, w16 = _slab_update_math(
        kind, w, g, states, jnp.asarray(lr, jnp.float32),
        jnp.asarray(inv_scale, jnp.float32),
        jnp.asarray(finite, jnp.float32), wd=wd, rescale_grad=rescale_grad,
        clip_gradient=clip_gradient, momentum=momentum, beta1=beta1,
        beta2=beta2, epsilon=epsilon)
    return new_w, new_states, w16


def fused_slab_update(kind, w, g, states, lr, inv_scale, finite, *, wd,
                      rescale_grad, clip_gradient, momentum=0.0, beta1=0.9,
                      beta2=0.999, epsilon=1e-8, interpret=None):
    """AMP optimizer step over a flat slab in one Pallas VMEM pass.

    w: (S,) f32 master shard; g: (S,) grad shard (bf16 under AMP);
    states: tuple of (S,) f32 state slabs (len per `kind`); lr /
    inv_scale / finite: traced f32 scalars (finite: 1.0 = apply,
    0.0 = skip bitwise-cleanly). Static hyperparameters are baked into
    the kernel. Returns (new_w f32, new_states tuple, w16 bf16), each
    (S,).
    """
    n_state = _SLAB_STATE_SLOTS[kind]
    assert len(states) == n_state, (kind, len(states))
    s = w.shape[0]
    rows = -(-s // _SLAB_LANES)
    block_rows = 256 if rows >= 256 else (-(-rows // 16) * 16)
    if interpret is None:
        interpret = _use_interpret()
    kern = functools.partial(
        _slab_kernel, kind, n_state, wd=float(wd),
        rescale_grad=float(rescale_grad),
        clip_gradient=float(clip_gradient) if clip_gradient else -1.0,
        momentum=float(momentum), beta1=float(beta1), beta2=float(beta2),
        epsilon=float(epsilon))
    # pads/stacks stay OUTSIDE the 32-bit context: under the global
    # jax_enable_x64 an outer trace caches their lowered subfunctions
    # with i64 scalar operands, and re-tracing them under disable_x64
    # emits i32 signatures for the same cache key — mixed-width
    # func.call verifier errors. Only the pallas_call itself (whose
    # Mosaic grid indexing must be 32-bit) runs under _no_x64.
    scalars = jnp.stack([
        jnp.asarray(lr, jnp.float32),
        jnp.asarray(inv_scale, jnp.float32),
        jnp.asarray(finite, jnp.float32)]).reshape(1, 3)
    w2 = _slab_pad_2d(w.astype(jnp.float32), rows, block_rows)
    g2 = _slab_pad_2d(g, rows, block_rows)
    st2 = [_slab_pad_2d(st.astype(jnp.float32), rows, block_rows)
           for st in states]
    rp = w2.shape[0]
    grid = (rp // block_rows,)
    blk = pl.BlockSpec((block_rows, _SLAB_LANES), lambda i: (i, 0))
    blk16 = pl.BlockSpec((block_rows, _SLAB_LANES), lambda i: (i, 0))
    with _no_x64():
        outs = pl.pallas_call(
            kern,
            grid=grid,
            in_specs=[pl.BlockSpec((1, 3), lambda i: (0, 0),
                                   memory_space=pltpu.SMEM),
                      blk, blk] + [blk] * n_state,
            out_specs=[blk] + [blk] * n_state + [blk16],
            out_shape=[jax.ShapeDtypeStruct((rp, _SLAB_LANES),
                                            jnp.float32)] * (n_state + 1)
            + [jax.ShapeDtypeStruct((rp, _SLAB_LANES), jnp.bfloat16)],
            interpret=interpret,
        )(scalars, w2, g2, *st2)
    new_w = outs[0].reshape(-1)[:s]
    new_states = tuple(o.reshape(-1)[:s] for o in outs[1:n_state + 1])
    w16 = outs[n_state + 1].reshape(-1)[:s]
    return new_w, new_states, w16


# ---------------------------------------------------------------------------
# conv-backward pair (ROADMAP item 3: the MFU climb).
#
# ResNet's dominant FLOP sink is conv backward, and the banked probes
# (conv_bwd_experiments / NOTES_r5 §8) showed XLA's native
# conv-backprop-filter can lose badly to an explicit tap decomposition.
# The kernels below productize that decomposition WITHOUT the im2col
# patches slab:
#
#   wgrad:  gw[o,c,kh,kw] = sum_{n,oh,ow} g[n,o,oh,ow]
#                           * xpad[n,c,oh+kh,ow+kw]
#   dgrad:  dx = stride-1 conv of the (kh-1-p)-padded grad with the
#           180°-rotated, O<->C-swapped filter
#
# Both are tiled over (N, H-out, W-out, C) blocks — a grid over N-blocks
# whose per-step VMEM working set is one halo'd NHWC activation block,
# one grad block, and the f32 accumulator; the kh*kw filter-tap
# accumulation happens in-register per block (one MXU dot_general per
# tap), never materializing a kh*kw-sized patches tensor. bf16 inputs
# accumulate in f32 via preferred_element_type; the accumulation order
# (grid-sequential over N blocks, then taps) is fixed, so bf16 results
# are bitwise stable across runs.
#
# Tuned envelope (conv_bwd_plan): stride (1,1), dilation (1,1),
# groups 1, f32/bf16, kernel covering its padding (k > p), channel
# counts in MXU-friendly multiples, and a VMEM bound on the block
# working set. Everything else returns None and the caller falls back
# to XLA or the MXNET_CONV_WGRAD=taps lever — the dispatch table is
# per-shape and memoized, so the decision costs nothing on the trace
# hot path.
# ---------------------------------------------------------------------------

_CONV_VMEM_BUDGET = int(os.environ.get(
    "MXTPU_CONV_KERNEL_VMEM", str(12 * 1024 * 1024)))
_conv_plan_cache = {}


def conv_kernel_enabled():
    """Whether the Pallas conv-backward pair replaces XLA's gradient
    convs for in-envelope shapes. ``MXTPU_CONV_KERNEL``: "pallas" (or
    "1") enables it everywhere (interpret mode off-TPU — the parity
    tests); unset/"0"/"xla" keeps XLA's lowering."""
    return os.environ.get("MXTPU_CONV_KERNEL", "") in ("pallas", "1")


def conv_bwd_plan(dshape, wshape, stride, pad, dilate, dtype):
    """Per-shape dispatch decision for the conv-backward kernels.

    Returns ``{"block_n": int}`` when BOTH kernels can run this shape
    inside the tuned envelope, else None (caller falls back to XLA /
    the taps lever). Memoized per shape signature so the elif chain in
    ops/nn.py pays one dict lookup per trace."""
    key = (tuple(dshape), tuple(wshape), tuple(stride), tuple(pad),
           tuple(dilate), str(dtype))
    hit = _conv_plan_cache.get(key, "miss")
    if hit != "miss":
        return hit
    plan = _conv_bwd_plan_uncached(*key)
    _conv_plan_cache[key] = plan
    return plan


def _conv_bwd_plan_uncached(dshape, wshape, stride, pad, dilate, dtype):
    n, c, h, w = dshape
    o, cg, kh, kw = wshape
    if str(dtype) not in ("float32", "bfloat16"):
        return None
    if tuple(stride) != (1, 1) or tuple(dilate) != (1, 1) or cg != c:
        return None
    # dgrad-as-flipped-conv needs the kernel to cover its padding
    if kh - 1 - pad[0] < 0 or kw - 1 - pad[1] < 0:
        return None
    oh = h + 2 * pad[0] - kh + 1
    ow = w + 2 * pad[1] - kw + 1
    if oh < 1 or ow < 1:
        return None
    # MXU-friendly channel counts (lane dim); every ResNet body conv
    # (64..512) qualifies, toy C=3 stems do not
    if c % 8 or o % 8:
        return None
    esz = 2 if str(dtype) == "bfloat16" else 4
    # per-grid-step VMEM at block_n images: halo'd x block + g block +
    # the larger of the two f32 accumulators (wgrad taps / dgrad out)
    def vmem(bn):
        x_blk = bn * (h + 2 * pad[0]) * (w + 2 * pad[1]) * c * esz
        g_blk = bn * max(oh * ow * o,
                         (h + kh - 1) * (w + kw - 1) * o) * esz
        acc = max(kh * kw * o * c * 4, bn * h * w * c * 4)
        return x_blk + g_blk + acc
    if vmem(1) > _CONV_VMEM_BUDGET:
        return None
    block_n = 1
    while (block_n * 2 <= min(n, 8) and n % (block_n * 2) == 0
           and vmem(block_n * 2) <= _CONV_VMEM_BUDGET):
        block_n *= 2
    return {"block_n": block_n}


def _conv_wgrad_kernel(x_ref, g_ref, out_ref, *, bn, oh, ow, kh, kw):
    ni = pl.program_id(0)

    @pl.when(ni == 0)
    def _():
        out_ref[...] = jnp.zeros_like(out_ref)

    g = g_ref[...].astype(jnp.float32).reshape(bn * oh * ow, -1)  # (M, O)
    x = x_ref[...]
    for ih in range(kh):
        for iw in range(kw):
            xs = x[:, ih:ih + oh, iw:iw + ow, :].astype(
                jnp.float32).reshape(bn * oh * ow, -1)  # (M, C)
            out_ref[ih * kw + iw] = out_ref[ih * kw + iw] + \
                jax.lax.dot_general(
                    g, xs, (((0,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32)  # (O, C)


def conv_bwd_filter(data, grad, wshape, pad, block_n=None, interpret=None):
    """Pallas filter gradient of a stride-1/dilation-1/groups-1 2-D conv.

    data: (N, C, H, W); grad: (N, O, OH, OW) cotangent; wshape:
    (O, C, kh, kw). Returns the f32 filter gradient (O, C, kh, kw).
    The tap accumulation runs in-register per (block_n, OH, OW, C)
    block; f32 accumulation regardless of input dtype."""
    n, c, h, w = data.shape
    o, _, kh, kw = wshape
    oh, ow = grad.shape[2], grad.shape[3]
    if interpret is None:
        interpret = _use_interpret()
    if block_n is None:
        plan = conv_bwd_plan(data.shape, wshape, (1, 1), pad, (1, 1),
                             data.dtype)
        block_n = plan["block_n"] if plan else 1
    # layout + halo pad happen OUTSIDE _no_x64 (see fused_slab_update's
    # note on i64/i32 subfunction cache keys under global x64)
    x_t = jnp.pad(jnp.transpose(data, (0, 2, 3, 1)),
                  ((0, 0), (pad[0], pad[0]), (pad[1], pad[1]), (0, 0)))
    g_t = jnp.transpose(grad, (0, 2, 3, 1))
    x_t, _ = _pad_to(x_t, 0, block_n)  # zero images contribute zero
    g_t, _ = _pad_to(g_t, 0, block_n)
    grid = (x_t.shape[0] // block_n,)
    hp, wp = x_t.shape[1], x_t.shape[2]
    kern = functools.partial(_conv_wgrad_kernel, bn=block_n, oh=oh, ow=ow,
                             kh=kh, kw=kw)
    with _no_x64():
        gw = pl.pallas_call(
            kern,
            grid=grid,
            in_specs=[
                pl.BlockSpec((block_n, hp, wp, c), lambda i: (i, 0, 0, 0)),
                pl.BlockSpec((block_n, oh, ow, o), lambda i: (i, 0, 0, 0)),
            ],
            # constant index map: the accumulator block stays
            # VMEM-resident across the whole N-block grid
            out_specs=pl.BlockSpec((kh * kw, o, c), lambda i: (0, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((kh * kw, o, c), jnp.float32),
            interpret=interpret,
        )(x_t, g_t)
    return jnp.transpose(gw, (1, 2, 0)).reshape(o, c, kh, kw)


def _conv_dgrad_kernel(g_ref, w_ref, out_ref, *, bn, h, w, kh, kw):
    g = g_ref[...]
    acc = jnp.zeros((bn * h * w, out_ref.shape[-1]), jnp.float32)
    for ih in range(kh):
        for iw in range(kw):
            gs = g[:, ih:ih + h, iw:iw + w, :].astype(
                jnp.float32).reshape(bn * h * w, -1)  # (M, O)
            acc = acc + jax.lax.dot_general(
                gs, w_ref[ih, iw].astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)  # (M, C)
    out_ref[...] = acc.reshape(out_ref.shape).astype(out_ref.dtype)


def conv_bwd_input(grad, weight, dshape, pad, block_n=None,
                   interpret=None):
    """Pallas data gradient of a stride-1/dilation-1/groups-1 2-D conv.

    grad: (N, O, OH, OW) cotangent; weight: (O, C, kh, kw); dshape:
    the (N, C, H, W) input shape to reconstruct. dgrad is the stride-1
    conv of the (k-1-p)-padded grad with the rotated/transposed filter;
    each grid step computes one (block_n, H, W, C) output block with
    in-register f32 tap accumulation. Returns f32 (N, C, H, W)."""
    n, c, h, w = dshape
    o, _, kh, kw = weight.shape
    if interpret is None:
        interpret = _use_interpret()
    if block_n is None:
        plan = conv_bwd_plan(dshape, weight.shape, (1, 1), pad, (1, 1),
                             grad.dtype)
        block_n = plan["block_n"] if plan else 1
    ph, pw = kh - 1 - pad[0], kw - 1 - pad[1]
    g_t = jnp.pad(jnp.transpose(grad, (0, 2, 3, 1)),
                  ((0, 0), (ph, ph), (pw, pw), (0, 0)))
    g_t, _ = _pad_to(g_t, 0, block_n)
    # w[o, c, ::-1, ::-1] transposed to (kh, kw, O, C): the correlation
    # taps of the full (lhs-dilation-free, stride already 1) dgrad conv
    w_rot = jnp.transpose(weight[:, :, ::-1, ::-1], (2, 3, 0, 1))
    grid = (g_t.shape[0] // block_n,)
    hgp, wgp = g_t.shape[1], g_t.shape[2]
    kern = functools.partial(_conv_dgrad_kernel, bn=block_n, h=h, w=w,
                             kh=kh, kw=kw)
    with _no_x64():
        gd = pl.pallas_call(
            kern,
            grid=grid,
            in_specs=[
                pl.BlockSpec((block_n, hgp, wgp, o),
                             lambda i: (i, 0, 0, 0)),
                pl.BlockSpec((kh, kw, o, c), lambda i: (0, 0, 0, 0)),
            ],
            out_specs=pl.BlockSpec((block_n, h, w, c),
                                   lambda i: (i, 0, 0, 0)),
            out_shape=jax.ShapeDtypeStruct(
                (g_t.shape[0], h, w, c), jnp.float32),
            interpret=interpret,
        )(g_t, w_rot)
    return jnp.transpose(gd[:n], (0, 3, 1, 2))


def reference_attention(q, k, v, causal=False, scale=None):
    """Materialized-scores attention, the correctness oracle for the
    kernels (and the XLA path for tiny sequence lengths)."""
    b, t, h, d = q.shape
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(
        q.dtype
    )
