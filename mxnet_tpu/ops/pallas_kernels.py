"""Pallas TPU kernels for the hot ops.

The reference reaches for hand-written CUDA / cuDNN where the stock ops
are too slow (SURVEY.md §2 N6 cudnn_*-inl.h, N18 mshadow). The TPU-native
equivalent is Pallas: kernels that XLA cannot produce from jnp alone
because they need explicit on-chip (VMEM) accumulation patterns. The
flagship here is flash attention — blockwise online-softmax attention
whose VMEM working set is O(block²+block·D) per grid step (the K/V axis
is walked by the innermost grid dimension, not loaded whole), forward and
backward both as MXU-tiled kernels.

Everything degrades gracefully off-TPU: ``interpret=True`` runs the same
kernels through the Pallas interpreter (tests), and callers can always
use the pure-jnp reference path (``reference_attention``).

Layout convention matches ``parallel/ring_attention``: [B, T, H, D].
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _use_interpret():
    return jax.default_backend() != "tpu"


def _pad_to(x, axis, mult):
    size = x.shape[axis]
    rem = size % mult
    if rem == 0:
        return x, size
    pad = [(0, 0)] * x.ndim
    pad[axis] = (0, mult - rem)
    return jnp.pad(x, pad), size


# ---------------------------------------------------------------------------
# forward kernel: grid (B*H, nq, nk), k innermost. The output block index
# map ignores the k dimension, so Mosaic keeps o_ref resident in VMEM
# while the k loop accumulates into scratch; only one (block_q, block_k)
# tile of each operand is on-chip at a time.
# ---------------------------------------------------------------------------

def _causal_block_live(qi, ki, block_q, block_k):
    """Whether k block ki intersects the causal triangle of q block qi."""
    return ki * jnp.int32(block_k) <= qi * jnp.int32(block_q) + jnp.int32(
        block_q - 1
    )


def _masked_scores(q, k_blk, qi, ki, *, block_q, block_k, t_real, scale,
                   causal):
    """The shared score/mask invariant of all three kernels:
    s = scale·q@kᵀ on the MXU plus the (padding, causal) keep-mask for
    this (qi, ki) block pair. Kept in ONE place so forward and backward
    can never disagree on masking."""
    s = jnp.float32(scale) * jax.lax.dot_general(
        q, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # [bq, bk]
    q_pos = qi * jnp.int32(block_q) + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = ki * jnp.int32(block_k) + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    mask = k_pos < jnp.int32(t_real)
    if causal:
        mask = mask & (q_pos >= k_pos)
    return s, mask


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, l_ref, acc, m_s, l_s,
                *, block_q, block_k, t_real, scale, causal):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        acc[:] = jnp.zeros_like(acc)
        m_s[:] = jnp.full_like(m_s, jnp.float32(_NEG_INF))
        l_s[:] = jnp.zeros_like(l_s)

    live = True
    if causal:
        live = _causal_block_live(qi, ki, block_q, block_k)

    @pl.when(live)
    def _():
        q = q_ref[0].astype(jnp.float32)  # [bq, D]
        k_blk = k_ref[0].astype(jnp.float32)  # [bk, D]
        v_blk = v_ref[0].astype(jnp.float32)
        s, mask = _masked_scores(
            q, k_blk, qi, ki, block_q=block_q, block_k=block_k,
            t_real=t_real, scale=scale, causal=causal)
        s = jnp.where(mask, s, jnp.float32(_NEG_INF))

        m_prev = m_s[:, 0]
        m_cur = jnp.maximum(m_prev, jnp.max(s, axis=1))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur[:, None])
        l_s[:, 0] = l_s[:, 0] * alpha + jnp.sum(p, axis=1)
        m_s[:, 0] = m_cur
        acc[:] = acc[:] * alpha[:, None] + jax.lax.dot_general(
            p, v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _():
        l_fin = l_s[:, 0]
        safe_l = jnp.where(l_fin > 0, l_fin, jnp.float32(1.0))
        o_ref[0] = (acc[:] / safe_l[:, None]).astype(o_ref.dtype)
        # logsumexp residual for backward
        l_ref[0, :, 0] = (m_s[:, 0] + jnp.log(safe_l)).astype(jnp.float32)


# ---------------------------------------------------------------------------
# backward kernels. dq: grid (bh, nq, nk); dkv: grid (bh, nk, nq).
# dS = P * (dP - delta), P = exp(S - L), dP = dO V^T,
# delta_i = sum_d dO_id * O_id.
# ---------------------------------------------------------------------------

def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, l_ref, d_ref, dq_ref,
                   dq_acc, *, block_q, block_k, t_real, scale, causal):
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ki == 0)
    def _():
        dq_acc[:] = jnp.zeros_like(dq_acc)

    live = True
    if causal:
        live = _causal_block_live(qi, ki, block_q, block_k)

    @pl.when(live)
    def _():
        q = q_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = l_ref[0, :, 0]
        delta = d_ref[0, :, 0]
        k_blk = k_ref[0].astype(jnp.float32)
        v_blk = v_ref[0].astype(jnp.float32)
        s, mask = _masked_scores(
            q, k_blk, qi, ki, block_q=block_q, block_k=block_k,
            t_real=t_real, scale=scale, causal=causal)
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), jnp.float32(0.0))
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None])
        dq_acc[:] = dq_acc[:] + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(ki == nk - 1)
    def _():
        dq_ref[0] = (jnp.float32(scale) * dq_acc[:]).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, l_ref, d_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, block_q, block_k,
                    t_real, scale, causal):
    ki = pl.program_id(1)
    qi = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(qi == 0)
    def _():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    live = True
    if causal:
        live = _causal_block_live(qi, ki, block_q, block_k)

    @pl.when(live)
    def _():
        k_blk = k_ref[0].astype(jnp.float32)  # [bk, D]
        v_blk = v_ref[0].astype(jnp.float32)
        q = q_ref[0].astype(jnp.float32)  # [bq, D]
        do = do_ref[0].astype(jnp.float32)
        lse = l_ref[0, :, 0]
        delta = d_ref[0, :, 0]
        s, mask = _masked_scores(
            q, k_blk, qi, ki, block_q=block_q, block_k=block_k,
            t_real=t_real, scale=scale, causal=causal)
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), jnp.float32(0.0))
        dv_acc[:] = dv_acc[:] + jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [bk, D]
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta[:, None])
        dk_acc[:] = dk_acc[:] + jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == nq - 1)
    def _():
        dk_ref[0] = (jnp.float32(scale) * dk_acc[:]).astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


# ---------------------------------------------------------------------------
# host-side wrappers
# ---------------------------------------------------------------------------

def _fwd_call(q3, k3, v3, t_real, scale, causal, block_q, block_k,
              interpret):
    bh, t_pad, d = q3.shape
    nq = t_pad // block_q
    nk = t_pad // block_k
    kern = functools.partial(
        _fwd_kernel, block_q=block_q, block_k=block_k, t_real=t_real,
        scale=scale, causal=causal,
    )
    # trace under 32-bit mode: the framework enables jax_enable_x64 globally
    # (reference float64 NDArray parity) but Mosaic kernels must stay 32-bit
    with jax.enable_x64(False):
        out, lse = pl.pallas_call(
            kern,
            grid=(bh, nq, nk),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, t_pad, d), q3.dtype),
                jax.ShapeDtypeStruct((bh, t_pad, 1), jnp.float32),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_q, d), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
            ],
            interpret=interpret,
        )(q3, k3, v3)
    return out, lse


def _bwd_call(q3, k3, v3, do3, lse, delta, t_real, scale, causal,
              block_q, block_k, interpret):
    bh, t_pad, d = q3.shape
    nq = t_pad // block_q
    nk = t_pad // block_k
    with jax.enable_x64(False):
        dq = pl.pallas_call(
            functools.partial(
                _bwd_dq_kernel, block_q=block_q, block_k=block_k,
                t_real=t_real, scale=scale, causal=causal,
            ),
            grid=(bh, nq, nk),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            ],
            out_specs=pl.BlockSpec(
                (1, block_q, d), lambda b, i, j: (b, i, 0)
            ),
            out_shape=jax.ShapeDtypeStruct((bh, t_pad, d), q3.dtype),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
            interpret=interpret,
        )(q3, k3, v3, do3, lse, delta)
        dk, dv = pl.pallas_call(
            functools.partial(
                _bwd_dkv_kernel, block_q=block_q, block_k=block_k,
                t_real=t_real, scale=scale, causal=causal,
            ),
            grid=(bh, nk, nq),
            in_specs=[
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, j, 0)),
                pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, j, 0)),
            ],
            out_specs=[
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
                pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, i, 0)),
            ],
            out_shape=[
                jax.ShapeDtypeStruct((bh, t_pad, d), q3.dtype),
                jax.ShapeDtypeStruct((bh, t_pad, d), q3.dtype),
            ],
            scratch_shapes=[
                pltpu.VMEM((block_k, d), jnp.float32),
                pltpu.VMEM((block_k, d), jnp.float32),
            ],
            interpret=interpret,
        )(q3, k3, v3, do3, lse, delta)
    return dq, dk, dv


@functools.partial(
    jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7)
)
def _flash(q3, k3, v3, t_real, scale, causal, block_q, block_k):
    interp = _use_interpret()
    out, _ = _fwd_call(q3, k3, v3, t_real, scale, causal, block_q,
                       block_k, interp)
    return out


def _flash_fwd(q3, k3, v3, t_real, scale, causal, block_q, block_k):
    interp = _use_interpret()
    out, lse = _fwd_call(q3, k3, v3, t_real, scale, causal, block_q,
                         block_k, interp)
    return out, (q3, k3, v3, out, lse)


def _flash_bwd(t_real, scale, causal, block_q, block_k, res, g):
    q3, k3, v3, out, lse = res
    interp = _use_interpret()
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1,
        keepdims=True,
    )  # [BH, T, 1]
    dq, dk, dv = _bwd_call(
        q3, k3, v3, g.astype(q3.dtype), lse, delta, t_real, scale,
        causal, block_q, block_k, interp,
    )
    return dq, dk, dv


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, causal=False, scale=None, block_q=128,
                    block_k=128):
    """Blockwise (flash) attention. q/k/v: [B, T, H, D] -> [B, T, H, D].

    Pallas MXU kernels on TPU; the same kernels run under the Pallas
    interpreter elsewhere so tests don't need hardware. The TPU-native
    replacement for what the reference delegates to cuDNN fused kernels
    (cudnn_rnn-inl.h being the closest 2017 analog of a fused
    sequence kernel).

    NOTE: pallas_call has no GSPMD partitioning rules — inside pjit over a
    sharded mesh, wrap calls in shard_map (see parallel/ring_attention for
    the sp-sharded composition) or keep attention inputs replicated.
    """
    b, t, h, d = q.shape
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))
    blk = min(block_q, block_k)
    if t < blk:
        block_q = block_k = max(8, 1 << (t - 1).bit_length())
    q3 = q.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    k3 = k.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    v3 = v.transpose(0, 2, 1, 3).reshape(b * h, t, d)
    mult = int(np.lcm(block_q, block_k))
    q3, _ = _pad_to(q3, 1, mult)
    k3, _ = _pad_to(k3, 1, mult)
    v3, _ = _pad_to(v3, 1, mult)
    out = _flash(q3, k3, v3, t, float(scale), bool(causal), int(block_q),
                 int(block_k))
    out = out[:, :t]
    return out.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def reference_attention(q, k, v, causal=False, scale=None):
    """Materialized-scores attention, the correctness oracle for the
    kernels (and the XLA path for tiny sequence lengths)."""
    b, t, h, d = q.shape
    if scale is None:
        scale = 1.0 / float(np.sqrt(d))
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        mask = jnp.tril(jnp.ones((t, t), bool))
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32)).astype(
        q.dtype
    )
