"""Elementwise unary/binary/scalar/logic ops and their broadcast variants.

Parity: reference ``src/operator/tensor/elemwise_unary_op.cc`` (~40 unary
ops), ``elemwise_binary_op.cc`` + ``_scalar`` + ``_logic`` variants, and
``elemwise_binary_broadcast_op*.cc``. The reference implements each as an
mshadow expression-template kernel; here each is a jnp one-liner that XLA
fuses on the VPU — the entire mshadow layer (SURVEY.md §2 N18) collapses
into these definitions.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .registry import OpDef, register
from .utils import binary_broadcast_infer, merge_shapes, same_shape_infer

_f32 = np.float32


def elemwise_backward_infer(attrs, in_shapes, out_shapes):
    """Reverse inference for same-shape ops: outputs refine inputs."""
    merged = None
    for s in list(out_shapes) + list(in_shapes):
        merged = merge_shapes(merged, s, "elemwise")
    return [merged] * len(in_shapes)


def _unary(name, fn, aliases=()):
    register(
        OpDef(
            name,
            lambda attrs, ins, is_train, _fn=fn: [_fn(ins[0])],
            arguments=("data",),
            infer_shape=same_shape_infer(1),
            backward_infer_shape=elemwise_backward_infer,
            aliases=aliases,
        )
    )


def _binary(name, fn, aliases=(), logic=False):
    def fcompute(attrs, ins, is_train, _fn=fn):
        out = _fn(ins[0], ins[1])
        if logic:  # reference logic ops return same dtype as inputs
            out = out.astype(ins[0].dtype)
        return [out]

    register(
        OpDef(
            name,
            fcompute,
            arguments=("lhs", "rhs"),
            infer_shape=same_shape_infer(2),
            backward_infer_shape=elemwise_backward_infer,
            aliases=aliases,
        )
    )


def _binary_scalar(name, fn, aliases=()):
    def fcompute(attrs, ins, is_train, _fn=fn):
        scalar = jnp.asarray(attrs["scalar"], dtype=ins[0].dtype)
        return [_fn(ins[0], scalar)]

    register(
        OpDef(
            name,
            fcompute,
            arguments=("data",),
            defaults={"scalar": 0.0},
            infer_shape=same_shape_infer(1),
            aliases=aliases,
        )
    )


def _broadcast(name, fn, aliases=(), logic=False):
    def fcompute(attrs, ins, is_train, _fn=fn):
        out = _fn(ins[0], ins[1])
        if logic:
            out = out.astype(ins[0].dtype)
        return [out]

    register(
        OpDef(
            name,
            fcompute,
            arguments=("lhs", "rhs"),
            infer_shape=binary_broadcast_infer,
            aliases=aliases,
        )
    )


# --------------------------------------------------------------------------
# unary (reference elemwise_unary_op.cc)
# --------------------------------------------------------------------------
def _relu(x):
    return jnp.where(x > 0, x, jnp.zeros_like(x))  # exact subgradient parity


_unary("relu", _relu)
_unary("sigmoid", jax.nn.sigmoid)
_unary("_copy", lambda x: x, aliases=("identity",))
_unary("BlockGrad", jax.lax.stop_gradient, aliases=("stop_gradient",))
_unary("make_loss", lambda x: x)
_unary("negative", jnp.negative)
_unary("abs", jnp.abs)
_unary("sign", jnp.sign)
_unary("round", jnp.round)
_unary("rint", jnp.rint)
_unary("ceil", jnp.ceil)
_unary("floor", jnp.floor)
_unary("trunc", jnp.trunc)
_unary("fix", jnp.trunc)  # round-toward-zero (jnp.fix deprecated alias)
_unary("square", jnp.square)
_unary("sqrt", jnp.sqrt)
_unary("rsqrt", jax.lax.rsqrt)
_unary("cbrt", jnp.cbrt)
_unary("rcbrt", lambda x: 1.0 / jnp.cbrt(x))
_unary("exp", jnp.exp)
_unary("log", jnp.log)
_unary("log10", jnp.log10)
_unary("log2", jnp.log2)
_unary("log1p", jnp.log1p)
_unary("expm1", jnp.expm1)
_unary("reciprocal", jnp.reciprocal)
_unary("sin", jnp.sin)
_unary("cos", jnp.cos)
_unary("tan", jnp.tan)
_unary("arcsin", jnp.arcsin)
_unary("arccos", jnp.arccos)
_unary("arctan", jnp.arctan)
_unary("sinh", jnp.sinh)
_unary("cosh", jnp.cosh)
_unary("tanh", jnp.tanh)
_unary("arcsinh", jnp.arcsinh)
_unary("arccosh", jnp.arccosh)
_unary("arctanh", jnp.arctanh)
_unary("degrees", jnp.degrees)
_unary("radians", jnp.radians)
_unary("gamma", lambda x: jnp.exp(jax.scipy.special.gammaln(x)))
_unary("gammaln", jax.scipy.special.gammaln)
_unary("erf", jax.scipy.special.erf)
_unary("softsign", jax.nn.soft_sign)


# Cast — dtype change (reference elemwise_unary_op.cc Cast)
def _cast_fcompute(attrs, ins, is_train):
    from ..base import np_dtype

    return [ins[0].astype(np_dtype(attrs["dtype"]))]


def _cast_infer_type(attrs, in_types):
    from ..base import np_dtype

    t = np_dtype(attrs["dtype"])
    inferred = [in_types[0] if in_types[0] is not None else _f32]
    return inferred, [t], []


register(
    OpDef(
        "Cast",
        _cast_fcompute,
        arguments=("data",),
        defaults={"dtype": "float32"},
        infer_shape=same_shape_infer(1),
        infer_type=_cast_infer_type,
        aliases=("cast",),
    )
)


# smooth_l1 (reference smooth_l1_unary-inl.h): scalar sigma; f(x) =
# 0.5 (sigma x)^2 if |x| < 1/sigma^2 else |x| - 0.5/sigma^2
def _smooth_l1(attrs, ins, is_train):
    sigma = float(attrs.get("scalar", 1.0))
    x = ins[0]
    s2 = sigma * sigma
    return [
        jnp.where(
            jnp.abs(x) < 1.0 / s2,
            0.5 * s2 * jnp.square(x),
            jnp.abs(x) - 0.5 / s2,
        )
    ]


register(
    OpDef(
        "smooth_l1",
        _smooth_l1,
        arguments=("data",),
        defaults={"scalar": 1.0},
        infer_shape=same_shape_infer(1),
    )
)

# --------------------------------------------------------------------------
# binary elemwise (same-shape) — reference elemwise_binary_op.cc
# --------------------------------------------------------------------------
_binary("elemwise_add", jnp.add, aliases=("_plus", "_add", "_Plus"))
_binary("elemwise_sub", jnp.subtract, aliases=("_minus", "_sub", "_Minus"))
_binary("elemwise_mul", jnp.multiply, aliases=("_mul", "_Mul"))
_binary("elemwise_div", jnp.divide, aliases=("_div", "_Div"))
_binary("_mod", jnp.mod, aliases=("_Mod",))
_binary("_power", jnp.power, aliases=("_Power", "_pow"))
_binary("_maximum", jnp.maximum, aliases=("_Maximum",))
_binary("_minimum", jnp.minimum, aliases=("_Minimum",))
_binary("_hypot", jnp.hypot)
_binary("_equal", jnp.equal, logic=True, aliases=("_Equal",))
_binary("_not_equal", jnp.not_equal, logic=True, aliases=("_Not_Equal",))
_binary("_greater", jnp.greater, logic=True, aliases=("_Greater",))
_binary("_greater_equal", jnp.greater_equal, logic=True, aliases=("_Greater_Equal",))
_binary("_lesser", jnp.less, logic=True, aliases=("_Lesser",))
_binary("_lesser_equal", jnp.less_equal, logic=True, aliases=("_Lesser_Equal",))

# --------------------------------------------------------------------------
# binary scalar — reference elemwise_binary_scalar_op.cc
# --------------------------------------------------------------------------
_binary_scalar("_plus_scalar", jnp.add, aliases=("_PlusScalar",))
_binary_scalar("_minus_scalar", jnp.subtract, aliases=("_MinusScalar",))
_binary_scalar("_rminus_scalar", lambda x, s: s - x, aliases=("_RMinusScalar",))
_binary_scalar("_mul_scalar", jnp.multiply, aliases=("_MulScalar",))
_binary_scalar("_div_scalar", jnp.divide, aliases=("_DivScalar",))
_binary_scalar("_rdiv_scalar", lambda x, s: s / x, aliases=("_RDivScalar",))
_binary_scalar("_mod_scalar", jnp.mod, aliases=("_ModScalar",))
_binary_scalar("_rmod_scalar", lambda x, s: jnp.mod(s, x), aliases=("_RModScalar",))
_binary_scalar("_power_scalar", jnp.power, aliases=("_PowerScalar",))
_binary_scalar("_rpower_scalar", lambda x, s: jnp.power(s, x), aliases=("_RPowerScalar",))
_binary_scalar("_maximum_scalar", jnp.maximum, aliases=("_MaximumScalar",))
_binary_scalar("_minimum_scalar", jnp.minimum, aliases=("_MinimumScalar",))
_binary_scalar("_hypot_scalar", jnp.hypot, aliases=("_HypotScalar",))
_binary_scalar("_equal_scalar", lambda x, s: (x == s).astype(x.dtype), aliases=("_EqualScalar",))
_binary_scalar("_not_equal_scalar", lambda x, s: (x != s).astype(x.dtype), aliases=("_NotEqualScalar",))
_binary_scalar("_greater_scalar", lambda x, s: (x > s).astype(x.dtype), aliases=("_GreaterScalar",))
_binary_scalar("_greater_equal_scalar", lambda x, s: (x >= s).astype(x.dtype), aliases=("_GreaterEqualScalar",))
_binary_scalar("_lesser_scalar", lambda x, s: (x < s).astype(x.dtype), aliases=("_LesserScalar",))
_binary_scalar("_lesser_equal_scalar", lambda x, s: (x <= s).astype(x.dtype), aliases=("_LesserEqualScalar",))

# --------------------------------------------------------------------------
# broadcast binary — reference elemwise_binary_broadcast_op_*.cc
# --------------------------------------------------------------------------
_broadcast("broadcast_add", jnp.add, aliases=("broadcast_plus",))
_broadcast("broadcast_sub", jnp.subtract, aliases=("broadcast_minus",))
_broadcast("broadcast_mul", jnp.multiply)
_broadcast("broadcast_div", jnp.divide)
_broadcast("broadcast_mod", jnp.mod)
_broadcast("broadcast_power", jnp.power)
_broadcast("broadcast_maximum", jnp.maximum)
_broadcast("broadcast_minimum", jnp.minimum)
_broadcast("broadcast_hypot", jnp.hypot)
_broadcast("broadcast_equal", jnp.equal, logic=True)
_broadcast("broadcast_not_equal", jnp.not_equal, logic=True)
_broadcast("broadcast_greater", jnp.greater, logic=True)
_broadcast("broadcast_greater_equal", jnp.greater_equal, logic=True)
_broadcast("broadcast_lesser", jnp.less, logic=True)
_broadcast("broadcast_lesser_equal", jnp.less_equal, logic=True)


# add_n / ElementwiseSum — variable input count (reference elemwise_sum.cc)
def _add_n(attrs, ins, is_train):
    out = ins[0]
    for x in ins[1:]:
        out = out + x
    return [out]


register(
    OpDef(
        "add_n",
        _add_n,
        arguments=("args",),
        key_var_num_args="num_args",
        infer_shape=lambda attrs, in_shapes: same_shape_infer(len(in_shapes))(
            attrs, in_shapes
        ),
        aliases=("ElementWiseSum", "_sum"),
    )
)
