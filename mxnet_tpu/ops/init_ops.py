"""Creation ops (_zeros/_ones/_arange/zeros_like/ones_like).

Parity: reference ``src/operator/tensor/init_op.cc``.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..base import np_dtype
from .registry import OpDef, register
from .utils import as_tuple


def _creation_infer(attrs, in_shapes):
    shape = as_tuple(attrs.get("shape", ()))
    return [], [shape], []


def _creation_type(attrs, in_types):
    return [], [np_dtype(attrs.get("dtype", "float32"))], []


def _register_creation(name, fill):
    register(
        OpDef(
            name,
            lambda attrs, ins, is_train, _v=fill: [
                jnp.full(
                    as_tuple(attrs.get("shape", ())),
                    _v,
                    dtype=np_dtype(attrs.get("dtype", "float32")),
                )
            ],
            arguments=(),
            defaults={"shape": (), "dtype": "float32"},
            infer_shape=_creation_infer,
            infer_type=_creation_type,
        )
    )


_register_creation("_zeros", 0)
_register_creation("_ones", 1)


def _arange(attrs, ins, is_train):
    start = float(attrs.get("start", 0.0))
    stop = attrs.get("stop")
    step = float(attrs.get("step", 1.0))
    repeat = int(attrs.get("repeat", 1))
    dt = np_dtype(attrs.get("dtype", "float32"))
    if stop is None:
        out = np.arange(0.0, start, step)
    else:
        out = np.arange(start, float(stop), step)
    if repeat > 1:
        out = np.repeat(out, repeat)
    return [jnp.asarray(out, dtype=dt)]


def _arange_infer(attrs, in_shapes):
    start = float(attrs.get("start", 0.0))
    stop = attrs.get("stop")
    step = float(attrs.get("step", 1.0))
    repeat = int(attrs.get("repeat", 1))
    if stop is None:
        n = len(np.arange(0.0, start, step))
    else:
        n = len(np.arange(start, float(stop), step))
    return [], [(n * repeat,)], []


register(
    OpDef(
        "_arange",
        _arange,
        arguments=(),
        defaults={"start": 0.0, "stop": None, "step": 1.0, "repeat": 1, "dtype": "float32"},
        infer_shape=_arange_infer,
        infer_type=_creation_type,
    )
)

register(
    OpDef(
        "zeros_like",
        lambda attrs, ins, is_train: [jnp.zeros_like(ins[0])],
        arguments=("data",),
    )
)
register(
    OpDef(
        "ones_like",
        lambda attrs, ins, is_train: [jnp.ones_like(ins[0])],
        arguments=("data",),
    )
)
