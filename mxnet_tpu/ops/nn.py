"""Neural-network layer operators.

Parity: the reference's legacy stateful layer ops (SURVEY.md §2 N6,
``src/operator/*-inl.h`` registered via MXNET_REGISTER_OP_PROPERTY):
Activation, FullyConnected, Convolution, Deconvolution, Pooling, BatchNorm,
Dropout, LRN, LeakyReLU, SoftmaxActivation/Output, regression outputs,
MakeLoss, InstanceNorm, L2Normalization, UpSampling, SequenceLast/Mask/
Reverse, softmax/log_softmax (``src/operator/nn/softmax.cc``),
softmax_cross_entropy (``loss_binary_op.cc``).

TPU-native notes:
- Convolution/FullyConnected lower to ``lax.conv_general_dilated`` /
  ``lax.dot_general`` → the MXU. FullyConnected forces fp32 accumulation
  via ``preferred_element_type``; convolutions rely on the MXU's native
  fp32 accumulation of bf16 matmuls (an explicit f32 output + cast breaks
  lax's conv transpose rules under bf16).
- The stateless/stateful split of the reference (OperatorProperty holding
  cuDNN descriptors) disappears: XLA owns algorithm choice, so every layer
  here is a pure function; BatchNorm's moving stats are threaded as aux
  inputs/outputs (the reference mutates them via FMutateInputs).
- Loss ops (``*Output``, MakeLoss) use jax.custom_vjp to reproduce the
  reference contract that Executor.backward() needs no head gradient — the
  op's backward ignores the incoming cotangent exactly as
  ``SoftmaxOutput::Backward`` ignores out_grad.
"""
from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .registry import OpDef, register
from .utils import as_tuple, same_shape_infer

_ACT = {
    "relu": lambda x: jnp.where(x > 0, x, jnp.zeros_like(x)),
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "softrelu": jax.nn.softplus,
    "softsign": jax.nn.soft_sign,
}


from .elemwise import elemwise_backward_infer

register(
    OpDef(
        "Activation",
        lambda attrs, ins, is_train: [_ACT[attrs.get("act_type", "relu")](ins[0])],
        arguments=("data",),
        defaults={"act_type": "relu"},
        infer_shape=same_shape_infer(1),
        backward_infer_shape=elemwise_backward_infer,
    )
)


def _leaky_relu(attrs, ins, is_train):
    act = attrs.get("act_type", "leaky")
    slope = float(attrs.get("slope", 0.25))
    x = ins[0]
    if act == "leaky":
        return [jnp.where(x > 0, x, slope * x)]
    if act == "elu":
        return [jnp.where(x > 0, x, slope * (jnp.exp(x) - 1.0))]
    if act == "prelu":
        gamma = ins[1].reshape((1, -1) + (1,) * (x.ndim - 2))
        return [jnp.where(x > 0, x, gamma * x)]
    if act == "rrelu":
        lo = float(attrs.get("lower_bound", 0.125))
        up = float(attrs.get("upper_bound", 0.334))
        if is_train:
            key = attrs["__rng__"]
            slope_r = jax.random.uniform(key, x.shape, minval=lo, maxval=up)
            return [jnp.where(x > 0, x, slope_r * x)]
        return [jnp.where(x > 0, x, ((lo + up) / 2.0) * x)]
    raise MXNetError("LeakyReLU: unknown act_type %s" % act)


def _leaky_relu_infer(attrs, in_shapes):
    d = tuple(in_shapes[0])
    if attrs.get("act_type", "leaky") == "prelu":
        return [d, (d[1],)], [d], []
    return [d], [d], []


_lrelu = OpDef(
    "LeakyReLU",
    _leaky_relu,
    arguments=("data",),
    defaults={
        "act_type": "leaky",
        "slope": 0.25,
        "lower_bound": 0.125,
        "upper_bound": 0.334,
    },
    infer_shape=_leaky_relu_infer,
    needs_rng=True,
)
_lrelu.list_arguments = lambda attrs=None: (
    ["data", "gamma"] if (attrs or {}).get("act_type") == "prelu" else ["data"]
)
register(_lrelu)


# --------------------------------------------------------------------------
# FullyConnected — reference fully_connected-inl.h:47-135
# --------------------------------------------------------------------------
def _fully_connected(attrs, ins, is_train):
    no_bias = bool(attrs.get("no_bias", False))
    data = ins[0]
    weight = ins[1]
    x2d = data.reshape(data.shape[0], -1)
    out = jax.lax.dot_general(
        x2d,
        weight,
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    ).astype(data.dtype)
    if not no_bias:
        out = out + ins[2]
    return [out]


def _fc_infer(attrs, in_shapes):
    nh = int(attrs["num_hidden"])
    no_bias = bool(attrs.get("no_bias", False))
    dshape = in_shapes[0]
    if dshape is None:
        raise MXNetError("FullyConnected: data shape required")
    if 0 in tuple(dshape)[1:]:
        # feature dims unknown (partial shape): only batch/out inferable
        return (
            [tuple(dshape)] + [None] * (len(in_shapes) - 1),
            [(dshape[0], nh)],
            [],
        )
    in_dim = int(np.prod(dshape[1:]))
    shapes = [tuple(dshape), (nh, in_dim)]
    if not no_bias:
        shapes.append((nh,))
    return shapes, [(dshape[0], nh)], []


def _fc_backward_infer(attrs, in_shapes, out_shapes):
    """Refine data batch dim (and, with known weight, the feature dim) from
    the output — resolves RNN begin_state zeros with unknown batch."""
    out = out_shapes[0]
    refined = list(in_shapes)
    dshape = in_shapes[0]
    if out is not None and out[0] > 0:
        wshape = in_shapes[1] if len(in_shapes) > 1 else None
        if dshape is not None:
            d = list(dshape)
            if d[0] == 0:
                d[0] = out[0]
            if (
                len(d) == 2
                and d[1] == 0
                and wshape is not None
                and wshape[1] > 0
            ):
                d[1] = wshape[1]
            refined[0] = tuple(d)
        elif wshape is not None and all(x > 0 for x in wshape):
            refined[0] = (out[0], wshape[1])
    return refined


_fc = OpDef(
    "FullyConnected",
    _fully_connected,
    arguments=("data", "weight", "bias"),
    defaults={"num_hidden": 0, "no_bias": False},
    infer_shape=_fc_infer,
    backward_infer_shape=_fc_backward_infer,
)
_fc.list_arguments = lambda attrs=None: (
    ["data", "weight"]
    if (attrs or {}).get("no_bias")
    else ["data", "weight", "bias"]
)
register(_fc)


# --------------------------------------------------------------------------
# Convolution / Deconvolution — reference convolution-inl.h; lowered to
# lax.conv_general_dilated (XLA chooses the MXU tiling; no im2col needed)
# --------------------------------------------------------------------------
def _conv_dims(attrs):
    kernel = as_tuple(attrs["kernel"])
    nd = len(kernel)
    stride = as_tuple(attrs.get("stride") or (1,) * nd, nd, "stride")
    dilate = as_tuple(attrs.get("dilate") or (1,) * nd, nd, "dilate")
    pad = as_tuple(attrs.get("pad") or (0,) * nd, nd, "pad")
    return kernel, stride, dilate, pad


def _conv_dn(nd):
    # NCHW / OIHW layout (reference layout); XLA relayouts internally for TPU
    spatial = "DHW"[-nd:] if nd <= 3 else None
    lhs = "NC" + spatial
    rhs = "OI" + spatial
    return jax.lax.conv_dimension_numbers(
        (1, 1) + (1,) * nd, (1, 1) + (1,) * nd, (lhs, rhs, lhs)
    )


def _conv_nhwc_dn():
    return jax.lax.conv_dimension_numbers(
        (1, 1, 1, 1), (1, 1, 1, 1), ("NHWC", "HWIO", "NHWC"))


def _conv2d_bwd_nhwc(data, weight, stride, pad, dilate, groups):
    """2-D conv, NCHW interface, with the BACKWARD convs computed in
    explicit NHWC layout (custom_vjp; forward stays the plain NCHW conv
    XLA already lays out well).

    Rationale: the r3 device trace puts 51.4 ms of the 96.4 ms ResNet-50
    bf16 step in conv backward, and the r3 layout probe falsified the
    whole-op NHWC wrap (fwd+bwd) as the lever — this targets ONLY the
    gradient convs, whose dgrad (lhs-dilated) and wgrad (batch-
    contracting) shapes are the ones layout assignment most often gets
    wrong. The backward derives the gradient convs by differentiating
    an NHWC-wrapped conv at transposed primals, so the grad math is
    jax's own (no hand-derived transposed-conv formulas to get wrong)
    and the only additions are the boundary transposes, which XLA can
    fuse or cancel. Gated by MXNET_CONV_BWD_LAYOUT=NHWC; numerics
    pinned against the default path in tests/test_conv_bwd_layout.py."""

    @jax.custom_vjp
    def conv(data, weight):
        return jax.lax.conv_general_dilated(
            data, weight, window_strides=stride,
            padding=[(p, p) for p in pad], rhs_dilation=dilate,
            dimension_numbers=_conv_dn(2), feature_group_count=groups)

    def fwd(data, weight):
        return conv(data, weight), (data, weight)

    def bwd(res, g):
        data, weight = res
        data_t = jnp.transpose(data, (0, 2, 3, 1))     # NCHW -> NHWC
        weight_t = jnp.transpose(weight, (2, 3, 1, 0))  # OIHW -> HWIO

        def f_nhwc(dt, wt):
            return jax.lax.conv_general_dilated(
                dt, wt, window_strides=stride,
                padding=[(p, p) for p in pad], rhs_dilation=dilate,
                dimension_numbers=_conv_nhwc_dn(),
                feature_group_count=groups)

        _, vjp_fn = jax.vjp(f_nhwc, data_t, weight_t)
        gd_t, gw_t = vjp_fn(jnp.transpose(g, (0, 2, 3, 1)))
        return (jnp.transpose(gd_t, (0, 3, 1, 2)),
                jnp.transpose(gw_t, (3, 2, 0, 1)))

    conv.defvjp(fwd, bwd)
    return conv(data, weight)


def _conv2d_wgrad_custom(data, weight, stride, pad, dilate, wgrad_fn):
    """Shared custom_vjp scaffold for the wgrad levers: forward and the
    DATA gradient stay jax's own lowerings (vjp of the plain conv);
    only the filter gradient is replaced by wgrad_fn(d, g, w) -> f32
    array reshapeable to w.shape. Keeping one scaffold means a fix to
    the dgrad construction or the cotangent dtype cast lands in every
    lever at once."""

    def plain(d, w):
        return jax.lax.conv_general_dilated(
            d, w, window_strides=stride,
            padding=[(p, p) for p in pad], rhs_dilation=dilate,
            dimension_numbers=_conv_dn(2))

    @jax.custom_vjp
    def conv(data, weight):
        return plain(data, weight)

    def fwd(data, weight):
        return conv(data, weight), (data, weight)

    def bwd(res, g):
        d, w = res
        _, dgrad_vjp = jax.vjp(lambda dd: plain(dd, w), d)
        gd, = dgrad_vjp(g)
        gw = wgrad_fn(d, g, w)
        return gd, gw.astype(w.dtype).reshape(w.shape)

    conv.defvjp(fwd, bwd)
    return conv(data, weight)


def _conv2d_wgrad_patches(data, weight, stride, pad, dilate):
    """2-D conv (NCHW, groups=1) whose FILTER gradient is computed as an
    explicit patches x grad matmul instead of XLA's native
    conv-backprop-filter (custom_vjp; forward and the data gradient stay
    jax's own lowerings).

    Rationale: the r3 device trace puts 51.4 ms of the 96.4 ms ResNet-50
    bf16 step in conv backward; wgrad contracts over (N, OH, OW), a
    shape XLA's layout assignment can tile badly on the MXU. Extracting
    the receptive-field patches (conv_general_dilated_patches) and
    contracting with one dot_general hands the MXU a single large
    matmul — and accumulates in f32 via preferred_element_type, which
    the native bf16 wgrad conv does not guarantee. Exact same math;
    gated by MXNET_CONV_WGRAD=patches; numerics pinned in
    tests/test_conv_bwd_layout.py.

    Memory: the patches tensor is (N, C*kh*kw, OH, OW) — ~kh*kw x the
    activation footprint (9x for 3x3), which can exceed HBM at large
    batch. MXNET_CONV_WGRAD_CHUNK=<k> splits the batch into k chunks
    and lax.scan-accumulates the f32 partial wgrads, bounding the live
    patches slab to N/k images at the cost of k smaller matmuls (same
    math — the contraction over N is a sum and accumulation stays f32;
    only f32 summation order differs)."""

    def partial_wgrad(dd, gg, w):
        """f32 (O, C*kh*kw) wgrad contribution of one batch chunk."""
        if (w.shape[2:] == (1, 1) and tuple(stride) == (1, 1)
                and tuple(pad) == (0, 0)):
            patches = dd  # 1x1/s1: the receptive field IS the input
        else:
            patches = jax.lax.conv_general_dilated_patches(
                dd, filter_shape=w.shape[2:], window_strides=stride,
                padding=[(p, p) for p in pad], rhs_dilation=dilate,
                dimension_numbers=_conv_dn(2))
        # patches: (n, C*kh*kw, OH, OW) with feature order (c, kh, kw);
        # gg: (n, O, OH, OW). Contract over (n, OH, OW) in ONE matmul.
        ckk = patches.shape[1]
        o = gg.shape[1]
        p2 = jnp.transpose(patches, (1, 0, 2, 3)).reshape(ckk, -1)
        g2 = jnp.transpose(gg, (1, 0, 2, 3)).reshape(o, -1)
        return jax.lax.dot_general(
            g2, p2, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)

    def wgrad(d, g, w):
        n = d.shape[0]
        try:
            chunks = int(os.environ.get("MXNET_CONV_WGRAD_CHUNK", "1"))
        except ValueError:
            chunks = 1
        if chunks > 1 and n % chunks == 0 and n // chunks >= 1:
            ds = d.reshape((chunks, n // chunks) + d.shape[1:])
            gs = g.reshape((chunks, n // chunks) + g.shape[1:])

            def body(acc, dg):
                dd, gg = dg
                return acc + partial_wgrad(dd, gg, w), None

            # C*kh*kw; equals C on the 1x1 fast path since kh=kw=1
            ckk = w.shape[1] * w.shape[2] * w.shape[3]
            gw, _ = jax.lax.scan(
                body, jnp.zeros((w.shape[0], ckk), jnp.float32),
                (ds, gs))
            return gw
        return partial_wgrad(d, g, w)

    return _conv2d_wgrad_custom(data, weight, stride, pad, dilate, wgrad)


def _conv2d_wgrad_taps(data, weight, stride, pad, dilate):
    """2-D conv (NCHW, groups=1) whose FILTER gradient is computed as
    kh*kw per-tap matmuls over shifted input views instead of XLA's
    native conv-backprop-filter or the patches lever's one big matmul.

    Rationale: the patches lever (_conv2d_wgrad_patches) hands the MXU
    one large contraction but materializes a (N, C*kh*kw, OH, OW) slab
    — kh*kw x the activation footprint, an HBM-bandwidth/capacity tax
    the r4 advisor flagged at large batch. The same contraction
    decomposes exactly by kernel tap:

        gw[o,c,kh,kw] = sum_{n,oh,ow} g[n,o,oh,ow] *
                        xpad[n,c, oh*s+kh*dh, ow*s+kw*dw]

    i.e. kh*kw independent (O x C) dot_generals, each contracting the
    SAME g against a strided view of the padded input — total FLOPs
    identical to the single matmul, peak memory 1x the activation (the
    strided slice is fusable), f32 accumulation via
    preferred_element_type. Data gradient stays jax's own lowering.
    Gated by MXNET_CONV_WGRAD=taps; numerics pinned in
    tests/test_conv_bwd_layout.py."""

    def wgrad(d, g, w):
        o, c, kh, kw = w.shape
        sh, sw = stride
        dh, dw = dilate
        oh, ow = g.shape[2], g.shape[3]
        xpad = jnp.pad(d, ((0, 0), (0, 0),
                           (pad[0], pad[0]), (pad[1], pad[1])))
        taps = []
        for ih in range(kh):
            for iw in range(kw):
                xs = jax.lax.slice(
                    xpad,
                    (0, 0, ih * dh, iw * dw),
                    (d.shape[0], c,
                     ih * dh + sh * (oh - 1) + 1,
                     iw * dw + sw * (ow - 1) + 1),
                    (1, 1, sh, sw))  # (N, C, OH, OW) view of this tap
                taps.append(jax.lax.dot_general(
                    g, xs,
                    (((0, 2, 3), (0, 2, 3)), ((), ())),
                    preferred_element_type=jnp.float32))  # (O, C)
        return jnp.stack(taps, axis=-1)  # (O, C, kh*kw)

    return _conv2d_wgrad_custom(data, weight, stride, pad, dilate, wgrad)


def _pallas_conv_plan(data, weight, stride, pad, dilate, groups):
    """Dispatch-table lookup for the Pallas conv-backward pair.

    Cheap env check first; the pallas_kernels import and the per-shape
    envelope decision (memoized there) only run when
    MXTPU_CONV_KERNEL=pallas is set. Returns the plan dict or None —
    None falls through to the taps lever / XLA default below."""
    if groups != 1:
        return None
    try:
        from . import pallas_kernels as _pk
    except Exception:  # noqa: BLE001 — pallas unavailable: fall back
        return None
    if not _pk.conv_kernel_enabled():
        return None
    return _pk.conv_bwd_plan(tuple(data.shape), tuple(weight.shape),
                             tuple(stride), tuple(pad), tuple(dilate),
                             data.dtype)


def _conv2d_pallas_bwd(data, weight, pad):
    """Stride-1 2-D conv whose BOTH gradient convs are the Pallas
    conv-backward pair (ops/pallas_kernels.conv_bwd_input/_filter):
    im2col-free in-register tap accumulation, f32 accumulators, no
    lhs-dilated dgrad conv. Forward stays XLA's own lowering (it is
    already MXU-shaped). Only called for shapes inside the tuned
    envelope (_pallas_conv_plan); numerics pinned in
    tests/test_conv_kernels.py."""
    from . import pallas_kernels as _pk

    def plain(d, w):
        return jax.lax.conv_general_dilated(
            d, w, window_strides=(1, 1),
            padding=[(p, p) for p in pad],
            dimension_numbers=_conv_dn(2))

    @jax.custom_vjp
    def conv(d, w):
        return plain(d, w)

    def fwd(d, w):
        return plain(d, w), (d, w)

    def bwd(res, g):
        d, w = res
        gd = _pk.conv_bwd_input(g, w, d.shape, pad)
        gw = _pk.conv_bwd_filter(d, g, w.shape, pad)
        return gd.astype(d.dtype), gw.astype(w.dtype)

    conv.defvjp(fwd, bwd)
    return conv(data, weight)


def _conv2d_s2d_strided(data, weight, kernel, pad, groups):
    """Stride-2 2-D conv computed in 2x2 space-to-depth space — exact,
    and the gradient convs become STRIDE-1 (no lhs-dilated dgrad, which
    wastes 3/4 of its MACs multiplying stuffed zeros; the generalization
    of the MLPerf stem trick to every stride-2 conv, same tap algebra as
    models/resnet.convert_stem_to_s2d).

    Per spatial dim (stride 2, kernel k, pad p): input index
    m = 2i + q - p maps tap q to (u, dm) with q = 2(u) + dm + p shifted
    so u ranges [u_min, u_max]; the s2d conv has kernel
    K = u_max - u_min + 1, asymmetric pad (-u_min, u_max), and weight
    w_s2d[o, (c,dh,dw), U, V] = w[o, c, 2(U+u_min_h)+dh+p_h, ...]
    (zero outside [0, k)). Autodiff differentiates straight through the
    reshapes + stride-1 conv, so no custom_vjp is needed.

    Gated by MXNET_CONV_S2D=1 (only stride (2,2), dilate 1, even
    spatial, and kernel in {2*pad+1, 2*pad+2} per dim — the s2d form
    always emits H/2 outputs, which equals the strided conv's count
    only for those 'same'-family shapes; the _convolution gate
    enforces this); numerics pinned in
    tests/test_conv_bwd_layout.py."""
    n, c, h, w = data.shape
    o, cg, kh, kw = weight.shape
    assert all(k in (2 * p + 1, 2 * p + 2)
               for k, p in zip(kernel, pad)), (kernel, pad)

    def dim_map(k, p):
        u_min = (0 - p - ((0 - p) % 2)) // 2
        u_max = (k - 1 - p - ((k - 1 - p) % 2)) // 2
        return u_min, u_max

    uh0, uh1 = dim_map(kh, pad[0])
    uw0, uw1 = dim_map(kw, pad[1])
    K_h, K_w = uh1 - uh0 + 1, uw1 - uw0 + 1

    # s2d input: (N, C, H, W) -> (N, C*4, H/2, W/2), channels (c, dh, dw)
    xs = data.reshape(n, c, h // 2, 2, w // 2, 2)
    xs = jnp.transpose(xs, (0, 1, 3, 5, 2, 4)).reshape(
        n, c * 4, h // 2, w // 2)

    # s2d weight, built by gathering taps (zero outside the kernel):
    # embed w into a zero canvas indexed by q = 2(U+u_min)+dm+p
    qh = 2 * (jnp.arange(K_h)[:, None] + uh0) + jnp.arange(2)[None, :] \
        + pad[0]  # (K_h, dh)
    qw = 2 * (jnp.arange(K_w)[:, None] + uw0) + jnp.arange(2)[None, :] \
        + pad[1]  # (K_w, dw)
    # gather with clamping + mask (jnp.take clamps; mask zeroes OOB taps)
    wh_idx = jnp.clip(qh, 0, kh - 1)
    ww_idx = jnp.clip(qw, 0, kw - 1)
    mask_h = ((qh >= 0) & (qh < kh)).astype(weight.dtype)
    mask_w = ((qw >= 0) & (qw < kw)).astype(weight.dtype)
    # w: (O, C/g, kh, kw) -> (O, C/g, K_h, dh, K_w, dw)
    wg = jnp.take(weight, wh_idx.reshape(-1), axis=2).reshape(
        o, cg, K_h, 2, kw)
    wg = jnp.take(wg, ww_idx.reshape(-1), axis=4).reshape(
        o, cg, K_h, 2, K_w, 2)
    wg = wg * mask_h[None, None, :, :, None, None] \
            * mask_w[None, None, None, None, :, :]
    # -> (O, (c, dh, dw), K_h, K_w) matching the input channel order
    ws = jnp.transpose(wg, (0, 1, 3, 5, 2, 4)).reshape(
        o, cg * 4, K_h, K_w)

    return jax.lax.conv_general_dilated(
        xs, ws, window_strides=(1, 1),
        padding=[(-uh0, uh1), (-uw0, uw1)],
        dimension_numbers=_conv_dn(2), feature_group_count=groups)


def _convolution(attrs, ins, is_train):
    kernel, stride, dilate, pad = _conv_dims(attrs)
    nd = len(kernel)
    groups = int(attrs.get("num_group", 1))
    data, weight = ins[0], ins[1]
    if (nd == 2 and os.environ.get("MXNET_CONV_S2D") == "1"
            and tuple(stride) == (2, 2) and tuple(dilate) == (1, 1)
            and data.shape[2] % 2 == 0 and data.shape[3] % 2 == 0
            and tuple(kernel) == (1, 1) and tuple(pad) == (0, 0)):
        # 1x1/s2: strided SLICE + dense 1x1 conv. The s2d canvas form
        # would 4x the dense MACs (masked zero channels are traced
        # values XLA can't prune); slicing keeps fwd/wgrad dense-sized
        # and the dgrad becomes slice-transpose (a cheap zero-pad
        # scatter) instead of an lhs-dilated conv.
        out = jax.lax.conv_general_dilated(
            data[:, :, ::2, ::2], weight, window_strides=(1, 1),
            padding=[(0, 0), (0, 0)], dimension_numbers=_conv_dn(2),
            feature_group_count=groups)
    elif (nd == 2 and os.environ.get("MXNET_CONV_S2D") == "1"
            and tuple(stride) == (2, 2) and tuple(dilate) == (1, 1)
            and data.shape[2] % 2 == 0 and data.shape[3] % 2 == 0
            and max(kernel) > 1
            # the s2d form emits exactly H/2 outputs per dim, which
            # matches the strided conv only for 'same'-family shapes
            # (k == 2p+1 or 2p+2); others (e.g. 3x3/s2/p0 inception
            # reductions) fall back to the default lowering
            and all(k in (2 * p + 1, 2 * p + 2)
                    for k, p in zip(kernel, pad))):
        out = _conv2d_s2d_strided(data, weight, kernel, pad, groups)
    elif (nd == 2
            and _pallas_conv_plan(data, weight, stride, pad, dilate,
                                  groups) is not None):
        # MXTPU_CONV_KERNEL=pallas and this shape is inside the tuned
        # envelope: gradient convs go through the Pallas pair.
        # Out-of-envelope shapes fall through — to the taps/patches
        # levers if also set, else XLA's default gradient lowering.
        out = _conv2d_pallas_bwd(data, weight, pad)
    elif nd == 2 and os.environ.get("MXNET_CONV_BWD_LAYOUT") == "NHWC":
        out = _conv2d_bwd_nhwc(data, weight, stride, pad, dilate, groups)
    elif (nd == 2 and os.environ.get("MXNET_CONV_WGRAD") == "patches"
            and groups == 1):
        out = _conv2d_wgrad_patches(data, weight, stride, pad, dilate)
    elif (nd == 2 and os.environ.get("MXNET_CONV_WGRAD") == "taps"
            and groups == 1):
        out = _conv2d_wgrad_taps(data, weight, stride, pad, dilate)
    else:
        # NOTE: no preferred_element_type here — the MXU accumulates bf16
        # matmuls in fp32 natively, and an explicit f32 output + cast
        # breaks lax's conv transpose rules under bf16 (mixed-dtype
        # cotangent)
        out = jax.lax.conv_general_dilated(
            data,
            weight,
            window_strides=stride,
            padding=[(p, p) for p in pad],
            rhs_dilation=dilate,
            dimension_numbers=_conv_dn(nd),
            feature_group_count=groups,
        )
    if not bool(attrs.get("no_bias", False)):
        bias = ins[2].reshape((1, -1) + (1,) * nd)
        out = out + bias
    return [out]


def _conv_infer(attrs, in_shapes):
    kernel, stride, dilate, pad = _conv_dims(attrs)
    nd = len(kernel)
    nf = int(attrs["num_filter"])
    groups = int(attrs.get("num_group", 1))
    no_bias = bool(attrs.get("no_bias", False))
    dshape = in_shapes[0]
    if dshape is None:
        raise MXNetError("Convolution: data shape required")
    if len(dshape) != nd + 2:
        raise MXNetError("Convolution: data must be %dD, got %s" % (nd + 2, (dshape,)))
    c = dshape[1]
    wshape = (nf, c // groups) + kernel
    out_sp = tuple(
        (dshape[2 + i] + 2 * pad[i] - (dilate[i] * (kernel[i] - 1) + 1)) // stride[i]
        + 1
        for i in range(nd)
    )
    oshape = (dshape[0], nf) + out_sp
    shapes = [tuple(dshape), wshape] + ([] if no_bias else [(nf,)])
    return shapes, [oshape], []


_conv = OpDef(
    "Convolution",
    _convolution,
    arguments=("data", "weight", "bias"),
    defaults={
        "kernel": (1, 1),
        "stride": None,
        "dilate": None,
        "pad": None,
        "num_filter": 1,
        "num_group": 1,
        "no_bias": False,
        "workspace": 1024,
        "cudnn_tune": None,
        "cudnn_off": False,
        "layout": None,
    },
    infer_shape=_conv_infer,
)
_conv.list_arguments = lambda attrs=None: (
    ["data", "weight"]
    if (attrs or {}).get("no_bias")
    else ["data", "weight", "bias"]
)
register(_conv)
from .registry import _REGISTRY as _R

_R["Convolution_v1"] = _conv  # reference keeps the pre-NNVM name alive


def _deconvolution(attrs, ins, is_train):
    kernel, stride, dilate, pad = _conv_dims(attrs)
    nd = len(kernel)
    groups = int(attrs.get("num_group", 1))
    adj = as_tuple(attrs.get("adj") or (0,) * nd, nd, "adj")
    data, weight = ins[0], ins[1]
    # Transposed conv = gradient of conv wrt its input: lhs-dilated conv with
    # flipped kernel (weight layout (C_in, C_out/g, *K) as in the reference).
    # Expressed directly as the transpose of a strided conv: an
    # lhs-dilated conv_general_dilated with the spatially-flipped,
    # in/out-swapped kernel. (lax.conv_transpose lacks group support and
    # its transpose_kernel path fails to differentiate in current jax.)
    c_in = weight.shape[0]
    c_out_g = weight.shape[1]
    # (C_in, C_out/g, *K) -> (C_out, C_in/g, *K)
    w = weight.reshape((groups, c_in // groups, c_out_g) + kernel)
    w = jnp.swapaxes(w, 1, 2).reshape(
        (groups * c_out_g, c_in // groups) + kernel)
    w = jnp.flip(w, axis=tuple(range(2, 2 + nd)))
    k_eff = tuple((k - 1) * d + 1 for k, d in zip(kernel, dilate))
    out = jax.lax.conv_general_dilated(
        data,
        w,
        window_strides=(1,) * nd,
        padding=[(ke - 1 - p, ke - 1 - p + a)
                 for ke, p, a in zip(k_eff, pad, adj)],
        lhs_dilation=stride,
        rhs_dilation=dilate,
        dimension_numbers=_conv_dn(nd),
        feature_group_count=groups,
    )
    if not bool(attrs.get("no_bias", True)):
        out = out + ins[2].reshape((1, -1) + (1,) * nd)
    return [out]


def _deconv_infer(attrs, in_shapes):
    kernel, stride, dilate, pad = _conv_dims(attrs)
    nd = len(kernel)
    nf = int(attrs["num_filter"])
    groups = int(attrs.get("num_group", 1))
    no_bias = bool(attrs.get("no_bias", True))
    adj = as_tuple(attrs.get("adj") or (0,) * nd, nd, "adj")
    dshape = in_shapes[0]
    c = dshape[1]
    wshape = (c, nf // groups) + kernel
    out_sp = tuple(
        stride[i] * (dshape[2 + i] - 1)
        + (dilate[i] * (kernel[i] - 1) + 1)
        - 2 * pad[i]
        + adj[i]
        for i in range(nd)
    )
    oshape = (dshape[0], nf) + out_sp
    shapes = [tuple(dshape), wshape] + ([] if no_bias else [(nf,)])
    return shapes, [oshape], []


_deconv = OpDef(
    "Deconvolution",
    _deconvolution,
    arguments=("data", "weight", "bias"),
    defaults={
        "kernel": (1, 1),
        "stride": None,
        "dilate": None,
        "pad": None,
        "adj": None,
        "target_shape": None,
        "num_filter": 1,
        "num_group": 1,
        "no_bias": True,
        "workspace": 512,
    },
    infer_shape=_deconv_infer,
)
_deconv.list_arguments = lambda attrs=None: (
    ["data", "weight"]
    if (attrs or {}).get("no_bias", True)
    else ["data", "weight", "bias"]
)
register(_deconv)


# --------------------------------------------------------------------------
# Pooling — reference pooling-inl.h; lax.reduce_window
# --------------------------------------------------------------------------
def _pool_out_dim(x, k, s, p, convention):
    if convention == "full":
        return int(np.ceil(float(x + 2 * p - k) / s)) + 1
    return (x + 2 * p - k) // s + 1


def _pooling(attrs, ins, is_train):
    data = ins[0]
    nd = data.ndim - 2
    global_pool = bool(attrs.get("global_pool", False))
    if global_pool:
        kernel = data.shape[2:]
        stride = (1,) * nd
        pad = (0,) * nd
    else:
        kernel = as_tuple(attrs["kernel"])
        stride = as_tuple(attrs.get("stride") or (1,) * nd, nd, "stride")
        pad = as_tuple(attrs.get("pad") or (0,) * nd, nd, "pad")
    ptype = attrs.get("pool_type", "max")
    window = (1, 1) + kernel
    strides = (1, 1) + stride
    # "full" convention (ceil output size): extend the high-side pad so
    # reduce_window emits ceil((x+2p-k)/s)+1 windows; the avg divisor
    # below only counts in-bounds elements so border windows stay exact.
    hi_extra = (0,) * nd
    if not global_pool and attrs.get("pooling_convention", "valid") == "full":
        hi_extra = tuple(
            max(0, (_pool_out_dim(data.shape[2 + i], kernel[i], stride[i],
                                  pad[i], "full") - 1) * stride[i]
                + kernel[i] - (data.shape[2 + i] + 2 * pad[i]))
            for i in range(nd)
        )
    padding = ((0, 0), (0, 0)) + tuple(
        (p, p + e) for p, e in zip(pad, hi_extra))
    # init values MUST be python scalars: a traced init keeps XLA from
    # recognizing the differentiable reduce_window_max/add patterns and
    # vjp-under-jit fails to linearize.
    if ptype == "max":
        if jnp.issubdtype(data.dtype, jnp.floating):
            init = -float(np.inf)
        else:
            init = int(np.iinfo(np.dtype(data.dtype)).min)
        out = jax.lax.reduce_window(
            data, init, jax.lax.max, window, strides, padding
        )
    elif ptype in ("avg", "sum"):
        zero = 0.0 if jnp.issubdtype(data.dtype, jnp.floating) else 0
        out = jax.lax.reduce_window(
            data, zero, jax.lax.add, window, strides, padding
        )
        if ptype == "avg":
            # divisor = window area clipped to the PADDED extent
            # (reference pool.h pool_sum_2d_cpu: pool_size uses
            # hend=min(hstart+k, H+pad) before clipping to real bounds,
            # i.e. padding counts toward the average, but the "full"
            # convention's extra high-side extension does not)
            cdt = data.dtype if jnp.issubdtype(data.dtype, jnp.floating) \
                else jnp.float32
            ones = jnp.ones(
                tuple(data.shape[2 + i] + 2 * pad[i] for i in range(nd)), cdt)
            counts = jax.lax.reduce_window(
                ones, 0.0, jax.lax.add, kernel, stride,
                tuple((0, e) for e in hi_extra)
            )
            out = (out / counts).astype(data.dtype)
    else:
        raise MXNetError("Pooling: unknown pool_type %s" % ptype)
    return [out]


def _pooling_infer(attrs, in_shapes):
    dshape = in_shapes[0]
    nd = len(dshape) - 2
    if bool(attrs.get("global_pool", False)):
        return [tuple(dshape)], [tuple(dshape[:2]) + (1,) * nd], []
    kernel = as_tuple(attrs["kernel"])
    stride = as_tuple(attrs.get("stride") or (1,) * nd, nd, "stride")
    pad = as_tuple(attrs.get("pad") or (0,) * nd, nd, "pad")
    conv = attrs.get("pooling_convention", "valid")
    out_sp = tuple(
        _pool_out_dim(dshape[2 + i], kernel[i], stride[i], pad[i], conv)
        for i in range(nd)
    )
    return [tuple(dshape)], [tuple(dshape[:2]) + out_sp], []


register(
    OpDef(
        "Pooling",
        _pooling,
        arguments=("data",),
        defaults={
            "kernel": (1, 1),
            "stride": None,
            "pad": None,
            "pool_type": "max",
            "global_pool": False,
            "pooling_convention": "valid",
            "cudnn_off": False,
        },
        infer_shape=_pooling_infer,
        aliases=("Pooling_v1",),
    )
)


# --------------------------------------------------------------------------
# BatchNorm — reference batch_norm-inl.h. aux: moving_mean/moving_var;
# outputs (output, save_mean, save_var) with 1 visible. Per-replica stats
# (no cross-replica sync) to match reference convergence (SURVEY.md §7).
#
# The training path is a custom_vjp core tuned from a v5e device trace:
# autodiff through the two-pass stats formulation cost 27.5 ms of a
# 110 ms ResNet-50 b256 step (25%). The core does one-pass stats
# (sum / sum-of-squares in a single multi-output reduce over the bf16
# input with f32 accumulation) and a closed-form backward (one fused
# (sum(dy), sum(dy*xhat)) reduce + one dx pass), which is the minimum
# HBM traffic without a persistent kernel.
# --------------------------------------------------------------------------
def _bn_reduce_axes(ndim):
    return tuple(i for i in range(ndim) if i != 1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _bn_train_core(x, gamma, beta, eps):
    y, mean, var, _rstd = _bn_train_fwd_math(x, gamma, beta, eps)
    return y, mean, var


def _bn_train_fwd_math(x, gamma, beta, eps):
    ax = _bn_reduce_axes(x.ndim)
    bshape = (1, -1) + (1,) * (x.ndim - 2)
    n = x.size // x.shape[1]
    x32 = x.astype(jnp.float32)
    # two reduces over one operand: XLA fuses into a single pass
    s1 = jnp.sum(x32, axis=ax)
    s2 = jnp.sum(x32 * x32, axis=ax)
    mean = s1 / n
    # E[x^2] - mean^2; clamp tiny negative cancellation residue
    var = jnp.maximum(s2 / n - mean * mean, 0.0)
    rstd = jax.lax.rsqrt(var + eps)
    scale = (gamma.astype(jnp.float32) * rstd).reshape(bshape)
    shift = (beta.astype(jnp.float32)
             - gamma.astype(jnp.float32) * rstd * mean).reshape(bshape)
    y = (x32 * scale + shift).astype(x.dtype)
    return y, mean, var, rstd


def _bn_core_fwd(x, gamma, beta, eps):
    # symbolic_zeros=True wraps primals in CustomVJPPrimal(.value,
    # .perturbed); unwrap before doing math
    x, gamma, beta = x.value, gamma.value, beta.value
    y, mean, var, rstd = _bn_train_fwd_math(x, gamma, beta, eps)
    return (y, mean, var), (x, gamma, mean, rstd)


def _bn_core_bwd(eps, res, cts):
    from jax.custom_derivatives import SymbolicZero

    dy, dmean, dvar = cts
    x, gamma, mean, rstd = res
    ax = _bn_reduce_axes(x.ndim)
    bshape = (1, -1) + (1,) * (x.ndim - 2)
    n = x.size // x.shape[1]
    g32 = gamma.astype(jnp.float32)
    x32 = x.astype(jnp.float32)
    if isinstance(dy, SymbolicZero):
        dx32 = jnp.zeros(x.shape, jnp.float32)
        dgamma = jnp.zeros(gamma.shape, jnp.float32)
        dbeta = jnp.zeros(gamma.shape, jnp.float32)
    else:
        dy32 = dy.astype(jnp.float32)
        xhat = (x32 - mean.reshape(bshape)) * rstd.reshape(bshape)
        # one fused two-output reduce over (dy, x)
        dbeta = jnp.sum(dy32, axis=ax)
        dgamma = jnp.sum(dy32 * xhat, axis=ax)
        dx32 = (g32 * rstd).reshape(bshape) * (
            dy32 - (dbeta / n).reshape(bshape)
            - xhat * (dgamma / n).reshape(bshape)
        )
    # mean/var cotangent terms: mean/var ARE graph outputs, but in the
    # training step they feed only the (non-differentiated) moving-stat
    # aux updates, so their cotangents are SYMBOLIC zeros — skipping the
    # terms at trace time removes a whole extra pass over the
    # activations (~16ms of a 96ms ResNet-50 b256 step on v5e: the
    # add_any accumulations and the dvar*x re-read do real HBM traffic
    # even when the incoming cotangent arrays are all-zero at runtime).
    if not isinstance(dmean, SymbolicZero):
        dx32 = dx32 + (dmean / n).reshape(bshape).astype(jnp.float32)
    if not isinstance(dvar, SymbolicZero):
        dx32 = dx32 + (
            dvar.reshape(bshape).astype(jnp.float32)
            * 2.0 / n * (x32 - mean.reshape(bshape))
        )
    return (dx32.astype(x.dtype), dgamma.astype(gamma.dtype),
            dbeta.astype(gamma.dtype))


_bn_train_core.defvjp(_bn_core_fwd, _bn_core_bwd, symbolic_zeros=True)


def _batch_norm(attrs, ins, is_train):
    data, gamma, beta, moving_mean, moving_var = ins
    eps = float(attrs.get("eps", 1e-3))
    momentum = float(attrs.get("momentum", 0.9))
    fix_gamma = bool(attrs.get("fix_gamma", True))
    use_global = bool(attrs.get("use_global_stats", False)) or not is_train
    ax = tuple(i for i in range(data.ndim) if i != 1)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    if fix_gamma:
        gamma = jnp.ones_like(gamma) + jax.lax.stop_gradient(gamma * 0)
    if use_global:
        mean, var = moving_mean, moving_var
        new_mean, new_var = moving_mean, moving_var
        out = (data - mean.reshape(bshape)) * jax.lax.rsqrt(
            var.reshape(bshape) + eps
        ) * gamma.reshape(bshape) + beta.reshape(bshape)
        # normalize in the STATS dtype (f32 moving stats) but return the
        # input's dtype: a bf16 graph's inference BN must not upcast the
        # activation stream — the next conv would see (f32, bf16) and
        # type inference already promised it data.dtype
        out = out.astype(data.dtype)
    else:
        out, mean, var = _bn_train_core(data, gamma, beta, eps)
        new_mean = momentum * moving_mean + (1.0 - momentum) * mean.astype(
            moving_mean.dtype
        )
        new_var = momentum * moving_var + (1.0 - momentum) * var.astype(
            moving_var.dtype
        )
    return [out, mean.astype(jnp.float32), var.astype(jnp.float32), new_mean, new_var]


def _bn_infer(attrs, in_shapes):
    dshape = in_shapes[0]
    if dshape is None:
        raise MXNetError("BatchNorm: data shape required")
    c = (dshape[1],)
    return (
        [tuple(dshape), c, c],
        [tuple(dshape), c, c],
        [c, c],
    )


_bn = OpDef(
    "BatchNorm",
    _batch_norm,
    arguments=("data", "gamma", "beta"),
    outputs=("output", "mean", "var"),
    aux=("moving_mean", "moving_var"),
    defaults={
        "eps": 1e-3,
        "momentum": 0.9,
        "fix_gamma": True,
        "use_global_stats": False,
        "output_mean_var": False,
    },
    infer_shape=_bn_infer,
    aliases=("CuDNNBatchNorm",),
)
_bn._num_visible_outputs = 1
register(_bn)


# --------------------------------------------------------------------------
# InstanceNorm / L2Normalization / LRN
# --------------------------------------------------------------------------
def _instance_norm(attrs, ins, is_train):
    data, gamma, beta = ins
    eps = float(attrs.get("eps", 1e-3))
    ax = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=ax, keepdims=True)
    var = jnp.mean(jnp.square(data - mean), axis=ax, keepdims=True)
    bshape = (1, -1) + (1,) * (data.ndim - 2)
    return [
        (data - mean) * jax.lax.rsqrt(var + eps) * gamma.reshape(bshape)
        + beta.reshape(bshape)
    ]


register(
    OpDef(
        "InstanceNorm",
        _instance_norm,
        arguments=("data", "gamma", "beta"),
        defaults={"eps": 1e-3},
        infer_shape=lambda attrs, in_shapes: (
            [tuple(in_shapes[0]), (in_shapes[0][1],), (in_shapes[0][1],)],
            [tuple(in_shapes[0])],
            [],
        ),
    )
)


def _l2_normalization(attrs, ins, is_train):
    data = ins[0]
    eps = float(attrs.get("eps", 1e-10))
    mode = attrs.get("mode", "instance")
    if mode == "instance":
        ax = tuple(range(1, data.ndim))
    elif mode == "channel":
        ax = (1,)
    elif mode == "spatial":
        ax = tuple(range(2, data.ndim))
    else:
        raise MXNetError("L2Normalization: unknown mode %s" % mode)
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=ax, keepdims=True) + eps)
    return [data / norm]


register(
    OpDef(
        "L2Normalization",
        _l2_normalization,
        arguments=("data",),
        defaults={"eps": 1e-10, "mode": "instance"},
        infer_shape=same_shape_infer(1),
    )
)


def _lrn(attrs, ins, is_train):
    x = ins[0]
    nsize = int(attrs.get("nsize", 5))
    alpha = float(attrs.get("alpha", 1e-4))
    beta = float(attrs.get("beta", 0.75))
    knorm = float(attrs.get("knorm", 2.0))
    sq = jnp.square(x)
    half = nsize // 2
    pad = [(0, 0), (half, half)] + [(0, 0)] * (x.ndim - 2)
    sq_pad = jnp.pad(sq, pad)
    window = jnp.stack(
        [sq_pad[:, i : i + x.shape[1]] for i in range(nsize)], axis=0
    ).sum(axis=0)
    return [x * jnp.power(knorm + (alpha / nsize) * window, -beta)]


register(
    OpDef(
        "LRN",
        _lrn,
        arguments=("data",),
        defaults={"nsize": 5, "alpha": 1e-4, "beta": 0.75, "knorm": 2.0},
        infer_shape=same_shape_infer(1),
    )
)


# --------------------------------------------------------------------------
# Dropout — reference dropout-inl.h (scale-at-train, identity at eval)
# --------------------------------------------------------------------------
def _dropout(attrs, ins, is_train):
    p = float(attrs.get("p", 0.5))
    if not is_train or p <= 0.0:
        return [ins[0]]
    key = attrs["__rng__"]
    keep = 1.0 - p
    mask = jax.random.bernoulli(key, keep, ins[0].shape)
    return [jnp.where(mask, ins[0] / keep, jnp.zeros_like(ins[0]))]


register(
    OpDef(
        "Dropout",
        _dropout,
        arguments=("data",),
        defaults={"p": 0.5, "mode": "training"},
        infer_shape=same_shape_infer(1),
        needs_rng=True,
    )
)


# --------------------------------------------------------------------------
# softmax / log_softmax / SoftmaxActivation
# --------------------------------------------------------------------------
register(
    OpDef(
        "softmax",
        lambda attrs, ins, is_train: [
            jax.nn.softmax(ins[0], axis=int(attrs.get("axis", -1)))
        ],
        arguments=("data",),
        defaults={"axis": -1, "temperature": None},
        infer_shape=same_shape_infer(1),
    )
)
register(
    OpDef(
        "log_softmax",
        lambda attrs, ins, is_train: [
            jax.nn.log_softmax(ins[0], axis=int(attrs.get("axis", -1)))
        ],
        arguments=("data",),
        defaults={"axis": -1, "temperature": None},
        infer_shape=same_shape_infer(1),
    )
)
register(
    OpDef(
        "SoftmaxActivation",
        lambda attrs, ins, is_train: [
            jax.nn.softmax(ins[0], axis=1)
            if attrs.get("mode", "instance") == "channel"
            else jax.nn.softmax(
                ins[0].reshape(ins[0].shape[0], -1), axis=-1
            ).reshape(ins[0].shape)
        ],
        arguments=("data",),
        defaults={"mode": "instance"},
        infer_shape=same_shape_infer(1),
    )
)


# --------------------------------------------------------------------------
# SoftmaxOutput and friends — loss heads with reference backward semantics
# --------------------------------------------------------------------------
def _normalize_grad(grad, label, attrs, valid_mask=None):
    normalization = attrs.get("normalization", "null")
    if normalization == "batch":
        grad = grad / label.shape[0]
    elif normalization == "valid" and valid_mask is not None:
        grad = grad / jnp.maximum(valid_mask.sum(), 1.0)
    elif normalization == "valid":
        grad = grad / float(np.prod(label.shape))
    return grad


@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _softmax_output_core(data, label, attr_key):
    attrs = dict(attr_key)
    if attrs.get("multi_output") and data.ndim > 2:
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data, axis=-1)


def _softmax_output_fwd(data, label, attr_key):
    out = _softmax_output_core(data, label, attr_key)
    return out, (out, label)


def _softmax_output_bwd(attr_key, res, g):
    # Reference contract: backward ignores the head gradient entirely
    # (softmax_output-inl.h Backward). g is unused by design.
    out, label = res
    attrs = dict(attr_key)
    grad_scale = float(attrs.get("grad_scale", 1.0))
    use_ignore = bool(attrs.get("use_ignore", False))
    ignore_label = float(attrs.get("ignore_label", -1.0))
    multi = bool(attrs.get("multi_output", False)) and out.ndim > 2
    axis = 1 if multi else -1
    depth = out.shape[axis]
    lbl = label.astype(jnp.int32)
    onehot = jax.nn.one_hot(lbl, depth, dtype=out.dtype)
    if multi:
        # label (n, d1...) → put class axis at 1
        onehot = jnp.moveaxis(onehot, -1, 1)
    grad = out - onehot
    valid = None
    if use_ignore:
        mask = (label != ignore_label).astype(out.dtype)
        valid = mask
        grad = grad * jnp.expand_dims(mask, axis=axis)
    grad = _normalize_grad(grad * grad_scale, label, attrs, valid)
    return grad.astype(out.dtype), jnp.zeros_like(label)


_softmax_output_core.defvjp(_softmax_output_fwd, _softmax_output_bwd)


def _softmax_output(attrs, ins, is_train):
    attr_key = tuple(
        sorted((k, v) for k, v in attrs.items() if not k.startswith("__") and not isinstance(v, jax.Array))
    )
    return [_softmax_output_core(ins[0], ins[1], attr_key)]


def _softmax_output_infer(attrs, in_shapes):
    dshape = in_shapes[0]
    if dshape is None:
        raise MXNetError("SoftmaxOutput: data shape required")
    if attrs.get("multi_output") and len(dshape) > 2:
        lshape = (dshape[0],) + tuple(dshape[2:])
    else:
        lshape = tuple(dshape[:-1]) if len(dshape) > 1 else (dshape[0],)
    return [tuple(dshape), lshape], [tuple(dshape)], []


register(
    OpDef(
        "SoftmaxOutput",
        _softmax_output,
        arguments=("data", "label"),
        defaults={
            "grad_scale": 1.0,
            "ignore_label": -1.0,
            "use_ignore": False,
            "multi_output": False,
            "normalization": "null",
            "preserve_shape": False,
            "out_grad": False,
        },
        infer_shape=_softmax_output_infer,
        need_top_grad=False,
        aliases=("Softmax",),
    )
)


def _make_output_op(name, bwd_fn, act=lambda x: x):
    """Regression output heads (linear/logistic/MAE) — backward ignores the
    head gradient, grad = bwd_fn(out, label) * grad_scale / batch."""

    @functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
    def core(data, label, grad_scale):
        return act(data)

    def fwd(data, label, grad_scale):
        out = core(data, label, grad_scale)
        return out, (out, label)

    def bwd(grad_scale, res, g):
        out, label = res
        n = float(np.prod(out.shape[1:])) if out.ndim > 1 else 1.0
        grad = bwd_fn(out, label.reshape(out.shape)) * (grad_scale / n)
        return grad.astype(out.dtype), jnp.zeros_like(label)

    core.defvjp(fwd, bwd)

    def fcompute(attrs, ins, is_train):
        return [core(ins[0], ins[1], float(attrs.get("grad_scale", 1.0)))]

    register(
        OpDef(
            name,
            fcompute,
            arguments=("data", "label"),
            defaults={"grad_scale": 1.0},
            infer_shape=lambda attrs, in_shapes: (
                [tuple(in_shapes[0]), tuple(in_shapes[0])],
                [tuple(in_shapes[0])],
                [],
            ),
            need_top_grad=False,
        )
    )


_make_output_op("LinearRegressionOutput", lambda o, l: o - l)
_make_output_op(
    "LogisticRegressionOutput", lambda o, l: o - l, act=jax.nn.sigmoid
)
_make_output_op("MAERegressionOutput", lambda o, l: jnp.sign(o - l))


# SVMOutput — reference svm_output-inl.h: hinge loss gradients
def _svm_output(attrs, ins, is_train):
    margin = float(attrs.get("margin", 1.0))
    reg = float(attrs.get("regularization_coefficient", 1.0))
    use_linear = bool(attrs.get("use_linear", False))

    @functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4))
    def core(data, label, margin, reg, use_linear):
        return data

    def fwd(data, label, margin, reg, use_linear):
        return data, (data, label)

    def bwd(margin, reg, use_linear, res, g):
        data, label = res
        lbl = label.astype(jnp.int32)
        onehot = jax.nn.one_hot(lbl, data.shape[-1], dtype=data.dtype)
        sign = 2.0 * onehot - 1.0  # +1 at true class, -1 elsewhere
        viol = (margin - sign * data) > 0
        if use_linear:
            grad = jnp.where(viol, -sign * reg, 0.0)
        else:
            grad = jnp.where(viol, -2.0 * (margin - sign * data) * sign * reg, 0.0)
        return grad.astype(data.dtype), jnp.zeros_like(label)

    core.defvjp(fwd, bwd)
    return [core(ins[0], ins[1], margin, reg, use_linear)]


register(
    OpDef(
        "SVMOutput",
        _svm_output,
        arguments=("data", "label"),
        defaults={
            "margin": 1.0,
            "regularization_coefficient": 1.0,
            "use_linear": False,
        },
        infer_shape=lambda attrs, in_shapes: (
            [tuple(in_shapes[0]), (in_shapes[0][0],)],
            [tuple(in_shapes[0])],
            [],
        ),
        need_top_grad=False,
    )
)


# MakeLoss layer — reference make_loss-inl.h
def _make_loss(attrs, ins, is_train):
    grad_scale = float(attrs.get("grad_scale", 1.0))
    normalization = attrs.get("normalization", "null")

    @functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2))
    def core(data, gs, norm):
        return data

    def fwd(data, gs, norm):
        return data, data

    def bwd(gs, norm, res, g):
        data = res
        scale = gs
        if norm == "batch":
            scale = gs / data.shape[0]
        return (jnp.full(data.shape, scale, data.dtype),)

    core.defvjp(fwd, bwd)
    return [core(ins[0], grad_scale, normalization)]


register(
    OpDef(
        "MakeLoss",
        _make_loss,
        arguments=("data",),
        defaults={"grad_scale": 1.0, "valid_thresh": 0.0, "normalization": "null"},
        infer_shape=same_shape_infer(1),
        need_top_grad=False,
    )
)


# softmax_cross_entropy — reference loss_binary_op.cc
def _softmax_cross_entropy(attrs, ins, is_train):
    data, label = ins
    logp = jax.nn.log_softmax(data, axis=-1)
    lbl = label.astype(jnp.int32)
    picked = jnp.take_along_axis(logp, lbl[:, None], axis=-1)
    return [-jnp.sum(picked).reshape(1)]


register(
    OpDef(
        "softmax_cross_entropy",
        _softmax_cross_entropy,
        arguments=("data", "label"),
        infer_shape=lambda attrs, in_shapes: (
            [tuple(in_shapes[0]), (in_shapes[0][0],)],
            [(1,)],
            [],
        ),
    )
)


# --------------------------------------------------------------------------
# UpSampling — reference upsampling-inl.h (nearest; bilinear via Deconvolution)
# --------------------------------------------------------------------------
def _upsampling(attrs, ins, is_train):
    scale = int(attrs["scale"])
    sample_type = attrs.get("sample_type", "nearest")
    if sample_type == "nearest":
        outs = []
        target = None
        for x in ins:
            h, w = x.shape[2], x.shape[3]
            if target is None:
                target = (h * scale, w * scale)
            s = target[0] // h
            up = jnp.repeat(jnp.repeat(x, s, axis=2), s, axis=3)
            outs.append(up)
        return [jnp.concatenate(outs, axis=1) if len(outs) > 1 else outs[0]]
    # bilinear: single input + weight, implemented via resize
    x = ins[0]
    out = jax.image.resize(
        x,
        (x.shape[0], x.shape[1], x.shape[2] * scale, x.shape[3] * scale),
        method="bilinear",
    )
    return [out]


def _upsampling_infer(attrs, in_shapes):
    scale = int(attrs["scale"])
    sample_type = attrs.get("sample_type", "nearest")
    d0 = in_shapes[0]
    if sample_type == "bilinear":
        nf = int(attrs.get("num_filter", d0[1]))
        kernel = 2 * scale - scale % 2
        wshape = (d0[1], 1, kernel, kernel)
        return (
            [tuple(d0), wshape],
            [(d0[0], d0[1], d0[2] * scale, d0[3] * scale)],
            [],
        )
    c = sum(s[1] for s in in_shapes)
    return (
        [tuple(s) for s in in_shapes],
        [(d0[0], c, d0[2] * scale, d0[3] * scale)],
        [],
    )


_ups = OpDef(
    "UpSampling",
    _upsampling,
    arguments=("data",),
    key_var_num_args="num_args",
    defaults={
        "scale": 1,
        "num_filter": 0,
        "sample_type": "nearest",
        "multi_input_mode": "concat",
        "num_args": 1,
        "workspace": 512,
    },
    infer_shape=_upsampling_infer,
)
register(_ups)


# --------------------------------------------------------------------------
# Sequence ops — reference sequence_last/mask/reverse-inl.h
# (TDNC layout: (seq_len, batch, ...))
# --------------------------------------------------------------------------
def _seq_lengths(attrs, ins, maxlen, batch):
    if bool(attrs.get("use_sequence_length", False)) and len(ins) > 1:
        return ins[1].astype(jnp.int32)
    return jnp.full((batch,), maxlen, jnp.int32)


def _sequence_last(attrs, ins, is_train):
    data = ins[0]
    lengths = _seq_lengths(attrs, ins, data.shape[0], data.shape[1])
    idx = jnp.maximum(lengths - 1, 0)
    return [jnp.take_along_axis(
        data, idx.reshape((1, -1) + (1,) * (data.ndim - 2)), axis=0
    )[0]]


_seq_last = OpDef(
    "SequenceLast",
    _sequence_last,
    arguments=("data", "sequence_length"),
    defaults={"use_sequence_length": False},
    infer_shape=lambda attrs, in_shapes: (
        [tuple(s) for s in in_shapes if s is not None],
        [tuple(in_shapes[0][1:])],
        [],
    ),
)
_seq_last.list_arguments = lambda attrs=None: (
    ["data", "sequence_length"]
    if (attrs or {}).get("use_sequence_length")
    else ["data"]
)
register(_seq_last)


def _sequence_mask(attrs, ins, is_train):
    data = ins[0]
    value = float(attrs.get("value", 0.0))
    lengths = _seq_lengths(attrs, ins, data.shape[0], data.shape[1])
    t = jnp.arange(data.shape[0])[:, None]
    mask = t < lengths[None, :]
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return [jnp.where(mask, data, jnp.asarray(value, data.dtype))]


_seq_mask = OpDef(
    "SequenceMask",
    _sequence_mask,
    arguments=("data", "sequence_length"),
    defaults={"use_sequence_length": False, "value": 0.0},
    infer_shape=lambda attrs, in_shapes: (
        [tuple(s) for s in in_shapes if s is not None],
        [tuple(in_shapes[0])],
        [],
    ),
)
_seq_mask.list_arguments = _seq_last.list_arguments
register(_seq_mask)


def _sequence_reverse(attrs, ins, is_train):
    data = ins[0]
    lengths = _seq_lengths(attrs, ins, data.shape[0], data.shape[1])
    maxlen = data.shape[0]
    t = jnp.arange(maxlen)[:, None]
    src = jnp.where(t < lengths[None, :], lengths[None, :] - 1 - t, t)
    return [jnp.take_along_axis(
        data, src.reshape(src.shape + (1,) * (data.ndim - 2)), axis=0
    )]


_seq_rev = OpDef(
    "SequenceReverse",
    _sequence_reverse,
    arguments=("data", "sequence_length"),
    defaults={"use_sequence_length": False},
    infer_shape=lambda attrs, in_shapes: (
        [tuple(s) for s in in_shapes if s is not None],
        [tuple(in_shapes[0])],
        [],
    ),
)
_seq_rev.list_arguments = _seq_last.list_arguments
register(_seq_rev)


# --------------------------------------------------------------------------
# Crop layer (reference crop-inl.h) — crop first input to match second (or
# h_w attr), offset-based
# --------------------------------------------------------------------------
def _crop(attrs, ins, is_train):
    x = ins[0]
    if len(ins) > 1:
        th, tw = ins[1].shape[2], ins[1].shape[3]
    else:
        th, tw = as_tuple(attrs["h_w"], 2, "h_w")
    if bool(attrs.get("center_crop", False)):
        oy = (x.shape[2] - th) // 2
        ox = (x.shape[3] - tw) // 2
    else:
        oy, ox = as_tuple(attrs.get("offset", (0, 0)), 2, "offset")
    return [x[:, :, oy : oy + th, ox : ox + tw]]


def _crop_infer(attrs, in_shapes):
    d0 = in_shapes[0]
    if int(attrs.get("num_args", 1)) > 1 and len(in_shapes) > 1 and in_shapes[1]:
        th, tw = in_shapes[1][2], in_shapes[1][3]
    else:
        th, tw = as_tuple(attrs["h_w"], 2, "h_w")
    return (
        [tuple(s) for s in in_shapes],
        [(d0[0], d0[1], th, tw)],
        [],
    )


register(
    OpDef(
        "Crop",
        _crop,
        arguments=("data",),
        key_var_num_args="num_args",
        defaults={"num_args": 1, "offset": (0, 0), "h_w": (0, 0), "center_crop": False},
        infer_shape=_crop_infer,
    )
)
