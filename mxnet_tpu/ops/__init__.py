"""Operator registry + all operator definitions.

Importing this package registers the full op corpus (parity with the
reference's ~150 NNVM tensor ops + ~50 legacy layer ops, SURVEY.md §2
N6/N7).
"""
from . import registry
from .registry import OpDef, get, exists, list_ops, primary_ops, register, register_op

# op definition modules — import order only matters for registration
from . import elemwise  # noqa: F401
from . import broadcast_reduce  # noqa: F401
from . import matrix  # noqa: F401
from . import indexing  # noqa: F401
from . import init_ops  # noqa: F401
from . import sample  # noqa: F401
from . import nn  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import rnn_op  # noqa: F401
from . import spatial  # noqa: F401
