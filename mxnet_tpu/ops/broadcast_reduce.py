"""Reductions and broadcast-shape ops.

Parity: reference ``src/operator/tensor/broadcast_reduce_op_value.cc``
(sum/nansum/prod/nanprod/max/min/norm, broadcast_to/broadcast_axis,
argmax/argmin/argmax_channel). The reference hand-writes tiled reduce
kernels (``broadcast_reduce-inl.{h,cuh}``); XLA's reduce emitter does that
scheduling here.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .registry import OpDef, register
from .utils import reduce_out_shape, same_shape_infer


def _reduce_infer(attrs, in_shapes):
    ishape = in_shapes[0]
    if ishape is None:
        raise MXNetError("reduce op: input shape required")
    out, _ = reduce_out_shape(
        ishape,
        attrs.get("axis"),
        bool(attrs.get("keepdims", False)),
        bool(attrs.get("exclude", False)),
    )
    return [tuple(ishape)], [out], []


def _register_reduce(name, fn, aliases=()):
    def fcompute(attrs, ins, is_train, _fn=fn):
        _, axes = reduce_out_shape(
            ins[0].shape,
            attrs.get("axis"),
            False,
            bool(attrs.get("exclude", False)),
        )
        out = _fn(ins[0], axis=axes, keepdims=bool(attrs.get("keepdims", False)))
        return [out]

    register(
        OpDef(
            name,
            fcompute,
            arguments=("data",),
            defaults={"axis": None, "keepdims": False, "exclude": False},
            infer_shape=_reduce_infer,
            aliases=aliases,
        )
    )


_register_reduce("sum", jnp.sum, aliases=("sum_axis",))
_register_reduce("mean", jnp.mean)
_register_reduce("prod", jnp.prod)
_register_reduce("nansum", jnp.nansum)
_register_reduce("nanprod", jnp.nanprod)
_register_reduce("max", jnp.max, aliases=("max_axis",))
_register_reduce("min", jnp.min, aliases=("min_axis",))


# norm: reference flattens to a scalar L2 norm (broadcast_reduce_op_value.cc)
register(
    OpDef(
        "norm",
        lambda attrs, ins, is_train: [
            jnp.sqrt(jnp.sum(jnp.square(ins[0].astype(jnp.float32)))).astype(
                ins[0].dtype
            )
        ],
        arguments=("data",),
        infer_shape=lambda attrs, in_shapes: ([tuple(in_shapes[0])], [(1,)], []),
    )
)


def _argminmax(fn):
    def fcompute(attrs, ins, is_train, _fn=fn):
        axis = attrs.get("axis")
        keepdims = bool(attrs.get("keepdims", False))
        x = ins[0]
        if axis is None:
            out = _fn(x.reshape(-1), axis=0)
            if keepdims:
                out = out.reshape((1,) * x.ndim)
        else:
            out = _fn(x, axis=int(axis))
            if keepdims:
                out = jnp.expand_dims(out, int(axis))
        return [out.astype(x.dtype)]

    return fcompute


def _argminmax_infer(attrs, in_shapes):
    ishape = in_shapes[0]
    if ishape is None:
        raise MXNetError("argmax/argmin: input shape required")
    axis = attrs.get("axis")
    keepdims = bool(attrs.get("keepdims", False))
    if axis is None:
        out = (1,) * len(ishape) if keepdims else ()
    else:
        out, _ = reduce_out_shape(ishape, int(axis), keepdims)
    return [tuple(ishape)], [out if out else (1,)], []


for _nm, _f in [("argmax", jnp.argmax), ("argmin", jnp.argmin)]:
    register(
        OpDef(
            _nm,
            _argminmax(_f),
            arguments=("data",),
            defaults={"axis": None, "keepdims": False},
            infer_shape=_argminmax_infer,
        )
    )

# argmax_channel: argmax over axis 1 keeping batch (reference: used by Accuracy)
register(
    OpDef(
        "argmax_channel",
        lambda attrs, ins, is_train: [
            jnp.argmax(ins[0], axis=1).astype(ins[0].dtype)
        ],
        arguments=("data",),
        infer_shape=lambda attrs, in_shapes: (
            [tuple(in_shapes[0])],
            [(in_shapes[0][0],) + tuple(in_shapes[0][2:])],
            [],
        ),
    )
)


# --------------------------------------------------------------------------
# broadcast_to / broadcast_axis
# --------------------------------------------------------------------------
def _broadcast_to_infer(attrs, in_shapes):
    ishape = in_shapes[0]
    tgt = tuple(int(d) for d in attrs["shape"])
    if ishape is None:
        raise MXNetError("broadcast_to: input shape required")
    out = tuple(t if t != 0 else s for t, s in zip(tgt, ishape))
    for s, o in zip(ishape, out):
        if s != o and s != 1:
            raise MXNetError("broadcast_to: cannot broadcast %s to %s" % (ishape, tgt))
    return [tuple(ishape)], [out], []


def _broadcast_to(attrs, ins, is_train):
    tgt = tuple(int(d) for d in attrs["shape"])
    out = tuple(t if t != 0 else s for t, s in zip(tgt, ins[0].shape))
    return [jnp.broadcast_to(ins[0], out)]


register(
    OpDef(
        "broadcast_to",
        _broadcast_to,
        arguments=("data",),
        defaults={"shape": ()},
        infer_shape=_broadcast_to_infer,
    )
)


def _broadcast_axis(attrs, ins, is_train):
    axes = attrs.get("axis", ())
    sizes = attrs.get("size", ())
    if isinstance(axes, (int, np.integer)):
        axes = (axes,)
    if isinstance(sizes, (int, np.integer)):
        sizes = (sizes,)
    out = list(ins[0].shape)
    for a, s in zip(axes, sizes):
        out[int(a)] = int(s)
    return [jnp.broadcast_to(ins[0], tuple(out))]


def _broadcast_axis_infer(attrs, in_shapes):
    ishape = list(in_shapes[0])
    axes = attrs.get("axis", ())
    sizes = attrs.get("size", ())
    if isinstance(axes, (int, np.integer)):
        axes = (axes,)
    if isinstance(sizes, (int, np.integer)):
        sizes = (sizes,)
    for a, s in zip(axes, sizes):
        ishape[int(a)] = int(s)
    return [tuple(in_shapes[0])], [tuple(ishape)], []


register(
    OpDef(
        "broadcast_axis",
        _broadcast_axis,
        arguments=("data",),
        defaults={"axis": (), "size": ()},
        infer_shape=_broadcast_axis_infer,
        aliases=("broadcast_axes",),
    )
)
