"""Shared shape-inference and param helpers for operator definitions.

Replaces the reference's ``elemwise_op_common.h`` shape-attr machinery and
the per-op dmlc::Parameter structs' normalization logic.
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError


def as_tuple(v, n=None, name="param"):
    """Normalize an int-or-tuple param to a tuple (kernel=(2,2) style)."""
    if v is None:
        return None
    if isinstance(v, (int, np.integer)):
        v = (int(v),) * (n or 1)
    v = tuple(int(x) for x in v)
    if n is not None and len(v) != n:
        raise MXNetError("%s must have %d elements, got %s" % (name, n, (v,)))
    return v


def broadcast_shape(lhs, rhs, name="broadcast"):
    """Numpy-style broadcast of two shapes."""
    l, r = list(lhs), list(rhs)
    if len(l) < len(r):
        l = [1] * (len(r) - len(l)) + l
    if len(r) < len(l):
        r = [1] * (len(l) - len(r)) + r
    out = []
    for a, b in zip(l, r):
        if a == b or b == 1:
            out.append(a)
        elif a == 1:
            out.append(b)
        else:
            raise MXNetError("%s: incompatible shapes %s %s" % (name, lhs, rhs))
    return tuple(out)


def merge_shapes(a, b, name="shape"):
    """Dim-wise merge with MXNet's 0-means-unknown convention."""
    if a is None:
        return tuple(b) if b is not None else None
    if b is None:
        return tuple(a)
    if len(a) != len(b):
        raise MXNetError("%s: rank mismatch %s vs %s" % (name, a, b))
    out = []
    for x, y in zip(a, b):
        if x == 0:
            out.append(y)
        elif y == 0 or x == y:
            out.append(x)
        else:
            raise MXNetError("%s: incompatible %s vs %s" % (name, a, b))
    return tuple(out)


def shape_known(s):
    return s is not None and all(d > 0 for d in s)


def same_shape_infer(n_in, n_out=1):
    """All inputs and outputs share one shape (elemwise). Handles partial
    shapes (0 = unknown) by dim-wise merging — the lightweight version of
    nnvm's bidirectional elemwise shape attr."""

    def infer(attrs, in_shapes):
        merged = None
        for s in in_shapes:
            merged = merge_shapes(merged, s, "elemwise")
        if merged is None:
            raise MXNetError("cannot infer shape: all inputs unknown")
        return [merged] * len(in_shapes), [merged] * n_out, []

    return infer


def binary_broadcast_infer(attrs, in_shapes):
    lhs, rhs = in_shapes
    if lhs is None or rhs is None:
        raise MXNetError("broadcast op: both input shapes required")
    return [tuple(lhs), tuple(rhs)], [broadcast_shape(lhs, rhs)], []


def reduce_out_shape(ishape, axis, keepdims, exclude=False):
    ishape = tuple(ishape)
    ndim = len(ishape)
    if axis is None or axis == () or axis == []:
        axes = tuple(range(ndim))
    else:
        if isinstance(axis, (int, np.integer)):
            axis = (int(axis),)
        axes = tuple(sorted(a % ndim for a in axis))
        if exclude:
            axes = tuple(a for a in range(ndim) if a not in axes)
    if keepdims:
        return tuple(1 if i in axes else d for i, d in enumerate(ishape)), axes
    out = tuple(d for i, d in enumerate(ishape) if i not in axes)
    return out, axes


def known(shape):
    return shape is not None and all(d is not None and d > 0 for d in shape)
