"""Fused RNN operator (multi-layer LSTM/GRU/vanilla RNN).

Parity: reference ``src/operator/rnn-inl.h`` + ``cudnn_rnn-inl.h`` (the
``RNN`` op used by FusedRNNCell, rnn/rnn_cell.py:497). The reference
delegates to cuDNN's fused RNN; here the recurrence is a ``lax.scan`` whose
per-step gate matmuls hit the MXU and whose sequential loop XLA pipelines —
the idiomatic TPU formulation of a fused RNN.

Weight layout matches cuDNN packing so FusedRNNCell.unfuse()/checkpoint
compatibility holds: per layer/direction, [W_i2h (gates*H, I), W_h2h
(gates*H, H)] concatenated across layers, then all biases [b_i2h, b_h2h].
Gate order: LSTM i,f,g(c~),o ; GRU r,z,n (cuDNN order, as the reference's
FusedRNNCell documents).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .registry import OpDef, register

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def _rnn_param_size(num_layers, input_size, state_size, bidirectional, mode):
    gates = _GATES[mode]
    dirs = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        inp = input_size if layer == 0 else state_size * dirs
        size += dirs * gates * state_size * (inp + state_size)  # weights
        size += dirs * gates * state_size * 2  # biases
    return size


def _unpack_params(params, num_layers, input_size, state_size, bidirectional, mode):
    gates = _GATES[mode]
    dirs = 2 if bidirectional else 1
    ws, bs = [], []
    off = 0
    for layer in range(num_layers):
        inp = input_size if layer == 0 else state_size * dirs
        layer_ws = []
        for _ in range(dirs):
            wi = params[off : off + gates * state_size * inp].reshape(
                gates * state_size, inp
            )
            off += gates * state_size * inp
            wh = params[off : off + gates * state_size * state_size].reshape(
                gates * state_size, state_size
            )
            off += gates * state_size * state_size
            layer_ws.append((wi, wh))
        ws.append(layer_ws)
    for layer in range(num_layers):
        layer_bs = []
        for _ in range(dirs):
            bi = params[off : off + gates * state_size]
            off += gates * state_size
            bh = params[off : off + gates * state_size]
            off += gates * state_size
            layer_bs.append((bi, bh))
        bs.append(layer_bs)
    return ws, bs


def _cell_step(mode, H):
    if mode == "lstm":

        def step(carry, gates_x, wh, bh):
            h, c = carry
            gates = gates_x + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c2 = f * c + i * g
            h2 = o * jnp.tanh(c2)
            return (h2, c2), h2

    elif mode == "gru":

        def step(carry, gates_x, wh, bh):
            (h,) = carry
            xr, xz, xn = jnp.split(gates_x, 3, axis=-1)
            hr, hz, hn = jnp.split(h @ wh.T + bh, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            n = jnp.tanh(xn + r * hn)
            h2 = (1.0 - z) * n + z * h
            return (h2,), h2

    else:
        act = jnp.tanh if mode == "rnn_tanh" else (lambda x: jnp.maximum(x, 0))

        def step(carry, gates_x, wh, bh):
            (h,) = carry
            h2 = act(gates_x + h @ wh.T + bh)
            return (h2,), h2

    return step


def _run_layer(x, h0, c0, wi, wh, bi, bh, mode, reverse=False):
    """x: (T, N, I) → (T, N, H). Precompute input gates as one big matmul
    (MXU-friendly), then scan the recurrence."""
    H = wh.shape[1]
    gates_x = jnp.einsum("tni,gi->tng", x, wi) + bi
    step = _cell_step(mode, H)
    if mode == "lstm":
        carry0 = (h0, c0)
    else:
        carry0 = (h0,)

    def scan_fn(carry, gx):
        return step(carry, gx, wh, bh)

    if reverse:
        gates_x = jnp.flip(gates_x, axis=0)
    carry, ys = jax.lax.scan(scan_fn, carry0, gates_x)
    if reverse:
        ys = jnp.flip(ys, axis=0)
    return ys, carry


def _rnn_fcompute(attrs, ins, is_train):
    mode = attrs["mode"]
    if mode not in _GATES:
        raise MXNetError("RNN: unknown mode %s" % mode)
    num_layers = int(attrs["num_layers"])
    H = int(attrs["state_size"])
    bidir = bool(attrs.get("bidirectional", False))
    dirs = 2 if bidir else 1
    p = float(attrs.get("p", 0.0))
    state_outputs = bool(attrs.get("state_outputs", False))
    if mode == "lstm":
        data, params, hx, cx = ins[:4]
    else:
        data, params, hx = ins[:3]
        cx = None
    T, N, I = data.shape
    ws, bs = _unpack_params(params, num_layers, I, H, bidir, mode)
    x = data
    h_out, c_out = [], []
    rng = attrs.get("__rng__")
    for layer in range(num_layers):
        outs = []
        for d in range(dirs):
            sidx = layer * dirs + d
            h0 = hx[sidx]
            c0 = cx[sidx] if cx is not None else None
            wi, wh = ws[layer][d]
            bi, bh = bs[layer][d]
            ys, carry = _run_layer(x, h0, c0, wi, wh, bi, bh, mode, reverse=(d == 1))
            outs.append(ys)
            h_out.append(carry[0])
            if mode == "lstm":
                c_out.append(carry[1])
        x = jnp.concatenate(outs, axis=-1) if dirs == 2 else outs[0]
        if is_train and p > 0 and layer < num_layers - 1 and rng is not None:
            key = jax.random.fold_in(rng, layer)
            mask = jax.random.bernoulli(key, 1.0 - p, x.shape)
            x = jnp.where(mask, x / (1.0 - p), jnp.zeros_like(x))
    outputs = [x]
    if state_outputs:
        outputs.append(jnp.stack(h_out, axis=0))
        if mode == "lstm":
            outputs.append(jnp.stack(c_out, axis=0))
    return outputs


def _rnn_infer(attrs, in_shapes):
    mode = attrs["mode"]
    num_layers = int(attrs["num_layers"])
    H = int(attrs["state_size"])
    bidir = bool(attrs.get("bidirectional", False))
    dirs = 2 if bidir else 1
    state_outputs = bool(attrs.get("state_outputs", False))
    dshape = in_shapes[0]
    if dshape is None:
        raise MXNetError("RNN: data shape required")
    T, N, I = dshape
    psize = _rnn_param_size(num_layers, I, H, bidir, mode)
    sshape = (num_layers * dirs, N, H)
    ishapes = [tuple(dshape), (psize,), sshape]
    if mode == "lstm":
        ishapes.append(sshape)
    oshapes = [(T, N, H * dirs)]
    if state_outputs:
        oshapes.append(sshape)
        if mode == "lstm":
            oshapes.append(sshape)
    return ishapes, oshapes, []


_rnn = OpDef(
    "RNN",
    _rnn_fcompute,
    arguments=("data", "parameters", "state", "state_cell"),
    defaults={
        "mode": "lstm",
        "num_layers": 1,
        "state_size": 0,
        "bidirectional": False,
        "p": 0.0,
        "state_outputs": False,
        "pkeep_": 1.0,
        "lstm_q_": False,
    },
    infer_shape=_rnn_infer,
    needs_rng=True,
)
_rnn.list_arguments = lambda attrs=None: (
    ["data", "parameters", "state", "state_cell"]
    if (attrs or {}).get("mode", "lstm") == "lstm"
    else ["data", "parameters", "state"]
)


def _rnn_outputs(attrs=None):
    a = attrs or {}
    outs = ["output"]
    if a.get("state_outputs"):
        outs.append("state")
        if a.get("mode", "lstm") == "lstm":
            outs.append("state_cell")
    return outs


_rnn.list_outputs = _rnn_outputs
register(_rnn)
