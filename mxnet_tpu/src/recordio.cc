// Native RecordIO reader/writer + MNIST/CSV parsers.
//
// Capability parity: reference src/io/ + dmlc-core RecordIO (SURVEY.md §2
// N11/N21). The dmlc wire format is kept (magic 0xced7230a, lrecord
// header, 4-byte alignment) so .rec files interoperate with files written
// by the python layer and by the reference's im2rec.
//
// The reader mmaps the file and indexes record offsets in one pass, then
// serves random/sequential reads with zero copies until the python
// boundary — the native fast path under io.py/image.py, replacing the
// reference's dmlc::RecordIOSplitter + OpenMP parse workers.
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <cstdlib>
#include <string>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {
constexpr uint32_t kMagic = 0xced7230a;

inline uint32_t DecodeLength(uint32_t lrec) { return lrec & ((1u << 29) - 1); }
}  // namespace

extern "C" {

struct RecReader {
  int fd = -1;
  const uint8_t* base = nullptr;
  size_t size = 0;
  std::vector<size_t> offsets;  // payload offsets
  std::vector<uint32_t> lengths;
};

// Open + index a RecordIO file. Returns nullptr on failure.
RecReader* recio_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size == 0) {
    ::close(fd);
    return nullptr;
  }
  void* mem = mmap(nullptr, st.st_size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (mem == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  auto* r = new RecReader();
  r->fd = fd;
  r->base = static_cast<const uint8_t*>(mem);
  r->size = static_cast<size_t>(st.st_size);
  size_t pos = 0;
  while (pos + 8 <= r->size) {
    uint32_t magic, lrec;
    std::memcpy(&magic, r->base + pos, 4);
    std::memcpy(&lrec, r->base + pos + 4, 4);
    if (magic != kMagic) break;
    uint32_t len = DecodeLength(lrec);
    if (pos + 8 + len > r->size) break;
    r->offsets.push_back(pos + 8);
    r->lengths.push_back(len);
    size_t advance = 8 + len;
    advance += (4 - len % 4) % 4;  // alignment padding
    pos += advance;
  }
  return r;
}

int64_t recio_num_records(RecReader* r) {
  return static_cast<int64_t>(r->offsets.size());
}

// Pointer+length of record i (zero-copy view into the mmap).
const uint8_t* recio_record(RecReader* r, int64_t i, int64_t* out_len) {
  if (i < 0 || static_cast<size_t>(i) >= r->offsets.size()) {
    *out_len = 0;
    return nullptr;
  }
  *out_len = r->lengths[i];
  return r->base + r->offsets[i];
}

// Payload byte offset of record i (record start + 8-byte header), so
// callers can reconcile external .idx files against physical layout.
int64_t recio_payload_offset(RecReader* r, int64_t i) {
  if (i < 0 || static_cast<size_t>(i) >= r->offsets.size()) return -1;
  return static_cast<int64_t>(r->offsets[i]);
}

void recio_close(RecReader* r) {
  if (!r) return;
  if (r->base) munmap(const_cast<uint8_t*>(r->base), r->size);
  if (r->fd >= 0) ::close(r->fd);
  delete r;
}

// ---------------------------------------------------------------------
// MNIST idx format parse (parity iter_mnist.cc): big-endian header, raw
// uint8 payload. Returns 0 on success; fills caller-allocated buffer.
// ---------------------------------------------------------------------
static uint32_t be32(const uint8_t* p) {
  return (uint32_t(p[0]) << 24) | (uint32_t(p[1]) << 16) |
         (uint32_t(p[2]) << 8) | uint32_t(p[3]);
}

int mnist_read_header(const char* path, int64_t* dims, int* ndim) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  uint8_t hdr[4];
  if (fread(hdr, 1, 4, f) != 4) {
    fclose(f);
    return -1;
  }
  int nd = hdr[3];
  *ndim = nd;
  for (int i = 0; i < nd; ++i) {
    uint8_t b[4];
    if (fread(b, 1, 4, f) != 4) {
      fclose(f);
      return -1;
    }
    dims[i] = be32(b);
  }
  fclose(f);
  return 0;
}

int mnist_read_data(const char* path, uint8_t* out, int64_t count) {
  FILE* f = fopen(path, "rb");
  if (!f) return -1;
  uint8_t hdr[4];
  if (fread(hdr, 1, 4, f) != 4) {
    fclose(f);
    return -1;
  }
  int nd = hdr[3];
  fseek(f, 4 + 4 * nd, SEEK_SET);
  size_t got = fread(out, 1, count, f);
  fclose(f);
  return got == static_cast<size_t>(count) ? 0 : -1;
}

// ---------------------------------------------------------------------
// CSV float parser (parity iter_csv.cc): parse a whole file of
// comma-separated floats into a caller buffer. Returns #values parsed.
// Much faster than numpy.loadtxt for large files.
// ---------------------------------------------------------------------
int64_t csv_parse_floats(const char* path, float* out, int64_t capacity) {
  // Read into a NUL-terminated heap buffer: strtof scans to a terminator,
  // so parsing straight off an mmap whose size is an exact page multiple
  // would run past the mapping on a file ending mid-number.
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return -1;
  struct stat st;
  if (fstat(fd, &st) != 0) {
    ::close(fd);
    return -1;
  }
  char* buf = static_cast<char*>(malloc(st.st_size + 1));
  if (!buf) {
    ::close(fd);
    return -1;
  }
  size_t got = 0;
  while (got < static_cast<size_t>(st.st_size)) {
    ssize_t k = ::read(fd, buf + got, st.st_size - got);
    if (k < 0) {  // I/O error: fail loudly, never return a truncated parse
      free(buf);
      ::close(fd);
      return -1;
    }
    if (k == 0) break;  // EOF (file shrank since fstat)
    got += static_cast<size_t>(k);
  }
  ::close(fd);
  buf[got] = '\0';
  const char* p = buf;
  const char* end = buf + got;
  int64_t n = 0;
  while (p < end && n < capacity) {
    char* next = nullptr;
    float v = strtof(p, &next);
    if (next == p) {
      ++p;  // skip separators / newlines
      continue;
    }
    out[n++] = v;
    p = next;
  }
  free(buf);
  return n;
}

}  // extern "C"
