// Native JPEG decode for the data pipeline (SURVEY.md §2.1 N11: the
// reference decodes with an OpenCV/libjpeg OpenMP team,
// iter_image_recordio_2.cc — this is the TPU build's equivalent fast
// path; the python decode pool calls it through ctypes, which releases
// the GIL, so worker threads decode truly in parallel where PIL would
// serialize).
//
// libjpeg is resolved at RUNTIME via dlopen: the shared library builds
// and loads everywhere, and hosts without libjpeg simply fall back to
// the PIL path (imdecode_jpeg returns -1).
#include <csetjmp>
#include <cstdint>
#include <cstring>

#if defined(__has_include)
#if __has_include(<jpeglib.h>) && __has_include(<dlfcn.h>)
#define MXTPU_HAVE_JPEG 1
#endif
#endif

#ifdef MXTPU_HAVE_JPEG
#include <dlfcn.h>
#include <cstdio>  // jpeglib.h needs FILE
#include <jpeglib.h>

namespace {

struct JpegApi {
  struct jpeg_error_mgr* (*std_error)(struct jpeg_error_mgr*);
  void (*create_decompress)(j_decompress_ptr, int, size_t);
  void (*mem_src)(j_decompress_ptr, const unsigned char*, unsigned long);
  int (*read_header)(j_decompress_ptr, boolean);
  boolean (*start_decompress)(j_decompress_ptr);
  JDIMENSION (*read_scanlines)(j_decompress_ptr, JSAMPARRAY, JDIMENSION);
  boolean (*finish_decompress)(j_decompress_ptr);
  void (*destroy_decompress)(j_decompress_ptr);
  bool ok = false;
};

bool bind_api(void* h, JpegApi* api) {
  auto sym = [h](const char* n) { return dlsym(h, n); };
  api->std_error = reinterpret_cast<decltype(api->std_error)>(
      sym("jpeg_std_error"));
  api->create_decompress = reinterpret_cast<decltype(api->create_decompress)>(
      sym("jpeg_CreateDecompress"));
  api->mem_src = reinterpret_cast<decltype(api->mem_src)>(
      sym("jpeg_mem_src"));
  api->read_header = reinterpret_cast<decltype(api->read_header)>(
      sym("jpeg_read_header"));
  api->start_decompress = reinterpret_cast<decltype(api->start_decompress)>(
      sym("jpeg_start_decompress"));
  api->read_scanlines = reinterpret_cast<decltype(api->read_scanlines)>(
      sym("jpeg_read_scanlines"));
  api->finish_decompress = reinterpret_cast<decltype(api->finish_decompress)>(
      sym("jpeg_finish_decompress"));
  api->destroy_decompress =
      reinterpret_cast<decltype(api->destroy_decompress)>(
          sym("jpeg_destroy_decompress"));
  return api->std_error && api->create_decompress && api->mem_src &&
         api->read_header && api->start_decompress && api->read_scanlines &&
         api->finish_decompress && api->destroy_decompress;
}

JpegApi load_api() {
  JpegApi api;
  // Prefer the soname matching the COMPILED JPEG_LIB_VERSION: the
  // runtime version/structsize check in jpeg_CreateDecompress rejects
  // mismatched ABIs, so starting with the matching one avoids pinning a
  // library we can't actually use.
#if JPEG_LIB_VERSION >= 90
  const char* candidates[] = {"libjpeg.so.9", "libjpeg.so",
                              "libjpeg.so.8", "libjpeg.so.62"};
#elif JPEG_LIB_VERSION >= 80
  const char* candidates[] = {"libjpeg.so.8", "libjpeg.so",
                              "libjpeg.so.9", "libjpeg.so.62"};
#else
  const char* candidates[] = {"libjpeg.so.62", "libjpeg.so",
                              "libjpeg.so.8", "libjpeg.so.9"};
#endif
  for (const char* name : candidates) {
    // RTLD_LOCAL: all symbols are fetched via dlsym, and exporting the
    // system libjpeg globally could interpose onto the DIFFERENT libjpeg
    // build PIL/cv2 bundle for the fallback path (ABI mismatch crash)
    void* h = dlopen(name, RTLD_NOW | RTLD_LOCAL);
    if (h == nullptr) continue;
    if (bind_api(h, &api)) {
      api.ok = true;
      return api;
    }
    dlclose(h);  // unusable build (e.g. no jpeg_mem_src): try the next
  }
  api.ok = false;
  return api;
}

void on_emit_message(j_common_ptr, int) {
  // corrupt-but-decodable inputs would otherwise spam stderr from every
  // decode-pool worker thread (the PIL path this replaces is silent)
}
void on_output_message(j_common_ptr) {}

const JpegApi& api() {
  static JpegApi a = load_api();
  return a;
}

struct ErrorTrap {
  struct jpeg_error_mgr mgr;
  jmp_buf jump;
};

void on_error(j_common_ptr cinfo) {
  ErrorTrap* trap = reinterpret_cast<ErrorTrap*>(cinfo->err);
  longjmp(trap->jump, 1);
}

}  // namespace

extern "C" {

// Decode a JPEG buffer to tightly-packed RGB8 (gray=1 -> single
// channel). Returns the byte size written (or required, when out is
// null/too small) or -1 when the buffer is not decodable / libjpeg is
// unavailable. w/h/c receive the image dims.
long long imdecode_jpeg(const unsigned char* buf, long long len,
                        unsigned char* out, long long cap, int gray,
                        int* w, int* h, int* c) {
  const JpegApi& J = api();
  if (!J.ok || buf == nullptr || len < 4) return -1;
  struct jpeg_decompress_struct cinfo;
  ErrorTrap trap;
  cinfo.err = J.std_error(&trap.mgr);
  trap.mgr.error_exit = on_error;
  trap.mgr.emit_message = on_emit_message;
  trap.mgr.output_message = on_output_message;
  if (setjmp(trap.jump)) {
    J.destroy_decompress(&cinfo);
    return -1;
  }
  J.create_decompress(&cinfo, JPEG_LIB_VERSION,
                      sizeof(struct jpeg_decompress_struct));
  J.mem_src(&cinfo, buf, static_cast<unsigned long>(len));
  if (J.read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    J.destroy_decompress(&cinfo);
    return -1;
  }
  cinfo.out_color_space = gray ? JCS_GRAYSCALE : JCS_RGB;
  J.start_decompress(&cinfo);
  const int width = static_cast<int>(cinfo.output_width);
  const int height = static_cast<int>(cinfo.output_height);
  const int channels = cinfo.output_components;
  const long long need =
      static_cast<long long>(width) * height * channels;
  if (w != nullptr) *w = width;
  if (h != nullptr) *h = height;
  if (c != nullptr) *c = channels;
  if (out == nullptr || cap < need) {
    J.destroy_decompress(&cinfo);
    return need;
  }
  while (cinfo.output_scanline < cinfo.output_height) {
    JSAMPROW row = out + static_cast<long long>(cinfo.output_scanline) *
                             width * channels;
    J.read_scanlines(&cinfo, &row, 1);
  }
  J.finish_decompress(&cinfo);
  J.destroy_decompress(&cinfo);
  return need;
}

}  // extern "C"

#else  // !MXTPU_HAVE_JPEG

extern "C" long long imdecode_jpeg(const unsigned char*, long long,
                                   unsigned char*, long long, int, int*,
                                   int*, int*) {
  return -1;
}

#endif
