// Native host-side dependency engine.
//
// Capability parity: reference src/engine/ (ThreadedEnginePerDevice /
// ThreadedEnginePooled — SURVEY.md §2 N1). On TPU the *device* scheduling
// role is played by XLA async dispatch; this engine schedules the host side
// (IO, decode, staging, KVStore host reductions) with the reference's
// exact dependency discipline:
//   - variables carry a queue of pending operations
//   - an op lists const (read) vars and mutable (write) vars
//   - reads run concurrently; writes serialize against reads and writes
//   - ops fire when their wait-count drains to zero (OprBlock::wait)
// C ABI (ctypes-friendly):
//   engine_create(num_workers) -> handle
//   engine_new_var(h) -> var id
//   engine_push(h, fn, ctx, const_vars, n_const, mut_vars, n_mut)
//   engine_wait_for_var(h, var)
//   engine_wait_all(h)
//   engine_destroy(h)
// The callback runs on a worker thread; for Python callers the binding
// acquires the GIL inside the trampoline (ctypes does this automatically).
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <functional>
#include <memory>
#include <mutex>
#include <queue>
#include <thread>
#include <unordered_map>
#include <vector>

extern "C" {
typedef void (*engine_fn)(void* ctx);
}

namespace mxtpu {

struct OprBlock;

// A dependency variable: pending-op queue + read/write state
// (reference ThreadedVar, threaded_engine.h:93-195).
struct Var {
  std::mutex mu;
  // queue entries: (is_write, opr)
  std::deque<std::pair<bool, OprBlock*>> queue;
  bool pending_write = false;
  int num_pending_reads = 0;
};

struct OprBlock {
  engine_fn fn;
  void* ctx;
  std::vector<Var*> const_vars;
  std::vector<Var*> mutable_vars;
  std::atomic<int> wait{0};
};

class ThreadedEngine {
 public:
  explicit ThreadedEngine(int num_workers) : shutdown_(false), inflight_(0) {
    if (num_workers <= 0) num_workers = 4;
    for (int i = 0; i < num_workers; ++i) {
      workers_.emplace_back([this] { WorkerLoop(); });
    }
  }

  ~ThreadedEngine() {
    WaitAll();
    {
      std::unique_lock<std::mutex> lk(task_mu_);
      shutdown_ = true;
    }
    task_cv_.notify_all();
    for (auto& t : workers_) t.join();
    for (auto& kv : vars_) delete kv.second;
  }

  int64_t NewVar() {
    std::unique_lock<std::mutex> lk(vars_mu_);
    int64_t id = next_var_++;
    vars_[id] = new Var();
    return id;
  }

  Var* GetVar(int64_t id) {
    std::unique_lock<std::mutex> lk(vars_mu_);
    auto it = vars_.find(id);
    return it == vars_.end() ? nullptr : it->second;
  }

  // Parity: Engine::PushAsync (engine.h:147) + Append{Read,Write}Dependency.
  void Push(engine_fn fn, void* ctx, const int64_t* cvars, int n_const,
            const int64_t* mvars, int n_mut) {
    auto* opr = new OprBlock();
    opr->fn = fn;
    opr->ctx = ctx;
    for (int i = 0; i < n_const; ++i) opr->const_vars.push_back(GetVar(cvars[i]));
    for (int i = 0; i < n_mut; ++i) opr->mutable_vars.push_back(GetVar(mvars[i]));
    inflight_.fetch_add(1);

    int pending = 0;
    for (Var* v : opr->const_vars) {
      std::unique_lock<std::mutex> lk(v->mu);
      if (v->pending_write || !v->queue.empty()) {
        v->queue.emplace_back(false, opr);
        ++pending;
      } else {
        ++v->num_pending_reads;
      }
    }
    for (Var* v : opr->mutable_vars) {
      std::unique_lock<std::mutex> lk(v->mu);
      if (v->pending_write || v->num_pending_reads > 0 || !v->queue.empty()) {
        v->queue.emplace_back(true, opr);
        ++pending;
      } else {
        v->pending_write = true;
      }
    }
    // Set wait AFTER appending: fetch_add returns previous; if all deps were
    // already satisfied at append time, the op is ready now.
    int prev = opr->wait.fetch_add(pending);
    if (prev + pending == 0) Enqueue(opr);
  }

  void WaitForVar(int64_t var_id) {
    std::mutex done_mu;
    std::condition_variable done_cv;
    bool done = false;
    struct Ctx {
      std::mutex* mu;
      std::condition_variable* cv;
      bool* done;
    } c{&done_mu, &done_cv, &done};
    auto notify = [](void* p) {
      auto* c = static_cast<Ctx*>(p);
      std::unique_lock<std::mutex> lk(*c->mu);
      *c->done = true;
      c->cv->notify_all();
    };
    int64_t v = var_id;
    Push(notify, &c, &v, 1, nullptr, 0);
    std::unique_lock<std::mutex> lk(done_mu);
    done_cv.wait(lk, [&] { return done; });
  }

  void WaitAll() {
    std::unique_lock<std::mutex> lk(idle_mu_);
    idle_cv_.wait(lk, [this] { return inflight_.load() == 0; });
  }

 private:
  void Enqueue(OprBlock* opr) {
    {
      std::unique_lock<std::mutex> lk(task_mu_);
      tasks_.push(opr);
    }
    task_cv_.notify_one();
  }

  void WorkerLoop() {
    for (;;) {
      OprBlock* opr = nullptr;
      {
        std::unique_lock<std::mutex> lk(task_mu_);
        task_cv_.wait(lk, [this] { return shutdown_ || !tasks_.empty(); });
        if (shutdown_ && tasks_.empty()) return;
        opr = tasks_.front();
        tasks_.pop();
      }
      opr->fn(opr->ctx);
      OnComplete(opr);
    }
  }

  // Parity: ThreadedEngine::OnComplete (threaded_engine.cc:351) —
  // CompleteReadDependency / CompleteWriteDependency + successor triggering.
  void OnComplete(OprBlock* opr) {
    std::vector<OprBlock*> ready;
    for (Var* v : opr->const_vars) {
      std::unique_lock<std::mutex> lk(v->mu);
      if (--v->num_pending_reads == 0) Drain(v, &ready);
    }
    for (Var* v : opr->mutable_vars) {
      std::unique_lock<std::mutex> lk(v->mu);
      v->pending_write = false;
      Drain(v, &ready);
    }
    for (OprBlock* nxt : ready) {
      if (nxt->wait.fetch_sub(1) == 1) Enqueue(nxt);
    }
    delete opr;
    if (inflight_.fetch_sub(1) == 1) {
      std::unique_lock<std::mutex> lk(idle_mu_);
      idle_cv_.notify_all();
    }
  }

  // caller holds v->mu
  void Drain(Var* v, std::vector<OprBlock*>* ready) {
    while (!v->queue.empty()) {
      auto [is_write, opr] = v->queue.front();
      if (is_write) {
        if (v->pending_write || v->num_pending_reads > 0) break;
        v->queue.pop_front();
        v->pending_write = true;
        ready->push_back(opr);
        break;
      } else {
        if (v->pending_write) break;
        v->queue.pop_front();
        ++v->num_pending_reads;
        ready->push_back(opr);
      }
    }
  }

  std::vector<std::thread> workers_;
  std::mutex task_mu_;
  std::condition_variable task_cv_;
  std::queue<OprBlock*> tasks_;
  bool shutdown_;

  std::mutex vars_mu_;
  std::unordered_map<int64_t, Var*> vars_;
  int64_t next_var_ = 1;

  std::atomic<int> inflight_;
  std::mutex idle_mu_;
  std::condition_variable idle_cv_;
};

}  // namespace mxtpu

extern "C" {

void* engine_create(int num_workers) {
  return new mxtpu::ThreadedEngine(num_workers);
}

void engine_destroy(void* h) { delete static_cast<mxtpu::ThreadedEngine*>(h); }

int64_t engine_new_var(void* h) {
  return static_cast<mxtpu::ThreadedEngine*>(h)->NewVar();
}

void engine_push(void* h, engine_fn fn, void* ctx, const int64_t* cvars,
                 int n_const, const int64_t* mvars, int n_mut) {
  static_cast<mxtpu::ThreadedEngine*>(h)->Push(fn, ctx, cvars, n_const, mvars,
                                               n_mut);
}

void engine_wait_for_var(void* h, int64_t var_id) {
  static_cast<mxtpu::ThreadedEngine*>(h)->WaitForVar(var_id);
}

void engine_wait_all(void* h) {
  static_cast<mxtpu::ThreadedEngine*>(h)->WaitAll();
}

}  // extern "C"
