"""Image pipeline (pure-python/NDArray-op).

Parity: reference ``python/mxnet/image.py`` (imdecode, augmenter closures,
CreateAugmenter, ImageIter reading .rec or .lst) and, via
``from_recordio_params``, the C++ ImageRecordIter parameter surface
(``src/io/iter_image_recordio_2.cc:559``). Decode/augment runs on host
worker threads (the reference's OMP decode pool,
iter_image_recordio_2.cc:103) feeding asynchronous device puts.
"""
from __future__ import annotations

import logging
import os
import queue as _queue
import random
import threading

import numpy as np

from . import ndarray as nd
from . import io as mxio
from .base import MXNetError
from .io import DataBatch, DataDesc, DataIter
from . import recordio


def imdecode(buf, **kwargs):
    """Decode an image byte buffer to an NDArray (HWC, RGB)."""
    arr = recordio._imdecode_np(
        buf if isinstance(buf, bytes) else bytes(buf),
        kwargs.get("flag", 1),
    )
    return nd.array(arr.astype(np.float32))


def scale_down(src_size, size):
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    h, w = src.shape[0], src.shape[1]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(src, new_w, new_h, interp=interp)


def imresize(src, w, h, interp=2):
    import jax.image

    arr = src.asnumpy() if isinstance(src, nd.NDArray) else np.asarray(src)
    out = np.asarray(
        jax.image.resize(arr, (h, w) + arr.shape[2:], method="bilinear")
    )
    return nd.array(out)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    out = src[y0 : y0 + h, x0 : x0 + w]
    if size is not None and (w, h) != size:
        out = imresize(out, size[0], size[1], interp=interp)
    return out


def random_crop(src, size, interp=2):
    h, w = src.shape[0], src.shape[1]
    new_w, new_h = scale_down((w, h), size)
    x0 = random.randint(0, w - new_w)
    y0 = random.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    h, w = src.shape[0], src.shape[1]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def color_normalize(src, mean, std=None):
    src = src - mean
    if std is not None:
        src = src / std
    return src


def random_size_crop(src, size, min_area=0.08, ratio=(3.0 / 4.0, 4.0 / 3.0),
                     interp=2):
    h, w = src.shape[0], src.shape[1]
    area = w * h
    for _ in range(10):
        new_area = random.uniform(min_area, 1.0) * area
        new_ratio = random.uniform(*ratio)
        new_w = int(np.sqrt(new_area * new_ratio))
        new_h = int(np.sqrt(new_area / new_ratio))
        if random.random() < 0.5:
            new_w, new_h = new_h, new_w
        if new_w <= w and new_h <= h:
            x0 = random.randint(0, w - new_w)
            y0 = random.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def ResizeAug(size, interp=2):
    def aug(src):
        return [resize_short(src, size, interp)]

    return aug


def RandomCropAug(size, interp=2):
    def aug(src):
        return [random_crop(src, size, interp)[0]]

    return aug


def RandomSizedCropAug(size, min_area, ratio, interp=2):
    def aug(src):
        return [random_size_crop(src, size, min_area, ratio, interp)[0]]

    return aug


def CenterCropAug(size, interp=2):
    def aug(src):
        return [center_crop(src, size, interp)[0]]

    return aug


def RandomOrderAug(ts):
    def aug(src):
        srcs = [src]
        # shuffle a per-call COPY: decode/augment runs on a thread pool,
        # and concurrent in-place shuffles of the shared closure list can
        # permanently corrupt it (duplicate one augmenter, drop another)
        order = list(ts)
        random.shuffle(order)
        for t in order:
            srcs = sum([t(s) for s in srcs], [])
        return srcs

    return aug


def ColorJitterAug(brightness, contrast, saturation):
    ts = []
    coef = nd.array(np.array([[[0.299, 0.587, 0.114]]], dtype=np.float32))
    if brightness > 0:

        def baug(src):
            alpha = 1.0 + random.uniform(-brightness, brightness)
            return [src * alpha]

        ts.append(baug)
    if contrast > 0:

        def caug(src):
            alpha = 1.0 + random.uniform(-contrast, contrast)
            gray = src * coef
            gray = (3.0 * (1.0 - alpha) / gray.size) * nd.sum(gray)
            return [src * alpha + gray]

        ts.append(caug)
    if saturation > 0:

        def saug(src):
            alpha = 1.0 + random.uniform(-saturation, saturation)
            gray = src * coef
            gray = nd.sum(gray, axis=2, keepdims=True)
            return [src * alpha + gray * (1.0 - alpha)]

        ts.append(saug)
    return RandomOrderAug(ts)


def LightingAug(alphastd, eigval, eigvec):
    def aug(src):
        alpha = np.random.normal(0, alphastd, size=(3,))
        rgb = np.dot(eigvec * alpha, eigval)
        return [src + nd.array(rgb)]

    return aug


def ColorNormalizeAug(mean, std):
    mean_nd = nd.array(mean) if not isinstance(mean, nd.NDArray) else mean
    std_nd = nd.array(std) if std is not None and not isinstance(std, nd.NDArray) else std

    def aug(src):
        return [color_normalize(src, mean_nd, std_nd)]

    return aug


def HorizontalFlipAug(p):
    def aug(src):
        if random.random() < p:
            return [nd.flip(src, axis=(1,))]
        return [src]

    return aug


def CastAug():
    def aug(src):
        return [src.astype(np.float32)]

    return aug


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, inter_method=2):
    """Parity image.py:351."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(
            RandomSizedCropAug(crop_size, 0.3, (3.0 / 4.0, 4.0 / 3.0), inter_method)
        )
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array(
            [
                [-0.5675, 0.7192, 0.4009],
                [-0.5808, -0.0045, -0.8140],
                [-0.5836, -0.6948, 0.4203],
            ]
        )
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    elif mean is not None:
        assert isinstance(mean, np.ndarray) and mean.shape[0] in [1, 3]
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    elif std is not None:
        assert isinstance(std, np.ndarray) and std.shape[0] in [1, 3]
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(DataIter):
    """Image iterator over .rec (RecordIO) or .lst+images.

    Parity: image.py:400 + the C++ ImageRecordIter capability. Decoding and
    augmentation run on `preprocess_threads` host workers; batches are
    assembled NCHW float32.
    """

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 path_imgidx=None, shuffle=False, part_index=0, num_parts=1,
                 aug_list=None, imglist=None, data_name="data",
                 label_name="softmax_label", preprocess_threads=4, **kwargs):
        super().__init__()
        assert path_imgrec or path_imglist or (isinstance(imglist, list))
        if path_imgrec:
            logging.info("loading recordio %s...", path_imgrec)
            if path_imgidx:
                self.imgrec = recordio.MXIndexedRecordIO(
                    path_imgidx, path_imgrec, "r"
                )
                self.imgidx = list(self.imgrec.keys)
            else:
                self.imgrec = recordio.MXRecordIO(path_imgrec, "r")
                self.imgidx = None
        else:
            self.imgrec = None
        if path_imglist:
            logging.info("loading image list %s...", path_imglist)
            with open(path_imglist) as fin:
                imglist = {}
                imgkeys = []
                for line in iter(fin.readline, ""):
                    line = line.strip().split("\t")
                    label = np.array([float(i) for i in line[1:-1]])
                    key = int(line[0])
                    imglist[key] = (label, line[-1])
                    imgkeys.append(key)
                self.imglist = imglist
        elif isinstance(imglist, list):
            logging.info("loading image list...")
            result = {}
            imgkeys = []
            index = 1
            for img in imglist:
                key = str(index)
                index += 1
                if isinstance(img[0], (list, np.ndarray)):
                    label = np.array(img[0])
                else:
                    label = np.array([img[0]])
                result[key] = (label, img[1])
                imgkeys.append(str(key))
            self.imglist = result
        else:
            self.imglist = None
        self.path_root = path_root

        self.check_data_shape(data_shape)
        self.provide_data = [DataDesc(data_name, (batch_size,) + data_shape)]
        if label_width > 1:
            self.provide_label = [
                DataDesc(label_name, (batch_size, label_width))
            ]
        else:
            self.provide_label = [DataDesc(label_name, (batch_size,))]
        self.batch_size = batch_size
        self.data_shape = data_shape
        self.label_width = label_width
        self.shuffle = shuffle
        self.preprocess_threads = int(preprocess_threads)
        self._pool = None
        self._fanout = None  # outputs per input, learned from 1st sample
        if self.imgrec is None:
            self.seq = imgkeys
        elif shuffle or num_parts > 1:
            assert self.imgidx is not None, (
                "shuffling/partition requires a .idx file"
            )
            self.seq = self.imgidx
        else:
            self.seq = None
        if num_parts > 1 and self.seq is not None:
            assert part_index < num_parts
            N = len(self.seq)
            C = N // num_parts
            self.seq = self.seq[part_index * C : (part_index + 1) * C]
        if aug_list is None:
            self.auglist = CreateAugmenter(data_shape, **{
                k: v for k, v in kwargs.items()
                if k in ("resize", "rand_crop", "rand_resize", "rand_mirror",
                         "mean", "std", "brightness", "contrast",
                         "saturation", "pca_noise", "inter_method")
            })
        else:
            self.auglist = aug_list
        self.cur = 0
        self.reset()

    @classmethod
    def from_recordio_params(cls, path_imgrec, data_shape, batch_size,
                             mean_r=0.0, mean_g=0.0, mean_b=0.0, scale=1.0,
                             rand_crop=False, rand_mirror=False, shuffle=False,
                             preprocess_threads=4, path_imgidx=None,
                             label_width=1, input_workers=None, seed=0,
                             shuffle_buffer=None, strict_order=None,
                             **kwargs):
        """Adapter giving the C++ ImageRecordIter's param names
        (iter_image_recordio_2.cc param struct).

        When ``input_workers`` (or ``MXTPU_INPUT_WORKERS``) is > 0 this
        returns the chunk-sharded, process-parallel
        :class:`io_pipeline.StreamingImageRecordIter` instead of the
        thread-pool ImageIter — the augment params here are all
        declarative, so they survive the process boundary as a recipe.
        """
        from . import io_pipeline

        mean = None
        if mean_r or mean_g or mean_b:
            mean = np.array([mean_r, mean_g, mean_b])
        if path_imgidx is None and path_imgrec.endswith(".rec"):
            # im2rec always writes the sibling .idx; pick it up so
            # shuffle/partition work without the extra param
            candidate = path_imgrec[:-4] + ".idx"
            if os.path.exists(candidate):
                path_imgidx = candidate
        if input_workers is None:
            input_workers = io_pipeline.input_workers()
        if input_workers > 0:
            recipe = {"rand_crop": rand_crop, "rand_mirror": rand_mirror,
                      "scale": scale}
            if mean is not None:
                recipe["mean"] = mean
            return io_pipeline.StreamingImageRecordIter(
                batch_size, tuple(data_shape), path_imgrec,
                path_imgidx=path_imgidx, label_width=label_width,
                shuffle=shuffle, seed=seed, aug_recipe=recipe,
                workers=input_workers, shuffle_buffer=shuffle_buffer,
                strict_order=strict_order,
            )
        aug = CreateAugmenter(
            data_shape, rand_crop=rand_crop, rand_mirror=rand_mirror, mean=mean
        )
        if scale != 1.0:
            aug.append(lambda src: [src * scale])
        return cls(
            batch_size, tuple(data_shape), label_width=label_width,
            path_imgrec=path_imgrec, path_imgidx=path_imgidx, shuffle=shuffle,
            aug_list=aug, preprocess_threads=preprocess_threads,
        )

    def reset(self):
        if self.shuffle and self.seq is not None:
            random.shuffle(self.seq)
        if self.imgrec is not None:
            self.imgrec.reset()
        self.cur = 0

    def next_sample(self):
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            if self.imgrec is not None:
                s = self.imgrec.read_idx(idx)
                header, img = recordio.unpack(s)
                if self.imglist is None:
                    return header.label, img
                return self.imglist[idx][0], img
            label, fname = self.imglist[idx]
            return label, self.read_image(fname)
        s = self.imgrec.read()
        if s is None:
            raise StopIteration
        header, img = recordio.unpack(s)
        return header.label, img

    def _decode_augment(self, s):
        """One sample's decode + augment chain — runs on a worker thread
        (PIL's JPEG decoder releases the GIL, the reference's OMP decode
        team translated, iter_image_recordio_2.cc:103-119). The image
        stays NUMPY end to end: a per-image device_put alone halves
        pipeline throughput (measured), and the batch is transferred
        once after assembly. Returns a list of numpy HWC images
        (augmenters may fan out)."""
        if isinstance(s, (bytes, bytearray)):
            arr = recordio._imdecode_np(bytes(s), 1).astype(np.float32)
        else:
            arr = np.asarray(s, np.float32)
        if arr.shape[0] == 0:
            return []
        data = [arr]
        for aug in self.auglist:
            data = [ret for src in data for ret in aug(src)]
        return [np.asarray(d.asnumpy() if isinstance(d, nd.NDArray) else d)
                for d in data]

    def _workers(self):
        if self._pool is None and self.preprocess_threads > 1:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=self.preprocess_threads)
        return self._pool

    def next(self):
        batch_size = self.batch_size
        c, h, w = self.data_shape
        batch_data = np.zeros((batch_size, h, w, c), dtype=np.float32)
        batch_label = np.zeros(
            (batch_size,) if self.label_width == 1 else (batch_size, self.label_width),
            dtype=np.float32,
        )
        pool = self._workers()
        i = 0
        exhausted = False
        while i < batch_size and not exhausted:
            # probe one sample until the augmenter fan-out is known, then
            # pull exactly the number of samples the remaining slots need
            # (invalid images simply leave the loop to pull replacements)
            fanout = self._fanout or 1
            need = (1 if self._fanout is None
                    else max(1, (batch_size - i) // fanout))
            samples = []
            try:
                while len(samples) < need:
                    samples.append(self.next_sample())
            except StopIteration:
                exhausted = True
                if not samples:
                    break
            if pool is not None and len(samples) > 1:
                decoded = list(pool.map(self._decode_augment,
                                        [s for _l, s in samples]))
            else:
                decoded = [self._decode_augment(s) for _l, s in samples]
            for (label, _s), imgs in zip(samples, decoded):
                if not imgs:
                    logging.debug("Invalid image, skipping.")
                    continue
                if self._fanout is None:
                    self._fanout = len(imgs)
                assert i + len(imgs) <= batch_size, \
                    "Batch size must be multiple of augmenter output length"
                for d in imgs:
                    batch_data[i] = d
                    batch_label[i] = label
                    i += 1
        if i == 0:
            raise StopIteration
        # NHWC → NCHW
        batch_nchw = np.transpose(batch_data, (0, 3, 1, 2))
        return DataBatch(
            [nd.array(batch_nchw)], [nd.array(batch_label)], batch_size - i
        )

    def check_data_shape(self, data_shape):
        if not len(data_shape) == 3:
            raise ValueError("data_shape should have length 3, with dimensions CxHxW")
        if not data_shape[0] == 3 and not data_shape[0] == 1:
            raise ValueError("This iterator expects inputs to have 1 or 3 channels.")

    def read_image(self, fname):
        with open(os.path.join(self.path_root or "", fname), "rb") as fin:
            return fin.read()


class ImageDetIter(DataIter):
    """Detection RecordIO iterator (parity src/io/iter_image_det_recordio.cc:563).

    Reads records packed by im2rec from detection .lst files (imdb.py
    convention: per-image label = [header_width, object_width,
    (id, xmin, ymin, xmax, ymax, ...)...] with normalized corners) and
    emits the C++ iterator's exact label contract per image
    (iter_image_det_recordio.cc:435-444):

        label[0..3] = channels, rows, cols, len(packed_label)
        label[4:4+len] = the packed label
        rest = label_pad_value

    The tensor width is 4 + label_pad_width, auto-estimated as the
    dataset's max packed width when label_pad_width <= 0 (the C++
    default); rand_mirror flips images AND their box x-coordinates (the
    det_aug_default behavior — plain augmenters would silently corrupt
    boxes).
    """

    def __init__(self, batch_size, data_shape, path_imgrec,
                 path_imgidx=None, shuffle=False, label_pad_width=-1,
                 label_pad_value=-1.0, rand_mirror=False, mean_pixels=None,
                 scale=1.0, data_name="data", label_name="label", **kwargs):
        super().__init__()
        if kwargs:
            # silently dropping a misspelled/unported C++ param would
            # train with silently different behavior
            raise TypeError("ImageDetIter: unsupported parameters %s"
                            % sorted(kwargs))
        self.batch_size = batch_size
        self.check_data_shape(data_shape)
        self.data_shape = data_shape
        self.label_pad_value = float(label_pad_value)
        self.rand_mirror = rand_mirror
        self.mean_pixels = (np.asarray(mean_pixels, np.float32)
                            if mean_pixels is not None else None)
        self.scale = scale
        if path_imgidx:
            self.imgrec = recordio.MXIndexedRecordIO(
                path_imgidx, path_imgrec, "r")
            self.seq = list(self.imgrec.keys)
        else:
            self.imgrec = recordio.MXRecordIO(path_imgrec, "r")
            self.seq = None
        if shuffle:
            assert self.seq is not None, "shuffle requires a .idx file"
        self.shuffle = shuffle

        if label_pad_width > 0:
            # explicit width: no startup scan; each record is validated
            # against it as it streams through next()
            self.pad_width = label_pad_width
        else:
            self.pad_width = self._scan_label_widths(path_imgrec)
        self.provide_data = [DataDesc(data_name,
                                      (batch_size,) + data_shape)]
        self.provide_label = [DataDesc(label_name,
                                       (batch_size, 4 + self.pad_width))]
        self.cur = 0
        self.reset()

    @staticmethod
    def _scan_label_widths(path_imgrec):
        """One pass over the record file for the max packed-label width
        (the C++ parser's auto-estimation, iter_image_det_recordio.cc:270)."""
        rec = recordio.MXRecordIO(path_imgrec, "r")
        max_width = 0
        while True:
            s = rec.read()
            if s is None:
                break
            header, _ = recordio.unpack(s)
            width = (header.label.size
                     if isinstance(header.label, np.ndarray) else 1)
            max_width = max(max_width, width)
        rec.close()
        return max_width

    def check_data_shape(self, data_shape):
        if len(data_shape) != 3 or data_shape[0] not in (1, 3):
            raise ValueError(
                "data_shape must be (1|3, H, W), got %s" % (data_shape,))

    def reset(self):
        self.cur = 0
        if self.shuffle:
            np.random.shuffle(self.seq)
        if self.seq is None:
            self.imgrec.reset()

    def _next_record(self):
        if self.seq is not None:
            if self.cur >= len(self.seq):
                return None
            s = self.imgrec.read_idx(self.seq[self.cur])
            self.cur += 1
            return s
        return self.imgrec.read()

    def _flip_boxes(self, buf):
        """Mirror normalized x-coords: xmin' = 1 - xmax, xmax' = 1 - xmin
        (image_det_aug_default.cc HorizontalFlip)."""
        buf = buf.copy()
        header_width = int(buf[0])
        obj_width = int(buf[1])
        objs = buf[header_width:]
        n = objs.size // obj_width
        boxes = objs[: n * obj_width].reshape(n, obj_width)
        xmin = boxes[:, 1].copy()
        boxes[:, 1] = 1.0 - boxes[:, 3]
        boxes[:, 3] = 1.0 - xmin
        buf[header_width:header_width + n * obj_width] = boxes.ravel()
        return buf

    def next(self):
        from PIL import Image

        c, h, w = self.data_shape
        data = np.zeros((self.batch_size, c, h, w), np.float32)
        label = np.full((self.batch_size, 4 + self.pad_width),
                        self.label_pad_value, np.float32)
        n = 0
        while n < self.batch_size:
            s = self._next_record()
            if s is None:
                break
            header, img = recordio.unpack_img(s)
            im = Image.fromarray(img.astype(np.uint8))
            if c == 1:
                im = im.convert("L")
            arr = np.asarray(im.resize((w, h)), np.float32)
            if arr.ndim == 2:
                arr = arr[:, :, None]
            buf = np.atleast_1d(np.asarray(header.label, np.float32))
            if buf.size > self.pad_width:
                raise MXNetError(
                    "label_pad_width %d smaller than record's label "
                    "width %d" % (self.pad_width, buf.size))
            if self.rand_mirror and np.random.rand() < 0.5:
                arr = arr[:, ::-1, :]
                buf = self._flip_boxes(buf)
            if self.mean_pixels is not None:
                arr = arr - self.mean_pixels.reshape(1, 1, -1)
            data[n] = (arr * self.scale).transpose(2, 0, 1)
            label[n, 0] = c
            label[n, 1] = h
            label[n, 2] = w
            label[n, 3] = buf.size
            label[n, 4:4 + buf.size] = buf
            n += 1
        if n == 0:
            raise StopIteration
        return DataBatch([nd.array(data)], [nd.array(label)],
                         self.batch_size - n)


class DetRecordIter(DataIter):
    """SSD-style detection feed (reference example/ssd/dataset/iterator.py
    DetRecordIter): wraps ImageDetIter and reshapes each packed label row
    to (batch, max_objects, object_width), stripping the [c, h, w, len]
    size header and the [header_width, object_width] packing header.
    Module.fit-ready: provide_label is fixed up-front by probing one
    batch (the reference estimates it on the first batch instead)."""

    def __init__(self, path_imgrec, batch_size, data_shape,
                 path_imgidx=None, shuffle=False, label_pad_width=-1,
                 label_name="label", **kwargs):
        super().__init__()
        self._iter = ImageDetIter(
            batch_size=batch_size, data_shape=data_shape,
            path_imgrec=path_imgrec, path_imgidx=path_imgidx,
            shuffle=shuffle, label_pad_width=label_pad_width, **kwargs)
        self.batch_size = batch_size
        self.label_name = label_name
        self.provide_data = self._iter.provide_data
        first = self._iter.next().label[0].asnumpy()
        self._header_width = int(first[0, 4])
        self._obj_width = int(first[0, 5])
        self._start = 4 + self._header_width
        self._max_obj = (first.shape[1] - self._start) // self._obj_width
        if self._obj_width < 5:
            raise MXNetError("object width must be >= 5 (cls + 4 corners)")
        self.provide_label = [DataDesc(
            label_name, (batch_size, self._max_obj, self._obj_width))]
        self._iter.reset()

    def reset(self):
        self._iter.reset()

    def next(self):
        batch = self._iter.next()
        rows = batch.label[0].asnumpy()
        end = self._start + self._max_obj * self._obj_width
        boxes = rows[:, self._start:end].reshape(
            rows.shape[0], self._max_obj, self._obj_width)
        return DataBatch(batch.data, [nd.array(boxes)], batch.pad,
                         provide_data=self.provide_data,
                         provide_label=self.provide_label)
