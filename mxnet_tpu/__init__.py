"""mxnet_tpu: a TPU-native framework with the capability surface of
pre-Gluon Apache MXNet 0.9.5 (reference: Johnqczhang/mxnet), built on
JAX/XLA/Pallas/pjit.

Usage mirrors the reference's ``import mxnet as mx``:

    import mxnet_tpu as mx
    a = mx.nd.ones((2, 3), ctx=mx.tpu())
    data = mx.sym.Variable('data')
    net = mx.sym.FullyConnected(data, num_hidden=10)
    mod = mx.mod.Module(net, context=mx.tpu())
"""
import jax as _jax

# float64 NDArrays are part of the reference API surface (mshadow DType
# switch); jax disables x64 by default — enable it before backend init.
# Weak typing keeps python-scalar arithmetic from promoting float32 arrays.
_jax.config.update("jax_enable_x64", True)

from .base import MXNetError, __version__
from .context import Context, cpu, gpu, tpu, current_context

from . import base
from . import ndarray
from . import ndarray as nd
from . import symbol
from . import symbol as sym
from . import symbol as symbol_doc  # reference keeps this alias
from . import ops
from . import executor
from . import operator
from . import autograd
from . import random
from . import random as rnd
from .attribute import AttrScope
from .name import NameManager, Prefix
from .executor import Executor

from . import initializer
from . import initializer as init
from . import optimizer
from . import lr_scheduler
from . import metric
from . import io
from . import io_pipeline
from . import recordio
from . import kvstore as kvs
from .kvstore import create as _kv_create
from . import kvstore
from . import callback
from . import monitor
from . import module
from . import module as mod
from . import rnn
from . import image
from . import profiler
from . import telemetry
from . import resilience
from . import visualization
from . import visualization as viz
from . import model
from .model import FeedForward
from . import test_utils
from . import engine
from . import parallel
from . import contrib
from . import executor_manager
from . import kvstore_server
from . import rtc
from . import libinfo
from . import log
from . import predict
from . import serving
from . import torch
from . import torch as th

kv = kvstore

# Parity __init__.py:37: non-worker DMLC roles get their documented no-op
# path at import (the PS tier is subsumed by in-step XLA collectives).
kvstore_server._init_kvstore_server_module()
