"""NDArray: the imperative tensor API.

Parity: reference ``python/mxnet/ndarray.py`` + ``src/ndarray/ndarray.cc``
+ ``include/mxnet/ndarray.h``. Design mapping (SURVEY.md §7 table):

- The reference NDArray is a Chunk (storage handle + engine var); every op
  is an engine push and ``WaitToRead`` is the sync point. Here an NDArray
  wraps a ``jax.Array`` — XLA's async dispatch IS the dependency engine
  (data dependencies are tracked by value), ``wait_to_read`` ≈
  ``block_until_ready``.
- ``MXImperativeInvoke`` (reference src/c_api/c_api_ndarray.cc:322 →
  PushFCompute) becomes :func:`imperative_invoke`: one jit-compiled,
  cache-keyed-by-(op, attrs, shapes, dtypes) callable per op instance, so
  steady-state imperative dispatch is a cache hit + async XLA launch.
- In-place mutation (``+=``, ``a[:]=``, out=) rebinds the handle's
  underlying value — the buffer-versioning layer SURVEY.md §7 calls for.
"""
from __future__ import annotations

import functools
import struct
import sys

import numpy as np

from . import autograd as _autograd
from . import random as _random
from .base import MXNetError, mx_dtype_code, np_dtype, dtype_name
from .context import Context, current_context
from .ops import registry as _registry

__all__ = ["NDArray", "zeros", "ones", "array", "empty", "full", "arange",
           "concatenate", "load", "save", "imperative_invoke", "waitall"]

# op-namespace generation below shadows some builtins at module scope
# (slice, sum, abs, ...); capture the ones methods need.
_py_slice = slice


def _jax():
    import jax

    return jax


def _ctx_of_jax_device(dev):
    """Context for a jax.Device — by LOCAL index, not global id.

    Context.jax_device indexes jax.local_devices(), so the round-trip
    must too: in a multi-controller job rank 1's first device has a
    global id >= num_local, and Context('cpu', global_id) would be out
    of range (or, worse, some peer's device)."""
    plat = dev.platform
    jax = _jax()
    try:
        idx = jax.local_devices(backend=plat).index(dev)
    except (RuntimeError, ValueError):
        idx = dev.id  # non-addressable peer device: keep the global id
    if plat == "cpu":
        return Context("cpu", idx)
    if plat in ("tpu", "axon"):
        return Context("tpu", idx)
    return Context("gpu", idx)


def _hashable(v):
    if isinstance(v, (list, tuple)):
        return tuple(_hashable(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _hashable(x)) for k, x in v.items()))
    return v


@functools.lru_cache(maxsize=None)
def _compiled_op(op_name, attr_key, is_train, with_rng):
    """One jitted callable per (op, static attrs, mode). The returned fn
    takes (rng_or_None, *arrays) and returns a tuple of arrays."""
    jax = _jax()
    opdef = _registry.get(op_name)

    def run(rng, *arrays):
        attrs = dict(attr_key)
        if with_rng:
            attrs["__rng__"] = rng
        out = opdef.fcompute(attrs, list(arrays), is_train)
        return tuple(out)

    return jax.jit(run)


def imperative_invoke(opdef, inputs, attrs, out=None):
    """Invoke an operator imperatively on NDArrays.

    Parity: MXImperativeInvoke (c_api_ndarray.cc:322): shape/type inference
    is implicit (abstract-eval inside jit tracing), the engine push is jax's
    async dispatch, and autograd recording hooks in exactly where
    RecordImperativeFCompute does (c_api_ndarray.cc:375).
    """
    if isinstance(opdef, str):
        opdef = _registry.get(opdef)
    if attrs:
        opdef.check_call_attrs(attrs)  # typo net (dmlc::Parameter analog)
    attrs = opdef.canon_attrs(attrs)
    is_train = _autograd.is_training()
    rng = _random.next_key() if opdef.needs_rng else None
    arrays = []
    for x in inputs:
        if isinstance(x, NDArray):
            if x._engine_dep is not None:  # kvstore-managed array
                x._drain_engine()
            arrays.append(x._data)
        else:
            arrays.append(np.asarray(x))
    from jax.core import Tracer

    if any(isinstance(a, Tracer) for a in arrays) or any(
        isinstance(v, Tracer) for v in attrs.values()
    ):
        # Already inside an outer jit trace (e.g. ShardedTrainStep tracing
        # through Optimizer.update): call fcompute inline — no per-op jit
        # cache (tracers are unhashable) and attrs may be traced scalars
        # (lr/wd enter the fused step as per-call inputs).
        run_attrs = dict(attrs)
        if opdef.needs_rng:
            run_attrs["__rng__"] = rng
        results = tuple(opdef.fcompute(run_attrs, list(arrays), is_train))
    else:
        attr_key = tuple(sorted((k, _hashable(v)) for k, v in attrs.items()))
        fn = _compiled_op(opdef.name, attr_key, is_train, opdef.needs_rng)
        results = fn(rng, *arrays)
    # Trailing results map to reference-mutated inputs: explicit
    # mutate_inputs (sgd_mom_update's momentum) or aux states (BatchNorm's
    # moving_mean/var, which the reference mutates via FMutateInputs).
    n_aux = len(opdef.list_auxiliary_states(attrs))
    n_args = opdef.num_inputs(attrs)
    n_writeback = len(opdef.mutate_inputs) + n_aux
    n_out = len(results) - n_writeback
    outs = results[:n_out]
    writeback_idx = list(opdef.mutate_inputs) + list(
        range(n_args, n_args + n_aux)
    )
    for idx, val in zip(writeback_idx, results[n_out:]):
        if idx < len(inputs) and isinstance(inputs[idx], NDArray):
            inputs[idx]._data = val

    if out is not None:
        out_list = [out] if isinstance(out, NDArray) else list(out)
        for o, v in zip(out_list, outs):
            o._data = v
        ret = out_list[0] if len(out_list) == 1 else out_list
    else:
        out_list = [NDArray(v) for v in outs]
        ret = out_list[0] if len(out_list) == 1 else out_list

    if _autograd.is_recording():
        # record ALL inputs positionally; non-NDArray inputs keep their
        # converted array value so backward replay sees the same arity
        recorded = [
            x if isinstance(x, NDArray) else a
            for x, a in zip(inputs, arrays)
        ]
        _autograd.record_op(
            opdef,
            dict(attr_key) | ({"__rng__": rng} if rng is not None else {}),
            recorded,
            out_list,
        )
    return ret


class NDArray:
    """An n-dimensional array on a device, with async-op semantics."""

    __slots__ = ("_data", "_engine_dep")
    # prefer our operators over numpy's in mixed expressions
    __array_priority__ = 1000.0

    def __init__(self, data):
        self._data = data
        # (engine, Var) when a host-side engine op (KVStore push/pull)
        # has claimed this array; None for the overwhelmingly common
        # case where jax's value tracking is the only discipline needed
        self._engine_dep = None

    # -- basic properties ---------------------------------------------------
    @property
    def shape(self):
        return tuple(self._data.shape)

    @property
    def size(self):
        return int(np.prod(self.shape)) if self.shape else 1

    @property
    def ndim(self):
        return self._data.ndim

    @property
    def dtype(self):
        return np_dtype(self._data.dtype)

    @property
    def context(self):
        jax = _jax()
        dev = self._data.device
        if hasattr(dev, "platform"):
            return _ctx_of_jax_device(dev)
        devs = list(self._data.devices())
        return _ctx_of_jax_device(devs[0])

    ctx = context

    @property
    def T(self):
        return imperative_invoke("transpose", [self], {})

    # -- sync ---------------------------------------------------------------
    def _engine_var(self, eng):
        """Attach (or return) this array's dependency Var on engine
        ``eng``. Engine-scheduled host ops (KVStore push/pull) declare
        reads/writes through it; readers drain via _drain_engine."""
        dep = self._engine_dep
        if dep is None or dep[0] is not eng:
            dep = (eng, eng.new_variable())
            self._engine_dep = dep
        return dep[1]

    def _drain_engine(self):
        """Wait for any outstanding engine-scheduled op on this array
        (no-op in the common case: one attribute check)."""
        dep = self._engine_dep
        if dep is not None:
            eng, var = dep
            wait_last = getattr(eng, "wait_last", None)
            if wait_last is not None:
                wait_last(var)
            else:
                eng.wait_for_var(var)

    def wait_to_read(self):
        self._drain_engine()
        self._data.block_until_ready()

    wait_to_write = wait_to_read

    def asnumpy(self):
        self._drain_engine()
        return np.asarray(self._data)

    def __array__(self, dtype=None, copy=None):
        """numpy protocol: without this, np.asarray(nd) falls back to the
        sequence protocol and builds the array ELEMENT-WISE through
        __getitem__ — ~20k traced gathers for a (300, 64) input (found
        via the C++ Predictor, which fed an NDArray to set_input's
        np.asarray and appeared to hang). The numpy-2 ``copy`` contract
        is honored: copy=True always copies; copy=False always raises,
        because materializing device-backed data can never be guaranteed
        zero-copy."""
        if copy is False:
            raise ValueError(
                "NDArray.__array__: cannot guarantee zero-copy for "
                "device-backed data (np.asarray(nd, copy=False))")
        self._drain_engine()
        a = np.asarray(self._data)
        if dtype is not None and a.dtype != np.dtype(dtype):
            return a.astype(dtype, copy=True)
        if copy:
            return a.copy()
        return a

    def asscalar(self):
        if self.size != 1:
            raise MXNetError("The current array is not a scalar")
        return self.asnumpy().reshape(-1)[0]

    # -- conversion / movement ---------------------------------------------
    def astype(self, dtype):
        return NDArray(self._data.astype(np_dtype(dtype)))

    def copyto(self, other):
        jax = _jax()
        if isinstance(other, NDArray):
            if other is self:
                return other
            if other._engine_dep is not None:
                # order this write after any in-flight engine op on the
                # target. The kvstore pull body writes its target via
                # _data assignment (not copyto) precisely so this drain
                # can't self-deadlock the op that holds the var.
                other._drain_engine()
            other._data = jax.device_put(self._data, other._data.device)
            return other
        if isinstance(other, Context):
            return NDArray(jax.device_put(self._data, other.jax_device))
        raise MXNetError("copyto: unsupported target %r" % (other,))

    def copy(self):
        return NDArray(self._data + 0)

    def as_in_context(self, context):
        if self.context == context:
            return self
        return self.copyto(context)

    # -- shape manipulation -------------------------------------------------
    def reshape(self, shape):
        if isinstance(shape, int):
            shape = (shape,)
        return imperative_invoke("Reshape", [self], {"shape": tuple(shape)})

    def broadcast_to(self, shape):
        return imperative_invoke("broadcast_to", [self], {"shape": tuple(shape)})

    # -- indexing -----------------------------------------------------------
    def __getitem__(self, key):
        if isinstance(key, int):
            return NDArray(self._data[key])
        if isinstance(key, _py_slice):
            if key.step is not None and key.step != 1:
                raise MXNetError("NDArray only supports step=1 slicing")
            return NDArray(self._data[key])
        if isinstance(key, tuple):
            return NDArray(self._data[key])
        if isinstance(key, NDArray):
            return NDArray(self._data[key._data.astype("int32")])
        raise MXNetError("unsupported index %r" % (key,))

    def __setitem__(self, key, value):
        import jax.numpy as jnp

        if self._engine_dep is not None:
            # an in-flight engine op (kvstore pull) targeting this array
            # must land BEFORE this write, or it would clobber it later
            self._drain_engine()
        if isinstance(value, NDArray):
            v = value._data
        else:
            v = value
        if isinstance(key, _py_slice) and key.start is None and key.stop is None:
            if np.isscalar(v):
                self._data = jnp.full_like(self._data, v)
            else:
                self._data = jnp.broadcast_to(
                    jnp.asarray(v, dtype=self.dtype), self.shape
                ) + jnp.zeros_like(self._data)
            return
        self._data = self._data.at[key].set(v)

    def slice(self, start, stop):
        return NDArray(self._data[start:stop])

    def at(self, idx):
        return NDArray(self._data[idx])

    # -- arithmetic ---------------------------------------------------------
    def _binop(self, other, op, scalar_op, reverse=False):
        if isinstance(other, NDArray):
            a, b = (other, self) if reverse else (self, other)
            if a.shape == b.shape:
                return imperative_invoke(op, [a, b], {})
            return imperative_invoke("broadcast_" + _BCAST_NAME[op], [a, b], {})
        if np.isscalar(other):
            name = ("_r" + scalar_op[1:]) if reverse and op in _NONCOMMUTATIVE else scalar_op
            return imperative_invoke(name, [self], {"scalar": float(other)})
        if isinstance(other, np.ndarray):
            return self._binop(array(other, ctx=self.context, dtype=self.dtype), op, scalar_op, reverse)
        jax = _jax()
        if isinstance(other, (jax.Array, jax.core.Tracer)):
            # jax values (incl. traced scalars like the fused step's lr)
            # participate directly as NDArray operands
            return self._binop(NDArray(other), op, scalar_op, reverse)
        return NotImplemented

    def __add__(self, o):
        return self._binop(o, "elemwise_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, "elemwise_sub", "_minus_scalar")

    def __rsub__(self, o):
        return self._binop(o, "elemwise_sub", "_minus_scalar", reverse=True)

    def __mul__(self, o):
        return self._binop(o, "elemwise_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __div__(self, o):
        return self._binop(o, "elemwise_div", "_div_scalar")

    __truediv__ = __div__

    def __rdiv__(self, o):
        return self._binop(o, "elemwise_div", "_div_scalar", reverse=True)

    __rtruediv__ = __rdiv__

    def __pow__(self, o):
        return self._binop(o, "_power", "_power_scalar")

    def __rpow__(self, o):
        return self._binop(o, "_power", "_power_scalar", reverse=True)

    def __mod__(self, o):
        return self._binop(o, "_mod", "_mod_scalar")

    def __rmod__(self, o):
        return self._binop(o, "_mod", "_mod_scalar", reverse=True)

    def __neg__(self):
        return imperative_invoke("negative", [self], {})

    def __eq__(self, o):
        if isinstance(o, (NDArray, int, float, np.ndarray)):
            return self._binop(o, "_equal", "_equal_scalar")
        return NotImplemented

    def __ne__(self, o):
        if isinstance(o, (NDArray, int, float, np.ndarray)):
            return self._binop(o, "_not_equal", "_not_equal_scalar")
        return NotImplemented

    def __gt__(self, o):
        return self._binop(o, "_greater", "_greater_scalar")

    def __ge__(self, o):
        return self._binop(o, "_greater_equal", "_greater_equal_scalar")

    def __lt__(self, o):
        return self._binop(o, "_lesser", "_lesser_scalar")

    def __le__(self, o):
        return self._binop(o, "_lesser_equal", "_lesser_equal_scalar")

    __hash__ = object.__hash__

    def __iadd__(self, o):
        r = self.__add__(o)
        self._data = r._data
        return self

    def __isub__(self, o):
        r = self.__sub__(o)
        self._data = r._data
        return self

    def __imul__(self, o):
        r = self.__mul__(o)
        self._data = r._data
        return self

    def __idiv__(self, o):
        r = self.__div__(o)
        self._data = r._data
        return self

    __itruediv__ = __idiv__

    def __len__(self):
        return self.shape[0]

    def __bool__(self):
        if self.size == 1:
            return bool(self.asscalar())
        raise MXNetError("ambiguous truth value of multi-element NDArray")

    def __repr__(self):
        return "<NDArray %s @%s>" % ("x".join(map(str, self.shape)), self.context)

    def __getstate__(self):
        return {"data": self.asnumpy()}

    def __setstate__(self, state):
        import jax.numpy as jnp

        self._data = jnp.asarray(state["data"])


_BCAST_NAME = {
    "elemwise_add": "add",
    "elemwise_sub": "sub",
    "elemwise_mul": "mul",
    "elemwise_div": "div",
    "_power": "power",
    "_mod": "mod",
    "_equal": "equal",
    "_not_equal": "not_equal",
    "_greater": "greater",
    "_greater_equal": "greater_equal",
    "_lesser": "lesser",
    "_lesser_equal": "lesser_equal",
}
_NONCOMMUTATIVE = {"elemwise_sub", "elemwise_div", "_power", "_mod"}


# --------------------------------------------------------------------------
# creation API
# --------------------------------------------------------------------------
def _put(arr, ctx):
    jax = _jax()
    ctx = ctx or current_context()
    return jax.device_put(arr, ctx.jax_device)


def empty(shape, ctx=None, dtype=np.float32):
    return zeros(shape, ctx, dtype)


def zeros(shape, ctx=None, dtype=np.float32):
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(_put(np.zeros(shape, np_dtype(dtype)), ctx))


def ones(shape, ctx=None, dtype=np.float32):
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(_put(np.ones(shape, np_dtype(dtype)), ctx))


def full(shape, val, ctx=None, dtype=np.float32):
    if isinstance(shape, int):
        shape = (shape,)
    return NDArray(_put(np.full(shape, val, np_dtype(dtype)), ctx))


def array(source_array, ctx=None, dtype=None):
    if isinstance(source_array, NDArray):
        arr = source_array.asnumpy()
    else:
        arr = np.asarray(source_array)
    if dtype is None:
        dtype = arr.dtype if arr.dtype != np.float64 else np.float32
    return NDArray(_put(arr.astype(np_dtype(dtype)), ctx))


def arange(start, stop=None, step=1.0, repeat=1, ctx=None, dtype=np.float32):
    if stop is None:
        start, stop = 0, start
    out = np.arange(start, stop, step)
    if repeat > 1:
        out = np.repeat(out, repeat)
    return NDArray(_put(out.astype(np_dtype(dtype)), ctx))


def concatenate(arrays, axis=0, always_copy=True):
    import jax.numpy as jnp

    return NDArray(jnp.concatenate([a._data for a in arrays], axis=axis))


def onehot_encode(indices, out):
    depth = out.shape[1]
    return imperative_invoke("one_hot", [indices], {"depth": depth}, out=out)


def waitall():
    """Parity: MXNDArrayWaitAll — barrier on all async work."""
    _jax().effects_barrier()


# --------------------------------------------------------------------------
# serialization — BINARY-COMPATIBLE with NDArray::Save/Load (reference
# src/ndarray/ndarray.cc:604-689 + python/mxnet/ndarray.py:2063-2097):
# published .params files load here and files written here load in the
# reference. Container layout (all little-endian):
#   uint64 magic=0x112, uint64 reserved=0
#   uint64 n_arrays, then per array (NDArray::Save):
#     uint32 ndim, ndim x uint32 dims          (mshadow TShape::Save)
#     int32 dev_type, int32 dev_id             (Context::Save; written 1,0)
#     int32 type_flag                          (mshadow dtype code)
#     raw contiguous data
#   uint64 n_names, then per name: uint64 len + bytes
# The round-1/2 private MXTPU001 container is still READ for backward
# compatibility with checkpoints written by those rounds.
# --------------------------------------------------------------------------
_DMLC_MAGIC = 0x112
_LEGACY_MAGIC = b"MXTPU001"


def save(fname, data):
    with open(fname, "wb") as f:
        _save_fileobj(f, data)


def save_buffer(data):
    """Serialize NDArrays to bytes (the c_predict param-bytes format)."""
    import io

    f = io.BytesIO()
    _save_fileobj(f, data)
    return f.getvalue()


def _save_fileobj(f, data):
    if isinstance(data, NDArray):
        data = [data]
    if isinstance(data, dict):
        names = list(data.keys())
        arrays = [data[k] for k in names]
    else:
        names = []
        arrays = list(data)
    f.write(struct.pack("<QQ", _DMLC_MAGIC, 0))
    f.write(struct.pack("<Q", len(arrays)))
    for a in arrays:
        arr = np.ascontiguousarray(a.asnumpy())
        if arr.ndim == 0:
            # reference TShape cannot express 0-d (ndim 0 means "none")
            raise MXNetError(
                "cannot save 0-d NDArray in the .params format; "
                "reshape to (1,) first")
        code = mx_dtype_code(arr.dtype)
        if code > 6:
            # bfloat16 (code 12) is a TPU-era extension: the file still
            # round-trips HERE, but reference MXNet's mshadow dtype
            # switch only knows codes 0-6 and would abort loading it
            import warnings

            warnings.warn(
                "saving dtype %s with extension code %d: this .params "
                "file will not load in reference MXNet (cast to float32 "
                "first for cross-compatibility)" % (arr.dtype, code),
                stacklevel=3)
        f.write(struct.pack("<I", arr.ndim))
        f.write(struct.pack("<%dI" % arr.ndim, *arr.shape))
        f.write(struct.pack("<ii", 1, 0))  # Context: cpu(0)
        f.write(struct.pack("<i", code))
        f.write(arr.tobytes())
    f.write(struct.pack("<Q", len(names)))
    for n in names:
        b = n.encode()
        f.write(struct.pack("<Q", len(b)))
        f.write(b)


def load(fname):
    with open(fname, "rb") as f:
        return _load_fileobj(f, fname)


def load_buffer(buf):
    """Deserialize NDArrays from an in-memory bytes buffer (parity: the
    c_predict_api path, MXNDListCreate over param bytes)."""
    import io

    return _load_fileobj(io.BytesIO(buf), "<buffer>")


def _load_fileobj(f, fname):
    head = f.read(8)
    if head == _LEGACY_MAGIC:
        return _load_legacy(f, fname)
    if len(head) < 8 or struct.unpack("<Q", head)[0] != _DMLC_MAGIC:
        raise MXNetError("invalid NDArray file %s" % fname)
    f.read(8)  # reserved
    return _load_dmlc(f, fname)


def _load_dmlc(f, fname):
    from .base import _DTYPE_MX_TO_NP

    (n_arr,) = struct.unpack("<Q", f.read(8))
    arrays = []
    for _ in range(n_arr):
        (ndim,) = struct.unpack("<I", f.read(4))
        if ndim == 0:
            raise MXNetError("%s: empty (none) NDArray entry" % fname)
        shape = struct.unpack("<%dI" % ndim, f.read(4 * ndim))
        f.read(8)  # Context (dev_type, dev_id): arrays land on default ctx
        (code,) = struct.unpack("<i", f.read(4))
        if code not in _DTYPE_MX_TO_NP:
            raise MXNetError("%s: unknown dtype code %d" % (fname, code))
        dt = np.dtype(_DTYPE_MX_TO_NP[code])
        count = int(np.prod(shape))
        arr = np.frombuffer(
            f.read(count * dt.itemsize), dtype=dt).reshape(shape)
        arrays.append(array(arr, dtype=dt))
    (n_names,) = struct.unpack("<Q", f.read(8))
    names = []
    for _ in range(n_names):
        (ln,) = struct.unpack("<Q", f.read(8))
        names.append(f.read(ln).decode())
    if names:
        return dict(zip(names, arrays))
    return arrays


def _load_legacy(f, fname):
    """Round-1/2 MXTPU001 container (magic already consumed)."""
    from .base import _DTYPE_MX_TO_NP

    n_arr, n_names = struct.unpack("<qq", f.read(16))
    names = []
    for _ in range(n_names):
        (ln,) = struct.unpack("<q", f.read(8))
        names.append(f.read(ln).decode())
    arrays = []
    for _ in range(n_arr):
        (code,) = struct.unpack("<q", f.read(8))
        (ndim,) = struct.unpack("<q", f.read(8))
        shape = struct.unpack("<%dq" % ndim, f.read(8 * ndim)) if ndim else ()
        dt = np.dtype(_DTYPE_MX_TO_NP[code])
        count = int(np.prod(shape)) if shape else 1
        arr = np.frombuffer(f.read(count * dt.itemsize), dtype=dt).reshape(shape)
        arrays.append(array(arr, dtype=dt))
    if names:
        return dict(zip(names, arrays))
    return arrays


# --------------------------------------------------------------------------
# op namespace generation — parity with _init_ndarray_module
# (reference ndarray.py:917): every registered op becomes a module function.
# --------------------------------------------------------------------------
def _make_ndarray_function(opdef):
    def fn(*args, **kwargs):
        out = kwargs.pop("out", None)
        kwargs.pop("name", None)
        ctx = kwargs.pop("ctx", None)
        inputs = []
        for a in args:
            if isinstance(a, NDArray):
                inputs.append(a)
            elif isinstance(a, (list, tuple)) and all(
                isinstance(x, NDArray) for x in a
            ):
                inputs.extend(a)
            else:
                inputs.append(a)
        result = imperative_invoke(opdef, inputs, kwargs, out=out)
        if ctx is not None and out is None:
            if isinstance(result, NDArray):
                result = result.copyto(ctx) if result.context != ctx else result
        return result

    fn.__name__ = opdef.name
    fn.__doc__ = opdef.docstring()
    return fn


def _init_ndarray_module():
    module = sys.modules[__name__]
    for name, opdef in list(_registry._REGISTRY.items()):
        if not hasattr(module, name):
            setattr(module, name, _make_ndarray_function(opdef))


def _init_random_module():
    """Expose samplers as mx.random.uniform/normal/... (reference random.py)."""
    rnd = sys.modules[_random.__name__]

    def make(op):
        def fn(*args, **kwargs):
            # reference signature: uniform(low, high, shape, ctx, dtype)
            names = {
                "_sample_uniform": ("low", "high"),
                "_sample_normal": ("loc", "scale"),
                "_sample_gamma": ("alpha", "beta"),
                "_sample_exponential": ("lam",),
                "_sample_poisson": ("lam",),
                "_sample_negbinomial": ("k", "p"),
                "_sample_gennegbinomial": ("mu", "alpha"),
            }[op]
            for n, v in zip(names, args):
                kwargs.setdefault(n, v)
            rest = args[len(names):]
            if rest:
                kwargs.setdefault("shape", rest[0])
            if len(rest) > 1:
                kwargs.setdefault("ctx", rest[1])
            ctx = kwargs.pop("ctx", None)
            out = kwargs.pop("out", None)
            if out is not None:
                kwargs.setdefault("shape", out.shape)
            kwargs.setdefault("shape", (1,))
            r = imperative_invoke(_registry.get(op), [], kwargs, out=out)
            if ctx is not None:
                r = r.copyto(ctx)
            return r

        return fn

    rnd.uniform = make("_sample_uniform")
    rnd.normal = make("_sample_normal")
    rnd.gamma = make("_sample_gamma")
    rnd.exponential = make("_sample_exponential")
    rnd.poisson = make("_sample_poisson")
    rnd.negative_binomial = make("_sample_negbinomial")
    rnd.generalized_negative_binomial = make("_sample_gennegbinomial")


_init_ndarray_module()
_init_random_module()


def imdecode(buf, index=0, flag=1, mean=None, clip_rect=None, out=None,
             **kwargs):
    """Decode an encoded image buffer to an HWC NDArray (parity: the
    reference registers imdecode as an NDArray function,
    src/io/image_io.cc — flag, mean subtraction, clip_rect crop, out).
    Unknown options raise rather than silently change the result."""
    if kwargs:
        raise MXNetError("imdecode: unsupported option(s) %s"
                         % sorted(kwargs))
    from . import image as _image

    img = _image.imdecode(buf, flag=flag)
    if clip_rect is not None:
        x0, y0, x1, y1 = (int(v) for v in clip_rect)
        img = NDArray(img._data[y0:y1, x0:x1])
    if mean is not None:
        mean_arr = mean._data if isinstance(mean, NDArray) else np.asarray(
            mean, np.float32)
        img = NDArray(img._data.astype(np.float32) - mean_arr)
    if out is not None:
        out[:] = img
        return out
    return img
