"""RNN checkpoint helpers.

Parity: reference ``python/mxnet/rnn/rnn.py`` (save/load_rnn_checkpoint
with fused-cell weight pack/unpack, do_rnn_checkpoint).
"""
from __future__ import annotations

from .. import ndarray as nd
from ..model import load_checkpoint, save_checkpoint


def rnn_unroll(cell, length, inputs=None, begin_state=None, input_prefix="",
               layout="NTC"):
    """Deprecated alias (parity rnn/rnn.py:10)."""
    return cell.unroll(
        length, inputs=inputs, begin_state=begin_state,
        input_prefix=input_prefix, layout=layout
    )


def save_rnn_checkpoint(cells, prefix, epoch, symbol, arg_params, aux_params):
    """Parity rnn/rnn.py:15 — unpack fused weights before saving."""
    if isinstance(cells, (list, tuple)):
        for cell in cells:
            arg_params = cell.unpack_weights(arg_params)
    else:
        arg_params = cells.unpack_weights(arg_params)
    save_checkpoint(prefix, epoch, symbol, arg_params, aux_params)


def load_rnn_checkpoint(cells, prefix, epoch):
    """Parity rnn/rnn.py:43."""
    sym, arg, aux = load_checkpoint(prefix, epoch)
    if isinstance(cells, (list, tuple)):
        for cell in cells:
            arg = cell.pack_weights(arg)
    else:
        arg = cells.pack_weights(arg)
    return sym, arg, aux


def do_rnn_checkpoint(cells, prefix, period=1):
    """Epoch-end callback (parity rnn/rnn.py:61)."""
    period = int(max(1, period))

    def _callback(iter_no, sym=None, arg=None, aux=None):
        if (iter_no + 1) % period == 0:
            save_rnn_checkpoint(cells, prefix, iter_no + 1, sym, arg, aux)

    return _callback
