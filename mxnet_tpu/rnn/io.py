"""Bucketed sequence iterators.

Capability parity with reference ``python/mxnet/rnn/io.py``
(BucketSentenceIter, encode_sentences). Buckets are the XLA-friendly
shape discipline (SURVEY.md §3.5): variable-length sequences pad into a
few static widths, one compiled program per width. Re-authored around a
per-bucket matrix + a flat (bucket, row-offset) schedule.
"""
from __future__ import annotations

import random

import numpy as np

from .. import ndarray as nd
from ..io import DataBatch, DataIter, DataDesc
from ..serving import buckets as _buckets


def encode_sentences(sentences, vocab=None, invalid_label=-1,
                     invalid_key="\n", start_label=0):
    """Tokenize nested word lists to int ids, growing the vocab only
    when the caller did not supply one."""
    grow = vocab is None
    if grow:
        vocab = {invalid_key: invalid_label}
    next_id = start_label
    encoded = []
    for sentence in sentences:
        ids = []
        for token in sentence:
            if token not in vocab:
                if not grow:
                    raise AssertionError("Unknown token %s" % token)
                if next_id == invalid_label:
                    next_id += 1
                vocab[token] = next_id
                next_id += 1
            ids.append(vocab[token])
        encoded.append(ids)
    return encoded, vocab


class BucketSentenceIter(DataIter):
    """Pads each sentence into the smallest bucket that fits and serves
    (data, next-token label) batches of one bucket at a time."""

    def __init__(self, sentences, batch_size, buckets=None, invalid_label=-1,
                 data_name="data", label_name="softmax_label",
                 dtype="float32"):
        super().__init__()
        if not buckets:
            # auto-buckets: every length with at least one full batch
            counts = np.bincount([len(s) for s in sentences])
            buckets = [length for length, n in enumerate(counts)
                       if n >= batch_size]
        self.buckets = sorted(buckets)

        per_bucket = [[] for _ in self.buckets]
        n_discarded = 0
        for sentence in sentences:
            # smallest covering bucket — shared with the serving queue
            # (serving/buckets.py is the one implementation of this rule)
            slot = _buckets.smallest_covering(self.buckets, len(sentence))
            if slot is None:
                n_discarded += 1
                continue
            row = _buckets.pad_to_width(
                np.asarray(sentence, dtype=dtype), self.buckets[slot],
                invalid_label)
            per_bucket[slot].append(row)
        # (0, width) for empty buckets keeps label shifting uniform
        self.data = [np.asarray(rows, dtype=dtype).reshape(-1, width)
                     for rows, width in zip(per_bucket, self.buckets)]
        print("WARNING: discarded %d sentences longer than the largest "
              "bucket." % n_discarded)

        self.batch_size = batch_size
        self.data_name = data_name
        self.label_name = label_name
        self.dtype = dtype
        self.invalid_label = invalid_label
        self.major_axis = 0
        self.default_bucket_key = max(self.buckets)
        default = (batch_size, self.default_bucket_key)
        self.provide_data = [DataDesc(data_name, default)]
        self.provide_label = [DataDesc(label_name, default)]
        # schedule: every full batch as a (bucket index, row offset) pair
        self.idx = [
            (b, off)
            for b, rows in enumerate(self.data)
            for off in range(0, len(rows) - batch_size + 1, batch_size)
        ]
        self.curr_idx = 0
        self.reset()

    def reset(self):
        self.curr_idx = 0
        random.shuffle(self.idx)
        for rows in self.data:
            np.random.shuffle(rows)
        # language-model targets: the sequence shifted left by one
        self.nddata, self.ndlabel = [], []
        for rows in self.data:
            target = np.roll(rows, -1, axis=1)
            target[:, -1] = self.invalid_label
            self.nddata.append(nd.array(rows, dtype=self.dtype))
            self.ndlabel.append(nd.array(target, dtype=self.dtype))

    def next(self):
        if self.curr_idx == len(self.idx):
            raise StopIteration
        bucket, off = self.idx[self.curr_idx]
        self.curr_idx += 1
        sl = slice(off, off + self.batch_size)
        data, label = self.nddata[bucket][sl], self.ndlabel[bucket][sl]
        return DataBatch(
            [data], [label], pad=0,
            bucket_key=self.buckets[bucket],
            provide_data=[DataDesc(self.data_name, data.shape)],
            provide_label=[DataDesc(self.label_name, label.shape)],
        )
