"""Data iterators.

Parity: reference ``python/mxnet/io.py`` (DataIter/DataBatch/DataDesc,
NDArrayIter, ResizeIter, PrefetchingIter) plus Python-native equivalents of
the C++ iterators in ``src/io/`` (MNISTIter ← iter_mnist.cc, CSVIter ←
iter_csv.cc, ImageRecordIter ← iter_image_recordio_2.cc). The reference's
PrefetcherIter double-buffering (iter_prefetcher.h) is kept, with produce
ops scheduled on the host dependency engine (mxnet_tpu.engine) — the
host-side pipeline design SURVEY.md §7 maps 1:1.
"""
from __future__ import annotations

import gzip
import os
import struct
import time
from collections import deque, namedtuple

import numpy as np

from . import ndarray as nd
from . import telemetry as _tm
from .base import MXNetError
from .ndarray import NDArray

DataDesc = namedtuple("DataDesc", ["name", "shape"])

_H_FEED_WAIT = _tm.histogram(
    "io.feed_wait_seconds",
    "Host time DeviceFeedIter.next() spends handing over the staged "
    "batch and re-filling the pipeline (the device transfers themselves "
    "are async and overlap compute)")


class DataBatch(object):
    """One mini-batch (parity io.py:82)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter(object):
    """Base iterator (parity io.py:143)."""

    def __init__(self):
        self.batch_size = 0

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(
                data=self.getdata(), label=self.getlabel(),
                pad=self.getpad(), index=self.getindex()
            )
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        pass

    def skip(self, num_batches):
        """Advance past ``num_batches`` batches without using them —
        checkpoint resume repositions a freshly reset iterator this way.
        The generic fallback simply consumes batches; iterators with a
        cheap cursor (NDArrayIter, DeviceFeedIter) override it."""
        for _ in range(int(num_batches)):
            try:
                self.next()
            except StopIteration:
                return

    def getdata(self):
        pass

    def getlabel(self):
        pass

    def getindex(self):
        return None

    def getpad(self):
        pass


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches (parity io.py:233)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Prefetcher over one or more iterators, scheduled on the host
    dependency engine.

    Parity: io.py:298 (python PrefetchingIter) and the native
    PrefetcherIter (src/io/iter_prefetcher.h) — the next batch is
    produced on an engine worker while the caller consumes the current
    one, so host decode overlaps device compute. Each source iterator
    owns an engine Var; produce ops take it as their mutable var, which
    serializes production per source exactly like the reference's
    engine-var discipline (and under MXNET_ENGINE_TYPE=NaiveEngine the
    whole pipeline runs synchronously — the same debug escape hatch,
    threaded_engine.h:329, applied to host IO).
    """

    def __init__(self, iters, rename_data=None, rename_label=None):
        super().__init__()
        from . import engine as _engine

        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0].shape[0]
        self._engine = _engine.get()
        self._slots = [self._engine.new_variable()
                       for _ in range(self.n_iter)]
        self.current_batch = None
        self.next_batch = [None] * self.n_iter
        self._errors = [None] * self.n_iter
        self._pending = [None] * self.n_iter  # opr handles from push()
        self._prefetch_all()

    def _prefetch(self, i):
        def _produce():
            try:
                self.next_batch[i] = self.iters[i].next()
            except StopIteration:
                self.next_batch[i] = None
            except Exception as e:  # surfaced in the consumer thread —
                # swallowing it would silently re-serve a stale batch
                self.next_batch[i] = None
                self._errors[i] = e

        self._pending[i] = self._engine.push(
            _produce, mutable_vars=(self._slots[i],))

    def _prefetch_all(self):
        for i in range(self.n_iter):
            self._prefetch(i)

    def _await_batches(self):
        for i, opr in enumerate(self._pending):
            # wait on the produce op itself when the engine hands back a
            # completion handle — a wait_for_var would push a whole extra
            # read-op per batch; engines without handles fall back to it
            if opr is not None and hasattr(opr, "done"):
                opr.done.wait()
            else:
                self._engine.wait_for_var(self._slots[i])
        for i, err in enumerate(self._errors):
            if err is not None:
                self._errors[i] = None
                raise err

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum(
            [
                [
                    DataDesc(r[x.name], x.shape)
                    if isinstance(x, DataDesc)
                    else DataDesc(r[x[0]], x[1])
                    for x in i.provide_data
                ]
                for r, i in zip(self.rename_data, self.iters)
            ],
            [],
        )

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum(
            [
                [
                    DataDesc(r[x.name], x.shape)
                    if isinstance(x, DataDesc)
                    else DataDesc(r[x[0]], x[1])
                    for x in i.provide_label
                ]
                for r, i in zip(self.rename_label, self.iters)
            ],
            [],
        )

    def reset(self):
        self._await_batches()  # let in-flight produces land first
        for i in self.iters:
            i.reset()
        self._prefetch_all()

    def iter_next(self):
        self._await_batches()
        if self.next_batch[0] is None:
            for i in self.next_batch:
                assert i is None, "Number of entry mismatches between iterators"
            return False
        for batch in self.next_batch:
            assert batch.pad == self.next_batch[0].pad, (
                "Number of entry mismatches between iterators"
            )
        self.current_batch = DataBatch(
            sum([batch.data for batch in self.next_batch], []),
            sum([batch.label for batch in self.next_batch], []),
            self.next_batch[0].pad,
            self.next_batch[0].index,
            provide_data=self.provide_data,
            provide_label=self.provide_label,
        )
        # produce the NEXT round while the caller consumes this one
        self._prefetch_all()
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class DeviceFeedIter(DataIter):
    """Device-resident double-buffered feed: overlap the host->device
    batch transfer with device compute (the input stage of the async
    dispatch pipeline, docs/performance.md).

    Wraps any DataIter and keeps up to ``depth`` upcoming batches'
    ``jax.device_put`` transfers IN FLIGHT onto ``sharding`` (e.g. a
    fused trainer's dp-sharded ``batch_sharding()``; for dp×tp meshes
    ``PartitionSpec('dp')`` shards rows over dp and replicates over the
    other axes). device_put is async: by the time the consumer finishes
    computing step i, step i+1's bytes are already resident, and
    Module's fused path recognizes the placement (sharding equality in
    ``_make_fused_batch``) and hands the arrays straight to the
    compiled step — the per-step synchronous asnumpy + device_put
    disappears from the hot loop.

    The reference's PrefetcherIter (iter_prefetcher.h) overlaps host
    DECODE with compute; this adds the host->device TRANSFER overlap
    that TF's input pipelines treat as structural (Abadi et al.,
    arXiv:1605.08695). Labels ride ``label_sharding`` when given,
    ``sharding`` otherwise.

    ``BaseModule.fit`` wraps the training iterator automatically when
    the fused path engages (opt out with MXTPU_DEVICE_FEED=0); wrap
    manually for custom loops. Not used on multi-process feeds (each
    process holds only its local rows — make_array_from_process_local_data
    territory).
    """

    def __init__(self, data_iter, sharding, label_sharding=None, depth=None):
        super().__init__()
        if depth is None:
            try:
                depth = int(os.environ.get("MXTPU_FEED_DEPTH", "2"))
            except ValueError:
                depth = 2
        if depth < 1:
            raise MXNetError("DeviceFeedIter depth must be >= 1, got %d"
                             % depth)
        self.iter = data_iter
        self.depth = depth
        self._sharding = sharding
        self._label_sharding = (label_sharding if label_sharding is not None
                                else sharding)
        self.batch_size = data_iter.batch_size
        self._staged = deque()
        self._exhausted = False
        self.current_batch = None
        self._fill()

    @property
    def provide_data(self):
        return self.iter.provide_data

    @property
    def provide_label(self):
        return self.iter.provide_label

    def _place(self, arr, sharding):
        import jax

        data = arr._data if isinstance(arr, NDArray) else np.asarray(arr)
        return NDArray(jax.device_put(data, sharding))

    def _stage_one(self):
        """Pull one host batch and ENQUEUE its device transfer (async:
        device_put returns immediately; the copy overlaps compute)."""
        if self._exhausted:
            return False
        try:
            b = self.iter.next()
        except StopIteration:
            self._exhausted = True
            return False
        self._staged.append(DataBatch(
            data=[self._place(a, self._sharding) for a in (b.data or [])],
            label=[self._place(a, self._label_sharding)
                   for a in (b.label or [])],
            pad=b.pad, index=b.index, bucket_key=b.bucket_key,
            provide_data=b.provide_data, provide_label=b.provide_label,
        ))
        return True

    def _fill(self):
        while len(self._staged) < self.depth and self._stage_one():
            pass

    def reset(self):
        # staged transfers are abandoned, not awaited: jax arrays are
        # immutable, so dropping the references mid-flight is safe
        self._staged.clear()
        self._exhausted = False
        self.current_batch = None
        self.iter.reset()
        self._fill()

    def rewind(self, seek_inner):
        """Guardrail rewind repositioning: drop every staged transfer
        (mid-flight abandonment is safe — jax arrays are immutable),
        hand the INNER iterator to ``seek_inner`` for repositioning
        (``seek_epoch``/``reset``), then restage from the new cursor."""
        self._staged.clear()
        self._exhausted = False
        self.current_batch = None
        seek_inner(self.iter)
        self._fill()

    def skip(self, num_batches):
        """Resume repositioning: drop already-staged transfers first
        (their references die; jax arrays are immutable so mid-flight
        abandonment is safe), push the remainder down to the inner
        iterator's (possibly O(1)) skip, then restage."""
        num_batches = int(num_batches)
        while num_batches > 0 and self._staged:
            self._staged.popleft()
            num_batches -= 1
        if num_batches > 0:
            self.iter.skip(num_batches)
        self._fill()

    def next(self):
        t0 = time.perf_counter()
        if not self._staged:
            self._fill()
        if not self._staged:
            raise StopIteration
        self.current_batch = self._staged.popleft()
        self._fill()  # keep `depth` transfers in flight
        _H_FEED_WAIT.observe(time.perf_counter() - t0)
        return self.current_batch

    def iter_next(self):
        try:
            self.next()
            return True
        except StopIteration:
            return False

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


def _init_data(data, allow_empty, default_name):
    """Normalize input data to list of (name, numpy) (parity io.py:431)."""
    assert data is not None or allow_empty
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of them or dict")
    for k, v in data.items():
        if isinstance(v, NDArray):
            data[k] = v.asnumpy()
    return list(data.items())


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (parity io.py:470)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data", label_name="softmax_label"):
        super().__init__()
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True, default_name=label_name)
        self.idx = np.arange(self.data[0][1].shape[0])
        if shuffle:
            np.random.shuffle(self.idx)
            self.data = [(k, v[self.idx]) for k, v in self.data]
            self.label = [(k, v[self.idx]) for k, v in self.label]
        if last_batch_handle == "discard":
            new_n = self.data[0][1].shape[0] - self.data[0][1].shape[0] % batch_size
            self.idx = self.idx[:new_n]
        self.data_list = [x[1] for x in self.data] + [x[1] for x in self.label]
        self.num_source = len(self.data_list)
        self.num_data = self.idx.shape[0]
        assert self.num_data >= batch_size, "batch_size needs to be smaller than data size."
        self.cursor = -batch_size
        self.batch_size = batch_size
        self.last_batch_handle = last_batch_handle

    @property
    def provide_data(self):
        return [
            DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])))
            for k, v in self.data
        ]

    @property
    def provide_label(self):
        return [
            DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])))
            for k, v in self.label
        ]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self.last_batch_handle == "roll_over" and self.cursor > self.num_data:
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def skip(self, num_batches):
        # cursor math, no data touched: resume repositioning is O(1).
        # Clamped exactly where sequential next() calls stop (the
        # increment of the first failing iter_next still lands, then
        # the generic DataIter.skip breaks on StopIteration): an
        # unclamped overshoot inflates the cursor past that point, and
        # roll_over's reset() derives the next epoch's wrap offset from
        # the cursor — skip(k) must leave the same value k next()s would.
        target = self.cursor + int(num_batches) * self.batch_size
        if target >= self.num_data:
            to_end = -(-(self.num_data - self.cursor) // self.batch_size)
            target = min(target,
                         self.cursor + max(1, to_end) * self.batch_size)
        self.cursor = target

    def next(self):
        if self.iter_next():
            return DataBatch(
                data=self.getdata(), label=self.getlabel(),
                pad=self.getpad(), index=None
            )
        raise StopIteration

    def _getdata(self, data_source):
        assert self.cursor < self.num_data, "DataIter needs reset."
        if self.cursor + self.batch_size <= self.num_data:
            return [
                nd.array(x[1][self.cursor : self.cursor + self.batch_size])
                for x in data_source
            ]
        pad = self.batch_size - self.num_data + self.cursor
        return [
            nd.array(np.concatenate((x[1][self.cursor :], x[1][:pad]), axis=0))
            for x in data_source
        ]

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if self.last_batch_handle == "pad" and self.cursor + self.batch_size > self.num_data:
            return self.cursor + self.batch_size - self.num_data
        return 0


class MNISTIter(DataIter):
    """MNIST idx-format reader (parity src/io/iter_mnist.cc:241)."""

    def __init__(self, image, label, batch_size=128, shuffle=True, flat=False,
                 silent=False, seed=0, input_shape=None, **kwargs):
        super().__init__()
        with (gzip.open(image, "rb") if image.endswith(".gz") else open(image, "rb")) as f:
            magic, num, rows, cols = struct.unpack(">IIII", f.read(16))
            imgs = np.frombuffer(f.read(), dtype=np.uint8).reshape(num, rows, cols)
        with (gzip.open(label, "rb") if label.endswith(".gz") else open(label, "rb")) as f:
            magic, num = struct.unpack(">II", f.read(8))
            lbls = np.frombuffer(f.read(), dtype=np.uint8)
        imgs = imgs.astype(np.float32) / 255.0
        if flat:
            imgs = imgs.reshape(imgs.shape[0], -1)
        else:
            imgs = imgs.reshape(imgs.shape[0], 1, rows, cols)
        if input_shape is not None:
            imgs = imgs.reshape((imgs.shape[0],) + tuple(input_shape))
        if shuffle:
            rng = np.random.RandomState(seed)
            order = rng.permutation(imgs.shape[0])
            imgs, lbls = imgs[order], lbls[order]
        self._inner = NDArrayIter(
            imgs, lbls.astype(np.float32), batch_size=batch_size,
            last_batch_handle="discard"
        )
        self.batch_size = batch_size

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


class CSVIter(DataIter):
    """CSV reader (parity src/io/iter_csv.cc:132)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        super().__init__()
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32, ndmin=2)
        data = data.reshape((-1,) + tuple(data_shape))
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32, ndmin=2)
            label = label.reshape((-1,) + tuple(label_shape))
            if label_shape == (1,):
                label = label.reshape(-1)
        else:
            label = np.zeros(data.shape[0], dtype=np.float32)
        self._inner = NDArrayIter(
            data, label, batch_size=batch_size,
            last_batch_handle="roll_over" if round_batch else "pad",
        )
        self.batch_size = batch_size

    @property
    def provide_data(self):
        return self._inner.provide_data

    @property
    def provide_label(self):
        return self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


def ImageRecordIter(**kwargs):
    """RecordIO image iterator (parity src/io/iter_image_recordio_2.cc:559).
    Implemented over mx.image.ImageIter + PrefetchingIter; accepts the
    reference's main params (path_imgrec, data_shape, batch_size,
    mean_r/g/b, scale, rand_crop, rand_mirror, shuffle,
    preprocess_threads). With ``input_workers`` > 0 (or
    ``MXTPU_INPUT_WORKERS``) the streaming pipeline takes over:
    chunk-sharded reads by (host_rank, num_hosts), a spawn-safe process
    decode pool, and the ``MXTPU_SHUFFLE_BUFFER`` cross-chunk shuffle —
    see ``io_pipeline.StreamingImageRecordIter``."""
    from .image import ImageIter

    return ImageIter.from_recordio_params(**kwargs)


def ImageDetRecordIter(**kwargs):
    """Detection RecordIO iterator (parity
    src/io/iter_image_det_recordio.cc:563): variable-width box labels,
    emitted with the C++ label contract [c, h, w, len, packed..., pad]."""
    from .image import ImageDetIter

    return ImageDetIter(**kwargs)


def DetRecordIter(**kwargs):
    """Module.fit-ready detection feed: ImageDetRecordIter + the SSD
    label reshape to (batch, max_objects, object_width) (reference
    example/ssd/dataset/iterator.py DetRecordIter)."""
    from .image import DetRecordIter as _Det

    return _Det(**kwargs)


MXDataIter = DataIter  # reference exposes C-iterator wrapper under this name
