"""ResNet v2 (pre-activation) — the north-star benchmark model.

Capability parity: reference example/image-classification/symbols/resnet.py
(accuracy goldens BASELINE.md: resnet-50 top-1 0.7527). Built fresh,
TPU-first: the whole network compiles to one XLA module; BatchNorm keeps
per-replica stats (reference convergence behavior); bf16-friendly (conv/
matmul accumulate fp32 via the op library's preferred_element_type).
"""
from .. import symbol as sym


def residual_unit(data, num_filter, stride, dim_match, name,
                  bottle_neck=True, bn_mom=0.9):
    if bottle_neck:
        bn1 = sym.BatchNorm(data, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                            name=name + "_bn1")
        act1 = sym.Activation(bn1, act_type="relu", name=name + "_relu1")
        conv1 = sym.Convolution(act1, num_filter=num_filter // 4,
                                kernel=(1, 1), stride=(1, 1), pad=(0, 0),
                                no_bias=True, name=name + "_conv1")
        bn2 = sym.BatchNorm(conv1, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                            name=name + "_bn2")
        act2 = sym.Activation(bn2, act_type="relu", name=name + "_relu2")
        conv2 = sym.Convolution(act2, num_filter=num_filter // 4,
                                kernel=(3, 3), stride=stride, pad=(1, 1),
                                no_bias=True, name=name + "_conv2")
        bn3 = sym.BatchNorm(conv2, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                            name=name + "_bn3")
        act3 = sym.Activation(bn3, act_type="relu", name=name + "_relu3")
        conv3 = sym.Convolution(act3, num_filter=num_filter, kernel=(1, 1),
                                stride=(1, 1), pad=(0, 0), no_bias=True,
                                name=name + "_conv3")
        if dim_match:
            shortcut = data
        else:
            shortcut = sym.Convolution(act1, num_filter=num_filter,
                                       kernel=(1, 1), stride=stride,
                                       no_bias=True, name=name + "_sc")
        return conv3 + shortcut
    bn1 = sym.BatchNorm(data, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                        name=name + "_bn1")
    act1 = sym.Activation(bn1, act_type="relu", name=name + "_relu1")
    conv1 = sym.Convolution(act1, num_filter=num_filter, kernel=(3, 3),
                            stride=stride, pad=(1, 1), no_bias=True,
                            name=name + "_conv1")
    bn2 = sym.BatchNorm(conv1, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                        name=name + "_bn2")
    act2 = sym.Activation(bn2, act_type="relu", name=name + "_relu2")
    conv2 = sym.Convolution(act2, num_filter=num_filter, kernel=(3, 3),
                            stride=(1, 1), pad=(1, 1), no_bias=True,
                            name=name + "_conv2")
    if dim_match:
        shortcut = data
    else:
        shortcut = sym.Convolution(act1, num_filter=num_filter, kernel=(1, 1),
                                   stride=stride, no_bias=True,
                                   name=name + "_sc")
    return conv2 + shortcut


def convert_stem_to_s2d(conv0_weight):
    """Convert a trained standard-stem kernel (O, C, 7, 7) to the
    space-to-depth stem's (O, 4C, 4, 4) — numerically EXACT, so zoo
    checkpoints keep working under stem_s2d=True.

    Derivation: y[i] = sum_p x[2i+p-3] w[p]. Writing the input index as
    2M+dm (dm = parity) maps tap p to (U, dm) with p = 2U+dm-1 after
    zero-padding w front-first to 8; the input needs asymmetric pad
    (2, 1) in s2d space. Verified tap-exact in
    tests/test_resnet_s2d.py."""
    import numpy as np

    w = conv0_weight.asnumpy() if hasattr(conv0_weight, "asnumpy") \
        else np.asarray(conv0_weight)
    o, c = w.shape[:2]
    w8 = np.zeros((o, c, 8, 8), w.dtype)
    w8[:, :, 1:, 1:] = w
    return (w8.reshape(o, c, 4, 2, 4, 2).transpose(0, 1, 3, 5, 2, 4)
            .reshape(o, c * 4, 4, 4))


def resnet(units, num_stages, filter_list, num_classes, image_shape,
           bottle_neck=True, bn_mom=0.9, dtype="float32",
           stem_s2d=False):
    data = sym.Variable("data")
    (nchannel, height, width) = image_shape
    data = sym.BatchNorm(data, fix_gamma=True, eps=2e-5, momentum=bn_mom,
                         name="bn_data")
    if dtype != "float32":
        # reference resnet_fp16.py pattern: cast after the input BN, cast
        # back before the loss head; infer_type then makes every weight
        # in between reduced-precision (bf16 on the MXU)
        data = sym.Cast(data, dtype=dtype, name="cast_in")
    if height <= 32:  # cifar
        body = sym.Convolution(data, num_filter=filter_list[0],
                               kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                               no_bias=True, name="conv0")
    elif stem_s2d:
        # MLPerf resnet-on-TPU stem: the 7x7/s2 conv on C=3 starves the
        # MXU's 128 lanes; 2x2 space-to-depth makes it the EXACT-
        # equivalent 4x4/s1 conv on C=12 (see convert_stem_to_s2d for
        # the tap mapping; asymmetric (2,1) pad preserves all 112
        # outputs). XLA folds the Pad into the conv.
        body = sym.Reshape(data, shape=(0, nchannel, height // 2, 2,
                                        width // 2, 2))
        body = sym.transpose(body, axes=(0, 1, 3, 5, 2, 4))
        body = sym.Reshape(body, shape=(0, nchannel * 4, height // 2,
                                        width // 2))
        body = sym.Pad(body, pad_width=(0, 0, 0, 0, 2, 1, 2, 1),
                       mode="constant")
        body = sym.Convolution(body, num_filter=filter_list[0],
                               kernel=(4, 4), stride=(1, 1), pad=(0, 0),
                               no_bias=True, name="conv0")
        body = sym.BatchNorm(body, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                             name="bn0")
        body = sym.Activation(body, act_type="relu", name="relu0")
        body = sym.Pooling(body, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                           pool_type="max")
    else:  # imagenet
        body = sym.Convolution(data, num_filter=filter_list[0],
                               kernel=(7, 7), stride=(2, 2), pad=(3, 3),
                               no_bias=True, name="conv0")
        body = sym.BatchNorm(body, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                             name="bn0")
        body = sym.Activation(body, act_type="relu", name="relu0")
        body = sym.Pooling(body, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                           pool_type="max")
    for i in range(num_stages):
        stride = (1, 1) if i == 0 else (2, 2)
        body = residual_unit(body, filter_list[i + 1], stride, False,
                             name="stage%d_unit%d" % (i + 1, 1),
                             bottle_neck=bottle_neck, bn_mom=bn_mom)
        for j in range(units[i] - 1):
            body = residual_unit(body, filter_list[i + 1], (1, 1), True,
                                 name="stage%d_unit%d" % (i + 1, j + 2),
                                 bottle_neck=bottle_neck, bn_mom=bn_mom)
    bn1 = sym.BatchNorm(body, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                        name="bn1")
    relu1 = sym.Activation(bn1, act_type="relu", name="relu1")
    pool1 = sym.Pooling(relu1, global_pool=True, kernel=(7, 7),
                        pool_type="avg", name="pool1")
    flat = sym.Flatten(pool1)
    fc1 = sym.FullyConnected(flat, num_hidden=num_classes, name="fc1")
    if dtype != "float32":
        fc1 = sym.Cast(fc1, dtype="float32", name="cast_out")
    return sym.SoftmaxOutput(fc1, name="softmax")


def get_symbol(num_classes=1000, num_layers=50, image_shape="3,224,224",
               dtype="float32", stem_s2d=False, **kwargs):
    """Parity with the reference CLI surface: --num-layers picks depth."""
    if isinstance(image_shape, str):
        image_shape = tuple(int(x) for x in image_shape.split(","))
    (nchannel, height, width) = image_shape
    if height <= 28:
        num_stages = 3
        if (num_layers - 2) % 9 == 0 and num_layers >= 164:
            per_unit = [(num_layers - 2) // 9]
            filter_list = [16, 64, 128, 256]
            bottle_neck = True
        elif (num_layers - 2) % 6 == 0 and num_layers < 164:
            per_unit = [(num_layers - 2) // 6]
            filter_list = [16, 16, 32, 64]
            bottle_neck = False
        else:
            raise ValueError("no experiments done on num_layers %d" % num_layers)
        units = per_unit * num_stages
    else:
        if num_layers >= 50:
            filter_list = [64, 256, 512, 1024, 2048]
            bottle_neck = True
        else:
            filter_list = [64, 64, 128, 256, 512]
            bottle_neck = False
        num_stages = 4
        units_map = {
            18: [2, 2, 2, 2],
            34: [3, 4, 6, 3],
            50: [3, 4, 6, 3],
            101: [3, 4, 23, 3],
            152: [3, 8, 36, 3],
            200: [3, 24, 36, 3],
            269: [3, 30, 48, 8],
        }
        if num_layers not in units_map:
            raise ValueError("no experiments done on num_layers %d" % num_layers)
        units = units_map[num_layers]
    return resnet(
        units=units, num_stages=num_stages, filter_list=filter_list,
        num_classes=num_classes, image_shape=image_shape,
        bottle_neck=bottle_neck, dtype=dtype,
        stem_s2d=stem_s2d,
    )
