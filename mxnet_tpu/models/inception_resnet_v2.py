"""Inception-ResNet-v2 (capability parity: reference
example/image-classification/symbols/inception-resnet-v2.py).

Built fresh from Szegedy et al. 2016 ("Inception-v4, Inception-ResNet
and the Impact of Residual Connections"): the three residual block
families (35x35, 17x17, 8x8) are one generic scaled-residual builder
over declarative tower tables — ``net += scale * towers(net)`` — instead
of three hand-unrolled factories. (The reference transcribes the paper
with a 129-filter typo in its 17x17 reduce; this build uses the paper's
128.) All convs are BN+ReLU, residual-merge convs linear, faithful to
the paper.
"""
from .. import symbol as sym


def _conv(data, num_filter, kernel, stride=(1, 1), pad=(0, 0), act=True,
          name=None):
    c = sym.Convolution(data, num_filter=num_filter, kernel=kernel,
                        stride=stride, pad=pad, name="%s_conv" % name)
    b = sym.BatchNorm(c, fix_gamma=False, eps=2e-5, momentum=0.9,
                      name="%s_bn" % name)
    if not act:
        return b
    return sym.Activation(b, act_type="relu", name="%s_relu" % name)


def _chain(net, specs, name):
    """Run ``net`` through a tower: [(filters, kernel, pad, stride), ...]."""
    for i, (nf, kernel, pad, stride) in enumerate(specs):
        net = _conv(net, nf, kernel, stride=stride, pad=pad,
                    name="%s_%d" % (name, i))
    return net


# tower tables per residual family: (filters, kernel, pad, stride)
def _t(nf, kernel=(1, 1), pad=(0, 0), stride=(1, 1)):
    return (nf, kernel, pad, stride)


_FAMILIES = {
    # 35x35 over 320 channels
    "block35": dict(
        channels=320, scale=0.17,
        towers=[
            [_t(32)],
            [_t(32), _t(32, (3, 3), (1, 1))],
            [_t(32), _t(48, (3, 3), (1, 1)), _t(64, (3, 3), (1, 1))],
        ]),
    # 17x17 over 1088 channels (asymmetric 1x7/7x1 factorization)
    "block17": dict(
        channels=1088, scale=0.10,
        towers=[
            [_t(192)],
            [_t(128), _t(160, (1, 7), (0, 3)), _t(192, (7, 1), (3, 0))],
        ]),
    # 8x8 over 2080 channels (1x3/3x1)
    "block8": dict(
        channels=2080, scale=0.20,
        towers=[
            [_t(192)],
            [_t(192), _t(224, (1, 3), (0, 1)), _t(256, (3, 1), (1, 0))],
        ]),
}


def _res_block(net, family, name, act=True):
    cfg = _FAMILIES[family]
    mixed = sym.Concat(
        *[_chain(net, tower, "%s_t%d" % (name, i))
          for i, tower in enumerate(cfg["towers"])],
        name="%s_mixed" % name)
    up = _conv(mixed, cfg["channels"], (1, 1), act=False,
               name="%s_up" % name)
    net = net + up * cfg["scale"]
    if act:
        net = sym.Activation(net, act_type="relu", name="%s_out" % name)
    return net


def get_symbol(num_classes=1000, **kwargs):
    data = sym.Variable("data")
    # stem (299x299 -> 35x35x320)
    net = _conv(data, 32, (3, 3), stride=(2, 2), name="stem1a")
    net = _conv(net, 32, (3, 3), name="stem2a")
    net = _conv(net, 64, (3, 3), pad=(1, 1), name="stem2b")
    net = sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pool_type="max",
                      name="stem_pool3a")
    net = _conv(net, 80, (1, 1), name="stem3b")
    net = _conv(net, 192, (3, 3), name="stem4a")
    net = sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pool_type="max",
                      name="stem_pool5a")
    mixed_5b = sym.Concat(
        _chain(net, [_t(96)], "m5b_t0"),
        _chain(net, [_t(48), _t(64, (5, 5), (2, 2))], "m5b_t1"),
        _chain(net, [_t(64), _t(96, (3, 3), (1, 1)),
                     _t(96, (3, 3), (1, 1))], "m5b_t2"),
        _chain(sym.Pooling(net, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                           pool_type="avg", name="m5b_pool"),
               [_t(64)], "m5b_t3"),
        name="mixed_5b")
    net = mixed_5b
    for i in range(10):
        net = _res_block(net, "block35", "b35_%d" % i)
    # reduction A (35 -> 17)
    net = sym.Concat(
        _chain(net, [_t(384, (3, 3), (0, 0), (2, 2))], "redA_t0"),
        _chain(net, [_t(256), _t(256, (3, 3), (1, 1)),
                     _t(384, (3, 3), (0, 0), (2, 2))], "redA_t1"),
        sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pool_type="max",
                    name="redA_pool"),
        name="reduction_a")
    for i in range(20):
        net = _res_block(net, "block17", "b17_%d" % i)
    # reduction B (17 -> 8)
    net = sym.Concat(
        _chain(net, [_t(256), _t(384, (3, 3), (0, 0), (2, 2))], "redB_t0"),
        _chain(net, [_t(256), _t(288, (3, 3), (0, 0), (2, 2))], "redB_t1"),
        _chain(net, [_t(256), _t(288, (3, 3), (1, 1)),
                     _t(320, (3, 3), (0, 0), (2, 2))], "redB_t2"),
        sym.Pooling(net, kernel=(3, 3), stride=(2, 2), pool_type="max",
                    name="redB_pool"),
        name="reduction_b")
    for i in range(9):
        net = _res_block(net, "block8", "b8_%d" % i)
    net = _res_block(net, "block8", "b8_9", act=False)
    net = _conv(net, 1536, (1, 1), name="head")
    net = sym.Pooling(net, global_pool=True, kernel=(1, 1),
                      pool_type="avg", name="global_pool")
    net = sym.Flatten(net, name="flatten")
    net = sym.Dropout(net, p=0.2, name="dropout")
    net = sym.FullyConnected(net, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(net, name="softmax")
