"""SSD-300 detection network (VGG-16 reduced backbone).

Capability parity with the reference SSD example
(``example/ssd/symbol/symbol_vgg16_ssd_300.py``): a multi-scale feature
pyramid over a reduced VGG-16, per-scale location/class convolutional
heads, ``MultiBoxPrior`` anchors, and a training head built from
``MultiBoxTarget`` + ``SoftmaxOutput(multi_output)`` + smooth-L1
``MakeLoss``, grouped into a multi-output symbol — the workload SURVEY.md
§7 lists as north-star 4a (multi-output executor). Built fresh for TPU:
every conv lowers to ``lax.conv_general_dilated`` on the MXU; the whole
multi-loss graph compiles to ONE XLA module, so the three heads fuse with
the backbone instead of being separate CUDA kernel launches.
"""
from __future__ import annotations

from .. import initializer
from .. import symbol as sym
from ..contrib import symbol as contrib_sym


def _conv_act(data, name, num_filter, kernel=(3, 3), pad=(1, 1),
              stride=(1, 1), dilate=(1, 1)):
    net = sym.Convolution(data, kernel=kernel, pad=pad, stride=stride,
                          dilate=dilate, num_filter=num_filter, name=name)
    return sym.Activation(net, act_type="relu", name="relu_" + name)


def vgg16_reduced(data):
    """VGG-16 through conv5_3 with the SSD modifications: pool5 is 3x3
    stride-1, fc6/fc7 become dilated convolutions. Returns
    (conv4_3, relu7) — the first two feature sources."""
    net = data
    cfg = [(2, 64), (2, 128), (3, 256)]
    for i, (reps, filt) in enumerate(cfg):
        for j in range(reps):
            net = _conv_act(net, "conv%d_%d" % (i + 1, j + 1), filt)
        net = sym.Pooling(net, pool_type="max", kernel=(2, 2), stride=(2, 2),
                          pooling_convention="full", name="pool%d" % (i + 1))
    for j in range(3):
        net = _conv_act(net, "conv4_%d" % (j + 1), 512)
    conv4_3 = net
    net = sym.Pooling(net, pool_type="max", kernel=(2, 2), stride=(2, 2),
                      pooling_convention="full", name="pool4")
    for j in range(3):
        net = _conv_act(net, "conv5_%d" % (j + 1), 512)
    net = sym.Pooling(net, pool_type="max", kernel=(3, 3), stride=(1, 1),
                      pad=(1, 1), name="pool5")
    net = _conv_act(net, "fc6", 1024, kernel=(3, 3), pad=(6, 6),
                    dilate=(6, 6))
    net = _conv_act(net, "fc7", 1024, kernel=(1, 1), pad=(0, 0))
    return conv4_3, net


def _extra_layers(relu7):
    """SSD extra feature layers: 1x1 squeeze then 3x3 stride-2."""
    sources = []
    net = relu7
    cfg = [("6", 256, 512), ("7", 128, 256), ("8", 128, 256)]
    for suffix, squeeze, expand in cfg:
        net = _conv_act(net, "conv%s_1" % suffix, squeeze, kernel=(1, 1),
                        pad=(0, 0))
        net = _conv_act(net, "conv%s_2" % suffix, expand, kernel=(3, 3),
                        pad=(1, 1), stride=(2, 2))
        sources.append(net)
    pool6 = sym.Pooling(net, pool_type="avg", global_pool=True,
                        kernel=(1, 1), name="pool6")
    sources.append(pool6)
    return sources


# Default SSD-300 anchor configuration (reference
# symbol_vgg16_ssd_300.py:112-127 equivalent scales/ratios).
DEFAULT_SIZES = [
    (0.1, 0.141), (0.2, 0.272), (0.37, 0.447),
    (0.54, 0.619), (0.71, 0.79), (0.88, 0.961),
]
DEFAULT_RATIOS = [
    (1, 2, 0.5), (1, 2, 0.5, 3, 1.0 / 3), (1, 2, 0.5, 3, 1.0 / 3),
    (1, 2, 0.5, 3, 1.0 / 3), (1, 2, 0.5), (1, 2, 0.5),
]
DEFAULT_NORMALIZATION = [20, -1, -1, -1, -1, -1]


def multibox_layer(from_layers, num_classes, sizes=DEFAULT_SIZES,
                   ratios=DEFAULT_RATIOS, normalization=DEFAULT_NORMALIZATION,
                   clip=False):
    """Build per-scale loc/cls heads + anchors and concatenate.

    Returns (loc_preds [B, A*4], cls_preds [B, (C+1)*A] flattened-per-anchor,
    anchors [1, A, 4]).
    """
    loc_layers, cls_layers, anchor_layers = [], [], []
    num_label_classes = num_classes + 1  # background = class 0
    for k, from_layer in enumerate(from_layers):
        name = "mb%d" % k
        net = from_layer
        if normalization[k] > 0:
            net = sym.L2Normalization(net, mode="channel",
                                      name=name + "_l2norm")
            scale = sym.Variable(name + "_scale", shape=(1, 512, 1, 1),
                                 init=initializer.Constant(
                                     float(normalization[k])))
            net = sym.broadcast_mul(net, scale)
        size, ratio = sizes[k], ratios[k]
        num_anchors = len(size) + len(ratio) - 1

        loc = sym.Convolution(net, kernel=(3, 3), pad=(1, 1),
                              num_filter=num_anchors * 4,
                              name=name + "_loc_pred_conv")
        loc = sym.transpose(loc, axes=(0, 2, 3, 1))
        loc = sym.Flatten(loc)
        loc_layers.append(loc)

        cls = sym.Convolution(net, kernel=(3, 3), pad=(1, 1),
                              num_filter=num_anchors * num_label_classes,
                              name=name + "_cls_pred_conv")
        cls = sym.transpose(cls, axes=(0, 2, 3, 1))
        cls = sym.Flatten(cls)
        cls_layers.append(cls)

        anchors = contrib_sym.MultiBoxPrior(
            net, sizes=size, ratios=ratio, clip=clip,
            name=name + "_anchors")
        anchor_layers.append(anchors)

    loc_preds = sym.Concat(*loc_layers, dim=1, name="multibox_loc_pred")
    cls_preds = sym.Concat(*cls_layers, dim=1, name="multibox_cls_pred_flat")
    anchors = sym.Concat(*anchor_layers, dim=1, name="multibox_anchors")
    return loc_preds, cls_preds, anchors


def _build_heads(data, num_classes, **kwargs):
    conv4_3, relu7 = vgg16_reduced(data)
    sources = [conv4_3, relu7] + _extra_layers(relu7)
    return multibox_layer(sources, num_classes, **kwargs)


def get_symbol_train(num_classes=20, **kwargs):
    """Training symbol: Group([cls_prob, loc_loss, cls_label]).

    Mirrors the reference training head: MultiBoxTarget encodes anchors
    against ground truth; classification trains through
    SoftmaxOutput(multi_output, ignore_label=-1, normalization='valid');
    localisation trains through smooth-L1 MakeLoss masked to matched
    anchors. The label variable is [B, M, 5] rows of
    (class_id, x1, y1, x2, y2) in [0,1] corner format, class_id < 0 pad.
    """
    data = sym.Variable("data")
    loc_preds, cls_preds_flat, anchors = _build_heads(
        data, num_classes, **kwargs)
    return training_head(loc_preds, cls_preds_flat, anchors, num_classes)


def training_head(loc_preds, cls_preds_flat, anchors, num_classes):
    """Attach the SSD multi-loss training head to prediction symbols."""
    label = sym.Variable("label")
    num_label_classes = num_classes + 1
    # [B, A*(C+1)] anchor-major → [B, C+1, A] class-major for multi_output
    cls_preds = sym.Reshape(cls_preds_flat, shape=(0, -1, num_label_classes),
                            name="cls_pred_anchor_major")
    cls_preds = sym.transpose(cls_preds, axes=(0, 2, 1), name="multibox_cls_pred")
    loc_target, loc_target_mask, cls_target = contrib_sym.MultiBoxTarget(
        anchors, label, cls_preds, overlap_threshold=0.5,
        ignore_label=-1, negative_mining_ratio=3,
        minimum_negative_samples=0, negative_mining_thresh=0.5,
        variances=(0.1, 0.1, 0.2, 0.2), name="multibox_target")
    cls_prob = sym.SoftmaxOutput(cls_preds, cls_target,
                                 ignore_label=-1.0, multi_output=True,
                                 use_ignore=True, normalization="valid",
                                 name="cls_prob")
    loc_diff = loc_preds - loc_target
    masked_loc_diff = sym.broadcast_mul(loc_target_mask, loc_diff)
    loc_loss_ = sym.smooth_l1(masked_loc_diff, scalar=1.0,
                              name="loc_loss_")
    loc_loss = sym.MakeLoss(loc_loss_, grad_scale=1.0,
                            normalization="valid", name="loc_loss")
    cls_label = sym.MakeLoss(sym.BlockGrad(cls_target), grad_scale=0.0,
                             name="cls_label")
    return sym.Group([cls_prob, loc_loss, cls_label])


def get_symbol(num_classes=20, nms_thresh=0.5, force_suppress=False,
               nms_topk=400, **kwargs):
    """Deploy symbol: decoded + NMS'd detections [B, A, 6]."""
    data = sym.Variable("data")
    loc_preds, cls_preds_flat, anchors = _build_heads(
        data, num_classes, **kwargs)
    num_label_classes = num_classes + 1
    cls_preds = sym.Reshape(cls_preds_flat, shape=(0, -1, num_label_classes))
    cls_preds = sym.transpose(cls_preds, axes=(0, 2, 1))
    cls_prob = sym.SoftmaxActivation(cls_preds, mode="channel",
                                     name="cls_prob")
    return contrib_sym.MultiBoxDetection(
        cls_prob, loc_preds, anchors, name="detection",
        nms_threshold=nms_thresh, force_suppress=force_suppress,
        variances=(0.1, 0.1, 0.2, 0.2), nms_topk=nms_topk)


class MultiBoxMetric(object):
    """Training metric for the SSD head (parity: the reference SSD
    example's ``train/metric.py`` MultiBoxMetric): tracks the validated
    cross-entropy of ``cls_prob`` against ``cls_label`` and the mean
    smooth-L1 localisation loss, as two named values.

    Duck-types the EvalMetric interface Module.fit consumes
    (update/reset/get/get_name_value).
    """

    def __init__(self, eps=1e-8):
        self.eps = eps
        self.name = ["CrossEntropy", "SmoothL1"]
        self.reset()

    def reset(self):
        self.num_inst = [0, 0]
        self.sum_metric = [0.0, 0.0]

    def update(self, labels, preds):
        import numpy as np

        cls_prob = preds[0].asnumpy()   # [B, C+1, A]
        loc_loss = preds[1].asnumpy()   # [B, A*4]
        cls_label = preds[2].asnumpy()  # [B, A]
        valid = cls_label >= 0
        n_valid = int(valid.sum())
        label = cls_label.astype(int)
        b_idx, a_idx = np.nonzero(valid)
        prob = cls_prob[b_idx, label[b_idx, a_idx], a_idx]
        self.sum_metric[0] += float(-np.log(prob + self.eps).sum())
        self.num_inst[0] += n_valid
        self.sum_metric[1] += float(loc_loss.sum())
        self.num_inst[1] += n_valid

    def get(self):
        values = [
            s / n if n > 0 else float("nan")
            for s, n in zip(self.sum_metric, self.num_inst)
        ]
        return (self.name, values)

    def get_name_value(self):
        names, values = self.get()
        return list(zip(names, values))
