"""Inception-v3 (capability parity: reference symbols/inception-v3.py;
BASELINE.md dist-scaling workload). Built fresh from the architecture
(Szegedy et al. 2015), MXNet-style symbol composition."""
from .. import symbol as sym


def _conv(data, num_filter, kernel=(1, 1), stride=(1, 1), pad=(0, 0),
          name=None, suffix=""):
    conv = sym.Convolution(
        data, num_filter=num_filter, kernel=kernel, stride=stride, pad=pad,
        no_bias=True, name="%s%s_conv2d" % (name, suffix)
    )
    bn = sym.BatchNorm(conv, eps=2e-5, fix_gamma=False,
                       name="%s%s_batchnorm" % (name, suffix))
    act = sym.Activation(bn, act_type="relu", name="%s%s_relu" % (name, suffix))
    return act


def _pooling(data, kernel, stride, pad, pool_type, name):
    return sym.Pooling(data, kernel=kernel, stride=stride, pad=pad,
                       pool_type=pool_type, name=name)


def inception_a(data, n1, n5r, n5, n3r, n3, proj, name):
    tower_1x1 = _conv(data, n1, name="%s_conv" % name)
    tower_5x5 = _conv(data, n5r, name="%s_tower" % name, suffix="_conv")
    tower_5x5 = _conv(tower_5x5, n5, kernel=(5, 5), pad=(2, 2),
                      name="%s_tower" % name, suffix="_conv_1")
    tower_3x3 = _conv(data, n3r, name="%s_tower_1" % name, suffix="_conv")
    tower_3x3 = _conv(tower_3x3, n3, kernel=(3, 3), pad=(1, 1),
                      name="%s_tower_1" % name, suffix="_conv_1")
    tower_3x3 = _conv(tower_3x3, n3, kernel=(3, 3), pad=(1, 1),
                      name="%s_tower_1" % name, suffix="_conv_2")
    pooling = _pooling(data, (3, 3), (1, 1), (1, 1), "avg",
                       "%s_pool" % name)
    cproj = _conv(pooling, proj, name="%s_tower_2" % name, suffix="_conv")
    return sym.Concat(tower_1x1, tower_5x5, tower_3x3, cproj,
                      name="ch_concat_%s_chconcat" % name)


def inception_b(data, n3, n3x3r, n3x3, name):
    tower_3x3 = _conv(data, n3, kernel=(3, 3), stride=(2, 2),
                      name="%s_conv" % name)
    tower_d3x3 = _conv(data, n3x3r, name="%s_tower" % name, suffix="_conv")
    tower_d3x3 = _conv(tower_d3x3, n3x3, kernel=(3, 3), pad=(1, 1),
                       name="%s_tower" % name, suffix="_conv_1")
    tower_d3x3 = _conv(tower_d3x3, n3x3, kernel=(3, 3), stride=(2, 2),
                       name="%s_tower" % name, suffix="_conv_2")
    pooling = _pooling(data, (3, 3), (2, 2), (0, 0), "max",
                       "max_pool_%s_pool" % name)
    return sym.Concat(tower_3x3, tower_d3x3, pooling,
                      name="ch_concat_%s_chconcat" % name)


def inception_c(data, n1, n7r, n7, nd7r, nd7, proj, name):
    tower_1x1 = _conv(data, n1, name="%s_conv" % name)
    tower_7x7 = _conv(data, n7r, name="%s_tower" % name, suffix="_conv")
    tower_7x7 = _conv(tower_7x7, n7r, kernel=(1, 7), pad=(0, 3),
                      name="%s_tower" % name, suffix="_conv_1")
    tower_7x7 = _conv(tower_7x7, n7, kernel=(7, 1), pad=(3, 0),
                      name="%s_tower" % name, suffix="_conv_2")
    tower_d7 = _conv(data, nd7r, name="%s_tower_1" % name, suffix="_conv")
    tower_d7 = _conv(tower_d7, nd7r, kernel=(7, 1), pad=(3, 0),
                     name="%s_tower_1" % name, suffix="_conv_1")
    tower_d7 = _conv(tower_d7, nd7r, kernel=(1, 7), pad=(0, 3),
                     name="%s_tower_1" % name, suffix="_conv_2")
    tower_d7 = _conv(tower_d7, nd7r, kernel=(7, 1), pad=(3, 0),
                     name="%s_tower_1" % name, suffix="_conv_3")
    tower_d7 = _conv(tower_d7, nd7, kernel=(1, 7), pad=(0, 3),
                     name="%s_tower_1" % name, suffix="_conv_4")
    pooling = _pooling(data, (3, 3), (1, 1), (1, 1), "avg",
                       "%s_pool" % name)
    cproj = _conv(pooling, proj, name="%s_tower_2" % name, suffix="_conv")
    return sym.Concat(tower_1x1, tower_7x7, tower_d7, cproj,
                      name="ch_concat_%s_chconcat" % name)


def inception_d(data, n3r, n3, n7r, n7, name):
    tower_3x3 = _conv(data, n3r, name="%s_tower" % name, suffix="_conv")
    tower_3x3 = _conv(tower_3x3, n3, kernel=(3, 3), stride=(2, 2),
                      name="%s_tower" % name, suffix="_conv_1")
    tower_7x7 = _conv(data, n7r, name="%s_tower_1" % name, suffix="_conv")
    tower_7x7 = _conv(tower_7x7, n7r, kernel=(1, 7), pad=(0, 3),
                      name="%s_tower_1" % name, suffix="_conv_1")
    tower_7x7 = _conv(tower_7x7, n7r, kernel=(7, 1), pad=(3, 0),
                      name="%s_tower_1" % name, suffix="_conv_2")
    tower_7x7 = _conv(tower_7x7, n7, kernel=(3, 3), stride=(2, 2),
                      name="%s_tower_1" % name, suffix="_conv_3")
    pooling = _pooling(data, (3, 3), (2, 2), (0, 0), "max",
                       "max_pool_%s_pool" % name)
    return sym.Concat(tower_3x3, tower_7x7, pooling,
                      name="ch_concat_%s_chconcat" % name)


def inception_e(data, n1, n3r, n3, nd3r, nd3, proj, name):
    tower_1x1 = _conv(data, n1, name="%s_conv" % name)
    tower_3x3 = _conv(data, n3r, name="%s_tower" % name, suffix="_conv")
    t3a = _conv(tower_3x3, n3, kernel=(1, 3), pad=(0, 1),
                name="%s_tower" % name, suffix="_mixed_conv")
    t3b = _conv(tower_3x3, n3, kernel=(3, 1), pad=(1, 0),
                name="%s_tower" % name, suffix="_mixed_conv_1")
    tower_d3 = _conv(data, nd3r, name="%s_tower_1" % name, suffix="_conv")
    tower_d3 = _conv(tower_d3, nd3, kernel=(3, 3), pad=(1, 1),
                     name="%s_tower_1" % name, suffix="_conv_1")
    td3a = _conv(tower_d3, nd3, kernel=(1, 3), pad=(0, 1),
                 name="%s_tower_1" % name, suffix="_mixed_conv")
    td3b = _conv(tower_d3, nd3, kernel=(3, 1), pad=(1, 0),
                 name="%s_tower_1" % name, suffix="_mixed_conv_1")
    pooling = _pooling(data, (3, 3), (1, 1), (1, 1), "avg", "%s_pool" % name)
    cproj = _conv(pooling, proj, name="%s_tower_2" % name, suffix="_conv")
    return sym.Concat(tower_1x1, t3a, t3b, td3a, td3b, cproj,
                      name="ch_concat_%s_chconcat" % name)


def get_symbol(num_classes=1000, **kwargs):
    data = sym.Variable("data")
    # stem
    conv = _conv(data, 32, kernel=(3, 3), stride=(2, 2), name="conv")
    conv_1 = _conv(conv, 32, kernel=(3, 3), name="conv_1")
    conv_2 = _conv(conv_1, 64, kernel=(3, 3), pad=(1, 1), name="conv_2")
    pool = _pooling(conv_2, (3, 3), (2, 2), (0, 0), "max", "pool")
    conv_3 = _conv(pool, 80, kernel=(1, 1), name="conv_3")
    conv_4 = _conv(conv_3, 192, kernel=(3, 3), name="conv_4")
    pool1 = _pooling(conv_4, (3, 3), (2, 2), (0, 0), "max", "pool1")
    # 3 x inception A
    in3a = inception_a(pool1, 64, 48, 64, 64, 96, 32, "mixed")
    in3b = inception_a(in3a, 64, 48, 64, 64, 96, 64, "mixed_1")
    in3c = inception_a(in3b, 64, 48, 64, 64, 96, 64, "mixed_2")
    # reduction B
    in3d = inception_b(in3c, 384, 64, 96, "mixed_3")
    # 4 x inception C
    in4a = inception_c(in3d, 192, 128, 192, 128, 192, 192, "mixed_4")
    in4b = inception_c(in4a, 192, 160, 192, 160, 192, 192, "mixed_5")
    in4c = inception_c(in4b, 192, 160, 192, 160, 192, 192, "mixed_6")
    in4d = inception_c(in4c, 192, 192, 192, 192, 192, 192, "mixed_7")
    # reduction D
    in4e = inception_d(in4d, 192, 320, 192, 192, "mixed_8")
    # 2 x inception E
    in5a = inception_e(in4e, 320, 384, 384, 448, 384, 192, "mixed_9")
    in5b = inception_e(in5a, 320, 384, 384, 448, 384, 192, "mixed_10")
    pool2 = sym.Pooling(in5b, kernel=(8, 8), global_pool=True,
                        pool_type="avg", name="global_pool")
    flatten = sym.Flatten(pool2, name="flatten")
    fc1 = sym.FullyConnected(flatten, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(fc1, name="softmax")
