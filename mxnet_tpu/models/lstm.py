"""LSTM language model (PTB) — bucketing workload.

Capability parity: reference example/rnn/lstm_bucketing.py +
cudnn_lstm_bucketing.py (SURVEY.md §7 workload 3). Two paths, matching the
reference:
- ``lstm_unroll``: explicitly unrolled LSTMCell stack (the nnvm-graph path)
- ``fused_lstm_sym``: FusedRNNCell → the ``RNN`` op (lax.scan kernel)
"""
from .. import symbol as sym
from ..rnn.rnn_cell import FusedRNNCell, LSTMCell, SequentialRNNCell


def lstm_unroll(num_layers, seq_len, input_size, num_hidden, num_embed,
                num_label, dropout=0.0):
    """Unrolled symbol for one bucket length (sym_gen inner)."""
    stack = SequentialRNNCell()
    for i in range(num_layers):
        stack.add(LSTMCell(num_hidden=num_hidden, prefix="lstm_l%d_" % i))
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    embed = sym.Embedding(data, input_dim=input_size, output_dim=num_embed,
                          name="embed")
    stack.reset()
    outputs, states = stack.unroll(seq_len, inputs=embed, merge_outputs=True)
    pred = sym.Reshape(outputs, shape=(-1, num_hidden))
    pred = sym.FullyConnected(pred, num_hidden=num_label, name="pred")
    label_flat = sym.Reshape(label, shape=(-1,))
    return sym.SoftmaxOutput(pred, label_flat, name="softmax")


def fused_lstm_sym(num_layers, seq_len, input_size, num_hidden, num_embed,
                   num_label, dropout=0.0):
    """FusedRNNCell path (parity cudnn_lstm_bucketing.py)."""
    cell = FusedRNNCell(num_hidden, num_layers=num_layers, mode="lstm",
                        dropout=dropout)
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    embed = sym.Embedding(data, input_dim=input_size, output_dim=num_embed,
                          name="embed")
    outputs, _ = cell.unroll(seq_len, inputs=embed, merge_outputs=True,
                             layout="NTC")
    pred = sym.Reshape(outputs, shape=(-1, num_hidden))
    pred = sym.FullyConnected(pred, num_hidden=num_label, name="pred")
    label_flat = sym.Reshape(label, shape=(-1,))
    return sym.SoftmaxOutput(pred, label_flat, name="softmax"), cell


class BucketingLSTMModel:
    """sym_gen factory for BucketingModule (parity lstm_bucketing.py:69)."""

    def __init__(self, num_layers, input_size, num_hidden, num_embed,
                 num_label, dropout=0.0, fused=False):
        self.num_layers = num_layers
        self.input_size = input_size
        self.num_hidden = num_hidden
        self.num_embed = num_embed
        self.num_label = num_label
        self.dropout = dropout
        self.fused = fused

    def __call__(self, bucket_key):
        builder = fused_lstm_sym if self.fused else lstm_unroll
        out = builder(
            self.num_layers, bucket_key, self.input_size, self.num_hidden,
            self.num_embed, self.num_label, self.dropout
        )
        symf = out[0] if isinstance(out, tuple) else out
        return symf, ("data",), ("softmax_label",)
