"""LSTM language model (PTB) — bucketing workload.

Capability parity: reference example/rnn/lstm_bucketing.py +
cudnn_lstm_bucketing.py (SURVEY.md §7 workload 3). Two paths, matching the
reference:
- ``lstm_unroll``: explicitly unrolled LSTMCell stack (the nnvm-graph path)
- ``fused_lstm_sym``: FusedRNNCell → the ``RNN`` op (lax.scan kernel)

Plus a TPU-native variant, ``lstm_attention_lm``: a pure-JAX
recurrence (lax.scan) with a causal self-attention readout over the
hidden-state sequence, routed through the same attention dispatcher the
transformer uses (ops.pallas_kernels.attention — reference / Pallas
flash / ring by mesh+length).
"""
import numpy as np

from .. import symbol as sym
from ..rnn.rnn_cell import FusedRNNCell, LSTMCell, SequentialRNNCell


def lstm_unroll(num_layers, seq_len, input_size, num_hidden, num_embed,
                num_label, dropout=0.0):
    """Unrolled symbol for one bucket length (sym_gen inner)."""
    stack = SequentialRNNCell()
    for i in range(num_layers):
        stack.add(LSTMCell(num_hidden=num_hidden, prefix="lstm_l%d_" % i))
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    embed = sym.Embedding(data, input_dim=input_size, output_dim=num_embed,
                          name="embed")
    stack.reset()
    outputs, states = stack.unroll(seq_len, inputs=embed, merge_outputs=True)
    pred = sym.Reshape(outputs, shape=(-1, num_hidden))
    pred = sym.FullyConnected(pred, num_hidden=num_label, name="pred")
    label_flat = sym.Reshape(label, shape=(-1,))
    return sym.SoftmaxOutput(pred, label_flat, name="softmax")


def fused_lstm_sym(num_layers, seq_len, input_size, num_hidden, num_embed,
                   num_label, dropout=0.0):
    """FusedRNNCell path (parity cudnn_lstm_bucketing.py)."""
    cell = FusedRNNCell(num_hidden, num_layers=num_layers, mode="lstm",
                        dropout=dropout)
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    embed = sym.Embedding(data, input_dim=input_size, output_dim=num_embed,
                          name="embed")
    outputs, _ = cell.unroll(seq_len, inputs=embed, merge_outputs=True,
                             layout="NTC")
    pred = sym.Reshape(outputs, shape=(-1, num_hidden))
    pred = sym.FullyConnected(pred, num_hidden=num_label, name="pred")
    label_flat = sym.Reshape(label, shape=(-1,))
    return sym.SoftmaxOutput(pred, label_flat, name="softmax"), cell


def lstm_attention_lm(vocab=10000, num_hidden=256, num_embed=256,
                      n_heads=4, dtype=None):
    """Pure-JAX LSTM LM with an attention readout.

    Returns (init_fn(seed) -> params, apply_fn(params, tokens,
    mesh=None) -> logits[B, T, vocab]). The recurrence is one
    ``lax.scan`` LSTM layer; instead of predicting from h_t alone, each
    position attends causally over the full hidden sequence (the
    "attentive language model" readout), which is where the flash /
    ring attention kernels slot into the RNN path.
    """
    import jax
    import jax.numpy as jnp

    if dtype is None:
        dtype = jnp.float32
    assert num_hidden % n_heads == 0
    head_dim = num_hidden // n_heads

    def init_fn(seed=0):
        rng = np.random.RandomState(seed)

        def w(*shape, scale=None):
            scale = scale or (1.0 / np.sqrt(shape[0]))
            return (rng.randn(*shape) * scale).astype(np.float32)

        return {
            "embed": w(vocab, num_embed, scale=0.02),
            # gate order i, f, g, o — matches rnn_cell.LSTMCell
            "wx": w(num_embed, 4 * num_hidden),
            "wh": w(num_hidden, 4 * num_hidden),
            "b": np.zeros((4 * num_hidden,), np.float32),
            "wq": w(num_hidden, num_hidden),
            "wk": w(num_hidden, num_hidden),
            "wv": w(num_hidden, num_hidden),
            "wo": w(num_hidden, num_hidden),
            "pred": w(num_hidden, vocab),
        }

    def apply_fn(params, tokens, mesh=None):
        B, T = tokens.shape
        x = jnp.take(params["embed"], tokens, axis=0).astype(dtype)
        wx, wh = params["wx"].astype(dtype), params["wh"].astype(dtype)
        b = params["b"].astype(dtype)

        def step(carry, xt):
            h, c = carry
            gates = xt @ wx + h @ wh + b
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            c = jax.nn.sigmoid(f) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
            h = jax.nn.sigmoid(o) * jnp.tanh(c)
            return (h, c), h

        h0 = jnp.zeros((B, num_hidden), dtype)
        _, hs = jax.lax.scan(step, (h0, h0), jnp.swapaxes(x, 0, 1))
        hs = jnp.swapaxes(hs, 0, 1)  # [B, T, H]

        q = (hs @ params["wq"].astype(dtype)).reshape(B, T, n_heads,
                                                      head_dim)
        k = (hs @ params["wk"].astype(dtype)).reshape(B, T, n_heads,
                                                      head_dim)
        v = (hs @ params["wv"].astype(dtype)).reshape(B, T, n_heads,
                                                      head_dim)
        from ..ops.pallas_kernels import attention as attn_dispatch

        o = attn_dispatch(q, k, v, causal=True, mesh=mesh)
        ctx = o.reshape(B, T, num_hidden) @ params["wo"].astype(dtype)
        return (hs + ctx).astype(jnp.float32) @ params["pred"]

    return init_fn, apply_fn


class BucketingLSTMModel:
    """sym_gen factory for BucketingModule (parity lstm_bucketing.py:69)."""

    def __init__(self, num_layers, input_size, num_hidden, num_embed,
                 num_label, dropout=0.0, fused=False):
        self.num_layers = num_layers
        self.input_size = input_size
        self.num_hidden = num_hidden
        self.num_embed = num_embed
        self.num_label = num_label
        self.dropout = dropout
        self.fused = fused

    def __call__(self, bucket_key):
        builder = fused_lstm_sym if self.fused else lstm_unroll
        out = builder(
            self.num_layers, bucket_key, self.input_size, self.num_hidden,
            self.num_embed, self.num_label, self.dropout
        )
        symf = out[0] if isinstance(out, tuple) else out
        return symf, ("data",), ("softmax_label",)
