"""Inception-BN (capability parity: reference
example/image-classification/symbols/inception-bn.py; BASELINE.md carries
its 152 img/s K80 row and top-1 0.72x accuracy golden).

Built fresh from the architecture (Ioffe & Szegedy 2015, "Batch
Normalization", the Inception-v2 network): the ten inception blocks are
encoded as a config table driving two generic block builders rather than
per-block factory calls, which keeps the whole body declarative and lets
the TPU build reuse one traced block structure per config row.
"""
from .. import symbol as sym

_EPS = 1e-10 + 1e-5


def _conv(data, num_filter, kernel=(1, 1), stride=(1, 1), pad=(0, 0),
          name=None):
    c = sym.Convolution(data, num_filter=num_filter, kernel=kernel,
                        stride=stride, pad=pad, name="conv_%s" % name)
    b = sym.BatchNorm(c, eps=_EPS, fix_gamma=False, momentum=0.9,
                      name="bn_%s" % name)
    return sym.Activation(b, act_type="relu", name="relu_%s" % name)


def _block_keep(data, cfg, name):
    """Same-resolution inception block: 1x1 | 3x3 | double-3x3 | pool+proj."""
    n1, n3r, n3, nd3r, nd3, pool, proj = cfg
    t1 = _conv(data, n1, name="%s_1x1" % name)
    t3 = _conv(data, n3r, name="%s_3x3_reduce" % name)
    t3 = _conv(t3, n3, kernel=(3, 3), pad=(1, 1), name="%s_3x3" % name)
    td = _conv(data, nd3r, name="%s_double_3x3_reduce" % name)
    td = _conv(td, nd3, kernel=(3, 3), pad=(1, 1),
               name="%s_double_3x3_0" % name)
    td = _conv(td, nd3, kernel=(3, 3), pad=(1, 1),
               name="%s_double_3x3_1" % name)
    p = sym.Pooling(data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                    pool_type=pool, name="%s_pool" % name)
    tp = _conv(p, proj, name="%s_proj" % name)
    return sym.Concat(t1, t3, td, tp, name="ch_concat_%s" % name)


def _block_reduce(data, cfg, name):
    """Stride-2 reduction block: 3x3/2 | double-3x3/2 | maxpool/2."""
    n3r, n3, nd3r, nd3 = cfg
    t3 = _conv(data, n3r, name="%s_3x3_reduce" % name)
    t3 = _conv(t3, n3, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
               name="%s_3x3" % name)
    td = _conv(data, nd3r, name="%s_double_3x3_reduce" % name)
    td = _conv(td, nd3, kernel=(3, 3), pad=(1, 1),
               name="%s_double_3x3_0" % name)
    td = _conv(td, nd3, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
               name="%s_double_3x3_1" % name)
    p = sym.Pooling(data, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                    pool_type="max", name="%s_pool" % name)
    return sym.Concat(t3, td, p, name="ch_concat_%s" % name)


# (block kind, name, config) — the published Inception-BN body.
_BODY = [
    ("keep", "3a", (64, 64, 64, 64, 96, "avg", 32)),
    ("keep", "3b", (64, 64, 96, 64, 96, "avg", 64)),
    ("reduce", "3c", (128, 160, 64, 96)),
    ("keep", "4a", (224, 64, 96, 96, 128, "avg", 128)),
    ("keep", "4b", (192, 96, 128, 96, 128, "avg", 128)),
    ("keep", "4c", (160, 128, 160, 128, 160, "avg", 128)),
    ("keep", "4d", (96, 128, 192, 160, 192, "avg", 128)),
    ("reduce", "4e", (128, 192, 192, 256)),
    ("keep", "5a", (352, 192, 320, 160, 224, "avg", 128)),
    ("keep", "5b", (352, 192, 320, 192, 224, "max", 128)),
]


def get_symbol(num_classes=1000, image_shape="3,224,224", **kwargs):
    height = int(image_shape.split(",")[1])
    data = sym.Variable("data")
    if height <= 28:
        # compact variant for small images (reference keeps one too)
        body = _conv(data, 96, kernel=(3, 3), pad=(1, 1), name="1")
        for name, (n1, n3) in [("in3a", (32, 32)), ("in3b", (32, 48))]:
            c1 = _conv(body, n1, name="%s_1x1" % name)
            c3 = _conv(body, n3, kernel=(3, 3), pad=(1, 1),
                       name="%s_3x3" % name)
            body = sym.Concat(c1, c3, name="%s_concat" % name)
        body = _block_reduce(body, (40, 80, 24, 48), "in3c")
        pool = sym.Pooling(body, global_pool=True, kernel=(7, 7),
                           pool_type="avg", name="global_pool")
    else:
        body = _conv(data, 64, kernel=(7, 7), stride=(2, 2), pad=(3, 3),
                     name="1")
        body = sym.Pooling(body, kernel=(3, 3), stride=(2, 2),
                           pool_type="max", name="pool_1")
        body = _conv(body, 64, name="2_red")
        body = _conv(body, 192, kernel=(3, 3), pad=(1, 1), name="2")
        body = sym.Pooling(body, kernel=(3, 3), stride=(2, 2),
                           pool_type="max", name="pool_2")
        for kind, name, cfg in _BODY:
            body = (_block_keep if kind == "keep" else _block_reduce)(
                body, cfg, name)
        # global head pool: identical to the reference's 7x7 window at
        # 224 input (where the map IS 7x7), and well-defined at other
        # input sizes where a literal 7x7 valid window would be rejected
        pool = sym.Pooling(body, global_pool=True, kernel=(7, 7),
                           pool_type="avg", name="global_pool")
    flat = sym.Flatten(pool, name="flatten")
    fc = sym.FullyConnected(flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(fc, name="softmax")
