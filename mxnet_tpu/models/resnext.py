"""ResNeXt (capability parity: reference
example/image-classification/symbols/resnext.py; BASELINE.md accuracy
goldens resnext-50 0.7689 / resnext-101 0.7844 / 101-64x4d top-1).

Built fresh from Xie et al. 2016 ("Aggregated Residual Transformations"):
post-activation residual bottlenecks whose 3x3 is a grouped convolution
(cardinality = num_group), lowered through the op library's
feature_group_count path so the MXU sees one batched grouped conv, not a
python loop over groups.
"""
from .. import symbol as sym

_DEPTHS = {
    50: [3, 4, 6, 3],
    101: [3, 4, 23, 3],
    152: [3, 8, 36, 3],
}


def _conv_bn(data, num_filter, kernel, stride, pad, name, num_group=1,
             relu=True, bn_mom=0.9):
    c = sym.Convolution(data, num_filter=num_filter, kernel=kernel,
                        stride=stride, pad=pad, num_group=num_group,
                        no_bias=True, name=name + "_conv")
    b = sym.BatchNorm(c, fix_gamma=False, eps=2e-5, momentum=bn_mom,
                      name=name + "_bn")
    if not relu:
        return b
    return sym.Activation(b, act_type="relu", name=name + "_relu")


def _next_unit(data, num_filter, stride, dim_match, num_group, name,
               width_ratio=0.5):
    """Grouped bottleneck: 1x1 down to width, grouped 3x3, 1x1 back up."""
    width = int(num_filter * width_ratio)
    x = _conv_bn(data, width, (1, 1), (1, 1), (0, 0), name + "_1")
    x = _conv_bn(x, width, (3, 3), stride, (1, 1), name + "_2",
                 num_group=num_group)
    x = _conv_bn(x, num_filter, (1, 1), (1, 1), (0, 0), name + "_3",
                 relu=False)
    if dim_match:
        shortcut = data
    else:
        shortcut = _conv_bn(data, num_filter, (1, 1), stride, (0, 0),
                            name + "_sc", relu=False)
    return sym.Activation(x + shortcut, act_type="relu", name=name + "_out")


def get_symbol(num_classes=1000, num_layers=50, num_group=32,
               image_shape="3,224,224", bottleneck_width=0.5, **kwargs):
    """--num-layers / --num-group mirror the reference CLI; the 64x4d
    variant of the goldens table is num_group=64, bottleneck_width=1.0."""
    if num_layers not in _DEPTHS:
        raise ValueError("resnext depth %d not supported (%s)"
                         % (num_layers, sorted(_DEPTHS)))
    units = _DEPTHS[num_layers]
    filters = [64, 256, 512, 1024, 2048]
    height = int(str(image_shape).split(",")[1]) \
        if isinstance(image_shape, str) else image_shape[1]

    data = sym.Variable("data")
    data = sym.BatchNorm(data, fix_gamma=True, eps=2e-5, momentum=0.9,
                         name="bn_data")
    if height <= 32:
        body = sym.Convolution(data, num_filter=filters[0], kernel=(3, 3),
                               stride=(1, 1), pad=(1, 1), no_bias=True,
                               name="conv0")
    else:
        body = _conv_bn(data, filters[0], (7, 7), (2, 2), (3, 3), "stem")
        body = sym.Pooling(body, kernel=(3, 3), stride=(2, 2), pad=(1, 1),
                           pool_type="max", name="pool0")
    for i, n_units in enumerate(units):
        stride = (1, 1) if i == 0 else (2, 2)
        body = _next_unit(body, filters[i + 1], stride, False, num_group,
                          "stage%d_unit1" % (i + 1),
                          width_ratio=bottleneck_width)
        for j in range(n_units - 1):
            body = _next_unit(body, filters[i + 1], (1, 1), True, num_group,
                              "stage%d_unit%d" % (i + 1, j + 2),
                              width_ratio=bottleneck_width)
    pool = sym.Pooling(body, global_pool=True, kernel=(7, 7),
                       pool_type="avg", name="pool1")
    flat = sym.Flatten(pool)
    fc = sym.FullyConnected(flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(fc, name="softmax")
