"""GoogLeNet / Inception-v1 (capability parity: reference
example/image-classification/symbols/googlenet.py).

Built fresh from Szegedy et al. 2014 ("Going Deeper with Convolutions"):
the nine inception modules are one config table over a single generic
module builder (1x1 | 3x3 | 5x5 | pool-proj towers, biased convs, no
batch norm — faithful to the original).
"""
from .. import symbol as sym


def _conv(data, num_filter, kernel=(1, 1), stride=(1, 1), pad=(0, 0),
          name=None):
    c = sym.Convolution(data, num_filter=num_filter, kernel=kernel,
                        stride=stride, pad=pad, name="conv_%s" % name)
    return sym.Activation(c, act_type="relu", name="relu_%s" % name)


def _inception(data, cfg, name):
    n1, n3r, n3, n5r, n5, proj = cfg
    t1 = _conv(data, n1, name="%s_1x1" % name)
    t3 = _conv(data, n3r, name="%s_3x3_reduce" % name)
    t3 = _conv(t3, n3, kernel=(3, 3), pad=(1, 1), name="%s_3x3" % name)
    t5 = _conv(data, n5r, name="%s_5x5_reduce" % name)
    t5 = _conv(t5, n5, kernel=(5, 5), pad=(2, 2), name="%s_5x5" % name)
    p = sym.Pooling(data, kernel=(3, 3), stride=(1, 1), pad=(1, 1),
                    pool_type="max", name="%s_pool" % name)
    tp = _conv(p, proj, name="%s_proj" % name)
    return sym.Concat(t1, t3, t5, tp, name="ch_concat_%s" % name)


# (n1x1, n3x3reduce, n3x3, n5x5reduce, n5x5, pool_proj) per module;
# None rows are stride-2 max-pool stage boundaries.
_BODY = [
    ("in3a", (64, 96, 128, 16, 32, 32)),
    ("in3b", (128, 128, 192, 32, 96, 64)),
    None,
    ("in4a", (192, 96, 208, 16, 48, 64)),
    ("in4b", (160, 112, 224, 24, 64, 64)),
    ("in4c", (128, 128, 256, 24, 64, 64)),
    ("in4d", (112, 144, 288, 32, 64, 64)),
    ("in4e", (256, 160, 320, 32, 128, 128)),
    None,
    ("in5a", (256, 160, 320, 32, 128, 128)),
    ("in5b", (384, 192, 384, 48, 128, 128)),
]


def get_symbol(num_classes=1000, **kwargs):
    data = sym.Variable("data")
    body = _conv(data, 64, kernel=(7, 7), stride=(2, 2), pad=(3, 3),
                 name="conv1")
    body = sym.Pooling(body, kernel=(3, 3), stride=(2, 2), pool_type="max",
                       name="pool1")
    body = _conv(body, 64, name="conv2_reduce")
    body = _conv(body, 192, kernel=(3, 3), pad=(1, 1), name="conv2")
    body = sym.Pooling(body, kernel=(3, 3), stride=(2, 2), pool_type="max",
                       name="pool2")
    pool_id = 3
    for row in _BODY:
        if row is None:
            body = sym.Pooling(body, kernel=(3, 3), stride=(2, 2),
                               pool_type="max", name="pool%d" % pool_id)
            pool_id += 1
            continue
        name, cfg = row
        body = _inception(body, cfg, name)
    # global (not fixed-7x7) head pool: with the reference's default
    # "valid" pooling convention a 224 input reaches this point at 6x6,
    # which a literal 7x7 window would reject — global_pool matches the
    # intended "average everything" semantics at any input size.
    pool = sym.Pooling(body, global_pool=True, kernel=(7, 7),
                       pool_type="avg", name="global_pool")
    flat = sym.Flatten(pool, name="flatten")
    fc = sym.FullyConnected(flat, num_hidden=num_classes, name="fc1")
    return sym.SoftmaxOutput(fc, name="softmax")
