"""Transformer LM with ring attention — the TPU-native long-context model.

The reference's long-sequence story is bucketing + model-parallel LSTM
(SURVEY.md §5.7); the idiomatic TPU equivalent is a transformer whose
sequence axis shards over the mesh 'sp' axis with ring attention
(mxnet_tpu.parallel.ring_attention) and whose FFN/attention projections
shard over 'tp'. This is a pure-JAX model (not the Symbol API): it is the
flagship for the multi-chip dryrun and the long-context benchmark.
"""
from __future__ import annotations

import numpy as np


def transformer_lm(vocab=32000, d_model=512, n_heads=8, n_layers=4,
                   d_ff=2048, dtype=None, moe_experts=0, moe_every=2):
    """Returns (init_fn(rng, seq_len, batch) -> params,
                apply_fn(params, tokens, mesh=None) -> logits).

    ``moe_experts > 0`` replaces every ``moe_every``-th layer's FFN with
    a Switch-MoE block (parallel/moe.py): expert weights lead with the E
    axis so a dp x ep mesh shards them with ``moe_partition_specs`` and
    GSPMD inserts the dispatch all-to-alls. MoE apply returns
    ``(logits, aux_loss)`` — the load-balance term to add to the LM loss."""
    import jax
    import jax.numpy as jnp

    if dtype is None:
        dtype = jnp.bfloat16
    head_dim = d_model // n_heads

    def _is_moe_layer(i):
        return moe_experts > 0 and i % moe_every == moe_every - 1

    def init_fn(seed=0):
        rng = np.random.RandomState(seed)

        def w(*shape, scale=None):
            scale = scale or (1.0 / np.sqrt(shape[0]))
            return (rng.randn(*shape) * scale).astype(np.float32)

        params = {"embed": w(vocab, d_model, scale=0.02)}
        for i in range(n_layers):
            layer = {
                "ln1": np.ones((d_model,), np.float32),
                "ln2": np.ones((d_model,), np.float32),
                "wq": w(d_model, n_heads * head_dim),
                "wk": w(d_model, n_heads * head_dim),
                "wv": w(d_model, n_heads * head_dim),
                "wo": w(n_heads * head_dim, d_model),
            }
            if _is_moe_layer(i):
                # one source of truth for the MoE param layout
                from ..parallel.moe import init_moe_params

                layer["moe"] = {
                    k: np.asarray(v) for k, v in init_moe_params(
                        rng.randint(1 << 30), d_model, d_ff,
                        moe_experts).items()
                }
            else:
                layer["w1"] = w(d_model, d_ff)
                layer["w2"] = w(d_ff, d_model)
            params["l%d" % i] = layer
        params["ln_f"] = np.ones((d_model,), np.float32)
        return params

    def rmsnorm(x, g):
        x32 = x.astype(jnp.float32)
        n = x32 * jax.lax.rsqrt(jnp.mean(jnp.square(x32), -1, keepdims=True) + 1e-6)
        return (n * g).astype(x.dtype)

    def attention(x, p, mesh=None):
        B, T, D = x.shape
        q = (x @ p["wq"].astype(dtype)).reshape(B, T, n_heads, head_dim)
        k = (x @ p["wk"].astype(dtype)).reshape(B, T, n_heads, head_dim)
        v = (x @ p["wv"].astype(dtype)).reshape(B, T, n_heads, head_dim)
        # ring (sp>1 mesh) / Pallas flash / reference selection lives in
        # one place now — ops.pallas_kernels.attention — shared with the
        # LSTM attention readout (models/lstm.py)
        from ..ops.pallas_kernels import attention as attn_dispatch

        o = attn_dispatch(q, k, v, causal=True, mesh=mesh)
        return o.reshape(B, T, D) @ p["wo"].astype(dtype)

    def apply_fn(params, tokens, mesh=None):
        x = jnp.take(params["embed"], tokens, axis=0).astype(dtype)
        # simple learned-free positional encoding (rotary-lite: sinusoidal)
        T = tokens.shape[1]
        pos = np.arange(100000)[:, None] / (
            10000 ** (np.arange(0, d_model, 2) / d_model)
        )
        pe = jnp.asarray(
            np.concatenate([np.sin(pos), np.cos(pos)], axis=-1)[:T], dtype
        )
        x = x + pe[None]
        aux_total = jnp.zeros((), jnp.float32)
        for i in range(n_layers):
            p = params["l%d" % i]
            x = x + attention(rmsnorm(x, p["ln1"].astype(dtype)), p, mesh)
            h = rmsnorm(x, p["ln2"].astype(dtype))
            if _is_moe_layer(i):
                from ..parallel.moe import switch_moe

                B = h.shape[0]
                y, aux = switch_moe(
                    p["moe"], h.reshape(B * T, d_model))
                x = x + y.reshape(B, T, d_model)
                aux_total = aux_total + aux
            else:
                h = jax.nn.gelu(h @ p["w1"].astype(dtype))
                x = x + h @ p["w2"].astype(dtype)
        x = rmsnorm(x, params["ln_f"].astype(dtype))
        logits = x.astype(jnp.float32) @ params["embed"].T
        if moe_experts > 0:
            return logits, aux_total
        return logits

    return init_fn, apply_fn


def _sinusoid_pe(n_rows, d_model):
    pos = np.arange(n_rows)[:, None] / (
        10000 ** (np.arange(0, d_model, 2) / d_model)
    )
    return np.concatenate([np.sin(pos), np.cos(pos)], axis=-1)


def transformer_lm_serving(vocab=32000, d_model=512, n_heads=8, n_layers=4,
                           d_ff=2048, dtype=None, max_len=256):
    """KV-cached serving twin of :func:`transformer_lm`: consumes the
    SAME param tree (``transformer_lm(...)[0]()``), adds a preallocated
    ring-buffer KV cache so autoregressive decode is one shape-stable
    step per token (no per-token recompiles) and prefill is one padded
    forward per (count, length) bucket.

    Returns ``(init_cache, prefill, decode_step)``:

    - ``init_cache(slots)`` → cache dict; ``slots`` is the fixed decode
      batch. ``k``/``v`` are ``[L, slots, max_len, H, Dh]`` rings; the
      in-graph ``length`` counter and ``pos_map`` (absolute position
      per ring cell, -1 = empty) keep every step's shapes static while
      handling per-slot lengths, ring wraparound, and slot reuse.
    - ``prefill(params, cache, tokens[n, T], slots[n], lengths[n],
      mesh=None)`` → ``(cache, last_logits[n, vocab])``: a normal
      causal forward (ops.pallas_kernels.attention dispatch, so an
      'sp' mesh routes long prompts through parallel/ring_attention)
      whose per-layer K/V scatter into the cache rows of ``slots`` —
      new sequences join a running batch mid-flight without touching
      other slots.
    - ``decode_step(params, cache, tokens[slots])`` →
      ``(cache, logits[slots, vocab])``: one token for EVERY slot
      against the cache (inactive slots compute garbage and are simply
      ignored by the caller — the price of a static shape).

    MoE layers are not supported on the decode path (dense FFN only).
    """
    import jax
    import jax.numpy as jnp

    if dtype is None:
        dtype = jnp.bfloat16
    head_dim = d_model // n_heads
    scale = 1.0 / float(np.sqrt(head_dim))
    # absolute positions live past the ring window; size the PE table
    # for the longest total sequence the engine may reach
    pe_rows = max(4 * max_len, 1024)
    pe_np = _sinusoid_pe(pe_rows, d_model)

    def rmsnorm(x, g):
        x32 = x.astype(jnp.float32)
        n = x32 * jax.lax.rsqrt(
            jnp.mean(jnp.square(x32), -1, keepdims=True) + 1e-6)
        return (n * g).astype(x.dtype)

    def init_cache(slots):
        return {
            "k": jnp.zeros((n_layers, slots, max_len, n_heads, head_dim),
                           dtype),
            "v": jnp.zeros((n_layers, slots, max_len, n_heads, head_dim),
                           dtype),
            "pos_map": jnp.full((slots, max_len), -1, jnp.int32),
            "length": jnp.zeros((slots,), jnp.int32),
        }

    def prefill(params, cache, tokens, slots, lengths, mesh=None):
        n, T = tokens.shape
        if T > max_len:
            raise ValueError(
                "prefill bucket %d exceeds KV window %d" % (T, max_len))
        pe = jnp.asarray(pe_np[:T], dtype)
        x = jnp.take(params["embed"], tokens, axis=0).astype(dtype) + pe[None]
        from ..ops.pallas_kernels import attention as attn_dispatch

        ck, cv = cache["k"], cache["v"]
        for i in range(n_layers):
            p = params["l%d" % i]
            h = rmsnorm(x, p["ln1"].astype(dtype))
            q = (h @ p["wq"].astype(dtype)).reshape(n, T, n_heads, head_dim)
            k = (h @ p["wk"].astype(dtype)).reshape(n, T, n_heads, head_dim)
            v = (h @ p["wv"].astype(dtype)).reshape(n, T, n_heads, head_dim)
            o = attn_dispatch(q, k, v, causal=True, mesh=mesh)
            x = x + o.reshape(n, T, d_model) @ p["wo"].astype(dtype)
            h = rmsnorm(x, p["ln2"].astype(dtype))
            h = jax.nn.gelu(h @ p["w1"].astype(dtype))
            x = x + h @ p["w2"].astype(dtype)
            ck = ck.at[i, slots, :T].set(k.astype(dtype))
            cv = cv.at[i, slots, :T].set(v.astype(dtype))
        # reset the WHOLE ring row for each admitted slot: cells past
        # the prompt stay -1 (empty), so a previous occupant's stale
        # K/V can never leak into the new sequence's attention
        cell = jnp.arange(max_len)[None, :]
        row = jnp.where(cell < lengths[:, None], cell, -1).astype(jnp.int32)
        pos_map = cache["pos_map"].at[slots].set(row)
        length = cache["length"].at[slots].set(lengths.astype(jnp.int32))
        xf = rmsnorm(x, params["ln_f"].astype(dtype))
        logits = xf.astype(jnp.float32) @ params["embed"].T
        last = logits[jnp.arange(n), lengths - 1]
        return {"k": ck, "v": cv, "pos_map": pos_map, "length": length}, last

    def decode_step(params, cache, tokens):
        S = tokens.shape[0]
        pos = cache["length"]  # [S] absolute position of the new token
        idx = pos % max_len  # ring cell it lands in
        rows = jnp.arange(S)
        # same rounding as prefill: embed and PE each cast to the
        # compute dtype BEFORE the add (adding in f32 and casting after
        # drifts ~1e-3 from the full-forward reference in bf16)
        pe = jnp.asarray(pe_np, dtype)
        x = (jnp.take(params["embed"], tokens, axis=0).astype(dtype)
             + pe[jnp.clip(pos, 0, pe_rows - 1)])
        pos_map = cache["pos_map"].at[rows, idx].set(pos)
        mask = (pos_map >= 0) & (pos_map <= pos[:, None])  # [S, M]
        ck, cv = cache["k"], cache["v"]
        for i in range(n_layers):
            p = params["l%d" % i]
            h = rmsnorm(x, p["ln1"].astype(dtype))
            q = (h @ p["wq"].astype(dtype)).reshape(S, n_heads, head_dim)
            k = (h @ p["wk"].astype(dtype)).reshape(S, n_heads, head_dim)
            v = (h @ p["wv"].astype(dtype)).reshape(S, n_heads, head_dim)
            ck = ck.at[i, rows, idx].set(k)
            cv = cv.at[i, rows, idx].set(v)
            # same numerics as reference_attention: f32 scores/softmax
            s = jnp.einsum("shd,smhd->shm", q, ck[i]).astype(
                jnp.float32) * scale
            s = jnp.where(mask[:, None, :], s, -1e30)
            prob = jax.nn.softmax(s, axis=-1)
            o = jnp.einsum("shm,smhd->shd", prob,
                           cv[i].astype(jnp.float32)).astype(dtype)
            x = x + o.reshape(S, d_model) @ p["wo"].astype(dtype)
            h = rmsnorm(x, p["ln2"].astype(dtype))
            h = jax.nn.gelu(h @ p["w1"].astype(dtype))
            x = x + h @ p["w2"].astype(dtype)
        xf = rmsnorm(x, params["ln_f"].astype(dtype))
        logits = xf.astype(jnp.float32) @ params["embed"].T
        new_cache = {"k": ck, "v": cv, "pos_map": pos_map,
                     "length": pos + 1}
        return new_cache, logits

    return init_cache, prefill, decode_step
