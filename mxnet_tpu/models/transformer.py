"""Transformer LM with ring attention — the TPU-native long-context model.

The reference's long-sequence story is bucketing + model-parallel LSTM
(SURVEY.md §5.7); the idiomatic TPU equivalent is a transformer whose
sequence axis shards over the mesh 'sp' axis with ring attention
(mxnet_tpu.parallel.ring_attention) and whose FFN/attention projections
shard over 'tp'. This is a pure-JAX model (not the Symbol API): it is the
flagship for the multi-chip dryrun and the long-context benchmark.
"""
from __future__ import annotations

import numpy as np


def transformer_lm(vocab=32000, d_model=512, n_heads=8, n_layers=4,
                   d_ff=2048, dtype=None, moe_experts=0, moe_every=2):
    """Returns (init_fn(rng, seq_len, batch) -> params,
                apply_fn(params, tokens, mesh=None) -> logits).

    ``moe_experts > 0`` replaces every ``moe_every``-th layer's FFN with
    a Switch-MoE block (parallel/moe.py): expert weights lead with the E
    axis so a dp x ep mesh shards them with ``moe_partition_specs`` and
    GSPMD inserts the dispatch all-to-alls. MoE apply returns
    ``(logits, aux_loss)`` — the load-balance term to add to the LM loss."""
    import jax
    import jax.numpy as jnp

    if dtype is None:
        dtype = jnp.bfloat16
    head_dim = d_model // n_heads

    def _is_moe_layer(i):
        return moe_experts > 0 and i % moe_every == moe_every - 1

    def init_fn(seed=0):
        rng = np.random.RandomState(seed)

        def w(*shape, scale=None):
            scale = scale or (1.0 / np.sqrt(shape[0]))
            return (rng.randn(*shape) * scale).astype(np.float32)

        params = {"embed": w(vocab, d_model, scale=0.02)}
        for i in range(n_layers):
            layer = {
                "ln1": np.ones((d_model,), np.float32),
                "ln2": np.ones((d_model,), np.float32),
                "wq": w(d_model, n_heads * head_dim),
                "wk": w(d_model, n_heads * head_dim),
                "wv": w(d_model, n_heads * head_dim),
                "wo": w(n_heads * head_dim, d_model),
            }
            if _is_moe_layer(i):
                # one source of truth for the MoE param layout
                from ..parallel.moe import init_moe_params

                layer["moe"] = {
                    k: np.asarray(v) for k, v in init_moe_params(
                        rng.randint(1 << 30), d_model, d_ff,
                        moe_experts).items()
                }
            else:
                layer["w1"] = w(d_model, d_ff)
                layer["w2"] = w(d_ff, d_model)
            params["l%d" % i] = layer
        params["ln_f"] = np.ones((d_model,), np.float32)
        return params

    def rmsnorm(x, g):
        x32 = x.astype(jnp.float32)
        n = x32 * jax.lax.rsqrt(jnp.mean(jnp.square(x32), -1, keepdims=True) + 1e-6)
        return (n * g).astype(x.dtype)

    def attention(x, p, mesh=None):
        B, T, D = x.shape
        q = (x @ p["wq"].astype(dtype)).reshape(B, T, n_heads, head_dim)
        k = (x @ p["wk"].astype(dtype)).reshape(B, T, n_heads, head_dim)
        v = (x @ p["wv"].astype(dtype)).reshape(B, T, n_heads, head_dim)
        # ring (sp>1 mesh) / Pallas flash / reference selection lives in
        # one place now — ops.pallas_kernels.attention — shared with the
        # LSTM attention readout (models/lstm.py)
        from ..ops.pallas_kernels import attention as attn_dispatch

        o = attn_dispatch(q, k, v, causal=True, mesh=mesh)
        return o.reshape(B, T, D) @ p["wo"].astype(dtype)

    def apply_fn(params, tokens, mesh=None):
        x = jnp.take(params["embed"], tokens, axis=0).astype(dtype)
        # simple learned-free positional encoding (rotary-lite: sinusoidal)
        T = tokens.shape[1]
        pos = np.arange(100000)[:, None] / (
            10000 ** (np.arange(0, d_model, 2) / d_model)
        )
        pe = jnp.asarray(
            np.concatenate([np.sin(pos), np.cos(pos)], axis=-1)[:T], dtype
        )
        x = x + pe[None]
        aux_total = jnp.zeros((), jnp.float32)
        for i in range(n_layers):
            p = params["l%d" % i]
            x = x + attention(rmsnorm(x, p["ln1"].astype(dtype)), p, mesh)
            h = rmsnorm(x, p["ln2"].astype(dtype))
            if _is_moe_layer(i):
                from ..parallel.moe import switch_moe

                B = h.shape[0]
                y, aux = switch_moe(
                    p["moe"], h.reshape(B * T, d_model))
                x = x + y.reshape(B, T, d_model)
                aux_total = aux_total + aux
            else:
                h = jax.nn.gelu(h @ p["w1"].astype(dtype))
                x = x + h @ p["w2"].astype(dtype)
        x = rmsnorm(x, params["ln_f"].astype(dtype))
        logits = x.astype(jnp.float32) @ params["embed"].T
        if moe_experts > 0:
            return logits, aux_total
        return logits

    return init_fn, apply_fn
