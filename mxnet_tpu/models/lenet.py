"""LeNet-5 style convnet for MNIST (capability parity:
reference example/image-classification/symbols/lenet.py — built fresh)."""
from .. import symbol as sym


def get_symbol(num_classes=10, **kwargs):
    data = sym.Variable("data")
    c1 = sym.Convolution(data, kernel=(5, 5), num_filter=20, name="conv1")
    a1 = sym.Activation(c1, act_type="tanh")
    p1 = sym.Pooling(a1, pool_type="max", kernel=(2, 2), stride=(2, 2))
    c2 = sym.Convolution(p1, kernel=(5, 5), num_filter=50, name="conv2")
    a2 = sym.Activation(c2, act_type="tanh")
    p2 = sym.Pooling(a2, pool_type="max", kernel=(2, 2), stride=(2, 2))
    f = sym.Flatten(p2)
    fc1 = sym.FullyConnected(f, num_hidden=500, name="fc1")
    a3 = sym.Activation(fc1, act_type="tanh")
    fc2 = sym.FullyConnected(a3, num_hidden=num_classes, name="fc2")
    return sym.SoftmaxOutput(fc2, name="softmax")
