"""Faster R-CNN end-to-end training graph.

Capability parity with the reference RCNN example (SURVEY.md §7 workload
4b): an RPN over a conv backbone, the native ``Proposal`` op, the
``proposal_target`` PYTHON CustomOp (the load-bearing CustomOp usage the
reference demonstrates — ``example/rcnn/rcnn/symbol/proposal.py`` /
``symbol_vgg.py:282``), ``ROIPooling``, and a two-head (cls + bbox)
Fast R-CNN top, grouped into a five-output training symbol driven
through ``MutableModule``.

TPU-native notes: batch-1 variable-size images become per-shape XLA
programs via MutableModule's compile cache; the proposal→target→pool
chain keeps STATIC roi counts (rpn_post_nms_top_n, batch_rois) so the
whole graph stays one fixed-shape XLA module — the reference gets ragged
numbers of rois per image, we get masked fixed-size blocks, which is the
idiomatic XLA formulation of the same computation.
"""
from __future__ import annotations

import numpy as np

from .. import operator
from .. import symbol as sym
from ..contrib import symbol as contrib_sym


# --------------------------------------------------------------------------
# proposal_target: python CustomOp sampling rois against ground truth
# --------------------------------------------------------------------------

def _bbox_transform(ex_rois, gt_rois):
    """Encode gt boxes relative to example rois (dx,dy,dw,dh)."""
    ew = ex_rois[:, 2] - ex_rois[:, 0] + 1.0
    eh = ex_rois[:, 3] - ex_rois[:, 1] + 1.0
    ecx = ex_rois[:, 0] + 0.5 * (ew - 1.0)
    ecy = ex_rois[:, 1] + 0.5 * (eh - 1.0)
    gw = gt_rois[:, 2] - gt_rois[:, 0] + 1.0
    gh = gt_rois[:, 3] - gt_rois[:, 1] + 1.0
    gcx = gt_rois[:, 0] + 0.5 * (gw - 1.0)
    gcy = gt_rois[:, 1] + 0.5 * (gh - 1.0)
    return np.stack([
        (gcx - ecx) / ew, (gcy - ecy) / eh,
        np.log(gw / ew), np.log(gh / eh),
    ], axis=-1).astype(np.float32)


def _np_iou(a, b):
    ix1 = np.maximum(a[:, None, 0], b[None, :, 0])
    iy1 = np.maximum(a[:, None, 1], b[None, :, 1])
    ix2 = np.minimum(a[:, None, 2], b[None, :, 2])
    iy2 = np.minimum(a[:, None, 3], b[None, :, 3])
    iw = np.maximum(ix2 - ix1 + 1.0, 0.0)
    ih = np.maximum(iy2 - iy1 + 1.0, 0.0)
    inter = iw * ih
    aa = (a[:, 2] - a[:, 0] + 1.0) * (a[:, 3] - a[:, 1] + 1.0)
    ab = (b[:, 2] - b[:, 0] + 1.0) * (b[:, 3] - b[:, 1] + 1.0)
    union = aa[:, None] + ab[None, :] - inter
    return np.where(union > 0, inter / union, 0.0)


@operator.register("proposal_target")
class ProposalTargetProp(operator.CustomOpProp):
    """Sample a fixed-size roi batch and produce Fast R-CNN head targets.

    Inputs: rois [N, 5] (batch_idx, x1, y1, x2, y2), gt_boxes
    [1, M, 5] (x1, y1, x2, y2, cls; cls is the 0-based FOREGROUND class
    id — output label = cls + 1, 0 = background; cls < 0 rows are
    padding — the leading batch dim keeps every module input
    batch-major).
    Outputs (all length ``batch_rois``, static for XLA): sampled rois,
    per-roi class label (0 = background), class-placed bbox targets
    [R, 4*num_classes] and matching weights.
    """

    def __init__(self, num_classes=21, batch_rois=128, fg_fraction=0.25,
                 fg_overlap=0.5):
        super().__init__(need_top_grad=False)
        self._num_classes = int(num_classes)
        self._batch_rois = int(batch_rois)
        self._fg_fraction = float(fg_fraction)
        self._fg_overlap = float(fg_overlap)

    def list_arguments(self):
        return ["rois", "gt_boxes"]

    def list_outputs(self):
        return ["rois_output", "label", "bbox_target", "bbox_weight"]

    def infer_shape(self, in_shape):
        rois_shape, gt_shape = in_shape
        R, C = self._batch_rois, self._num_classes
        return ([rois_shape, gt_shape],
                [(R, 5), (R,), (R, 4 * C), (R, 4 * C)], [])

    def create_operator(self, ctx, in_shapes, in_dtypes):
        num_classes = self._num_classes
        batch_rois = self._batch_rois
        fg_rois = int(round(self._batch_rois * self._fg_fraction))
        fg_overlap = self._fg_overlap

        class ProposalTarget(operator.CustomOp):
            def forward(self, is_train, req, in_data, out_data, aux):
                rois = in_data[0].asnumpy()
                gt = in_data[1].asnumpy().reshape(-1, 5)
                gt = gt[gt[:, 4] >= 0]
                # ground-truth boxes participate as candidate rois
                # (guarantees foreground samples early in training)
                if len(gt):
                    gt_as_rois = np.concatenate(
                        [np.zeros((len(gt), 1), np.float32), gt[:, :4]],
                        axis=1)
                    all_rois = np.concatenate([rois, gt_as_rois], axis=0)
                else:
                    all_rois = rois

                R = batch_rois
                labels = np.zeros((R,), np.float32)
                targets = np.zeros((R, 4 * num_classes), np.float32)
                weights = np.zeros((R, 4 * num_classes), np.float32)
                if len(gt):
                    iou = _np_iou(all_rois[:, 1:5], gt[:, :4])
                    max_iou = iou.max(axis=1)
                    argmax = iou.argmax(axis=1)
                    fg_idx = np.where(max_iou >= fg_overlap)[0]
                    bg_idx = np.where(max_iou < fg_overlap)[0]
                    if len(fg_idx) > fg_rois:
                        fg_idx = fg_idx[
                            np.argsort(-max_iou[fg_idx])[:fg_rois]]
                    n_fg = len(fg_idx)
                    n_bg = R - n_fg
                    if len(bg_idx) == 0:
                        # no true background: pad with the LOWEST-overlap
                        # rois; they are labeled below by their own
                        # overlap, so a fg roi is never mislabeled bg
                        bg_idx = np.argsort(max_iou)[:1]
                    bg_take = np.resize(bg_idx, n_bg)
                    keep = np.concatenate([fg_idx, bg_take])
                    sampled = all_rois[keep]
                    # label every slot from ITS OWN overlap (padding
                    # duplicates of a fg roi keep their fg class)
                    slot_fg = max_iou[keep] >= fg_overlap
                    labels[:] = np.where(
                        slot_fg, gt[argmax[keep], 4] + 1.0, 0.0)
                    if slot_fg.any():
                        t = _bbox_transform(sampled[:, 1:5],
                                            gt[argmax[keep], :4])
                        for i in np.where(slot_fg)[0]:
                            c = int(labels[i])
                            targets[i, 4 * c:4 * c + 4] = t[i]
                            weights[i, 4 * c:4 * c + 4] = 1.0
                else:
                    sampled = np.resize(all_rois, (R, 5))
                self.assign(out_data[0], req[0], sampled.astype(np.float32))
                self.assign(out_data[1], req[1], labels)
                self.assign(out_data[2], req[2], targets)
                self.assign(out_data[3], req[3], weights)

            def backward(self, req, out_grad, in_data, out_data, in_grad,
                         aux):
                self.assign(in_grad[0], req[0],
                            np.zeros_like(in_data[0].asnumpy()))
                self.assign(in_grad[1], req[1],
                            np.zeros_like(in_data[1].asnumpy()))

        return ProposalTarget()


# --------------------------------------------------------------------------
# symbols
# --------------------------------------------------------------------------

def _vgg_feat(data):
    """VGG-16 conv body through conv5_3 (feature stride 16)."""
    net = data
    for i, (reps, filt) in enumerate(
            [(2, 64), (2, 128), (3, 256), (3, 512)]):
        for j in range(reps):
            net = sym.Convolution(net, kernel=(3, 3), pad=(1, 1),
                                  num_filter=filt,
                                  name="conv%d_%d" % (i + 1, j + 1))
            net = sym.Activation(net, act_type="relu")
        net = sym.Pooling(net, pool_type="max", kernel=(2, 2),
                          stride=(2, 2), name="pool%d" % (i + 1))
    for j in range(3):
        net = sym.Convolution(net, kernel=(3, 3), pad=(1, 1),
                              num_filter=512, name="conv5_%d" % (j + 1))
        net = sym.Activation(net, act_type="relu")
    return net


def _tiny_feat(data):
    """Two-conv stride-4 backbone for tests."""
    net = sym.Convolution(data, kernel=(3, 3), pad=(1, 1), stride=(2, 2),
                          num_filter=8, name="tc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.Convolution(net, kernel=(3, 3), pad=(1, 1), stride=(2, 2),
                          num_filter=16, name="tc2")
    return sym.Activation(net, act_type="relu")


def get_symbol_train(num_classes=21, backbone="vgg", feature_stride=16,
                     scales=(8, 16, 32), ratios=(0.5, 1, 2),
                     rpn_batch_size=256, batch_rois=128,
                     rpn_pre_nms_top_n=6000, rpn_post_nms_top_n=300,
                     rpn_min_size=16, pooled_size=(7, 7), hidden=1024):
    """End-to-end Faster R-CNN training symbol (batch 1, like the
    reference ``train_end2end.py``). Outputs:
    [rpn_cls_prob, rpn_bbox_loss, cls_prob, bbox_loss, BlockGrad(label)].

    Expects from the data iterator: data, im_info [1,3], gt_boxes [M,5]
    and RPN targets rpn_label [1, A*H, W] (-1 = ignore), rpn_bbox_target /
    rpn_bbox_weight [1, 4A, H, W] (see ``assign_anchors``).
    """
    data = sym.Variable("data")
    im_info = sym.Variable("im_info")
    gt_boxes = sym.Variable("gt_boxes")
    rpn_label = sym.Variable("rpn_label")
    rpn_bbox_target = sym.Variable("rpn_bbox_target")
    rpn_bbox_weight = sym.Variable("rpn_bbox_weight")

    feat = _vgg_feat(data) if backbone == "vgg" else _tiny_feat(data)
    num_anchors = len(scales) * len(ratios)

    # RPN head
    rpn_conv = sym.Convolution(feat, kernel=(3, 3), pad=(1, 1),
                               num_filter=256 if backbone != "vgg" else 512,
                               name="rpn_conv_3x3")
    rpn_relu = sym.Activation(rpn_conv, act_type="relu")
    rpn_cls_score = sym.Convolution(rpn_relu, kernel=(1, 1), pad=(0, 0),
                                    num_filter=2 * num_anchors,
                                    name="rpn_cls_score")
    rpn_bbox_pred = sym.Convolution(rpn_relu, kernel=(1, 1), pad=(0, 0),
                                    num_filter=4 * num_anchors,
                                    name="rpn_bbox_pred")

    # RPN losses
    # (1, 2A, H, W) → (1, 2, A*H, W): bg/fg pair axis in front, kept 4-D
    # so the activation can be folded back to (1, 2A, H, W) for Proposal
    rpn_cls_score_reshape = sym.Reshape(rpn_cls_score, shape=(0, 2, -1, 0),
                                        name="rpn_cls_score_reshape")
    rpn_cls_prob = sym.SoftmaxOutput(rpn_cls_score_reshape, rpn_label,
                                     multi_output=True, use_ignore=True,
                                     ignore_label=-1.0,
                                     normalization="valid",
                                     name="rpn_cls_prob")
    rpn_bbox_diff = sym.broadcast_mul(
        rpn_bbox_weight, rpn_bbox_pred - rpn_bbox_target)
    rpn_bbox_loss_ = sym.smooth_l1(rpn_bbox_diff, scalar=3.0,
                                   name="rpn_bbox_loss_")
    rpn_bbox_loss = sym.MakeLoss(rpn_bbox_loss_,
                                 grad_scale=1.0 / rpn_batch_size,
                                 name="rpn_bbox_loss")

    # proposals (no gradient flows through Proposal)
    rpn_cls_act = sym.SoftmaxActivation(rpn_cls_score_reshape,
                                        mode="channel",
                                        name="rpn_cls_act")
    rpn_cls_act_reshape = sym.Reshape(rpn_cls_act,
                                      shape=(0, 2 * num_anchors, -1, 0),
                                      name="rpn_cls_act_reshape")
    rois = contrib_sym.Proposal(
        sym.BlockGrad(rpn_cls_act_reshape), sym.BlockGrad(rpn_bbox_pred),
        im_info, feature_stride=feature_stride, scales=scales,
        ratios=ratios, rpn_pre_nms_top_n=rpn_pre_nms_top_n,
        rpn_post_nms_top_n=rpn_post_nms_top_n, rpn_min_size=rpn_min_size,
        name="rois")

    # sample + targets via the python CustomOp
    group = sym.Custom(rois, gt_boxes, op_type="proposal_target",
                       num_classes=num_classes, batch_rois=batch_rois,
                       name="proposal_target")
    rois_out, label, bbox_target, bbox_weight = (
        group[0], group[1], group[2], group[3])

    # Fast R-CNN head
    pool5 = sym.ROIPooling(feat, rois_out, pooled_size=pooled_size,
                           spatial_scale=1.0 / feature_stride,
                           name="roi_pool5")
    flat = sym.Flatten(pool5)
    fc6 = sym.FullyConnected(flat, num_hidden=hidden, name="fc6")
    relu6 = sym.Activation(fc6, act_type="relu")
    fc7 = sym.FullyConnected(relu6, num_hidden=hidden, name="fc7")
    relu7 = sym.Activation(fc7, act_type="relu")
    cls_score = sym.FullyConnected(relu7, num_hidden=num_classes,
                                   name="cls_score")
    cls_prob = sym.SoftmaxOutput(cls_score, label,
                                 normalization="batch", name="cls_prob")
    bbox_pred = sym.FullyConnected(relu7, num_hidden=4 * num_classes,
                                   name="bbox_pred")
    bbox_diff = bbox_weight * (bbox_pred - bbox_target)
    bbox_loss_ = sym.smooth_l1(bbox_diff, scalar=1.0, name="bbox_loss_")
    bbox_loss = sym.MakeLoss(bbox_loss_, grad_scale=1.0 / batch_rois,
                             name="bbox_loss")
    return sym.Group([rpn_cls_prob, rpn_bbox_loss, cls_prob, bbox_loss,
                      sym.BlockGrad(label)])


# --------------------------------------------------------------------------
# AnchorLoader equivalent: RPN target assignment on the host
# --------------------------------------------------------------------------

def generate_anchors(base_size, scales, ratios):
    """Base anchors centered on a base_size cell (numpy).

    Delegates to the SAME generator the in-graph ``Proposal`` op uses
    (contrib/ops.py) — host-side RPN targets and in-graph proposal
    decoding must enumerate anchors bit-identically."""
    from ..contrib.ops import _generate_base_anchors
    return _generate_base_anchors(base_size, scales, ratios)


def assign_anchors(gt_boxes, feat_shape, im_shape, feature_stride=16,
                   scales=(8, 16, 32), ratios=(0.5, 1, 2),
                   batch_size=256, fg_fraction=0.5, fg_overlap=0.7,
                   bg_overlap=0.3):
    """Compute RPN training targets for one image (the host-side job the
    reference does in AnchorLoader, ``rcnn/core/loader.py``). Returns
    (rpn_label [1, A*H, W], rpn_bbox_target [1, 4A, H, W],
    rpn_bbox_weight [1, 4A, H, W])."""
    H, W = feat_shape
    base = generate_anchors(feature_stride, scales, ratios)
    A = len(base)
    sx = np.arange(W) * feature_stride
    sy = np.arange(H) * feature_stride
    sxg, syg = np.meshgrid(sx, sy)
    shifts = np.stack([sxg.ravel(), syg.ravel(),
                       sxg.ravel(), syg.ravel()], axis=-1)
    anchors = (base[None] + shifts[:, None]).reshape(-1, 4)  # [HW*A, 4]
    n = len(anchors)
    labels = -np.ones((n,), np.float32)
    targets = np.zeros((n, 4), np.float32)
    inside = ((anchors[:, 0] >= 0) & (anchors[:, 1] >= 0)
              & (anchors[:, 2] < im_shape[1])
              & (anchors[:, 3] < im_shape[0]))
    gt = gt_boxes[gt_boxes[:, 4] >= 0] if len(gt_boxes) else gt_boxes
    if len(gt):
        iou = _np_iou(anchors, gt[:, :4])
        max_iou = iou.max(axis=1)
        argmax = iou.argmax(axis=1)
        labels[inside & (max_iou < bg_overlap)] = 0
        labels[inside & (max_iou >= fg_overlap)] = 1
        # best INSIDE anchor per gt is always fg (the reference's
        # AnchorLoader only ever assigns labels to inside anchors)
        if inside.any():
            iou_inside = np.where(inside[:, None], iou, -1.0)
            best = iou_inside.argmax(axis=0)
            labels[best[iou_inside.max(axis=0) > 0]] = 1
        fg = np.where(labels == 1)[0]
        max_fg = int(batch_size * fg_fraction)
        if len(fg) > max_fg:
            labels[np.random.choice(fg, len(fg) - max_fg, False)] = -1
        bg = np.where(labels == 0)[0]
        max_bg = batch_size - int((labels == 1).sum())
        if len(bg) > max_bg:
            labels[np.random.choice(bg, len(bg) - max_bg, False)] = -1
        fg = np.where(labels == 1)[0]
        targets[fg] = _bbox_transform(anchors[fg], gt[argmax[fg], :4])
    else:
        labels[inside] = 0

    # [HW*A] → the (1, A*H*W) / (1, 4A, H, W) layouts the symbol expects
    # (anchor-major per spatial position, matching rpn_cls_score_reshape)
    lab = labels.reshape(H, W, A).transpose(2, 0, 1).reshape(1, A * H, W)
    tgt = targets.reshape(H, W, A * 4).transpose(2, 0, 1)[None]
    fg_mask = (labels == 1).reshape(H, W, A)
    wgt_hw = np.repeat(fg_mask[:, :, :, None], 4, axis=3).reshape(
        H, W, 4 * A).transpose(2, 0, 1)[None]
    wgt = wgt_hw.astype(np.float32)
    return lab, tgt.astype(np.float32), wgt
