"""Model zoo: symbol builders for the reference's headline workloads.

Capability parity targets (SURVEY.md §7 / BASELINE.md): MLP + LeNet
(MNIST), ResNet-18/34/50/101/152 + ResNeXt, Inception-v3/BN, AlexNet,
VGG (ImageNet), LSTM language models (PTB), and a transformer with ring
attention (the TPU-native long-context flagship — beyond reference
parity, standing in for its model-parallel LSTM).
"""
from .mlp import get_symbol as mlp
from .lenet import get_symbol as lenet
from .alexnet import get_symbol as alexnet
from .resnet import get_symbol as resnet
from .inception_v3 import get_symbol as inception_v3
from .inception_bn import get_symbol as inception_bn
from .inception_resnet_v2 import get_symbol as inception_resnet_v2
from .googlenet import get_symbol as googlenet
from .resnext import get_symbol as resnext
from .vgg import get_symbol as vgg
from .lstm import lstm_unroll, BucketingLSTMModel
from .transformer import transformer_lm
