"""Executor manager: batch-slicing + multi-device executor driving.

Parity: reference ``python/mxnet/executor_manager.py`` (the pre-Module
data-parallel trainer layer used by FeedForward's
``_train_multi_device``, model.py:132). The modern Module stack routes
through ``module.executor_group.DataParallelExecutorGroup``; this module
keeps the reference's standalone surface — ``_split_input_slice``,
``_load_data``/``_load_label``, ``DataParallelExecutorManager`` — for
scripts that drive executors directly.

TPU-native: a "device slice" is a static sub-batch shape; each slice's
executor is one compiled XLA program, and copy_params_from is a device
put, not a cudaMemcpy.
"""
from __future__ import annotations

import logging

from .base import MXNetError
from .module.executor_group import (  # noqa: F401  (re-exported parity API)
    DataParallelExecutorGroup,
    _load_data,
    _load_general,
    _load_label,
    _split_input_slice,
)


class DataParallelExecutorManager(object):
    """Drive a symbol over multiple devices with sliced batches
    (parity executor_manager.py:196 — the FeedForward-era trainer).

    Internally delegates to DataParallelExecutorGroup, which compiles
    one XLA program per device slice and shares parameters.
    """

    def __init__(self, symbol, ctx, train_data, arg_names, param_names,
                 aux_names, work_load_list=None, logger=None,
                 sym_gen=None):
        if logger is None:
            logger = logging
        self._symbol = symbol
        self._ctx = ctx
        self._arg_names = arg_names
        self._param_names = param_names
        self._aux_names = aux_names
        if work_load_list is None:
            work_load_list = [1] * len(ctx)
        if len(work_load_list) != len(ctx):
            raise MXNetError("Invalid settings for work load.")
        self._work_load_list = work_load_list
        self._data_shapes = [
            (name, tuple(shape)) for name, shape in train_data.provide_data
        ]
        self._label_shapes = [
            (name, tuple(shape)) for name, shape in train_data.provide_label
        ]
        self._exec_group = DataParallelExecutorGroup(
            symbol, ctx, work_load_list, self._data_shapes,
            self._label_shapes, param_names, for_training=True,
            inputs_need_grad=False, shared_group=None, logger=logger,
        )
        self.slices = self._exec_group.slices

    @property
    def param_arrays(self):
        return self._exec_group.param_arrays

    @property
    def grad_arrays(self):
        return self._exec_group.grad_arrays

    @property
    def aux_arrays(self):
        return self._exec_group.aux_arrays

    def install_monitor(self, monitor):
        self._exec_group.install_monitor(monitor)

    def set_params(self, arg_params, aux_params):
        self._exec_group.set_params(arg_params, aux_params)

    def copy_to(self, arg_params, aux_params):
        """Copy current (possibly device-sharded) params into the given
        host dicts (parity executor_manager.py:261)."""
        self._exec_group.get_params(arg_params, aux_params)

    def load_data_batch(self, data_batch):
        self._curr_batch = data_batch

    def forward(self, is_train=False):
        self._exec_group.forward(self._curr_batch, is_train=is_train)

    def backward(self):
        self._exec_group.backward()

    def update_metric(self, metric, labels):
        self._exec_group.update_metric(metric, labels)
