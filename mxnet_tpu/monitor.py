"""Tensor-level training monitor.

Capability parity with reference ``python/mxnet/monitor.py``: install on
executors, collect a statistic of every op output whose name matches a
pattern on every ``interval``-th step, plus the matching weights at
``toc``. The reference pipes executor outputs through
Executor::SetMonitorCallback (graph_executor.cc:760); here the
executor's monitor hook feeds the same records. Re-designed around an
explicit record list and a single ``_format`` path rather than the
reference's queue/string concatenation."""
from __future__ import annotations

import logging
import re
from math import sqrt

from . import ndarray as nd
from .ndarray import NDArray


def _rms(x):
    """Default statistic: ||x||_2 / sqrt(n) — scale-free activation/
    weight magnitude."""
    return nd.norm(x) / sqrt(x.size)


class Monitor(object):
    """Collects (step, tensor_name, stat) records while activated.

    Use: ``mon.install(exe)`` once per executor, then per batch
    ``mon.tic()`` before forward and ``mon.toc()``/``toc_print()`` after.
    """

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        self.stat_func = stat_func or _rms
        self.interval = interval
        self.sort = sort
        self._pattern = re.compile(pattern)
        self.activated = False
        self.step = 0
        self.exes = []
        self._records = []
        # bound hook the executor calls with every op output
        self.stat_helper = self._observe

    def _observe(self, name, array):
        if self.activated and self._pattern.match(name):
            self._records.append((self.step, name, self.stat_func(array)))

    def install(self, exe):
        exe.set_monitor_callback(self.stat_helper)
        self.exes.append(exe)

    def _sync(self):
        for exe in self.exes:
            for array in exe.arg_arrays:
                array.wait_to_read()

    def tic(self):
        """Arm collection if this step is on the interval."""
        if self.step % self.interval == 0:
            self._sync()
            self._records = []
            self.activated = True
        self.step += 1

    def toc(self):
        """Disarm and return [(step, name, formatted stat)] — including
        a stat of each matching weight, not just op outputs."""
        if not self.activated:
            return []
        self._sync()
        for exe in self.exes:
            for name, array in zip(exe._symbol.list_arguments(),
                                   exe.arg_arrays):
                if self._pattern.match(name):
                    self._records.append(
                        (self.step, name, self.stat_func(array)))
        self.activated = False
        records = sorted(self._records, key=lambda r: r[1]) if self.sort \
            else self._records
        out = [(step, name, self._format(stat))
               for step, name, stat in records]
        self._records = []
        return out

    @staticmethod
    def _format(stat):
        vals = [stat] if isinstance(stat, NDArray) else stat
        assert isinstance(vals, list)
        parts = []
        for v in vals:
            assert isinstance(v, NDArray)
            parts.append(str(v.asscalar() if v.shape == (1,) else v.asnumpy()))
        return "\t".join(parts) + "\t"

    def toc_print(self):
        for step, name, val in self.toc():
            logging.info("Batch: {:7d} {:30s} {:s}".format(step, name, val))
