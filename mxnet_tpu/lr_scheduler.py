"""Learning-rate schedules.

Capability parity with reference ``python/mxnet/lr_scheduler.py``
(FactorScheduler, MultiFactorScheduler), re-designed as CLOSED-FORM
functions of ``num_update`` instead of the reference's stateful
while-loop mutation: the lr for any update count is computed directly,
which makes schedules idempotent (safe to re-evaluate for the fused
train step's per-step host lr) and trivially resumable from a
checkpointed update count.
"""
from __future__ import annotations

import logging


class LRScheduler:
    """Maps a global update count to a learning rate. ``base_lr`` is
    assigned by the owning Optimizer (optimizer.py sets it from its own
    learning_rate at construction)."""

    def __init__(self, base_lr=0.01):
        self.base_lr = base_lr
        self._last_stage = 0

    def _stage(self, num_update):
        """How many decay boundaries lie strictly below num_update."""
        raise NotImplementedError()

    def _lr_at_stage(self, k):
        raise NotImplementedError()

    def __call__(self, num_update):
        k = self._stage(num_update)
        lr = self._lr_at_stage(k)
        if k != self._last_stage:
            self._last_stage = k
            logging.info("Update[%d]: Change learning rate to %0.5e",
                         num_update, lr)
        return lr


class FactorScheduler(LRScheduler):
    """lr = base_lr * factor^(floor((num_update-1)/step)), floored at
    ``stop_factor_lr``."""

    def __init__(self, step, factor=1.0, stop_factor_lr=1e-8):
        super().__init__()
        if step < 1:
            raise ValueError("Schedule step must be greater or equal than 1")
        if factor > 1.0:
            raise ValueError("Factor must be no more than 1 to make lr reduce")
        self.step = step
        self.factor = factor
        self.stop_factor_lr = stop_factor_lr

    def _stage(self, num_update):
        return max(0, num_update - 1) // self.step

    def _lr_at_stage(self, k):
        return max(self.stop_factor_lr, self.base_lr * self.factor ** k)


class MultiFactorScheduler(LRScheduler):
    """Decay by ``factor`` at each boundary in the increasing list
    ``step`` (boundaries are update counts, exclusive)."""

    def __init__(self, step, factor=1):
        super().__init__()
        if not isinstance(step, list) or not step:
            raise ValueError("step must be a non-empty list")
        if any(s < 1 for s in step):
            raise ValueError("Schedule step must be greater or equal than 1")
        if any(b >= a for a, b in zip(step[1:], step)):
            raise ValueError("Schedule step must be an increasing integer list")
        if factor > 1.0:
            raise ValueError("Factor must be no more than 1 to make lr reduce")
        self.step = step
        self.factor = factor

    def _stage(self, num_update):
        return sum(1 for boundary in self.step if num_update > boundary)

    def _lr_at_stage(self, k):
        return self.base_lr * self.factor ** k
