"""Standalone inference — ``c_predict_api`` parity.

Parity: reference ``src/c_api/c_predict_api.cc`` /
``include/mxnet/c_predict_api.h:59-140`` (SURVEY.md §3.6): a
self-contained predictor ABI — ``MXPredCreate(symbol_json, param_bytes,
dev, input_shapes)`` → ``MXPredSetInput`` → ``MXPredForward`` →
``MXPredGetOutput`` — that the amalgamation ships to mobile/JS.

TPU-native: ``Predictor`` AOT-compiles the whole inference graph to one
XLA executable at construction (the reference builds a pruned
MXNET_PREDICT_ONLY executor); ``forward`` is a single device call. The
reference's partial-shape re-create (``MXPredReshape``) maps to
``reshape()`` which compiles one more program and keeps the weights.

The amalgamation analog is ``export_bundle``/``load_bundle``: one file
that contains symbol JSON + params, loadable with zero framework state.
"""
from __future__ import annotations

import struct

import numpy as np

from . import ndarray as nd
from . import symbol as sym_mod
from .base import MXNetError
from .context import Context, cpu


class Predictor(object):
    """``MXPredCreate`` equivalent.

    Parameters
    ----------
    symbol_json : str — symbol graph JSON (``Symbol.tojson()``)
    param_raw : bytes | dict — serialized params (``nd.save`` format with
        ``arg:``/``aux:`` prefixed names, as ``save_checkpoint`` writes)
        or an already-loaded {name: NDArray} dict
    input_shapes : dict of name → shape
    ctx : Context (default cpu())
    """

    def __init__(self, symbol_json, param_raw, input_shapes, ctx=None):
        self.symbol = sym_mod.load_json(symbol_json)
        ctx = ctx if ctx is not None else cpu()
        if isinstance(param_raw, (bytes, bytearray)):
            loaded = nd.load_buffer(bytes(param_raw))
        else:
            loaded = param_raw
        if not isinstance(loaded, dict):
            raise MXNetError(
                "Predictor needs NAMED params (a dict serialized by "
                "nd.save / save_checkpoint); got an unnamed list")
        arg_params, aux_params = {}, {}
        for k, v in loaded.items():
            if k.startswith("arg:"):
                arg_params[k[4:]] = v
            elif k.startswith("aux:"):
                aux_params[k[4:]] = v
            else:
                arg_params[k] = v
        self._ctx = ctx
        self._input_shapes = dict(input_shapes)
        self._arg_params = arg_params
        self._aux_params = aux_params
        self._bind()

    def _bind(self):
        self._exec = self.symbol.simple_bind(
            ctx=self._ctx, grad_req="null", **self._input_shapes)
        for name, arr in self._arg_params.items():
            if name in self._exec.arg_dict:
                if tuple(self._exec.arg_dict[name].shape) != tuple(arr.shape):
                    raise MXNetError(
                        "param %s shape mismatch %s vs %s"
                        % (name, arr.shape, self._exec.arg_dict[name].shape))
                self._exec.arg_dict[name][:] = arr.asnumpy()
        for name, arr in self._aux_params.items():
            if name in self._exec.aux_dict:
                self._exec.aux_dict[name][:] = arr.asnumpy()

    # -- c_predict_api surface ----------------------------------------
    def set_input(self, name, data):
        """``MXPredSetInput``."""
        if name not in self._input_shapes:
            raise MXNetError("unknown input %s" % name)
        data = np.asarray(data)
        want = tuple(self._exec.arg_dict[name].shape)
        if tuple(data.shape) != want:
            raise MXNetError(
                "input %s shape %s does not match bound shape %s"
                % (name, tuple(data.shape), want))
        self._exec.arg_dict[name][:] = data

    def forward(self):
        """``MXPredForward``."""
        self._exec.forward(is_train=False)

    def get_output(self, index=0):
        """``MXPredGetOutput`` → numpy."""
        return self._exec.outputs[index].asnumpy()

    def reshape(self, new_input_shapes):
        """``MXPredReshape``: rebind with new shapes, keep weights."""
        self._input_shapes.update(new_input_shapes)
        self._bind()

    def predict(self, **inputs):
        """Convenience: set all inputs, forward, return all outputs."""
        for name, data in inputs.items():
            self.set_input(name, data)
        self.forward()
        return [o.asnumpy() for o in self._exec.outputs]


# --------------------------------------------------------------------------
# amalgamation analog: single-file inference bundle
# --------------------------------------------------------------------------

_BUNDLE_MAGIC = b"MXTPUPRED1"


def export_bundle(fname, symbol, arg_params, aux_params=None):
    """Write symbol JSON + params as ONE file (the role the reference's
    amalgamation plays: a self-contained deployable predict artifact)."""
    js = symbol.tojson().encode()
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    if aux_params:
        save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_bytes = nd.save_buffer(save_dict)
    with open(fname, "wb") as f:
        f.write(_BUNDLE_MAGIC)
        f.write(struct.pack("<qq", len(js), len(param_bytes)))
        f.write(js)
        f.write(param_bytes)


def load_bundle(fname, input_shapes, ctx=None):
    """Load an ``export_bundle`` file into a ready Predictor."""
    with open(fname, "rb") as f:
        magic = f.read(len(_BUNDLE_MAGIC))
        if magic != _BUNDLE_MAGIC:
            raise MXNetError("%s is not a predictor bundle" % fname)
        js_len, p_len = struct.unpack("<qq", f.read(16))
        js = f.read(js_len).decode()
        param_bytes = f.read(p_len)
    return Predictor(js, param_bytes, input_shapes, ctx=ctx)
