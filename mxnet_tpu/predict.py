"""Standalone inference — ``c_predict_api`` parity + the serving AOT pool.

Parity: reference ``src/c_api/c_predict_api.cc`` /
``include/mxnet/c_predict_api.h:59-140`` (SURVEY.md §3.6): a
self-contained predictor ABI — ``MXPredCreate(symbol_json, param_bytes,
dev, input_shapes)`` → ``MXPredSetInput`` → ``MXPredForward`` →
``MXPredGetOutput`` — that the amalgamation ships to mobile/JS.

TPU-native: ``Predictor`` AOT-compiles the whole inference graph to one
XLA executable per input-shape bucket. ``reshape()`` keeps every
previously-bound executor in an LRU pool keyed on the input-shape
signature (the reference re-creates; here a bucket flip is a dict
lookup), and all executors share one set of parameter buffers via
``shared_exec`` binding. ``compile()`` lowers and compiles the serving
fast path per bucket up front — warm-started through
``MXTPU_COMPILE_CACHE`` — with the streaming input buffers donated, so
the steady-state request loop never traces (proven by the
telemetry.anatomy recompile detector: every dispatch routes through
``_GraphProgram.dispatch_plan``).

The amalgamation analog is ``export_bundle``/``load_bundle``: one file
that contains symbol JSON + params. Bundles now carry per-section and
per-tensor CRC32s (same integrity discipline as the resilience
MANIFEST), so a corrupt bundle fails loudly naming the file and the
tensor; ``params_from_checkpoint`` loads a resilience checkpoint
directory through its MANIFEST/CRC verification for the
fp32-master/AMP training→serving path.

Env knobs: ``MXTPU_SERVE_EXEC_CACHE`` (LRU capacity, default 8),
``MXTPU_SERVE_QUANT=int8`` (experimental weight quantization,
serving/quant.py).
"""
from __future__ import annotations

import collections
import json
import os
import struct
import zlib

import numpy as np

from . import ndarray as nd
from . import symbol as sym_mod
from . import telemetry as _tm
from .base import MXNetError
from .context import Context, cpu

_H_DISPATCH_SECONDS = _tm.histogram(
    "predict.dispatch_seconds",
    "device time per AOT predict dispatch")
_C_EXEC_EVICTIONS = _tm.counter(
    "predict.exec_evictions",
    "executors dropped from the shape-signature LRU pool")


def _exec_cache_cap():
    try:
        return max(1, int(os.environ.get("MXTPU_SERVE_EXEC_CACHE", "8")))
    except ValueError:
        return 8


def _shape_key(input_shapes):
    return tuple(sorted(
        (name, tuple(int(d) for d in shape))
        for name, shape in input_shapes.items()))


class Predictor(object):
    """``MXPredCreate`` equivalent.

    Parameters
    ----------
    symbol_json : str — symbol graph JSON (``Symbol.tojson()``)
    param_raw : bytes | dict — serialized params (``nd.save`` format with
        ``arg:``/``aux:`` prefixed names, as ``save_checkpoint`` writes)
        or an already-loaded {name: NDArray} dict
    input_shapes : dict of name → shape
    ctx : Context (default cpu())
    quant : None | "int8" — weight quantization mode (default: the
        MXTPU_SERVE_QUANT env var). "int8" stores dense/conv weights as
        int8 + per-output-channel scales and dequantizes at bind
        (serving/quant.py, experimental).
    """

    def __init__(self, symbol_json, param_raw, input_shapes, ctx=None,
                 quant=None):
        self.symbol = sym_mod.load_json(symbol_json)
        ctx = ctx if ctx is not None else cpu()
        if isinstance(param_raw, (bytes, bytearray)):
            loaded = nd.load_buffer(bytes(param_raw))
        else:
            loaded = param_raw
        if not isinstance(loaded, dict):
            raise MXNetError(
                "Predictor needs NAMED params (a dict serialized by "
                "nd.save / save_checkpoint); got an unnamed list")
        arg_params, aux_params = {}, {}
        for k, v in loaded.items():
            if k.startswith("arg:"):
                arg_params[k[4:]] = v
            elif k.startswith("aux:"):
                aux_params[k[4:]] = v
            else:
                arg_params[k] = v
        self._ctx = ctx
        self._input_shapes = dict(input_shapes)
        self._arg_params = arg_params
        self._aux_params = aux_params
        self.quant = quant if quant is not None else os.environ.get(
            "MXTPU_SERVE_QUANT", "")
        if self.quant not in ("", "int8"):
            raise MXNetError(
                "unsupported MXTPU_SERVE_QUANT mode %r (only int8)"
                % self.quant)
        if self.quant == "int8":
            from .serving import quant as _quant

            self._arg_params = _quant.quantize_arg_params(self._arg_params)
        # LRU pool: shape signature -> bound Executor; all entries share
        # parameter buffers with the first-ever bind (_shared_exec)
        self._exec_cache = collections.OrderedDict()
        self._serve_cache = {}  # shape signature -> _ServeFn
        self._shared_exec = None
        self._exec = None
        self._bind()

    # -- executor pool -------------------------------------------------
    def _bind(self):
        self._exec = self._executor_for(_shape_key(self._input_shapes),
                                        self._input_shapes)

    def _executor_for(self, key, input_shapes):
        exec_ = self._exec_cache.get(key)
        if exec_ is not None:
            self._exec_cache.move_to_end(key)
            return exec_
        exec_ = self.symbol.simple_bind(
            ctx=self._ctx, grad_req="null", shared_exec=self._shared_exec,
            **input_shapes)
        self._load_params_into(exec_)
        if self._shared_exec is None:
            self._shared_exec = exec_
        self._exec_cache[key] = exec_
        cap = _exec_cache_cap()
        while len(self._exec_cache) > cap:
            old_key, _ = self._exec_cache.popitem(last=False)
            self._serve_cache.pop(old_key, None)
            _C_EXEC_EVICTIONS.inc()
        return exec_

    def _dequant(self, name, arr):
        if self.quant == "int8":
            from .serving import quant as _quant

            return _quant.maybe_dequantize(arr)
        return arr.asnumpy() if hasattr(arr, "asnumpy") else np.asarray(arr)

    def _load_params_into(self, exec_):
        for name, arr in self._arg_params.items():
            if name in exec_.arg_dict:
                data = self._dequant(name, arr)
                if tuple(exec_.arg_dict[name].shape) != tuple(data.shape):
                    raise MXNetError(
                        "param %s shape mismatch %s vs %s"
                        % (name, tuple(data.shape),
                           tuple(exec_.arg_dict[name].shape)))
                exec_.arg_dict[name][:] = data
        for name, arr in self._aux_params.items():
            if name in exec_.aux_dict:
                exec_.aux_dict[name][:] = (
                    arr.asnumpy() if hasattr(arr, "asnumpy")
                    else np.asarray(arr))

    # -- c_predict_api surface ----------------------------------------
    def set_input(self, name, data):
        """``MXPredSetInput``."""
        if name not in self._input_shapes:
            raise MXNetError("unknown input %s" % name)
        data = np.asarray(data)
        want = tuple(self._exec.arg_dict[name].shape)
        if tuple(data.shape) != want:
            raise MXNetError(
                "input %s shape %s does not match bound shape %s"
                % (name, tuple(data.shape), want))
        self._exec.arg_dict[name][:] = data

    def forward(self):
        """``MXPredForward``."""
        self._exec.forward(is_train=False)

    def get_output(self, index=0):
        """``MXPredGetOutput`` → numpy."""
        return self._exec.outputs[index].asnumpy()

    def reshape(self, new_input_shapes):
        """``MXPredReshape``: switch to new input shapes, keeping the
        weights. Previously-seen shape signatures reuse their compiled
        executor from the LRU pool (the reference rebinds every time)."""
        self._input_shapes.update(new_input_shapes)
        self._bind()

    def predict(self, **inputs):
        """Convenience: set all inputs, forward, return all outputs."""
        for name, data in inputs.items():
            self.set_input(name, data)
        self.forward()
        return [o.asnumpy() for o in self._exec.outputs]

    # -- serving AOT fast path -----------------------------------------
    def compile(self, input_shapes_list=None):
        """AOT-lower and compile the serving fast path for each shape
        bucket up front (default: the currently-bound shapes). After
        this, ``predict_batch`` for any compiled bucket is a single
        donated-buffer device call with zero tracing; with
        ``MXTPU_COMPILE_CACHE`` set, the XLA executables warm-start
        from the persistent cache across process restarts."""
        if input_shapes_list is None:
            input_shapes_list = [dict(self._input_shapes)]
        for shapes in input_shapes_list:
            merged = dict(self._input_shapes)
            merged.update(shapes)
            key = _shape_key(merged)
            if key in self._serve_cache:
                continue
            exec_ = self._executor_for(key, merged)
            self._serve_cache[key] = _ServeFn(exec_, merged)
        return self

    def predict_batch(self, **inputs):
        """Serving dispatch: route the named input arrays through the
        AOT-compiled executable for their exact shape signature,
        compiling it on first sight (warmup). Returns a list of numpy
        outputs. Every call runs the program's ``dispatch_plan`` so the
        PR 5 recompile detector audits the steady state."""
        merged = dict(self._input_shapes)
        for name, data in inputs.items():
            if name not in self._input_shapes:
                raise MXNetError("unknown input %s" % name)
            merged[name] = tuple(np.asarray(data).shape)
        key = _shape_key(merged)
        fn = self._serve_cache.get(key)
        if fn is None:
            self.compile([merged])
            fn = self._serve_cache[key]
        return fn(inputs)

    @property
    def cached_shape_keys(self):
        """Shape signatures currently resident in the executor pool."""
        return list(self._exec_cache)


class _ServeFn(object):
    """One AOT-compiled forward for one input-shape bucket: parameters
    closed over as executable constants, streaming inputs donated."""

    def __init__(self, exec_, input_shapes):
        import jax

        self._exec = exec_
        self._program = exec_._program
        self._data_names = tuple(sorted(input_shapes))
        self._output_names = list(exec_._output_names)
        arg_names = tuple(exec_._arg_names)
        aux_names = tuple(exec_._aux_names)
        program = exec_._program
        data_names = self._data_names
        const_args = {
            name: arr._data
            for name, arr in zip(arg_names, exec_.arg_arrays)
            if name not in input_shapes
        }
        aux_vals = {n: a._data for n, a in zip(aux_names, exec_.aux_arrays)}
        rng = jax.random.PRNGKey(0) if exec_._needs_rng else None

        def serve(*data_vals):
            args = dict(const_args)
            args.update(zip(data_names, data_vals))
            outs, _ = program(args, aux_vals, rng, False)
            return tuple(outs)

        jitted = jax.jit(
            serve, donate_argnums=tuple(range(len(data_names))))
        self._avals = [
            jax.ShapeDtypeStruct(
                tuple(input_shapes[n]),
                exec_.arg_dict[n]._data.dtype)
            for n in data_names
        ]
        # AOT: lower + compile now (MXTPU_COMPILE_CACHE warm-starts
        # this), so the first request pays zero trace/compile time.
        # CPU XLA cannot honor donation — silence that warning, the
        # request stays meaningful on TPU.
        import warnings

        with warnings.catch_warnings():
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            self._compiled = jitted.lower(*self._avals).compile()
        # dispatch-plan signature: lets the anatomy recompile detector
        # fingerprint every serving dispatch exactly like a training
        # step dispatch (first sight per program = warmup-exempt)
        self._sig = tuple(
            (n, tuple(a.shape), str(a.dtype), "serve")
            for n, a in zip(data_names, self._avals))
        overrides = program.shape_overrides
        program.dispatch_plan(self._sig, lambda: overrides)

    def __call__(self, inputs):
        import time

        import jax.numpy as jnp

        overrides = self._program.shape_overrides
        self._program.dispatch_plan(self._sig, lambda: overrides)
        data_vals = []
        for name, aval in zip(self._data_names, self._avals):
            data = np.asarray(inputs[name])
            if tuple(data.shape) != tuple(aval.shape):
                raise MXNetError(
                    "input %s shape %s does not match compiled bucket %s"
                    % (name, tuple(data.shape), tuple(aval.shape)))
            # fresh device array per call: its buffer is donated to the
            # executable, so the output can alias it in place
            data_vals.append(jnp.asarray(data, dtype=aval.dtype))
        t0 = time.perf_counter()
        outs = self._compiled(*data_vals)
        outs = [np.asarray(o) for o in outs]
        _H_DISPATCH_SECONDS.observe(time.perf_counter() - t0)
        return outs


# --------------------------------------------------------------------------
# amalgamation analog: single-file inference bundle
# --------------------------------------------------------------------------

_BUNDLE_MAGIC_V1 = b"MXTPUPRED1"
_BUNDLE_MAGIC = b"MXTPUPRED2"


def _tensor_crcs(save_dict):
    return {
        name: zlib.crc32(np.ascontiguousarray(arr.asnumpy()).tobytes())
        for name, arr in save_dict.items()
    }


def export_bundle(fname, symbol, arg_params, aux_params=None):
    """Write symbol JSON + params as ONE file (the role the reference's
    amalgamation plays: a self-contained deployable predict artifact).
    The v2 header carries a manifest with per-section and per-tensor
    CRC32s — the same integrity discipline as the resilience
    checkpoint MANIFEST — so corruption is caught at load, not at
    first NaN."""
    js = symbol.tojson().encode()
    save_dict = {("arg:%s" % k): v for k, v in arg_params.items()}
    if aux_params:
        save_dict.update({("aux:%s" % k): v for k, v in aux_params.items()})
    param_bytes = nd.save_buffer(save_dict)
    manifest = json.dumps({
        "version": 2,
        "symbol": {"bytes": len(js), "crc32": zlib.crc32(js)},
        "params": {"bytes": len(param_bytes),
                   "crc32": zlib.crc32(param_bytes)},
        "tensors": _tensor_crcs(save_dict),
    }).encode()
    with open(fname, "wb") as f:
        f.write(_BUNDLE_MAGIC)
        f.write(struct.pack("<qqq", len(manifest), len(js),
                            len(param_bytes)))
        f.write(manifest)
        f.write(js)
        f.write(param_bytes)


def _verify_bundle_params(fname, manifest, param_bytes):
    """Per-tensor CRC verification: decode the param dict and check each
    tensor against the manifest so a corrupt bundle names the exact
    tensor, mirroring resilience.checkpoint.verify_checkpoint(deep=True)."""
    loaded = nd.load_buffer(param_bytes)
    want = manifest.get("tensors", {})
    for name, arr in loaded.items():
        if name not in want:
            raise MXNetError(
                "bundle %s: tensor %s missing from manifest (corrupt or "
                "tampered)" % (fname, name))
        got = zlib.crc32(np.ascontiguousarray(arr.asnumpy()).tobytes())
        if got != want[name]:
            raise MXNetError(
                "bundle %s: tensor %s fails CRC32 (corrupt)"
                % (fname, name))
    missing = set(want) - set(loaded)
    if missing:
        raise MXNetError(
            "bundle %s: tensors %s listed in manifest but absent"
            % (fname, sorted(missing)))
    return loaded


def load_bundle(fname, input_shapes, ctx=None, quant=None):
    """Load an ``export_bundle`` file into a ready Predictor. v2
    bundles are CRC-verified section by section and tensor by tensor;
    any mismatch raises naming the file and the tensor. v1 bundles
    (no manifest) still load."""
    with open(fname, "rb") as f:
        magic = f.read(len(_BUNDLE_MAGIC))
        if magic == _BUNDLE_MAGIC_V1:
            js_len, p_len = struct.unpack("<qq", f.read(16))
            js = f.read(js_len).decode()
            param_bytes = f.read(p_len)
            return Predictor(js, param_bytes, input_shapes, ctx=ctx,
                             quant=quant)
        if magic != _BUNDLE_MAGIC:
            raise MXNetError("%s is not a predictor bundle" % fname)
        m_len, js_len, p_len = struct.unpack("<qqq", f.read(24))
        manifest_raw = f.read(m_len)
        js_raw = f.read(js_len)
        param_bytes = f.read(p_len)
    try:
        manifest = json.loads(manifest_raw.decode())
    except (UnicodeDecodeError, json.JSONDecodeError):
        raise MXNetError(
            "bundle %s: manifest section unreadable (corrupt header)"
            % fname)
    if len(js_raw) != manifest["symbol"]["bytes"] or \
            zlib.crc32(js_raw) != manifest["symbol"]["crc32"]:
        raise MXNetError(
            "bundle %s: symbol section fails CRC32 (corrupt)" % fname)
    if len(param_bytes) != manifest["params"]["bytes"] or \
            zlib.crc32(param_bytes) != manifest["params"]["crc32"]:
        # locate the guilty tensor for the error message before failing
        try:
            _verify_bundle_params(fname, manifest, param_bytes)
        except MXNetError:
            raise
        except Exception:
            pass  # params not even decodable — use the section error
        raise MXNetError(
            "bundle %s: params section fails CRC32 (corrupt)" % fname)
    loaded = _verify_bundle_params(fname, manifest, param_bytes)
    return Predictor(js_raw.decode(), loaded, input_shapes, ctx=ctx,
                     quant=quant)


def params_from_checkpoint(ckpt_dir):
    """Load ``{arg:.../aux:...}`` params from a resilience checkpoint
    directory through its MANIFEST/CRC verification (deep per-tensor
    check) — the fp32-master / AMP training→serving path. Corruption
    raises CheckpointError naming the file and tensor."""
    from .resilience import checkpoint as ckpt

    ckpt.verify_checkpoint(ckpt_dir, deep=True)
    state = ckpt.load_state(ckpt_dir, verify=False)
    params = {}
    for name, arr in state["module"]["arg"].items():
        params["arg:%s" % name] = nd.array(np.asarray(arr, np.float32))
    for name, arr in state["module"]["aux"].items():
        params["aux:%s" % name] = nd.array(np.asarray(arr, np.float32))
    return params
