"""Mixture-of-Experts FFN with expert parallelism (ep mesh axis).

Beyond-reference capability (SURVEY.md §2.3 lists expert parallel as
absent in the reference): the TPU-native MoE recipe in the
Mesh-TensorFlow / GShard / Switch-Transformer lineage, written the XLA
way — routing, dispatch and combine are einsums over dense one-hot
dispatch tensors, and expert parallelism is nothing but a sharding
annotation: expert-major tensors carry ``PartitionSpec("ep", ...)``,
tokens stay dp-sharded, and GSPMD inserts the all-to-alls between the
token and expert layouts. No hand-written collectives, so the same
function runs single-device (tests) and on a dp x ep mesh (dryrun)
with identical numerics.

Routing is Switch-style top-1 with a capacity limit: tokens that
overflow an expert's capacity are dropped (contribute zero), matching
the published behavior; an auxiliary load-balance loss (Switch
Transformer eq. 4) keeps the router from collapsing onto one expert.
"""
import jax
import jax.numpy as jnp


def init_moe_params(rng, d_model, d_hidden, num_experts, dtype=jnp.float32):
    """Router + expert weights. Expert-major tensors lead with the E axis
    so ``PartitionSpec("ep", ...)`` shards whole experts."""
    import numpy as np

    r = np.random.RandomState(rng)
    scale = 1.0 / np.sqrt(d_model)
    return {
        "gate_w": jnp.asarray(
            r.randn(d_model, num_experts) * scale, dtype),
        "w_up": jnp.asarray(
            r.randn(num_experts, d_model, d_hidden) * scale, dtype),
        "w_down": jnp.asarray(
            r.randn(num_experts, d_hidden, d_model) / np.sqrt(d_hidden),
            dtype),
    }


def moe_partition_specs():
    """PartitionSpecs for init_moe_params output on a (dp, ..., ep) mesh."""
    from jax.sharding import PartitionSpec as P

    return {
        "gate_w": P(),                 # router replicated
        "w_up": P("ep", None, None),   # whole experts per ep shard
        "w_down": P("ep", None, None),
    }


def switch_moe(params, x, capacity_factor=1.25):
    """Top-1 MoE FFN. x: [tokens, d_model] -> ([tokens, d_model], aux_loss).

    Dense-dispatch formulation: dispatch/combine are [tokens, E, C]
    one-hots, expert compute is a batched einsum over [E, C, d] — the
    shape GSPMD splits cleanly along E (ep axis) with all-to-alls at the
    einsum boundaries.
    """
    tokens, d_model = x.shape
    num_experts = params["gate_w"].shape[1]
    capacity = int(max(1, tokens * capacity_factor / num_experts))

    logits = x.astype(jnp.float32) @ params["gate_w"].astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)              # [T, E]
    expert_idx = jnp.argmax(probs, axis=-1)              # [T]
    expert_prob = jnp.take_along_axis(
        probs, expert_idx[:, None], axis=-1)[:, 0]       # [T]
    assign = jax.nn.one_hot(expert_idx, num_experts, dtype=jnp.float32)

    # position of each token within its expert's queue; >= capacity drops
    pos_in_expert = (jnp.cumsum(assign, axis=0) - assign) * assign  # [T, E]
    keep = (pos_in_expert < capacity) * assign                      # [T, E]
    pos = pos_in_expert.sum(-1).astype(jnp.int32)                   # [T]
    pos_hot = jax.nn.one_hot(pos, capacity, dtype=jnp.float32)      # [T, C]

    dispatch = keep[:, :, None] * pos_hot[:, None, :]    # [T, E, C]
    combine = dispatch * expert_prob[:, None, None]      # [T, E, C]

    # Routing above stays f32; the expert FFN itself runs in the caller's
    # compute dtype (bf16 on the MXU) like the dense FFN it replaces.
    cdtype = x.dtype
    # token layout -> expert layout (GSPMD: all-to-all over ep here)
    expert_in = jnp.einsum("tec,td->ecd", dispatch.astype(cdtype), x)
    h = jax.nn.relu(jnp.einsum(
        "ecd,edh->ech", expert_in, params["w_up"].astype(cdtype)))
    expert_out = jnp.einsum(
        "ech,ehd->ecd", h, params["w_down"].astype(cdtype))
    # expert layout -> token layout (all-to-all back)
    y = jnp.einsum("tec,ecd->td", combine.astype(cdtype), expert_out)

    # Switch load-balance loss: E * sum_e fraction_tokens_e * mean_prob_e
    frac_tokens = assign.mean(0)
    mean_prob = probs.mean(0)
    aux_loss = num_experts * jnp.sum(frac_tokens * mean_prob)
    return y.astype(x.dtype), aux_loss


