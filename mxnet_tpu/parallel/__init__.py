"""TPU-native parallelism: meshes, sharded training steps, collectives.

This package is the TPU redesign of the reference's distribution stack
(SURVEY.md §2.3 / §5.8): DataParallelExecutorGroup + KVStore + ps-lite
become ONE compiled program over a ``jax.sharding.Mesh`` — gradients sync
with ``psum`` over ICI inside the step (dist_device_sync ≡ in-XLA
allreduce), the optimizer state shards ZeRO-style across data-parallel
peers (the "Automatic Cross-Replica Sharding of Weight Update" recipe from
PAPERS.md), and model-parallel placement (the reference's ctx_group +
PlaceDevice pass) becomes PartitionSpec annotations.
"""
from .mesh import (
    make_mesh, barrier, dp_sharding, replicated_sharding, device_count,
    init_distributed, allreduce_sum, reduce_scatter_sum, all_gather,
    broadcast_from_root,
)
from .train_step import ShardedTrainStep
from .ring_attention import ring_attention
from .moe import switch_moe, init_moe_params, moe_partition_specs
from .pipeline import pipeline_stages, pipelined_loss

__all__ = [
    "make_mesh", "barrier", "dp_sharding", "replicated_sharding",
    "device_count", "ShardedTrainStep", "ring_attention",
    "init_distributed", "allreduce_sum", "reduce_scatter_sum",
    "all_gather", "broadcast_from_root",
    "switch_moe", "init_moe_params", "moe_partition_specs",
    "pipeline_stages", "pipelined_loss",
]
