"""Ring attention: sequence/context parallelism over the mesh 'sp' axis.

The reference has NO sequence parallelism (SURVEY.md §2.3) — it handles
long sequences by bucketing + BPTT truncation. For a TPU framework,
sequence parallelism is first-class: this module implements ring attention
(blockwise attention with KV blocks rotated around the ring via
``jax.lax.ppermute`` over ICI), the idiomatic way to train sequences that
don't fit one chip — the capability the reference approximates with
model-parallel LSTM placement.

Used inside shard_map with sequence axis sharded over 'sp':
    out = ring_attention(q, k, v, axis_name='sp')
Each device holds a [B, T/sp, H, D] shard; after sp steps every query
block has attended to every KV block, with online softmax accumulation
(flash-attention style, numerically exact).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def _block_attn(q, k, v, bias=None, scale=1.0):
    """One (q-block, kv-block) interaction: returns (numerator, denominator,
    running max) for online softmax. Shapes: q [B,Tq,H,D], k/v [B,Tk,H,D]."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if bias is not None:
        s = s + bias
    m = jnp.max(s, axis=-1, keepdims=True)  # [B,H,Tq,1]
    p = jnp.exp(s - m)
    num = jnp.einsum("bhqk,bkhd->bqhd", p, v)
    den = jnp.sum(p, axis=-1)  # [B,H,Tq]
    return num, den, m[..., 0]


def ring_attention(q, k, v, axis_name="sp", causal=False, scale=None,
                   q_offset=None):
    """Exact attention with KV rotation around the `axis_name` ring.

    q, k, v: [B, T_local, H, D] shards (sequence sharded over axis_name).
    causal: apply causal masking using global positions.
    Returns [B, T_local, H, D].
    """
    n_dev = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    t_local = q.shape[1]
    if scale is None:
        scale = 1.0 / (q.shape[-1] ** 0.5)
    if q_offset is None:
        q_offset = idx * t_local

    def make_bias(kv_idx):
        if not causal:
            return None
        q_pos = q_offset + jnp.arange(t_local)  # [Tq]
        k_pos = kv_idx * t_local + jnp.arange(t_local)  # [Tk]
        mask = q_pos[:, None] >= k_pos[None, :]
        return jnp.where(mask, 0.0, -1e30)[None, None]  # [1,1,Tq,Tk]

    def body(carry, _):
        (kv_idx, kb, vb, num, den, mx) = carry
        bias = make_bias(kv_idx)
        n_i, d_i, m_i = _block_attn(q, kb, vb, bias, scale)
        # online softmax merge
        new_m = jnp.maximum(mx, m_i)
        alpha = jnp.exp(mx - new_m)  # rescale old accumulators
        beta = jnp.exp(m_i - new_m)
        num = num * alpha[..., None].transpose(0, 2, 1, 3) + \
            n_i * beta[..., None].transpose(0, 2, 1, 3)
        den = den * alpha + d_i * beta
        # rotate KV block to the next device over ICI
        perm = [(j, (j + 1) % n_dev) for j in range(n_dev)]
        kb = jax.lax.ppermute(kb, axis_name, perm)
        vb = jax.lax.ppermute(vb, axis_name, perm)
        kv_idx = jax.lax.ppermute(kv_idx, axis_name, perm)
        return (kv_idx, kb, vb, num, den, new_m), None

    b, t, h, d = q.shape
    num0 = jnp.zeros((b, t, h, d), q.dtype)
    den0 = jnp.zeros((b, h, t), q.dtype)
    m0 = jnp.full((b, h, t), -1e30, q.dtype)
    carry0 = (idx, k, v, num0, den0, m0)
    (kv_idx, kb, vb, num, den, mx), _ = jax.lax.scan(
        body, carry0, None, length=n_dev
    )
    den_t = den.transpose(0, 2, 1)[..., None]  # [B,Tq,H,1]
    return num / jnp.maximum(den_t, 1e-30)


def sequence_parallel_attention(q, k, v, mesh, causal=True, q_offset=0):
    """Convenience wrapper: shard_map ring_attention over mesh axis 'sp'.

    ``q_offset`` shifts every query's global position by a constant —
    the chunked-prefill continuation hook: when a serving engine
    prefills a long prompt in sequence chunks, a later chunk's queries
    sit at ``q_offset = chunk_start`` while its KV ring is local, so
    the causal mask keeps absolute-position semantics across chunks."""
    from jax.sharding import PartitionSpec as P
    from jax import shard_map

    def _shard_fn(qb, kb, vb):
        idx = jax.lax.axis_index("sp")
        return ring_attention(
            qb, kb, vb, axis_name="sp", causal=causal,
            q_offset=q_offset + idx * qb.shape[1])

    spec = P(None, "sp", None, None)
    fn = shard_map(
        _shard_fn,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return fn(q, k, v)
