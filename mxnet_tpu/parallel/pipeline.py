"""Pipeline parallelism over the pp mesh axis (GPipe schedule).

Beyond-reference capability (SURVEY.md §2.3: the reference's closest
analog is the model-parallel LSTM whose engine pipelines timesteps
across devices implicitly). Here the schedule is explicit and
TPU-native: inside ``shard_map`` over the ``pp`` axis each device holds
ONE stage's parameters, and a ``lax.scan`` over M + S - 1 ticks moves
activations stage-to-stage with ``ppermute`` — the collective-permute
pipelining recipe (scaling-book "training" chapter; PAPERS.md GPipe).
``ppermute`` is differentiable (its vjp is the reverse permute), so
``jax.grad`` through this function yields the correct 1F1B-equivalent
backward with no hand-written schedule.

Layout contract:
  * ``stage_params``: pytree whose leaves lead with an S axis, sharded
    ``PartitionSpec("pp", ...)`` — inside shard_map each device sees its
    own stage's slice (leading axis length 1, squeezed).
  * ``x``: [M, mb, ...] microbatches, replicated across pp (only stage 0
    reads it).
  * returns [M, mb, ...] last-stage outputs, valid on the LAST pp rank
    (other ranks return zeros — psum_gather or index at the caller).
"""
import jax
import jax.numpy as jnp


def pipeline_stages(stage_fn, stage_params, x, axis="pp"):
    """GPipe forward inside shard_map over ``axis``.

    stage_fn(params_slice, act) -> act, applied S times in sequence
    across the pp ranks; M microbatches stream through with a bubble of
    S - 1 ticks (GPipe fill/drain).
    """
    n_stages = jax.lax.axis_size(axis)
    stage = jax.lax.axis_index(axis)
    n_micro = x.shape[0]
    params_here = jax.tree_util.tree_map(
        lambda p: jnp.squeeze(p, 0), stage_params)

    perm = [(i, i + 1) for i in range(n_stages - 1)]
    zero_act = jnp.zeros_like(stage_fn(params_here, x[0]))

    def tick(carry, t):
        recv = carry
        # stage 0 feeds microbatch t (clamped; ticks past M are drain)
        feed = x[jnp.minimum(t, n_micro - 1)]
        act_in = jnp.where(stage == 0, feed, recv)
        act_out = stage_fn(params_here, act_in)
        # collect on the last stage for valid ticks t in [S-1, S-1+M)
        out_idx = t - (n_stages - 1)
        valid = jnp.logical_and(stage == n_stages - 1, out_idx >= 0)
        collected = jnp.where(valid, act_out, zero_act)
        sent = jax.lax.ppermute(act_out, axis, perm)
        return sent, (collected, out_idx)

    total_ticks = n_micro + n_stages - 1
    _, (outs, idxs) = jax.lax.scan(
        tick, zero_act, jnp.arange(total_ticks))
    # scatter collected ticks into microbatch order; invalid ticks
    # (fill bubble, idx < 0) are masked to zero and clamped onto slot 0,
    # so on the final stage every microbatch lands exactly once
    mask = (idxs >= 0).reshape((-1,) + (1,) * (outs.ndim - 1))
    ys = jnp.zeros((n_micro,) + outs.shape[1:], outs.dtype)
    ys = ys.at[jnp.clip(idxs, 0, n_micro - 1)].add(
        jnp.where(mask, outs, 0.0))
    return ys


def pipelined_loss(stage_fn, loss_fn, mesh, axis="pp"):
    """Build loss(params, x, y) running stages over the pp axis.

    ``loss_fn(last_act, y) -> scalar`` is computed on the last stage and
    psum-broadcast so every rank returns the same scalar (required for
    jax.grad under shard_map).
    """
    from jax.sharding import PartitionSpec as P
    from jax import shard_map

    def _inner(params, x, y):
        outs = pipeline_stages(stage_fn, params, x, axis=axis)
        n_stages = jax.lax.axis_size(axis)
        is_last = jax.lax.axis_index(axis) == n_stages - 1
        # zeros on non-final ranks; psum yields the last stage's loss
        loss = jnp.where(is_last, loss_fn(outs, y), 0.0)
        return jax.lax.psum(loss, axis)

    # P(axis) is a pytree-prefix spec: every params leaf leads with the
    # stacked stage axis and shards over pp; data/labels replicated.
    return shard_map(
        _inner, mesh=mesh, in_specs=(P(axis), P(), P()), out_specs=P(),
        check_vma=False,
    )
