"""Device-mesh utilities.

The reference's device topology handling (per-GPU engine workers, CUDA P2P
rings in CommDevice, PS key sharding across servers) collapses into one
``jax.sharding.Mesh``: ICI collectives replace P2P rings, GSPMD replaces
key sharding. Mesh axes follow the scaling-book convention:

- ``dp``: data parallel (batch dim)
- ``tp``: tensor parallel (hidden/feature dims)
- ``pp``: pipeline stages (inter-layer, the reference's ctx_group model
  parallelism)
- ``sp``: sequence/context parallel (ring attention)
"""
from __future__ import annotations

import logging
import os
import time as _time_mod

import numpy as np

from .. import telemetry as _tm

try:
    from ..resilience import fault as _fault
except ImportError:  # standalone import by path (tools helpers)
    _fault = None

_H_COLLECTIVE_SECONDS = _tm.histogram(
    "parallel.collective_seconds",
    "Host-observed latency of explicit cross-process collectives "
    "(labelled by op: barrier / allreduce_sum / broadcast)")

_INJECT_WARNED = False


def _injected_latency_ms():
    """MXNET_KVSTORE_INJECT_LATENCY_MS (bench/test knob), parsed to a
    float or 0. Warns ONCE per process when active: a forgotten export
    injects sleep into EVERY cross-process allreduce and is
    indistinguishable from a slow interconnect in the telemetry
    (ADVICE r5)."""
    global _INJECT_WARNED
    raw = os.environ.get("MXNET_KVSTORE_INJECT_LATENCY_MS")
    if not raw:
        return 0.0
    try:
        ms = float(raw)
    except ValueError:
        return 0.0
    if ms > 0.0 and not _INJECT_WARNED:
        _INJECT_WARNED = True
        logging.getLogger(__name__).warning(
            "MXNET_KVSTORE_INJECT_LATENCY_MS=%s: injecting %.1f ms of "
            "artificial latency into every cross-process allreduce "
            "(bench/test knob — unset it for real runs)", raw, ms)
    return ms


def device_count():
    import jax

    return jax.device_count()


#: Elastic world size (tools/watchdog.py --elastic exports it per
#: attempt): cap the mesh to the first N devices instead of all of
#: jax.devices(), so a restart after a replica loss can rebuild a
#: smaller mesh on the same host topology without a new launch config.
ENV_WORLD = "MXTPU_WORLD_SIZE"


def world_size(default=0):
    """The supervisor-imposed world size, or ``default`` when unset or
    malformed. 0 means "use every visible device"."""
    try:
        return max(0, int(os.environ.get(ENV_WORLD, default)))
    except (TypeError, ValueError):
        return max(0, int(default))


def host_count(default=1):
    """How many host processes share the input dataset — the sharding
    divisor for the streaming input pipeline's chunk shards
    (``io_pipeline``). Resolution order: ``MXTPU_NUM_HOSTS`` (explicit
    supervisor override, the host-level sibling of :data:`ENV_WORLD`),
    ``DMLC_NUM_WORKER`` (launcher convention), then
    ``jax.process_count()`` when jax is already up — never imported
    here, so a data-only process stays backend-free."""
    for name in ("MXTPU_NUM_HOSTS", "DMLC_NUM_WORKER"):
        raw = os.environ.get(name)
        if raw:
            try:
                return max(1, int(raw))
            except ValueError:
                pass
    import sys

    if "jax" in sys.modules:
        try:
            return max(1, int(sys.modules["jax"].process_count()))
        except Exception:  # noqa: BLE001 — backend not initialized yet
            pass
    return max(1, int(default))


def host_rank(default=0):
    """This process's rank within :func:`host_count` (same resolution
    order: ``MXTPU_HOST_RANK``, ``DMLC_RANK``, ``jax.process_index()``)."""
    for name in ("MXTPU_HOST_RANK", "DMLC_RANK"):
        raw = os.environ.get(name)
        if raw:
            try:
                return max(0, int(raw))
            except ValueError:
                pass
    import sys

    if "jax" in sys.modules:
        try:
            return max(0, int(sys.modules["jax"].process_index()))
        except Exception:  # noqa: BLE001
            pass
    return max(0, int(default))


def make_mesh(dp=None, tp=1, pp=1, sp=1, ep=1, devices=None):
    """Create a Mesh with axes (dp, tp, pp, sp, ep). dp defaults to
    whatever is left after tp*pp*sp*ep. With ``devices=None`` the mesh
    spans ``jax.devices()``, truncated to :data:`ENV_WORLD` when the
    supervisor imposed an elastic world size."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
        world = world_size()
        if world:
            devices = devices[:min(world, len(devices))]
    n = len(devices)
    if dp is None:
        assert n % (tp * pp * sp * ep) == 0, (
            "devices (%d) not divisible by tp*pp*sp*ep (%d)"
            % (n, tp * pp * sp * ep)
        )
        dp = n // (tp * pp * sp * ep)
    need = dp * tp * pp * sp * ep
    assert need <= n, "mesh %dx%dx%dx%dx%d needs %d devices, have %d" % (
        dp, tp, pp, sp, ep, need, n
    )
    dev_array = np.asarray(devices[:need]).reshape(dp, tp, pp, sp, ep)
    return Mesh(dev_array, ("dp", "tp", "pp", "sp", "ep"))


def dp_sharding(mesh):
    """Batch-sharded NamedSharding (leading axis over dp)."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec("dp"))


def replicated_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())


def barrier(tag="mxnet-tpu-barrier"):
    """Cross-PROCESS barrier (the TPU stand-in for ps::Postoffice::Barrier).

    Every process in the distributed runtime must reach this call before
    any returns — enforced by the coordination service via
    ``sync_global_devices``, which hard-fails (rather than silently
    passing) if a peer is gone. Single-process jobs return immediately:
    within one process XLA's program order already serializes."""
    import jax

    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        with _tm.span("mesh.barrier", tag=tag):
            t0 = _time_mod.perf_counter()
            multihost_utils.sync_global_devices(tag)
            _H_COLLECTIVE_SECONDS.observe(
                _time_mod.perf_counter() - t0, op="barrier")


_ALLREDUCE_CACHE = {}
_REDUCE_SCATTER_CACHE = {}
_ALL_GATHER_CACHE = {}


def _collective_preamble():
    """Shared guard for explicit host collectives: injected-latency
    bench knob + fault-injection hook. Collectives are never retried
    (peers issue them in lockstep), so delay is the only injectable
    fault — see allreduce_sum for the full rationale."""
    inj_ms = _injected_latency_ms()  # warns once when the knob is live
    if inj_ms:
        _time_mod.sleep(inj_ms / 1000.0)
    if _fault is not None and _fault.configured():
        _fault.fire("collective")


def allreduce_sum(value):
    """Sum a host value across ALL processes; returns numpy on each.

    The explicit (non-compiled) cross-worker reduction behind KVStore
    dist push — the TPU-native replacement for the reference's
    ps::KVWorker::ZPush + server-side merge (kvstore_dist_server.h
    DataHandleEx sync path). The compiled training path never calls
    this: there gradients sync as in-step psum over ICI/DCN.

    Implemented as a real XLA reduction over a device axis spanning all
    processes — O(N) on the wire and in host memory, unlike an
    allgather-then-sum which is O(P*N) per push and would dominate at
    real model sizes. Each process stages its contribution on its first
    local device (other local devices contribute zeros), XLA sums over
    the axis, and the replicated result is read back locally."""
    import jax

    value = np.asarray(value)
    if jax.process_count() <= 1:
        return value
    # Bench/test knob: model a high-RTT interconnect by sleeping before
    # the collective (benchmarks/dist_overlap_worker.py uses it to show
    # what the comm engine's overlap buys when the network, not the CPU,
    # is the bottleneck — on the 1-core CI box localhost gloo has ~zero
    # latency, so without this the collective chain can never be hidden).
    # The sleep releases the GIL like a real network wait would.
    # MXTPU_FAULT_INJECT delay_collective_ms: the slow/hung-peer class
    # the watchdog's progress staleness signal must catch. Collectives
    # are never retried (peers issue them in lockstep; re-entering one a
    # peer already left deadlocks the mesh), so delay is the only
    # injectable fault here.
    _collective_preamble()
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    nloc = jax.local_device_count()
    key = (value.shape, value.dtype.str, nloc)
    if key not in _ALLREDUCE_CACHE:
        mesh = Mesh(np.asarray(jax.devices()), ("proc",))
        in_sharding = NamedSharding(mesh, P("proc"))
        out_sharding = NamedSharding(mesh, P())
        fn = jax.jit(lambda x: jnp.sum(x, axis=0),
                     out_shardings=out_sharding)
        _ALLREDUCE_CACHE[key] = (in_sharding, fn)
    in_sharding, fn = _ALLREDUCE_CACHE[key]
    # exact sum: the value rides row 0, the other local rows are zeros
    with _tm.span("mesh.allreduce_sum", nbytes=value.nbytes):
        t0 = _time_mod.perf_counter()
        local = np.zeros((nloc,) + value.shape, value.dtype)
        local[0] = value
        garr = jax.make_array_from_process_local_data(in_sharding, local)
        out = np.asarray(fn(garr).addressable_data(0))
        _H_COLLECTIVE_SECONDS.observe(
            _time_mod.perf_counter() - t0, op="allreduce_sum")
    return out


def reduce_scatter_sum(value):
    """Sum a host value across ALL processes and return only THIS
    process's contiguous row-shard of the result.

    The first phase of the sharded weight update (arXiv:2004.13336, the
    ZeRO-1 pattern): instead of every worker receiving the full summed
    gradient (allreduce_sum) and redundantly applying the full optimizer
    update, each worker receives rows ``[rank*R/P, (rank+1)*R/P)`` of the
    sum, updates only that shard, and publishes it back via
    :func:`all_gather`. ``value.shape[0]`` must divide evenly by the
    process count — callers pad (kvstore.GradBucketer rounds flat
    buckets up). Single-process jobs get the whole sum back, so callers
    never special-case.

    Same staging scheme as allreduce_sum (value rides local row 0, other
    local device rows are zeros, XLA sums over the process-spanning
    device axis), but the output stays sharded over that axis so each
    process only reads back its own rows — the readback is O(N/P)
    instead of O(N)."""
    import jax

    value = np.asarray(value)
    nproc = jax.process_count()
    if nproc <= 1:
        return value
    assert value.ndim >= 1 and value.shape[0] % nproc == 0, (
        "reduce_scatter_sum: leading dim %r not divisible by %d processes"
        % (value.shape, nproc))
    _collective_preamble()
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    nloc = jax.local_device_count()
    key = (value.shape, value.dtype.str, nloc)
    if key not in _REDUCE_SCATTER_CACHE:
        mesh = Mesh(np.asarray(jax.devices()).reshape(nproc, nloc),
                    ("proc", "loc"))
        in_sharding = NamedSharding(mesh, P(("proc", "loc")))
        # sum over the staging axis; keep the result row-sharded over
        # processes so each one materializes only its own rows
        out_sharding = NamedSharding(mesh, P("proc"))
        fn = jax.jit(lambda x: jnp.sum(x, axis=0),
                     out_shardings=out_sharding)
        _REDUCE_SCATTER_CACHE[key] = (in_sharding, fn)
    in_sharding, fn = _REDUCE_SCATTER_CACHE[key]
    with _tm.span("mesh.reduce_scatter_sum", nbytes=value.nbytes):
        t0 = _time_mod.perf_counter()
        local = np.zeros((nloc,) + value.shape, value.dtype)
        local[0] = value
        garr = jax.make_array_from_process_local_data(in_sharding, local)
        out = fn(garr)
        # result is sharded over "proc" and replicated over "loc": every
        # local device holds this process's full row-shard — read one
        mine = np.asarray(out.addressable_shards[0].data)
        _H_COLLECTIVE_SECONDS.observe(
            _time_mod.perf_counter() - t0, op="reduce_scatter_sum")
    return mine


def all_gather(value):
    """Concatenate equal-shaped per-process shards along axis 0; every
    process receives the full result (inverse of reduce_scatter_sum —
    the publish phase of the sharded weight update: each worker
    contributes its updated weight shard, all receive the full vector).

    Single-process jobs return the value unchanged."""
    import jax

    value = np.asarray(value)
    nproc = jax.process_count()
    if nproc <= 1:
        return value
    _collective_preamble()
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    nloc = jax.local_device_count()
    key = (value.shape, value.dtype.str, nloc)
    if key not in _ALL_GATHER_CACHE:
        mesh = Mesh(np.asarray(jax.devices()).reshape(nproc, nloc),
                    ("proc", "loc"))
        in_sharding = NamedSharding(mesh, P(("proc", "loc")))
        out_sharding = NamedSharding(mesh, P())
        # local rows beyond row 0 are zeros; summing within each
        # process's block recovers that process's contribution exactly,
        # then blocks concatenate in process order
        def _gather(x):
            blocks = x.reshape((nproc, nloc) + value.shape)
            per_proc = jnp.sum(blocks, axis=1)  # (nproc,) + value.shape
            return per_proc.reshape((nproc * value.shape[0],)
                                    + value.shape[1:])

        fn = jax.jit(_gather, out_shardings=out_sharding)
        _ALL_GATHER_CACHE[key] = (in_sharding, fn)
    in_sharding, fn = _ALL_GATHER_CACHE[key]
    with _tm.span("mesh.all_gather", nbytes=value.nbytes):
        t0 = _time_mod.perf_counter()
        local = np.zeros((nloc,) + value.shape, value.dtype)
        local[0] = value
        garr = jax.make_array_from_process_local_data(in_sharding, local)
        out = np.asarray(fn(garr).addressable_data(0))
        _H_COLLECTIVE_SECONDS.observe(
            _time_mod.perf_counter() - t0, op="all_gather")
    return out


def broadcast_from_root(value):
    """Broadcast a host value from process 0 to every process.

    KVStore dist init semantics: the reference's kv.init writes rank 0's
    value to the servers and every worker pulls it, so all workers start
    from identical weights regardless of local seeding."""
    import jax

    value = np.asarray(value)
    if jax.process_count() <= 1:
        return value
    from jax.experimental import multihost_utils

    t0 = _time_mod.perf_counter()
    out = np.asarray(multihost_utils.broadcast_one_to_all(value))
    _H_COLLECTIVE_SECONDS.observe(
        _time_mod.perf_counter() - t0, op="broadcast")
    return out


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None):
    """Join the multi-host JAX runtime (the worker-side counterpart of
    tools/launch.py — the TPU replacement for the reference's
    DMLC_PS_ROOT_URI bootstrap, kvstore.h InitPSEnv).

    Reads JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID
    (as set by tools/launch.py) when args are omitted; a single-process
    job is a no-op. Safe to call twice.
    """
    import os

    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    num_processes = int(num_processes or os.environ.get(
        "JAX_NUM_PROCESSES", 1))
    process_id = int(process_id if process_id is not None
                     else os.environ.get("JAX_PROCESS_ID", 0))
    if num_processes <= 1 or coordinator_address is None:
        return False
    if jax.distributed.is_initialized():
        return True
    try:
        # The CPU backend needs an explicit collectives implementation
        # for cross-process psum/allgather (without it they silently
        # reduce over local devices only — tested, not hypothetical).
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except AttributeError:
        pass  # older jax: option absent, CPU multi-process unsupported
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
    except RuntimeError as e:
        # jax 0.9 raises "distributed.initialize should only be called
        # once."; older versions say "already initialized"
        msg = str(e).lower()
        if "already" in msg or "once" in msg:
            return True
        raise
    return True
