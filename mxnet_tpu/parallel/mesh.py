"""Device-mesh utilities.

The reference's device topology handling (per-GPU engine workers, CUDA P2P
rings in CommDevice, PS key sharding across servers) collapses into one
``jax.sharding.Mesh``: ICI collectives replace P2P rings, GSPMD replaces
key sharding. Mesh axes follow the scaling-book convention:

- ``dp``: data parallel (batch dim)
- ``tp``: tensor parallel (hidden/feature dims)
- ``pp``: pipeline stages (inter-layer, the reference's ctx_group model
  parallelism)
- ``sp``: sequence/context parallel (ring attention)
"""
from __future__ import annotations

import numpy as np


def device_count():
    import jax

    return jax.device_count()


def make_mesh(dp=None, tp=1, pp=1, sp=1, devices=None):
    """Create a Mesh with axes (dp, tp, pp, sp). dp defaults to whatever is
    left after tp*pp*sp."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if dp is None:
        assert n % (tp * pp * sp) == 0, (
            "devices (%d) not divisible by tp*pp*sp (%d)" % (n, tp * pp * sp)
        )
        dp = n // (tp * pp * sp)
    need = dp * tp * pp * sp
    assert need <= n, "mesh %dx%dx%dx%d needs %d devices, have %d" % (
        dp, tp, pp, sp, need, n
    )
    dev_array = np.asarray(devices[:need]).reshape(dp, tp, pp, sp)
    return Mesh(dev_array, ("dp", "tp", "pp", "sp"))


def dp_sharding(mesh):
    """Batch-sharded NamedSharding (leading axis over dp)."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec("dp"))


def replicated_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())


def barrier(mesh=None):
    """Cross-device barrier: a tiny psum everyone must reach (the TPU
    stand-in for ps::Postoffice::Barrier)."""
    import jax
    import jax.numpy as jnp

    x = jnp.ones(())
    jax.block_until_ready(x + 0)


def init_distributed(coordinator_address=None, num_processes=None,
                     process_id=None):
    """Join the multi-host JAX runtime (the worker-side counterpart of
    tools/launch.py — the TPU replacement for the reference's
    DMLC_PS_ROOT_URI bootstrap, kvstore.h InitPSEnv).

    Reads JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES / JAX_PROCESS_ID
    (as set by tools/launch.py) when args are omitted; a single-process
    job is a no-op. Safe to call twice.
    """
    import os

    import jax

    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    num_processes = int(num_processes or os.environ.get(
        "JAX_NUM_PROCESSES", 1))
    process_id = int(process_id if process_id is not None
                     else os.environ.get("JAX_PROCESS_ID", 0))
    if num_processes <= 1 or coordinator_address is None:
        return False
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes, process_id=process_id)
    except RuntimeError as e:
        # jax 0.9 raises "distributed.initialize should only be called
        # once."; older versions say "already initialized"
        msg = str(e).lower()
        if "already" in msg or "once" in msg:
            return True
        raise
    return True
