"""Device-mesh utilities.

The reference's device topology handling (per-GPU engine workers, CUDA P2P
rings in CommDevice, PS key sharding across servers) collapses into one
``jax.sharding.Mesh``: ICI collectives replace P2P rings, GSPMD replaces
key sharding. Mesh axes follow the scaling-book convention:

- ``dp``: data parallel (batch dim)
- ``tp``: tensor parallel (hidden/feature dims)
- ``pp``: pipeline stages (inter-layer, the reference's ctx_group model
  parallelism)
- ``sp``: sequence/context parallel (ring attention)
"""
from __future__ import annotations

import numpy as np


def device_count():
    import jax

    return jax.device_count()


def make_mesh(dp=None, tp=1, pp=1, sp=1, devices=None):
    """Create a Mesh with axes (dp, tp, pp, sp). dp defaults to whatever is
    left after tp*pp*sp."""
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    n = len(devices)
    if dp is None:
        assert n % (tp * pp * sp) == 0, (
            "devices (%d) not divisible by tp*pp*sp (%d)" % (n, tp * pp * sp)
        )
        dp = n // (tp * pp * sp)
    need = dp * tp * pp * sp
    assert need <= n, "mesh %dx%dx%dx%d needs %d devices, have %d" % (
        dp, tp, pp, sp, need, n
    )
    dev_array = np.asarray(devices[:need]).reshape(dp, tp, pp, sp)
    return Mesh(dev_array, ("dp", "tp", "pp", "sp"))


def dp_sharding(mesh):
    """Batch-sharded NamedSharding (leading axis over dp)."""
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec("dp"))


def replicated_sharding(mesh):
    from jax.sharding import NamedSharding, PartitionSpec

    return NamedSharding(mesh, PartitionSpec())


def barrier(mesh=None):
    """Cross-device barrier: a tiny psum everyone must reach (the TPU
    stand-in for ps::Postoffice::Barrier)."""
    import jax
    import jax.numpy as jnp

    x = jnp.ones(())
    jax.block_until_ready(x + 0)
