"""Sharded training step: the fused TPU path for Module training.

This is the TPU-native replacement for the reference's §3.1 hot loop
(per-device executors + KVStore push/pull): the ENTIRE step — forward,
backward, gradient allreduce, optimizer update — compiles to one XLA
program over a Mesh:

- batch sharded over ``dp`` (DataParallelExecutorGroup.decide_slices →
  PartitionSpec('dp'))
- params replicated over dp, optionally sharded over ``tp``
  (PlaceDevice/ctx_group → PartitionSpec)
- gradient sync = psum over ICI, inserted by GSPMD from the shardings
  (KVStore device/dist_device_sync → in-XLA allreduce; the reference's
  priority-ordered push overlap becomes XLA latency-hiding scheduling)
- optimizer state sharded over dp (ZeRO / "Automatic Cross-Replica
  Sharding of Weight Update", PAPERS.md)
"""
from __future__ import annotations

import functools

import numpy as np


class ShardedTrainStep:
    """Compile a Symbol's train step over a Mesh.

    Wraps the same _GraphProgram the Executor uses, but jits it with
    sharding constraints instead of per-device loops. Loss convention:
    mean over the global batch of the first output (the *Output loss heads
    carry their own backward, so we drive vjp with ones like the Executor
    does).
    """

    def __init__(self, symbol, mesh, optimizer=None, param_specs=None,
                 data_names=("data",), label_names=("softmax_label",),
                 dtype=None, zero1=True):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..executor import _GraphProgram

        self.symbol = symbol
        self.mesh = mesh
        self.optimizer = optimizer
        self.program = _GraphProgram(symbol)
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.data_names = list(data_names)
        self.label_names = list(label_names)
        self.param_names = [
            n for n in self.arg_names
            if n not in self.data_names + self.label_names
        ]
        self.zero1 = zero1
        # parameter shardings: default replicated; caller may pass
        # name -> PartitionSpec (tp-sharded layers)
        self.param_specs = dict(param_specs or {})
        self._mesh_axes = mesh.axis_names
        self._batch_spec = P("dp")
        self._step = None

    # ------------------------------------------------------------------
    def _spec_for(self, name):
        from jax.sharding import PartitionSpec as P

        return self.param_specs.get(name, P())

    def init(self, arg_shapes_by_name, initializer, seed=0):
        """Allocate + initialize sharded params/opt-state on the mesh."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding

        rng = np.random.RandomState(seed)
        params = {}
        for name in self.param_names:
            shape = arg_shapes_by_name[name]
            host = np.zeros(shape, np.float32)

            class _Arr:
                def __init__(self, a):
                    self._a = a
                    self.shape = a.shape
                    self.size = a.size
                    self.dtype = a.dtype

                def __setitem__(self, k, v):
                    self._a[k] = v

            wrapper = _Arr(host)
            initializer(name, wrapper)
            sharding = NamedSharding(self.mesh, self._spec_for(name))
            params[name] = jax.device_put(host, sharding)
        aux = {}
        for name, shape in arg_shapes_by_name.items():
            if name in self.aux_names:
                pass
        _, _, aux_shapes = self.symbol.infer_shape(**arg_shapes_by_name)
        for name, shape in zip(self.aux_names, aux_shapes):
            init_val = (
                np.ones(shape, np.float32)
                if name.endswith("var")
                else np.zeros(shape, np.float32)
            )
            aux[name] = jax.device_put(
                init_val, NamedSharding(self.mesh, self._spec_for(name))
            )
        opt_state = self._init_opt_state(params)
        return params, aux, opt_state

    def _init_opt_state(self, params):
        """SGD-momentum / Adam state, optionally dp-sharded (ZeRO-1)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        if self.optimizer is None:
            return {}
        kind = type(self.optimizer).__name__.lower()
        state = {}
        for name, p in params.items():
            spec = self._spec_for(name)
            if self.zero1 and spec == P() and p.ndim >= 1 and p.shape[0] % self.mesh.shape["dp"] == 0:
                spec = P("dp")  # shard replicated-param state over dp
            sharding = NamedSharding(self.mesh, spec)
            zeros = jax.device_put(np.zeros(p.shape, np.float32), sharding)
            if kind in ("sgd", "nag", "ccsgd") and getattr(self.optimizer, "momentum", 0):
                state[name] = (zeros,)
            elif kind == "adam":
                state[name] = (zeros, jax.device_put(
                    np.zeros(p.shape, np.float32), sharding))
        return state

    # ------------------------------------------------------------------
    def compile(self, data_shapes_by_name):
        """Build + jit the fused step fn. Returns self."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        program = self.program
        param_names = tuple(self.param_names)
        aux_names = tuple(self.aux_names)
        opt = self.optimizer
        kind = type(opt).__name__.lower() if opt is not None else None
        lr = float(getattr(opt, "lr", 0.01)) if opt else 0.0
        momentum = float(getattr(opt, "momentum", 0.0)) if opt else 0.0
        wd = float(getattr(opt, "wd", 0.0)) if opt else 0.0
        rescale = float(getattr(opt, "rescale_grad", 1.0)) if opt else 1.0
        beta1 = float(getattr(opt, "beta1", 0.9)) if opt else 0.9
        beta2 = float(getattr(opt, "beta2", 0.999)) if opt else 0.999
        eps = float(getattr(opt, "epsilon", 1e-8)) if opt else 1e-8

        batch_sharding = NamedSharding(self.mesh, self._batch_spec)

        def step(params, aux, opt_state, batch, rng, t):
            def loss_fn(ps):
                args = dict(ps)
                args.update(batch)
                outs, new_aux = program(args, aux, rng, True)
                # *Output heads: drive vjp with ones (Executor.backward
                # convention — the loss op bakes its own gradient)
                return sum(jnp.sum(o) for o in outs), (outs, new_aux)

            grads, (outs, new_aux) = jax.grad(
                loss_fn, has_aux=True
            )(params)
            # gradient allreduce over dp happens implicitly: params are
            # replicated, batch is dp-sharded → GSPMD inserts psum here.
            new_params = {}
            new_opt = {}
            for name in param_names:
                g = grads[name] * rescale + wd * params[name]
                if kind in ("sgd", "nag", "ccsgd") and name in opt_state:
                    (mom,) = opt_state[name]
                    mom = momentum * mom - lr * g
                    new_params[name] = params[name] + mom
                    new_opt[name] = (mom,)
                elif kind == "adam" and name in opt_state:
                    m, v = opt_state[name]
                    m = beta1 * m + (1 - beta1) * g
                    v = beta2 * v + (1 - beta2) * jnp.square(g)
                    mhat = m / (1 - beta1 ** t)
                    vhat = v / (1 - beta2 ** t)
                    new_params[name] = params[name] - lr * mhat / (
                        jnp.sqrt(vhat) + eps
                    )
                    new_opt[name] = (m, v)
                else:
                    new_params[name] = params[name] - lr * g
            return new_params, new_aux, new_opt, outs

        # pin shardings: params by spec, batch over dp
        param_shardings = {
            n: NamedSharding(self.mesh, self._spec_for(n))
            for n in self.param_names
        }
        aux_shardings = {
            n: NamedSharding(self.mesh, self._spec_for(n))
            for n in self.aux_names
        }
        batch_shardings = {
            n: batch_sharding for n in data_shapes_by_name
        }
        self._step = jax.jit(
            step,
            in_shardings=(
                param_shardings, aux_shardings, None, batch_shardings,
                None, None,
            ),
            donate_argnums=(0, 2),
        )
        return self

    def __call__(self, params, aux, opt_state, batch, rng, t=1):
        assert self._step is not None, "call compile() first"
        return self._step(params, aux, opt_state, batch, rng, t)
