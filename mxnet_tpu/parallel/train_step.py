"""Sharded training step: the fused TPU path for Module training.

This is the TPU-native replacement for the reference's §3.1 hot loop
(per-device executors + KVStore push/pull, python/mxnet/module/module.py:432-553
+ model.py:88-117): the ENTIRE step — forward, backward, gradient
allreduce, optimizer update — compiles to one XLA program over a Mesh:

- batch sharded over ``dp`` (DataParallelExecutorGroup.decide_slices →
  jax.sharding with PartitionSpec('dp'))
- params replicated over dp, optionally sharded over ``tp``
  (PlaceDevice/ctx_group → PartitionSpec)
- gradient sync = psum over ICI, inserted by GSPMD from the shardings
  (KVStore device/dist_device_sync → in-XLA allreduce; the reference's
  priority-ordered push overlap becomes XLA latency-hiding scheduling)
- optimizer state optionally sharded over dp (ZeRO-1 / "Automatic
  Cross-Replica Sharding of Weight Update", PAPERS.md)

The optimizer update is NOT re-implemented here: the step function
traces straight through ``Optimizer.update`` of ANY registered optimizer
(reference python/mxnet/optimizer.py surface) by wrapping the traced
jax values in NDArrays — the imperative op layer nests fine under jit.
Step-dependent quantities (learning rate after scheduling, update count
``t`` for Adam-style bias correction) enter the compiled program as
traced scalars so one compilation serves every step.
"""
from __future__ import annotations

import numpy as np

from .. import telemetry as _tm

_M_STEPS = _tm.counter(
    "train_step.steps", "Optimizer steps dispatched through the fused "
    "ShardedTrainStep path")


class _EveryKeyCount(dict):
    """Stand-in for Optimizer._index_update_count during tracing: every
    parameter reads the SAME traced step counter ``t`` (the fused step
    updates all params exactly once per step, so the per-index counts
    the reference tracks are all equal to t here)."""

    def __init__(self, t):
        super().__init__()
        self._t = t

    def __getitem__(self, key):
        return self._t

    def __setitem__(self, key, value):
        pass

    def __contains__(self, key):
        return True


def _wrap_state(state, NDArray):
    if state is None:
        return None
    if isinstance(state, tuple):
        return tuple(_wrap_state(s, NDArray) for s in state)
    return NDArray(state)


def _unwrap_state(state):
    if state is None:
        return None
    if isinstance(state, tuple):
        return tuple(_unwrap_state(s) for s in state)
    return state._data


class ShardedTrainStep:
    """Compile a Symbol's full train step over a Mesh.

    Wraps the same _GraphProgram the Executor uses, but jits it with
    sharding constraints instead of per-device loops. Loss convention:
    sum of outputs drives the vjp (the *Output loss heads carry their own
    backward, like Executor.backward); the optimizer's rescale_grad
    normalizes by global batch exactly as the reference's updater does.
    """

    def __init__(self, symbol, mesh, optimizer=None, param_specs=None,
                 data_names=("data",), label_names=("softmax_label",),
                 dtype=None, zero1=False):
        from jax.sharding import PartitionSpec as P

        from ..executor import _GraphProgram

        self.symbol = symbol
        self.mesh = mesh
        self.optimizer = optimizer
        self.program = _GraphProgram(symbol)
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.data_names = list(data_names)
        self.label_names = list(label_names)
        self.param_names = [
            n for n in self.arg_names
            if n not in self.data_names + self.label_names
        ]
        # ZeRO-1: shard otherwise-replicated optimizer state over dp when
        # the leading dim divides evenly (opt-in: changes layout only, not
        # numerics — each dp rank updates its state shard then the
        # all-gather is implicit in the next step's reads).
        self.zero1 = zero1
        # parameter shardings: default replicated; caller may pass
        # name -> PartitionSpec (tp-sharded layers)
        self.param_specs = dict(param_specs or {})
        self._batch_spec = P("dp")
        self._step = None
        self._step_multi = {}  # K -> jitted K-step scan program
        self._needs_rng = any(
            (not n.is_variable) and n.op.needs_rng
            for n in self.program.nodes
        )

    # ------------------------------------------------------------------
    def _spec_for(self, name):
        from jax.sharding import PartitionSpec as P

        return self.param_specs.get(name, P())

    def _sharding_for(self, name):
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, self._spec_for(name))

    def _state_sharding_for(self, name, arr):
        """Opt-state sharding: param's spec, or dp-sharded under ZeRO-1."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = self._spec_for(name)
        if (self.zero1 and spec == P() and arr.ndim >= 1
                and arr.shape[0] % self.mesh.shape["dp"] == 0):
            spec = P("dp")
        return NamedSharding(self.mesh, spec)

    def batch_sharding(self):
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, self._batch_spec)

    def batch_sharding_stacked(self):
        """Sharding for a (K, batch, ...) stack of K step batches: the
        scan axis is unsharded, rows shard over dp like batch_sharding."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(
            self.mesh, P(*((None,) + tuple(self._batch_spec))))

    # ------------------------------------------------------------------
    def place_params(self, arg_arrays_by_name, aux_arrays_by_name):
        """device_put host/NDArray values onto the mesh by spec.

        Accepts numpy arrays or NDArrays; returns dict of jax.Arrays."""
        import jax

        def _np(v):
            return v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)

        params = {
            n: jax.device_put(_np(arg_arrays_by_name[n]), self._sharding_for(n))
            for n in self.param_names
        }
        aux = {
            n: jax.device_put(_np(aux_arrays_by_name[n]), self._sharding_for(n))
            for n in self.aux_names
        }
        return params, aux

    def make_state(self, params):
        """Build optimizer state via the optimizer's OWN create_state on
        host zeros, then place it on the mesh (ZeRO-1 aware)."""
        import jax

        from .. import ndarray as ndmod

        if self.optimizer is None:
            return {}
        state = {}
        for i, name in enumerate(self.param_names):
            p = params[name]
            host_w = ndmod.zeros(p.shape)
            st = self.optimizer.create_state(i, host_w)

            def _place(s):
                if s is None:
                    return None
                if isinstance(s, tuple):
                    return tuple(_place(x) for x in s)
                return jax.device_put(
                    s.asnumpy(), self._state_sharding_for(name, s)
                )

            state[name] = _place(st)
        return state

    def init(self, arg_shapes_by_name, initializer, seed=0):
        """Allocate + initialize sharded params/aux/opt-state on the mesh."""
        host_params = {}
        for name in self.param_names:
            shape = arg_shapes_by_name[name]
            host = np.zeros(shape, np.float32)

            class _Arr:
                def __init__(self, a):
                    self._a = a
                    self.shape = a.shape
                    self.size = a.size
                    self.dtype = a.dtype

                def __setitem__(self, k, v):
                    self._a[k] = v

                def asnumpy(self):
                    return self._a

            wrapper = _Arr(host)
            initializer(name, wrapper)
            host_params[name] = host
        _, _, aux_shapes = self.symbol.infer_shape(**arg_shapes_by_name)
        host_aux = {}
        for name, shape in zip(self.aux_names, aux_shapes):
            host_aux[name] = (
                np.ones(shape, np.float32)
                if name.endswith("var")
                else np.zeros(shape, np.float32)
            )
        params, aux = self.place_params(host_params, host_aux)
        opt_state = self.make_state(params)
        return params, aux, opt_state

    # ------------------------------------------------------------------
    def _apply_optimizer(self, params, grads, opt_state, lr, t):
        """Trace through Optimizer.update for every param.

        Patches the instance's step-dependent attributes with traced
        stand-ins for the duration of the trace (this method only runs
        at trace time), so the SAME compiled program is valid for every
        step: lr comes from the host scheduler each call, t drives
        Adam-style bias correction in-graph."""
        from ..ndarray import NDArray

        opt = self.optimizer
        new_params, new_state = {}, {}
        if opt is None:
            for name in self.param_names:
                new_params[name] = params[name] - lr * grads[name]
            return new_params, new_state

        saved_lr = opt.lr
        saved_sched = opt.lr_scheduler
        saved_counts = opt._index_update_count
        saved_num_update = opt.num_update
        opt.lr = lr
        opt.lr_scheduler = None  # host computes the scheduled lr
        opt._index_update_count = _EveryKeyCount(t)
        opt._update_count = lambda index: None  # instance shadow
        try:
            for i, name in enumerate(self.param_names):
                w = NDArray(params[name])
                g = NDArray(grads[name])
                st = _wrap_state(opt_state.get(name), NDArray)
                opt.update(i, w, g, st)
                new_params[name] = w._data
                if st is not None:
                    new_state[name] = _unwrap_state(st)
            # params/state owned by a sharing module (BucketingModule:
            # the owner dict may cover a superset of this symbol's args)
            # pass through untouched
            for name in params:
                if name not in new_params:
                    new_params[name] = params[name]
            for name in opt_state:
                if name not in new_state:
                    new_state[name] = opt_state[name]
        finally:
            del opt.__dict__["_update_count"]
            opt.lr = saved_lr
            opt.lr_scheduler = saved_sched
            opt._index_update_count = saved_counts
            opt.num_update = saved_num_update
        return new_params, new_state

    def _make_step_fn(self):
        """The single-step fwd+bwd+psum+optimizer body (pure; shared by
        the per-step jit and the K-step lax.scan program)."""
        import jax
        import jax.numpy as jnp

        from ..executor import _mirror_enabled, _mirror_policy

        program = self.program
        do_mirror = _mirror_enabled()

        def step(params, aux, opt_state, batch, rng, lr, t):
            def loss_fn(ps):
                args = dict(ps)
                args.update(batch)
                outs, new_aux = program(args, aux, rng, True)
                # *Output heads: drive vjp with ones (Executor.backward
                # convention — the loss op bakes its own gradient)
                return sum(jnp.sum(o) for o in outs), (outs, new_aux)

            if do_mirror:
                # MXNET_BACKWARD_DO_MIRROR: rematerialize cheap ops in
                # backward, keep dot/conv residuals (executor._mirror_policy)
                loss_fn = jax.checkpoint(loss_fn, policy=_mirror_policy)

            grads, (outs, new_aux) = jax.grad(loss_fn, has_aux=True)(params)
            # gradient allreduce over dp happens implicitly: params are
            # replicated, batch is dp-sharded → GSPMD inserts psum here.
            new_params, new_opt = self._apply_optimizer(
                params, grads, opt_state, lr, t
            )
            new_aux = {**aux, **new_aux}  # carry shared-owner extras through
            return new_params, new_aux, new_opt, outs

        return step

    def compile(self, data_shapes_by_name=None):
        """Build + jit the fused step fn. Returns self.

        Shardings are NOT pinned here: inputs arrive committed (placed by
        place_params/make_state/batch device_put) and GSPMD propagates —
        the idiomatic "computation follows sharding" path; donation keeps
        params/opt-state in place across steps."""
        import jax

        self._step = jax.jit(self._make_step_fn(), donate_argnums=(0, 1, 2))
        return self

    def compile_multi(self, k):
        """Jit a K-step program: lax.scan of the fused step over stacked
        batches — ONE host dispatch per K optimizer steps.

        Motivation (VERDICT r4 #3): on the tunneled v5e a b32 step pays
        ~13.7 ms host dispatch against ~11.6 ms device time; scanning K
        steps inside one XLA program amortizes the dispatch to 1/K per
        step, the in-graph analog of the reference's dispatch-hiding
        threaded engine (threaded_engine_perdevice.cc:26-136 — its
        python thread never waits on the device). Exact same per-step
        math: the scan body IS the single-step body; lr/t/rng arrive as
        (K,)-stacked xs so schedules advance per micro-step.

        Returns the jitted fn (params, aux, opt, batches[K,...],
        rngs[K,2], lrs[K], ts[K]) -> (params, aux, opt, outs[K, ...]);
        cached per K."""
        import jax

        fn = self._step_multi.get(k)
        if fn is not None:
            return fn
        step = self._make_step_fn()

        def multi(params, aux, opt_state, batches, rngs, lrs, ts):
            def body(carry, xs):
                p, a, s = carry
                batch_k, rng_k, lr_k, t_k = xs
                np_, na, ns, outs = step(p, a, s, batch_k, rng_k,
                                         lr_k, t_k)
                return (np_, na, ns), outs

            (p, a, s), outs = jax.lax.scan(
                body, (params, aux, opt_state), (batches, rngs, lrs, ts))
            return p, a, s, outs

        fn = jax.jit(multi, donate_argnums=(0, 1, 2))
        self._step_multi[k] = fn
        return fn

    def call_multi(self, params, aux, opt_state, batches, lrs, ts):
        """Run K fused steps in one dispatch (see compile_multi).

        `batches`: dict name -> (K, batch, ...) arrays already placed
        with batch_sharding_stacked(); `lrs`/`ts`: length-K sequences
        (per-micro-step schedule values, host-computed)."""
        import jax.numpy as jnp

        k = len(lrs)
        fn = self.compile_multi(k)
        # dispatch fast path (_GraphProgram.dispatch_plan): key on the
        # batch entries alone — param shapes are fixed per trainer, and
        # creation-shape overrides depend only on the PER-STEP shapes
        # (scan axis dropped)
        sig = tuple(
            (n, tuple(v.shape[1:]), str(v.dtype),
             getattr(v, "sharding", None))
            for n, v in batches.items())

        def _build():
            from ..executor import resolve_creation_shapes

            shapes = {n: tuple(v.shape) for n, v in params.items()}
            shapes.update(
                {n: tuple(v.shape[1:]) for n, v in batches.items()})
            return resolve_creation_shapes(self.symbol, shapes)

        self.program.dispatch_plan(sig, _build)
        if self._needs_rng:
            from .. import random as _random

            rngs = jnp.stack([_random.next_key() for _ in range(k)])
        else:
            rngs = jnp.zeros((k, 2), jnp.uint32)
        _M_STEPS.inc(k, path="multi")
        with _tm.span("train_step.dispatch", k=k):
            return fn(params, aux, opt_state, batches, rngs,
                      jnp.asarray(lrs, jnp.float32),
                      jnp.asarray(ts, jnp.float32))

    def __call__(self, params, aux, opt_state, batch, rng=None, lr=None, t=1):
        assert self._step is not None, "call compile() first"
        import jax.numpy as jnp

        # resolve 0-dims in creation-op shape attrs (rnn begin_state zeros
        # etc.) against the CURRENT input shapes, before jit traces. The
        # dispatch plan is keyed on the batch entries' (shape, dtype,
        # sharding) alone — param shapes are fixed per trainer — so the
        # steady state iterates 1-4 batch items instead of rebuilding and
        # sorting the full params+batch shape dict every step; a
        # batch-size change (Module.reshape, partial final batch) or a
        # re-placed input re-resolves once. Already-traced signatures
        # stay cached in jit.
        sig = tuple(
            (n, tuple(v.shape), str(v.dtype), getattr(v, "sharding", None))
            for n, v in batch.items())

        def _build():
            from ..executor import resolve_creation_shapes

            shapes = {n: tuple(v.shape) for n, v in params.items()}
            shapes.update({n: tuple(v.shape) for n, v in batch.items()})
            return resolve_creation_shapes(self.symbol, shapes)

        self.program.dispatch_plan(sig, _build)

        if lr is None:
            opt = self.optimizer
            if opt is not None and opt.lr_scheduler is not None:
                lr = float(opt.lr_scheduler(opt.num_update))
            else:
                lr = float(getattr(opt, "lr", 0.01))
        if rng is None:
            if self._needs_rng:
                from .. import random as _random

                rng = _random.next_key()
            else:
                rng = jnp.zeros((2,), jnp.uint32)  # unused placeholder
        _M_STEPS.inc(path="single")
        with _tm.span("train_step.dispatch", t=t):
            return self._step(
                params, aux, opt_state, batch, rng,
                jnp.asarray(lr, jnp.float32), jnp.asarray(t, jnp.float32),
            )
