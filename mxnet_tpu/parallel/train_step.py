"""Sharded training step: the fused TPU path for Module training.

This is the TPU-native replacement for the reference's §3.1 hot loop
(per-device executors + KVStore push/pull, python/mxnet/module/module.py:432-553
+ model.py:88-117): the ENTIRE step — forward, backward, gradient
allreduce, optimizer update — compiles to one XLA program over a Mesh:

- batch sharded over ``dp`` (DataParallelExecutorGroup.decide_slices →
  jax.sharding with PartitionSpec('dp'))
- params replicated over dp, optionally sharded over ``tp``
  (PlaceDevice/ctx_group → PartitionSpec)
- gradient sync = psum over ICI, inserted by GSPMD from the shardings
  (KVStore device/dist_device_sync → in-XLA allreduce; the reference's
  priority-ordered push overlap becomes XLA latency-hiding scheduling)
- optimizer state optionally sharded over dp (ZeRO-1 / "Automatic
  Cross-Replica Sharding of Weight Update", PAPERS.md)

The optimizer update is NOT re-implemented here: the step function
traces straight through ``Optimizer.update`` of ANY registered optimizer
(reference python/mxnet/optimizer.py surface) by wrapping the traced
jax values in NDArrays — the imperative op layer nests fine under jit.
Step-dependent quantities (learning rate after scheduling, update count
``t`` for Adam-style bias correction) enter the compiled program as
traced scalars so one compilation serves every step.
"""
from __future__ import annotations

import contextlib
import logging
import os

import numpy as np

from .. import telemetry as _tm

_M_STEPS = _tm.counter(
    "train_step.steps", "Optimizer steps dispatched through the fused "
    "ShardedTrainStep path")
_M_FLAT_BUCKETS = _tm.counter(
    "train_step.flat_buckets", "Flat update buckets planned by the "
    "sharded/bucketed fused-update path (one count per bucket per plan)")
_H_BUCKET_BYTES = _tm.histogram(
    "kvstore.bucket_bytes", "Payload bytes per coalesced gradient bucket "
    "(kvstore GradBucketer flushes and fused flat-update plan buckets)")

from ..base import bucket_bytes_env as _env_bucket_bytes  # noqa: E402


class _FlatBucket:
    """One size-capped flat slab of the parameter space: contiguous
    per-key views carved out of a single (padded) 1-D buffer, all
    sharing one (dtype, lr_mult, wd_mult) signature so a single set of
    fused-optimizer scalar kwargs is valid for the whole slab."""

    __slots__ = ("rep_index", "dtype", "views", "size", "padded")

    def __init__(self, rep_index, dtype, views, dp):
        self.rep_index = rep_index  # index whose _fused_kwargs apply
        self.dtype = dtype
        self.views = views  # [(index, name, offset, size, shape)]
        self.size = sum(v[3] for v in views)
        # pad so the slab splits evenly into dp contiguous shards
        self.padded = -(-self.size // dp) * dp


class _FlatUpdatePlan:
    """Bucketing layout for the flat fused update (tentpole part 2/3).

    Groups params by (dtype, lr_mult, wd_mult), walks each group in
    REVERSE key order (backward produces late keys' gradients first, so
    their buckets' collectives can fly while earlier layers are still
    differentiating), and packs size-capped buckets."""

    def __init__(self, param_names, shapes, dtypes, optimizer, dp,
                 bucket_bytes, comm_itemsize=None):
        groups = {}
        order = []
        for i, name in enumerate(param_names):
            key = (dtypes[name],
                   optimizer._mult_for(i, optimizer.lr_mult),
                   optimizer._mult_for(i, optimizer.wd_mult))
            if key not in groups:
                groups[key] = []
                order.append(key)
            groups[key].append((i, name))
        self.buckets = []
        for key in order:
            dtype = key[0]
            # the size cap counts bytes as they move on the WIRE: under
            # AMP the slab dtype is the fp32 master but gradients and the
            # gathered weight copy travel bf16, so the caller passes
            # comm_itemsize=2 and MXTPU_BUCKET_BYTES keeps meaning actual
            # collective payload bytes
            itemsize = comm_itemsize or np.dtype(dtype).itemsize
            cap = max(1, bucket_bytes // itemsize)
            pending = []
            pending_elems = 0
            for i, name in reversed(groups[key]):
                size = int(np.prod(shapes[name])) if shapes[name] else 1
                if pending and pending_elems + size > cap:
                    self._close(pending, dtype, dp)
                    pending, pending_elems = [], 0
                pending.append((i, name, size, shapes[name]))
                pending_elems += size
            if pending:
                self._close(pending, dtype, dp)
        self.by_name = {}
        for bi, b in enumerate(self.buckets):
            for (i, name, off, size, shape) in b.views:
                self.by_name[name] = (bi, off, size, shape)
        for b in self.buckets:
            _M_FLAT_BUCKETS.inc()
            _H_BUCKET_BYTES.observe(
                b.size * np.dtype(b.dtype).itemsize, path="flat_update")

    def _close(self, pending, dtype, dp):
        views = []
        off = 0
        for (i, name, size, shape) in pending:
            views.append((i, name, off, size, shape))
            off += size
        self.buckets.append(_FlatBucket(pending[0][0], dtype, views, dp))


class _EveryKeyCount(dict):
    """Stand-in for Optimizer._index_update_count during tracing: every
    parameter reads the SAME traced step counter ``t`` (the fused step
    updates all params exactly once per step, so the per-index counts
    the reference tracks are all equal to t here)."""

    def __init__(self, t):
        super().__init__()
        self._t = t

    def __getitem__(self, key):
        return self._t

    def __setitem__(self, key, value):
        pass

    def __contains__(self, key):
        return True


def _wrap_state(state, NDArray):
    if state is None:
        return None
    if isinstance(state, tuple):
        return tuple(_wrap_state(s, NDArray) for s in state)
    return NDArray(state)


def _unwrap_state(state):
    if state is None:
        return None
    if isinstance(state, tuple):
        return tuple(_unwrap_state(s) for s in state)
    return state._data


class ShardedTrainStep:
    """Compile a Symbol's full train step over a Mesh.

    Wraps the same _GraphProgram the Executor uses, but jits it with
    sharding constraints instead of per-device loops. Loss convention:
    sum of outputs drives the vjp (the *Output loss heads carry their own
    backward, like Executor.backward); the optimizer's rescale_grad
    normalizes by global batch exactly as the reference's updater does.
    """

    def __init__(self, symbol, mesh, optimizer=None, param_specs=None,
                 data_names=("data",), label_names=("softmax_label",),
                 dtype=None, zero1=False, flat_update=None):
        from jax.sharding import PartitionSpec as P

        from ..executor import _GraphProgram

        self.symbol = symbol
        self.mesh = mesh
        self.optimizer = optimizer
        self.program = _GraphProgram(symbol)
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.data_names = list(data_names)
        self.label_names = list(label_names)
        self.param_names = [
            n for n in self.arg_names
            if n not in self.data_names + self.label_names
        ]
        # ZeRO-1: shard otherwise-replicated optimizer state over dp when
        # the leading dim divides evenly (opt-in: changes layout only, not
        # numerics — each dp rank updates its state shard then the
        # all-gather is implicit in the next step's reads).
        self.zero1 = zero1
        # parameter shardings: default replicated; caller may pass
        # name -> PartitionSpec (tp-sharded layers)
        self.param_specs = dict(param_specs or {})
        self._batch_spec = P("dp")
        self._step = None
        self._step_multi = {}  # K -> jitted K-step scan program
        self._needs_rng = any(
            (not n.is_variable) and n.op.needs_rng
            for n in self.program.nodes
        )
        # -- flat bucketed/sharded update (arXiv:2004.13336) ------------
        # flat_mode: None = legacy per-param update;
        # "shard" = each dp replica updates its contiguous 1/N shard of
        #   the flat param+state space inside shard_map, state is
        #   materialized sharded (1/N per device), updated weights are
        #   all-gathered in-step;
        # "replicated" = identical flat layout and identical shard-width
        #   update body, but run on every replica via a scan over the dp
        #   chunks with full-size state — the bitwise-matched baseline
        #   the sharded mode is tested against (same chunk width ⇒ same
        #   XLA elementwise codegen; full-width codegen may contract
        #   mul+add into FMA differently, which is why the baseline is
        #   chunk-matched rather than the monolithic legacy update).
        self.flat_bucket_bytes = _env_bucket_bytes()
        dp = mesh.shape.get("dp", 1)
        non_dp = 1
        for ax, n in mesh.shape.items():
            if ax != "dp":
                non_dp *= n
        eligible = (
            optimizer is not None
            and getattr(optimizer, "elementwise_update", False)
            and dp > 1
            and non_dp == 1
            and not self.param_specs
            and not zero1  # explicit ZeRO-1 request → legacy layout
            and self.flat_bucket_bytes > 0
        )
        if flat_update is False or not eligible:
            self.flat_mode = None
        else:
            self.flat_mode = (
                "shard"
                if os.environ.get("MXTPU_SHARD_UPDATE", "1") != "0"
                else "replicated")
            logging.getLogger(__name__).info(
                "fused update path: flat bucketed (%s, dp=%d, "
                "MXTPU_BUCKET_BYTES=%d)", self.flat_mode, dp,
                self.flat_bucket_bytes)
        self._flat_plan = None  # built lazily from placed param shapes
        # -- bf16 AMP (ISSUE 8 tentpole) --------------------------------
        # forward/backward in bf16, fp32 master weights living as flat
        # slabs in opt_state, bf16 gradient + weight collectives, dynamic
        # loss scaling. Rides the flat update exclusively: the masters
        # ARE the flat slabs, so AMP without the flat path has nowhere to
        # keep fp32 truth.
        amp_req = os.environ.get("MXTPU_AMP", "").lower()
        self.amp = False
        if amp_req in ("bf16", "bfloat16"):
            if self.flat_mode is not None:
                self.amp = True
                logging.getLogger(__name__).info(
                    "AMP: bf16 compute + fp32 master slabs (%s mode)",
                    self.flat_mode)
            else:
                logging.getLogger(__name__).warning(
                    "MXTPU_AMP=bf16 ignored: requires the flat fused-"
                    "update path (elementwise optimizer, dp>1, "
                    "MXTPU_BUCKET_BYTES>0, no tp/zero1)")
        elif amp_req not in ("", "0", "off", "none", "fp32", "f32",
                             "float32"):
            logging.getLogger(__name__).warning(
                "MXTPU_AMP=%s not understood (only bf16); running fp32",
                amp_req)
        self.amp_cast_data = os.environ.get(
            "MXTPU_AMP_CAST_DATA", "1") != "0"
        self.amp_scale_init = float(
            os.environ.get("MXTPU_LOSS_SCALE", str(2.0 ** 15)))
        self.amp_scale_window = int(
            os.environ.get("MXTPU_LOSS_SCALE_WINDOW", "2000"))
        self.amp_scale_max = 2.0 ** 24
        # -- training guardrails (resilience/guardrail.py) --------------
        # guard=True makes the step (a) emit a (loss, grad_norm²,
        # gate_ok) diag output head and (b) apply the AMP-style
        # branchless select — generalized to fp32 — so a non-finite or
        # out-of-threshold gradient updates NOTHING, bitwise.
        # guard_threshold is the host-side grad-norm² bound the
        # GuardrailMonitor refreshes at group boundaries; it rides into
        # the compiled program as a traced scalar (no recompiles), inf
        # means "gate on non-finite only" (detector warmup). fit() arms
        # this AFTER construction (guardrails="auto"), re-jitting the
        # already-lazy step wrappers.
        self.guard = False
        self.guard_threshold = float("inf")

    # ------------------------------------------------------------------
    def _spec_for(self, name):
        from jax.sharding import PartitionSpec as P

        return self.param_specs.get(name, P())

    def _sharding_for(self, name):
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, self._spec_for(name))

    def _state_sharding_for(self, name, arr):
        """Opt-state sharding: param's spec, or dp-sharded under ZeRO-1."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = self._spec_for(name)
        if (self.zero1 and spec == P() and arr.ndim >= 1
                and arr.shape[0] % self.mesh.shape["dp"] == 0):
            spec = P("dp")
        return NamedSharding(self.mesh, spec)

    # -- flat bucketed/sharded update layer -----------------------------
    @staticmethod
    def _flat_key(bucket_index):
        """Opt-state dict key of one flat bucket's state slab (the dict
        otherwise maps param name -> state; flat slabs span params)."""
        return "__flat__%d" % bucket_index

    # AMP additions to the opt_state dict: fp32 master weight slab per
    # bucket (same layout/sharding as the state slabs) plus two
    # replicated device scalars — the live loss scale and the count of
    # consecutive finite steps. Living in opt_state means they ride the
    # K-step scan carry, buffer donation, and checkpointing for free.
    AMP_SCALE_KEY = "__amp_scale__"
    AMP_GOOD_KEY = "__amp_good__"

    @staticmethod
    def _master_key(bucket_index):
        return "__master__%d" % bucket_index

    def amp_cast_params(self, params):
        """bf16 working copies of fp32 params (the arrays the forward/
        backward consumes under AMP); non-f32 entries pass through."""
        import jax
        import jax.numpy as jnp

        if not self.amp:
            return params
        out = {}
        for n, p in params.items():
            if p.dtype == jnp.float32:
                out[n] = jax.device_put(
                    jnp.asarray(p, jnp.bfloat16), self._sharding_for(n))
            else:
                out[n] = p
        return out

    def build_amp_master_state(self, params_by_name, scale=None,
                               good=0.0):
        """Pack full-shape fp32 params into master slabs + the scale
        scalars. `params_by_name` must be fp32 truth (host or device);
        `scale`/`good` seed the loss scaler (fresh init by default)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        plan = self._flat_plan
        assert plan is not None, "flat plan not built yet"
        sharding = self._flat_state_sharding()
        state = {}
        for bi, b in enumerate(plan.buckets):
            parts = [np.asarray(params_by_name[name],
                                np.float32).reshape(-1)
                     for (_i, name, _o, _s, _sh) in b.views]
            pad = b.padded - b.size
            if pad:
                parts.append(np.zeros((pad,), np.float32))
            state[self._master_key(bi)] = jax.device_put(
                np.concatenate(parts), sharding)
        rep = NamedSharding(self.mesh, P())
        state[self.AMP_SCALE_KEY] = jax.device_put(
            np.asarray(self.amp_scale_init if scale is None else scale,
                       np.float32), rep)
        state[self.AMP_GOOD_KEY] = jax.device_put(
            np.asarray(good, np.float32), rep)
        return state

    def master_params_named(self, opt_state):
        """fp32 master weights carved back to per-param shapes (lazy
        device slices — the fp32 truth for metrics/checkpoints)."""
        plan = self._flat_plan
        assert plan is not None, "flat plan not built yet"
        out = {}
        for bi, b in enumerate(plan.buckets):
            m = opt_state[self._master_key(bi)]
            for (_i, name, off, size, shape) in b.views:
                out[name] = m[off:off + size].reshape(shape)
        return out

    def master_params_placed(self, opt_state):
        """Masters as full fp32 params at their param shardings — what a
        demoted (non-flat, non-AMP) run continues from."""
        import jax

        named = self.master_params_named(opt_state)
        return {n: jax.device_put(np.asarray(v, np.float32),
                                  self._sharding_for(n))
                for n, v in named.items()}

    def amp_state_blob(self, opt_state):
        """Host snapshot of the scaler scalars for checkpoints."""
        return {
            "scale": float(np.asarray(opt_state[self.AMP_SCALE_KEY])),
            "good": float(np.asarray(opt_state[self.AMP_GOOD_KEY])),
        }

    def _ensure_flat_plan(self, params):
        if self._flat_plan is None:
            shapes = {n: tuple(params[n].shape) for n in self.param_names}
            dtypes = {n: str(params[n].dtype) for n in self.param_names}
            comm_itemsize = None
            if self.amp:
                # the plan describes the fp32 MASTER slabs regardless of
                # whether it is built from fp32 params (make_state) or
                # their bf16 working copies (step trace) — same layout
                # either way; the cap counts bf16 wire bytes
                dtypes = {n: ("float32" if d == "bfloat16" else d)
                          for n, d in dtypes.items()}
                comm_itemsize = 2
            self._flat_plan = _FlatUpdatePlan(
                self.param_names, shapes, dtypes, self.optimizer,
                self.mesh.shape["dp"], self.flat_bucket_bytes,
                comm_itemsize=comm_itemsize)
        return self._flat_plan

    def _flat_state_sharding(self):
        """State-slab sharding: each dp replica materializes only its
        contiguous 1/N shard in "shard" mode; the "replicated" baseline
        keeps full slabs everywhere (that redundancy is what the sharded
        mode removes)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        spec = P("dp") if self.flat_mode == "shard" else P()
        return NamedSharding(self.mesh, spec)

    def flat_state_to_named(self, opt_state):
        """Carve the flat state slabs back into the per-param nested
        trees the legacy layout uses (lazy device-side slices; callers
        numpy-ify off-thread). Checkpoints and save_optimizer_states
        always store THIS layout, so snapshots are layout-independent:
        a run with sharding on resumes with it off and vice versa."""
        plan = self._flat_plan
        assert plan is not None, "flat plan not built yet"

        def _slice(st, off, size, shape):
            if st is None:
                return None
            if isinstance(st, tuple):
                return tuple(_slice(s, off, size, shape) for s in st)
            return st[off:off + size].reshape(shape)

        named = {}
        for bi, b in enumerate(plan.buckets):
            st = opt_state.get(self._flat_key(bi))
            for (_i, name, off, size, shape) in b.views:
                named[name] = _slice(st, off, size, shape)
        return named

    def named_state_to_flat(self, named):
        """Inverse of flat_state_to_named: pack per-param (host) state
        trees into device-placed flat slabs, zero-padding each slab to a
        dp multiple (pad lanes stay exactly zero under every
        elementwise_update optimizer, so they never leak into views)."""
        import jax

        plan = self._flat_plan
        assert plan is not None, "flat plan not built yet"
        sharding = self._flat_state_sharding()

        def _pack(parts, pad, dtype):
            if all(p is None for p in parts):
                return None
            if isinstance(parts[0], tuple):
                return tuple(
                    _pack([p[j] for p in parts], pad, dtype)
                    for j in range(len(parts[0])))
            flats = [np.asarray(p).reshape(-1) for p in parts]
            leaf_dtype = flats[0].dtype
            if pad:
                flats.append(np.zeros((pad,), leaf_dtype))
            return jax.device_put(np.concatenate(flats), sharding)

        state = {}
        for bi, b in enumerate(plan.buckets):
            try:
                parts = [named[name] for (_i, name, _o, _s, _sh) in b.views]
            except KeyError as exc:
                raise KeyError(
                    "optimizer state for param %s missing from the named "
                    "snapshot — the checkpoint does not match this "
                    "symbol's parameter set" % (exc,))
            state[self._flat_key(bi)] = _pack(
                parts, b.padded - b.size, b.dtype)
        return state

    def opt_state_shard_info(self, opt_state):
        """(total_elements, resident_elements) across the optimizer
        state tree, where *resident* counts what THIS process's first
        addressable device actually materializes. The 1/N-memory claim
        of the sharded update is exactly ``resident ≈ total / dp`` —
        tests at each elastic world size assert on this surface instead
        of groping at device allocator stats."""
        total = 0
        resident = 0

        def _walk(leaf):
            nonlocal total, resident
            if leaf is None:
                return
            if isinstance(leaf, tuple):
                for part in leaf:
                    _walk(part)
                return
            total += int(leaf.size)
            shards = getattr(leaf, "addressable_shards", None)
            if shards:
                resident += int(shards[0].data.size)
            else:
                resident += int(leaf.size)

        for leaf in (opt_state or {}).values():
            _walk(leaf)
        return total, resident

    def disable_flat_update(self, opt_state):
        """Demote to the legacy per-param update (borrow_optimizer /
        BucketingModule: borrowers share a param-name SUBSET, which the
        flat slabs cannot express). Converts the flat state back to
        per-name placement and invalidates compiled steps; returns the
        converted opt_state dict."""
        if self.flat_mode is None:
            return opt_state
        import jax

        named = self.flat_state_to_named(opt_state)

        def _place(name, s):
            if s is None:
                return None
            if isinstance(s, tuple):
                return tuple(_place(name, x) for x in s)
            host = np.asarray(s)
            return jax.device_put(host,
                                  self._state_sharding_for(name, host))

        placed = {n: _place(n, s) for n, s in named.items()}
        self.flat_mode = None
        # AMP cannot outlive the flat path (the masters ARE the slabs);
        # callers reconstitute fp32 params via master_params_placed()
        # BEFORE this conversion drops the master/scale keys
        self.amp = False
        self._step = None
        self._step_multi = {}
        return placed

    def batch_sharding(self):
        from jax.sharding import NamedSharding

        return NamedSharding(self.mesh, self._batch_spec)

    def batch_sharding_stacked(self):
        """Sharding for a (K, batch, ...) stack of K step batches: the
        scan axis is unsharded, rows shard over dp like batch_sharding."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        return NamedSharding(
            self.mesh, P(*((None,) + tuple(self._batch_spec))))

    # ------------------------------------------------------------------
    def place_params(self, arg_arrays_by_name, aux_arrays_by_name):
        """device_put host/NDArray values onto the mesh by spec.

        Accepts numpy arrays or NDArrays; returns dict of jax.Arrays."""
        import jax

        def _np(v):
            return v.asnumpy() if hasattr(v, "asnumpy") else np.asarray(v)

        params = {
            n: jax.device_put(_np(arg_arrays_by_name[n]), self._sharding_for(n))
            for n in self.param_names
        }
        aux = {
            n: jax.device_put(_np(aux_arrays_by_name[n]), self._sharding_for(n))
            for n in self.aux_names
        }
        return params, aux

    def make_state(self, params):
        """Build optimizer state via the optimizer's OWN create_state on
        host zeros, then place it on the mesh (ZeRO-1 aware)."""
        import jax

        from .. import ndarray as ndmod

        if self.optimizer is None:
            return {}
        if self.flat_mode is not None:
            plan = self._ensure_flat_plan(params)
            sharding = self._flat_state_sharding()
            state = {}
            for bi, b in enumerate(plan.buckets):
                st = self.optimizer.create_state_flat(
                    b.rep_index, b.padded, dtype=b.dtype)

                def _place_flat(s):
                    if s is None:
                        return None
                    if isinstance(s, tuple):
                        return tuple(_place_flat(x) for x in s)
                    return jax.device_put(s.asnumpy(), sharding)

                placed = _place_flat(st)
                if placed is not None:
                    state[self._flat_key(bi)] = placed
            if self.amp:
                # params here must be fp32 truth (callers pass the placed
                # fp32 params BEFORE amp_cast_params) — they become the
                # master slabs
                state.update(self.build_amp_master_state(params))
            return state
        state = {}
        for i, name in enumerate(self.param_names):
            p = params[name]
            host_w = ndmod.zeros(p.shape)
            st = self.optimizer.create_state(i, host_w)

            def _place(s):
                if s is None:
                    return None
                if isinstance(s, tuple):
                    return tuple(_place(x) for x in s)
                return jax.device_put(
                    s.asnumpy(), self._state_sharding_for(name, s)
                )

            state[name] = _place(st)
        return state

    def init(self, arg_shapes_by_name, initializer, seed=0):
        """Allocate + initialize sharded params/aux/opt-state on the mesh."""
        host_params = {}
        for name in self.param_names:
            shape = arg_shapes_by_name[name]
            host = np.zeros(shape, np.float32)

            class _Arr:
                def __init__(self, a):
                    self._a = a
                    self.shape = a.shape
                    self.size = a.size
                    self.dtype = a.dtype

                def __setitem__(self, k, v):
                    self._a[k] = v

                def asnumpy(self):
                    return self._a

            wrapper = _Arr(host)
            initializer(name, wrapper)
            host_params[name] = host
        _, _, aux_shapes = self.symbol.infer_shape(**arg_shapes_by_name)
        host_aux = {}
        for name, shape in zip(self.aux_names, aux_shapes):
            host_aux[name] = (
                np.ones(shape, np.float32)
                if name.endswith("var")
                else np.zeros(shape, np.float32)
            )
        params, aux = self.place_params(host_params, host_aux)
        opt_state = self.make_state(params)
        if self.amp:
            params = self.amp_cast_params(params)
        return params, aux, opt_state

    # ------------------------------------------------------------------
    @contextlib.contextmanager
    def _patched_optimizer(self, lr, t):
        """Patch the optimizer's step-dependent attributes with traced
        stand-ins for the duration of a trace (only runs at trace time),
        so the SAME compiled program is valid for every step: lr comes
        from the host scheduler each call, t drives Adam-style bias
        correction in-graph."""
        opt = self.optimizer
        saved_lr = opt.lr
        saved_sched = opt.lr_scheduler
        saved_counts = opt._index_update_count
        saved_num_update = opt.num_update
        opt.lr = lr
        opt.lr_scheduler = None  # host computes the scheduled lr
        opt._index_update_count = _EveryKeyCount(t)
        opt._update_count = lambda index: None  # instance shadow
        try:
            yield opt
        finally:
            del opt.__dict__["_update_count"]
            opt.lr = saved_lr
            opt.lr_scheduler = saved_sched
            opt._index_update_count = saved_counts
            opt.num_update = saved_num_update

    def _apply_optimizer(self, params, grads, opt_state, lr, t):
        """Trace through Optimizer.update for every param (legacy
        per-key layout; see _apply_optimizer_flat for the bucketed
        path)."""
        from ..ndarray import NDArray

        opt = self.optimizer
        new_params, new_state = {}, {}
        if opt is None:
            for name in self.param_names:
                new_params[name] = params[name] - lr * grads[name]
            return new_params, new_state

        with self._patched_optimizer(lr, t):
            for i, name in enumerate(self.param_names):
                w = NDArray(params[name])
                g = NDArray(grads[name])
                st = _wrap_state(opt_state.get(name), NDArray)
                opt.update(i, w, g, st)
                new_params[name] = w._data
                if st is not None:
                    new_state[name] = _unwrap_state(st)
            # params/state owned by a sharing module (BucketingModule:
            # the owner dict may cover a superset of this symbol's args)
            # pass through untouched
            for name in params:
                if name not in new_params:
                    new_params[name] = params[name]
            for name in opt_state:
                if name not in new_state:
                    new_state[name] = opt_state[name]
        return new_params, new_state

    def _flat_body(self, bucket, w_c, g_c, st_c, lr, t):
        """One optimizer step on a width-S chunk of a flat bucket.

        Shared verbatim by BOTH flat modes: in "shard" mode it is the
        shard_map per-device body (S = padded/dp); in "replicated" mode
        the lax.scan body walks the same dp chunks of width S. Chunk
        widths matching is what makes the two modes bitwise-equal — XLA
        contracts mul+add into FMA per fusion width, so a full-width
        replicated update would round differently than the sharded one.
        The optimizer attrs are re-pointed at THIS scope's tracers so
        shard_map never closes over outer-scope values."""
        from ..ndarray import NDArray

        opt = self.optimizer
        opt.lr = lr
        opt._index_update_count = _EveryKeyCount(t)
        w = NDArray(w_c)
        g = NDArray(g_c)
        st = _wrap_state(st_c, NDArray)
        opt.update(bucket.rep_index, w, g, st)
        return w._data, _unwrap_state(st) if st is not None else None

    def _flat_body_amp(self, bucket, m_c, g_c, st_c, lr, t, inv_scale,
                       finite):
        """One AMP optimizer step on a width-S chunk: bf16 grad in, fp32
        master + state updated, bf16 weight copy out; non-finite steps
        pass old values through bitwise (branchless select).

        Optimizers that declare a `fused_slab_kernel` run the Pallas
        kernel (ops/pallas_kernels.fused_slab_update) when
        MXTPU_FUSED_UPDATE_KERNEL allows — one VMEM pass for the whole
        unscale/update/cast chain — or its shared-math jnp reference
        otherwise (same `_slab_update_math`, so toggling the kernel
        changes codegen, not formulas). Other elementwise optimizers
        trace through their own Optimizer.update on the unscaled fp32
        gradient exactly like `_flat_body`."""
        import jax
        import jax.numpy as jnp

        from ..ndarray import NDArray
        from ..ops import pallas_kernels as pk

        opt = self.optimizer
        opt.lr = lr
        opt._index_update_count = _EveryKeyCount(t)
        kind = getattr(opt, "fused_slab_kernel", None)
        if kind == "sgd" and getattr(opt, "momentum", 0.0):
            kind = "sgd_mom"
        if kind is not None:
            kwargs = opt._fused_kwargs(bucket.rep_index)
            lr_eff = kwargs["lr"]
            if kind == "adam":
                tt = opt._index_update_count[bucket.rep_index]
                lr_eff = lr_eff * (
                    (1.0 - opt.beta2 ** tt) ** 0.5
                    / (1.0 - opt.beta1 ** tt))
            states = ()
            if st_c is not None:
                states = st_c if isinstance(st_c, tuple) else (st_c,)
            fn = (pk.fused_slab_update if pk.fused_update_enabled()
                  else pk.slab_update_reference)
            nm, nst, w16 = fn(
                kind, m_c, g_c, states, lr_eff, inv_scale, finite,
                wd=kwargs["wd"], rescale_grad=kwargs["rescale_grad"],
                clip_gradient=kwargs["clip_gradient"],
                momentum=getattr(opt, "momentum", 0.0),
                beta1=getattr(opt, "beta1", 0.9),
                beta2=getattr(opt, "beta2", 0.999),
                epsilon=getattr(opt, "epsilon", 1e-8))
            if st_c is None:
                new_st = None
            elif isinstance(st_c, tuple):
                new_st = tuple(nst)
            else:
                new_st = nst[0]
            return nm, new_st, w16
        # generic elementwise optimizer: unscale to fp32, trace through
        # its own update, select, cast
        g32 = g_c.astype(jnp.float32) * inv_scale
        w = NDArray(m_c)
        g = NDArray(g32)
        st = _wrap_state(st_c, NDArray)
        opt.update(bucket.rep_index, w, g, st)
        keep = finite > jnp.float32(0.5)
        nm = jnp.where(keep, w._data, m_c)
        nst_raw = _unwrap_state(st) if st is not None else None
        new_st = jax.tree_util.tree_map(
            lambda new, old: jnp.where(keep, new, old), nst_raw, st_c)
        return nm, new_st, nm.astype(jnp.bfloat16)

    def _apply_optimizer_flat_amp(self, params, grads, opt_state, lr, t):
        """The AMP twin of _apply_optimizer_flat. Differences:

        - no weight concat: the fp32 masters already live as flat slabs
          in opt_state, so only gradients get flattened per bucket
        - one global finite flag over every flat grad slab gates ALL
          buckets identically (a half-applied step could never be
          resumed consistently)
        - in "shard" mode the all-gather moves the bf16 weight copy —
          half the weight-collective bytes of the fp32 path
        - the loss scaler (scale, good-step count) updates in-graph:
          ×2 after `amp_scale_window` consecutive finite steps, ×0.5
          (floor 1.0) on any non-finite step, which also skips the
          update bitwise-cleanly via the finite select."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        plan = self._ensure_flat_plan(params)
        dp = self.mesh.shape["dp"]
        scale = opt_state[self.AMP_SCALE_KEY]
        good = opt_state[self.AMP_GOOD_KEY]
        new_params, new_state = {}, {}
        # pass 1: flatten grads (bf16) per bucket + global finite flag
        flat_gs = []
        finite = jnp.asarray(True)
        for b in plan.buckets:
            pad = b.padded - b.size
            g_parts = [grads[name].reshape(-1)
                       for (_i, name, _o, _s, _sh) in b.views]
            if pad:
                g_parts.append(jnp.zeros((pad,), g_parts[0].dtype))
            flat_g = jnp.concatenate(g_parts)
            # same hard fusion boundary as the fp32 path: the update
            # consumes materialized slabs, not fused gradient chains
            flat_g = jax.lax.optimization_barrier(flat_g)
            # pin the grad slab replicated: without this the partitioner
            # rebuilds each bucket's concat from partial per-tensor sums
            # with a SECOND full-slab all-reduce (observed on the CPU
            # partitioner at multi-bucket sizes). The fp32 path cannot
            # pin its grad concat (bitwise shard<->replicated parity
            # constraints, see _apply_optimizer_flat); the AMP path has
            # no such cross-mode bitwise contract.
            flat_g = jax.lax.with_sharding_constraint(
                flat_g, NamedSharding(self.mesh, P()))
            finite = jnp.logical_and(
                finite, jnp.all(jnp.isfinite(flat_g)))
            flat_gs.append(flat_g)
        finite_f = finite.astype(jnp.float32)
        inv_scale = jnp.float32(1.0) / scale
        with self._patched_optimizer(lr, t):
            for bi, b in enumerate(plan.buckets):
                flat_g = flat_gs[bi]
                master = opt_state[self._master_key(bi)]
                st = opt_state.get(self._flat_key(bi))

                if self.flat_mode == "shard":
                    from jax.experimental.shard_map import shard_map

                    def body(m_c, g_c, st_c, lr_c, t_c, inv_c, fin_c,
                             _b=b):
                        nm, nst, w16 = self._flat_body_amp(
                            _b, m_c, g_c, st_c, lr_c, t_c, inv_c, fin_c)
                        # the bf16 copy rejoins the replicated dispatch
                        # plan; master + state stay on their shard
                        w16_full = jax.lax.all_gather(
                            w16, "dp", tiled=True)
                        return w16_full, nm, nst

                    w16_full, nmaster, nst = shard_map(
                        body, mesh=self.mesh,
                        in_specs=(P("dp"), P("dp"), P("dp"), P(), P(),
                                  P(), P()),
                        out_specs=(P(), P("dp"), P("dp")),
                        check_rep=False,
                    )(master, flat_g, st, lr, t, inv_scale, finite_f)
                else:
                    S = b.padded // dp

                    def scan_body(carry, xs, _b=b):
                        m_c, g_c, st_c = xs
                        return carry, self._flat_body_amp(
                            _b, m_c, g_c, st_c, lr, t, inv_scale,
                            finite_f)

                    m2 = master.reshape(dp, S)
                    g2 = flat_g.reshape(dp, S)
                    st2 = jax.tree_util.tree_map(
                        lambda a: a.reshape(dp, S), st)
                    _, (nm2, nst2, w16_2) = jax.lax.scan(
                        scan_body, 0, (m2, g2, st2))
                    nmaster = nm2.reshape(b.padded)
                    nst = jax.tree_util.tree_map(
                        lambda a: a.reshape(b.padded), nst2)
                    w16_full = w16_2.reshape(b.padded)

                for (_i, name, off, size, shape) in b.views:
                    new_params[name] = (
                        w16_full[off:off + size].reshape(shape))
                new_state[self._master_key(bi)] = nmaster
                if nst is not None:
                    new_state[self._flat_key(bi)] = nst
        # dynamic loss scaler (grow/backoff), branchless
        window = jnp.float32(self.amp_scale_window)
        grown = (good + 1.0) >= window
        new_state[self.AMP_SCALE_KEY] = jnp.where(
            finite,
            jnp.where(grown,
                      jnp.minimum(scale * 2.0,
                                  jnp.float32(self.amp_scale_max)),
                      scale),
            jnp.maximum(scale * 0.5, jnp.float32(1.0)))
        new_state[self.AMP_GOOD_KEY] = jnp.where(
            finite,
            jnp.where(grown, jnp.float32(0.0), good + 1.0),
            jnp.float32(0.0))
        for name in params:
            if name not in new_params:
                new_params[name] = params[name]
        for k in opt_state:
            if k not in new_state:
                new_state[k] = opt_state[k]
        return new_params, new_state

    def _apply_optimizer_flat(self, params, grads, opt_state, lr, t):
        """Bucketed flat update: concat params/grads per bucket, run the
        optimizer on dp-wide chunks, carve per-key views back out.

        "shard" mode (MXTPU_SHARD_UPDATE=1, the default): the update
        runs inside shard_map — each replica updates only its contiguous
        1/N shard of the flat space against its reduce-scattered slice
        of the (GSPMD-allreduced) gradient, state stays sharded P("dp"),
        and updated weights are all-gathered back to replicated. The
        arXiv:2004.13336 recipe: O(params/N) update flops + state bytes.

        "replicated" mode: identical math via lax.scan over the same dp
        chunks on every replica — the bitwise parity baseline."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        opt = self.optimizer
        if opt is None or self.flat_mode is None:
            return self._apply_optimizer(params, grads, opt_state, lr, t)

        plan = self._ensure_flat_plan(params)
        dp = self.mesh.shape["dp"]
        new_params, new_state = {}, {}
        with self._patched_optimizer(lr, t):
            for bi, b in enumerate(plan.buckets):
                pad = b.padded - b.size
                w_parts = [params[name].reshape(-1)
                           for (_i, name, _o, _s, _sh) in b.views]
                g_parts = [grads[name].reshape(-1)
                           for (_i, name, _o, _s, _sh) in b.views]
                if pad:
                    zpad = jnp.zeros((pad,), w_parts[0].dtype)
                    w_parts.append(zpad)
                    g_parts.append(zpad)
                flat_w = jnp.concatenate(w_parts)
                flat_g = jnp.concatenate(g_parts)
                # hard fusion boundary: materialize the flat buffers in
                # BOTH modes so XLA cannot FMA-contract the gradient
                # chain into the update kernel differently per mode —
                # bitwise parity depends on both modes consuming the
                # same materialized values at the same chunk width
                flat_w, flat_g = jax.lax.optimization_barrier(
                    (flat_w, flat_g))
                # keep the weight concat replicated too: otherwise GSPMD
                # builds the flat buffer sharded and re-assembles it
                # with an extra full-size all-reduce (CPU partitioner).
                # The GRADIENT concat is left alone — constraining it
                # perturbs sharding propagation through the backward
                # graph enough to change reduction orders, which breaks
                # the bitwise shard↔replicated parity.
                rep = NamedSharding(self.mesh, P())
                flat_w = jax.lax.with_sharding_constraint(flat_w, rep)
                st = opt_state.get(self._flat_key(bi))

                if self.flat_mode == "shard":
                    from jax.experimental.shard_map import shard_map

                    def body(w_c, g_c, st_c, lr_c, t_c, _b=b):
                        nw, nst = self._flat_body(_b, w_c, g_c, st_c,
                                                  lr_c, t_c)
                        # weights rejoin the replicated dispatch plan;
                        # state stays resident on its owning shard
                        nw_full = jax.lax.all_gather(
                            nw, "dp", tiled=True)
                        return nw_full, nst

                    flat_nw, nst = shard_map(
                        body, mesh=self.mesh,
                        in_specs=(P("dp"), P("dp"), P("dp"), P(), P()),
                        out_specs=(P(), P("dp")),
                        check_rep=False,
                    )(flat_w, flat_g, st, lr, t)
                else:
                    S = b.padded // dp

                    def scan_body(carry, xs, _b=b):
                        w_c, g_c, st_c = xs
                        return carry, self._flat_body(_b, w_c, g_c,
                                                      st_c, lr, t)

                    w2 = flat_w.reshape(dp, S)
                    g2 = flat_g.reshape(dp, S)
                    st2 = jax.tree_util.tree_map(
                        lambda a: a.reshape(dp, S), st)
                    _, (nw2, nst2) = jax.lax.scan(
                        scan_body, 0, (w2, g2, st2))
                    flat_nw = nw2.reshape(b.padded)
                    nst = jax.tree_util.tree_map(
                        lambda a: a.reshape(b.padded), nst2)

                for (_i, name, off, size, shape) in b.views:
                    new_params[name] = (
                        flat_nw[off:off + size].reshape(shape))
                if nst is not None:
                    new_state[self._flat_key(bi)] = nst
        for name in params:
            if name not in new_params:
                new_params[name] = params[name]
        for k in opt_state:
            if k not in new_state:
                new_state[k] = opt_state[k]
        return new_params, new_state

    def _make_step_fn(self):
        """The single-step fwd+bwd+psum+optimizer body (pure; shared by
        the per-step jit and the K-step lax.scan program)."""
        import jax
        import jax.numpy as jnp

        from ..executor import _mirror_enabled, _mirror_policy

        program = self.program
        do_mirror = _mirror_enabled()
        amp = self.amp
        guard = self.guard
        amp_cast = set(self.data_names) if (amp and self.amp_cast_data) \
            else set()

        def step(params, aux, opt_state, batch, rng, lr, t, gthr):
            if amp_cast:
                # bf16 activations from the first op: cast floating DATA
                # feeds (never labels — loss heads compare against them
                # exactly). MXTPU_AMP_CAST_DATA=0 keeps feeds untouched.
                batch = {
                    n: (v.astype(jnp.bfloat16)
                        if (n in amp_cast
                            and jnp.issubdtype(v.dtype, jnp.floating))
                        else v)
                    for n, v in batch.items()}

            def loss_fn(ps):
                args = dict(ps)
                args.update(batch)
                outs, new_aux = program(args, aux, rng, True)
                # *Output heads: drive vjp with ones (Executor.backward
                # convention — the loss op bakes its own gradient)
                loss = sum(jnp.sum(o.astype(jnp.float32) if amp else o)
                           for o in outs)
                return loss, (outs, new_aux)

            if do_mirror:
                # MXNET_BACKWARD_DO_MIRROR: rematerialize cheap ops in
                # backward, keep dot/conv residuals (executor._mirror_policy)
                loss_fn = jax.checkpoint(loss_fn, policy=_mirror_policy)

            if guard:
                # value_and_grad instead of grad: the diag head needs
                # the loss VALUE; the gradient computation is identical.
                (loss_val, (outs, new_aux)), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params)
            else:
                grads, (outs, new_aux) = jax.grad(
                    loss_fn, has_aux=True)(params)
            if amp:
                # Loss scaling rides the GRADIENT stream, not the loss
                # value: every loss head here ignores its incoming
                # cotangent by design (softmax_output-inl.h Backward —
                # ops/nn.py), so scaling the summed loss would never
                # reach the gradients. Multiplying the post-chain grads
                # by the scale is equivalent (bf16 carries fp32's full
                # exponent range, so the chain itself cannot overflow at
                # any representable scale) and exact for the
                # power-of-two scales the scaler produces.
                scale = opt_state[self.AMP_SCALE_KEY]
                grads = {k: g * scale.astype(g.dtype)
                         for k, g in grads.items()}
                outs = [o.astype(jnp.float32) for o in outs]
            # gradient allreduce over dp happens implicitly: params are
            # replicated, batch is dp-sharded → GSPMD inserts psum here.
            # (In flat "shard" mode the P("dp") in_specs then slice that
            # allreduced gradient per replica — allreduce+slice is XLA's
            # canonical reduce-scatter decomposition, which its collective
            # combiner re-forms into reduce-scatter on TPU.)
            if self.flat_mode is not None:
                # pin grads replicated at the source, IDENTICALLY in both
                # flat modes: without this GSPMD shards the downstream
                # flat concat and re-assembles it with an extra full-size
                # all-reduce per flat buffer (CPU partitioner), and any
                # mode-asymmetric resharding of the backward graph would
                # break the bitwise shard↔replicated parity
                from jax.sharding import NamedSharding, PartitionSpec as P

                rep = NamedSharding(self.mesh, P())
                grads = {k: jax.lax.with_sharding_constraint(g, rep)
                         for k, g in grads.items()}
            if amp:
                apply = self._apply_optimizer_flat_amp
            elif self.flat_mode is not None:
                apply = self._apply_optimizer_flat
            else:
                apply = self._apply_optimizer
            new_params, new_opt = apply(params, grads, opt_state, lr, t)
            new_aux = {**aux, **new_aux}  # carry shared-owner extras through
            if amp:
                # aux state (BN moving stats) keeps its fp32 dtype across
                # steps even when bf16 activations produced the batch
                # statistics this step folded in
                new_aux = {
                    k: (v.astype(aux[k].dtype)
                        if (k in aux and hasattr(v, "dtype")
                            and v.dtype != aux[k].dtype) else v)
                    for k, v in new_aux.items()}
            if guard:
                # Global grad-norm² from the SAME gradient stream the
                # optimizer just consumed — replicated already, so this
                # adds local reductions but no new collective. AMP grads
                # arrive pre-multiplied by the loss scale; unscale the
                # squared norm so the gate threshold and the host
                # detector both see true magnitudes.
                gn2 = sum(jnp.sum(jnp.square(g.astype(jnp.float32)))
                          for g in grads.values())
                if amp:
                    inv = 1.0 / opt_state[self.AMP_SCALE_KEY].astype(
                        jnp.float32)
                    gn2 = gn2 * inv * inv
                ok = jnp.logical_and(jnp.isfinite(gn2), gn2 <= gthr)

                def _sel(new, old):
                    # branchless select over a (possibly nested) state
                    # entry: select(True, new, old) is bitwise `new`, so
                    # a clean step is untouched by the gate
                    return jax.tree_util.tree_map(
                        lambda n_, o_: jnp.where(ok, n_, o_), new, old)

                # AMP's scaler bookkeeping stays LIVE through a skip:
                # reverting the scale would undo the backoff that makes
                # the next attempt finite (same contract as the inner
                # AMP gate, which also exempts these two keys).
                passthru = ({self.AMP_SCALE_KEY, self.AMP_GOOD_KEY}
                            if amp else ())
                new_params = {k: (_sel(v, params[k]) if k in params else v)
                              for k, v in new_params.items()}
                new_opt = {k: (v if (k in passthru or k not in opt_state)
                               else _sel(v, opt_state[k]))
                           for k, v in new_opt.items()}
                new_aux = {k: (_sel(v, aux[k]) if k in aux else v)
                           for k, v in new_aux.items()}
                diag = jnp.stack([
                    jnp.asarray(loss_val, jnp.float32), gn2,
                    ok.astype(jnp.float32)])
                outs = list(outs) + [diag]
            return new_params, new_aux, new_opt, outs

        return step

    def compile(self, data_shapes_by_name=None):
        """Build + jit the fused step fn. Returns self.

        Shardings are NOT pinned here: inputs arrive committed (placed by
        place_params/make_state/batch device_put) and GSPMD propagates —
        the idiomatic "computation follows sharding" path; donation keeps
        params/opt-state in place across steps."""
        import jax

        self._step = jax.jit(self._make_step_fn(), donate_argnums=(0, 1, 2))
        try:
            _tm.anatomy.register_program(
                self.program._program_uid,
                mesh=str(dict(self.mesh.shape)),
                donation="params,aux,opt_state")
        except Exception:  # noqa: BLE001 — observer only
            pass
        return self

    def arm_guard(self):
        """Turn the guardrail gate + diag head on (fit(guardrails=...)).

        Re-wraps the step jits; jax.jit traces lazily, so arming before
        the first dispatch costs nothing extra, and arming later in a
        trainer's life retraces once at the next call. Idempotent."""
        if not self.guard:
            self.guard = True
            self._step_multi.clear()
            self.compile()
        return self

    def compile_multi(self, k):
        """Jit a K-step program: lax.scan of the fused step over stacked
        batches — ONE host dispatch per K optimizer steps.

        Motivation (VERDICT r4 #3): on the tunneled v5e a b32 step pays
        ~13.7 ms host dispatch against ~11.6 ms device time; scanning K
        steps inside one XLA program amortizes the dispatch to 1/K per
        step, the in-graph analog of the reference's dispatch-hiding
        threaded engine (threaded_engine_perdevice.cc:26-136 — its
        python thread never waits on the device). Exact same per-step
        math: the scan body IS the single-step body; lr/t/rng arrive as
        (K,)-stacked xs so schedules advance per micro-step.

        Returns the jitted fn (params, aux, opt, batches[K,...],
        rngs[K,2], lrs[K], ts[K]) -> (params, aux, opt, outs[K, ...]);
        cached per K."""
        import jax

        fn = self._step_multi.get(k)
        if fn is not None:
            return fn
        step = self._make_step_fn()

        def multi(params, aux, opt_state, batches, rngs, lrs, ts, gthr):
            def body(carry, xs):
                p, a, s = carry
                batch_k, rng_k, lr_k, t_k = xs
                # gthr is a loop constant: the monitor refreshes it at
                # group boundaries, never inside a K-group
                np_, na, ns, outs = step(p, a, s, batch_k, rng_k,
                                         lr_k, t_k, gthr)
                return (np_, na, ns), outs

            (p, a, s), outs = jax.lax.scan(
                body, (params, aux, opt_state), (batches, rngs, lrs, ts))
            return p, a, s, outs

        fn = jax.jit(multi, donate_argnums=(0, 1, 2))
        self._step_multi[k] = fn
        return fn

    def call_multi(self, params, aux, opt_state, batches, lrs, ts):
        """Run K fused steps in one dispatch (see compile_multi).

        `batches`: dict name -> (K, batch, ...) arrays already placed
        with batch_sharding_stacked(); `lrs`/`ts`: length-K sequences
        (per-micro-step schedule values, host-computed)."""
        import jax.numpy as jnp

        k = len(lrs)
        fn = self.compile_multi(k)
        # dispatch fast path (_GraphProgram.dispatch_plan): key on the
        # batch entries alone — param shapes are fixed per trainer, and
        # creation-shape overrides depend only on the PER-STEP shapes
        # (scan axis dropped)
        sig = tuple(
            (n, tuple(v.shape[1:]), str(v.dtype),
             getattr(v, "sharding", None))
            for n, v in batches.items())

        def _build():
            from ..executor import resolve_creation_shapes

            shapes = {n: tuple(v.shape) for n, v in params.items()}
            shapes.update(
                {n: tuple(v.shape[1:]) for n, v in batches.items()})
            return resolve_creation_shapes(self.symbol, shapes)

        self.program.dispatch_plan(sig, _build)
        if self._needs_rng:
            from .. import random as _random

            rngs = jnp.stack([_random.next_key() for _ in range(k)])
        else:
            rngs = jnp.zeros((k, 2), jnp.uint32)
        lrs_arr = jnp.asarray(lrs, jnp.float32)
        ts_arr = jnp.asarray(ts, jnp.float32)
        gthr_arr = jnp.asarray(self.guard_threshold, jnp.float32)
        if _tm.anatomy.wants_cost():
            # AOT lower+compile BEFORE the donating dispatch (lower does
            # not consume buffers); cached per signature, so the steady
            # state pays a dict lookup. No steps=k division: XLA's cost
            # analysis sums the scan BODY once (trip count is not
            # multiplied in), so the K-step program already reports
            # per-step cost
            _tm.anatomy.capture_cost(
                self.program._program_uid, ("multi", k) + sig,
                lambda: fn.lower(params, aux, opt_state, batches, rngs,
                                 lrs_arr, ts_arr, gthr_arr).compile(),
                dtype="bf16" if self.amp else "f32")
        _M_STEPS.inc(k, path="multi")
        with _tm.span("train_step.dispatch", k=k):
            return fn(params, aux, opt_state, batches, rngs,
                      lrs_arr, ts_arr, gthr_arr)

    def __call__(self, params, aux, opt_state, batch, rng=None, lr=None, t=1):
        assert self._step is not None, "call compile() first"
        import jax.numpy as jnp

        # resolve 0-dims in creation-op shape attrs (rnn begin_state zeros
        # etc.) against the CURRENT input shapes, before jit traces. The
        # dispatch plan is keyed on the batch entries' (shape, dtype,
        # sharding) alone — param shapes are fixed per trainer — so the
        # steady state iterates 1-4 batch items instead of rebuilding and
        # sorting the full params+batch shape dict every step; a
        # batch-size change (Module.reshape, partial final batch) or a
        # re-placed input re-resolves once. Already-traced signatures
        # stay cached in jit.
        sig = tuple(
            (n, tuple(v.shape), str(v.dtype), getattr(v, "sharding", None))
            for n, v in batch.items())

        def _build():
            from ..executor import resolve_creation_shapes

            shapes = {n: tuple(v.shape) for n, v in params.items()}
            shapes.update({n: tuple(v.shape) for n, v in batch.items()})
            return resolve_creation_shapes(self.symbol, shapes)

        self.program.dispatch_plan(sig, _build)

        if lr is None:
            opt = self.optimizer
            if opt is not None and opt.lr_scheduler is not None:
                lr = float(opt.lr_scheduler(opt.num_update))
            else:
                lr = float(getattr(opt, "lr", 0.01))
        if rng is None:
            if self._needs_rng:
                from .. import random as _random

                rng = _random.next_key()
            else:
                rng = jnp.zeros((2,), jnp.uint32)  # unused placeholder
        lr_arr = jnp.asarray(lr, jnp.float32)
        t_arr = jnp.asarray(t, jnp.float32)
        gthr_arr = jnp.asarray(self.guard_threshold, jnp.float32)
        if _tm.anatomy.wants_cost():
            _tm.anatomy.capture_cost(
                self.program._program_uid, ("single",) + sig,
                lambda: self._step.lower(params, aux, opt_state, batch,
                                         rng, lr_arr, t_arr,
                                         gthr_arr).compile(),
                dtype="bf16" if self.amp else "f32")
        _M_STEPS.inc(path="single")
        with _tm.span("train_step.dispatch", t=t):
            return self._step(params, aux, opt_state, batch, rng,
                              lr_arr, t_arr, gthr_arr)
