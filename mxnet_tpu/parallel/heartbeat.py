"""Worker liveness: heartbeats + dead-node detection + watchdog support.

Capability parity, SURVEY.md §5.3: the reference's ps-lite Van sends
heartbeats to the scheduler and surfaces stale peers through
``KVStore::get_num_dead_node(node_id, timeout)`` (kvstore.h:235-244).
The TPU build has no scheduler process — ICI/DCN collectives are the
comm fabric — so liveness runs over the one medium every launcher
already shares with its workers: the run directory. This is
deliberately not a collective: liveness checks must keep working
exactly when collectives hang.

Two signals per rank, two files:

* ``hb_<rank>`` — **process liveness.** Touched every ``interval``
  seconds by a daemon thread. Detects dead/frozen processes, NOT a main
  thread wedged in a collective (the daemon keeps beating).
* ``prog_<rank>`` — **training progress.** Touched (rate-limited) from
  the worker's own hot path — KVStore push/pull/barrier call
  ``HeartbeatWriter.progress()``. A rank hung inside a collective stops
  touching this one, so ``tools/watchdog.py --progress-timeout`` catches
  exactly the hang class the liveness beat cannot. The timeout must
  exceed the longest legitimate gap between optimizer steps (first XLA
  compile included).

``tools/launch.py`` exports ``MXTPU_RUN_DIR`` so heartbeats start
automatically whenever a dist kvstore is created; ``tools/watchdog.py``
supervises a training command on exit code + both staleness signals.
"""
import os
import threading
import time

try:
    from .. import telemetry as _tm
except ImportError:
    # Loaded standalone by file path (tools/watchdog.py helpers and the
    # failure-recovery tests do this so liveness needs zero heavy
    # imports); record nothing in that mode.
    class _NoopMetric:
        def set(self, *args, **kwargs):
            pass

    class _NoopTelemetry:
        @staticmethod
        def enabled():
            return False

        @staticmethod
        def gauge(*args, **kwargs):
            return _NoopMetric()

    _tm = _NoopTelemetry()

_G_HB_AGE = _tm.gauge(
    "heartbeat.age_seconds",
    "Per-rank liveness-beat age at the last dead_nodes() poll "
    "(inf = never beat)")
_G_PROG_AGE = _tm.gauge(
    "heartbeat.progress_age_seconds",
    "Per-rank progress-mark age at the last stalled_nodes() poll")

RUN_DIR_ENV = "MXTPU_RUN_DIR"
_HB_PREFIX = "hb_"
_PROG_PREFIX = "prog_"
# Tombstones: an external controller (or resilience/fault.py's
# replica_lost / heartbeat_stall directives — which replicate these
# file names to stay stdlib-standalone) declares a rank gone by
# dropping ``lost_<rank>`` / ``stall_<rank>`` into the run dir. Writers
# honor them (a tombstoned rank stops beating / reporting progress) and
# lost_nodes() treats a lost tombstone as immediately dead — no need to
# wait out the staleness timeout, which keeps elastic-shrink tests
# deterministic.
_LOST_PREFIX = "lost_"
_STALL_PREFIX = "stall_"


def run_dir():
    """The launcher-provided liveness directory, or None outside a
    launched job."""
    return os.environ.get(RUN_DIR_ENV) or None


def _touch(path):
    with open(path, "a"):
        pass
    os.utime(path, None)


def _tombstone(directory, prefix, rank):
    return os.path.join(directory, "%s%d" % (prefix, int(rank)))


def mark_lost(directory, rank, stall_only=False):
    """Declare ``rank`` lost (or, with ``stall_only``, progress-wedged):
    drop the tombstone and back-date the corresponding signal file so
    pollers trip on their next pass regardless of timeout. This is the
    controller-side half of the elastic contract; the passive half is
    that this rank's own HeartbeatWriter stops touching the file."""
    prefixes = ((_STALL_PREFIX, _PROG_PREFIX) if stall_only
                else (_LOST_PREFIX, _HB_PREFIX))
    _touch(_tombstone(directory, prefixes[0], rank))
    stale = os.path.join(directory, "%s%d" % (prefixes[1], int(rank)))
    with open(stale, "a"):
        pass
    os.utime(stale, (1.0, 1.0))


def tombstoned(directory):
    """Ranks with a ``lost_<rank>`` tombstone in the run dir (what
    tools/watchdog.py --elastic reads to size the restart world)."""
    ranks = set()
    try:
        entries = os.listdir(directory)
    except OSError:
        return ranks
    for name in entries:
        if name.startswith(_LOST_PREFIX):
            try:
                ranks.add(int(name[len(_LOST_PREFIX):]))
            except ValueError:
                pass
    return ranks


class HeartbeatWriter:
    """Touch ``<run_dir>/hb_<rank>`` every ``interval`` seconds from a
    daemon thread (reference analog: Van::Heartbeat thread), and
    ``prog_<rank>`` whenever the worker reports forward progress."""

    def __init__(self, directory, rank, interval=2.0):
        self._dir = directory
        self.rank = int(rank)
        self._path = os.path.join(directory, "%s%d" % (_HB_PREFIX, rank))
        self._prog_path = os.path.join(
            directory, "%s%d" % (_PROG_PREFIX, rank))
        self._interval = float(interval)
        self._stop = threading.Event()
        self._thread = None
        self._last_prog = 0.0
        self._last_ticks = 0
        self._lost = False  # sticky once the tombstone is seen
        os.makedirs(directory, exist_ok=True)

    def _is_lost(self):
        """A ``lost_<rank>`` tombstone silences this writer for good:
        fault injection (replica_lost) simulates a vanished replica by
        freezing its heartbeat, and a writer that kept re-touching the
        back-dated file would un-kill it every interval."""
        if not self._lost:
            self._lost = os.path.exists(
                _tombstone(self._dir, _LOST_PREFIX, self.rank))
        return self._lost

    def start(self):
        if self._thread is not None:
            if self._thread.is_alive() and not self._stop.is_set():
                return self  # already beating
            # Previous thread is winding down (stop() timed out before
            # it exited) or already finished; wait it out and reap it so
            # two beaters never run at once.
            self._thread.join()
            self._thread = None
        self._stop.clear()  # writers are restartable (stop() then start())
        self._beat()
        self.progress()
        try:
            # the writer is the one long-lived per-rank presence in the
            # run dir, so it also drops the clock handshake the fleet
            # aggregator aligns timelines with (telemetry/fleet.py) —
            # best-effort: absent telemetry package (standalone load)
            # the per-rank JSONL sink writes it instead
            from ..telemetry import export as _texport

            _texport.write_clock_handshake(self._dir, self.rank)
        except Exception:  # noqa: BLE001 — liveness must start regardless
            pass
        self._thread = threading.Thread(
            target=self._loop, name="mxtpu-heartbeat", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self._interval + 1.0)
            if self._thread.is_alive():
                # Join timed out: the thread is still winding down (e.g.
                # blocked in a slow _touch). Keep the handle so start()
                # cannot spawn a second beater alongside it; the next
                # start() reaps it once it exits.
                return
            self._thread = None

    def progress(self, ticks=1):
        """Mark forward progress from the worker's OWN thread (kvstore
        push/pull/barrier; fused update). Rate-limited to one touch per
        interval so per-key push loops don't turn into an utime storm.

        ``ticks`` > 1 reports a multi-batch dispatch (Module.update_multi
        runs K optimizer steps per host call, so the next report is K
        batch-times away). The K-1 extra ticks bank FUTURE mtime credit
        — estimated from the previous inter-report gap — so
        ``tools/watchdog.py --progress-timeout`` tuned to per-batch
        cadence doesn't false-trip mid-dispatch (ADVICE r5)."""
        now = time.monotonic()
        if ticks <= 1 and now - self._last_prog < self._interval:
            return
        if self._is_lost() or os.path.exists(
                _tombstone(self._dir, _STALL_PREFIX, self.rank)):
            return  # tombstoned: the rank must LOOK wedged to pollers
        per_tick = 0.0
        if self._last_prog > 0.0 and self._last_ticks > 0:
            per_tick = max(0.0, now - self._last_prog) / self._last_ticks
        self._last_prog = now
        self._last_ticks = ticks
        try:
            _touch(self._prog_path)
            credit = (ticks - 1) * per_tick
            if credit > 0.0:
                t = time.time() + credit
                os.utime(self._prog_path, (t, t))
        except OSError:
            pass  # progress is advisory; liveness beat handles teardown

    def _beat(self):
        # liveness is the file's mtime (all dead_nodes reads); touch is
        # cheaper and atomic vs the readers, no payload needed
        if self._is_lost():
            return
        _touch(self._path)

    def _loop(self):
        while not self._stop.wait(self._interval):
            try:
                self._beat()
            except OSError:
                # Only give up if the run dir is actually gone (job
                # teardown); transient write errors (ENOSPC blip, NFS
                # hiccup) must not silently stop liveness and get a
                # healthy job killed.
                if not os.path.isdir(self._dir):
                    return


def dead_nodes(directory, num_workers, timeout=60.0, now=None,
               prefix=_HB_PREFIX):
    """Ranks whose heartbeat is missing or older than ``timeout`` seconds.

    Semantics of ``get_num_dead_node``: a node that never wrote a
    heartbeat counts as dead (the reference's scheduler likewise treats
    an unregistered-but-expected node as not alive)."""
    now = time.time() if now is None else now
    record = _tm.enabled() and prefix == _HB_PREFIX
    dead = []
    for rank in range(int(num_workers)):
        path = os.path.join(directory, "%s%d" % (prefix, rank))
        try:
            age = now - os.path.getmtime(path)
        except OSError:
            if record:
                _G_HB_AGE.set(float("inf"), rank=str(rank))
            dead.append(rank)
            continue
        if record:
            _G_HB_AGE.set(age, rank=str(rank))
        if age > timeout:
            dead.append(rank)
    return dead


def stalled_nodes(directory, num_workers, timeout, now=None):
    """Ranks alive (process beating) but without recent progress — the
    wedged-in-a-collective signature.

    A missing ``prog_`` file is "not yet started", not "stalled": the
    initial progress touch can land after the liveness beat (start()
    ordering) or be swallowed by a transient write error, and killing a
    healthy job over that race would be worse than missing one poll.
    Such a rank only counts once its prog file exists and is stale."""
    now = time.time() if now is None else now
    alive = set(range(int(num_workers))) - set(
        dead_nodes(directory, num_workers, timeout, now=now))
    stalled = []
    for rank in sorted(alive):
        path = os.path.join(directory, "%s%d" % (_PROG_PREFIX, rank))
        try:
            age = now - os.path.getmtime(path)
        except OSError:
            continue  # never progressed yet -> startup, not a stall
        if _tm.enabled():
            _G_PROG_AGE.set(age, rank=str(rank))
        if age > timeout:
            stalled.append(rank)
    return stalled


def lost_nodes(directory, num_workers, timeout=60.0, now=None):
    """Ranks declared LOST for elastic-shrink purposes: a ``lost_``
    tombstone, or a heartbeat file that exists but is stale past
    ``timeout``.

    Deliberately stricter than :func:`dead_nodes`: a rank that never
    wrote a heartbeat is a launcher/startup problem (watchdog
    startup_timeout territory), not a shrink signal — treating it as
    lost would shrink a healthy fleet that is still compiling. Only a
    rank that was seen alive and then went silent (or was explicitly
    tombstoned) votes for a smaller world."""
    now = time.time() if now is None else now
    lost = tombstoned(directory)
    for rank in range(int(num_workers)):
        path = os.path.join(directory, "%s%d" % (_HB_PREFIX, rank))
        try:
            age = now - os.path.getmtime(path)
        except OSError:
            continue  # never started: not a shrink vote
        if age > timeout:
            lost.add(rank)
    return sorted(r for r in lost if 0 <= r < int(num_workers))
