"""Worker liveness: heartbeats + dead-node detection + watchdog support.

Capability parity, SURVEY.md §5.3: the reference's ps-lite Van sends
heartbeats to the scheduler and surfaces stale peers through
``KVStore::get_num_dead_node(node_id, timeout)`` (kvstore.h:235-244).
The TPU build has no scheduler process — ICI/DCN collectives are the
comm fabric — so liveness runs over the one medium every launcher
already shares with its workers: the run directory. Each worker's
``HeartbeatWriter`` daemon thread touches ``hb_<rank>`` every
``interval`` seconds; any process (a peer's kvstore, the watchdog, an
operator's shell) can then read staleness with ``dead_nodes``. This is
deliberately not a collective: liveness checks must keep working
exactly when collectives hang.

``tools/launch.py`` exports ``MXTPU_RUN_DIR`` so heartbeats start
automatically whenever a dist kvstore is created; ``tools/watchdog.py``
supervises a training command with the same signals (exit code +
heartbeat staleness) and restarts it from its checkpoints.
"""
import os
import threading
import time

RUN_DIR_ENV = "MXTPU_RUN_DIR"
_HB_PREFIX = "hb_"


def run_dir():
    """The launcher-provided liveness directory, or None outside a
    launched job."""
    return os.environ.get(RUN_DIR_ENV) or None


class HeartbeatWriter:
    """Touch ``<run_dir>/hb_<rank>`` every ``interval`` seconds from a
    daemon thread (reference analog: Van::Heartbeat thread)."""

    def __init__(self, directory, rank, interval=2.0):
        self._path = os.path.join(directory, "%s%d" % (_HB_PREFIX, rank))
        self._interval = float(interval)
        self._stop = threading.Event()
        self._thread = None
        os.makedirs(directory, exist_ok=True)

    def start(self):
        if self._thread is not None:
            return self
        self._beat()
        self._thread = threading.Thread(
            target=self._loop, name="mxtpu-heartbeat", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self._interval + 1.0)
            self._thread = None

    def _beat(self):
        # liveness is the file's mtime (all dead_nodes reads); touch is
        # cheaper and atomic vs the readers, no payload needed
        with open(self._path, "a"):
            pass
        os.utime(self._path, None)

    def _loop(self):
        while not self._stop.wait(self._interval):
            try:
                self._beat()
            except OSError:
                # run dir vanished (job teardown) — stop quietly
                return


def dead_nodes(directory, num_workers, timeout=60.0, now=None):
    """Ranks whose heartbeat is missing or older than ``timeout`` seconds.

    Semantics of ``get_num_dead_node``: a node that never wrote a
    heartbeat counts as dead (the reference's scheduler likewise treats
    an unregistered-but-expected node as not alive)."""
    now = time.time() if now is None else now
    dead = []
    for rank in range(int(num_workers)):
        path = os.path.join(directory, "%s%d" % (_HB_PREFIX, rank))
        try:
            age = now - os.path.getmtime(path)
        except OSError:
            dead.append(rank)
            continue
        if age > timeout:
            dead.append(rank)
    return dead
