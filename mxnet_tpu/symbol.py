"""Symbol: the declarative graph API.

Parity: reference ``python/mxnet/symbol.py`` + the vendored NNVM Symbol/
Graph (SURVEY.md §2 N19). The graph IR here is a plain Python node list —
no separate C++ IR is needed because lowering happens by *tracing the graph
as a JAX function* (symbol → jaxpr → XLA), which subsumes the reference's
InferShape/InferType/PlanMemory/Gradient passes:

- InferShape/InferType → per-op ``infer_shape`` fns (this file drives the
  fixpoint), plus abstract eval inside jit.
- nnvm::pass::Gradient → ``jax.grad`` over the traced function (executor).
- PlanMemory / inplace → XLA buffer assignment + donation.
- SaveJSON/LoadJSON → :meth:`Symbol.tojson` / :func:`load_json` with the
  reference's graph-JSON schema (nodes/arg_nodes/heads) so checkpoints
  interoperate structurally.
"""
from __future__ import annotations

import json
import sys

import numpy as np

from .attribute import AttrScope
from .base import MXNetError, attr_repr, np_dtype, dtype_name
from .name import NameManager
from .ops import registry as _registry

__all__ = ["Symbol", "Variable", "Group", "load", "load_json", "var"]


class _Node:
    """One graph node: a variable (op is None) or an op instance."""

    __slots__ = ("op", "name", "attrs", "inputs", "_extra")

    def __init__(self, op, name, attrs=None, inputs=None):
        self.op = op  # OpDef or None for variables
        self.name = name
        self.attrs = dict(attrs or {})  # string-valued (graph JSON parity)
        self.inputs = list(inputs or [])  # list[(Node, int)]
        self._extra = {}

    @property
    def is_variable(self):
        return self.op is None

    def canon_attrs(self):
        return self.op.canon_attrs(self.attrs) if self.op else {}

    def output_names(self):
        if self.is_variable:
            return [self.name]
        attrs = self.canon_attrs()
        outs = self.op.list_outputs(attrs)
        n_visible = self.op.num_visible_outputs(attrs)
        if len(outs) == 1:
            return ["%s_%s" % (self.name, outs[0])]
        return ["%s_%s" % (self.name, o) for o in outs[:n_visible]] + [
            "%s_%s" % (self.name, o) for o in outs[n_visible:]
        ]

    def num_outputs(self):
        if self.is_variable:
            return 1
        return len(self.op.list_outputs(self.canon_attrs()))

    def num_visible_outputs(self):
        if self.is_variable:
            return 1
        return self.op.num_visible_outputs(self.canon_attrs())


def _topo_order(head_nodes):
    """Post-order DFS — matches nnvm's DFSVisit ordering, which defines
    list_arguments order in the reference."""
    visited = set()
    order = []

    def visit(node):
        if id(node) in visited:
            return
        visited.add(id(node))
        for (child, _) in node.inputs:
            visit(child)
        order.append(node)

    for n in head_nodes:
        visit(n)
    return order


class Symbol:
    """A handle to one or more output entries of a graph."""

    __slots__ = ("_outputs",)

    def __init__(self, outputs):
        self._outputs = list(outputs)  # list[(Node, int)]

    # ------------------------------------------------------------------
    # composition
    # ------------------------------------------------------------------
    @property
    def name(self):
        if len(self._outputs) == 1:
            return self._outputs[0][0].name
        return None

    def __iter__(self):
        return (self[i] for i in range(len(self.list_outputs())))

    def __getitem__(self, index):
        if isinstance(index, str):
            names = self.list_outputs()
            if index not in names:
                raise MXNetError("output %s not found in %s" % (index, names))
            index = names.index(index)
        return Symbol([self._visible_outputs()[index]])

    def _visible_outputs(self):
        out = []
        for node, idx in self._outputs:
            out.append((node, idx))
        return out

    def __len__(self):
        return len(self.list_outputs())

    def get_internals(self):
        """All intermediate outputs as a grouped symbol (reference
        symbol.py get_internals — used for feature extraction / shared
        layers)."""
        nodes = _topo_order([n for n, _ in self._outputs])
        outs = []
        for n in nodes:
            for i in range(n.num_visible_outputs()):
                outs.append((n, i))
        return Symbol(outs)

    # ------------------------------------------------------------------
    # arguments / outputs / aux
    # ------------------------------------------------------------------
    def _nodes(self):
        return _topo_order([n for n, _ in self._outputs])

    def list_arguments(self):
        args = []
        for n in self._nodes():
            if n.is_variable and not n._extra.get("is_aux"):
                args.append(n.name)
        return args

    def list_outputs(self):
        names = []
        for node, idx in self._outputs:
            names.append(node.output_names()[idx])
        return names

    def list_auxiliary_states(self):
        aux = []
        for n in self._nodes():
            if n.is_variable and n._extra.get("is_aux"):
                aux.append(n.name)
        return aux

    def list_attr(self, recursive=False):
        if recursive:
            out = {}
            for n in self._nodes():
                for k, v in n.attrs.items():
                    out["%s_%s" % (n.name, k)] = v
            return out
        return dict(self._outputs[0][0].attrs)

    def attr(self, key):
        return self._outputs[0][0].attrs.get(key)

    def attr_dict(self):
        out = {}
        for n in self._nodes():
            if n.attrs:
                out[n.name] = dict(n.attrs)
        return out

    def _set_attr(self, **kwargs):
        for k, v in kwargs.items():
            self._outputs[0][0].attrs[k] = v

    # ------------------------------------------------------------------
    # arithmetic composition
    # ------------------------------------------------------------------
    def _binop(self, other, op, scalar_op, reverse=False):
        if isinstance(other, Symbol):
            a, b = (other, self) if reverse else (self, other)
            return _create_symbol(op, [a, b], {})
        if np.isscalar(other):
            name = scalar_op
            if reverse and op in ("elemwise_sub", "elemwise_div", "_power", "_mod"):
                name = "_r" + scalar_op[1:]
            return _create_symbol(name, [self], {"scalar": other})
        return NotImplemented

    def __add__(self, o):
        return self._binop(o, "elemwise_add", "_plus_scalar")

    __radd__ = __add__

    def __sub__(self, o):
        return self._binop(o, "elemwise_sub", "_minus_scalar")

    def __rsub__(self, o):
        return self._binop(o, "elemwise_sub", "_minus_scalar", reverse=True)

    def __mul__(self, o):
        return self._binop(o, "elemwise_mul", "_mul_scalar")

    __rmul__ = __mul__

    def __div__(self, o):
        return self._binop(o, "elemwise_div", "_div_scalar")

    __truediv__ = __div__

    def __rdiv__(self, o):
        return self._binop(o, "elemwise_div", "_div_scalar", reverse=True)

    __rtruediv__ = __rdiv__

    def __pow__(self, o):
        return self._binop(o, "_power", "_power_scalar")

    def __neg__(self):
        return _create_symbol("negative", [self], {})

    def __copy__(self):
        return Symbol(list(self._outputs))

    def __call__(self, *args, **kwargs):
        """Compose: replace this symbol's free variables (reference
        symbol.py:321 __call__/Compose)."""
        s = self.__copy__()
        s._compose(*args, **kwargs)
        return s

    def _compose(self, *args, **kwargs):
        name = kwargs.pop("name", None)
        if args and kwargs:
            raise MXNetError("compose only accepts all-positional or all-keyword")
        arg_names = self.list_arguments()
        mapping = {}
        if args:
            for n, s in zip(arg_names, args):
                mapping[n] = s
        else:
            for k, v in kwargs.items():
                if not isinstance(v, Symbol):
                    raise MXNetError("compose expects Symbols")
                mapping[k] = v
        # rebuild graph with substituted variables
        memo = {}

        def rebuild(node):
            if id(node) in memo:
                return memo[id(node)]
            if node.is_variable and node.name in mapping:
                sub = mapping[node.name]._outputs[0][0]
                memo[id(node)] = sub
                return sub
            new = _Node(node.op, node.name, node.attrs, [])
            memo[id(node)] = new
            new._extra = dict(node._extra)
            new.inputs = [(rebuild(c), i) for (c, i) in node.inputs]
            return new

        self._outputs = [(rebuild(n), i) for (n, i) in self._outputs]
        if name is not None and len(self._outputs) == 1:
            self._outputs[0][0].name = name

    # ------------------------------------------------------------------
    # shape / type inference (fixpoint over per-op inference fns)
    # ------------------------------------------------------------------
    def infer_shape(self, *args, **kwargs):
        return self._infer_shape_impl(False, *args, **kwargs)[:3]

    def infer_shape_partial(self, *args, **kwargs):
        return self._infer_shape_impl(True, *args, **kwargs)[:3]

    def _infer_shape_env(self, **kwargs):
        """infer_shape + the resolved per-(node, out_idx) shape map — the
        executor uses this to materialize creation ops whose attr shape has
        unknown dims (begin_state zeros)."""
        return self._infer_shape_impl(False, **kwargs)[3]

    def _infer_shape_impl(self, partial, *args, **kwargs):
        nodes = self._nodes()
        known = {}  # (id(node), out_idx) -> shape
        arg_names = self.list_arguments()
        if args:
            for n, s in zip(arg_names, args):
                if s is not None:
                    kwargs[n] = s
        name2var = {n.name: n for n in nodes if n.is_variable}
        for k, v in kwargs.items():
            if k in name2var:
                known[(id(name2var[k]), 0)] = tuple(v)
        # variables may carry shape attrs (__shape__)
        for n in nodes:
            if n.is_variable and "__shape__" in n.attrs:
                from .base import parse_attr_value

                known.setdefault((id(n), 0), tuple(parse_attr_value(n.attrs["__shape__"])))

        from .ops.utils import merge_shapes, shape_known

        def assign(key, s, where):
            if s is None:
                return False
            prev = known.get(key)
            merged = merge_shapes(prev, s, where)
            if merged != prev:
                known[key] = merged
                return True
            return False

        for _ in range(4):  # forward+backward fixpoint (nnvm InferShape)
            changed = False
            for node in nodes:
                if node.is_variable:
                    continue
                attrs = node.canon_attrs()
                in_shapes = [known.get((id(c), i)) for (c, i) in node.inputs]
                n_args = node._extra.get("n_args", len(node.inputs))
                try:
                    arg_sh, out_sh, aux_sh = node.op.infer_shape(
                        attrs, in_shapes[:n_args]
                    )
                except (MXNetError, TypeError, IndexError):
                    continue
                completed = list(arg_sh) + list(aux_sh)
                for (c, i), s in zip(node.inputs, completed):
                    changed |= assign((id(c), i), s, c.name)
                for i, s in enumerate(out_sh):
                    changed |= assign((id(node), i), s, node.name)
            # reverse sweep: consumers refine producers
            for node in reversed(nodes):
                if node.is_variable or node.op.backward_infer_shape is None:
                    continue
                attrs = node.canon_attrs()
                in_shapes = [known.get((id(c), i)) for (c, i) in node.inputs]
                out_shapes = [
                    known.get((id(node), i)) for i in range(node.num_outputs())
                ]
                try:
                    refined = node.op.backward_infer_shape(
                        attrs, in_shapes, out_shapes
                    )
                except (MXNetError, TypeError, IndexError):
                    continue
                for (c, i), s in zip(node.inputs, refined):
                    changed |= assign((id(c), i), s, c.name)
            if not changed:
                break

        def finalize(s):
            if s is not None and 0 in s:
                return None if not partial else s
            return s

        arg_shapes = [finalize(known.get((id(name2var[n]), 0))) for n in arg_names]
        out_shapes = [finalize(known.get((id(n), i))) for (n, i) in self._outputs]
        aux_shapes = [
            finalize(known.get((id(name2var[n]), 0)))
            for n in self.list_auxiliary_states()
        ]
        if not partial and any(s is None for s in arg_shapes + out_shapes):
            missing = [n for n, s in zip(arg_names, arg_shapes) if s is None]
            raise MXNetError(
                "infer_shape: cannot fully infer shapes; unresolved args: %s"
                % missing
            )
        return arg_shapes, out_shapes, aux_shapes, known

    def infer_type(self, *args, **kwargs):
        nodes = self._nodes()
        known = {}
        arg_names = self.list_arguments()
        if args:
            for n, t in zip(arg_names, args):
                if t is not None:
                    kwargs[n] = t
        name2var = {n.name: n for n in nodes if n.is_variable}
        for k, v in kwargs.items():
            if k in name2var:
                known[(id(name2var[k]), 0)] = np_dtype(v)
        for n in nodes:
            if n.is_variable and "__dtype__" in n.attrs:
                known.setdefault((id(n), 0), np_dtype(n.attrs["__dtype__"]))
        for _ in range(3):
            changed = False
            for node in nodes:
                if node.is_variable:
                    continue
                attrs = node.canon_attrs()
                in_types = [known.get((id(c), i)) for (c, i) in node.inputs]
                n_args = node._extra.get("n_args", len(node.inputs))
                try:
                    arg_t, out_t, aux_t = node.op.infer_type(attrs, in_types[:n_args])
                except MXNetError:
                    continue
                completed = list(arg_t) + list(aux_t)
                for (c, i), t in zip(node.inputs, completed):
                    if t is not None and known.get((id(c), i)) is None:
                        known[(id(c), i)] = t
                        changed = True
                for i, t in enumerate(out_t):
                    if known.get((id(node), i)) is None:
                        known[(id(node), i)] = t
                        changed = True
            if not changed:
                break
        arg_types = [known.get((id(name2var[n]), 0), np.float32) for n in arg_names]
        out_types = [known.get((id(n), i), np.float32) for (n, i) in self._outputs]
        aux_types = [
            known.get((id(name2var[n]), 0), np.float32)
            for n in self.list_auxiliary_states()
        ]
        return arg_types, out_types, aux_types

    # ------------------------------------------------------------------
    # JSON serialization — reference graph-JSON schema
    # ------------------------------------------------------------------
    def tojson(self):
        nodes = self._nodes()
        node_ids = {id(n): i for i, n in enumerate(nodes)}
        jnodes = []
        for n in nodes:
            jnodes.append(
                {
                    "op": "null" if n.is_variable else n.op.name,
                    "name": n.name,
                    "attr": {k: str(v) for k, v in n.attrs.items()},
                    "inputs": [[node_ids[id(c)], i, 0] for (c, i) in n.inputs],
                }
            )
        arg_nodes = [i for i, n in enumerate(nodes) if n.is_variable]
        heads = [[node_ids[id(n)], i, 0] for (n, i) in self._outputs]
        return json.dumps(
            {
                "nodes": jnodes,
                "arg_nodes": arg_nodes,
                "node_row_ptr": list(range(len(nodes) + 1)),
                "heads": heads,
                "attrs": {"mxnet_version": ["int", 905]},
            },
            indent=2,
        )

    def save(self, fname):
        with open(fname, "w") as f:
            f.write(self.tojson())

    # ------------------------------------------------------------------
    # binding (executor construction) — see executor.py
    # ------------------------------------------------------------------
    def simple_bind(self, ctx, grad_req="write", type_dict=None, group2ctx=None,
                    shared_exec=None, **kwargs):
        from .executor import Executor

        return Executor.simple_bind(
            self, ctx, grad_req=grad_req, type_dict=type_dict,
            group2ctx=group2ctx, shared_exec=shared_exec, **kwargs
        )

    def bind(self, ctx, args, args_grad=None, grad_req="write", aux_states=None,
             group2ctx=None, shared_exec=None):
        from .executor import Executor

        return Executor.bind(
            self, ctx, args, args_grad=args_grad, grad_req=grad_req,
            aux_states=aux_states, group2ctx=group2ctx, shared_exec=shared_exec
        )

    def eval(self, ctx=None, **kwargs):
        from .context import current_context

        ctx = ctx or current_context()
        ex = self.bind(ctx, kwargs)
        return ex.forward()

    def grad(self, wrt):
        raise MXNetError(
            "Symbol.grad: use bind(args_grad=...) + backward; gradient graphs "
            "are produced by jax.grad at executor compile time"
        )

    # debug
    def debug_str(self):
        lines = []
        for n in self._nodes():
            kind = "Variable" if n.is_variable else n.op.name
            lines.append(
                "%s %s inputs=%s" % (kind, n.name, [c.name for c, _ in n.inputs])
            )
        return "\n".join(lines)

    def __repr__(self):
        return "<Symbol %s>" % (self.name or self.list_outputs())


# --------------------------------------------------------------------------
# constructors
# --------------------------------------------------------------------------
def Variable(name, attr=None, shape=None, lr_mult=None, wd_mult=None,
             dtype=None, init=None):
    if not isinstance(name, str):
        raise MXNetError("Variable name must be a string")
    attr = AttrScope.current().get(attr or {})
    node = _Node(None, name, attr)
    if shape is not None:
        node.attrs["__shape__"] = str(tuple(shape))
    if dtype is not None:
        node.attrs["__dtype__"] = dtype_name(dtype)
    if lr_mult is not None:
        node.attrs["__lr_mult__"] = str(lr_mult)
    if wd_mult is not None:
        node.attrs["__wd_mult__"] = str(wd_mult)
    if init is not None:
        node.attrs["__init__"] = init if isinstance(init, str) else init.dumps()
    return Symbol([(node, 0)])


var = Variable


def Group(symbols):
    outputs = []
    for s in symbols:
        outputs.extend(s._visible_outputs())
    return Symbol(outputs)


def load_json(json_str):
    data = json.loads(json_str)
    nodes = []
    for jn in data["nodes"]:
        if jn["op"] == "null":
            node = _Node(None, jn["name"], jn.get("attr") or jn.get("attrs") or {})
        else:
            opdef = _registry.get(jn["op"])
            node = _Node(opdef, jn["name"], jn.get("attr") or jn.get("attrs") or {})
        nodes.append(node)
    for jn, node in zip(data["nodes"], nodes):
        node.inputs = [(nodes[i[0]], i[1]) for i in jn["inputs"]]
        if node.op is not None:
            attrs = node.canon_attrs()
            n_args = len(node.op.list_arguments(attrs))
            # NOTE: generated op fns shadow some builtins at module scope
            # (min/max/sum) — use a conditional, not builtin min().
            node._extra["n_args"] = (
                n_args if n_args < len(node.inputs) else len(node.inputs)
            )
            # mark aux variable inputs
            for (c, _), _n in zip(
                node.inputs[node._extra["n_args"]:],
                node.op.list_auxiliary_states(attrs),
            ):
                c._extra["is_aux"] = True
    heads = [(nodes[h[0]], h[1]) for h in data["heads"]]
    return Symbol(heads)


def load(fname):
    with open(fname) as f:
        return load_json(f.read())


# --------------------------------------------------------------------------
# op → symbol-creation functions (reference symbol.py:1585 _init_symbol_module)
# --------------------------------------------------------------------------
def _create_symbol(op_name, sym_inputs, attrs, name=None, attr=None):
    opdef = _registry.get(op_name)
    opdef.check_call_attrs(attrs)  # typo net (dmlc::Parameter analog)
    canon = opdef.canon_attrs(attrs)
    hint = opdef.name.lower().lstrip("_")
    name = NameManager.current().get(name, hint)
    node_attrs = {
        k: (v if isinstance(v, str) else attr_repr(v))
        for k, v in attrs.items()
        if v is not None
    }
    node_attrs.update(AttrScope.current().get(attr or {}))
    node = _Node(opdef, name, node_attrs)

    arg_names = opdef.list_arguments(canon)
    inputs = []
    provided = {i: s for i, s in enumerate(sym_inputs)}
    if opdef.key_var_num_args and opdef.key_var_num_args not in attrs:
        node.attrs[opdef.key_var_num_args] = str(len(sym_inputs))
        arg_names = ["arg%d" % i for i in range(len(sym_inputs))]
    for i, aname in enumerate(arg_names):
        if i in provided and provided[i] is not None:
            s = provided[i]
            if not isinstance(s, Symbol):
                raise MXNetError(
                    "%s: input %s must be a Symbol, got %r" % (op_name, aname, s)
                )
            inputs.append(s._outputs[0])
        else:
            vnode = _Node(None, "%s_%s" % (name, aname), AttrScope.current().get({}))
            inputs.append((vnode, 0))
    n_args = len(inputs)
    for aux_name in opdef.list_auxiliary_states(canon):
        vnode = _Node(None, "%s_%s" % (name, aux_name), {})
        vnode._extra["is_aux"] = True
        inputs.append((vnode, 0))
    node.inputs = inputs
    node._extra["n_args"] = n_args
    n_visible = opdef.num_visible_outputs(canon)
    if n_visible == 1:
        return Symbol([(node, 0)])
    return Symbol([(node, i) for i in range(n_visible)])


def _make_symbol_function(opdef):
    def fn(*args, **kwargs):
        name = kwargs.pop("name", None)
        attr = kwargs.pop("attr", None)
        sym_kwargs = {}
        attrs = {}
        for k, v in kwargs.items():
            if isinstance(v, Symbol):
                sym_kwargs[k] = v
            else:
                attrs[k] = v
        sym_inputs = list(args)
        if sym_kwargs:
            canon = opdef.canon_attrs(attrs)
            if opdef.key_var_num_args and opdef.key_var_num_args not in attrs:
                # named-kwarg composition not meaningful for varargs ops
                raise MXNetError(
                    "%s: pass variable-arity inputs positionally" % opdef.name
                )
            arg_names = opdef.list_arguments(canon)
            merged = [None] * len(arg_names)
            for i, s in enumerate(sym_inputs):
                merged[i] = s
            for k, v in sym_kwargs.items():
                if k not in arg_names:
                    raise MXNetError("%s: unknown input %s" % (opdef.name, k))
                merged[arg_names.index(k)] = v
            sym_inputs = merged
        return _create_symbol(opdef.name, sym_inputs, attrs, name=name, attr=attr)

    fn.__name__ = opdef.name
    fn.__doc__ = opdef.docstring()
    return fn


def _init_symbol_module():
    module = sys.modules[__name__]
    for name, opdef in list(_registry._REGISTRY.items()):
        if not hasattr(module, name):
            setattr(module, name, _make_symbol_function(opdef))


_init_symbol_module()


def zeros(shape, dtype=None, name=None, **kwargs):
    return _create_symbol(
        "_zeros", [], {"shape": shape, "dtype": dtype or "float32"}, name=name
    )


def ones(shape, dtype=None, name=None, **kwargs):
    return _create_symbol(
        "_ones", [], {"shape": shape, "dtype": dtype or "float32"}, name=name
    )


def arange(start, stop=None, step=1.0, repeat=1, name=None, dtype=None):
    return _create_symbol(
        "_arange",
        [],
        {"start": start, "stop": stop, "step": step, "repeat": repeat,
         "dtype": dtype or "float32"},
        name=name,
    )
