"""int8 weight quantization for serving (experimental).

``MXTPU_SERVE_QUANT=int8`` (or ``Predictor(quant="int8")``) stores
dense/conv weight matrices as int8 plus a per-output-channel float
scale computed at load (symmetric, max-abs calibration), and
dequantizes to bf16-rounded values at bind time — activations stay in
the executor's compute dtype (bf16 on TPU). Biases, norms, and
1-D/embedding params pass through untouched.

This is a weight-memory/bandwidth optimization (4x smaller resident
weights on the host side, bf16-equivalent numerics on device); the
parity gate lives in benchmarks/serving_bench.py — top-1 agreement
vs the unquantized model must be ≥ 99% on the bench model.
"""
from __future__ import annotations

import numpy as np

_MIN_QUANT_ELEMS = 64  # skip tiny tensors: no memory win, pure noise


class QuantizedTensor(object):
    """int8 data + per-output-channel scales for one weight tensor.

    Axis 0 is the output-channel axis for both FullyConnected weights
    ``[out, in]`` and Convolution weights ``[out, in, kh, kw]``."""

    __slots__ = ("q", "scale", "shape")

    def __init__(self, q, scale, shape):
        self.q = q
        self.scale = scale
        self.shape = shape

    @classmethod
    def quantize(cls, arr):
        arr = np.asarray(arr, np.float32)
        flat = arr.reshape(arr.shape[0], -1)
        amax = np.max(np.abs(flat), axis=1)
        scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
        q = np.clip(np.rint(flat / scale[:, None]), -127, 127).astype(
            np.int8)
        return cls(q, scale, arr.shape)

    def dequantize(self):
        """int8 * scale, rounded through bf16 (the serving activation
        dtype) so the dequantized weights are exactly representable on
        the bf16 path."""
        import jax.numpy as jnp

        w = self.q.astype(np.float32) * self.scale[:, None]
        w = np.asarray(jnp.asarray(w, jnp.bfloat16).astype(jnp.float32))
        return w.reshape(self.shape)

    @property
    def nbytes(self):
        return self.q.nbytes + self.scale.nbytes


def _quantizable(name, arr):
    shape = tuple(arr.shape)
    if len(shape) not in (2, 4):  # FC [out,in] / conv [out,in,kh,kw]
        return False
    if int(np.prod(shape)) < _MIN_QUANT_ELEMS:
        return False
    return name.endswith("weight")


def quantize_arg_params(arg_params):
    """Map a {name: NDArray|ndarray} param dict to one where every
    quantizable weight is a QuantizedTensor; everything else passes
    through unchanged."""
    out = {}
    for name, arr in arg_params.items():
        raw = arr.asnumpy() if hasattr(arr, "asnumpy") else np.asarray(arr)
        if _quantizable(name, raw):
            out[name] = QuantizedTensor.quantize(raw)
        else:
            out[name] = arr
    return out


def maybe_dequantize(arr):
    """Numpy view of a param that may or may not be quantized."""
    if isinstance(arr, QuantizedTensor):
        return arr.dequantize()
    return arr.asnumpy() if hasattr(arr, "asnumpy") else np.asarray(arr)


def top1_agreement(logits_a, logits_b):
    """Fraction of rows whose argmax agrees — the parity-gate metric."""
    a = np.argmax(np.asarray(logits_a), axis=-1).reshape(-1)
    b = np.argmax(np.asarray(logits_b), axis=-1).reshape(-1)
    return float(np.mean(a == b))
