"""Continuous/dynamic batching over the AOT predict executor pool.

The serving half of SURVEY.md §3.5's static-shape discipline: requests
arrive one at a time, the dispatcher coalesces whatever is in flight
into the smallest covering batch bucket (serving/buckets.py — the same
rule BucketSentenceIter applies to sentence lengths), pads, dispatches
one AOT-compiled executable call, and scatters rows back per request.
The TensorFlow-Serving insight (PAPERS.md, arXiv:1605.08695): batching
amortizes dispatch overhead and keeps the chip saturated without
holding early requests hostage — a request waits at most
``MXTPU_SERVE_BATCH_TIMEOUT_MS`` for co-riders.

Telemetry (scrapeable via telemetry.fleet.MetricsServer, summarized by
tools/perf_doctor.py):

    serve.queue_wait_seconds   histogram — enqueue → dispatch
    serve.e2e_seconds          histogram — enqueue → result ready
    serve.queue_depth          gauge     — requests waiting
    serve.batch_occupancy      gauge     — filled rows / bucket rows
    serve.requests             counter   — completed requests
    serve.batches              counter   — dispatched device calls
    serve.pad_rows             counter   — wasted padding rows
"""
from __future__ import annotations

import collections
import os
import threading
import time

import numpy as np

from .. import telemetry as _tm
from ..base import MXNetError
from . import buckets as _buckets

_H_QUEUE_WAIT = _tm.histogram(
    "serve.queue_wait_seconds", "request enqueue -> batch dispatch")
_H_E2E = _tm.histogram(
    "serve.e2e_seconds", "request enqueue -> result ready")
_G_QUEUE_DEPTH = _tm.gauge("serve.queue_depth", "requests waiting")
_G_OCCUPANCY = _tm.gauge(
    "serve.batch_occupancy", "filled rows / bucket rows of last batch")
_C_REQUESTS = _tm.counter("serve.requests", "completed requests")
_C_BATCHES = _tm.counter("serve.batches", "dispatched device calls")
_C_PAD_ROWS = _tm.counter("serve.pad_rows", "wasted padding rows")


class ServeClosed(MXNetError):
    """Raised by submit() once the engine is draining or stopped."""


class _Request(object):
    __slots__ = ("inputs", "outputs", "error", "done", "t_enqueue",
                 "t_dispatch", "sig")

    def __init__(self, inputs, sig):
        self.inputs = inputs
        self.sig = sig
        self.outputs = None
        self.error = None
        self.done = threading.Event()
        self.t_enqueue = time.perf_counter()
        self.t_dispatch = None

    # future surface ---------------------------------------------------
    def result(self, timeout=None):
        if not self.done.wait(timeout):
            raise TimeoutError("serving request timed out")
        if self.error is not None:
            raise self.error
        return self.outputs


def _env_int(name, default):
    try:
        return int(os.environ.get(name, str(default)))
    except ValueError:
        return default


def _env_float(name, default):
    try:
        return float(os.environ.get(name, str(default)))
    except ValueError:
        return default


class ServingEngine(object):
    """Request queue + dispatcher thread over a Predictor.

    Parameters
    ----------
    predictor : predict.Predictor — the model; ``compile()`` is called
        for every batch bucket at start() so steady state never traces.
    max_batch : int — batch cap (default MXTPU_SERVE_MAX_BATCH or 8).
        The bucket ladder is powers of two up to the cap.
    batch_timeout_ms : float — how long the head-of-line request waits
        for co-riders (default MXTPU_SERVE_BATCH_TIMEOUT_MS or 2.0).

    Requests are per-example (no batch axis); the engine owns the batch
    axis. Only requests with identical per-example shape/dtype
    signatures coalesce; mixed streams split into per-signature batches.
    """

    def __init__(self, predictor, max_batch=None, batch_timeout_ms=None):
        self.predictor = predictor
        self.max_batch = max_batch if max_batch is not None else _env_int(
            "MXTPU_SERVE_MAX_BATCH", 8)
        timeout_ms = (batch_timeout_ms if batch_timeout_ms is not None
                      else _env_float("MXTPU_SERVE_BATCH_TIMEOUT_MS", 2.0))
        self.batch_timeout = timeout_ms / 1000.0
        self.batch_buckets = _buckets.bucket_ladder(self.max_batch)
        self._queue = collections.deque()
        self._lock = threading.Lock()
        self._have_work = threading.Condition(self._lock)
        self._draining = False
        self._stopped = True
        self._thread = None
        self._input_names = sorted(predictor._input_shapes)

    # -- lifecycle -----------------------------------------------------
    def start(self, precompile=True):
        """Spawn the dispatcher. ``precompile`` AOT-compiles every batch
        bucket first so the request path never traces (warm via
        MXTPU_COMPILE_CACHE)."""
        if self._thread is not None:
            return self
        if precompile:
            feature_shapes = {
                n: tuple(self.predictor._input_shapes[n][1:])
                for n in self._input_names
            }
            self.precompile(feature_shapes)
        self._stopped = False
        self._draining = False
        self._thread = threading.Thread(
            target=self._run, name="mxtpu-serve-dispatch", daemon=True)
        self._thread.start()
        return self

    def precompile(self, feature_shapes):
        """Compile the forward for every batch bucket × the given
        per-example feature shapes ({input_name: shape-sans-batch})."""
        self.predictor.compile([
            {n: (b,) + tuple(s) for n, s in feature_shapes.items()}
            for b in self.batch_buckets
        ])

    def drain(self, timeout=30.0):
        """Graceful shutdown: reject new work, finish everything queued
        and in flight, stop the dispatcher. Idempotent."""
        with self._lock:
            self._draining = True
            self._have_work.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout)
            self._thread = None
        self._stopped = True

    # -- client surface ------------------------------------------------
    def submit(self, **inputs):
        """Enqueue one request ({input_name: per-example array}, no
        batch axis). Returns a future with ``.result(timeout)`` →
        list of per-request output arrays."""
        arrays = {}
        for name in self._input_names:
            if name not in inputs:
                raise MXNetError("request missing input %s" % name)
            arrays[name] = np.asarray(inputs[name])
        sig = tuple(
            (n, arrays[n].shape, str(arrays[n].dtype))
            for n in self._input_names)
        req = _Request(arrays, sig)
        with self._lock:
            if self._draining or self._stopped:
                raise ServeClosed(
                    "serving engine is draining; not accepting new work")
            self._queue.append(req)
            _G_QUEUE_DEPTH.set(len(self._queue))
            self._have_work.notify()
        return req

    def __call__(self, timeout=None, **inputs):
        """Synchronous convenience: submit + wait."""
        return self.submit(**inputs).result(timeout)

    # -- dispatcher ----------------------------------------------------
    def _take_batch(self):
        """Under the lock: wait for work, then pop up to max_batch
        same-signature requests (head-of-line's signature; preserving
        order for the rest)."""
        with self._lock:
            while not self._queue:
                if self._draining:
                    return None
                self._have_work.wait(0.1)
            head = self._queue[0]
            deadline = head.t_enqueue + self.batch_timeout
            while (len(self._queue) < self.max_batch
                   and not self._draining):
                remaining = deadline - time.perf_counter()
                if remaining <= 0:
                    break
                self._have_work.wait(remaining)
            batch = []
            rest = collections.deque()
            while self._queue and len(batch) < self.max_batch:
                req = self._queue.popleft()
                if req.sig == head.sig:
                    batch.append(req)
                else:
                    rest.append(req)
            rest.extend(self._queue)
            self._queue = rest
            _G_QUEUE_DEPTH.set(len(self._queue))
            return batch

    def _dispatch(self, batch):
        n = len(batch)
        bucket = _buckets.covering_value(self.batch_buckets, n)
        if bucket is None:  # n <= max_batch by construction
            bucket = self.max_batch
        now = time.perf_counter()
        for req in batch:
            req.t_dispatch = now
            _H_QUEUE_WAIT.observe(now - req.t_enqueue)
        feeds = {
            name: _buckets.pad_batch(
                [req.inputs[name] for req in batch], bucket)
            for name in self._input_names
        }
        try:
            outs = self.predictor.predict_batch(**feeds)
        except Exception as e:  # surface per request, keep serving
            for req in batch:
                req.error = e
                req.done.set()
            return
        per_req = _buckets.scatter_rows(outs, n)
        _C_BATCHES.inc()
        _C_PAD_ROWS.inc(bucket - n)
        _G_OCCUPANCY.set(n / float(bucket))
        done = time.perf_counter()
        for req, rows in zip(batch, per_req):
            req.outputs = rows
            _H_E2E.observe(done - req.t_enqueue)
            req.done.set()
        _C_REQUESTS.inc(n)

    def _run(self):
        while True:
            batch = self._take_batch()
            if batch is None:
                return  # draining and queue empty
            self._dispatch(batch)
