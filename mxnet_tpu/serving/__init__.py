"""Production inference serving (ROADMAP item 1).

The predict-side analog of the training stack: an AOT-compiled
executor pool over a small ladder of padded bucket shapes
(``predict.Predictor.compile``), fed by a continuous-batching request
queue (``serving.engine.ServingEngine``) and — for autoregressive
models — a slot-based KV-cached decode loop
(``serving.decode.GenerationEngine``). Shape bucketing lives in
``serving.buckets`` and is shared with training (rnn/io.py,
module/bucketing_module.py): one smallest-covering-bucket
implementation for both sides.

Import is jax-light: the engine/decode modules (which pull in jax)
load lazily on first attribute access.
"""
from __future__ import annotations

from . import buckets  # noqa: F401  (pure numpy/bisect — always safe)

_LAZY = {
    "engine": ".engine",
    "decode": ".decode",
    "quant": ".quant",
    "ServingEngine": ".engine",
    "ServeClosed": ".engine",
    "GenerationEngine": ".decode",
}

__all__ = ["buckets"] + sorted(_LAZY)


def __getattr__(name):
    if name in _LAZY:
        import importlib

        mod = importlib.import_module(_LAZY[name], __name__)
        leaf = _LAZY[name].lstrip(".")
        if name == leaf:
            value = mod
        else:
            value = getattr(mod, name)
        globals()[name] = value
        return value
    raise AttributeError("module %r has no attribute %r" % (__name__, name))
