"""Slot-based continuous batching for KV-cached autoregressive decode.

The "continuous" half of continuous batching (Orca-style iteration
scheduling): a fixed pool of KV-cache slots decodes in lock-step — one
shape-stable ``decode_step`` per token for the whole pool — while new
requests join mid-flight through a bucketed ``prefill`` that scatters
their K/V into freed slots without disturbing the others. Finished
sequences (EOS or token budget) release their slot immediately; the
next admission reuses it. Nothing ever changes shape, so after the
per-bucket warmup the anatomy recompile detector stays at zero.

Works with any model factory exposing the
``models.transformer.transformer_lm_serving`` contract:
``init_cache(slots)``, ``prefill(params, cache, tokens, slots,
lengths)``, ``decode_step(params, cache, tokens)``. Long prompts
prefill through ``parallel/ring_attention.py`` when a mesh with an
'sp' axis is supplied.

Env knobs: ``MXTPU_SERVE_SLOTS`` (decode batch, default 4),
``MXTPU_SERVE_MAX_LEN`` (KV window, model-side default).
"""
from __future__ import annotations

import collections
import os
import threading
import time

import numpy as np

from .. import telemetry as _tm
from ..base import MXNetError
from . import buckets as _buckets
from .engine import ServeClosed

_H_PREFILL = _tm.histogram(
    "serve.prefill_seconds", "prefill dispatch wall time")
_H_DECODE = _tm.histogram(
    "serve.decode_step_seconds", "one lock-step decode step")
_H_GEN_WAIT = _tm.histogram(
    "serve.gen_queue_wait_seconds", "generation request enqueue -> admit")
_H_GEN_E2E = _tm.histogram(
    "serve.gen_e2e_seconds", "generation request enqueue -> done")
_G_GEN_QUEUE = _tm.gauge("serve.gen_queue_depth", "generation requests waiting")
_G_SLOTS = _tm.gauge(
    "serve.slot_occupancy", "active decode slots / total slots")
_C_TOKENS = _tm.counter("serve.tokens", "generated tokens")
_C_GEN_REQS = _tm.counter("serve.gen_requests", "completed generations")
_C_ADMITTED = _tm.counter("serve.admissions", "prefill admissions")


class _GenRequest(object):
    __slots__ = ("prompt", "max_new", "eos_id", "tokens", "error", "done",
                 "t_enqueue", "t_admit")

    def __init__(self, prompt, max_new, eos_id):
        self.prompt = np.asarray(prompt, np.int32).reshape(-1)
        if self.prompt.size == 0:
            raise MXNetError("empty prompt")
        self.max_new = int(max_new)
        self.eos_id = eos_id
        self.tokens = []  # generated continuation
        self.error = None
        self.done = threading.Event()
        self.t_enqueue = time.perf_counter()
        self.t_admit = None

    def result(self, timeout=None):
        if not self.done.wait(timeout):
            raise TimeoutError("generation request timed out")
        if self.error is not None:
            raise self.error
        return list(self.tokens)


class _Slot(object):
    __slots__ = ("request", "last_token")

    def __init__(self):
        self.request = None
        self.last_token = 0


_ENGINE_IDS = iter(range(1 << 30))


class GenerationEngine(object):
    """Continuous-batching decode loop over a KV-cache model.

    Parameters
    ----------
    params : model param tree (``transformer_lm(...)[0]()``-shaped)
    model : ``(init_cache, prefill, decode_step)`` from
        ``transformer_lm_serving`` (or anything with that contract)
    slots : decode batch size (default MXTPU_SERVE_SLOTS or 4)
    max_len : KV window — only used to derive prefill length buckets
    mesh : optional jax mesh with an 'sp' axis; routes prefill
        attention through ring attention (long-context path)
    """

    def __init__(self, params, model, slots=None, max_len=256, mesh=None):
        import jax

        init_cache, prefill, decode_step = model
        self.slots = slots if slots is not None else int(
            os.environ.get("MXTPU_SERVE_SLOTS", "4"))
        env_max_len = int(os.environ.get("MXTPU_SERVE_MAX_LEN", "0"))
        self.max_len = env_max_len if env_max_len > 0 else max_len
        self.params = params
        self.mesh = mesh
        self.len_buckets = _buckets.bucket_ladder(self.max_len, base=8)
        self.count_buckets = _buckets.bucket_ladder(self.slots)
        # one extra scratch row: admission pads its slot-index vector
        # with the scratch, so a partially-filled prefill bucket never
        # clobbers a live slot's cache row
        self._scratch = self.slots
        self._cache = init_cache(self.slots + 1)
        self._prefill_fn = jax.jit(
            lambda p, c, t, s, l: prefill(p, c, t, s, l, mesh=mesh),
            donate_argnums=1)
        self._decode_fn = jax.jit(decode_step, donate_argnums=1)
        self._slot_state = [_Slot() for _ in range(self.slots)]
        self._free = list(range(self.slots))
        self._pending = collections.deque()
        self._lock = threading.Lock()
        self._have_work = threading.Condition(self._lock)
        self._draining = False
        self._thread = None
        # recompile accounting: one anatomy program uid per (engine,
        # bucket) — each engine instance jits fresh programs, so each
        # bucket's first compile is warmup-exempt and any shape drift
        # afterwards counts as a steady-state recompile
        self._engine_id = next(_ENGINE_IDS)
        self._seen_sigs = set()

    # -- recompile detector hookup ------------------------------------
    def _note_dispatch(self, kind, shape):
        sig = ((kind, tuple(shape), "int32", "serve"),)
        if sig not in self._seen_sigs:
            self._seen_sigs.add(sig)
            _tm.anatomy.note_plan_miss("serve:e%d:%s:%s" % (
                self._engine_id, kind,
                "x".join(str(d) for d in shape)), sig)

    # -- compile-ahead -------------------------------------------------
    def compile(self, prompt_lengths=None):
        """Warm every (count-bucket × length-bucket) prefill program and
        the decode step, so the serving loop never traces. With
        MXTPU_COMPILE_CACHE set the XLA executables come from the
        persistent cache."""
        import jax.numpy as jnp

        lengths = prompt_lengths or self.len_buckets
        len_set = sorted({
            _buckets.covering_value(self.len_buckets, int(l)) for l in lengths
            if _buckets.covering_value(self.len_buckets, int(l)) is not None})
        for nb in self.count_buckets:
            for T in len_set:
                toks = jnp.zeros((nb, T), jnp.int32)
                slot_ids = jnp.full((nb,), self._scratch, jnp.int32)
                lens = jnp.ones((nb,), jnp.int32)
                self._note_dispatch("prefill", (nb, T))
                self._cache, _ = self._prefill_fn(
                    self.params, self._cache, toks, slot_ids, lens)
        self._note_dispatch("decode", (self.slots + 1,))
        self._cache, _ = self._decode_fn(
            self.params, self._cache,
            jnp.zeros((self.slots + 1,), jnp.int32))
        # warmup wrote junk into the scratch row only; live slots are
        # untouched and the pool starts empty anyway
        return self

    # -- lifecycle -----------------------------------------------------
    def start(self, precompile=True):
        if self._thread is not None:
            return self
        if precompile:
            self.compile()
        self._draining = False
        self._thread = threading.Thread(
            target=self._run, name="mxtpu-serve-decode", daemon=True)
        self._thread.start()
        return self

    def drain(self, timeout=60.0):
        """Stop admitting, finish every queued + in-flight generation,
        stop the loop. Idempotent."""
        with self._lock:
            self._draining = True
            self._have_work.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout)
            self._thread = None

    # -- client surface ------------------------------------------------
    def submit(self, prompt, max_new=16, eos_id=None):
        req = _GenRequest(prompt, max_new, eos_id)
        if req.prompt.size > self.max_len:
            raise MXNetError(
                "prompt length %d exceeds KV window %d"
                % (req.prompt.size, self.max_len))
        with self._lock:
            if self._draining:
                raise ServeClosed(
                    "generation engine is draining; not accepting new work")
            self._pending.append(req)
            _G_GEN_QUEUE.set(len(self._pending))
            self._have_work.notify()
        return req

    def generate(self, prompt, max_new=16, eos_id=None, timeout=None):
        """Synchronous convenience: submit + wait."""
        return self.submit(prompt, max_new, eos_id).result(timeout)

    # -- scheduler -----------------------------------------------------
    @property
    def active(self):
        return sum(1 for s in self._slot_state if s.request is not None)

    def step(self):
        """One scheduler iteration: admit pending requests into free
        slots (bucketed prefill), then advance every active sequence by
        one token. Returns True if any work happened. The background
        thread calls this in a loop; tests may drive it directly."""
        admitted = self._admit()
        decoded = self._decode_tick()
        return admitted or decoded

    def _admit(self):
        with self._lock:
            if not self._pending or not self._free:
                return False
            take = min(len(self._pending), len(self._free))
            reqs = [self._pending.popleft() for _ in range(take)]
            slot_ids = [self._free.pop(0) for _ in range(take)]
            _G_GEN_QUEUE.set(len(self._pending))
        import jax.numpy as jnp

        n = len(reqs)
        nb = _buckets.covering_value(self.count_buckets, n)
        T = _buckets.covering_value(
            self.len_buckets, max(r.prompt.size for r in reqs))
        toks = np.zeros((nb, T), np.int32)
        lens = np.ones((nb,), np.int32)
        ids = np.full((nb,), self._scratch, np.int32)
        now = time.perf_counter()
        for i, (req, sid) in enumerate(zip(reqs, slot_ids)):
            toks[i, :req.prompt.size] = req.prompt
            lens[i] = req.prompt.size
            ids[i] = sid
            req.t_admit = now
            _H_GEN_WAIT.observe(now - req.t_enqueue)
        self._note_dispatch("prefill", (nb, T))
        t0 = time.perf_counter()
        self._cache, last = self._prefill_fn(
            self.params, self._cache, jnp.asarray(toks), jnp.asarray(ids),
            jnp.asarray(lens))
        last = np.asarray(last)
        _H_PREFILL.observe(time.perf_counter() - t0)
        _C_ADMITTED.inc(n)
        for i, (req, sid) in enumerate(zip(reqs, slot_ids)):
            slot = self._slot_state[sid]
            slot.request = req
            slot.last_token = int(np.argmax(last[i]))
            self._finish_token(sid, slot.last_token)
        _G_SLOTS.set(self.active / float(self.slots))
        return True

    def _finish_token(self, sid, token):
        """Record one generated token for a slot; evict on EOS or
        budget. Eviction is host-side only — prefill fully resets a
        ring row on reuse, so freeing a slot costs zero device work."""
        slot = self._slot_state[sid]
        req = slot.request
        req.tokens.append(token)
        _C_TOKENS.inc()
        if (len(req.tokens) >= req.max_new
                or (req.eos_id is not None and token == req.eos_id)):
            slot.request = None
            req.done.set()
            _H_GEN_E2E.observe(time.perf_counter() - req.t_enqueue)
            _C_GEN_REQS.inc()
            with self._lock:
                self._free.append(sid)
            _G_SLOTS.set(self.active / float(self.slots))

    def _decode_tick(self):
        import jax.numpy as jnp

        active = [i for i, s in enumerate(self._slot_state)
                  if s.request is not None]
        if not active:
            return False
        toks = np.zeros((self.slots + 1,), np.int32)
        for i in active:
            toks[i] = self._slot_state[i].last_token
        self._note_dispatch("decode", (self.slots + 1,))
        t0 = time.perf_counter()
        self._cache, logits = self._decode_fn(
            self.params, self._cache, jnp.asarray(toks))
        logits = np.asarray(logits)
        _H_DECODE.observe(time.perf_counter() - t0)
        for i in active:
            slot = self._slot_state[i]
            nxt = int(np.argmax(logits[i]))
            slot.last_token = nxt
            self._finish_token(i, nxt)
        return True

    def _run(self):
        while True:
            if not self.step():
                with self._lock:
                    if self._draining and not self._pending:
                        return
                    self._have_work.wait(0.05)
