"""Shape bucketing shared by training and serving.

One implementation of the smallest-covering-bucket discipline
(SURVEY.md §3.5: a few static shapes, one compiled program each).
``rnn.io.BucketSentenceIter`` uses it to pad sentences into sequence
buckets, ``module.BucketingModule.covering_bucket_key`` uses it to
route odd-length batches to an already-compiled bucket, and
``serving.engine`` uses it to coalesce request batches into the
smallest compiled batch bucket. Pure numpy/bisect — no jax import.
"""
from __future__ import annotations

import bisect

import numpy as np


def bucket_ladder(cap, base=1):
    """Powers-of-two ladder up to and including ``cap``:
    bucket_ladder(8) -> [1, 2, 4, 8]; a non-power cap is appended so
    the ladder always covers it (bucket_ladder(6) -> [1, 2, 4, 6])."""
    if cap < 1:
        raise ValueError("bucket ladder cap must be >= 1, got %r" % (cap,))
    ladder = []
    b = max(1, base)
    while b < cap:
        ladder.append(b)
        b *= 2
    ladder.append(cap)
    return ladder


def smallest_covering(buckets, size):
    """Index of the smallest bucket >= size, or None if nothing covers.

    ``buckets`` must be sorted ascending. This is THE bucket-selection
    rule: the same bisect both BucketSentenceIter and the serving
    queue apply."""
    slot = bisect.bisect_left(buckets, size)
    if slot == len(buckets):
        return None
    return slot


def covering_value(buckets, size):
    """The smallest bucket value >= size, or None."""
    slot = smallest_covering(buckets, size)
    return None if slot is None else buckets[slot]


def pad_to_width(row, width, fill):
    """Pad a 1-D sequence into a fixed-width numpy row (training-side
    sentence padding)."""
    row = np.asarray(row)
    out = np.full((width,), fill, dtype=row.dtype)
    out[: len(row)] = row
    return out


def pad_batch(rows, bucket_batch, fill=0):
    """Stack per-request arrays (each ``[feature...]``, no batch axis)
    into a ``[bucket_batch, feature...]`` array, padding the trailing
    rows with ``fill`` (serving-side batch coalescing). Returns the
    padded array; callers slice the first ``len(rows)`` outputs back."""
    if not rows:
        raise ValueError("pad_batch needs at least one row")
    first = np.asarray(rows[0])
    if len(rows) > bucket_batch:
        raise ValueError(
            "pad_batch: %d rows exceed bucket batch %d"
            % (len(rows), bucket_batch))
    out = np.full((bucket_batch,) + first.shape, fill, dtype=first.dtype)
    for i, r in enumerate(rows):
        r = np.asarray(r)
        if r.shape != first.shape or r.dtype != first.dtype:
            raise ValueError(
                "pad_batch: row %d shape/dtype %s/%s differs from row 0 "
                "%s/%s" % (i, r.shape, r.dtype, first.shape, first.dtype))
        out[i] = r
    return out


def scatter_rows(batched, n):
    """Inverse of pad_batch: split the first ``n`` rows of each output
    array back out per request. ``batched`` is a list of
    ``[bucket_batch, ...]`` arrays; returns a list of n per-request
    lists."""
    return [[np.asarray(o)[i] for o in batched] for i in range(n)]
