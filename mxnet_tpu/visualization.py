"""Network visualization: layer summary table + graphviz plot.

Capability parity with reference ``python/mxnet/visualization.py``
(print_summary, plot_network), re-designed to walk the Symbol graph
directly instead of round-tripping through graph JSON, and to count
parameters from the actually-inferred argument shapes rather than the
reference's per-op-type arithmetic (which under-counts anything it has
no special case for).
"""
from __future__ import annotations

from .symbol import Symbol, _topo_order


def _walk(symbol):
    """(nodes in topo order, head node set) for a Symbol."""
    nodes = _topo_order([n for n, _ in symbol._outputs])
    heads = {id(n) for n, _ in symbol._outputs}
    return nodes, heads


def _fmt_shape(shape):
    return "x".join(str(d) for d in shape)


def print_summary(symbol, shape=None, line_length=120,
                  positions=(0.44, 0.64, 0.74, 1.0)):
    """Print a Keras-style table: layer, output shape, #params, inputs.

    Parameter counts are exact: every variable feeding a layer (weights,
    biases, gammas...) contributes its inferred size."""
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be Symbol")
    out_shapes = {}
    arg_shapes = {}
    if shape is not None:
        internals = symbol.get_internals()
        _, shapes, _ = internals.infer_shape(**shape)
        if shapes is None:
            raise ValueError("Input shape is incomplete")
        out_shapes = dict(zip(internals.list_outputs(), shapes))
        arg_shapes = dict(zip(symbol.list_arguments(),
                              symbol.infer_shape(**shape)[0]))

    cols = [int(line_length * p) if p <= 1 else p for p in positions]

    def emit(fields):
        line = ""
        for width, field in zip(cols, fields):
            line = (line + str(field))[:width].ljust(width)
        print(line)

    nodes, heads = _walk(symbol)
    first = nodes[0] if nodes else None
    # inputs (given shapes, labels) are fed, not learned
    non_params = set(shape or ()) | {
        n for n in arg_shapes if n.endswith("label")}

    print("_" * line_length)
    emit(["Layer (type)", "Output Shape", "Param #", "Previous Layer"])
    print("=" * line_length)

    total = 0
    rows = []
    for node in nodes:
        if node.is_variable and node is not first and id(node) not in heads:
            continue  # parameters are counted into their layer's row
        if node.is_variable:
            op_name = "null"
            prev = []
            n_params = 0
        else:
            op_name = node.op.name
            prev = [c.name for (c, _i) in node.inputs
                    if not c.is_variable or id(c) in heads]
            # exact: sum the sizes of this node's parameter variables
            n_params = 0
            for (c, _i) in node.inputs:
                if c.is_variable and c.name in arg_shapes and \
                        c.name not in non_params:
                    s = arg_shapes[c.name]
                    size = 1
                    for d in s:
                        size *= int(d)
                    n_params += size
        key = node.name if node.is_variable else node.name + "_output"
        oshape = out_shapes.get(key, ())
        oshape = oshape[1:] if oshape else []
        rows.append((node, op_name, oshape, n_params, prev))
        total += n_params

    for i, (node, op_name, oshape, n_params, prev) in enumerate(rows):
        emit(["%s(%s)" % (node.name, op_name), _fmt_shape(oshape),
              n_params, prev[0] if prev else ""])
        for extra in prev[1:]:
            emit(["", "", "", extra])
        print(("=" if i == len(rows) - 1 else "_") * line_length)
    print("Total params: %s" % total)
    print("_" * line_length)
    return total


_PARAM_SUFFIXES = ("_weight", "_bias", "_gamma", "_beta",
                   "_moving_var", "_moving_mean")

# fillcolor + label builder per op family (colorbrewer Set3)
_STYLE = {
    "Convolution": ("#fb8072", lambda a: "Convolution\n%s/%s, %s" % (
        a.get("kernel", ""), a.get("stride", "1"), a.get("num_filter", ""))),
    "FullyConnected": ("#fb8072", lambda a: "FullyConnected\n%s"
                       % a.get("num_hidden", "")),
    "Activation": ("#ffffb3", lambda a: "Activation\n%s"
                   % a.get("act_type", "")),
    "LeakyReLU": ("#ffffb3", lambda a: "LeakyReLU\n%s"
                  % a.get("act_type", "")),
    "BatchNorm": ("#bebada", None),
    "Pooling": ("#80b1d3", lambda a: "Pooling\n%s, %s/%s" % (
        a.get("pool_type", ""), a.get("kernel", ""), a.get("stride", "1"))),
    "Concat": ("#fdb462", None),
    "Flatten": ("#fdb462", None),
    "Reshape": ("#fdb462", None),
    "Softmax": ("#b3de69", None),
}


def plot_network(symbol, title="plot", save_format="pdf", shape=None,
                 node_attrs=None, hide_weights=True):
    """Build a graphviz Digraph of the network (raises without graphviz)."""
    try:
        from graphviz import Digraph
    except ImportError as e:
        raise ImportError("Draw network requires graphviz library") from e
    if not isinstance(symbol, Symbol):
        raise TypeError("symbol must be a Symbol")

    out_shapes = {}
    if shape is not None:
        internals = symbol.get_internals()
        _, shapes, _ = internals.infer_shape(**shape)
        out_shapes = dict(zip(internals.list_outputs(), shapes))

    base_attr = {"shape": "box", "fixedsize": "true", "width": "1.3",
                 "height": "0.8034", "style": "filled"}
    base_attr.update(node_attrs or {})
    dot = Digraph(name=title, format=save_format)

    nodes, _heads = _walk(symbol)
    hidden = set()
    for node in nodes:
        attr = dict(base_attr)
        if node.is_variable:
            if hide_weights and node.name.endswith(_PARAM_SUFFIXES):
                hidden.add(node.name)
                continue
            attr.update(shape="oval", fillcolor="#8dd3c7")
            dot.node(name=node.name, label=node.name, **attr)
            continue
        color, labeler = _STYLE.get(node.op.name, ("#fccde5", None))
        attrs = {k: str(v) for k, v in node.attrs.items()}
        label = labeler(attrs) if labeler else node.name
        attr["fillcolor"] = color
        dot.node(name=node.name, label=label, **attr)

    for node in nodes:
        if node.is_variable:
            continue
        for (src, _i) in node.inputs:
            if src.name in hidden:
                continue
            edge = {"dir": "back", "arrowtail": "open"}
            key = src.name if src.is_variable else src.name + "_output"
            if key in out_shapes:
                edge["label"] = _fmt_shape(out_shapes[key][1:])
            dot.edge(tail_name=node.name, head_name=src.name, **edge)
    return dot
