"""Torch interoperability — the torch plugin, reimplemented for TPU.

Parity: reference ``plugin/torch`` + ``python/mxnet/torch.py`` (N23):
run (Lua-)Torch tensor functions and nn modules as MXNet operators. Here
the host framework is PyTorch (CPU build baked into the image) and the
bridge is the CustomOp host: torch computations execute as host
callbacks (``jax.pure_callback`` under the hood), with gradients
threaded through ``torch.autograd`` — so a torch ``nn.Module`` can sit
in the middle of an otherwise XLA-compiled graph.

Two surfaces:

- function namespace: ``mx.th.exp(x)``, ``mx.th.mm(a, b)`` ... — any
  ``torch.*`` function applied to NDArrays (reference torch.py generated
  wrappers).
- ``wrap_module(nn_module)`` → a symbol factory: embeds the module as a
  trainable-free graph op with exact torch forward/backward (reference
  TorchModule op, ``plugin/torch/torch_module-inl.h``).
"""
from __future__ import annotations

import numpy as np

from . import operator
from . import symbol as sym_mod
from .base import MXNetError
from .ndarray import NDArray, array


def _torch():
    try:
        import torch as _t
        return _t
    except ImportError:
        raise MXNetError(
            "torch interop requires pytorch (baked into this image)")


def _to_torch(x):
    t = _torch()
    if isinstance(x, NDArray):
        # copy: jax buffers are read-only and torch requires writable
        return t.from_numpy(np.array(x.asnumpy()))
    if isinstance(x, np.ndarray):
        return t.from_numpy(np.array(x))
    return x


def _from_torch(v):
    t = _torch()
    if isinstance(v, t.Tensor):
        return array(v.detach().cpu().numpy())
    return v


def __getattr__(name):
    """mx.th.<fn>: call torch.<fn> on NDArrays (PEP 562 module attr)."""
    try:
        t = _torch()
    except MXNetError as e:
        # PEP 562 contract: missing attributes must raise AttributeError
        # so hasattr()/getattr(default) degrade instead of crashing
        raise AttributeError(str(e))
    fn = getattr(t, name, None)
    if fn is None or not callable(fn):
        raise AttributeError("torch has no function %r" % name)

    def wrapper(*args, **kwargs):
        targs = [_to_torch(a) for a in args]
        tkwargs = {k: _to_torch(v) for k, v in kwargs.items()}
        out = fn(*targs, **tkwargs)
        if isinstance(out, (list, tuple)):
            return type(out)(_from_torch(v) for v in out)
        return _from_torch(out)

    wrapper.__name__ = name
    return wrapper


# --------------------------------------------------------------------------
# nn.Module as a graph op
# --------------------------------------------------------------------------

_WRAPPED = {}


def wrap_module(nn_module, name=None):
    """Register a torch ``nn.Module`` as a CustomOp and return a symbol
    factory ``f(data_sym, name=...) -> Symbol``.

    The module runs on the host in float32; forward saves the graph and
    backward calls ``torch.autograd.grad`` w.r.t. the op input AND the
    module's own parameters, applying parameter gradients directly to
    the torch module (torch params are NOT visible to the MXNet
    optimizer — matching the reference TorchModule's self-owned weights
    updated by its own updateParameters).
    """
    t = _torch()
    op_name = name or ("torch_%s_%d" % (
        type(nn_module).__name__.lower(), len(_WRAPPED)))
    if op_name in _WRAPPED:
        raise MXNetError("torch module op %r already registered" % op_name)
    _WRAPPED[op_name] = nn_module

    @operator.register(op_name)
    class _TorchModuleProp(operator.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=True)

        def list_arguments(self):
            return ["data"]

        def list_outputs(self):
            return ["output"]

        def infer_shape(self, in_shape):
            was_training = nn_module.training
            nn_module.eval()  # the zero-probe must not touch BN stats
            try:
                with t.no_grad():
                    probe = t.zeros(*[int(d) for d in in_shape[0]])
                    out = nn_module(probe)
            finally:
                nn_module.train(was_training)
            return [in_shape[0]], [tuple(out.shape)], []

        def create_operator(self, ctx, in_shapes, in_dtypes):
            class _TorchModuleOp(operator.CustomOp):
                def forward(self, is_train, req, in_data, out_data, aux):
                    x = _to_torch(in_data[0]).float()
                    # keep torch's train/eval semantics (Dropout,
                    # BatchNorm running stats) in sync with mx is_train
                    nn_module.train(bool(is_train))
                    if is_train:
                        x.requires_grad_(True)
                        y = nn_module(x)
                        self._saved = (x, y)
                    else:
                        with t.no_grad():
                            y = nn_module(x)
                    self.assign(out_data[0], req[0],
                                y.detach().cpu().numpy())

                def backward(self, req, out_grad, in_data, out_data,
                             in_grad, aux):
                    x, y = self._saved
                    gy = _to_torch(out_grad[0]).float()
                    params = [p for p in nn_module.parameters()
                              if p.requires_grad]
                    grads = t.autograd.grad(
                        y, [x] + params, grad_outputs=gy,
                        allow_unused=True, retain_graph=False)
                    gx = grads[0]
                    self.assign(
                        in_grad[0], req[0],
                        np.zeros(x.shape, np.float32) if gx is None
                        else gx.cpu().numpy())
                    with t.no_grad():
                        for p, g in zip(params, grads[1:]):
                            if g is not None:
                                if p.grad is None:
                                    p.grad = g.clone()
                                else:
                                    p.grad += g

            return _TorchModuleOp()

    def build(data_sym, name=None, **kwargs):
        return sym_mod.Custom(data_sym, op_type=op_name,
                              name=name or op_name, **kwargs)

    build.op_name = op_name
    return build
