"""Weight initializers.

Parity: reference ``python/mxnet/initializer.py`` (InitDesc, name-pattern
dispatch, Uniform/Normal/Orthogonal/Xavier/MSRAPrelu/Bilinear/LSTMBias/
Load/Mixed/Constant).
"""
from __future__ import annotations

import json
import re

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray


class InitDesc(str):
    """Name + attrs describing how to initialize a variable."""

    def __new__(cls, name, attrs=None, global_init=None):
        ret = super().__new__(cls, name)
        ret.attrs = attrs or {}
        ret.global_init = global_init
        return ret


_INIT_REGISTRY = {}


def register(klass):
    _INIT_REGISTRY[klass.__name__.lower()] = klass
    return klass


class Initializer:
    """Base: dispatch by name pattern (reference initializer.py:62+)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, str):
            raise TypeError("desc must be str or InitDesc")
        if isinstance(desc, InitDesc) and desc.global_init is None:
            desc.global_init = self
        init = getattr(desc, "attrs", {}).get("__init__", "")
        if init:
            klass, kwargs = json.loads(init)
            _INIT_REGISTRY[klass.lower()](**kwargs)._init_weight(desc, arr)
            return
        name = desc.lower()
        if name.endswith("upsampling"):
            self._init_bilinear(desc, arr)
        elif name.endswith("bias"):
            self._init_bias(desc, arr)
        elif name.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif name.endswith("beta"):
            self._init_beta(desc, arr)
        elif name.endswith("weight"):
            self._init_weight(desc, arr)
        elif name.endswith("moving_mean") or name.endswith("running_mean"):
            self._init_zero(desc, arr)
        elif name.endswith("moving_var") or name.endswith("running_var"):
            self._init_one(desc, arr)
        elif name.endswith("moving_inv_var"):
            self._init_zero(desc, arr)
        elif name.endswith("moving_avg"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    def _init_bilinear(self, _, arr):
        weight = np.zeros(arr.size, dtype=np.float32)
        shape = arr.shape
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(arr.size):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        arr[:] = weight.reshape(shape)

    def _init_zero(self, _, arr):
        arr[:] = 0.0

    def _init_one(self, _, arr):
        arr[:] = 1.0

    def _init_bias(self, _, arr):
        arr[:] = 0.0

    def _init_gamma(self, _, arr):
        arr[:] = 1.0

    def _init_beta(self, _, arr):
        arr[:] = 0.0

    def _init_weight(self, name, arr):
        raise NotImplementedError("must override _init_weight")

    def _init_default(self, name, _):
        raise ValueError(
            "Unknown initialization pattern for %s. Default init supports "
            "weight/bias/gamma/beta; use mx.sym.Variable(init=...) otherwise"
            % name
        )


@register
class Load:
    """Init from a dict of arrays (reference initializer.py:226)."""

    def __init__(self, param, default_init=None, verbose=False):
        self.param = {
            (k[4:] if k.startswith(("arg:", "aux:")) else k): v
            for k, v in param.items()
        }
        self.default_init = default_init
        self.verbose = verbose

    def __call__(self, name, arr):
        if name in self.param:
            if tuple(self.param[name].shape) != tuple(arr.shape):
                raise MXNetError("shape mismatch loading %s" % name)
            self.param[name].copyto(arr) if isinstance(
                self.param[name], NDArray
            ) else arr.__setitem__(slice(None), self.param[name])
        else:
            if self.default_init is None:
                raise MXNetError("no initializer for %s" % name)
            self.default_init(name, arr)


@register
class Mixed:
    """Pattern → initializer list (reference initializer.py:273)."""

    def __init__(self, patterns, initializers):
        if len(patterns) != len(initializers):
            raise MXNetError("patterns and initializers mismatched")
        self.map = list(zip([re.compile(p) for p in patterns], initializers))

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise MXNetError("no matching pattern for %s" % name)


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 0.0

    _init_default = _init_weight


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        arr[:] = 1.0

    _init_default = _init_weight


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        arr[:] = self.value

    _init_default = _init_weight


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        arr[:] = np.random.uniform(-self.scale, self.scale, arr.shape).astype(
            np.float32
        )


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        arr[:] = np.random.normal(0, self.sigma, arr.shape).astype(np.float32)


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == tmp.shape else v
        arr[:] = (self.scale * q).reshape(arr.shape).astype(np.float32)


@register
class Xavier(Initializer):
    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(
            rnd_type=rnd_type, factor_type=factor_type, magnitude=magnitude
        )
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, _, arr):
        shape = arr.shape
        hw_scale = 1.0
        if len(shape) > 2:
            hw_scale = np.prod(shape[2:])
        fan_in, fan_out = shape[1] * hw_scale, shape[0] * hw_scale
        factor = {"avg": (fan_in + fan_out) / 2.0, "in": fan_in, "out": fan_out}[
            self.factor_type
        ]
        scale = np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            arr[:] = np.random.uniform(-scale, scale, shape).astype(np.float32)
        else:
            arr[:] = np.random.normal(0, scale, shape).astype(np.float32)


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    def _init_weight(self, name, arr):
        self._init_bilinear(name, arr)


@register
class LSTMBias(Initializer):
    """Forget-gate bias init (reference initializer.py:587)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = np.zeros(arr.shape, dtype=np.float32)
        num_hidden = int(arr.shape[0] / 4)
        b[num_hidden : 2 * num_hidden] = self.forget_bias  # gate order i,f,g,o
        arr[:] = b


@register
class FusedRNN(Initializer):
    """Init a fused RNN parameter blob by unpacking → init → repacking
    (reference initializer.py:609)."""

    def __init__(self, init, num_hidden, num_layers, mode,
                 bidirectional=False, forget_bias=1.0):
        if isinstance(init, str):
            klass, kwargs = json.loads(init)
            init = _INIT_REGISTRY[klass.lower()](**kwargs)
        super().__init__(
            init=init.dumps() if init is not None else None,
            num_hidden=num_hidden, num_layers=num_layers, mode=mode,
            bidirectional=bidirectional, forget_bias=forget_bias,
        )
        self._init = init
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        from .rnn.rnn_cell import FusedRNNCell

        cell = FusedRNNCell(
            self._num_hidden, self._num_layers, self._mode,
            self._bidirectional, forget_bias=self._forget_bias
        )
        args = cell.unpack_weights({cell._parameter.name: arr})
        for name, a in args.items():
            # strip the blob's own __init__ attr: the unpacked slices
            # must dispatch by NAME (i2h/h2h/bias), not recurse into
            # this FusedRNN initializer again
            attrs = dict(getattr(desc, "attrs", {}) or {})
            attrs.pop("__init__", None)
            desc2 = InitDesc(name, attrs)
            if self._init is None:
                getattr(desc, "global_init", Uniform())(desc2, a)
            else:
                self._init(desc2, a)
        arr[:] = cell.pack_weights(args)[cell._parameter.name]
