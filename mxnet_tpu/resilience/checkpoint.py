"""Atomic full-state training checkpoints with manifest verification.

Format: one directory per checkpoint, ``<dir>/ckpt-<step 12 digits>/``::

    state.params     params + aux in the reference .params container
                     (keys "arg:<name>" / "aux:<name>", so the file is
                     loadable by plain ``mx.nd.load`` too)
    optimizer.state  pickled optimizer payload (fused host tree, updater
                     bytes, or {"kind": "none"})
    train_state.pkl  pickled loop position: epoch, nbatch, global_step,
                     metric state, RNG (numpy MT state + jax PRNGKey)
    MANIFEST.json    written LAST: per-file byte counts + CRC32 and
                     per-tensor CRC32s. A directory without a readable,
                     matching manifest is by definition torn and is
                     never resumed from.

Atomicity protocol (the TensorFlow-checkpoint recovery model, done with
POSIX primitives): build everything in a ``.tmp-*`` sibling dir, fsync
each file, write the manifest last, ``os.replace`` the dir into its
final name, fsync the parent. Readers either see a complete checkpoint
or none; a crash at ANY byte leaves only a ``.tmp-*`` that retention
sweeps away. Verification re-hashes on read, so silent storage
corruption (torn page after the rename) is also caught and skipped by
``latest_valid()``.

Snapshot cost model: the caller (Module.fit) captures device-array
references on the train thread — immutable jax.Arrays make a dict copy
a consistent zero-cost snapshot — and ``save_async`` does the host
pulls, hashing, and fsyncs on a background thread so the step loop
barely stalls.
"""
from __future__ import annotations

import contextlib
import json
import logging
import os
import pickle
import re
import shutil
import threading
import time
import zlib

import numpy as np

from . import fault, retry

try:
    from .. import telemetry as _tm
except ImportError:  # standalone import (tools/ckpt_inspect.py by path)
    _tm = None

#: Exit code for "preempted after writing a final checkpoint" — EX_TEMPFAIL,
#: the sysexits.h "transient failure, retry the job" code. Supervisors
#: (tools/watchdog.py, k8s restart policies) can distinguish this from a
#: real training failure.
EXIT_PREEMPTED = 75

#: Exit code for "a replica was declared lost, final checkpoint written,
#: restart me at the surviving world size". Distinct from EXIT_PREEMPTED
#: so supervisors know a same-size retry would hang on the dead rank:
#: ``tools/watchdog.py --elastic`` answers by shrinking MXTPU_WORLD_SIZE.
EXIT_RESHAPE = 76

ENV_INTERVAL = "MXTPU_CKPT_INTERVAL"
ENV_KEEP = "MXTPU_CKPT_KEEP"

MANIFEST = "MANIFEST.json"
PARAMS_FILE = "state.params"
OPT_FILE = "optimizer.state"
TRAIN_FILE = "train_state.pkl"
_FORMAT_VERSION = 1

_CKPT_RE = re.compile(r"^ckpt-(\d{12})$")

log = logging.getLogger(__name__)


def _metric(kind, name, help_):
    if _tm is None:
        return None
    return getattr(_tm, kind)(name, help_)


_H_WRITE_S = _metric("histogram", "checkpoint.write_seconds",
                     "Wall seconds to build+fsync+publish one checkpoint")
_C_BYTES = _metric("counter", "checkpoint.bytes",
                   "Bytes written into published checkpoints")
_C_WRITTEN = _metric("counter", "checkpoint.written",
                     "Checkpoints successfully published")
_C_FAILED = _metric("counter", "checkpoint.failed",
                    "Checkpoint attempts that aborted (no partial state "
                    "is ever published)")
_C_SKIPPED = _metric("counter", "resume.skipped_corrupt",
                     "Checkpoints skipped by latest_valid() for failing "
                     "manifest verification")


class CheckpointError(Exception):
    """A checkpoint exists but cannot be trusted (torn, corrupt, or an
    incompatible format version)."""


class _HostArray:
    """Minimal .asnumpy() carrier so ndarray._save_fileobj can serialize
    host snapshots without constructing device-backed NDArrays."""

    __slots__ = ("_a",)

    def __init__(self, a):
        self._a = np.asarray(a)

    def asnumpy(self):
        return self._a


@contextlib.contextmanager
def atomic_file(path, mode="wb"):
    """Write ``path`` all-or-nothing: temp file in the same directory,
    flush + fsync, then ``os.replace`` over the target and fsync the
    parent dir. On any error the temp file is removed and the previous
    ``path`` (if any) is left untouched."""
    directory = os.path.dirname(os.path.abspath(path)) or "."
    tmp = os.path.join(
        directory, ".tmp-%s-%d" % (os.path.basename(path), os.getpid()))
    f = open(tmp, mode)
    try:
        yield f
        f.flush()
        os.fsync(f.fileno())
        f.close()
        os.replace(tmp, path)
        _fsync_dir(directory)
    except BaseException:
        with contextlib.suppress(OSError):
            f.close()
        with contextlib.suppress(OSError):
            os.unlink(tmp)
        raise


def _fsync_dir(path):
    # Directory fsync makes the rename itself durable. Some filesystems
    # refuse O_RDONLY dir fsync; crash-consistency degrades gracefully.
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _crc_file(path, chunk=1 << 20):
    crc = 0
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            crc = zlib.crc32(block, crc)
    return crc & 0xFFFFFFFF


def _write_member(ckpt_dir, name, payload):
    """Write one checkpoint member durably; returns (bytes, crc32).

    The write itself goes through the shared retry policy — a transient
    EIO from flaky network storage should cost a backoff, not the whole
    snapshot — while ENOSPC and friends abort the attempt immediately.
    """
    path = os.path.join(ckpt_dir, name)

    def _do():
        fault.fire("ckpt_write", path=path)
        with open(path, "wb") as f:
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())

    retry.call(_do, name="ckpt.write")
    return len(payload), zlib.crc32(payload) & 0xFFFFFFFF


def step_dir(directory, step):
    return os.path.join(directory, "ckpt-%012d" % int(step))


def list_checkpoints(directory):
    """All checkpoint step numbers present (valid or not), ascending."""
    try:
        entries = os.listdir(directory)
    except OSError:
        return []
    steps = []
    for name in entries:
        m = _CKPT_RE.match(name)
        if m:
            steps.append(int(m.group(1)))
    return sorted(steps)


def read_manifest(path):
    with open(os.path.join(path, MANIFEST)) as f:
        manifest = json.load(f)
    if manifest.get("version") != _FORMAT_VERSION:
        raise CheckpointError(
            "%s: unsupported checkpoint format version %r"
            % (path, manifest.get("version")))
    return manifest


def verify_checkpoint(path, deep=False):
    """Check a checkpoint directory against its manifest.

    Shallow (default): every listed file exists with the recorded size
    and whole-file CRC32 — catches truncation and torn writes. ``deep``
    additionally re-hashes every individual tensor payload against the
    per-tensor CRCs (catches in-place bit corruption localized to one
    array). Returns the manifest; raises :class:`CheckpointError`.
    """
    try:
        manifest = read_manifest(path)
    except CheckpointError:
        raise
    except (OSError, ValueError) as exc:
        raise CheckpointError("%s: unreadable manifest: %s" % (path, exc))
    for name, meta in manifest.get("files", {}).items():
        fpath = os.path.join(path, name)
        try:
            size = os.path.getsize(fpath)
        except OSError:
            raise CheckpointError("%s: missing member %s" % (path, name))
        if size != meta["bytes"]:
            raise CheckpointError(
                "%s: %s is %d bytes, manifest says %d (torn write)"
                % (path, name, size, meta["bytes"]))
        if _crc_file(fpath) != meta["crc32"]:
            raise CheckpointError(
                "%s: %s fails CRC32 (corrupt)" % (path, name))
    if deep:
        _verify_tensors(path, manifest)
    return manifest


def _verify_tensors(path, manifest):
    from .. import ndarray as nd

    arrays = nd.load(os.path.join(path, PARAMS_FILE))
    for key, want in manifest.get("tensors", {}).items():
        arr = arrays.get(key)
        if arr is None:
            raise CheckpointError("%s: tensor %s missing" % (path, key))
        got = zlib.crc32(
            np.ascontiguousarray(arr.asnumpy()).tobytes()) & 0xFFFFFFFF
        if got != want:
            raise CheckpointError(
                "%s: tensor %s fails CRC32 (corrupt)" % (path, key))


def load_state(path, verify=True):
    """Read a checkpoint directory back into the state dict shape that
    :meth:`CheckpointManager.save` accepted."""
    if verify:
        verify_checkpoint(path)
    from .. import ndarray as nd

    arrays = nd.load(os.path.join(path, PARAMS_FILE))
    arg = {}
    aux = {}
    for key, arr in arrays.items():
        kind, _, name = key.partition(":")
        (arg if kind == "arg" else aux)[name] = arr.asnumpy()
    with open(os.path.join(path, OPT_FILE), "rb") as f:
        opt = pickle.load(f)
    with open(os.path.join(path, TRAIN_FILE), "rb") as f:
        train = pickle.load(f)
    state = dict(train)
    state["module"] = {"arg": arg, "aux": aux, "opt": opt}
    return state


class CheckpointManager:
    """Owns one checkpoint directory: atomic writes, retention,
    background snapshots, and valid-checkpoint discovery.

    ``state`` dicts passed to :meth:`save` look like::

        {"module": {"arg": {name: array-like}, "aux": {...},
                    "opt": <picklable>},
         "epoch": int, "nbatch": int, "global_step": int,
         "metric": bytes|None, "rng": {...}}

    Array-likes need only ``np.asarray()`` to work — numpy arrays,
    jax.Arrays, and NDArrays all qualify.
    """

    def __init__(self, directory, keep=None):
        self.directory = directory
        if keep is None:
            try:
                keep = int(os.environ.get(ENV_KEEP, 3))
            except ValueError:
                keep = 3
        self.keep = max(1, int(keep))
        self.last_step = None
        self._thread = None
        self._last_error = None
        os.makedirs(directory, exist_ok=True)

    # -- write side -----------------------------------------------------

    def save(self, state, step):
        """Synchronously publish ``state`` as checkpoint ``step``.

        Returns the published directory. Raises on failure; a failed
        attempt never leaves a partial ``ckpt-*`` dir behind.
        """
        self.wait()
        step = int(step)
        final = step_dir(self.directory, step)
        if os.path.isdir(final):
            # Step already checkpointed (interval boundary coinciding
            # with epoch end): publishing twice would tear the existing
            # good copy for zero information gain.
            return final
        t0 = time.monotonic()
        tmp = os.path.join(
            self.directory, ".tmp-%012d-%d" % (step, os.getpid()))
        try:
            total = self._build(tmp, state, step)
            os.replace(tmp, final)
            _fsync_dir(self.directory)
        except BaseException:
            shutil.rmtree(tmp, ignore_errors=True)
            if _C_FAILED:
                _C_FAILED.inc()
            raise
        dt = time.monotonic() - t0
        if _H_WRITE_S:
            _H_WRITE_S.observe(dt)
        if _C_BYTES:
            _C_BYTES.inc(total)
        if _C_WRITTEN:
            _C_WRITTEN.inc()
        self.last_step = step
        fault.fire("ckpt_done", path=final)
        self._retain()
        return final

    def save_async(self, state, step):
        """Publish on a background thread. Waits for any previous
        in-flight snapshot first (at most one outstanding). Failures are
        logged and counted, not raised — a flaky periodic snapshot must
        not kill the training loop; the final/preemption checkpoint uses
        synchronous :meth:`save` which does raise."""
        self.wait()

        def _run():
            try:
                self.save(state, step)
            except BaseException as exc:  # noqa: B036 - logged, counted
                self._last_error = exc
                log.warning("async checkpoint at step %d failed: %s",
                            step, exc)

        self._thread = threading.Thread(
            target=_run, name="mxtpu-ckpt", daemon=True)
        self._thread.start()
        return self._thread

    def wait(self):
        """Block until any in-flight async snapshot has finished."""
        t = self._thread
        if t is not None and t is not threading.current_thread():
            t.join()
            self._thread = None

    def _build(self, tmp, state, step):
        os.makedirs(tmp, exist_ok=True)
        module = state.get("module") or {}
        files = {}
        tensors = {}

        payload, tensors = _pack_params(
            module.get("arg") or {}, module.get("aux") or {})
        files[PARAMS_FILE] = _member_meta(
            *_write_member(tmp, PARAMS_FILE, payload))
        # Optimizer state may arrive as device-array references (fused
        # path snapshots are reference copies); the blocking host pull
        # happens here, on the writer thread.
        opt = _host_tree(module.get("opt") or {"kind": "none"})
        files[OPT_FILE] = _member_meta(*_write_member(
            tmp, OPT_FILE, pickle.dumps(opt, protocol=2)))
        train = {k: v for k, v in state.items() if k != "module"}
        files[TRAIN_FILE] = _member_meta(
            *_write_member(tmp, TRAIN_FILE, pickle.dumps(train, protocol=2)))

        manifest = {
            "version": _FORMAT_VERSION,
            "step": step,
            "time": time.time(),
            "files": files,
            "tensors": tensors,
        }
        # The writer's runtime topology (dp degree, mesh shape, batch
        # geometry) rides in the manifest so inspection tools can warn
        # about a cross-world restore BEFORE the restoring process gets
        # an opaque shape error. Informational only: the state payload
        # itself is named-tree / layout-independent by design.
        if state.get("topology"):
            manifest["topology"] = state["topology"]
        # The input pipeline's O(1) cursor: global sample position at
        # snapshot time. Readers (resume at any dp, MANIFEST inspection,
        # StreamingImageRecordIter.seek_sample) reposition from this
        # single integer — no batch replay, no decode.
        if state.get("sample_position") is not None:
            manifest["sample_position"] = int(state["sample_position"])
        # Guardrail health stamp (resilience/guardrail.py): known-clean
        # flag + detector state at snapshot time. Rides in the MANIFEST
        # (not just the train pickle) so last_good()/ckpt_inspect can
        # judge a checkpoint without deserializing its payload.
        if state.get("health"):
            manifest["health"] = state["health"]
        payload = json.dumps(manifest, indent=1, sort_keys=True).encode()
        _write_member(tmp, MANIFEST, payload)
        return sum(m["bytes"] for m in files.values()) + len(payload)

    def _retain(self):
        steps = list_checkpoints(self.directory)
        evict = steps[:-self.keep] if len(steps) > self.keep else []
        if evict:
            # never evict the newest known-good snapshot: if every
            # checkpoint inside the keep-window is health-stamped
            # unclean, the rewind target lives in the evict range and
            # must survive retention pressure
            protected = self._newest_clean(steps)
            if protected is not None and protected in evict:
                evict = [s for s in evict if s != protected]
        for step in evict:
            shutil.rmtree(step_dir(self.directory, step),
                          ignore_errors=True)
        # Sweep orphaned build dirs from crashed writers (not ours: a
        # concurrent writer pid could be mid-build, but stale pids from
        # dead processes dominate and rebuilds are cheap).
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return
        suffix = "-%d" % os.getpid()
        for name in entries:
            if name.startswith(".tmp-") and not name.endswith(suffix):
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)

    def _newest_clean(self, steps):
        """Newest step whose MANIFEST health stamp says ``clean`` (None
        when no checkpoint carries a stamp — unstamped runs have no
        guardrail, so nothing needs protecting). Manifest-only: no
        payload read, cheap enough for every retention pass."""
        for step in reversed(steps):
            try:
                manifest = read_manifest(step_dir(self.directory, step))
            except (OSError, ValueError):
                continue
            health = manifest.get("health")
            if isinstance(health, dict) and health.get("clean"):
                return step
        return None

    # -- read side ------------------------------------------------------

    def last_good(self, deep=False):
        """Path of the newest checkpoint that verifies AND whose health
        stamp is clean, or None. Stamped-unclean checkpoints are
        skipped; an unstamped (pre-guardrail / guardrail-off) manifest
        counts as good — absence of evidence is not an anomaly."""
        for step in reversed(list_checkpoints(self.directory)):
            path = step_dir(self.directory, step)
            try:
                manifest = read_manifest(path)
            except (OSError, ValueError):
                continue
            health = manifest.get("health")
            if isinstance(health, dict) and not health.get("clean"):
                continue
            try:
                verify_checkpoint(path, deep=deep)
                return path
            except CheckpointError as exc:
                if _C_SKIPPED:
                    _C_SKIPPED.inc()
                log.warning("skipping corrupt checkpoint %s: %s", path, exc)
        return None

    def load_last_good(self):
        """Load the newest known-good checkpoint (rewind target), or
        None when no healthy checkpoint exists."""
        path = self.last_good()
        if path is None:
            return None
        return load_state(path)

    def latest_valid(self, deep=False):
        """Newest checkpoint that verifies, or None. Torn/corrupt
        candidates are skipped (counted in ``resume.skipped_corrupt``)
        and the scan falls back to the previous one — the acceptance
        behavior for a truncated newest checkpoint."""
        for step in reversed(list_checkpoints(self.directory)):
            path = step_dir(self.directory, step)
            try:
                verify_checkpoint(path, deep=deep)
                return path
            except CheckpointError as exc:
                if _C_SKIPPED:
                    _C_SKIPPED.inc()
                log.warning("skipping corrupt checkpoint %s: %s", path, exc)
        return None

    def load(self, step=None):
        """Load checkpoint ``step`` (default: latest valid). Returns the
        state dict, or None when ``step`` is None and nothing valid
        exists."""
        if step is None:
            path = self.latest_valid()
            if path is None:
                return None
        else:
            path = step_dir(self.directory, step)
        return load_state(path)


def _member_meta(nbytes, crc):
    return {"bytes": nbytes, "crc32": crc}


def _host_tree(obj):
    """Recursively pull a state tree to picklable host values (device
    arrays -> numpy, containers preserved, scalars/bytes passed through)."""
    if obj is None or isinstance(obj, (bool, int, float, str, bytes)):
        return obj
    if isinstance(obj, dict):
        return {k: _host_tree(v) for k, v in obj.items()}
    if isinstance(obj, tuple):
        return tuple(_host_tree(v) for v in obj)
    if isinstance(obj, list):
        return [_host_tree(v) for v in obj]
    if hasattr(obj, "asnumpy"):
        return np.asarray(obj.asnumpy())
    return np.asarray(obj)


def _pack_params(arg, aux):
    """Serialize {name: array-like} dicts to reference .params bytes plus
    per-tensor CRC32s. Host transfer happens here (np.asarray pulls
    jax.Arrays off device) — call on the background thread."""
    from .. import ndarray as nd

    data = {}
    tensors = {}
    for prefix, source in (("arg", arg), ("aux", aux)):
        for name, value in source.items():
            host = np.ascontiguousarray(np.asarray(
                value.asnumpy() if hasattr(value, "asnumpy") else value))
            key = "%s:%s" % (prefix, name)
            data[key] = _HostArray(host)
            tensors[key] = zlib.crc32(host.tobytes()) & 0xFFFFFFFF
    return nd.save_buffer(data), tensors
