"""Retry-with-jittered-backoff for transient I/O and transport faults.

The parameter-server lineage this stack descends from (ps-lite) resends
on timeout instead of dying; the JAX port so far has treated every
OSError on a kvstore push or a recordio read as fatal. This module is
the single retry policy, shared by kvstore push/pull, recordio reads,
and checkpoint I/O so the backoff shape and telemetry are uniform.

Classification, not blanket retries: only errors that plausibly heal on
their own (EINTR/EAGAIN/EIO/ETIMEDOUT/... and explicit
``TransientError``) are retried. Corruption (``MXNetError`` from a bad
magic), programming errors, and ENOSPC are raised immediately —
retrying a full disk just burns the preemption grace window.

Cross-process collectives are deliberately NOT retried anywhere in the
codebase: peers issue collectives in lockstep, and one rank re-entering
an allreduce its peers already left deadlocks the mesh. Recovery there
is process-level (watchdog restart + checkpoint resume).
"""
from __future__ import annotations

import errno
import functools
import os
import random
import time

try:
    from .. import telemetry as _tm
except ImportError:  # standalone import by tools / subprocess scripts
    _tm = None


class TransientError(Exception):
    """Raise to mark an error as retryable regardless of its type."""


#: OS errors worth retrying: interrupted/busy/timeout/connection classes.
#: Notably absent: ENOSPC (disk full won't heal within a backoff window)
#: and ENOENT (a missing file is a logic error, not a blip).
RETRYABLE_ERRNOS = frozenset((
    errno.EINTR, errno.EAGAIN, errno.EBUSY, errno.EIO, errno.ETIMEDOUT,
    errno.ECONNRESET, errno.ECONNREFUSED, errno.EPIPE, errno.ESTALE,
))

ENV_MAX = "MXTPU_RETRY_MAX"
_DEF_MAX = 3


def is_retryable(exc):
    if isinstance(exc, TransientError):
        return True
    if isinstance(exc, OSError):
        return exc.errno in RETRYABLE_ERRNOS
    return False


def _max_attempts():
    try:
        return max(1, int(os.environ.get(ENV_MAX, _DEF_MAX)))
    except ValueError:
        return _DEF_MAX


def _metrics():
    if _tm is None or not _tm.enabled():
        return None
    return (
        _tm.counter("retry.attempts", "Calls entering a retry wrapper"),
        _tm.counter("retry.retries", "Transient failures retried"),
        _tm.counter("retry.giveup",
                    "Retry wrappers that exhausted max attempts"),
    )


def call(fn, *args, max_attempts=None, base_delay=0.05, max_delay=2.0,
         jitter=0.5, retryable=is_retryable, name=None, sleep=time.sleep,
         **kwargs):
    """Run ``fn(*args, **kwargs)``, retrying transient failures.

    Backoff: ``min(max_delay, base_delay * 2**(attempt-1))`` scaled by a
    uniform jitter factor in ``[1, 1+jitter]`` so a fleet of workers
    hitting the same flaky store doesn't re-stampede it in sync.
    ``max_attempts`` defaults to ``MXTPU_RETRY_MAX`` (3). The final
    failure is re-raised unchanged.
    """
    attempts = _max_attempts() if max_attempts is None else int(max_attempts)
    attempts = max(1, attempts)
    site = name or getattr(fn, "__name__", "call")
    mets = _metrics()
    if mets:
        mets[0].inc(site=site)
    for attempt in range(1, attempts + 1):
        try:
            return fn(*args, **kwargs)
        except BaseException as exc:  # noqa: B036 - classified below
            if attempt >= attempts or not retryable(exc):
                if mets and attempt >= attempts and retryable(exc):
                    mets[2].inc(site=site)
                raise
            if mets:
                mets[1].inc(site=site)
            delay = min(max_delay, base_delay * (2.0 ** (attempt - 1)))
            sleep(delay * (1.0 + jitter * random.random()))


def retry(fn=None, **policy):
    """Decorator form of :func:`call`.

    ``@retry`` or ``@retry(max_attempts=5, name="kv.push")``.
    """
    if fn is not None:
        return retry()(fn)

    def deco(f):
        if "name" not in policy:
            policy["name"] = getattr(f, "__name__", "call")

        @functools.wraps(f)
        def wrapped(*args, **kwargs):
            return call(f, *args, **policy, **kwargs)

        return wrapped

    return deco
