"""Training guardrails: anomaly detection, rewind-to-last-good, and
poison-batch quarantine (docs/robustness.md "Training guardrails").

The resilience stack below this module survives *process* death
(atomic checkpoints, elastic shrink, preemption drain) but says
nothing about the *numerics*: a poisoned batch, a corrupt record, or a
diverging loss sails straight into the optimizer. This module is the
numeric counterpart, a policy ladder with three rungs:

1. **Skip** — the fused step (parallel/train_step.py, ``guard=True``)
   computes the global grad-norm² from the gradient stream it already
   has in hand and applies the same branchless ``select(ok, new, old)``
   the AMP loss scaler uses — generalized to fp32 — so a non-finite or
   out-of-threshold gradient updates NOTHING, bitwise. The step also
   emits a ``(loss, grad_norm², gate_ok)`` diag head for the host.
2. **Rewind** — :class:`GuardrailMonitor` watches the diag stream with
   a robust z-score (EMA of windowed median+MAD, warmup-exempt). On
   ``MXTPU_GUARD_REWIND_AFTER`` consecutive trips it raises
   :class:`GuardrailRewind`; ``fit(guardrails="auto")`` restores the
   newest *known-good* checkpoint (MANIFEST ``health`` stamp; retention
   never evicts it), repositions the sample cursor past the poison
   window (O(1), no decode), and re-enters the epoch loop.
3. **Verdict** — after ``MXTPU_GUARD_MAX_REWINDS`` rewinds the run is
   declared unrecoverable: a structured ``{"type": "guardrail"}``
   verdict is published atomically where the watchdog looks
   (``MXTPU_RUN_DIR``) and the process exits :data:`EXIT_GUARDRAIL`.
   ``tools/watchdog.py`` records the verdict in ``decisions.jsonl``
   and stops retrying — restarts cannot fix poisoned data.

The detector is observation-only until it trips: a guardrail-enabled
run with zero anomalies is bitwise identical to a guardrail-off run
(proven in tests/test_guardrail.py).
"""
from __future__ import annotations

import json
import logging
import math
import os
import time
from collections import deque

try:
    from .. import telemetry as _tm
except ImportError:  # standalone import (tools by path)
    _tm = None

ENV_WINDOW = "MXTPU_GUARD_WINDOW"
ENV_ZMAX = "MXTPU_GUARD_ZMAX"
ENV_REWIND_AFTER = "MXTPU_GUARD_REWIND_AFTER"
ENV_MAX_REWINDS = "MXTPU_GUARD_MAX_REWINDS"

#: Exit code for "numerics diverged beyond the rewind budget" — the
#: guardrail verdict. Distinct from EXIT_PREEMPTED (75, retry same
#: size) and EXIT_RESHAPE (76, shrink): a supervisor must STOP, because
#: replaying the same data through the same model diverges again.
EXIT_GUARDRAIL = 78

VERDICT_FILE = "guardrail_verdict.json"

log = logging.getLogger(__name__)


def _metric(kind, name, help_):
    if _tm is None:
        return None
    return getattr(_tm, kind)(name, help_)


_C_TRIPS = _metric("counter", "guard.trips",
                   "Guardrail anomaly trips (in-graph skips + host-side "
                   "z-score detections)")
_C_SKIPS = _metric("counter", "guard.skips",
                   "Optimizer steps the in-graph gate skipped bitwise "
                   "(non-finite or out-of-threshold gradient)")
_C_REWINDS = _metric("counter", "guard.rewinds",
                     "Rewind-to-last-good recoveries performed by fit()")


def _env_int(name, default):
    try:
        return int(os.environ.get(name, "") or default)
    except ValueError:
        return int(default)


def _env_float(name, default):
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return float(default)


class GuardrailRewind(Exception):
    """Raised at a group boundary when the monitor votes to rewind.

    Carries where the anomaly run was detected so fit() can skip the
    poison window after restoring the last-good checkpoint.
    """

    def __init__(self, step, epoch, nbatch, reason):
        super().__init__(reason)
        self.step = int(step)
        self.epoch = int(epoch)
        self.nbatch = int(nbatch)
        self.reason = reason


class _RobustStream:
    """Sliding-window median+MAD location/scale estimate, EMA-smoothed.

    Median+MAD instead of mean+std because the statistic must not be
    dragged by the very outliers it exists to flag; the EMA (alpha =
    2/(window+1)) smooths the windowed estimates so a single window
    turnover cannot step the threshold. ``warm`` only after a full
    window — the warmup trend of a fresh run is not an anomaly.
    """

    __slots__ = ("window", "buf", "med", "mad")

    def __init__(self, window):
        self.window = max(2, int(window))
        self.buf = deque(maxlen=self.window)
        self.med = None
        self.mad = None

    @property
    def warm(self):
        return len(self.buf) >= self.window and self.med is not None

    def sigma(self):
        """Robust std estimate with a relative floor: 1.4826·MAD is the
        gaussian-consistent scale; the 5%-of-median floor keeps an
        ultra-smooth stream (MAD ≈ 0) from flagging normal jitter."""
        return (1.4826 * (self.mad or 0.0)
                + 0.05 * abs(self.med or 0.0) + 1e-12)

    def z(self, x):
        """One-sided robust z of ``x`` (0.0 while warming up — the
        warmup exemption; only positive excursions count, a dropping
        loss is progress, not an anomaly)."""
        if not self.warm or not math.isfinite(x):
            return 0.0
        return max(0.0, (float(x) - self.med) / self.sigma())

    def update(self, x):
        if not math.isfinite(x):
            return
        self.buf.append(float(x))
        med = _median(self.buf)
        mad = _median([abs(v - med) for v in self.buf])
        alpha = 2.0 / (self.window + 1.0)
        self.med = med if self.med is None \
            else (1.0 - alpha) * self.med + alpha * med
        self.mad = mad if self.mad is None \
            else (1.0 - alpha) * self.mad + alpha * mad

    def state(self):
        return {"med": self.med, "mad": self.mad, "buf": list(self.buf)}

    def restore(self, blob):
        if not blob:
            return
        self.buf.clear()
        for v in (blob.get("buf") or [])[-self.window:]:
            self.buf.append(float(v))
        self.med = blob.get("med")
        self.mad = blob.get("mad")


def _median(values):
    vals = sorted(values)
    n = len(vals)
    if not n:
        return 0.0
    mid = n // 2
    if n % 2:
        return float(vals[mid])
    return 0.5 * (vals[mid - 1] + vals[mid])


class GuardrailMonitor:
    """Streaming anomaly detector over the fused step's diag stream.

    One :meth:`observe` call per optimizer step (fit drains them at
    group boundaries — the detector never blocks the dispatch
    frontier). Policy ladder: an anomalous step answers ``"skip"``
    (the in-graph gate already protected the params);
    ``rewind_after`` CONSECUTIVE anomalies answer ``"rewind"`` — a
    transient glitch self-heals, a persistent divergence does not.

    Statistics update only on clean steps, so a poison run can never
    drag the baseline toward itself.
    """

    def __init__(self, window=None, zmax=None, rewind_after=None,
                 max_rewinds=None, logger=None):
        self.window = int(window if window is not None
                          else _env_int(ENV_WINDOW, 64))
        self.zmax = float(zmax if zmax is not None
                          else _env_float(ENV_ZMAX, 10.0))
        self.rewind_after = max(1, int(
            rewind_after if rewind_after is not None
            else _env_int(ENV_REWIND_AFTER, 3)))
        self.max_rewinds = max(0, int(
            max_rewinds if max_rewinds is not None
            else _env_int(ENV_MAX_REWINDS, 2)))
        self.log = logger or log
        self.loss = _RobustStream(self.window)
        self.gnorm = _RobustStream(self.window)
        self.last_clean_step = 0
        self.consecutive = 0
        self.trips = 0
        self.skips = 0
        self.rewinds = 0
        self.last_reason = None

    # -- observation ---------------------------------------------------

    def observe(self, step, loss, gnorm_sq, gate_ok):
        """Fold one step's diag into the detector.

        Returns ``"ok"`` | ``"skip"`` | ``"rewind"``. ``gate_ok`` is
        the in-graph select's verdict (1.0 = the update was applied).
        """
        step = int(step)
        loss = float(loss)
        gnorm = (math.sqrt(gnorm_sq)
                 if math.isfinite(gnorm_sq) and gnorm_sq >= 0.0
                 else float("inf"))
        reason = None
        if gate_ok < 0.5:
            self.skips += 1
            if _C_SKIPS:
                _C_SKIPS.inc()
            reason = ("in-graph gate skipped step %d (non-finite or "
                      "out-of-threshold gradient, grad_norm=%g)"
                      % (step, gnorm))
        elif not math.isfinite(loss) or not math.isfinite(gnorm):
            reason = ("non-finite observable at step %d "
                      "(loss=%r, grad_norm=%r)" % (step, loss, gnorm))
        else:
            z_loss = self.loss.z(loss)
            z_gnorm = self.gnorm.z(gnorm)
            if z_loss > self.zmax:
                reason = ("loss anomaly at step %d: %g is %.1f robust "
                          "sigmas above the windowed median %g"
                          % (step, loss, z_loss, self.loss.med))
            elif z_gnorm > self.zmax:
                reason = ("grad-norm anomaly at step %d: %g is %.1f "
                          "robust sigmas above the windowed median %g"
                          % (step, gnorm, z_gnorm, self.gnorm.med))
        if reason is None:
            self.loss.update(loss)
            self.gnorm.update(gnorm)
            self.consecutive = 0
            self.last_clean_step = step
            return "ok"
        self.trips += 1
        self.consecutive += 1
        self.last_reason = reason
        if _C_TRIPS:
            _C_TRIPS.inc()
        self.log.warning("guardrail trip (%d consecutive): %s",
                         self.consecutive, reason)
        if self.consecutive >= self.rewind_after:
            return "rewind"
        return "skip"

    def gate_threshold(self):
        """grad-norm² bound for the in-graph branchless select: ``inf``
        until the gnorm stream is warm (warmup-exempt — the gate then
        trips on non-finite only), afterwards the z == zmax contour of
        the robust statistics."""
        s = self.gnorm
        if not s.warm:
            return float("inf")
        bound = s.med + self.zmax * s.sigma()
        return float(bound * bound)

    # -- checkpoint stamp ----------------------------------------------

    def health_blob(self, step):
        """The ``health`` stamp a checkpoint carries: known-clean flag,
        last clean step, and the full detector state so a rewind (or
        resume) restarts the statistics exactly where the snapshot's
        history left them."""
        return {
            "clean": self.consecutive == 0,
            "step": int(step),
            "last_clean_step": int(self.last_clean_step),
            "trips": int(self.trips),
            "skips": int(self.skips),
            "window": int(self.window),
            "loss": self.loss.state(),
            "gnorm": self.gnorm.state(),
        }

    def restore(self, blob):
        """Reinstate detector state from a checkpoint's health stamp.
        The rewind budget (``rewinds``) intentionally survives: it
        counts recoveries THIS process attempted, not the snapshot's
        history."""
        if not blob:
            return
        self.last_clean_step = int(blob.get("last_clean_step", 0))
        self.trips = int(blob.get("trips", 0))
        self.skips = int(blob.get("skips", 0))
        self.consecutive = 0
        self.last_reason = None
        self.loss.restore(blob.get("loss"))
        self.gnorm.restore(blob.get("gnorm"))


def count_rewind(monitor):
    """Record one rewind recovery (fit's handler): monitor bookkeeping
    plus the ``guard.rewinds`` counter."""
    monitor.rewinds += 1
    if _C_REWINDS:
        _C_REWINDS.inc()


def write_verdict(verdict, extra_dir=None):
    """Atomically publish a structured guardrail verdict.

    Written to ``$MXTPU_RUN_DIR/guardrail_verdict.json`` (where
    tools/watchdog.py looks after a nonzero exit) and, when given, to
    ``extra_dir`` (the checkpoint directory — the post-mortem location
    for runs without a run dir). Returns the list of paths written.
    """
    verdict = dict(verdict)
    verdict.setdefault("type", "guardrail")
    verdict.setdefault("t", time.time())
    payload = (json.dumps(verdict, indent=1, sort_keys=True) + "\n").encode()
    wrote = []
    targets = []
    run_dir = os.environ.get("MXTPU_RUN_DIR")
    if run_dir:
        targets.append(run_dir)
    if extra_dir and extra_dir not in targets:
        targets.append(extra_dir)
    for directory in targets:
        path = os.path.join(directory, VERDICT_FILE)
        tmp = "%s.tmp-%d" % (path, os.getpid())
        try:
            os.makedirs(directory, exist_ok=True)
            with open(tmp, "wb") as f:
                f.write(payload)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            wrote.append(path)
        except OSError as exc:
            log.warning("guardrail verdict not written to %s: %s",
                        directory, exc)
    return wrote


def read_verdict(run_dir):
    """The published verdict under ``run_dir``, or None (missing or
    unreadable — a supervisor must not crash on a torn verdict)."""
    if not run_dir:
        return None
    try:
        with open(os.path.join(run_dir, VERDICT_FILE)) as f:
            verdict = json.load(f)
    except (OSError, ValueError):
        return None
    return verdict if isinstance(verdict, dict) else None
