"""MXTPU_FAULT_INJECT: deterministic fault injection for resilience tests.

The production fault-tolerance story (atomic checkpoints, retry with
backoff, preemption handling) is only trustworthy if it is exercised by
the same classes of failure it claims to survive. This module is the
single switchboard: instrumented sites call ``fire(point, ...)`` and the
``MXTPU_FAULT_INJECT`` spec decides whether that call dies, raises, or
delays. With the env var unset every ``fire`` is a one-dict-lookup no-op,
so the hooks are safe to leave in hot paths.

Spec grammar: comma-separated ``directive=value`` pairs, e.g.::

    MXTPU_FAULT_INJECT="kill_at_step=7,enospc_at_ckpt_write=1"

Directives (value is always an integer):

=======================  ====================================================
``kill_at_step=K``       SIGKILL this process when optimizer step K completes
                         (fit's ``step`` point) — the preemptible-pool worker
                         loss that leaves NO chance to clean up.
``exit_at_step=K``       ``os._exit(77)`` at step K — abrupt but signal-free.
``preempt_at_step=K``    SIGTERM self at step K — drives the graceful
                         preemption drain instead of the hard kill.
``enospc_at_ckpt_write=N``  The N-th checkpoint file write raises
                         ``OSError(ENOSPC)`` (non-retryable: the atomic
                         writer must abort and leave prior checkpoints
                         intact).
``fail_ckpt_write=N``    The first N checkpoint file writes raise a
                         transient ``OSError(EIO)`` — the retry wrapper is
                         expected to absorb them.
``truncate_ckpt=1``      After the next checkpoint finalizes, truncate its
                         params file in place — the torn-storage case
                         resume must skip.
``delay_collective_ms=M``  Sleep M ms inside every cross-process collective
                         (the delayed-collective hang class).
``fail_recordio_read=N`` First N recordio reads raise transient EIO.
``fail_kv_push=N``       First N kvstore push bodies raise transient EIO.
``fail_kv_pull=N``       First N kvstore pull bodies raise transient EIO.
``replica_lost=R@K``     At step K, declare rank R lost: ``lost_R``
                         tombstone + back-dated ``hb_R`` in MXTPU_RUN_DIR
                         (that rank's HeartbeatWriter goes silent for
                         good), and if THIS process is rank R (DMLC_RANK)
                         it vanishes from subsequent host collectives —
                         the elastic shrink trigger, deterministic like
                         kill_at_step.
``heartbeat_stall=R@K``  At step K, freeze rank R's PROGRESS mark only
                         (``stall_R`` tombstone + back-dated ``prog_R``):
                         the alive-but-wedged-in-a-collective signature
                         stalled_nodes()/--progress-timeout catch.
``nan_grad_at_step=K``   Poison the batch feeding optimizer step K with
                         NaNs (fit's ``batch_poison`` hook) — the
                         gradient goes non-finite and the guardrail's
                         in-graph finite gate must skip it bitwise.
``loss_spike_at_step=K`` Scale the batch feeding step K by 1e4 — a
                         finite but wildly out-of-distribution loss /
                         grad-norm spike for the robust z detector.
``bad_record=N``         The first N record decodes raise ValueError
                         (``record_decode`` point) — drives the
                         quarantine path in ``_decode_chunk_payloads``
                         instead of the transport-level
                         ``fail_recordio_read``.
``kill_at_rewind=1``     SIGKILL this process inside fit's
                         rewind-to-last-good handler, after the
                         last-good checkpoint was chosen but before
                         restore completes — the SIGKILL-during-rewind
                         chain (a relaunch must still converge).
=======================  ====================================================

Values are integers except ``replica_lost``/``heartbeat_stall``, whose
``<rank>@<step>`` pairs parse to (rank, step) tuples; malformed values
are still ignored. Counters are per-process and keyed by the raw spec
string, so a monkeypatched spec in tests starts fresh. Stdlib-only and
importable standalone (tools and subprocess test scripts load it by
path) — which is why the run-dir file names it shares with
parallel/heartbeat.py are replicated here instead of imported.
"""
from __future__ import annotations

import errno
import os
import signal
import time

ENV = "MXTPU_FAULT_INJECT"

# (raw spec string, directive) -> times fired already
_fired = {}
_parse_cache = {}


def configured():
    """Whether any fault spec is active (the cheap hot-path guard)."""
    return bool(os.environ.get(ENV))


def _spec():
    raw = os.environ.get(ENV)
    if not raw:
        return None, None
    spec = _parse_cache.get(raw)
    if spec is None:
        spec = {}
        for part in raw.split(","):
            part = part.strip()
            if not part or "=" not in part:
                continue
            key, _, val = part.partition("=")
            try:
                spec[key.strip()] = int(val)
            except ValueError:
                if "@" in val:  # <rank>@<step> pair (replica_lost & co)
                    rank, _, step = val.partition("@")
                    try:
                        spec[key.strip()] = (int(rank), int(step))
                    except ValueError:
                        pass
                # else malformed directive: ignore, never crash the host
        _parse_cache[raw] = spec
    return raw, spec


def _take(raw, directive, limit):
    """Consume one firing budget unit; True while under ``limit``."""
    key = (raw, directive)
    n = _fired.get(key, 0)
    if n >= limit:
        return False
    _fired[key] = n + 1
    return True


def _transient(msg):
    return OSError(errno.EIO, "injected transient fault: %s" % msg)


def fire(point, **ctx):
    """Hit a named fault point. No-op unless MXTPU_FAULT_INJECT matches.

    Points: ``step`` (ctx: step), ``ckpt_write`` (ctx: path),
    ``ckpt_done`` (ctx: path), ``collective``, ``recordio_read``
    (ctx: uri, offset), ``record_decode`` (ctx: uri, ordinal),
    ``rewind`` (ctx: step), ``kv_push`` / ``kv_pull`` (ctx: key).
    """
    raw, spec = _spec()
    if not spec:
        return
    if point == "step":
        step = ctx.get("step")
        if spec.get("kill_at_step") == step and _take(raw, "kill", 1):
            os.kill(os.getpid(), signal.SIGKILL)
        if spec.get("exit_at_step") == step and _take(raw, "exit", 1):
            os._exit(77)
        if spec.get("preempt_at_step") == step and _take(raw, "preempt", 1):
            os.kill(os.getpid(), signal.SIGTERM)
        rl = spec.get("replica_lost")
        if (isinstance(rl, tuple) and rl[1] == step
                and _take(raw, "replica_lost", 1)):
            _mark_rank(rl[0], stall_only=False)
        hs = spec.get("heartbeat_stall")
        if (isinstance(hs, tuple) and hs[1] == step
                and _take(raw, "heartbeat_stall", 1)):
            _mark_rank(hs[0], stall_only=True)
    elif point == "ckpt_write":
        n = spec.get("enospc_at_ckpt_write")
        if n is not None:
            key = (raw, "enospc_seen")
            seen = _fired.get(key, 0) + 1
            _fired[key] = seen
            if seen == n:
                raise OSError(errno.ENOSPC,
                              "injected ENOSPC: %s" % ctx.get("path"))
        n = spec.get("fail_ckpt_write", 0)
        if n and _take(raw, "fail_ckpt_write", n):
            raise _transient("ckpt_write %s" % ctx.get("path"))
    elif point == "ckpt_done":
        if spec.get("truncate_ckpt", 0) and _take(raw, "truncate_ckpt", 1):
            _truncate_params(ctx.get("path"))
    elif point == "collective":
        ms = spec.get("delay_collective_ms", 0)
        if ms > 0:
            time.sleep(ms / 1000.0)
        rl = spec.get("replica_lost")
        # ctx local=True marks a single-process (local kvstore) reduce:
        # there is no peer to wedge, so the lost rank must keep running
        # until its own liveness goes stale — blocking here would hang
        # the only process in the job.
        if (isinstance(rl, tuple) and _fired.get((raw, "replica_lost"))
                and not ctx.get("local")
                and os.environ.get("DMLC_RANK") == str(rl[0])):
            # The lost rank drops out of the fleet's collectives: block
            # here indefinitely, the way a preempted peer would — its
            # survivors' progress marks go stale and the watchdog (or
            # fit's elastic guard on the peers) takes it from there.
            while True:
                time.sleep(60.0)
    elif point == "rewind":
        if spec.get("kill_at_rewind", 0) and _take(raw, "kill_at_rewind", 1):
            os.kill(os.getpid(), signal.SIGKILL)
    elif point == "record_decode":
        n = spec.get("bad_record", 0)
        if n and _take(raw, "bad_record", n):
            raise ValueError(
                "injected bad record: %s ordinal=%s"
                % (ctx.get("uri"), ctx.get("ordinal")))
    elif point == "recordio_read":
        n = spec.get("fail_recordio_read", 0)
        if n and _take(raw, "fail_recordio_read", n):
            raise _transient("recordio read %s@%s"
                             % (ctx.get("uri"), ctx.get("offset")))
    elif point == "kv_push":
        n = spec.get("fail_kv_push", 0)
        if n and _take(raw, "fail_kv_push", n):
            raise _transient("kv push key=%s" % ctx.get("key"))
    elif point == "kv_pull":
        n = spec.get("fail_kv_pull", 0)
        if n and _take(raw, "fail_kv_pull", n):
            raise _transient("kv pull key=%s" % ctx.get("key"))


def batch_poison(step):
    """Poison verdict for the batch feeding optimizer step ``step``:
    ``"nan"`` / ``"spike"`` / None. A separate entry point from
    :func:`fire` because the injection must ALTER the batch (fit
    rebuilds it poisoned), not raise or kill — each directive fires at
    most once per process, like the other ``*_at_step`` budgets."""
    raw, spec = _spec()
    if not spec:
        return None
    if (spec.get("nan_grad_at_step") == step
            and _take(raw, "nan_grad", 1)):
        return "nan"
    if (spec.get("loss_spike_at_step") == step
            and _take(raw, "loss_spike", 1)):
        return "spike"
    return None


_RUN_DIR_ENV = "MXTPU_RUN_DIR"


def _mark_rank(rank, stall_only):
    """File-level mirror of parallel/heartbeat.py ``mark_lost``
    (names replicated so this module stays stdlib-standalone): drop the
    tombstone and back-date the signal file so liveness pollers trip on
    their very next pass — no waiting out a staleness timeout."""
    directory = os.environ.get(_RUN_DIR_ENV)
    if not directory:
        return  # no run dir: nothing is polling liveness anyway
    tomb, sig = ("stall_", "prog_") if stall_only else ("lost_", "hb_")
    try:
        os.makedirs(directory, exist_ok=True)
        for prefix, backdate in ((tomb, False), (sig, True)):
            path = os.path.join(directory, "%s%d" % (prefix, int(rank)))
            with open(path, "a"):
                pass
            if backdate:
                os.utime(path, (1.0, 1.0))
    except OSError:
        pass  # injection is best-effort; never crash the host


def _truncate_params(ckpt_path):
    """Tear the params file of a finalized checkpoint in half — the
    storage-level corruption the manifest CRCs exist to catch."""
    if not ckpt_path:
        return
    target = os.path.join(ckpt_path, "state.params")
    if not os.path.isfile(target):
        return
    size = os.path.getsize(target)
    with open(target, "r+b") as f:
        f.truncate(max(1, size // 2))
