"""Fault tolerance for preemptible TPU training.

Four coordinated pieces (see docs/robustness.md):

* :mod:`.checkpoint` — atomic full-state checkpoints (temp + fsync +
  rename, CRC32 manifest, keep-last-N) and valid-checkpoint discovery.
* Preemption handling — ``Module.fit`` installs SIGTERM/SIGINT handlers
  when checkpointing is enabled, drains in-flight dispatch, writes a
  final checkpoint, and exits with :data:`EXIT_PREEMPTED`.
* Auto-resume — ``fit(..., checkpoint_dir=..., resume="auto")`` restores
  params, optimizer state, RNG, metrics, and data-iterator position from
  the newest checkpoint that verifies, for bitwise-exact continuation.
* :mod:`.retry` — jittered-exponential-backoff retries with transient
  error classification, shared by kvstore, recordio, and checkpoint I/O.

* :mod:`.guardrail` — numeric guardrails: streaming anomaly detection
  over loss/grad-norm, rewind-to-last-good (``fit(guardrails="auto")``),
  and the :data:`EXIT_GUARDRAIL` verdict when the rewind budget runs out.

:mod:`.fault` is the test-only injection switchboard driving the
crash-resume integration suite (``MXTPU_FAULT_INJECT``).
"""
from . import checkpoint, fault, guardrail, retry  # noqa: F401
from .checkpoint import (  # noqa: F401
    EXIT_PREEMPTED, EXIT_RESHAPE, CheckpointError, CheckpointManager,
    atomic_file, list_checkpoints, load_state, verify_checkpoint,
)
from .guardrail import (  # noqa: F401
    EXIT_GUARDRAIL, GuardrailMonitor, GuardrailRewind,
)
from .retry import TransientError, is_retryable  # noqa: F401
