"""Runtime kernel compilation — ``mx.rtc`` parity, Pallas edition.

Parity: reference ``python/mxnet/rtc.py`` + ``src/common/mxrtc.cc``
(N15): users hand the framework a CUDA C kernel *source string* at
runtime; it is NVRTC-compiled once, cached, and launched with
``push(ins, outs, grid, block)``.

TPU-native redesign: the kernel source is the BODY of a Pallas TPU
kernel instead of CUDA C. Parameter refs are in scope as ``<name>_ref``
(inputs first, then outputs) plus ``pl`` (jax.experimental.pallas),
``pltpu``, ``jnp`` and ``np``. Compilation is Mosaic instead of NVRTC,
the compile cache is keyed on (source, shapes, dtypes) exactly like the
reference's kernel-name cache, and off-TPU the same kernel runs under
the Pallas interpreter so RTC code is portable to tests.

``grid_dims`` maps to the Pallas ``grid``; ``block_dims`` has no
meaning on a TPU (Mosaic owns the on-chip tiling) and is accepted and
ignored for signature parity.

Example::

    x = mx.nd.ones((8, 128))
    y = mx.nd.zeros((8, 128))
    k = mx.rtc.Rtc('axpy', [('x', x)], [('y', y)],
                   "y_ref[...] = x_ref[...] * 2.0")
    k.push([x], [y], (1, 1, 1), (1, 1, 1))
"""
from __future__ import annotations

import textwrap

import jax
import numpy as np

from .base import MXNetError
from .ndarray import NDArray


class Rtc(object):
    def __init__(self, name, inputs, outputs, kernel):
        self.name = name
        self.in_names = [n for n, _ in inputs]
        self.out_names = [n for n, _ in outputs]
        self.kernel_source = kernel
        self._cache = {}

        ref_args = [n + "_ref" for n in self.in_names + self.out_names]
        src = "def _rtc_kernel(%s):\n%s" % (
            ", ".join(ref_args),
            textwrap.indent(textwrap.dedent(kernel), "    ") or "    pass",
        )
        import jax.numpy as jnp
        from jax.experimental import pallas as pl
        from jax.experimental.pallas import tpu as pltpu

        namespace = {"pl": pl, "pltpu": pltpu, "jnp": jnp, "np": np,
                     "jax": jax}
        try:
            exec(compile(src, "<rtc:%s>" % name, "exec"), namespace)
        except SyntaxError as e:
            raise MXNetError("Rtc %s: invalid kernel source: %s" % (name, e))
        self._kernel = namespace["_rtc_kernel"]
        self._pl = pl

    def _compiled(self, in_shapes, in_dtypes, out_shapes, out_dtypes, grid):
        key = (in_shapes, in_dtypes, out_shapes, out_dtypes, grid)
        fn = self._cache.get(key)
        if fn is None:
            interpret = jax.default_backend() != "tpu"
            kwargs = {} if grid is None else {"grid": grid}
            call = self._pl.pallas_call(
                self._kernel,
                out_shape=[
                    jax.ShapeDtypeStruct(s, d)
                    for s, d in zip(out_shapes, out_dtypes)
                ],
                interpret=interpret,
                **kwargs,
            )
            fn = jax.jit(call)
            self._cache[key] = fn
        return fn

    def push(self, ins, outs, grid_dims=(1, 1, 1), block_dims=None):
        """Run the kernel. ``ins``/``outs`` are NDArray lists matching the
        constructor templates; results are written into ``outs``."""
        del block_dims  # no thread-block concept on TPU (Mosaic tiles)
        if len(ins) != len(self.in_names) or len(outs) != len(self.out_names):
            raise MXNetError("Rtc %s: wrong number of arrays" % self.name)
        # strip only TRAILING unit dims: interior 1s must survive or
        # pl.program_id axis numbering shifts under the kernel
        grid = tuple(int(g) for g in grid_dims)
        while grid and grid[-1] == 1:
            grid = grid[:-1]
        grid = grid or None
        in_vals = [a._data if isinstance(a, NDArray) else a for a in ins]
        fn = self._compiled(
            tuple(tuple(v.shape) for v in in_vals),
            tuple(str(v.dtype) for v in in_vals),
            tuple(tuple(o.shape) for o in outs),
            tuple(str(np.dtype(o.dtype)) for o in outs),
            grid,
        )
        results = fn(*in_vals)
        for o, r in zip(outs, results):
            o[:] = np.asarray(r)
        return outs


def rtc(name, inputs, outputs, kernel):
    """Functional alias mirroring ``mx.rtc.Rtc``."""
    return Rtc(name, inputs, outputs, kernel)
