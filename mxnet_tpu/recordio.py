"""RecordIO: record-packed dataset files.

Parity: reference ``python/mxnet/recordio.py`` + dmlc-core's RecordIO
format (MXRecordIO/MXIndexedRecordIO readers/writers, IRHeader pack/unpack).
The binary format matches dmlc recordio (magic 0xced7230a, 4-byte-aligned
records, lrecord encoding) so .rec files made by the reference's im2rec
are readable.
"""
from __future__ import annotations

import ctypes
import os
import collections
import struct

import numpy as np

from .base import MXNetError
from .resilience import fault as _fault
from .resilience import retry as _retry

_MAGIC = 0xCED7230A
_KMAGIC_STRUCT = struct.Struct("<II")


def _encode_lrec(cflag, length):
    return (cflag << 29) | length


def _decode_lrec(data):
    cflag = (data >> 29) & 7
    length = data & ((1 << 29) - 1)
    return cflag, length


class MXRecordIO(object):
    """Sequential RecordIO reader/writer (parity recordio.py:17)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.handle = None
        self.is_open = False
        self.open()

    def open(self):
        if self.flag == "w":
            self.handle = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.handle = open(self.uri, "rb")
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        self.is_open = True

    def close(self):
        if not self.is_open:
            return
        self.handle.close()
        self.is_open = False

    def __del__(self):
        try:
            self.close()
        except (OSError, ValueError, AttributeError, TypeError, NameError):
            # interpreter teardown: builtins (open) may already be gone
            # (NameError/AttributeError/TypeError) or the fd is already
            # unusable (OSError/ValueError on a closed file); an
            # unflushed idx of a leaked writer is the caller's bug.
            # Anything else (e.g. corruption raised from a close-time
            # flush) propagates.
            pass

    def reset(self):
        if self.writable:
            # reopening with "wb" would silently truncate everything
            # written so far — there is no sane meaning for "rewind" on
            # a streaming writer, so make it an explicit error
            raise MXNetError(
                "%s: reset() on a write-mode MXRecordIO would truncate "
                "the file; close() it and open a reader instead"
                % self.uri)
        self.close()
        self.open()

    def write(self, buf):
        assert self.writable
        data = _KMAGIC_STRUCT.pack(_MAGIC, _encode_lrec(0, len(buf)))
        self.handle.write(data)
        self.handle.write(buf)
        pad = (4 - len(buf) % 4) % 4
        if pad:
            self.handle.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        start = self.handle.tell()

        def _attempt():
            # A transient read error mid-record must not leave the
            # cursor between fields — rewind so the retry re-reads the
            # whole record.
            self.handle.seek(start)
            _fault.fire("recordio_read", uri=self.uri, offset=start)
            header = self.handle.read(8)
            if not header:
                return None  # clean EOF on a record boundary
            if len(header) < 8:
                raise MXNetError(
                    "%s: truncated record header at offset %d "
                    "(%d of 8 bytes)" % (self.uri, start, len(header)))
            magic, lrec = _KMAGIC_STRUCT.unpack(header)
            if magic != _MAGIC:
                raise MXNetError(
                    "%s: invalid record magic 0x%08x at offset %d"
                    % (self.uri, magic, start))
            _, length = _decode_lrec(lrec)
            buf = self.handle.read(length)
            if len(buf) < length:
                raise MXNetError(
                    "%s: truncated record payload at offset %d "
                    "(%d of %d bytes)" % (self.uri, start, len(buf), length))
            pad = (4 - length % 4) % 4
            if pad and len(self.handle.read(pad)) < pad:
                raise MXNetError(
                    "%s: truncated record padding at offset %d"
                    % (self.uri, start))
            return buf

        return _retry.call(_attempt, name="recordio.read")

    def tell(self):
        return self.handle.tell()


class MXIndexedRecordIO(MXRecordIO):
    """Random-access RecordIO with .idx file (parity recordio.py:87).
    Reads go through the native mmap-indexed reader (src/recordio.cc) when
    available — the equivalent of the reference's dmlc RecordIO fast path."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        self._native = None
        self._key_to_ord = {}
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as fin:
                for line in fin.readlines():
                    line = line.strip().split("\t")
                    key = self.key_type(line[0])
                    self.idx[key] = int(line[1])
                    self.keys.append(key)
        if not self.writable:
            try:
                from .native import NativeRecordReader

                self._native = NativeRecordReader(self.uri)
                # The .idx file stores record-START byte offsets; the native
                # reader indexes PAYLOAD offsets (start + 8-byte header).
                # Match through the offsets — never list position: a sorted
                # or subset .idx would otherwise silently return the wrong
                # record.
                ord_by_payload = {
                    self._native.payload_offset(i): i
                    for i in range(len(self._native))
                }
                self._key_to_ord = {}
                for k in self.keys:
                    o = ord_by_payload.get(self.idx[k] + 8)
                    if o is not None:
                        self._key_to_ord[k] = o
            except (ImportError, OSError, MXNetError):
                # The native mmap reader is an optional fast path: a
                # missing extension, an unreadable file, or a format the
                # native indexer rejects all fall back to the pure-python
                # seek+read path. Index corruption surfaces from
                # read()/read_idx() with offset context instead of being
                # masked here.
                self._native = None
                self._key_to_ord = {}

    def close(self):
        if not self.is_open:
            return
        if self._native is not None:
            self._native.close()
            self._native = None
        self._key_to_ord = {}
        if self.writable:
            with open(self.idx_path, "w") as fout:
                for k in self.keys:
                    fout.write("%s\t%d\n" % (str(k), self.idx[k]))
        super().close()

    def seek(self, idx):
        assert not self.writable
        self.handle.seek(self.idx[idx])

    def read_idx(self, idx):
        if self._native is not None and idx in self._key_to_ord:
            return self._native.read(self._key_to_ord[idx])
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        self.idx[key] = self.tell()
        self.keys.append(key)
        self.write(buf)


# ---------------------------------------------------------------------------
# Chunked byte-range access (streaming input pipeline, io_pipeline.py).
#
# A .rec file is a flat sequence of 4-byte-aligned records; any record
# START offset is a valid resume point. Splitting the file into
# byte-range chunks lets hosts read disjoint data (shard by
# (host_rank, num_hosts)) and lets decode workers pull whole chunks
# with one sequential read each — the dmlc-core InputSplit design the
# reference's iter_image_recordio_2.cc builds on.

#: One contiguous run of records: [start, end) byte range, the global
#: ordinal of its first record, and how many records it holds.
RecordChunk = collections.namedtuple(
    "RecordChunk", ["start", "end", "ordinal", "n_records"])


def scan_record_offsets(uri):
    """Byte offset of every record start, by hopping header to header
    (reads 8 bytes per record, never the payloads). The no-.idx
    fallback for :func:`build_chunks`."""
    offsets = []
    size = os.path.getsize(uri)
    with open(uri, "rb") as f:
        pos = 0
        while pos + 8 <= size:
            f.seek(pos)
            header = f.read(8)
            if len(header) < 8:
                break
            magic, lrec = _KMAGIC_STRUCT.unpack(header)
            if magic != _MAGIC:
                raise MXNetError(
                    "%s: invalid record magic 0x%08x at offset %d"
                    % (uri, magic, pos))
            _, length = _decode_lrec(lrec)
            offsets.append(pos)
            pos += 8 + length + (4 - length % 4) % 4
    return offsets


def build_chunks(uri, idx_path=None, chunk_bytes=4 << 20):
    """Split a .rec file into record-aligned byte-range chunks of at
    least ``chunk_bytes`` each (the last one may be smaller). Offsets
    come from the sibling .idx when given (O(records) text parse, no
    data reads); otherwise from a header-hopping scan. Returns a list
    of :class:`RecordChunk` covering every record exactly once, in
    file order — shard it ``chunks[host_rank::num_hosts]`` for
    disjoint per-host reads."""
    offsets = None
    if idx_path and os.path.isfile(idx_path):
        offsets = []
        with open(idx_path) as fin:
            for line in fin:
                line = line.strip()
                if line:
                    offsets.append(int(line.split("\t")[1]))
        # .idx line order follows write order; a sorted/subset idx
        # would misalign ordinals — normalize to file order
        offsets.sort()
    if not offsets:
        offsets = scan_record_offsets(uri)
    if not offsets:
        return []
    size = os.path.getsize(uri)
    chunk_bytes = max(1, int(chunk_bytes))
    chunks = []
    start_i = 0
    for i in range(1, len(offsets) + 1):
        end = offsets[i] if i < len(offsets) else size
        if end - offsets[start_i] >= chunk_bytes or i == len(offsets):
            chunks.append(RecordChunk(
                start=offsets[start_i], end=end, ordinal=start_i,
                n_records=i - start_i))
            start_i = i
    return chunks


def split_chunk(buf, uri="<chunk>", base_offset=0):
    """Split one chunk's raw bytes into record payloads (the in-memory
    analog of sequential :meth:`MXRecordIO.read` calls)."""
    payloads = []
    pos = 0
    n = len(buf)
    while pos + 8 <= n:
        magic, lrec = _KMAGIC_STRUCT.unpack_from(buf, pos)
        if magic != _MAGIC:
            raise MXNetError(
                "%s: invalid record magic 0x%08x at offset %d"
                % (uri, magic, base_offset + pos))
        _, length = _decode_lrec(lrec)
        end = pos + 8 + length
        if end > n:
            raise MXNetError(
                "%s: truncated record payload at offset %d"
                % (uri, base_offset + pos))
        payloads.append(bytes(buf[pos + 8:end]))
        pos = end + (4 - length % 4) % 4
    return payloads


def read_chunk(handle, chunk, uri="<chunk>"):
    """One sequential read of ``chunk``'s byte range through an open
    binary ``handle``, split into record payloads."""
    handle.seek(chunk.start)
    buf = handle.read(chunk.end - chunk.start)
    if len(buf) < chunk.end - chunk.start:
        raise MXNetError(
            "%s: truncated chunk [%d, %d) — file shrank under the reader"
            % (uri, chunk.start, chunk.end))
    payloads = split_chunk(buf, uri=uri, base_offset=chunk.start)
    if len(payloads) != chunk.n_records:
        raise MXNetError(
            "%s: chunk at %d holds %d records, index said %d"
            % (uri, chunk.start, len(payloads), chunk.n_records))
    return payloads


# The user-facing header is a namedtuple exactly like the reference
# (recordio.py IRHeader); the wire layout is flag:uint32 label:float32
# id:uint64 id2:uint64.
IRHeader = collections.namedtuple("HEADER", ["flag", "label", "id", "id2"])
_HDR = struct.Struct("IfQQ")


def pack(header, s):
    """Pack (IRHeader, bytes) into a record payload (parity recordio.py:206)."""
    flag, label, id_, id2 = header
    if isinstance(label, (list, tuple, np.ndarray)) and not np.isscalar(label):
        label = np.asarray(label, dtype=np.float32)
        hdr = _HDR.pack(len(label), 0.0, id_, id2)
        return hdr + label.tobytes() + s
    return _HDR.pack(0, float(label), id_, id2) + s


def unpack(s):
    """Unpack a record payload into (IRHeader, bytes)."""
    flag, label, id_, id2 = _HDR.unpack(s[: _HDR.size])
    s = s[_HDR.size:]
    if flag > 0:
        label = np.frombuffer(s[: flag * 4], dtype=np.float32)
        s = s[flag * 4:]
    return IRHeader(flag, label, id_, id2), s


def unpack_img(s, iscolor=-1):
    """Unpack a record into (IRHeader, image ndarray) — decodes JPEG/PNG."""
    header, s = unpack(s)
    img = _imdecode_np(s, iscolor)
    return header, img


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack an image array into a record (uses PIL if available)."""
    import io as _io

    try:
        from PIL import Image
    except ImportError as e:
        raise MXNetError("pack_img requires PIL") from e
    buf = _io.BytesIO()
    fmt = "JPEG" if img_fmt in (".jpg", ".jpeg") else "PNG"
    Image.fromarray(img).save(buf, format=fmt, quality=quality)
    return pack(header, buf.getvalue())


def _imdecode_np(buf, iscolor=-1):
    import io as _io

    # native fast path: libjpeg through the GIL-releasing C library
    # (parallel decode across pool threads); non-JPEG payloads and
    # jpeg-less hosts fall through to PIL/cv2
    if len(buf) >= 2 and buf[0] == 0xFF and buf[1] == 0xD8:
        from . import native as _native

        img = _native.imdecode_jpeg(buf, gray=(iscolor == 0))
        if img is not None:
            return img

    try:
        from PIL import Image
    except ImportError:
        try:
            import cv2

            arr = np.frombuffer(buf, dtype=np.uint8)
            img = cv2.imdecode(arr, iscolor)
            return img[:, :, ::-1] if img is not None and img.ndim == 3 else img
        except ImportError as e:
            raise MXNetError("image decode requires PIL or cv2") from e
    img = Image.open(_io.BytesIO(buf))
    if iscolor == 0:
        img = img.convert("L")
    else:
        img = img.convert("RGB")
    return np.asarray(img)
