"""Logging helpers (parity: reference ``python/mxnet/log.py``).

A thin layer over ``logging`` adding the reference's level-colored
single-line format and a ``getLogger(name, filename, filemode, level)``
convenience.
"""
from __future__ import annotations

import logging
import sys

PY3 = sys.version_info[0] >= 3

CRITICAL = logging.CRITICAL
ERROR = logging.ERROR
WARNING = logging.WARNING
INFO = logging.INFO
DEBUG = logging.DEBUG
NOTSET = logging.NOTSET


class _Formatter(logging.Formatter):
    """Level-tagged (and tty-colored) format, reference log.py:22."""

    def __init__(self, colored=True):
        self.colored = colored
        super().__init__(datefmt="%m%d %H:%M:%S")

    def _color(self, level):
        return {
            logging.WARNING: "\x1b[0;33m",
            logging.ERROR: "\x1b[0;31m",
            logging.CRITICAL: "\x1b[0;35m",
        }.get(level, "\x1b[0;32m")

    def format(self, record):
        label = record.levelname[0]
        if self.colored and sys.stderr.isatty():
            head = "%s%s%%(asctime)s %%(process)d %%(pathname)s:%%(lineno)d]\x1b[0m" \
                % (self._color(record.levelno), label)
        else:
            head = "%s%%(asctime)s %%(process)d %%(pathname)s:%%(lineno)d]" % label
        # build a per-call formatter instead of mutating the SHARED
        # self._style._fmt: two handlers (or two threads) formatting
        # records of different levels concurrently would race on the
        # instance and emit each other's level tag/color
        return logging.Formatter(
            head + " %(message)s", datefmt=self.datefmt).format(record)


def getLogger(name=None, filename=None, filemode=None, level=WARNING):
    """Create/configure a logger (parity log.py:48)."""
    logger = logging.getLogger(name)
    if name is not None and not getattr(logger, "_init_done", False):
        logger._init_done = True
        if filename:
            mode = filemode if filemode else "a"
            hdlr = logging.FileHandler(filename, mode)
        else:
            hdlr = logging.StreamHandler()
        hdlr.setFormatter(_Formatter())
        logger.addHandler(hdlr)
        logger.setLevel(level)
    return logger
