"""KVStore server bootstrap — reference API parity for the PS tier.

Parity: reference ``python/mxnet/kvstore_server.py`` — in the reference,
a process whose ``DMLC_ROLE`` is ``server``/``scheduler`` blocks inside
``import mxnet`` running a ps-lite server loop, and the rank-0 worker
ships it a pickled Optimizer via ``SendCommandToServers(0, ...)``
(SURVEY.md N9, §3.4).

TPU-native redesign (SURVEY.md §5.8): there IS no server tier — gradient
synchronization is an XLA all-reduce over ICI/DCN inside the compiled
training step, and the optimizer runs (replicated or ZeRO-sharded) on
the workers themselves. This module therefore exists to (a) give
launcher scripts that still set ``DMLC_ROLE=server`` a well-defined,
documented no-op path instead of a crash, and (b) keep the controller
command protocol (command 0 = pickled optimizer) testable.
"""
from __future__ import annotations

import logging
import os
import pickle


class KVStoreServer(object):
    """Command-loop shim for reference server processes
    (parity kvstore_server.py:24 ``KVStoreServer``)."""

    def __init__(self, kvstore):
        self.kvstore = kvstore
        self.handlers = {}
        self._running = False

    def _controller(self, cmd_id, cmd_body):
        """Parity kvstore_server.py:35: command 0 installs the pickled
        optimizer as the store's updater."""
        if cmd_id == 0:
            optimizer = pickle.loads(cmd_body)
            self.kvstore.set_optimizer(optimizer)
        else:
            handler = self.handlers.get(cmd_id)
            if handler is None:
                logging.warning("server got unknown command %d", cmd_id)
            else:
                handler(cmd_body)

    def run(self, commands=()):
        """Process controller commands. The reference blocks forever on
        ZMQ; with the PS tier deleted there is nothing to wait on, so
        this drains the given commands and returns."""
        self._running = True
        for cmd_id, cmd_body in commands:
            self._controller(cmd_id, cmd_body)
        self._running = False


def _init_kvstore_server_module():
    """Parity kvstore_server.py:58 / __init__.py:37: called at import.

    In the reference this never returns for server/scheduler roles. Here
    non-worker roles log that the PS tier is subsumed by in-step XLA
    collectives and return immediately, so a reference launcher that
    still spawns servers degrades to harmless processes.
    """
    role = os.environ.get("DMLC_ROLE", "worker")
    if role in ("server", "scheduler"):
        logging.info(
            "DMLC_ROLE=%s: no parameter-server tier in the TPU-native "
            "build (gradient sync is an XLA collective inside the "
            "compiled step); role is a no-op.", role)
    return role
