"""Tools parity tests: im2rec packer (reference tools/im2rec.*),
launch.py env contract (tools/launch.py + dmlc tracker), and the
allreduce bandwidth measure (tools/bandwidth/measure.py)."""
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")
sys.path.insert(0, TOOLS)


def _write_images(root, n_per_class=3, classes=("cat", "dog")):
    from PIL import Image

    rng = np.random.RandomState(0)
    for cls in classes:
        d = os.path.join(root, cls)
        os.makedirs(d, exist_ok=True)
        for i in range(n_per_class):
            arr = (rng.rand(24, 32, 3) * 255).astype(np.uint8)
            Image.fromarray(arr).save(os.path.join(d, "img%d.jpg" % i))


def test_im2rec_list_pack_and_iterate(tmp_path):
    import im2rec

    root = str(tmp_path / "imgs")
    _write_images(root)
    prefix = str(tmp_path / "data")
    out, classes = im2rec.make_list(prefix, root)
    assert len(classes) == 2
    lines = open(out).read().strip().splitlines()
    assert len(lines) == 6

    n = im2rec.pack(prefix, root, num_workers=1, resize=0)
    assert n == 6
    assert os.path.exists(prefix + ".rec") and os.path.exists(prefix + ".idx")

    # records round-trip through the recordio reader
    reader = mx.recordio.MXIndexedRecordIO(prefix + ".idx", prefix + ".rec",
                                           "r")
    assert len(reader.keys) == 6
    header, img = mx.recordio.unpack_img(reader.read_idx(reader.keys[0]))
    assert img.shape == (24, 32, 3)
    reader.close()

    # and feed training through the ImageRecordIter surface
    it = mx.io.ImageRecordIter(path_imgrec=prefix + ".rec",
                               data_shape=(3, 24, 24), batch_size=2,
                               rand_crop=True, shuffle=False)
    batch = next(iter(it))
    assert batch.data[0].shape == (2, 3, 24, 24)
    assert batch.label[0].shape == (2,)


def test_launch_local_env_contract(tmp_path):
    import launch

    env = launch.worker_env(2, 4, "127.0.0.1:29500")
    assert env["JAX_PROCESS_ID"] == "2"
    assert env["DMLC_RANK"] == "2"
    assert env["DMLC_NUM_WORKER"] == "4"
    assert env["DMLC_ROLE"] == "worker"

    script = tmp_path / "worker.py"
    script.write_text(
        "import os, sys\n"
        "sys.exit(0 if os.environ['DMLC_RANK'] in '0123' and "
        "os.environ['DMLC_NUM_WORKER'] == '2' else 1)\n")
    rc = launch.launch_local(2, [sys.executable, str(script)])
    assert rc == 0


def test_bandwidth_measure_runs():
    sys.path.insert(0, os.path.join(TOOLS, "bandwidth"))
    import measure

    results = measure.measure(sizes_mb=(0.25,), iters=2)
    assert results[0]["devices"] >= 1
    assert results[0]["busbw_GBps"] >= 0.0


def test_bandwidth_kvstore_mode():
    """Reference-parity mode (tools/bandwidth/measure.py --network):
    real per-layer model gradients through the product KVStore, merged
    result must match the numpy oracle exactly (error == 0), both with
    and without the optimizer applied on the store."""
    sys.path.insert(0, os.path.join(TOOLS, "bandwidth"))
    import measure

    rows = measure.measure_kvstore(
        network="mlp", ndev=3, kv_store="local", num_batches=2,
        image_shape="1,28,28", num_classes=10)
    assert len(rows) == 2
    # Tolerance (not exact zero): a pairwise/tree device reduction is a
    # legitimate KVStore implementation and reorders the float sums.
    assert all(r["error"] < 1e-6 for r in rows)
    rows = measure.measure_kvstore(
        network="mlp", ndev=2, kv_store="device", num_batches=2,
        image_shape="1,28,28", num_classes=10, optimizer="sgd")
    assert all(r["error"] < 1e-6 for r in rows)


def test_op_docs_fresh():
    """docs/op_docs.md must match the live registry (tools/gen_op_docs.py
    --check is the CI freshness hook; SURVEY §5.6 docgen surface)."""
    r = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "gen_op_docs.py"),
         "--check"],
        capture_output=True, text=True, timeout=240)
    assert r.returncode == 0, r.stdout + r.stderr


def test_launch_tracker_modes_dry_run(tmp_path, capsys, monkeypatch):
    """mpi/sge/yarn trackers (reference dmlc tracker parity): --dry-run
    emits a submission command wrapping the rank shim; the shim itself
    must map every scheduler's rank variable onto the JAX/DMLC env
    contract and exec the command."""
    import launch

    # mpi/sge/yarn all write the shim into cwd: remote tasks see the
    # submit dir via the shared filesystem, never this node's /tmp
    # (ADVICE r5 — the mpi shim used to land in /tmp and broke
    # multi-node runs with file-not-found)
    monkeypatch.chdir(tmp_path)

    for mode, fn, kw in (
            ("mpi", launch.launch_mpi, {}),
            ("sge", launch.launch_sge, {"queue": "batch.q"}),
            ("yarn", launch.launch_yarn, {})):
        rc = fn(3, ["python", "train.py"], dry_run=True, **kw)
        assert rc == 0, mode
        out = capsys.readouterr().out
        shim = next(tok for tok in out.split()
                    if "mxtpu_launch_" in tok).rstrip("'\"")
        shim = shim.split("=")[-1]
        assert os.path.dirname(os.path.abspath(shim)) == str(tmp_path), mode
        body = open(shim).read()
        assert "JAX_NUM_PROCESSES=\"3\"" in body, mode
        assert "DMLC_NUM_WORKER=\"3\"" in body, mode
        assert "exec python train.py" in body, mode
        if mode == "sge":
            assert "-t 1-3" in out
            assert "-q batch.q" in out
        if mode == "yarn":
            assert "-num_containers 3" in out

    # the shim's rank mapping, executed for real under each scheduler's
    # env convention (mpi OMPI var; sge task id is 1-based)
    echo = tmp_path / "echo_rank.sh"
    echo.write_text("#!/bin/sh\necho rank=$DMLC_RANK\n")
    echo.chmod(0o755)
    shim = launch._write_rank_shim(4, "127.0.0.1:29500",
                                   ["sh", str(echo)])
    for envvar, value, want in (("OMPI_COMM_WORLD_RANK", "2", "rank=2"),
                                ("SGE_TASK_ID", "3", "rank=2")):
        env = {k: v for k, v in os.environ.items()
               if k not in ("OMPI_COMM_WORLD_RANK", "SGE_TASK_ID")}
        env[envvar] = value
        r = subprocess.run(["sh", shim], capture_output=True, text=True,
                           env=env, timeout=30)
        assert r.stdout.strip() == want, (envvar, r.stdout, r.stderr)


def test_ckpt_inspect_cli_self_test():
    repo = os.path.join(os.path.dirname(__file__), "..")
    res = subprocess.run(
        [sys.executable, "-m", "tools.ckpt_inspect", "--self-test"],
        cwd=repo, capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stderr
    assert "self-test passed" in res.stdout


def test_watchdog_cli_self_test():
    """Elastic restart decision table + stub-job supervision end to end
    (dead rank -> shrink, exit-75 -> same-size retry, exhausted budget
    -> fail)."""
    repo = os.path.join(os.path.dirname(__file__), "..")
    res = subprocess.run(
        [sys.executable, "-m", "tools.watchdog", "--self-test"],
        cwd=repo, capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stderr
    assert "self-test passed" in res.stdout


def test_watchdog_decision_table_rows():
    from tools import watchdog

    # the three ISSUE rows, pinned here as well as in --self-test
    assert watchdog.decide(
        watchdog.EXIT_RESHAPE, [3], 0, 2, 8, True) == ("shrink", 7)
    assert watchdog.decide(
        watchdog.EXIT_PREEMPTED, [], 0, 2, 8, True) == ("retry", 8)
    assert watchdog.decide(1, [], 2, 2, 8, True) == ("fail", 8)
    # shrink is budget-free; elastic off never shrinks
    assert watchdog.decide(
        watchdog.EXIT_RESHAPE, [3], 2, 2, 8, True) == ("shrink", 7)
    assert watchdog.decide(
        watchdog.EXIT_RESHAPE, [3], 2, 2, 8, False) == ("fail", 8)


def test_fleet_top_cli_self_test():
    """Synthetic 3-rank run dir -> straggler table + Prometheus format
    checker (accepts merged registry output, rejects malformed text)."""
    repo = os.path.join(os.path.dirname(__file__), "..")
    res = subprocess.run(
        [sys.executable, "-m", "tools.fleet_top", "--self-test"],
        cwd=repo, capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stderr
    assert "self-test passed" in res.stdout


def test_fleet_top_prometheus_checker():
    from tools import fleet_top

    good = ("# HELP h help text\n"
            "# TYPE h histogram\n"
            'h_bucket{le="1"} 2\nh_bucket{le="+Inf"} 5\n'
            "h_sum 7.5\nh_count 5\n"
            "# TYPE g gauge\n"
            'g{rank="0"} 1.25e-3\n')
    assert fleet_top.check_prometheus_text(good) == []
    # malformed sample line
    assert fleet_top.check_prometheus_text('metric{le="x} 1\n')
    # non-cumulative buckets
    bad = ("# TYPE h histogram\n"
           'h_bucket{le="1"} 5\nh_bucket{le="+Inf"} 3\nh_count 3\n')
    assert fleet_top.check_prometheus_text(bad)
    # +Inf bucket must be present and equal _count
    bad = ("# TYPE h histogram\n"
           'h_bucket{le="1"} 3\nh_count 3\n')
    assert fleet_top.check_prometheus_text(bad)


def test_perf_doctor_cli_self_test():
    repo = os.path.join(os.path.dirname(__file__), "..")
    res = subprocess.run(
        [sys.executable, "-m", "tools.perf_doctor", "--self-test"],
        cwd=repo, capture_output=True, text=True, timeout=120)
    assert res.returncode == 0, res.stderr
    assert "self-test passed" in res.stdout


def test_ckpt_inspect_cli_on_real_checkpoints(tmp_path, capsys):
    from mxnet_tpu.resilience import checkpoint as ck
    from tools import ckpt_inspect

    mgr = ck.CheckpointManager(str(tmp_path), keep=5)
    state = {
        "module": {"arg": {"w": np.eye(3, dtype=np.float32)},
                   "aux": {}, "opt": {"kind": "none"}},
        "epoch": 0, "nbatch": 4, "global_step": 4,
        "metric": None, "rng": {},
    }
    mgr.save(state, 4)

    assert ckpt_inspect.main([str(tmp_path), "--verify"]) == 0
    assert "OK (deep)" in capsys.readouterr().out

    assert ckpt_inspect.main([str(tmp_path), "--state", "latest"]) == 0
    out = capsys.readouterr().out
    assert "global_step: 4" in out
    assert "arg:w" in out

    # a torn member must flip both the listing and the exit code
    params = os.path.join(ck.step_dir(str(tmp_path), 4), ck.PARAMS_FILE)
    with open(params, "r+b") as f:
        f.truncate(8)
    assert ckpt_inspect.main([str(tmp_path)]) == 1
    assert "CORRUPT" in capsys.readouterr().out
