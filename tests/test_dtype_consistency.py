"""Runtime dtype must match declared (inferred) dtype for every layer
op in a reduced-precision graph — in BOTH train and inference modes.

Motivated by the BatchNorm inference bug (f32 moving stats upcast the
bf16 activation stream; the next conv crashed on mixed dtypes): type
inference promises downstream ops data.dtype, so any op that silently
promotes breaks the chain. This sweep binds each layer under a
bfloat16 cast and checks the output dtype both ways.
"""
import numpy as np
import pytest

import mxnet_tpu as mx

LAYERS = [
    ("conv", lambda x: mx.sym.Convolution(
        x, kernel=(3, 3), num_filter=4, pad=(1, 1), name="op")),
    ("deconv", lambda x: mx.sym.Deconvolution(
        x, kernel=(2, 2), stride=(2, 2), num_filter=4, name="op")),
    ("pool_max", lambda x: mx.sym.Pooling(
        x, kernel=(2, 2), stride=(2, 2), pool_type="max")),
    ("pool_avg", lambda x: mx.sym.Pooling(
        x, kernel=(2, 2), stride=(2, 2), pool_type="avg")),
    ("bn", lambda x: mx.sym.BatchNorm(x, name="op")),
    ("lrn", lambda x: mx.sym.LRN(x, nsize=3)),
    ("act", lambda x: mx.sym.Activation(x, act_type="relu")),
    ("leaky", lambda x: mx.sym.LeakyReLU(x, act_type="leaky")),
    ("dropout", lambda x: mx.sym.Dropout(x, p=0.3)),
    ("fc", lambda x: mx.sym.FullyConnected(
        mx.sym.Flatten(x), num_hidden=6, name="op")),
    ("concat_self", lambda x: mx.sym.Concat(x, x)),
    ("elemwise", lambda x: x + x * 0.5),
    ("softmax_act", lambda x: mx.sym.SoftmaxActivation(
        mx.sym.Flatten(x))),
]


@pytest.mark.parametrize("name,layer", LAYERS, ids=[n for n, _ in LAYERS])
@pytest.mark.parametrize("is_train", [True, False],
                         ids=["train", "infer"])
def test_layer_preserves_bf16(name, layer, is_train):
    data = mx.sym.Variable("data")
    net = layer(mx.sym.Cast(data, dtype="bfloat16"))
    declared = net.infer_type(data="float32")[1][0]
    assert np.dtype(declared).name == "bfloat16", (
        "%s DECLARES %s for a bf16 input" % (name, declared))
    exe = net.simple_bind(ctx=mx.cpu(), data=(2, 3, 8, 8),
                          grad_req="null")
    rng = np.random.RandomState(0)
    for k, a in exe.arg_dict.items():
        if k != "data":
            a[:] = (rng.rand(*a.shape).astype(np.float32) - 0.5)
    exe.arg_dict["data"][:] = rng.rand(2, 3, 8, 8).astype(np.float32)
    out = exe.forward(is_train=is_train)[0]
    got = out.asnumpy().dtype
    assert got.name == "bfloat16", (
        "%s emits %s at runtime for a bf16 input (%s mode) — type "
        "inference promised bfloat16 downstream"
        % (name, got, "train" if is_train else "infer"))
