"""tools/caffe_converter: self-contained Caffe -> mxnet_tpu conversion
(reference tools/caffe_converter/{convert_symbol,convert_model}.py —
which need caffe importable; ours parses the protobuf wire/text formats
directly, so it must be validated against independently-encoded bytes).

The test hand-encodes a .caffemodel with its own minimal protobuf
writer (varints, length-delimited messages, packed floats — the wire
spec, not shared code with the converter's reader) and uses torch as
the numerical oracle: caffe semantics map onto
conv2d / max_pool2d(ceil_mode=True) / batch_norm / linear / softmax.
"""
import os
import struct
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "tools"))

import mxnet_tpu as mx
import caffe_converter as cc


# --- minimal protobuf wire writer (test-side, independent of the reader) ---

def _varint(x):
    out = b""
    while True:
        b = x & 0x7F
        x >>= 7
        if x:
            out += bytes([b | 0x80])
        else:
            return out + bytes([b])


def _tag(field, wtype):
    return _varint((field << 3) | wtype)


def _ld(field, payload):
    return _tag(field, 2) + _varint(len(payload)) + payload


def _s(field, text):
    return _ld(field, text.encode())


def _packed_f32(field, values):
    return _ld(field, struct.pack("<%df" % len(values),
                                  *[float(v) for v in values]))


def _packed_i64(field, values):
    return _ld(field, b"".join(_varint(int(v)) for v in values))


def _blob(arr):
    arr = np.asarray(arr, np.float32)
    shape = _ld(7, _packed_i64(1, arr.shape))
    return shape + _packed_f32(5, arr.reshape(-1))


def _layer(name, ltype, blobs=()):
    payload = _s(1, name) + _s(2, ltype)
    for b in blobs:
        payload += _ld(7, _blob(b))
    return _ld(100, payload)  # NetParameter.layer


PROTOTXT = """
name: "tiny"
input: "data"
input_dim: 2 input_dim: 3 input_dim: 8 input_dim: 8
layer { name: "conv1" type: "Convolution" bottom: "data" top: "conv1"
  convolution_param { num_output: 4 kernel_size: 3 pad: 1 stride: 1 } }
layer { name: "relu1" type: "ReLU" bottom: "conv1" top: "conv1" }
layer { name: "pool1" type: "Pooling" bottom: "conv1" top: "pool1"
  pooling_param { pool: MAX kernel_size: 3 stride: 2 } }
layer { name: "bn1" type: "BatchNorm" bottom: "pool1" top: "bn1"
  batch_norm_param { use_global_stats: true eps: 1e-5 } }
layer { name: "scale1" type: "Scale" bottom: "bn1" top: "bn1"
  scale_param { bias_term: true } }
layer { name: "fc1" type: "InnerProduct" bottom: "bn1" top: "fc1"
  inner_product_param { num_output: 5 } }
layer { name: "prob" type: "Softmax" bottom: "fc1" top: "prob" }
"""


@pytest.fixture
def tiny_model(tmp_path):
    rng = np.random.RandomState(0)
    w_conv = rng.randn(4, 3, 3, 3).astype(np.float32) * 0.3
    b_conv = rng.randn(4).astype(np.float32) * 0.1
    bn_mean = rng.randn(4).astype(np.float32) * 0.2
    bn_var = (rng.rand(4).astype(np.float32) + 0.5)
    bn_scale = np.asarray([2.0], np.float32)  # caffe stores mean*factor
    gamma = rng.rand(4).astype(np.float32) + 0.5
    beta = rng.randn(4).astype(np.float32) * 0.1
    # pool1 of 8x8 with k3/s2 ceil-mode -> 4x4 spatial
    w_fc = rng.randn(5, 4 * 4 * 4).astype(np.float32) * 0.1
    b_fc = rng.randn(5).astype(np.float32) * 0.1

    net = (_s(1, "tiny")
           + _layer("conv1", "Convolution", [w_conv, b_conv])
           + _layer("bn1", "BatchNorm",
                    [bn_mean * bn_scale[0], bn_var * bn_scale[0],
                     bn_scale])
           + _layer("scale1", "Scale", [gamma, beta])
           + _layer("fc1", "InnerProduct", [w_fc, b_fc]))
    prototxt = tmp_path / "tiny.prototxt"
    prototxt.write_text(PROTOTXT)
    caffemodel = tmp_path / "tiny.caffemodel"
    caffemodel.write_bytes(net)
    weights = dict(w_conv=w_conv, b_conv=b_conv, bn_mean=bn_mean,
                   bn_var=bn_var, gamma=gamma, beta=beta, w_fc=w_fc,
                   b_fc=b_fc)
    return str(prototxt), str(caffemodel), weights


def _torch_forward(x, w):
    import torch
    import torch.nn.functional as F

    t = torch.from_numpy(x)
    t = F.conv2d(t, torch.from_numpy(w["w_conv"]),
                 torch.from_numpy(w["b_conv"]), padding=1)
    t = F.relu(t)
    t = F.max_pool2d(t, 3, stride=2, ceil_mode=True)  # caffe convention
    t = F.batch_norm(t, torch.from_numpy(w["bn_mean"]),
                     torch.from_numpy(w["bn_var"]),
                     torch.from_numpy(w["gamma"]),
                     torch.from_numpy(w["beta"]), training=False,
                     eps=1e-5)
    t = F.linear(t.reshape(t.shape[0], -1), torch.from_numpy(w["w_fc"]),
                 torch.from_numpy(w["b_fc"]))
    return F.softmax(t, dim=1).numpy()


def test_convert_model_matches_torch_oracle(tiny_model, tmp_path):
    prototxt, caffemodel, w = tiny_model
    prefix = str(tmp_path / "converted")
    sym, args, auxs = cc.convert_model(prototxt, caffemodel, prefix)
    assert os.path.exists(prefix + "-symbol.json")
    assert os.path.exists(prefix + "-0000.params")

    # the Scale layer folded into bn1's gamma/beta; the stored caffe
    # mean/var were scaled by the factor blob and must be unscaled
    np.testing.assert_allclose(args["bn1_gamma"].asnumpy(), w["gamma"])
    np.testing.assert_allclose(auxs["bn1_moving_mean"].asnumpy(),
                               w["bn_mean"], rtol=1e-6)

    exe = sym.simple_bind(ctx=mx.cpu(), data=(2, 3, 8, 8))
    for k, v in args.items():
        exe.arg_dict[k][:] = v.asnumpy()
    for k, v in auxs.items():
        exe.aux_dict[k][:] = v.asnumpy()
    x = np.random.RandomState(7).randn(2, 3, 8, 8).astype(np.float32)
    exe.arg_dict["data"][:] = x
    got = exe.forward(is_train=False)[0].asnumpy()
    want = _torch_forward(x, w)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_converted_checkpoint_loads_as_module(tiny_model, tmp_path):
    prototxt, caffemodel, w = tiny_model
    prefix = str(tmp_path / "ckpt")
    cc.convert_model(prototxt, caffemodel, prefix)
    sym, args, auxs = mx.model.load_checkpoint(prefix, 0)
    mod = mx.mod.Module(sym, label_names=[])
    mod.bind(data_shapes=[("data", (2, 3, 8, 8))], for_training=False)
    mod.set_params(args, auxs)
    x = np.random.RandomState(7).randn(2, 3, 8, 8).astype(np.float32)
    mod.forward(mx.io.DataBatch([mx.nd.array(x)], []))
    out = mod.get_outputs()[0].asnumpy()
    np.testing.assert_allclose(out, _torch_forward(x, w),
                               rtol=1e-4, atol=1e-5)


def test_v1_binary_layers_normalize(tmp_path):
    """Legacy V1 'layers' (NetParameter field 2; V1LayerParameter
    name=4 / type=5 enum / blobs=6) parse into the same normalized
    BinLayer form the modern format yields."""
    w = np.arange(12, dtype=np.float32).reshape(2, 2, 3)
    v1_layer = (_s(4, "conv1") + _tag(5, 0) + _varint(4)  # CONVOLUTION
                + _ld(6, _blob(w)))
    net = _s(1, "old") + _ld(2, v1_layer)
    p = tmp_path / "old.caffemodel"
    p.write_bytes(net)
    layers = cc.parse_caffemodel(str(p))
    assert [(l.name, l.type) for l in layers] == [("conv1", "Convolution")]
    np.testing.assert_array_equal(layers[0].blobs[0], w)


def test_v1_prototxt_normalizes():
    proto = cc.parse_prototxt("""
    name: "old"
    input: "data" input_dim: 1 input_dim: 3 input_dim: 4 input_dim: 4
    layers { name: "conv1" type: CONVOLUTION bottom: "data" top: "conv1"
      convolution_param { num_output: 2 kernel_size: 3 } }
    layers { name: "relu1" type: RELU bottom: "conv1" top: "conv1" }
    layers { name: "loss" type: SOFTMAX_LOSS bottom: "conv1" top: "loss" }
    """)
    layers = cc._proto_layers(proto)
    assert [l["type"][-1] for l in layers] == [
        "Convolution", "ReLU", "SoftmaxWithLoss"]


def test_convert_mean_roundtrip(tmp_path):
    mean = np.random.RandomState(0).rand(3, 4, 4).astype(np.float32)
    p = tmp_path / "mean.binaryproto"
    p.write_bytes(_blob(mean))
    nd = cc.convert_mean(str(p), str(tmp_path / "mean.nd"))
    np.testing.assert_allclose(nd.asnumpy(), mean)
    loaded = mx.nd.load(str(tmp_path / "mean.nd"))["mean_image"]
    np.testing.assert_allclose(loaded.asnumpy(), mean)


def test_prototxt_parser_roundtrips_structure():
    proto = cc.parse_prototxt(PROTOTXT)
    assert proto["name"][-1] == "tiny"
    assert [int(d) for d in proto["input_dim"]] == [2, 3, 8, 8]
    layers = proto["layer"]
    assert [l["type"][-1] for l in layers] == [
        "Convolution", "ReLU", "Pooling", "BatchNorm", "Scale",
        "InnerProduct", "Softmax"]
    assert layers[0]["convolution_param"][-1]["num_output"][-1] == 4


def test_repeated_per_axis_params():
    """caffe's `repeated uint32` conv params: two entries mean (h, w),
    one means square, explicit _h/_w win."""
    proto = cc.parse_prototxt("""
    layer { name: "c" type: "Convolution" bottom: "data" top: "c"
      convolution_param { num_output: 2
        kernel_size: 3 kernel_size: 2
        stride: 2 stride: 1
        pad: 1 pad: 0 } }
    """)
    p = proto["layer"][0]["convolution_param"][-1]
    assert cc._xy(p, "kernel_size", "kernel_h", "kernel_w", None) == (3, 2)
    assert cc._xy(p, "stride", "stride_h", "stride_w", (1, 1)) == (2, 1)
    assert cc._xy(p, "pad", "pad_h", "pad_w", (0, 0)) == (1, 0)
    p2 = cc.parse_prototxt(
        'p { kernel_size: 3 kernel_h: 5 kernel_w: 4 }')["p"][-1]
    assert cc._xy(p2, "kernel_size", "kernel_h", "kernel_w", None) == (5, 4)


def test_one_sided_hw_params():
    """A lone pad_h / kernel_w etc. is legal caffe and must not
    KeyError: the absent side falls back to the repeated single value,
    then the default, then mirrors the present side (ADVICE r5)."""
    assert cc._xy({"pad_h": ["2"]}, "pad", "pad_h", "pad_w",
                  (0, 0)) == (2, 0)
    assert cc._xy({"stride_w": ["3"]}, "stride", "stride_h", "stride_w",
                  (1, 1)) == (1, 3)
    # the single-value entry supplies the missing side before the default
    assert cc._xy({"kernel_size": ["7"], "kernel_h": ["5"]},
                  "kernel_size", "kernel_h", "kernel_w", None) == (5, 7)
    # no single value, no default (kernel): mirror the present side
    assert cc._xy({"kernel_w": ["3"]}, "kernel_size", "kernel_h",
                  "kernel_w", None) == (3, 3)


def test_scale_pairs_by_topology_not_file_order(tmp_path):
    """Two BNs then one Scale consuming the FIRST BN's top: the folded
    gamma/beta must land on bn_a (topology), not bn_b (file order)."""
    prototxt = tmp_path / "two_bn.prototxt"
    prototxt.write_text("""
    name: "twobn"
    input: "data" input_dim: 1 input_dim: 2 input_dim: 4 input_dim: 4
    layer { name: "bn_a" type: "BatchNorm" bottom: "data" top: "a"
      batch_norm_param { use_global_stats: true } }
    layer { name: "bn_b" type: "BatchNorm" bottom: "a" top: "b"
      batch_norm_param { use_global_stats: true } }
    layer { name: "sc" type: "Scale" bottom: "a" top: "a2"
      scale_param { bias_term: true } }
    """)
    # NOTE: caffe graphs are dataflow; 'sc' reads blob "a" (bn_a's top)
    gamma = np.asarray([2.0, 3.0], np.float32)
    beta = np.asarray([0.5, -0.5], np.float32)
    zeros2 = np.zeros(2, np.float32)
    ones2 = np.ones(2, np.float32)
    one = np.ones(1, np.float32)
    net = (_s(1, "twobn")
           + _layer("bn_a", "BatchNorm", [zeros2, ones2, one])
           + _layer("bn_b", "BatchNorm", [zeros2, ones2, one])
           + _layer("sc", "Scale", [gamma, beta]))
    caffemodel = tmp_path / "two_bn.caffemodel"
    caffemodel.write_bytes(net)
    _, args, _ = cc.convert_model(str(prototxt), str(caffemodel))
    np.testing.assert_allclose(args["bn_a_gamma"].asnumpy(), gamma)
    np.testing.assert_allclose(args["bn_a_beta"].asnumpy(), beta)
    np.testing.assert_allclose(args["bn_b_gamma"].asnumpy(), ones2)
