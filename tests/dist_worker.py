"""Worker body for the 2-process distributed tests (test_dist.py).

Launched N times by tools/launch.py local mode; each process joins the
JAX distributed runtime over the coordinator env the launcher set, then
proves the three things a distributed MXNet worker needs (reference
proof: tests/nightly/dist_sync_kvstore.py + dist_lenet.py):

1. dist_sync KVStore push/pull crosses the process boundary with the
   reference's deterministic cross-worker sum.
2. barrier() actually synchronizes processes (measured skew, not
   vibes: rank 0 must WAIT for the sleeping peer).
3. the fused ShardedTrainStep runs over a mesh SPANNING processes:
   gradients psum over dp across the process boundary inside the
   compiled step, loss falls, and ranks stay bit-identical.

Writes rank{r}.json into --out; any assertion kills the worker and the
launcher's exit code fails the pytest.
"""
import argparse
import json
import os
import sys
import time

# Platform routing must happen before ANY jax backend touch: 2 local CPU
# devices per process so the global mesh (4 devices / 2 processes) has
# both intra- and inter-process axes.
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")

from mxnet_tpu.parallel import init_distributed  # noqa: E402

init_distributed()

import numpy as np  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu.parallel import ShardedTrainStep, barrier, make_mesh  # noqa: E402
from mxnet_tpu.parallel.mesh import allreduce_sum  # noqa: E402


def check_kvstore(rank, size, results):
    kv = mx.kv.create("dist_sync")
    assert kv.rank == rank, (kv.rank, rank)
    assert kv.num_workers == size, (kv.num_workers, size)
    shape = (5, 7)
    # init must broadcast rank 0's value: give ranks DIFFERENT values
    kv.init(3, mx.nd.ones(shape) * (42 if rank == 0 else -1))
    pulled = mx.nd.zeros(shape)
    kv.pull(3, out=pulled)
    np.testing.assert_allclose(pulled.asnumpy(), 42.0)

    # reference dist_sync_kvstore.py semantics: every push merges across
    # workers; with updater store += rate * merged the stored value after
    # nrepeat pushes of (rank+1)-filled arrays is
    #   init + rate * nrepeat * sum_r(r+1)
    rate = 2.0
    kv.set_updater(lambda key, recv, stored: stored.__iadd__(recv * rate))
    nrepeat = 3
    for _ in range(nrepeat):
        # two "device" shards per worker, like pushing a per-device list:
        # local reduce then cross-worker merge
        kv.push(3, [mx.nd.ones(shape) * (rank + 1) * 0.5,
                    mx.nd.ones(shape) * (rank + 1) * 0.5])
    kv.pull(3, out=pulled)
    expected = 42.0 + rate * nrepeat * sum(r + 1 for r in range(size))
    np.testing.assert_allclose(pulled.asnumpy(), expected, rtol=1e-6)
    results["kvstore_value"] = float(pulled.asnumpy()[0, 0])
    results["kvstore_expected"] = expected


def check_barrier_skew(rank, results):
    """rank != 0 sleeps before the barrier; rank 0's measured wait proves
    the barrier blocked on the peer rather than passing locally."""
    sleep_s = 2.0
    t0 = time.perf_counter()
    if rank != 0:
        time.sleep(sleep_s)
    barrier("skew-test")
    waited = time.perf_counter() - t0
    if rank == 0:
        assert waited >= 0.5 * sleep_s, (
            "barrier returned in %.2fs while peer slept %.1fs: not a real "
            "barrier" % (waited, sleep_s))
    results["barrier_wait_s"] = round(waited, 3)


def check_fused_step(rank, size, results):
    ndev = jax.device_count()
    mesh = make_mesh(dp=ndev)

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    opt = mx.optimizer.SGD(learning_rate=0.5, rescale_grad=1.0 / 16)
    step = ShardedTrainStep(net, mesh, optimizer=opt).compile()
    shapes = {"data": (16, 8), "softmax_label": (16,)}
    arg_shapes, _, _ = net.infer_shape(**shapes)
    shapes_by_name = dict(zip(net.list_arguments(), arg_shapes))
    np.random.seed(7)
    params, aux, opt_state = step.init(shapes_by_name, mx.initializer.Xavier())

    rng = np.random.RandomState(0)  # same data on every rank, split below
    X = rng.randn(16, 8).astype(np.float32)
    y = (rng.rand(16) * 4).astype(np.float32)
    # each process feeds ONLY its local rows of the globally-sharded batch
    per = 16 // size
    lo = rank * per
    sharding = step.batch_sharding()
    batch = {
        "data": jax.make_array_from_process_local_data(
            sharding, X[lo:lo + per]),
        "softmax_label": jax.make_array_from_process_local_data(
            sharding, y[lo:lo + per]),
    }

    def loss_of(outs):
        # outs[0] is dp-sharded softmax probs; score the local rows only
        local = np.concatenate(
            [np.asarray(s.data) for s in outs[0].addressable_shards])
        lab = y[lo:lo + per].astype(int)
        return float(-np.mean(np.log(local[np.arange(per), lab] + 1e-8)))

    losses = []
    for t in range(12):
        params, aux, opt_state, outs = step(
            params, aux, opt_state, batch, t=t + 1)
        losses.append(loss_of(outs))
    assert losses[-1] < 0.5 * losses[0], losses
    results["fused_losses"] = [round(l, 4) for l in (losses[0], losses[-1])]

    # ranks must agree bit-for-bit on the replicated params
    w = np.asarray(jax.device_get(
        params["fc1_weight"].addressable_shards[0].data))
    gathered = allreduce_sum(w)  # sum of identical copies = size * w
    np.testing.assert_array_equal(gathered, w * size)
    results["params_identical"] = True


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", required=True)
    args = ap.parse_args()
    rank = jax.process_index()
    size = jax.process_count()
    assert size > 1, "worker did not join a multi-process runtime"

    results = {"rank": rank, "size": size,
               "global_devices": jax.device_count()}
    check_kvstore(rank, size, results)
    check_barrier_skew(rank, results)
    check_fused_step(rank, size, results)
    results["ok"] = True
    with open(os.path.join(args.out, "rank%d.json" % rank), "w") as f:
        json.dump(results, f)
    print("[dist_worker rank %d] ok" % rank, flush=True)


if __name__ == "__main__":
    main()
