"""RNN cell tests (parity: reference test_rnn.py — shape contracts and
fused-vs-unfused consistency)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.rnn import (
    BidirectionalCell, FusedRNNCell, GRUCell, LSTMCell, RNNCell,
    SequentialRNNCell, DropoutCell,
)


def test_rnn_cell_unroll_shapes():
    cell = RNNCell(10, prefix="rnn_")
    outputs, _ = cell.unroll(3, input_prefix="rnn_")
    outputs = sym.Group(outputs)
    args = set(outputs.list_arguments())
    assert "rnn_i2h_weight" in args and "rnn_h2h_weight" in args
    _, outs, _ = outputs.infer_shape(
        rnn_t0_data=(4, 5), rnn_t1_data=(4, 5), rnn_t2_data=(4, 5)
    )
    assert outs == [(4, 10)] * 3


def test_lstm_cell_unroll():
    cell = LSTMCell(8, prefix="lstm_")
    outputs, states = cell.unroll(3, input_prefix="l_")
    outputs = sym.Group(outputs)
    _, outs, _ = outputs.infer_shape(
        l_t0_data=(2, 4), l_t1_data=(2, 4), l_t2_data=(2, 4)
    )
    assert outs == [(2, 8)] * 3
    assert len(states) == 2


def test_gru_cell_unroll():
    cell = GRUCell(6, prefix="gru_")
    outputs, _ = cell.unroll(2, input_prefix="g_")
    outputs = sym.Group(outputs)
    _, outs, _ = outputs.infer_shape(g_t0_data=(3, 4), g_t1_data=(3, 4))
    assert outs == [(3, 6)] * 2


def test_stack_and_bidirectional():
    cell = SequentialRNNCell()
    cell.add(LSTMCell(4, prefix="l0_"))
    cell.add(LSTMCell(4, prefix="l1_"))
    outputs, states = cell.unroll(2, input_prefix="s_")
    outputs = sym.Group(outputs)
    _, outs, _ = outputs.infer_shape(s_t0_data=(2, 3), s_t1_data=(2, 3))
    assert outs == [(2, 4)] * 2
    assert len(states) == 4

    bi = BidirectionalCell(LSTMCell(4, prefix="bl_"), LSTMCell(4, prefix="br_"))
    outputs, _ = bi.unroll(2, input_prefix="b_")
    outputs = sym.Group(outputs)
    _, outs, _ = outputs.infer_shape(b_t0_data=(2, 3), b_t1_data=(2, 3))
    assert outs == [(2, 8)] * 2


def test_fused_unfused_consistency():
    """FusedRNNCell (lax.scan RNN op) must match the unfused LSTMCell stack
    given the same packed weights (reference test_rnn.py core check)."""
    T, N, I, H = 3, 2, 4, 5
    fused = FusedRNNCell(H, num_layers=1, mode="lstm", prefix="lstm_",
                         get_next_state=False)
    f_out, _ = fused.unroll(T, inputs=sym.Variable("data"), layout="TNC")
    unfused = fused.unfuse()
    u_outs, _ = unfused.unroll(
        T,
        inputs=list(sym.SliceChannel(
            sym.Variable("data"), axis=0, num_outputs=T, squeeze_axis=1
        )),
    )
    u_out = sym.Group([sym.expand_dims(o, axis=0) for o in u_outs])

    rng = np.random.RandomState(0)
    x = rng.rand(T, N, I).astype("f")
    psize = fused._get_param_size(I)
    blob = rng.rand(psize).astype("f") * 0.2

    fe = f_out.simple_bind(mx.cpu(), data=(T, N, I))
    fe.arg_dict["data"][:] = x
    fe.arg_dict[fused._parameter.name][:] = blob
    fe.forward()
    fused_vals = fe.outputs[0].asnumpy()

    # blob → per-gate args → packed per-layer args for the unfused cells
    args = unfused.pack_weights(
        fused.unpack_weights({fused._parameter.name: mx.nd.array(blob)})
    )
    ue = sym.Group(u_out).simple_bind(mx.cpu(), data=(T, N, I))
    ue.arg_dict["data"][:] = x
    matched = 0
    for name, arr in args.items():
        if name in ue.arg_dict:
            ue.arg_dict[name][:] = arr.asnumpy()
            matched += 1
    assert matched >= 4, "weight names did not line up: %s vs %s" % (
        sorted(args), sorted(ue.arg_dict)
    )
    ue.forward()
    unfused_vals = np.concatenate(
        [o.asnumpy() for o in ue.outputs], axis=0
    )
    np.testing.assert_allclose(fused_vals, unfused_vals, rtol=1e-4, atol=1e-5)


def test_pack_unpack_roundtrip():
    cell = FusedRNNCell(6, num_layers=2, mode="lstm", prefix="lstm_")
    psize = cell._get_param_size(4)
    blob = mx.nd.array(np.random.rand(psize).astype("f"))
    args = cell.unpack_weights({cell._parameter.name: blob})
    packed = cell.pack_weights(args)
    np.testing.assert_allclose(
        packed[cell._parameter.name].asnumpy(), blob.asnumpy(), rtol=1e-6
    )


def test_dropout_cell():
    cell = SequentialRNNCell()
    cell.add(RNNCell(4, prefix="r_"))
    cell.add(DropoutCell(0.5, prefix="d_"))
    outputs, _ = cell.unroll(2, input_prefix="x_")
    g = sym.Group(outputs)
    _, outs, _ = g.infer_shape(x_t0_data=(2, 3), x_t1_data=(2, 3))
    assert outs == [(2, 4)] * 2
