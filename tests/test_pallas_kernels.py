"""Pallas flash-attention kernel tests (interpret mode on CPU — the same
kernel code that compiles via Mosaic on TPU; the backend-equivalence trick
mirrors the reference's cpu-vs-gpu check_consistency harness,
tests/python/gpu/test_operator_gpu.py)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mxnet_tpu.ops.pallas_kernels import flash_attention, reference_attention


CASES = [
    (2, 64, 2, 32, False),
    (1, 100, 3, 16, True),   # non-multiple T exercises padding+masking
    (2, 128, 2, 64, True),
]


@pytest.mark.parametrize("b,t,h,d,causal", CASES)
def test_flash_forward_matches_reference(b, t, h, d, causal):
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
    )


@pytest.mark.parametrize("b,t,h,d,causal", CASES[:2])
def test_flash_backward_matches_reference(b, t, h, d, causal):
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    k = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)
    v = jnp.asarray(rng.randn(b, t, h, d), jnp.float32)

    def loss(fn):
        return lambda q, k, v: jnp.sum(fn(q, k, v) ** 2)

    flash = lambda q, k, v: flash_attention(
        q, k, v, causal=causal, block_q=32, block_k=32
    )
    ref = lambda q, k, v: reference_attention(q, k, v, causal=causal)
    g_f = jax.grad(loss(flash), argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(loss(ref), argnums=(0, 1, 2))(q, k, v)
    for name, a, b_ in zip("dq dk dv".split(), g_f, g_r):
        rel = float(
            jnp.abs(a - b_).max() / (jnp.abs(b_).max() + 1e-9)
        )
        assert rel < 5e-4, (name, rel)


def test_flash_small_t_fallback_blocks():
    # T smaller than the block size: wrapper shrinks blocks instead of
    # exploding the pad
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(1, 8, 1, 8), jnp.float32)
    out = flash_attention(q, q, q, causal=False)
    ref = reference_attention(q, q, q, causal=False)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=1e-5, atol=1e-5
    )


def test_transformer_uses_flash_shapes_consistent():
    # the model path that selects flash on TPU falls back to jnp here (CPU)
    # — this asserts the two paths agree through the full model interface
    from mxnet_tpu.models.transformer import transformer_lm

    init_fn, apply_fn = transformer_lm(
        vocab=50, d_model=32, n_layers=1, n_heads=2, dtype=jnp.float32,
    )
    params = init_fn(seed=0)
    toks = np.random.RandomState(1).randint(0, 50, (2, 16))
    logits = apply_fn(params, jnp.asarray(toks))
    assert logits.shape == (2, 16, 50)


def test_transformer_flash_branch_matches_reference(monkeypatch):
    # force the model's flash branch off-TPU (Pallas interpreter) and
    # check it agrees with the reference-attention branch — this executes
    # the actual flash_attention call site in the transformer, so a
    # swapped q/k/v argument or wrong keyword there fails here, not on
    # hardware
    from mxnet_tpu.models.transformer import transformer_lm

    init_fn, apply_fn = transformer_lm(
        vocab=50, d_model=32, n_layers=1, n_heads=2, dtype=jnp.float32,
    )
    params = init_fn(seed=0)
    toks = jnp.asarray(np.random.RandomState(1).randint(0, 50, (2, 16)))
    ref_logits = apply_fn(params, toks)
    monkeypatch.setenv("MXNET_TPU_FORCE_FLASH", "1")
    flash_logits = apply_fn(params, toks)
    np.testing.assert_allclose(
        np.asarray(flash_logits), np.asarray(ref_logits),
        rtol=2e-4, atol=2e-4,
    )
