"""SSD workload tests (parity: reference example/ssd — SURVEY.md §7
workload 4a, the multi-output-executor north star).

The full VGG16-SSD-300 symbol is checked structurally (shape inference:
the canonical 8732 anchors). End-to-end forward/backward/update runs on a
tiny two-scale detector so the suite stays fast on the CPU mesh.
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import symbol as sym
from mxnet_tpu.models import ssd


def test_ssd300_symbol_structure():
    net = ssd.get_symbol_train(num_classes=20)
    args, outs, _ = net.infer_shape(data=(2, 3, 300, 300), label=(2, 8, 5))
    by_name = dict(zip(net.list_outputs(), outs))
    assert by_name["cls_prob_output"] == (2, 21, 8732)
    assert by_name["loc_loss_output"] == (2, 8732 * 4)
    assert by_name["cls_label_output"] == (2, 8732)
    # deploy symbol decodes to [B, A, 6]
    det = ssd.get_symbol(num_classes=20)
    _, douts, _ = det.infer_shape(data=(1, 3, 300, 300))
    assert douts[0] == (1, 8732, 6)


def _tiny_detector(num_classes=3):
    data = sym.Variable("data")
    c1 = sym.Convolution(data, kernel=(3, 3), pad=(1, 1), stride=(2, 2),
                         num_filter=8, name="c1")
    r1 = sym.Activation(c1, act_type="relu")
    c2 = sym.Convolution(r1, kernel=(3, 3), pad=(1, 1), stride=(2, 2),
                         num_filter=8, name="c2")
    r2 = sym.Activation(c2, act_type="relu")
    return data, ssd.multibox_layer(
        [r1, r2], num_classes,
        sizes=[(0.2, 0.3), (0.5, 0.6)],
        ratios=[(1, 2), (1, 2, 0.5)],
        normalization=[-1, -1])


def test_tiny_ssd_train_step():
    num_classes = 3
    _, (loc_preds, cls_preds, anchors) = _tiny_detector(num_classes)
    net = ssd.training_head(loc_preds, cls_preds, anchors, num_classes)

    batch = 2
    label = -np.ones((batch, 4, 5), np.float32)
    label[0, 0] = [1, 0.1, 0.1, 0.5, 0.5]
    label[0, 1] = [0, 0.6, 0.6, 0.9, 0.9]
    label[1, 0] = [2, 0.3, 0.2, 0.8, 0.7]

    mod = mx.mod.Module(net, data_names=("data",), label_names=("label",),
                        context=mx.cpu())
    mod.bind(data_shapes=[("data", (batch, 3, 16, 16))],
             label_shapes=[("label", (batch, 4, 5))])
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.1})
    metric = ssd.MultiBoxMetric()

    batch_data = mx.io.DataBatch(
        data=[mx.nd.array(np.random.RandomState(0).rand(batch, 3, 16, 16))],
        label=[mx.nd.array(label)])
    losses = []
    for _ in range(8):
        mod.forward(batch_data, is_train=True)
        metric.reset()
        mod.update_metric(metric, batch_data.label)
        mod.backward()
        mod.update()
        names, values = metric.get()
        assert names == ["CrossEntropy", "SmoothL1"]
        assert np.isfinite(values[0])
        losses.append(values[0])
    # training must reduce the classification loss on this fixed batch
    assert losses[-1] < losses[0]


def test_tiny_ssd_detection_forward():
    num_classes = 3
    _, (loc_preds, cls_preds_flat, anchors) = _tiny_detector(num_classes)
    cls_preds = sym.Reshape(cls_preds_flat, shape=(0, -1, num_classes + 1))
    cls_preds = sym.transpose(cls_preds, axes=(0, 2, 1))
    cls_prob = sym.SoftmaxActivation(cls_preds, mode="channel")
    from mxnet_tpu.contrib import symbol as contrib_sym
    det = contrib_sym.MultiBoxDetection(cls_prob, loc_preds, anchors,
                                        nms_threshold=0.5)
    exe = det.simple_bind(ctx=mx.cpu(), data=(1, 3, 16, 16))
    for name, arr in exe.arg_dict.items():
        if name != "data":
            arr[:] = np.random.RandomState(1).randn(*arr.shape) * 0.1
    exe.arg_dict["data"][:] = np.random.RandomState(2).rand(1, 3, 16, 16)
    out = exe.forward(is_train=False)[0].asnumpy()
    A = 8 * 8 * 3 + 4 * 4 * 4  # anchors of the two scales
    assert out.shape == (1, A, 6)
    # every row: [cls_id(-1 = suppressed), score, x1, y1, x2, y2]
    assert ((out[..., 0] >= -1) & (out[..., 0] < num_classes)).all()
    assert ((out[..., 1] >= 0) & (out[..., 1] <= 1)).all()


def _pack_det_rec(tmp_path, n_images=6, size=24):
    """Pack synthetic detection data the way the reference SSD pipeline
    does (imdb.py save_imglist -> im2rec): per-image label
    [header_width=2, object_width=5, (cls, xmin, ymin, xmax, ymax)...]."""
    from mxnet_tpu import recordio

    rng = np.random.RandomState(3)
    rec_path = str(tmp_path / "det.rec")
    idx_path = str(tmp_path / "det.idx")
    writer = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    truths = []
    for i in range(n_images):
        img = (rng.rand(size, size, 3) * 255).astype(np.uint8)
        n_obj = 1 + i % 2
        objs = []
        for _ in range(n_obj):
            x0, y0 = rng.uniform(0.05, 0.4, 2)
            x1, y1 = x0 + rng.uniform(0.2, 0.5), y0 + rng.uniform(0.2, 0.5)
            objs.append([rng.randint(0, 3), x0, y0, min(x1, 0.95),
                         min(y1, 0.95)])
        label = np.asarray([2, 5] + [v for o in objs for v in o], np.float32)
        writer.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, label, i, 0), img, img_fmt=".png"))
        truths.append(np.asarray(objs, np.float32))
    writer.close()
    return rec_path, idx_path, truths


def test_image_det_record_iter_contract(tmp_path):
    """The C++ ImageDetRecordIter label contract
    (iter_image_det_recordio.cc:435-444): [c, h, w, len, packed, -1 pad]."""
    rec_path, idx_path, truths = _pack_det_rec(tmp_path)
    it = mx.io.ImageDetRecordIter(
        path_imgrec=rec_path, path_imgidx=idx_path, batch_size=3,
        data_shape=(3, 16, 16))
    batch = next(iter(it))
    label = batch.label[0].asnumpy()
    assert label.shape == (3, 4 + 2 + 2 * 5)  # max 2 objects
    for row, truth in zip(label, truths):
        assert tuple(row[:3]) == (3.0, 16.0, 16.0)
        buf_len = int(row[3])
        assert buf_len == 2 + truth.size
        assert row[4] == 2 and row[5] == 5
        np.testing.assert_allclose(row[6:6 + truth.size], truth.ravel(),
                                   rtol=1e-6)
        assert np.all(row[4 + buf_len:] == -1.0)
    assert batch.data[0].shape == (3, 3, 16, 16)


def test_ssd_trains_from_rec_file(tmp_path):
    """End-to-end VERDICT item 9: SSD trains a step from a packed .rec
    through ImageDetRecordIter (no synthetic NDArrayIter shortcut)."""
    rec_path, idx_path, _ = _pack_det_rec(tmp_path)
    batch_size = 3
    it = mx.io.ImageDetRecordIter(
        path_imgrec=rec_path, path_imgidx=idx_path, batch_size=batch_size,
        data_shape=(3, 16, 16), scale=1.0 / 255)

    num_classes = 3
    _, (loc_preds, cls_preds, anchors) = _tiny_detector(num_classes)
    net = ssd.training_head(loc_preds, cls_preds, anchors, num_classes)
    mod = mx.mod.Module(net, data_names=("data",), label_names=("label",),
                        context=mx.cpu())

    losses = []
    metric = ssd.MultiBoxMetric()
    for epoch in range(6):
        it.reset()
        for batch in it:
            label = batch.label[0].asnumpy()
            # SSD's DetRecordIter reshape (example/ssd/dataset/iterator.py):
            # strip the 4-value size header + the [hw, ow] packing header,
            # view as (batch, max_objects, object_width)
            header_width = int(label[0, 4])
            obj_width = int(label[0, 5])
            start = 4 + header_width
            max_obj = (label.shape[1] - start) // obj_width
            boxes = label[:, start:start + max_obj * obj_width].reshape(
                batch_size, max_obj, obj_width)
            det_batch = mx.io.DataBatch(data=batch.data,
                                        label=[mx.nd.array(boxes)])
            if not mod.binded:
                mod.bind(data_shapes=[("data", (batch_size, 3, 16, 16))],
                         label_shapes=[("label", boxes.shape)])
                mod.init_params(initializer=mx.init.Xavier())
                mod.init_optimizer(
                    optimizer="sgd",
                    optimizer_params={"learning_rate": 0.1})
            mod.forward(det_batch, is_train=True)
            metric.reset()
            mod.update_metric(metric, det_batch.label)
            mod.backward()
            mod.update()
            losses.append(metric.get()[1][0])
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
