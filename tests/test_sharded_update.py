"""Sharded weight update parity (ISSUE 5 tentpole acceptance).

The flat bucketed update ships in two modes sharing ONE chunk-width
update body (parallel/train_step.py): "shard" (MXTPU_SHARD_UPDATE=1,
the dp>1 default — each replica updates its 1/N shard inside shard_map,
optimizer state materialized at 1/N, weights all-gathered in-step) and
"replicated" (=0 — the same dp-chunk body scanned on every replica).
Matching chunk widths is what makes the two bitwise-equal: XLA contracts
mul+add into FMA per fusion width, so a monolithic full-width update
would round differently from the sharded one.

These tests pin the acceptance criteria: bitwise-equal params, optimizer
state, and metrics between the sharded and replicated paths — for
SGD-momentum and Adam, under MXNET_FIT_MULTISTEP and MXTPU_DEVICE_FEED,
across 1/2/4 simulated devices — including SIGKILL crash-resume through
resilience checkpoints and checkpoint portability across modes.
"""
import os
import shutil
import signal
import subprocess
import sys
import textwrap

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import mxnet_tpu as mx
from mxnet_tpu.resilience import checkpoint as ck
from mxnet_tpu.resilience import fault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# in-process end-to-end parity (the suite runs on an 8-device CPU mesh)
# ---------------------------------------------------------------------------

def _small_net():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _opt_params(optname):
    p = {"learning_rate": 0.1, "rescale_grad": 1.0 / 16}
    if optname == "sgd":
        p["momentum"] = 0.9
    return p


def _fit_once(ndev, optname, num_epoch=2):
    np.random.seed(0)
    mx.random.seed(0)
    rng = np.random.RandomState(42)
    X = rng.randn(128, 8).astype(np.float32)
    y = rng.randint(0, 4, 128).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_small_net(),
                        context=[mx.cpu(i) for i in range(ndev)])
    metric = mx.metric.create("acc")
    mod.fit(it, eval_metric=metric, kvstore="device", optimizer=optname,
            optimizer_params=_opt_params(optname),
            initializer=mx.init.Uniform(0.1), num_epoch=num_epoch)
    assert mod._fused_trainer is not None, "fused path did not engage"
    return mod, metric


def _snapshot(mod, metric):
    arg, aux = mod.get_params()
    blob = {"arg:" + k: v.asnumpy() for k, v in arg.items()}
    blob.update({"aux:" + k: v.asnumpy() for k, v in aux.items()})
    blob["__metric__"] = np.asarray([metric.get()[1]])
    host = mod._fused_opt_host_state()
    blob["__t__"] = np.asarray([host["t"]])

    def _flatten(prefix, s):
        if s is None:
            return
        if isinstance(s, tuple):
            for j, x in enumerate(s):
                _flatten(prefix + "." + str(j), x)
        else:
            blob["opt:" + prefix] = np.asarray(s)

    for name, s in host["state"].items():
        _flatten(name, s)
    return blob


def _assert_bitwise(got, want):
    assert sorted(got) == sorted(want), (sorted(got), sorted(want))
    for k in want:
        np.testing.assert_array_equal(got[k], want[k],
                                      err_msg="%s differs" % k)


@pytest.mark.parametrize("ndev,optname,fit_k,feed,bucket", [
    (2, "sgd", "1", "1", None),
    (2, "adam", "2", "0", "256"),   # tiny cap: multiple buckets + padding
    (4, "sgd", "2", "1", None),
    (4, "adam", "1", "0", None),
    (8, "sgd", "1", "1", "256"),
])
def test_sharded_bitwise_parity(monkeypatch, ndev, optname, fit_k, feed,
                                bucket):
    """MXTPU_SHARD_UPDATE=1 vs =0: params, optimizer state, and metric
    bitwise-equal across device counts, optimizers, multi-step fit, and
    device-resident feeds; sharded state genuinely at 1/N."""
    from jax.sharding import PartitionSpec as P

    monkeypatch.setenv("MXNET_FIT_MULTISTEP", fit_k)
    monkeypatch.setenv("MXTPU_DEVICE_FEED", feed)
    if bucket is not None:
        monkeypatch.setenv("MXTPU_BUCKET_BYTES", bucket)

    monkeypatch.setenv("MXTPU_SHARD_UPDATE", "1")
    mod_s, met_s = _fit_once(ndev, optname)
    tr = mod_s._fused_owner._fused_trainer
    assert tr.flat_mode == "shard", tr.flat_mode
    for st in mod_s._fused_owner._fused_opt.values():
        leaf = st[0] if isinstance(st, tuple) else st
        assert leaf.sharding.spec == P("dp"), leaf.sharding.spec
        shard0 = leaf.addressable_shards[0].data
        assert shard0.shape[0] * ndev == leaf.shape[0], \
            "state not materialized at 1/N"
    blob_s = _snapshot(mod_s, met_s)

    monkeypatch.setenv("MXTPU_SHARD_UPDATE", "0")
    mod_r, met_r = _fit_once(ndev, optname)
    assert mod_r._fused_owner._fused_trainer.flat_mode == "replicated"
    _assert_bitwise(blob_s, _snapshot(mod_r, met_r))


def test_single_device_uses_legacy_path(monkeypatch):
    """dp=1: nothing to shard — the flat layer must stay out of the way
    (at one device the fused trainer may not even engage; either way no
    flat mode and training completes)."""
    monkeypatch.setenv("MXTPU_SHARD_UPDATE", "1")
    np.random.seed(0)
    mx.random.seed(0)
    rng = np.random.RandomState(42)
    X = rng.randn(128, 8).astype(np.float32)
    y = rng.randint(0, 4, 128).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    mod = mx.mod.Module(_small_net(), context=[mx.cpu(0)])
    metric = mx.metric.create("acc")
    mod.fit(it, eval_metric=metric, kvstore="device", optimizer="sgd",
            optimizer_params=_opt_params("sgd"),
            initializer=mx.init.Uniform(0.1), num_epoch=1)
    if mod._fused_trainer is not None:
        assert mod._fused_owner._fused_trainer.flat_mode is None
    assert np.isfinite(metric.get()[1])


def test_bucket_bytes_zero_disables_flat(monkeypatch):
    monkeypatch.setenv("MXTPU_BUCKET_BYTES", "0")
    mod, _ = _fit_once(2, "sgd", num_epoch=1)
    assert mod._fused_owner._fused_trainer.flat_mode is None


def test_flat_update_plan_packing():
    """_FlatUpdatePlan: reverse-key packing, size caps, dp padding, and
    full per-key view coverage."""
    from mxnet_tpu import optimizer as opt
    from mxnet_tpu.parallel.train_step import _FlatUpdatePlan

    names = ["a", "b", "c", "d"]
    shapes = {"a": (8, 4), "b": (8,), "c": (6, 4), "d": (3,)}
    dtypes = {n: "float32" for n in names}
    sgd = opt.create("sgd", learning_rate=0.1, momentum=0.9)
    # cap = 32 floats = 128 bytes; reverse walk packs d,c then b,a
    plan = _FlatUpdatePlan(names, shapes, dtypes, sgd, dp=4,
                           bucket_bytes=128)
    assert len(plan.buckets) >= 2
    seen = {}
    for bi, b in enumerate(plan.buckets):
        assert b.size <= 32 or len(b.views) == 1
        assert b.padded % 4 == 0 and b.padded >= b.size
        off_end = 0
        for (_i, name, off, size, shape) in b.views:
            assert off == off_end  # views are contiguous
            off_end = off + size
            assert size == int(np.prod(shape))
            seen[name] = bi
    assert sorted(seen) == sorted(names)
    # reverse-key issue order: later keys land in earlier buckets
    assert seen["d"] <= seen["a"]


def test_flat_plan_groups_by_mult():
    """Keys with distinct lr_mult cannot share a bucket (one scalar
    fused-kwargs set per slab)."""
    from mxnet_tpu import optimizer as opt
    from mxnet_tpu.parallel.train_step import _FlatUpdatePlan

    names = ["w1", "w2"]
    shapes = {"w1": (4,), "w2": (4,)}
    dtypes = {n: "float32" for n in names}
    sgd = opt.create("sgd", learning_rate=0.1,
                     param_idx2name={0: "w1", 1: "w2"})
    sgd.set_lr_mult({"w2": 0.5})
    plan = _FlatUpdatePlan(names, shapes, dtypes, sgd, dp=2,
                           bucket_bytes=1 << 20)
    assert len(plan.buckets) == 2


def test_elementwise_update_flags():
    """Optimizers whose update math is NOT elementwise over the flat
    space must be excluded from the flat path."""
    from mxnet_tpu import optimizer as opt

    for name in ("sgd", "adam", "rmsprop", "adagrad", "adadelta", "ftrl"):
        assert opt.create(name).elementwise_update, name
    for name in ("sgld", "dcasgd"):
        assert not opt.create(name).elementwise_update, name


def test_borrow_optimizer_demotes_flat(monkeypatch):
    """borrow_optimizer shares a param-name subset the flat slabs cannot
    express: the owner must demote to the per-param update, converting
    state in place, and keep training."""
    monkeypatch.setenv("MXTPU_SHARD_UPDATE", "1")
    mod, metric = _fit_once(2, "sgd", num_epoch=1)
    owner_tr = mod._fused_owner._fused_trainer
    assert owner_tr.flat_mode == "shard"
    borrower = mx.mod.Module(_small_net(),
                             context=[mx.cpu(i) for i in range(2)])
    rng = np.random.RandomState(1)
    X = rng.randn(32, 8).astype(np.float32)
    y = rng.randint(0, 4, 32).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=16)
    borrower.bind(data_shapes=it.provide_data,
                  label_shapes=it.provide_label,
                  shared_module=mod)
    borrower.init_params(mx.init.Uniform(0.1))
    borrower.borrow_optimizer(mod)
    assert owner_tr.flat_mode is None  # demoted
    # state keys converted back to per-name layout
    assert all(not str(k).startswith("__flat__")
               for k in mod._fused_owner._fused_opt)
    batch = next(iter(it))
    borrower.forward(batch)
    borrower.backward()
    borrower.update()  # must not raise


# ---------------------------------------------------------------------------
# mesh collective primitives
# ---------------------------------------------------------------------------

def test_reduce_scatter_all_gather_single_process():
    """Single-process passthrough (the multi-process path is covered by
    the dist worker tests): reduce_scatter returns the full sum, gather
    returns its input, and the divisibility contract is enforced."""
    from mxnet_tpu.parallel import all_gather, reduce_scatter_sum

    v = np.arange(12, dtype=np.float32).reshape(6, 2)
    np.testing.assert_array_equal(reduce_scatter_sum(v), v)
    np.testing.assert_array_equal(all_gather(v), v)


def test_bucket_round_trip_two_phase(monkeypatch):
    """MXTPU_BUCKET_TWO_PHASE routes kvstore bucket collectives through
    reduce_scatter_sum + all_gather (with padding); values must round-
    trip exactly."""
    monkeypatch.setenv("MXTPU_BUCKET_TWO_PHASE", "1")
    monkeypatch.setenv("MXNET_KVSTORE_ASYNC", "0")
    kv = mx.kv.create("local")
    kv.type = "dist_sync"  # fake dist: collectives pass through at P=1
    kv._size = 2
    kv.init(0, mx.nd.zeros((5,)))
    kv.init(1, mx.nd.zeros((3,)))
    kv.push(0, mx.nd.array(np.arange(5, dtype=np.float32)))
    kv.push(1, mx.nd.array(np.arange(3, dtype=np.float32) + 10))
    kv._flush_buckets()
    out0, out1 = mx.nd.zeros((5,)), mx.nd.zeros((3,))
    kv.pull(0, out=out0)
    kv.pull(1, out=out1)
    np.testing.assert_array_equal(out0.asnumpy(),
                                  np.arange(5, dtype=np.float32))
    np.testing.assert_array_equal(out1.asnumpy(),
                                  np.arange(3, dtype=np.float32) + 10)


# ---------------------------------------------------------------------------
# crash-resume + checkpoint portability (subprocess: own device count,
# SIGKILL fault injection — the pattern of test_resilience.py)
# ---------------------------------------------------------------------------

TRAIN_SCRIPT = textwrap.dedent("""\
    import os, sys
    sys.path.insert(0, %(repo)r)
    ndev = int(os.environ.get("T_NDEV", "4"))
    os.environ["XLA_FLAGS"] = (
        "--xla_force_host_platform_device_count=" + str(ndev))
    import logging
    logging.basicConfig(level=logging.INFO)
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import mxnet_tpu as mx

    ckpt_dir, out = sys.argv[1], sys.argv[2]
    np.random.seed(0)
    mx.random.seed(0)
    rng = np.random.RandomState(42)
    X = rng.randn(128, 8).astype(np.float32)
    y = rng.randint(0, 4, 128).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=16)  # 8 batches/epoch

    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")

    optname = os.environ.get("T_OPT", "sgd")
    opt_params = {"learning_rate": 0.1, "rescale_grad": 1.0 / 16}
    if optname == "sgd":
        opt_params["momentum"] = 0.9
    mod = mx.mod.Module(net, context=[mx.cpu(i) for i in range(ndev)])
    metric = mx.metric.create("acc")
    kw = {}
    if ckpt_dir != "-":
        kw = dict(checkpoint_dir=ckpt_dir, resume="auto")
    mod.fit(it, eval_metric=metric, kvstore="device", optimizer=optname,
            optimizer_params=opt_params,
            initializer=mx.init.Uniform(0.1), num_epoch=2, **kw)
    assert mod._fused_trainer is not None
    tr = mod._fused_owner._fused_trainer
    want = os.environ.get("T_WANT_MODE")
    if want:
        got = tr.flat_mode or "none"
        assert got == want, (got, want)

    arg, aux = mod.get_params()
    blob = {"arg:" + k: v.asnumpy() for k, v in arg.items()}
    blob.update({"aux:" + k: v.asnumpy() for k, v in aux.items()})
    blob["__metric__"] = np.asarray([metric.get()[1]])
    host = mod._fused_opt_host_state()
    blob["__t__"] = np.asarray([host["t"]])
    def _flatten(prefix, s):
        if s is None:
            return
        if isinstance(s, tuple):
            for j, x in enumerate(s):
                _flatten(prefix + "." + str(j), x)
        else:
            blob["opt:" + prefix] = np.asarray(s)
    for name, s in host["state"].items():
        _flatten(name, s)
    np.savez(out, **blob)
    print("TRAIN-DONE", flush=True)
""") % {"repo": REPO}


def _run_train(script_dir, ckpt_dir, out, extra_env, timeout=300):
    script = os.path.join(script_dir, "train_sharded.py")
    if not os.path.exists(script):
        with open(script, "w") as f:
            f.write(TRAIN_SCRIPT)
    env = os.environ.copy()
    env.pop("XLA_FLAGS", None)
    env.pop(fault.ENV, None)
    for k in ("MXTPU_SHARD_UPDATE", "MXTPU_BUCKET_BYTES",
              "MXNET_FIT_MULTISTEP", "MXTPU_DEVICE_FEED"):
        env.pop(k, None)
    env.update(extra_env)
    return subprocess.run(
        [sys.executable, script, ckpt_dir, out],
        capture_output=True, text=True, timeout=timeout, env=env)


def _load_blob(path):
    with np.load(path) as z:
        return {k: z[k] for k in z.files}


def test_sharded_kill_resume_and_cross_mode(tmp_path):
    """SIGKILL mid-epoch under the sharded update, auto-resume: bitwise
    parity with the uninterrupted run. Then resume the SAME crash
    checkpoints with MXTPU_SHARD_UPDATE=0 — the snapshot layout is
    per-param, so checkpoints are portable across modes and the result
    is STILL bitwise-identical (both modes share the chunk-width
    body)."""
    base_env = {"T_NDEV": "4", "T_OPT": "sgd",
                "MXTPU_SHARD_UPDATE": "1", ck.ENV_INTERVAL: "3"}
    ref_out = str(tmp_path / "ref.npz")
    proc = _run_train(str(tmp_path), str(tmp_path / "ref_ck"), ref_out,
                      dict(base_env, T_WANT_MODE="shard"))
    assert proc.returncode == 0, proc.stderr
    assert "TRAIN-DONE" in proc.stdout

    crash_dir = str(tmp_path / "crash_ck")
    crash_env = dict(base_env, **{fault.ENV: "kill_at_step=13"})
    proc = _run_train(str(tmp_path), crash_dir,
                      str(tmp_path / "unused.npz"), crash_env)
    assert proc.returncode == -signal.SIGKILL
    assert ck.list_checkpoints(crash_dir), "no checkpoint survived"
    crash_copy = str(tmp_path / "crash_ck_copy")
    shutil.copytree(crash_dir, crash_copy)

    res_out = str(tmp_path / "res.npz")
    proc = _run_train(str(tmp_path), crash_dir, res_out,
                      dict(base_env, T_WANT_MODE="shard"))
    assert proc.returncode == 0, proc.stderr
    assert "resume: restored step" in proc.stderr
    _assert_bitwise(_load_blob(res_out), _load_blob(ref_out))

    # cross-mode: same crash checkpoints, replicated-mode resume
    swap_out = str(tmp_path / "swap.npz")
    proc = _run_train(str(tmp_path), crash_copy, swap_out,
                      dict(base_env, MXTPU_SHARD_UPDATE="0",
                           T_WANT_MODE="replicated"))
    assert proc.returncode == 0, proc.stderr
    assert "resume: restored step" in proc.stderr
    _assert_bitwise(_load_blob(swap_out), _load_blob(ref_out))
