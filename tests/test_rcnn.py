"""Faster R-CNN workload tests (parity: reference example/rcnn —
SURVEY.md §7 workload 4b). Exercises the full chain the reference's
MutableModule training runs: RPN losses, native Proposal, the
proposal_target python CustomOp, ROIPooling, two-head Fast R-CNN top —
end to end through MutableModule, including a variable-size rebind.
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.models import rcnn

FS = 4                 # tiny backbone stride
SCALES = (2, 4)
RATIOS = (1.0,)
A = len(SCALES) * len(RATIOS)


def _make_symbol():
    return rcnn.get_symbol_train(
        num_classes=3, backbone="tiny", feature_stride=FS,
        scales=SCALES, ratios=RATIOS, rpn_batch_size=16, batch_rois=8,
        rpn_pre_nms_top_n=32, rpn_post_nms_top_n=16, rpn_min_size=2,
        pooled_size=(3, 3), hidden=32)


def _make_batch(im_hw, seed=0):
    H, W = im_hw
    h, w = H // FS, W // FS
    rng = np.random.RandomState(seed)
    data = rng.rand(1, 3, H, W).astype(np.float32)
    im_info = np.array([[H, W, 1.0]], np.float32)
    # classes are 0-based foreground ids (label = cls+1, 0 = background)
    gt = np.array([[2.0, 2.0, H * 0.6, W * 0.6, 0.0],
                   [H * 0.3, W * 0.3, H - 3.0, W - 3.0, 1.0]], np.float32)
    lab, tgt, wgt = rcnn.assign_anchors(
        gt, (h, w), (H, W), feature_stride=FS, scales=SCALES,
        ratios=RATIOS, batch_size=16, fg_overlap=0.5, bg_overlap=0.3)
    return mx.io.DataBatch(
        data=[mx.nd.array(data), mx.nd.array(im_info),
              mx.nd.array(gt[None])],
        label=[mx.nd.array(lab), mx.nd.array(tgt), mx.nd.array(wgt)],
        provide_data=[("data", data.shape), ("im_info", (1, 3)),
                      ("gt_boxes", (1,) + gt.shape)],
        provide_label=[("rpn_label", lab.shape),
                       ("rpn_bbox_target", tgt.shape),
                       ("rpn_bbox_weight", wgt.shape)])


def test_proposal_target_custom_op():
    rois = np.array([[0, 0, 0, 10, 10],
                     [0, 1, 1, 12, 12],
                     [0, 20, 20, 30, 30]], np.float32)
    gt = np.array([[[0, 0, 11, 11, 1.0]]], np.float32)
    out = mx.sym.Custom(mx.sym.Variable("rois"), mx.sym.Variable("gt"),
                        op_type="proposal_target", num_classes=3,
                        batch_rois=4, fg_fraction=0.5)
    exe = out.simple_bind(mx.cpu(), rois=(3, 5), gt=(1, 1, 5))
    exe.arg_dict["rois"][:] = rois
    exe.arg_dict["gt"][:] = gt
    sampled, label, bt, bw = [o.asnumpy() for o in exe.forward()]
    assert sampled.shape == (4, 5) and label.shape == (4,)
    assert bt.shape == (4, 12) and bw.shape == (4, 12)
    # the overlapping rois (and the injected gt box) are foreground cls 2
    assert (label == 2).sum() >= 2
    # weights are only set on the fg rows, in the class-2 slot
    fg = label == 2
    assert bw[fg][:, 8:12].all() and not bw[fg][:, :8].any()
    assert not bw[~fg].any()


def test_rcnn_end2end_mutable_module():
    net = _make_symbol()
    batch32 = _make_batch((32, 32), seed=0)
    batch16 = _make_batch((16, 32), seed=1)  # different H → rebind path

    mod = mx.mod.MutableModule(
        net, data_names=("data", "im_info", "gt_boxes"),
        label_names=("rpn_label", "rpn_bbox_target", "rpn_bbox_weight"),
        context=mx.cpu(),
        max_data_shapes=[("data", (1, 3, 32, 32))])
    mod.bind(data_shapes=batch32.provide_data,
             label_shapes=batch32.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.01})

    assert mod._curr_module is mod._base_module
    for step, batch in enumerate([batch32, batch32, batch16, batch32]):
        mod.forward(batch, is_train=True)
        if step == 2:
            # variable-size image triggered a shared-param rebind
            assert mod._curr_module is not mod._base_module
        outs = [o.asnumpy() for o in mod.get_outputs()]
        # [rpn_cls_prob, rpn_bbox_loss, cls_prob, bbox_loss, label]
        assert all(np.isfinite(o).all() for o in outs), step
        mod.backward()
        mod.update()
    # cls_prob rows are distributions over the 3 classes
    cls_prob = outs[2]
    np.testing.assert_allclose(cls_prob.sum(axis=1), 1.0, rtol=1e-4)


def test_mutable_module_force_rebind_keeps_params():
    net = _make_symbol()
    batch32 = _make_batch((32, 32), seed=0)
    mod = mx.mod.MutableModule(
        net, data_names=("data", "im_info", "gt_boxes"),
        label_names=("rpn_label", "rpn_bbox_target", "rpn_bbox_weight"),
        context=mx.cpu())
    mod.bind(data_shapes=batch32.provide_data,
             label_shapes=batch32.provide_label)
    mod.init_params(initializer=mx.init.Xavier())
    before, _ = mod.get_params()
    mod.bind(data_shapes=batch32.provide_data,
             label_shapes=batch32.provide_label, force_rebind=True)
    assert mod.params_initialized
    after, _ = mod.get_params()
    for name in before:
        np.testing.assert_allclose(
            after[name].asnumpy(), before[name].asnumpy(), rtol=1e-6)
    # and the rebound module still runs
    mod.forward(batch32, is_train=False)
    assert np.isfinite(mod.get_outputs()[0].asnumpy()).all()


def test_rcnn_trains_from_det_rec_file(tmp_path):
    """AnchorLoader-over-.rec: images + gt boxes read from a packed
    detection RecordIO (the reference's roidb source), converted to the
    RCNN feed (im_info, pixel-space gt_boxes, RPN anchor targets) and
    trained end to end — detection no longer needs synthetic feeds."""
    from mxnet_tpu import recordio

    rng = np.random.RandomState(5)
    rec_path = str(tmp_path / "rcnn.rec")
    idx_path = str(tmp_path / "rcnn.idx")
    writer = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(4):
        img = (rng.rand(32, 32, 3) * 255).astype(np.uint8)
        # one normalized box per image, class id in {0, 1}
        x0, y0 = rng.uniform(0.1, 0.3, 2)
        label = np.asarray([2, 5, i % 2, x0, y0, x0 + 0.5, y0 + 0.5],
                           np.float32)
        writer.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, label, i, 0), img, img_fmt=".png"))
    writer.close()

    H = W = 32
    it = mx.io.ImageDetRecordIter(
        path_imgrec=rec_path, path_imgidx=idx_path, batch_size=1,
        data_shape=(3, H, W), scale=1.0 / 255, label_pad_width=8)

    net = _make_symbol()
    mod = mx.mod.MutableModule(
        net, data_names=("data", "im_info", "gt_boxes"),
        label_names=("rpn_label", "rpn_bbox_target", "rpn_bbox_weight"),
        context=mx.cpu(),
        max_data_shapes=[("data", (1, 3, H, W))])

    losses = []
    for epoch in range(3):
        it.reset()
        for batch in it:
            row = batch.label[0].asnumpy()[0]
            header_width, obj_width = int(row[4]), int(row[5])
            objs = row[4 + header_width: 4 + int(row[3])].reshape(
                -1, obj_width)
            # det convention (cls, xmin..ymax normalized) -> rcnn gt
            # (x1, y1, x2, y2, cls-id) in pixels
            gt = np.stack([objs[:, 1] * W, objs[:, 2] * H,
                           objs[:, 3] * W, objs[:, 4] * H,
                           objs[:, 0]], axis=1).astype(np.float32)
            h, w = H // FS, W // FS
            lab, tgt, wgt = rcnn.assign_anchors(
                gt, (h, w), (H, W), feature_stride=FS, scales=SCALES,
                ratios=RATIOS, batch_size=16, fg_overlap=0.5,
                bg_overlap=0.3)
            fb = mx.io.DataBatch(
                data=[batch.data[0],
                      mx.nd.array([[H, W, 1.0]]),
                      mx.nd.array(gt[None])],
                label=[mx.nd.array(lab), mx.nd.array(tgt),
                       mx.nd.array(wgt)],
                provide_data=[("data", (1, 3, H, W)), ("im_info", (1, 3)),
                              ("gt_boxes", (1,) + gt.shape)],
                provide_label=[("rpn_label", lab.shape),
                               ("rpn_bbox_target", tgt.shape),
                               ("rpn_bbox_weight", wgt.shape)])
            if not mod.binded:
                mod.bind(data_shapes=fb.provide_data,
                         label_shapes=fb.provide_label)
                mod.init_params(initializer=mx.init.Xavier())
                mod.init_optimizer(
                    optimizer="sgd",
                    optimizer_params={"learning_rate": 0.01})
            mod.forward(fb, is_train=True)
            outs = [o.asnumpy() for o in mod.get_outputs()]
            assert all(np.isfinite(o).all() for o in outs)
            mod.backward()
            mod.update()
            # rpn classification loss on this batch
            probs = outs[0].reshape(2, -1)
            mask = lab.ravel() != -1
            pick = probs[lab.ravel()[mask].astype(int),
                         np.where(mask)[0]]
            losses.append(float(-np.log(pick + 1e-8).mean()))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
