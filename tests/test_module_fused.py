"""Module.fit -> fused mesh path (kvstore='device').

VERDICT round-1 item 3: ctx=[...multiple devices...] + kvstore 'device'
must route updates through ShardedTrainStep (one XLA program per step:
forward, backward, psum gradient sync, optimizer) and produce the SAME
numerics as the single-device executor path — the reference proves its
multi-device path the same way (tests/nightly/multi_lenet.py parity of
convergence; tests/python/unittest/test_module.py).

Optimizer generality matters: the fused step traces through the real
Optimizer.update, so every registered optimizer must work unmodified.
"""
import numpy as np
import pytest

import mxnet_tpu as mx


def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _blob_iter(batch_size=32, n=128, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.randn(4, 8) * 3
    x = np.concatenate(
        [c + rng.randn(n // 4, 8) * 0.3 for c in centers]
    ).astype("f")
    y = np.repeat(np.arange(4), n // 4).astype("f")
    perm = rng.permutation(n)
    return mx.io.NDArrayIter(x[perm], y[perm], batch_size=batch_size)


def _train_params(ctx, kvstore, optimizer, optimizer_params, n_batches=3,
                  seed=0):
    net = _mlp()
    it = _blob_iter()
    mod = mx.mod.Module(net, context=ctx)
    mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mx.random.seed(seed)
    np.random.seed(seed)
    mod.init_params(mx.init.Uniform(0.1))
    mod.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                       optimizer_params=optimizer_params)
    it.reset()
    for i, batch in enumerate(it):
        if i >= n_batches:
            break
        mod.forward(batch)
        mod.backward()
        mod.update()
    args, auxs = mod.get_params()
    return mod, {k: v.asnumpy() for k, v in args.items()}


FOUR_DEV = [mx.cpu(i) for i in range(4)]


@pytest.mark.parametrize("optimizer,opt_params", [
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.01}),
    ("rmsprop", {"learning_rate": 0.01}),
    ("nag", {"learning_rate": 0.1, "momentum": 0.9}),
    ("adagrad", {"learning_rate": 0.1}),
])
def test_fused_matches_single_device(optimizer, opt_params):
    mod_f, fused = _train_params(FOUR_DEV, "device", optimizer, opt_params)
    assert mod_f._fused_trainer is not None, "fused path not taken"
    mod_s, single = _train_params(mx.cpu(), "local", optimizer, opt_params)
    assert mod_s._fused_trainer is None
    for k in single:
        np.testing.assert_allclose(
            fused[k], single[k], rtol=2e-4, atol=2e-5, err_msg=k
        )


def test_fused_lr_scheduler():
    """Scheduled lr enters the fused program as a traced input: lr changes
    take effect WITHOUT recompilation. Expected schedule for
    FactorScheduler(step=2, factor=0.1) at base 0.5 over 4 steps:
    [0.5, 0.5, 0.05, 0.05] (post-increment query — the reference's
    per-param Updater staggers the first param by one batch, an
    interleaving artifact the fused step does not reproduce)."""
    sched = mx.lr_scheduler.FactorScheduler(step=2, factor=0.1)
    mod_f, fused = _train_params(
        FOUR_DEV, "device", "sgd",
        {"learning_rate": 0.5, "lr_scheduler": sched}, n_batches=4)
    assert mod_f._fused_trainer is not None

    # single-device reference applying the same explicit lr sequence
    net = _mlp()
    it = _blob_iter()
    mod_s = mx.mod.Module(net, context=mx.cpu())
    mod_s.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mx.random.seed(0)
    np.random.seed(0)
    mod_s.init_params(mx.init.Uniform(0.1))
    mod_s.init_optimizer(kvstore="local", optimizer="sgd",
                         optimizer_params={"learning_rate": 0.5})
    it.reset()
    for i, batch in enumerate(it):
        if i >= 4:
            break
        mod_s._optimizer.lr = [0.5, 0.5, 0.05, 0.05][i]
        mod_s.forward(batch)
        mod_s.backward()
        mod_s.update()
    single = {k: v.asnumpy() for k, v in mod_s.get_params()[0].items()}
    for k in single:
        np.testing.assert_allclose(
            fused[k], single[k], rtol=2e-4, atol=2e-5, err_msg=k
        )


def test_fused_fit_and_score():
    """End-to-end fit on the mesh, then score through the synced
    executor path."""
    net = _mlp()
    it = _blob_iter()
    val = _blob_iter(seed=0)  # same blob centers; score on-distribution
    mod = mx.mod.Module(net, context=FOUR_DEV)
    mod.fit(it, optimizer="sgd",
            optimizer_params={"learning_rate": 0.2, "momentum": 0.9},
            kvstore="device", num_epoch=8)
    assert mod._fused_trainer is not None
    acc = dict(mod.score(val, mx.metric.Accuracy()))["accuracy"]
    assert acc >= 0.95, acc


def test_fused_checkpoint_roundtrip(tmp_path):
    net = _mlp()
    it = _blob_iter()
    mod = mx.mod.Module(net, context=FOUR_DEV)
    mod.fit(it, optimizer="adam", optimizer_params={"learning_rate": 0.01},
            kvstore="device", num_epoch=2)
    prefix = str(tmp_path / "fused")
    mod.save_checkpoint(prefix, 2, save_optimizer_states=True)

    mod2 = mx.mod.Module.load(prefix, 2, load_optimizer_states=True,
                              context=FOUR_DEV)
    it.reset()
    mod2.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
    mod2.init_optimizer(kvstore="device", optimizer="adam",
                        optimizer_params={"learning_rate": 0.01})
    assert mod2._fused_t == mod._fused_t  # resumed Adam step count
    # one more step trains without error and changes params
    batch = next(iter(it))
    before = {k: v.asnumpy().copy() for k, v in mod2.get_params()[0].items()}
    mod2.forward(batch)
    mod2.backward()
    mod2.update()
    after = mod2.get_params()[0]
    changed = any(
        not np.allclose(before[k], after[k].asnumpy()) for k in before
    )
    assert changed
