"""MXTPU_COMPILE_CACHE: persistent XLA compilation cache wiring.

base._init_compile_cache() runs at import and points JAX's persistent
compilation cache at the given directory with the size/time thresholds
dropped to 0 (our programs are many small jit bodies). Verified in a
subprocess because the knob must be set before any compilation.
"""
import json
import os
import subprocess
import sys

import pytest

_PROBE = r"""
import json, os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import mxnet_tpu  # triggers _init_compile_cache()
import jax, jax.numpy as jnp

cfg_dir = jax.config.jax_compilation_cache_dir
out = jax.jit(lambda x: x * 2.0 + 1.0)(jnp.arange(8, dtype=jnp.float32))
out.block_until_ready()
cache_dir = os.environ["MXTPU_COMPILE_CACHE"]
entries = []
for root, _, files in os.walk(cache_dir):
    entries.extend(files)
print(json.dumps({"cfg_dir": cfg_dir, "entries": entries}))
"""


def _run_probe(env):
    full_env = dict(os.environ)
    full_env.update(env)
    full_env.pop("XLA_FLAGS", None)  # single device is fine here
    proc = subprocess.run(
        [sys.executable, "-c", _PROBE], env=full_env,
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr
    return json.loads(proc.stdout.strip().splitlines()[-1])


def test_compile_cache_populates_dir(tmp_path):
    cache = tmp_path / "xla_cache"
    cache.mkdir()
    res = _run_probe({"MXTPU_COMPILE_CACHE": str(cache),
                      "PYTHONPATH": os.path.dirname(
                          os.path.dirname(os.path.abspath(__file__)))})
    assert res["cfg_dir"] == str(cache)
    if not res["entries"]:  # some jax builds can't cache CPU executables
        pytest.skip("jax persistent cache wrote no CPU entries here")
    assert res["entries"]


def test_compile_cache_off_by_default():
    from mxnet_tpu import base

    env_backup = os.environ.pop("MXTPU_COMPILE_CACHE", None)
    try:
        # no env -> no-op, must not raise or touch jax config
        base._init_compile_cache()
    finally:
        if env_backup is not None:
            os.environ["MXTPU_COMPILE_CACHE"] = env_backup
