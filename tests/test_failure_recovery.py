"""Failure detection + recovery (SURVEY.md §5.3).

Reference level: ps-lite heartbeats surfaced as
``KVStore::get_num_dead_node`` (kvstore.h:235-244) and checkpoint/resume
by hand. This build reproduces the detection surface over the launcher
run dir (parallel/heartbeat.py) and goes one step further with
tools/watchdog.py: crash AND hang detection with checkpoint-based
auto-restart, proven here by fault injection.
"""
import os
import subprocess
import sys
import textwrap
import time

import pytest

import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.parallel import heartbeat as hb

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import watchdog  # noqa: E402


def test_heartbeat_dead_node_detection(tmp_path):
    d = str(tmp_path)
    w0 = hb.HeartbeatWriter(d, 0, interval=0.2).start()
    w1 = hb.HeartbeatWriter(d, 1, interval=0.2).start()
    try:
        time.sleep(0.3)
        # rank 2 never started -> dead; 0 and 1 alive
        assert hb.dead_nodes(d, 3, timeout=5.0) == [2]
        # age rank 1 out deterministically (no reliance on thread timing)
        w1.stop()
        old = time.time() - 120
        os.utime(os.path.join(d, "hb_1"), (old, old))
        assert hb.dead_nodes(d, 3, timeout=30.0) == [1, 2]
        assert hb.dead_nodes(d, 1, timeout=30.0) == []
    finally:
        w0.stop()
        w1.stop()


def test_kvstore_reports_dead_nodes(tmp_path, monkeypatch):
    d = str(tmp_path)
    monkeypatch.setenv(hb.RUN_DIR_ENV, d)
    monkeypatch.setenv("DMLC_NUM_WORKER", "3")
    kv = mx.kvstore.create("local")
    assert kv.num_workers == 3
    hb.HeartbeatWriter(d, 0).start().stop()
    hb.HeartbeatWriter(d, 1).start().stop()
    # rank 2 missing entirely
    assert kv.get_num_dead_node(0, timeout=60) == 1
    # age everyone out
    old = time.time() - 120
    for r in (0, 1):
        os.utime(os.path.join(d, "hb_%d" % r), (old, old))
    assert kv.get_num_dead_node(0, timeout=60) == 3


def test_find_latest_checkpoint(tmp_path):
    prefix = str(tmp_path / "model")
    assert watchdog.find_latest_checkpoint(prefix) is None
    for e in (1, 2, 10):
        open("%s-%04d.params" % (prefix, e), "w").close()
    assert watchdog.find_latest_checkpoint(prefix) == 10


TRAIN_SCRIPT = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, %(repo)r)
    os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")
    import jax; jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import mxnet_tpu as mx
    sys.path.insert(0, os.path.join(%(repo)r, "tools"))
    from watchdog import find_latest_checkpoint

    prefix, fault_flag = sys.argv[1], sys.argv[2]
    num_epoch = 4
    rng = np.random.RandomState(0)
    X = rng.randn(200, 16).astype(np.float32)
    y = (X[:, 0] > 0).astype(np.float32)
    it = mx.io.NDArrayIter(X, y, batch_size=50)

    net = mx.sym.SoftmaxOutput(
        mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=2),
        name="softmax")
    mod = mx.mod.Module(net, context=mx.cpu())

    last = find_latest_checkpoint(prefix)
    begin = 0
    if last is not None:
        # resume exactly where the crashed run left off
        lsym, args, auxs = mx.model.load_checkpoint(prefix, last)
        mod = mx.mod.Module(lsym, context=mx.cpu())
        mod.bind(data_shapes=it.provide_data, label_shapes=it.provide_label)
        mod.set_params(args, auxs)
        begin = last

    def crash_mid_training(epoch, *_):
        # fault injection: die once, after epoch 1's checkpoint
        if epoch == 1 and not os.path.exists(fault_flag):
            open(fault_flag, "w").close()
            os._exit(17)

    mod.fit(it, num_epoch=num_epoch, begin_epoch=begin,
            optimizer="sgd", optimizer_params={"learning_rate": 0.1},
            epoch_end_callback=[mx.callback.do_checkpoint(prefix),
                                crash_mid_training])
    print("TRAIN-DONE", flush=True)
""")


@pytest.mark.slow
def test_watchdog_restarts_crashed_training(tmp_path):
    script = tmp_path / "train.py"
    prefix = str(tmp_path / "ckpt")
    flag = str(tmp_path / "crashed_once")
    script.write_text(TRAIN_SCRIPT % {"repo": REPO})

    logs = []
    rc = watchdog.supervise(
        [sys.executable, str(script), prefix, flag],
        max_restarts=2, log=logs.append)
    assert rc == 0
    assert os.path.exists(flag), "fault was never injected"
    assert watchdog.find_latest_checkpoint(prefix) == 4
    assert any("restart 1/2" in m for m in logs), logs


@pytest.mark.slow
def test_watchdog_startup_deadline(tmp_path):
    """A rank wedged BEFORE its first heartbeat (e.g. stuck distributed
    init) must trip the startup deadline, not hang the watchdog."""
    script = tmp_path / "wedge.py"
    flag = str(tmp_path / "wedged_once")
    script.write_text(textwrap.dedent("""
        import os, sys, time
        flag = sys.argv[1]
        if os.path.exists(flag):
            sys.exit(0)          # second attempt: healthy
        open(flag, "w").close()
        time.sleep(600)          # never heartbeats
    """))
    # startup_timeout must outlast interpreter boot on a LOADED CI box
    # (2s flaked when a parallel suite pegged the cores), and a spare
    # restart absorbs one spurious deadline kill
    rc = watchdog.supervise(
        [sys.executable, str(script), flag],
        max_restarts=2, num_workers=1, heartbeat_timeout=60.0,
        poll_interval=0.3, startup_timeout=8.0,
        run_dir=str(tmp_path / "run"), log=lambda *_: None)
    assert rc == 0


@pytest.mark.slow
def test_watchdog_catches_wedged_collective(tmp_path):
    """The hang class liveness beats CANNOT catch: the process is alive
    (daemon thread keeps beating) but the main thread is wedged — e.g.
    inside a collective. Progress marks stop; --progress-timeout fires."""
    script = tmp_path / "wedge_collective.py"
    flag = str(tmp_path / "wedged_once")
    script.write_text(textwrap.dedent("""
        import importlib.util, os, sys, time
        spec = importlib.util.spec_from_file_location(
            "hb", os.path.join(%r, "mxnet_tpu", "parallel", "heartbeat.py"))
        hb = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(hb)
        flag = sys.argv[1]
        w = hb.HeartbeatWriter(os.environ["MXTPU_RUN_DIR"], 0,
                               interval=0.2).start()
        if os.path.exists(flag):
            sys.exit(0)          # second attempt: healthy
        open(flag, "w").close()
        time.sleep(600)          # liveness keeps beating; progress stops
    """ % REPO))
    logs = []
    rc = watchdog.supervise(
        [sys.executable, str(script), flag],
        max_restarts=1, num_workers=1, heartbeat_timeout=60.0,
        progress_timeout=2.0, poll_interval=0.3,
        run_dir=str(tmp_path / "run"), log=logs.append)
    assert rc == 0
    assert any("no training progress" in m for m in logs), logs


@pytest.mark.slow
def test_watchdog_kills_hung_job(tmp_path):
    """Hang detection: a worker that stops heartbeating gets killed and
    the job restarted — exit codes alone can never catch this."""
    script = tmp_path / "hang.py"
    flag = str(tmp_path / "hung_once")
    script.write_text(textwrap.dedent("""
        import os, sys, time
        flag = sys.argv[1]
        d = os.environ["MXTPU_RUN_DIR"]
        open(os.path.join(d, "hb_0"), "w").close()
        if os.path.exists(flag):
            sys.exit(0)          # second attempt: healthy
        open(flag, "w").close()
        time.sleep(600)          # first attempt: beat once, then hang
    """))
    t0 = time.time()
    rc = watchdog.supervise(
        [sys.executable, str(script), flag],
        max_restarts=1, num_workers=1, heartbeat_timeout=3.0,
        poll_interval=0.3, run_dir=str(tmp_path / "run"), log=lambda *_: None)
    assert rc == 0
    assert time.time() - t0 < 120
