"""Streaming input pipeline (io_pipeline.py): chunked sharded reads,
process-pool decode, shuffle buffer, and the O(1) sample cursor.

The ordering contract under test: in strict mode, batch contents are a
pure function of (seed, shard, shuffle-buffer size) — independent of
worker count, thread count, and completion timing — and the cursor
repositions a fresh iterator bitwise after skip(), seek_sample(), or a
SIGKILL mid-epoch.
"""
import os
import signal
import time

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import io_pipeline, recordio

SIZE = 32
SHAPE = (3, SIZE, SIZE)


@pytest.fixture(autouse=True)
def _reap_pools():
    """No orphaned spawn children may outlive a test, pass or fail."""
    yield
    io_pipeline.shutdown_all()


def _pack(tmp_path, n, seed=0, name="data"):
    rng = np.random.RandomState(seed)
    rec = str(tmp_path / ("%s.rec" % name))
    idx = str(tmp_path / ("%s.idx" % name))
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(n):
        img = rng.randint(0, 255, (SIZE, SIZE, 3)).astype(np.uint8)
        w.write_idx(i, recordio.pack_img(
            recordio.IRHeader(0, float(i), i, 0), img))
    w.close()
    return rec, idx


def _collect(it, n=None):
    """[(data, label, pad)] until StopIteration (or n batches)."""
    out = []
    while n is None or len(out) < n:
        try:
            b = it.next()
        except StopIteration:
            break
        out.append((np.asarray(b.data[0].asnumpy()),
                    np.asarray(b.label[0].asnumpy()), b.pad or 0))
    return out


def _assert_batches_equal(a, b):
    assert len(a) == len(b), (len(a), len(b))
    for i, ((da, la, pa), (db, lb, pb)) in enumerate(zip(a, b)):
        assert pa == pb, ("pad", i, pa, pb)
        np.testing.assert_array_equal(la, lb, err_msg="label batch %d" % i)
        np.testing.assert_array_equal(da, db, err_msg="data batch %d" % i)


# ---------------------------------------------------------------------------
# chunking + sharding


def test_build_chunks_cover_every_record(tmp_path):
    rec, idx = _pack(tmp_path, 23)
    chunks = recordio.build_chunks(rec, idx, chunk_bytes=4096)
    assert len(chunks) > 1  # the small target must actually split
    assert sum(c.n_records for c in chunks) == 23
    # record-aligned: every chunk parses cleanly from its byte range,
    # and ordinals tile [0, 23) exactly once in file order
    seen = []
    with open(rec, "rb") as f:
        for c in chunks:
            payloads = recordio.read_chunk(f, c, uri=rec)
            assert len(payloads) == c.n_records
            for j, s in enumerate(payloads):
                header, _ = recordio.unpack(s)
                seen.append((c.ordinal + j, float(header.label)))
    assert [o for o, _ in seen] == list(range(23))
    assert [int(l) for _, l in seen] == list(range(23))


def test_build_chunks_without_idx_scans(tmp_path):
    rec, idx = _pack(tmp_path, 9)
    with_idx = recordio.build_chunks(rec, idx, chunk_bytes=4096)
    scanned = recordio.build_chunks(rec, None, chunk_bytes=4096)
    assert with_idx == scanned


def test_host_shards_are_disjoint_and_complete(tmp_path):
    rec, _ = _pack(tmp_path, 30)
    labels = {}
    for rank in range(3):
        it = io_pipeline.StreamingImageRecordIter(
            5, SHAPE, rec, shuffle=False, workers=0,
            host_rank=rank, num_hosts=3)
        labels[rank] = [int(l) for d, lab, p in _collect(it)
                        for l in lab[:len(lab) - p]]
        assert it.num_samples == len(labels[rank])
    all_labels = sum(labels.values(), [])
    assert sorted(all_labels) == list(range(30))  # disjoint AND complete


# ---------------------------------------------------------------------------
# parity: the ordering contract


def test_imagerecorditer_threads_parity(tmp_path):
    """Classic thread path: same seed => identical batches across
    preprocess_threads in {1, 4} (deterministic augmenters)."""
    rec, idx = _pack(tmp_path, 50)
    runs = {}
    for threads in (1, 4):
        it = mx.io.ImageRecordIter(
            path_imgrec=rec, path_imgidx=idx, batch_size=8,
            data_shape=SHAPE, preprocess_threads=threads,
            input_workers=0)
        runs[threads] = _collect(it)
    assert len(runs[1]) == 7 and runs[1][-1][2] == 6  # 50 = 6*8 + 2
    _assert_batches_equal(runs[1], runs[4])


@pytest.mark.timeout(300)
def test_imagerecorditer_worker_parity_strict(tmp_path):
    """MXTPU_INPUT_WORKERS in {0, 2}: workers=0 is the classic
    thread-pool ImageIter, workers=2 the streaming process pool — in
    strict_order mode they must produce identical batch tensors."""
    rec, idx = _pack(tmp_path, 50)
    runs = {}
    for workers in (0, 2):
        it = mx.io.ImageRecordIter(
            path_imgrec=rec, path_imgidx=idx, batch_size=8,
            data_shape=SHAPE, preprocess_threads=2,
            input_workers=workers, strict_order=True)
        runs[workers] = _collect(it)
        # and epoch 2 stays in lockstep across the reset
        it.reset()
        runs[workers] += _collect(it, 2)
        if hasattr(it, "close"):
            it.close()
    _assert_batches_equal(runs[0], runs[2])


@pytest.mark.timeout(300)
def test_streaming_worker_count_independent_with_augment(tmp_path):
    """Random augmenters stay deterministic across worker placement:
    per-sample RNG is seeded from the record's global ordinal, so
    inline (workers=0) and pool (workers=2) runs of the STREAMING path
    agree bitwise even with rand_mirror + shuffle on."""
    rec, _ = _pack(tmp_path, 40)
    kw = dict(batch_size=8, data_shape=SHAPE, path_imgrec=rec,
              shuffle=True, seed=11, shuffle_buffer=16,
              aug_recipe={"rand_mirror": True}, strict_order=True)
    a = io_pipeline.StreamingImageRecordIter(workers=0, **kw)
    b = io_pipeline.StreamingImageRecordIter(workers=2, **kw)
    _assert_batches_equal(_collect(a), _collect(b))
    b.close()


def test_shuffle_buffer_mixes_across_chunks(tmp_path):
    rec, _ = _pack(tmp_path, 48)
    base = dict(batch_size=8, data_shape=SHAPE, path_imgrec=rec,
                workers=0, seed=5, strict_order=True)
    plain = io_pipeline.StreamingImageRecordIter(shuffle=False, **base)
    mixed = io_pipeline.StreamingImageRecordIter(
        shuffle=True, shuffle_buffer=24, **base)
    order_plain = [int(l) for d, lab, p in _collect(plain) for l in lab]
    order_mixed = [int(l) for d, lab, p in _collect(mixed) for l in lab]
    assert order_plain == list(range(48))  # no shuffle => file order
    assert sorted(order_mixed) == list(range(48))  # permutation...
    assert order_mixed != order_plain  # ...that actually mixed
    # epochs draw different permutations, reproducibly
    mixed.reset()
    e2 = [int(l) for d, lab, p in _collect(mixed) for l in lab]
    assert sorted(e2) == list(range(48)) and e2 != order_mixed
    again = io_pipeline.StreamingImageRecordIter(
        shuffle=True, shuffle_buffer=24, **base)
    again.reset()
    assert [int(l) for d, lab, p in _collect(again) for l in lab] == e2


# ---------------------------------------------------------------------------
# the cursor


def test_skip_repositions_without_decode(tmp_path):
    rec, _ = _pack(tmp_path, 64)
    kw = dict(batch_size=8, data_shape=SHAPE, path_imgrec=rec,
              workers=0, shuffle=True, seed=3, shuffle_buffer=16,
              strict_order=True)
    ref = _collect(io_pipeline.StreamingImageRecordIter(**kw))
    it = io_pipeline.StreamingImageRecordIter(**kw)
    it.skip(3)
    assert it.sample_position == 24
    _assert_batches_equal(_collect(it), ref[3:])


def test_seek_sample_absolute_and_rewind(tmp_path):
    rec, _ = _pack(tmp_path, 64)
    kw = dict(batch_size=8, data_shape=SHAPE, path_imgrec=rec,
              workers=0, shuffle=True, seed=9, shuffle_buffer=8,
              strict_order=True)
    ref = _collect(io_pipeline.StreamingImageRecordIter(**kw))
    it = io_pipeline.StreamingImageRecordIter(**kw)
    it.seek_sample(40)
    _assert_batches_equal(_collect(it, 1), [ref[5]])
    it.seek_sample(8)  # rewind restarts the SAME epoch's schedule
    _assert_batches_equal(_collect(it, 1), [ref[1]])


@pytest.mark.slow
@pytest.mark.timeout(300)
def test_sigkill_resume_repositions_bitwise(tmp_path):
    """Crash-exact resume on a sharded iterator: a child consumes two
    batches, reports its sample cursor (the MANIFEST field), and dies
    by SIGKILL mid-epoch; a fresh process seeks to that cursor and must
    continue bitwise-identically to an uninterrupted run."""
    import multiprocessing as mp

    rec, _ = _pack(tmp_path, 60)
    kw = dict(batch_size=6, data_shape=SHAPE, path_imgrec=rec,
              workers=0, shuffle=True, seed=17, shuffle_buffer=12,
              strict_order=True, host_rank=1, num_hosts=2)
    ref = _collect(io_pipeline.StreamingImageRecordIter(**kw))
    assert len(ref) >= 4  # the shard is real, not empty

    cursor_file = str(tmp_path / "cursor")
    ctx = mp.get_context("spawn")
    child = ctx.Process(
        target=_consume_then_hang, args=(rec, cursor_file), daemon=True)
    child.start()
    deadline = time.monotonic() + 240
    while not os.path.exists(cursor_file):
        assert child.is_alive(), "child died before reporting its cursor"
        assert time.monotonic() < deadline, "child never reported"
        time.sleep(0.05)
    os.kill(child.pid, signal.SIGKILL)
    child.join(timeout=30)
    assert not child.is_alive()

    with open(cursor_file) as f:
        cursor = int(f.read())
    assert cursor == 2 * kw["batch_size"]
    resumed = io_pipeline.StreamingImageRecordIter(**kw)
    resumed.seek_sample(cursor)
    _assert_batches_equal(_collect(resumed), ref[2:])


def _consume_then_hang(rec, cursor_file):
    from mxnet_tpu import io_pipeline as iop

    it = iop.StreamingImageRecordIter(
        6, SHAPE, rec, workers=0, shuffle=True, seed=17,
        shuffle_buffer=12, strict_order=True, host_rank=1, num_hosts=2)
    it.next()
    it.next()
    tmp = cursor_file + ".tmp"
    with open(tmp, "w") as f:
        f.write(str(it.sample_position))
    os.rename(tmp, cursor_file)
    time.sleep(300)  # the parent SIGKILLs us here — a real crash


def test_sample_position_lands_in_manifest(tmp_path):
    """The fit loop's snapshot carries the global sample position and
    checkpoint MANIFESTs expose it to readers."""
    from mxnet_tpu.resilience.checkpoint import CheckpointManager

    mgr = CheckpointManager(str(tmp_path / "ckpt"))
    state = {"module": {"arg": {}, "aux": {},
                        "opt": {"kind": "none"}},
             "epoch": 0, "nbatch": 7, "sample_position": 7 * 48,
             "global_step": 7}
    mgr.save(state, step=7)
    mgr.wait()
    import glob
    import json
    manifest = sorted(glob.glob(
        str(tmp_path / "ckpt" / "*" / "MANIFEST.json")))[-1]
    with open(manifest) as f:
        assert json.load(f)["sample_position"] == 336


# ---------------------------------------------------------------------------
# handoff + telemetry


def test_device_feed_handoff_and_telemetry(tmp_path):
    from mxnet_tpu import telemetry as _tm

    rec, _ = _pack(tmp_path, 32)
    was = _tm.enabled()
    if not was:
        _tm.enable()
    try:
        import jax
        from jax.sharding import SingleDeviceSharding

        inner = io_pipeline.StreamingImageRecordIter(
            8, SHAPE, rec, workers=0, shuffle=True, seed=1,
            shuffle_buffer=8, strict_order=True)
        fed = mx.io.DeviceFeedIter(
            inner, SingleDeviceSharding(jax.devices()[0]))
        n = sum(1 for _ in fed)
        assert n == 4
        snap = _tm.REGISTRY.snapshot()
        assert snap["io.decode_seconds"]["streams"], snap
        assert _tm.total("io.bytes_read") > 0
        assert "io.queue_depth" in snap
    finally:
        if not was:
            _tm.disable()


@pytest.mark.timeout(300)
def test_relaxed_mode_covers_epoch(tmp_path):
    """strict_order=0: completion-order assembly still yields every
    sample exactly once per epoch (determinism is not promised)."""
    rec, _ = _pack(tmp_path, 36)
    it = io_pipeline.StreamingImageRecordIter(
        6, SHAPE, rec, workers=2, shuffle=True, seed=2,
        shuffle_buffer=8, strict_order=False)
    labels = [int(l) for d, lab, p in _collect(it)
              for l in lab[:len(lab) - p]]
    assert sorted(labels) == list(range(36))
    it.reset()
    labels2 = [int(l) for d, lab, p in _collect(it)
               for l in lab[:len(lab) - p]]
    assert sorted(labels2) == list(range(36))
    it.close()
